"""Prometheus/OpenMetrics textfile exporter for the metrics registry.

``GALAH_OBS_OPENMETRICS=<path>`` makes every heartbeat tick render the
process-wide metrics registry — and, when a fleet rollup provider is
installed (``galah-tpu fleet run``), the cross-shard blame rollup —
to ``<path>`` in Prometheus text exposition format (0.0.4), swapped
atomically (io/atomic tmp+fsync+rename) so a scraper or node-exporter
textfile collector never reads a torn file.

Naming: registry names are dotted (``cache.hits``); exported names
are ``galah_`` + the name with every non-alphanumeric run collapsed
to ``_``. The ``name[key]`` suffix convention (``retries[site]``,
``workload.pipeline_occupancy[stage]``) becomes a label: ``stage=``
for occupancy gauges, ``site=`` for everything else. Counters gain
the conventional ``_total`` suffix; histograms export as summaries
(``_count``/``_sum``) plus ``_min``/``_max`` gauges.

No accelerator imports, no locks: state is two module attributes
written by the main thread and read by the heartbeat thread (atomic
reference reads — no partial state is observable).
"""

from __future__ import annotations

import os
import re
from typing import Any, Callable, Dict, List, Optional, Tuple

from galah_tpu.io import atomic

#: Metric-name prefix for everything this process exports.
PREFIX = "galah_"

_NAME_RE = re.compile(r"[^a-zA-Z0-9_]+")
_BRACKET_RE = re.compile(r"^(?P<base>[^\[\]]+)\[(?P<key>[^\[\]]*)\]$")

#: Optional fleet-rollup provider installed by ``fleet run`` (a
#: zero-arg callable returning the fleet_view.rollup dict or None).
_rollup_provider: Optional[Callable[[], Optional[dict]]] = None


def set_rollup_provider(
        provider: Optional[Callable[[], Optional[dict]]]) -> None:
    global _rollup_provider
    _rollup_provider = provider


def reset() -> None:
    """Drop run-scoped exporter state (obs.reset_run)."""
    set_rollup_provider(None)


def _sanitize(name: str) -> str:
    out = _NAME_RE.sub("_", name).strip("_")
    if out and out[0].isdigit():
        out = "_" + out
    return PREFIX + out


def _escape_help(text: str) -> str:
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def _escape_label(value: str) -> str:
    return (value.replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


def _split_labels(name: str) -> Tuple[str, str]:
    """``name[key]`` -> (base, label string); plain names pass through
    with no labels."""
    m = _BRACKET_RE.match(name)
    if not m:
        return name, ""
    base, key = m.group("base"), m.group("key")
    label = "stage" if base.endswith("occupancy") else "site"
    return base, '{%s="%s"}' % (label, _escape_label(key))


def _fmt(value: Any) -> str:
    try:
        v = float(value)
    except (TypeError, ValueError):
        return "0"
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v)


def render(metrics_snapshot: Dict[str, dict],
           rollup: Optional[dict] = None) -> str:
    """The full exposition page for one registry snapshot (and an
    optional fleet rollup)."""
    lines: List[str] = []
    typed: set = set()

    def emit(name: str, mtype: str, help_text: str, labels: str,
             value: Any) -> None:
        if name not in typed:
            typed.add(name)
            if help_text:
                lines.append(f"# HELP {name} "
                             f"{_escape_help(help_text)}")
            lines.append(f"# TYPE {name} {mtype}")
        lines.append(f"{name}{labels} {_fmt(value)}")

    for raw_name in sorted(metrics_snapshot):
        snap = metrics_snapshot[raw_name]
        if not isinstance(snap, dict):
            continue
        base, labels = _split_labels(raw_name)
        name = _sanitize(base)
        kind = snap.get("kind")
        help_text = snap.get("help") or ""
        if kind == "counter":
            emit(name + "_total", "counter", help_text, labels,
                 snap.get("value") or 0)
        elif kind == "gauge":
            if snap.get("value") is None:
                continue
            emit(name, "gauge", help_text, labels, snap["value"])
        elif kind == "histogram":
            if name not in typed:
                typed.add(name)
                if help_text:
                    lines.append(f"# HELP {name} "
                                 f"{_escape_help(help_text)}")
                lines.append(f"# TYPE {name} summary")
            lines.append(f"{name}_count{labels} "
                         f"{_fmt(snap.get('count') or 0)}")
            lines.append(f"{name}_sum{labels} "
                         f"{_fmt(snap.get('sum') or 0.0)}")
            for agg in ("min", "max"):
                if snap.get(agg) is not None:
                    emit(f"{name}_{agg}", "gauge",
                         f"{agg} observed {raw_name}", labels,
                         snap[agg])

    if rollup:
        emit(PREFIX + "fleet_wall_seconds", "gauge",
             "Fleet wall clock decomposed by the rollup", "",
             rollup.get("fleet_wall_s") or 0.0)
        # one contiguous block per metric: the text format requires
        # every sample of a metric grouped under its single TYPE line
        comps = [(comp, c) for comp, c in sorted(
            (rollup.get("components") or {}).items())
            if isinstance(c, dict)]
        for comp, c in comps:
            emit(PREFIX + "fleet_blame_seconds", "gauge",
                 "Fleet wall blamed on one rollup component",
                 '{component="%s"}' % _escape_label(comp),
                 c.get("blame_s") or 0.0)
        for comp, c in comps:
            emit(PREFIX + "fleet_blame_share", "gauge",
                 "Fraction of the fleet wall blamed on one "
                 "rollup component",
                 '{component="%s"}' % _escape_label(comp),
                 c.get("share") or 0.0)
        shards = [(sid, entry) for sid, entry in sorted(
            (rollup.get("shards") or {}).items(),
            key=lambda kv: str(kv[0])) if isinstance(entry, dict)]
        for sid, entry in shards:
            emit(PREFIX + "fleet_shard_wall_seconds", "gauge",
                 "Per-shard running wall inside the supervise "
                 "window", '{shard="%s"}' % _escape_label(str(sid)),
                 entry.get("wall_s") or 0.0)
        for sid, entry in shards:
            emit(PREFIX + "fleet_shard_blame_seconds", "gauge",
                 "Per-shard compute blame from the fleet rollup",
                 '{shard="%s"}' % _escape_label(str(sid)),
                 entry.get("blame_s") or 0.0)

    return "\n".join(lines) + "\n"


def export_path() -> Optional[str]:
    """The configured textfile path, or None when export is off."""
    return os.environ.get("GALAH_OBS_OPENMETRICS") or None


def write_textfile(path: str,
                   metrics_snapshot: Optional[Dict[str, dict]] = None,
                   rollup: Optional[dict] = None) -> str:
    """Render and atomically swap the ``.prom`` file at ``path``."""
    if metrics_snapshot is None:
        from galah_tpu.obs import metrics as obs_metrics

        metrics_snapshot = obs_metrics.snapshot()
    atomic.write_text(path, render(metrics_snapshot, rollup=rollup),
                      site="io.atomic.write[openmetrics]")
    return path


def maybe_export() -> Optional[str]:
    """One export tick: no-op unless GALAH_OBS_OPENMETRICS is set.

    Called from Heartbeat.beat() — failures must never take down the
    beat, so callers wrap this in try/except. The rollup provider is
    itself best-effort: a torn fleet dir mid-kill yields None and the
    page simply omits fleet series for that tick."""
    path = export_path()
    if not path:
        return None
    rollup = None
    provider = _rollup_provider
    if provider is not None:
        try:
            rollup = provider()
        except Exception:
            rollup = None
    return write_textfile(path, rollup=rollup)
