"""Fleet-wide observability rollup: one view over every shard.

A fleet run (fleet/scheduler) leaves its telemetry scattered: one
``fleet_events.jsonl`` for the supervisor, and per shard a
``run_report.json`` + ``heartbeat.jsonl`` under
``<fleet_dir>/shards/shard_NNN/``. This module stitches them — with
no accelerator imports and no live-process state, so it runs on any
host against any fleet dir, including one a chaos kill left
half-written — into:

* :func:`rollup` — the ``fleet_rollup`` report section (schema v9):
  a cross-shard critical path decomposing the fleet wall into
  scheduler blame (launch + backoff), per-shard compute blame
  (reusing each shard's own flow critical path), straggler wait
  (fleet wall beyond the median shard wall, charged to the named
  slowest shards), and merge wall — component shares summing exactly
  to the fleet wall.
* :func:`fleet_grid` / :func:`render_fleet_grid` — the live per-shard
  grid behind ``galah-tpu top <fleet_dir>`` fleet mode.
* :func:`write_fleet_report` — a schema-valid ``fleet_report.json``
  for ``galah-tpu fleet analyze``.

Tolerance contract: torn event/heartbeat tails are skipped (atomic
framing), a shard dir deleted mid-aggregate contributes nothing, and
shard reports of any schema version v6+ are accepted — a v6/v7 report
without some section simply yields an unsplit compute blame for that
shard. :func:`rollup` returns ``None`` only when the dir carries no
event log at all (rollup-impossible: there is no fleet timeline).
"""

from __future__ import annotations

import os
import re
import statistics
import time
from typing import Any, Dict, List, Optional, Tuple

from galah_tpu.io import atomic

#: Aggregated report filename written by ``galah-tpu fleet analyze``.
FLEET_REPORT_FILENAME = "fleet_report.json"

#: How many named slowest shards the straggler component carries.
MAX_NAMED_STRAGGLERS = 4

#: Events that open / close a shard's running interval on the fleet
#: timeline. Unknown event types are ignored (forward compatibility).
_OPEN_EVS = frozenset({"shard-launched", "shard-started"})
_CLOSE_EVS = frozenset({"shard-preempted", "shard-done",
                        "fleet-shard-failed"})

_SHARD_DIR_RE = re.compile(r"shard_(\d+)$")


def _wall() -> float:
    return time.time()


def fleet_report_path(fleet_dir: str) -> str:
    return os.path.join(fleet_dir, FLEET_REPORT_FILENAME)


def is_fleet_dir(directory: str) -> bool:
    """True when ``directory`` looks like a fleet dir (has a plan or
    an event log) — the auto-detection behind ``top`` fleet mode."""
    from galah_tpu.fleet import plan as plan_mod

    return (os.path.exists(plan_mod.plan_path(directory))
            or os.path.exists(plan_mod.events_path(directory)))


# ------------------------------------------------------------ loading


def _load_events(fleet_dir: str) -> Tuple[List[dict], int]:
    from galah_tpu.fleet import plan as plan_mod

    records, torn = atomic.read_jsonl(plan_mod.events_path(fleet_dir))
    evs = [r for r in records
           if isinstance(r, dict) and isinstance(r.get("ts"),
                                                 (int, float))]
    evs.sort(key=lambda r: float(r["ts"]))
    return evs, torn


def _shard_ids(fleet_dir: str, events: List[dict]) -> List[int]:
    """Planned shard ids; falls back to ids seen in events, then to
    shard dirs on disk, so a dir whose plan was torn still rolls up."""
    from galah_tpu.fleet import plan as plan_mod

    doc = plan_mod.load_plan(fleet_dir)
    if doc is not None:
        ids = sorted({int(d.get("shard_id"))
                      for d in doc.get("shards", [])
                      if isinstance(d.get("shard_id"), int)})
        if ids:
            return ids
    ids = {int(r["shard"]) for r in events
           if isinstance(r.get("shard"), int)}
    shards_root = os.path.join(fleet_dir, "shards")
    try:
        for name in os.listdir(shards_root):
            m = _SHARD_DIR_RE.match(name)
            if m:
                ids.add(int(m.group(1)))
    except OSError:
        pass
    return sorted(ids)


def _load_shard_report(fleet_dir: str, sid: int) -> Optional[dict]:
    """Torn/missing-tolerant shard report load (never raises): a shard
    mid-write or deleted mid-aggregate reads as absent."""
    import json

    from galah_tpu.fleet import scheduler as sched_mod

    try:
        with open(sched_mod.shard_report_path(fleet_dir, sid)) as f:
            rep = json.load(f)
    except (OSError, ValueError):
        return None
    return rep if isinstance(rep, dict) else None


def _latest_beat(fleet_dir: str, sid: int) -> Optional[dict]:
    from galah_tpu.fleet import scheduler as sched_mod
    from galah_tpu.obs.heartbeat import read_latest_beat

    try:
        return read_latest_beat(
            sched_mod.shard_heartbeat_path(fleet_dir, sid))
    except Exception:
        return None


# ------------------------------------------------------- interval math


def _union_length(intervals: List[Tuple[float, float]],
                  lo: float, hi: float) -> float:
    """Length of the union of ``intervals`` clipped to [lo, hi]."""
    clipped = sorted((max(lo, a), min(hi, b)) for a, b in intervals
                     if min(hi, b) > max(lo, a))
    total, cur_a, cur_b = 0.0, None, None
    for a, b in clipped:
        if cur_b is None or a > cur_b:
            if cur_b is not None:
                total += cur_b - cur_a
            cur_a, cur_b = a, b
        else:
            cur_b = max(cur_b, b)
    if cur_b is not None:
        total += cur_b - cur_a
    return total


def _replay_intervals(events: List[dict], t_end: float
                      ) -> Dict[int, List[Tuple[float, float]]]:
    """Per-shard running intervals from the event log. A shard whose
    last attempt never closed (scheduler killed) closes at ``t_end``."""
    intervals: Dict[int, List[Tuple[float, float]]] = {}
    open_at: Dict[int, float] = {}
    for rec in events:
        sid = rec.get("shard")
        if not isinstance(sid, int):
            continue
        ev, ts = rec.get("ev"), float(rec["ts"])
        if ev in _OPEN_EVS:
            open_at.setdefault(sid, ts)
        elif ev in _CLOSE_EVS:
            start = open_at.pop(sid, None)
            if start is not None and ts > start:
                intervals.setdefault(sid, []).append((start, ts))
    for sid, start in open_at.items():
        if t_end > start:
            intervals.setdefault(sid, []).append((start, t_end))
    return intervals


# -------------------------------------------------------------- rollup


def rollup(fleet_dir: str) -> Optional[dict]:
    """The cross-shard critical path for ``fleet_dir``, or ``None``
    when the dir has no fleet event log (rollup-impossible).

    Conservation: ``scheduler + compute + straggler_wait + merge``
    blame seconds sum exactly to ``fleet_wall_s`` by construction —
    each bucket is defined as a remainder of the one before it.
    """
    events, torn = _load_events(fleet_dir)
    if not events:
        return None
    shard_ids = _shard_ids(fleet_dir, events)

    reports: Dict[int, Optional[dict]] = {
        sid: _load_shard_report(fleet_dir, sid) for sid in shard_ids}
    beats: Dict[int, Optional[dict]] = {
        sid: _latest_beat(fleet_dir, sid) for sid in shard_ids}

    t0 = float(events[0]["ts"])
    t_end = float(events[-1]["ts"])
    for beat in beats.values():
        if beat and isinstance(beat.get("ts"), (int, float)):
            t_end = max(t_end, float(beat["ts"]))
    fleet_wall = max(0.0, t_end - t0)

    # merge wall: the post-supervise stamp the CLI appends after the
    # cross-shard merge; clamped so a clock-skewed stamp cannot break
    # conservation
    merge_s = 0.0
    for rec in events:
        if rec.get("ev") == "fleet-merge-done":
            try:
                merge_s = float(rec.get("wall_s") or 0.0)
            except (TypeError, ValueError):
                merge_s = 0.0
    merge_s = min(max(0.0, merge_s), fleet_wall)
    supervise_end = t0 + (fleet_wall - merge_s)

    intervals = _replay_intervals(events, t_end)
    all_ivals = [iv for ivs in intervals.values() for iv in ivs]
    coverage = _union_length(all_ivals, t0, supervise_end)

    # scheduler blame: supervise time where NO shard was running —
    # launch latency, poll slack, and retry backoff. The backoff
    # bucket is bounded by the stamped backoff_s events; the rest is
    # launch/poll.
    sched_s = max(0.0, (fleet_wall - merge_s) - coverage)
    stamped_backoff = sum(
        float(r.get("backoff_s") or 0.0) for r in events
        if r.get("ev") == "shard-backoff")
    backoff_s = min(max(0.0, stamped_backoff), sched_s)
    launch_s = sched_s - backoff_s

    # per-shard walls (clipped to the supervise window, so queued
    # relaunches after a preemption never double-charge merge time)
    walls = {sid: _union_length(intervals.get(sid, []),
                                t0, supervise_end)
             for sid in shard_ids}
    positive = [w for w in walls.values() if w > 0]
    med = statistics.median(positive) if positive else 0.0

    # straggler wait: coverage beyond the median shard wall, charged
    # to the shards that ran longer than the median; the remainder is
    # genuine parallel compute
    straggler_s = (max(0.0, coverage - med)
                   if len(positive) >= 2 else 0.0)
    compute_s = coverage - straggler_s
    slowest = sorted(
        ({"shard": sid, "wall_s": round(w, 6),
          "excess_s": round(w - med, 6)}
         for sid, w in walls.items() if w > med),
        key=lambda d: -d["excess_s"])[:MAX_NAMED_STRAGGLERS]

    wall_sum = sum(walls.values())

    # per-shard detail: compute blame proportional to shard wall,
    # split further by the shard's own flow critical path when its
    # report carries one (v6+; older/missing reports stay unsplit)
    schema_versions: List[int] = []
    missing: List[int] = []
    shard_out: Dict[str, dict] = {}
    state: Dict[int, str] = {sid: "pending" for sid in shard_ids}
    attempts: Dict[int, int] = {sid: 0 for sid in shard_ids}
    preempts: Dict[int, int] = {sid: 0 for sid in shard_ids}
    for rec in events:
        sid = rec.get("shard")
        if sid not in state:
            continue
        ev = rec.get("ev")
        if ev == "shard-launched":
            attempts[sid] += 1
            state[sid] = "running"
        elif ev == "shard-preempted":
            preempts[sid] += 1
            state[sid] = "pending"
        elif ev == "shard-done":
            state[sid] = "done"
        elif ev == "fleet-shard-failed":
            state[sid] = "failed"
    for sid in shard_ids:
        rep = reports[sid]
        if rep is None:
            missing.append(sid)
        else:
            v = rep.get("version")
            if isinstance(v, int) and v not in schema_versions:
                schema_versions.append(v)
        blame = (compute_s * walls[sid] / wall_sum
                 if wall_sum > 0 else 0.0)
        entry: Dict[str, Any] = {
            "wall_s": round(walls[sid], 6),
            "blame_s": round(blame, 6),
            "share": round(blame / fleet_wall, 6)
            if fleet_wall > 0 else 0.0,
            "status": state[sid],
            "attempts": attempts[sid],
            "preemptions": preempts[sid],
            "report_version": (rep or {}).get("version"),
        }
        cp = ((rep or {}).get("flow") or {}).get("critical_path")
        if isinstance(cp, dict) and isinstance(cp.get("stages"), dict):
            entry["bottleneck"] = cp.get("bottleneck")
            entry["stages"] = {
                name: {"blame_s": round(
                    blame * float(st.get("share") or 0.0), 6),
                    "share": round(float(st.get("share") or 0.0), 6)}
                for name, st in cp["stages"].items()
                if isinstance(st, dict)}
        beat = beats[sid]
        if beat and isinstance(beat.get("ts"), (int, float)):
            entry["beat_age_s"] = round(
                max(0.0, _wall() - float(beat["ts"])), 3)
            if beat.get("role") is not None:
                entry["role"] = beat.get("role")
        shard_out[str(sid)] = entry

    def _share(v: float) -> float:
        return round(v / fleet_wall, 6) if fleet_wall > 0 else 0.0

    # the named bottleneck: largest single blame bucket, with a
    # winning shard narrowed to its own critical-path stage
    candidates: List[Tuple[float, str]] = [
        (sched_s, "scheduler"),
        (straggler_s, "straggler-wait"),
        (merge_s, "merge"),
    ]
    for sid in shard_ids:
        entry = shard_out[str(sid)]
        name = f"shard-{sid}"
        if entry.get("bottleneck"):
            name = f"shard-{sid}:{entry['bottleneck']}"
        candidates.append((entry["blame_s"], name))
    bottleneck = max(candidates, key=lambda c: c[0])[1] \
        if fleet_wall > 0 else None

    from galah_tpu.fleet import plan as plan_mod

    return {
        "fleet_wall_s": round(fleet_wall, 6),
        "source": {
            "events": len(events),
            "torn_events": torn,
            "plan": plan_mod.load_plan(fleet_dir) is not None,
            "shards_planned": len(shard_ids),
            "shards_reported": len(shard_ids) - len(missing),
            "shards_missing": missing,
            "schema_versions": sorted(schema_versions),
        },
        "components": {
            "scheduler": {
                "blame_s": round(sched_s, 6),
                "share": _share(sched_s),
                "launch_s": round(launch_s, 6),
                "backoff_s": round(backoff_s, 6),
            },
            "compute": {
                "blame_s": round(compute_s, 6),
                "share": _share(compute_s),
            },
            "straggler_wait": {
                "blame_s": round(straggler_s, 6),
                "share": _share(straggler_s),
                "slowest": slowest,
            },
            "merge": {
                "blame_s": round(merge_s, 6),
                "share": _share(merge_s),
            },
        },
        "shards": shard_out,
        "bottleneck": bottleneck,
    }


def render_rollup(ru: dict, indent: str = "") -> List[str]:
    """Human blame table for a rollup dict (``fleet analyze`` body)."""
    src = ru.get("source", {})
    comps = ru.get("components", {})
    wall = float(ru.get("fleet_wall_s") or 0.0)
    lines = [
        f"{indent}fleet critical path "
        f"(wall {wall:.2f}s, {src.get('shards_reported', 0)}/"
        f"{src.get('shards_planned', 0)} shard reports"
        + (f", {src.get('torn_events')} torn" if src.get("torn_events")
           else "") + ")"]
    order = ("scheduler", "compute", "straggler_wait", "merge")
    for name in order:
        c = comps.get(name)
        if not isinstance(c, dict):
            continue
        extra = ""
        if name == "scheduler":
            extra = (f"  (launch {c.get('launch_s', 0.0):.2f}s, "
                     f"backoff {c.get('backoff_s', 0.0):.2f}s)")
        elif name == "straggler_wait" and c.get("slowest"):
            names = ", ".join(f"shard-{d['shard']}"
                              for d in c["slowest"])
            extra = f"  (slowest: {names})"
        lines.append(
            f"{indent}  {name:<16} "
            f"{float(c.get('blame_s') or 0.0):8.2f}s "
            f"{100.0 * float(c.get('share') or 0.0):5.1f}%{extra}")
    for sid, entry in sorted(ru.get("shards", {}).items(),
                             key=lambda kv: int(kv[0])):
        bn = entry.get("bottleneck")
        lines.append(
            f"{indent}  shard {int(sid):3d} {entry.get('status', '?'):<8}"
            f" wall {float(entry.get('wall_s') or 0.0):7.2f}s "
            f"blame {float(entry.get('blame_s') or 0.0):7.2f}s"
            + (f"  bottleneck={bn}" if bn else ""))
    if ru.get("bottleneck"):
        lines.append(f"{indent}  bottleneck: {ru['bottleneck']}")
    return lines


# ---------------------------------------------------------- fleet grid


def fleet_grid(fleet_dir: str) -> Optional[dict]:
    """Live per-shard grid + scheduler event tail for ``top`` fleet
    mode; ``None`` when the dir has neither plan nor events."""
    if not is_fleet_dir(fleet_dir):
        return None
    events, torn = _load_events(fleet_dir)
    shard_ids = _shard_ids(fleet_dir, events)
    state = {sid: "pending" for sid in shard_ids}
    attempts = {sid: 0 for sid in shard_ids}
    chain: Dict[int, List[str]] = {sid: [] for sid in shard_ids}
    for rec in events:
        sid = rec.get("shard")
        if sid not in state:
            continue
        ev = rec.get("ev")
        if ev == "shard-launched":
            attempts[sid] += 1
            state[sid] = "running"
        elif ev == "shard-preempted":
            chain[sid].append(str(rec.get("reason") or "unknown"))
            state[sid] = "pending"
        elif ev == "shard-done":
            state[sid] = "done"
        elif ev == "fleet-shard-failed":
            state[sid] = "failed"
    now = _wall()
    shards = {}
    for sid in shard_ids:
        beat = _latest_beat(fleet_dir, sid)
        entry: Dict[str, Any] = {
            "state": state[sid],
            "attempts": attempts[sid],
            "chain": chain[sid],
        }
        if beat:
            ts = float(beat.get("ts") or 0.0)
            entry["beat_age_s"] = round(max(0.0, now - ts), 3)
            occ = beat.get("occupancy") or {}
            if occ:
                entry["occupancy"] = occ
            if beat.get("rss_mb") is not None:
                entry["rss_mb"] = beat.get("rss_mb")
            if beat.get("role") is not None:
                entry["role"] = beat.get("role")
        shards[str(sid)] = entry
    tail = [{"ev": r.get("ev"), "ts": r.get("ts"),
             **({"shard": r["shard"]} if isinstance(
                 r.get("shard"), int) else {})}
            for r in events[-8:]]
    return {"fleet_dir": fleet_dir, "shards": shards,
            "events": len(events), "torn_events": torn,
            "event_tail": tail}


def render_fleet_grid(grid: dict) -> str:
    lines = [f"fleet {grid.get('fleet_dir')}  "
             f"events {grid.get('events', 0)}"
             + (f"  ({grid['torn_events']} torn)"
                if grid.get("torn_events") else "")]
    for sid, e in sorted(grid.get("shards", {}).items(),
                         key=lambda kv: int(kv[0])):
        occ = e.get("occupancy") or {}
        occ_s = " ".join(f"{k}={v:.2f}" for k, v in
                         sorted(occ.items())) if occ else "-"
        beat = (f"{e['beat_age_s']:.1f}s"
                if e.get("beat_age_s") is not None else "-")
        rss = (f"{float(e['rss_mb']):.0f}MB"
               if e.get("rss_mb") is not None else "-")
        chain = "->".join(e.get("chain") or []) or "-"
        lines.append(
            f"  shard {int(sid):3d} {e.get('state', '?'):<8}"
            f" attempts={e.get('attempts', 0)}"
            f" beat-age={beat:<7} rss={rss:<7}"
            f" occ[{occ_s}] chain={chain}")
    tail = grid.get("event_tail") or []
    if tail:
        lines.append("  recent events:")
        for rec in tail:
            shard = (f" shard={rec['shard']}"
                     if "shard" in rec else "")
            lines.append(f"    {rec.get('ev')}{shard}")
    return "\n".join(lines) + "\n"


# --------------------------------------------------------- full report


def write_fleet_report(fleet_dir: str, ru: dict,
                       argv: Optional[List[str]] = None,
                       started_at: Optional[float] = None) -> str:
    """Assemble and atomically write ``fleet_report.json`` (a normal
    schema-valid run report whose ``fleet_rollup`` is ``ru``)."""
    from galah_tpu.obs import report as report_mod

    rep = report_mod.assemble("fleet-analyze", argv=argv,
                              started_at=started_at)
    rep["fleet_rollup"] = ru
    path = fleet_report_path(fleet_dir)
    report_mod.write(path, rep)
    return path
