"""Embeddable library API: build and run the clusterer from another tool.

The reference deliberately exports its orchestration layer so CoverM can
embed Galah as a library — `GalahClusterer`, `generate_galah_clusterer`,
`add_cluster_subcommand`, and a `GalahClustererCommandDefinition` whose
fields parameterize the *flag names* so the embedding tool can rename
them (reference: src/cluster_argument_parsing.rs:84-124, :897-1158,
:1265-1375). This module is the equivalent surface:

    import argparse
    from galah_tpu.api import (ClustererCommandDefinition,
                               add_cluster_arguments,
                               generate_galah_clusterer)

    defn = ClustererCommandDefinition(ani="dereplication-ani")
    parser = argparse.ArgumentParser()
    add_cluster_arguments(parser, defn)     # embeds the renamed flags
    args = parser.parse_args()
    clusterer = generate_galah_clusterer(genome_paths, vars(args), defn)
    clusters = clusterer.cluster()          # indices into .genome_paths

The CLI (cli.py) is a thin consumer of the same functions with the
default (un-renamed) definition.
"""

from __future__ import annotations

import argparse
import dataclasses
import logging
from typing import Dict, List, Optional, Sequence, Tuple

from galah_tpu.config import (
    CLUSTER_METHODS,
    Defaults,
    HASH_ALGORITHMS,
    PRECLUSTER_METHODS,
    QUALITY_FORMULAS,
    parse_percentage,
)

logger = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class ClustererCommandDefinition:
    """Flag names as data, so an embedding tool can rename them.

    Each field is the long-option name (without leading dashes) used for
    that parameter; defaults match the standalone CLI (reference analog:
    GalahClustererCommandDefinition, cluster_argument_parsing.rs:90-124).
    """

    ani: str = "ani"
    precluster_ani: str = "precluster-ani"
    min_aligned_fraction: str = "min-aligned-fraction"
    fragment_length: str = "fragment-length"
    precluster_method: str = "precluster-method"
    cluster_method: str = "cluster-method"
    quality_formula: str = "quality-formula"
    hash_algorithm: str = "hash-algorithm"
    ani_subsample: str = "ani-subsample"
    rep_scan_window: str = "rep-scan-window"
    rep_rounds: str = "rep-rounds"
    checkm_tab_table: str = "checkm-tab-table"
    checkm2_quality_report: str = "checkm2-quality-report"
    genome_info: str = "genome-info"
    min_completeness: str = "min-completeness"
    max_contamination: str = "max-contamination"
    threads: str = "threads"
    on_bad_genome: str = "on-bad-genome"

    def dest(self, flag_name: str) -> str:
        return flag_name.replace("-", "_")


def add_cluster_arguments(
    parser: argparse.ArgumentParser,
    definition: ClustererCommandDefinition = ClustererCommandDefinition(),
) -> None:
    """Add the clustering/quality flags under the definition's names."""
    d = definition
    parser.add_argument(f"--{d.ani}", type=float, default=Defaults.ANI,
                        help="Average nucleotide identity threshold for "
                             "clustering (default: 95)")
    parser.add_argument(f"--{d.precluster_ani}", type=float,
                        default=Defaults.PRETHRESHOLD_ANI,
                        help="Require at least this sketch-derived ANI "
                             "for preclustering (default: 90)")
    parser.add_argument(f"--{d.min_aligned_fraction}", type=float,
                        default=Defaults.ALIGNED_FRACTION * 100,
                        help="Min aligned fraction of two genomes for "
                             "clustering (default: 15)")
    parser.add_argument(f"--{d.fragment_length}", type=int,
                        default=Defaults.FRAGMENT_LENGTH,
                        help="Length of fragment used in fastANI-style "
                             "calculation (default: 3000)")
    parser.add_argument(f"--{d.precluster_method}",
                        default=Defaults.PRECLUSTER_METHOD,
                        choices=PRECLUSTER_METHODS,
                        help="Method of calculating rough ANI for "
                             "dereplication (default: skani)")
    parser.add_argument(f"--{d.cluster_method}",
                        default=Defaults.CLUSTER_METHOD,
                        choices=CLUSTER_METHODS,
                        help="Method of calculating exact ANI for "
                             "dereplication (default: skani)")
    parser.add_argument(f"--{d.checkm_tab_table}",
                        help="Output of `checkm qa .. --tab_table`")
    parser.add_argument(f"--{d.checkm2_quality_report}",
                        help="CheckM2 quality_report.tsv output")
    parser.add_argument(f"--{d.genome_info}",
                        help="dRep-style genome info CSV "
                             "(genome,completeness,contamination)")
    parser.add_argument(f"--{d.min_completeness}", type=float,
                        help="Ignore genomes with less completeness than "
                             "this percentage")
    parser.add_argument(f"--{d.max_contamination}", type=float,
                        help="Ignore genomes with more contamination than "
                             "this percentage")
    parser.add_argument(f"--{d.quality_formula}",
                        default=Defaults.QUALITY_FORMULA,
                        choices=QUALITY_FORMULAS,
                        help="Quality formula for ranking genomes "
                             "(default: Parks2020_reduced)")
    parser.add_argument(f"--{d.hash_algorithm}",
                        default=Defaults.HASH_ALGO,
                        choices=HASH_ALGORITHMS,
                        help="Sketch hash: murmur3 (reference-"
                             "compatible) or tpufast (multiply-free "
                             "TPU mixer, ~20x faster sketching; "
                             "default: murmur3)")
    parser.add_argument(f"--{d.ani_subsample}", type=int,
                        default=Defaults.ANI_SUBSAMPLE,
                        help="FracMinHash compression of the exact "
                             "fragment-ANI stage: keep only k-mers "
                             "with hash < 2^64/c (1 = every k-mer, "
                             "dense; skani's own compression is 125). "
                             "Higher is ~c-fold faster with slightly "
                             "noisier per-fragment identity "
                             "(default: 1)")
    parser.add_argument(f"--{d.rep_scan_window}", type=int,
                        default=None,
                        help="Speculative rep-scan batch width: genomes "
                             "per window evaluated against all current "
                             "representatives in one backend call "
                             "(default: 128). Wider = fewer device "
                             "round trips, more speculative ANIs; the "
                             "waste is reported as the exact-ani-wasted "
                             "counter in the stage report")
    parser.add_argument(f"--{d.rep_rounds}", type=int,
                        default=None,
                        help="Device greedy-selection round width: "
                             "genomes speculatively taken per round of "
                             "the round-based representative scan "
                             "(default: 1024). Only the device strategy "
                             "reads it; GALAH_TPU_GREEDY_STRATEGY pins "
                             "device/host selection")
    parser.add_argument(f"--{d.threads}", "-t", type=int, default=1,
                        help="Host threads for FASTA stats/IO fan-out "
                             "and CPU-backend native sketching/"
                             "profiling; "
                             "device parallelism is managed by the mesh")
    from galah_tpu.resilience.quarantine import ON_BAD_GENOME_CHOICES

    parser.add_argument(f"--{d.on_bad_genome}",
                        default="error", choices=ON_BAD_GENOME_CHOICES,
                        help="What to do with unreadable genome FASTAs "
                             "(missing, empty, corrupt): 'error' aborts "
                             "on first touch (default); 'skip' "
                             "preflights every input, quarantines the "
                             "bad ones into quarantine.json next to "
                             "the outputs, and clusters the rest")


@dataclasses.dataclass
class GalahClusterer:
    """A ready-to-run clustering job over quality-ordered genome paths.

    `genome_paths` is the post-filter, quality-ordered list; `cluster()`
    returns clusters of indices into it, representative first
    (reference analog: GalahClusterer, cluster_argument_parsing.rs:84-88
    and its .cluster() at :1185).
    """

    genome_paths: List[str]
    preclusterer: object
    clusterer: object
    checkpoint: Optional[object] = None
    #: sketch-level backend settings (fed into the checkpoint
    #: fingerprint so a resume under different sketching params starts
    #: fresh)
    backend_params: Dict = dataclasses.field(default_factory=dict)
    #: speculative rep-scan batch width (None = engine default); the
    #: waste it buys is reported as the exact-ani-wasted counter
    rep_scan_window: Optional[int] = None
    #: device greedy-selection round width (None = engine default)
    rep_rounds: Optional[int] = None
    #: genomes quarantined by the --on-bad-genome=skip preflight (None
    #: under the default error policy); the CLI writes this next to the
    #: outputs as quarantine.json
    quarantine: Optional[object] = None

    def cluster(self) -> List[List[int]]:
        from galah_tpu.cluster import cluster as run

        return run(self.genome_paths, self.preclusterer, self.clusterer,
                   checkpoint=self.checkpoint,
                   rep_scan_window=self.rep_scan_window,
                   rep_rounds=self.rep_rounds)


def _get(values: Dict, definition: ClustererCommandDefinition,
         flag_name: str):
    return values.get(definition.dest(flag_name))


def quality_order_genomes(
    genome_paths: Sequence[str],
    values: Dict,
    definition: ClustererCommandDefinition = ClustererCommandDefinition(),
    threads: int = 1,
    missing_key: str = "checkm-input-missing",
    missing_msg: str = ("Since CheckM input is missing, genomes are not "
                        "being ordered by quality. Instead the order of "
                        "their input is being used"),
) -> Tuple[List[str], bool]:
    """Quality-filter + order `genome_paths` from `values`' inputs.

    Returns (ordered_paths, used_quality). When no quality input was
    given the paths come back in input order, `used_quality` is False,
    and `missing_msg` is warned once under `missing_key` — `galah-tpu
    index` passes its own key/message so the unranked-insert fallback
    stays a distinct, countable signal (satellite of the index PR).
    Raises ValueError on conflicting quality inputs, like the
    reference's factory.
    """
    from galah_tpu import quality as quality_mod

    d = definition
    quality_inputs = [
        ("checkm_tab_table", _get(values, d, d.checkm_tab_table)),
        ("checkm2_quality_report",
         _get(values, d, d.checkm2_quality_report)),
        ("genome_info", _get(values, d, d.genome_info)),
    ]
    given = [(k, v) for k, v in quality_inputs if v]
    if len(given) > 1:
        raise ValueError(
            "Specify at most one of --checkm-tab-table, "
            "--checkm2-quality-report and --genome-info")
    if not given:
        from galah_tpu.obs.events import warn_once

        # Repeated construction (bench rungs, embedding tools) must not
        # repeat this once-per-run fact — BENCH_r05's tail carried one
        # copy per in-process bench stage. The explicit key dedupes
        # across every module that might phrase the same fact.
        warn_once(logger, missing_msg, key=missing_key)
        return list(genome_paths), False
    kind, path = given[0]
    formula = _get(values, d, d.quality_formula) \
        or Defaults.QUALITY_FORMULA
    if kind == "checkm_tab_table":
        logger.info("Reading CheckM tab table ..")
        table = quality_mod.read_checkm1_tab_table(path)
    elif kind == "checkm2_quality_report":
        logger.info("Reading CheckM2 Quality report ..")
        table = quality_mod.read_checkm2_quality_report(path)
    else:
        if formula == "dRep":
            raise ValueError(
                "The dRep quality formula cannot be used with "
                "--genome-info")
        table = quality_mod.read_genome_info_file(path)
    min_comp = _get(values, d, d.min_completeness)
    max_cont = _get(values, d, d.max_contamination)
    ordered = quality_mod.filter_and_order_genomes(
        list(genome_paths), table, formula=formula,
        min_completeness=(parse_percentage(
            min_comp, f"--{d.min_completeness}")
            if min_comp is not None else None),
        max_contamination=(parse_percentage(
            max_cont, f"--{d.max_contamination}")
            if max_cont is not None else None),
        threads=threads,
    )
    return ordered, True


def generate_galah_clusterer(
    genome_paths: Sequence[str],
    values: Dict,
    definition: ClustererCommandDefinition = ClustererCommandDefinition(),
    cache=None,
    quarantine_manifest=None,
) -> GalahClusterer:
    """Quality-filter + order genomes and construct the backends.

    `values` is a vars(args)-style mapping keyed by the definition's
    dest names (reference analog: generate_galah_clusterer,
    cluster_argument_parsing.rs:897-1158). Raises ValueError on
    conflicting quality inputs, like the reference's factory.
    """
    from galah_tpu.backends import (
        FastANIEquivalentClusterer,
        HLLPreclusterer,
        MinHashPreclusterer,
        ProfileStore,
        SkaniEquivalentClusterer,
        SkaniPreclusterer,
    )
    from galah_tpu.io import diskcache

    d = definition
    cache = cache or diskcache.get_cache()

    ani = parse_percentage(_get(values, d, d.ani), f"--{d.ani}")
    precluster_ani = parse_percentage(
        _get(values, d, d.precluster_ani), f"--{d.precluster_ani}")
    min_af = parse_percentage(
        _get(values, d, d.min_aligned_fraction),
        f"--{d.min_aligned_fraction}")
    fraglen = int(_get(values, d, d.fragment_length)
                  or Defaults.FRAGMENT_LENGTH)
    pre_method = _get(values, d, d.precluster_method)
    cl_method = _get(values, d, d.cluster_method)
    threads = int(_get(values, d, d.threads) or 1)
    hash_algo = _get(values, d, d.hash_algorithm) or Defaults.HASH_ALGO
    if hash_algo not in HASH_ALGORITHMS:
        raise ValueError(
            f"unknown hash algorithm {hash_algo!r}; "
            f"choices: {HASH_ALGORITHMS}")
    raw_subsample = _get(values, d, d.ani_subsample)
    ani_subsample = int(raw_subsample if raw_subsample is not None
                        else Defaults.ANI_SUBSAMPLE)
    if not 1 <= ani_subsample <= 1000:
        raise ValueError(
            f"--{d.ani_subsample} must be in [1, 1000], "
            f"got {ani_subsample}")
    raw_window = _get(values, d, d.rep_scan_window)
    rep_scan_window = int(raw_window) if raw_window is not None else None
    if rep_scan_window is not None and rep_scan_window < 1:
        raise ValueError(
            f"--{d.rep_scan_window} must be >= 1, got {rep_scan_window}")
    raw_rounds = _get(values, d, d.rep_rounds)
    rep_rounds = int(raw_rounds) if raw_rounds is not None else None
    if rep_rounds is not None and rep_rounds < 1:
        raise ValueError(
            f"--{d.rep_rounds} must be >= 1, got {rep_rounds}")

    # Bad-input quarantine — BEFORE quality ordering, which already
    # reads every genome for stats: under 'skip' the unreadable ones
    # are removed here (identically on every host) so neither the
    # quality pass nor the sketch stage ever touches them. The default
    # 'error' policy costs zero extra IO: first touch still raises.
    on_bad = (_get(values, d, d.on_bad_genome) or "error")
    from galah_tpu.resilience.quarantine import (
        ON_BAD_GENOME_CHOICES,
        preflight_quarantine,
    )

    if on_bad not in ON_BAD_GENOME_CHOICES:
        raise ValueError(
            f"unknown --{d.on_bad_genome} policy {on_bad!r}; "
            f"choices: {ON_BAD_GENOME_CHOICES}")
    quarantine = quarantine_manifest
    genome_paths = list(genome_paths)
    if on_bad == "skip":
        genome_paths, quarantine = preflight_quarantine(
            genome_paths, manifest=quarantine_manifest)
        if not genome_paths:
            raise ValueError(
                "every input genome was quarantined as unreadable; "
                "nothing to cluster (see the quarantine manifest)")

    # Quality filter + ordering (shared with `galah-tpu index`, which
    # passes its own missing-input warning so unranked incremental
    # inserts are observable as a distinct event)
    genome_paths, _used_quality = quality_order_genomes(
        genome_paths, values, definition=d, threads=threads)

    # skani+skani: precluster at the final threshold (reference:
    # src/cluster_argument_parsing.rs:983-1030)
    if pre_method == "skani" and cl_method == "skani":
        precluster_ani = ani

    store = ProfileStore(fraglen=fraglen, cache=cache,
                         subsample_c=ani_subsample, threads=threads,
                         hash_algorithm=hash_algo)
    if pre_method == "finch":
        pre = MinHashPreclusterer(min_ani=precluster_ani, cache=cache,
                                  hash_algo=hash_algo, threads=threads)
    elif pre_method == "skani":
        pre = SkaniPreclusterer(threshold=precluster_ani,
                                min_aligned_fraction=min_af, store=store)
    elif pre_method == "dashing":
        pre = HLLPreclusterer(min_ani=precluster_ani, cache=cache,
                              hash_algo=hash_algo, threads=threads)
    else:
        raise ValueError(f"unknown precluster method {pre_method!r}")

    if cl_method == "fastani":
        cl = FastANIEquivalentClusterer(
            threshold=ani, min_aligned_fraction=min_af, fraglen=fraglen,
            store=store)
    elif cl_method == "skani":
        cl = SkaniEquivalentClusterer(
            threshold=ani, min_aligned_fraction=min_af, store=store)
    else:
        raise ValueError(f"unknown cluster method {cl_method!r}")

    from galah_tpu.backends.fragment_backend import ANI_KMER
    from galah_tpu.ops.hll import DEFAULT_P

    backend_params = {
        "minhash": {"sketch_size": Defaults.MINHASH_SKETCH_SIZE,
                    "k": Defaults.MINHASH_KMER, "seed": 0,
                    "algo": hash_algo},
        "hll": {"p": DEFAULT_P, "k": Defaults.MINHASH_KMER, "seed": 0,
                "algo": hash_algo},
        "fragment": {"k": ANI_KMER, "fraglen": fraglen,
                     "screen_identity": SkaniPreclusterer.SCREEN_IDENTITY,
                     # only recorded when active so default-path
                     # checkpoint fingerprints survive the upgrade
                     **({"subsample_c": ani_subsample}
                        if ani_subsample != 1 else {})},
    }
    return GalahClusterer(genome_paths=genome_paths, preclusterer=pre,
                          clusterer=cl, backend_params=backend_params,
                          rep_scan_window=rep_scan_window,
                          rep_rounds=rep_rounds,
                          quarantine=quarantine)
