"""Extended `--full-help` pages, rendered man-style to the pager.

The reference generates roff man pages from its flag definitions and
pipes them through `man` for --full-help (reference:
src/cluster_argument_parsing.rs:1194-1263 and the bird_tool_utils-man
builder). Here the same content is generated from the argparse parser
plus section prose, rendered as plain text (no roff/man dependency), and
paged when stdout is a TTY.
"""

from __future__ import annotations

import argparse
import os
import shutil
import subprocess
import sys
import textwrap
from typing import List, Tuple

WIDTH = 78


def _wrap(text: str, indent: int = 3) -> str:
    return textwrap.fill(
        " ".join(text.split()), width=WIDTH,
        initial_indent=" " * indent, subsequent_indent=" " * indent)


def _format_action(action: argparse.Action) -> str:
    flags = ", ".join(action.option_strings)
    if action.metavar:
        flags += f" {action.metavar}"
    elif action.nargs != 0 and not isinstance(
            action, (argparse._StoreTrueAction, argparse._VersionAction)):
        flags += f" <{action.dest.upper()}>"
    lines = [f"  {flags}"]
    if action.help:
        help_text = action.help
        if action.choices:
            help_text += f" [choices: {', '.join(map(str, action.choices))}]"
        lines.append(_wrap(help_text, indent=6))
    return "\n".join(lines)


# Flags grouped into man-page sections; every flag not named here lands
# in OTHER GENERAL OPTIONS so new flags can never silently vanish from
# the page.
_SECTIONS: List[Tuple[str, str, List[str]]] = [
    ("GENOME INPUT",
     "Genomes may be given as explicit FASTA paths, a directory of "
     "FASTA files, or a text file listing one path per line. All input "
     "modes can be combined.",
     ["--genome-fasta-files", "--genome-fasta-list",
      "--genome-fasta-directory", "--genome-fasta-extension"]),
    ("CLUSTERING PARAMETERS",
     "Dereplication proceeds in two stages: a cheap sketch-based "
     "precluster pass over all genome pairs, then an exact ANI pass "
     "restricted to pairs that survived preclustering. Thresholds "
     "accept percentages (1-100) or fractions (0-1).",
     ["--ani", "--precluster-ani", "--min-aligned-fraction",
      "--fragment-length", "--precluster-method", "--cluster-method",
      "--hash-algorithm", "--ani-subsample"]),
    ("QUALITY FILTERING AND RANKING",
     "When a quality table is provided, genomes are filtered by "
     "completeness/contamination and ranked by the quality formula; "
     "higher-ranked genomes are preferred as cluster representatives. "
     "Without one, input order is used (a warning is printed).",
     ["--checkm-tab-table", "--checkm2-quality-report", "--genome-info",
      "--min-completeness", "--max-contamination", "--quality-formula"]),
    ("OUTPUT",
     "Outputs are opened before compute starts so misconfiguration "
     "fails fast.",
     ["--output-cluster-definition",
      "--output-representative-fasta-directory",
      "--output-representative-fasta-directory-copy",
      "--output-representative-list"]),
    ("PERFORMANCE AND RESUMPTION",
     "Device parallelism (TPU mesh sharding) is automatic; --threads "
     "only affects host-side FASTA ingestion. Sketches/profiles can "
     "persist across runs, and long runs can checkpoint and resume.",
     ["--threads", "--sketch-cache", "--checkpoint-dir",
      "--profile-trace-dir"]),
    ("OBSERVABILITY",
     "Every run can emit a machine-readable run_report.json (stage "
     "wall-clock tree, dispatch/sync round-trip counts, the "
     "precluster funnel, config-flag snapshot, and resilience "
     "events) and a Chrome-trace-format event timeline loadable in "
     "Perfetto alongside the XLA profile. Render or compare reports "
     "with `galah-tpu report [--diff A B]`. See docs/observability.md.",
     ["--run-report", "--trace-events"]),
]

_EPILOGS = {
    "cluster": """\
REPEAT-DRIVEN MERGES
   The exact-ANI gate passes a pair when EITHER direction's
   matched-fragment fraction reaches --min-aligned-fraction, and the
   reported ANI is the max over directions (reference fastANI-wrapper
   semantics). Genomes that merely share repeats or mobile elements
   can clear a low threshold on a sliver of their length: matched
   windows sit near 100% identity, so the pair reports high ANI over
   a low-but-passing aligned fraction. A runtime warning flags the
   signature (marginal AND direction-asymmetric aligned fractions);
   raising --min-aligned-fraction is the documented defense.

EXIT STATUS
   0 on success, 1 on recoverable user error (bad flags, missing
   files); unexpected internal errors raise a traceback.

EXAMPLES
   Dereplicate a directory of MAGs at 95% ANI, writing the cluster
   table and symlinking representatives:

      galah-tpu cluster -d genomes/ -x fna \\
         --output-cluster-definition clusters.tsv \\
         --output-representative-fasta-directory reps/

   Quality-rank with CheckM2 and require 70% completeness:

      galah-tpu cluster -d genomes/ \\
         --checkm2-quality-report quality_report.tsv \\
         --min-completeness 70 --max-contamination 10 \\
         --output-cluster-definition clusters.tsv
""",
    "cluster-validate": """\
EXIT STATUS
   0 on success (violations are logged as errors, matching the
   reference's behavior of reporting rather than aborting).

EXAMPLES
      galah-tpu cluster-validate --cluster-file clusters.tsv --ani 95
""",
    "report": """\
REPORT CONTENTS
   A run report (produced by `cluster --run-report PATH` or the
   GALAH_OBS_REPORT variable, schema committed at
   galah_tpu/obs/run_report.schema.json) records the stage wall-clock
   tree, per-stage device dispatch and host-sync round trips, the
   precluster funnel (possible -> screened -> kept -> ANI-computed
   pairs plus sketch-cache hit rate), the full GALAH_* flag snapshot,
   device topology, typed metrics, and every resilience event
   (retries, CPU-fallback demotions, quarantined genomes).

EXIT STATUS
   0 on success (including a clean diff); 1 on unreadable or
   schema-invalid input.

EXAMPLES
   Render one report:

      galah-tpu report run_report.json

   Diff two runs stage-by-stage and metric-by-metric:

      galah-tpu report --diff before.json after.json
""",
    "index": """\
INDEX MODEL
   The index directory (docs/index.md) persists the dereplication
   state of a genome catalogue: sketches, thresholded sketch-ANI
   pairs, and the greedy representative/membership decisions, under
   a monotonically versioned generation pointer. `build` runs the
   device sketch pipeline once; `insert` sketches ONLY the new
   genomes, computes only their pairs (bit-identical host math), and
   commits the next generation — the resulting clusters are byte-
   identical to re-dereplicating the grown catalogue from scratch,
   as long as inserts respect the quality order. `query` mutates
   nothing and answers in milliseconds from the committed state.
   `remove` tombstones a genome and locally re-elects within its own
   cluster (local repair, not a from-scratch equivalence).

   Every append is durable (per-record fsync + checksum framing) and
   a generation commits by an atomic pointer swap, so a writer
   killed at ANY instant leaves the index loadable at its previous
   generation; rerunning the same insert converges to the same
   bytes. SIGTERM/SIGINT stop at the next batch boundary with exit
   status 75.

EXIT STATUS
   0 on success, 1 on user error or a failed fsck, 75 when a
   cooperative-preemption request stopped an insert at a safe
   boundary (rerun to continue).

EXAMPLES
   Build an index over a catalogue, quality-ranked:

      galah-tpu index --index-dir idx/ build -d genomes/ -x fna \\
         --checkm2-quality-report quality_report.tsv --ani 95

   Insert this week's new MAGs (only they are sketched):

      galah-tpu index --index-dir idx/ insert -d new_mags/ -x fna

   Ask where a genome would land, without changing anything:

      galah-tpu index --index-dir idx/ query -f novel.fna

   Audit the on-disk state:

      galah-tpu index --index-dir idx/ fsck
""",
}


_ENV_SECTION_TITLES = [
    ("runtime", "Runtime and IO"),
    ("kernel", "Kernel and device policy"),
    ("resilience", "Resilience"),
    ("observability", "Observability"),
    ("bench", "Benchmarks"),
    ("test", "Test selection"),
    ("scripts", "Scripts"),
]


def render_environment_section() -> str:
    """The ENVIRONMENT section, auto-rendered from the central
    GALAH_* registry (config.FLAGS) so the manpage can never drift
    from the code — `galah-tpu lint` (GL405) asserts every registered
    flag appears here."""
    from galah_tpu.config import FLAGS

    out = ["ENVIRONMENT",
           _wrap("Every GALAH_* variable the project reads, from the "
                 "central registry in galah_tpu.config.FLAGS."),
           ""]
    by_section = {}
    for flag in FLAGS.values():
        by_section.setdefault(flag.section, []).append(flag)
    for section, title in _ENV_SECTION_TITLES:
        flags = sorted(by_section.pop(section, []),
                       key=lambda f: f.name)
        if not flags:
            continue
        out.append(f"  {title}:")
        for flag in flags:
            head = f"  {flag.name}"
            if flag.default is not None:
                head += f" (default: {flag.default})"
            out.append(head)
            help_text = flag.help
            if flag.choices:
                help_text += f" [choices: {', '.join(flag.choices)}]"
            out.append(_wrap(help_text, indent=6))
        out.append("")
    # a section key unknown to the titles table must still render —
    # flags can never silently vanish from the page
    for section in sorted(by_section):
        out.append(f"  {section}:")
        for flag in sorted(by_section[section], key=lambda f: f.name):
            out.append(f"  {flag.name}")
            out.append(_wrap(flag.help, indent=6))
        out.append("")
    return "\n".join(out)


def render_full_help(parser: argparse.ArgumentParser,
                     subcommand: str) -> str:
    by_flag = {}
    general = []
    for action in parser._actions:
        if not action.option_strings:
            continue
        key = action.option_strings[-1]
        by_flag[key] = action
        general.append(key)

    out = []
    prog = f"galah-tpu {subcommand}"
    out.append(prog.upper())
    out.append("")
    out.append("NAME")
    out.append(_wrap(f"{prog} — {parser.description}"))
    out.append("")

    used = set()
    for title, prose, flags in _SECTIONS:
        present = [f for f in flags if f in by_flag]
        if not present:
            continue
        out.append(title)
        if prose:
            out.append(_wrap(prose))
            out.append("")
        for f in present:
            out.append(_format_action(by_flag[f]))
            used.add(f)
        out.append("")

    rest = [f for f in general if f not in used and f != "--help"]
    if rest:
        out.append("OTHER GENERAL OPTIONS")
        for f in rest:
            out.append(_format_action(by_flag[f]))
        out.append("")

    out.append(render_environment_section())
    out.append(_EPILOGS.get(subcommand, ""))
    return "\n".join(out)


def render_full_help_roff(parser: argparse.ArgumentParser,
                          subcommand: str) -> str:
    """The same page as groff man source (the reference renders its
    help through roff via bird_tool_utils-man; --full-help-roff exposes
    the source the same way)."""
    import galah_tpu

    def esc(t: str) -> str:
        return t.replace("\\", "\\\\").replace("-", "\\-")

    by_flag = {}
    general = []
    for action in parser._actions:
        if not action.option_strings:
            continue
        key = action.option_strings[-1]
        by_flag[key] = action
        general.append(key)

    prog = f"galah-tpu {subcommand}"
    out = [
        f'.TH "{prog.upper().replace(" ", "-")}" "1" "" '
        f'"galah-tpu {galah_tpu.__version__}" "User Commands"',
        ".SH NAME",
        f"{esc(prog)} \\- {esc(parser.description or '')}",
    ]

    def emit_action(action) -> None:
        names = ", ".join(f"\\fB{esc(o)}\\fR"
                          for o in action.option_strings)
        if action.metavar or (action.nargs != 0
                              and action.const is None
                              and not isinstance(action.nargs, int)
                              and action.type is not None
                              or action.choices):
            names += " \\fI<value>\\fR"
        out.append(".TP")
        out.append(names)
        help_text = action.help or ""
        if action.choices:
            help_text += (" [choices: "
                          + ", ".join(map(str, action.choices)) + "]")
        out.append(esc(help_text))

    used = set()
    for title, prose, flags in _SECTIONS:
        present = [f for f in flags if f in by_flag]
        if not present:
            continue
        out.append(f".SH {title}")
        if prose:
            out.append(esc(prose))
        for f in present:
            emit_action(by_flag[f])
            used.add(f)
    rest = [f for f in general if f not in used and f != "--help"]
    if rest:
        out.append(".SH OTHER GENERAL OPTIONS")
        for f in rest:
            emit_action(by_flag[f])
    from galah_tpu.config import FLAGS

    out.append(".SH ENVIRONMENT")
    for flag in sorted(FLAGS.values(), key=lambda f: f.name):
        out.append(".TP")
        head = f"\\fB{esc(flag.name)}\\fR"
        if flag.default is not None:
            head += f" (default: {esc(flag.default)})"
        out.append(head)
        help_text = flag.help
        if flag.choices:
            help_text += f" [choices: {', '.join(flag.choices)}]"
        out.append(esc(help_text))

    epilog = _EPILOGS.get(subcommand, "")
    for block in epilog.split("\n\n"):
        if not block.strip():
            continue
        first, _, restb = block.partition("\n")
        if first.isupper():
            out.append(f".SH {first.strip()}")
            if restb:
                out.append(".nf")
                out.append(esc(restb))
                out.append(".fi")
        else:
            out.append(esc(block))
    return "\n".join(out) + "\n"


def print_full_help(parser: argparse.ArgumentParser,
                    subcommand: str) -> None:
    text = render_full_help(parser, subcommand)
    pager = os.environ.get("PAGER") or "less"
    if sys.stdout.isatty() and shutil.which(pager.split()[0]):
        proc = subprocess.Popen([pager.split()[0], "-"] if pager == "less"
                                else pager.split(),
                                stdin=subprocess.PIPE)
        proc.communicate(text.encode())
    else:
        sys.stdout.write(text)
