"""Command-line interface: `galah-tpu cluster` / `galah-tpu cluster-validate`.

Flag surface mirrors the reference CLI (reference: src/main.rs:53-118 and
src/cluster_argument_parsing.rs:1265-1375); percentage arguments accept
either 1-100 or 0-1 and normalize to fractions (reference:
src/cluster_argument_parsing.rs:1160-1182). The compute path underneath is
the TPU-native pipeline.
"""

from __future__ import annotations

import argparse
import logging
import os
import sys
import time

import galah_tpu
from galah_tpu.api import add_cluster_arguments, generate_galah_clusterer
from galah_tpu.config import (Defaults, HASH_ALGORITHMS,
                              QUALITY_FORMULAS, parse_percentage)
from galah_tpu.utils import timing
from galah_tpu.utils.logging import set_log_level

logger = logging.getLogger("galah_tpu")


def _add_verbosity(p: argparse.ArgumentParser) -> None:
    p.add_argument("-v", "--verbose", action="store_true",
                   help="Print extra debugging information")
    p.add_argument("-q", "--quiet", action="store_true",
                   help="Unless there is an error, do not print log messages")
    p.add_argument("--platform", default=None,
                   help="Force the JAX platform (e.g. cpu, tpu). Wins over "
                        "site-wide defaults that pin a device backend — "
                        "JAX_PLATFORMS alone can be overridden by an "
                        "interpreter sitecustomize, this flag cannot. Env "
                        "equivalent: GALAH_TPU_PLATFORM. Default: the "
                        "interpreter's JAX default")
    p.add_argument("--full-help", action="store_true",
                   help="Display an extended man-style help page and exit")
    p.add_argument("--full-help-roff", action="store_true",
                   help="Print the extended help as raw roff man source "
                        "and exit (pipe through `man -l -`)")


def _add_genome_inputs(p: argparse.ArgumentParser) -> None:
    p.add_argument("-f", "--genome-fasta-files", nargs="+",
                   help="Path(s) to FASTA files of each genome")
    p.add_argument("--genome-fasta-list",
                   help="File containing FASTA file paths, one per line")
    p.add_argument("-d", "--genome-fasta-directory",
                   help="Directory containing FASTA files of each genome")
    p.add_argument("-x", "--genome-fasta-extension", default="fna",
                   help="File extension of genomes in the directory "
                        "(default: fna)")


def _add_index_quality(p: argparse.ArgumentParser) -> None:
    """Quality-ordering inputs for `index build`/`index insert` — the
    same surface `cluster` carries, because insert order IS the greedy
    quality order the persisted decisions are sound under."""
    p.add_argument("--checkm-tab-table",
                   help="Output of `checkm qa .. --tab_table`")
    p.add_argument("--checkm2-quality-report",
                   help="CheckM2 quality_report.tsv output")
    p.add_argument("--genome-info",
                   help="dRep-style genome info CSV "
                        "(genome,completeness,contamination)")
    p.add_argument("--quality-formula",
                   default=Defaults.QUALITY_FORMULA,
                   choices=QUALITY_FORMULAS,
                   help="Quality formula for ranking genomes "
                        "(default: Parks2020_reduced)")
    p.add_argument("--min-completeness", type=float,
                   help="Ignore genomes with less completeness than "
                        "this percentage")
    p.add_argument("--max-contamination", type=float,
                   help="Ignore genomes with more contamination than "
                        "this percentage")


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="galah-tpu",
        description="Metagenome assembled genome (MAG) dereplicator / "
                    "clusterer, TPU-native")
    parser.add_argument("--version", action="version",
                        version=galah_tpu.__version__)
    sub = parser.add_subparsers(dest="subcommand")

    c = sub.add_parser(
        "cluster",
        help="Cluster genomes by ANI, choosing quality-ranked "
             "representatives",
        description="Cluster genomes by average nucleotide identity "
                    "(ANI), choosing one quality-ranked representative "
                    "genome per cluster, on TPU")
    _add_verbosity(c)
    _add_genome_inputs(c)
    # Shared clustering/quality flags come from the embeddable API
    # factory (api.py) so the CLI and embedding tools stay in lockstep.
    add_cluster_arguments(c)
    c.add_argument("--sketch-cache",
                   help="Directory for the persistent sketch/profile "
                        "cache (also via GALAH_TPU_CACHE); sketches are "
                        "reused across runs when genome files are "
                        "unchanged")
    c.add_argument("--profile-trace-dir",
                   help="Capture an XLA profiler trace of the run into "
                        "this directory (TensorBoard-loadable)")
    c.add_argument("--trace-events",
                   help="Write a Chrome-trace-format event timeline "
                        "(stage spans, JAX compile events, resilience "
                        "events; Perfetto-loadable) to this file. Env "
                        "equivalent: GALAH_OBS_TRACE_EVENTS")
    c.add_argument("--run-report",
                   help="Write the machine-readable run_report.json "
                        "(stage tree, dispatch counts, precluster "
                        "funnel, flag snapshot, resilience events) to "
                        "this file at run end; render or diff it with "
                        "`galah-tpu report`. Env equivalent: "
                        "GALAH_OBS_REPORT")
    c.add_argument("--checkpoint-dir",
                   help="Persist the distance pass and finished "
                        "preclusters here; an interrupted run resumes "
                        "from the last completed precluster")
    c.add_argument("--resume", action="store_true",
                   help="Require resuming from --checkpoint-dir: fail "
                        "if the checkpoint is missing or belongs to a "
                        "different configuration instead of silently "
                        "starting fresh. Without this flag a matching "
                        "checkpoint still auto-resumes; --resume makes "
                        "\"no checkpoint\" an error. The run report's "
                        "preemption section records the resume chain")
    c.add_argument("--output-cluster-definition",
                   help="Output file of rep<TAB>member lines")
    c.add_argument("--output-representative-fasta-directory",
                   help="Symlink representative genomes into this directory")
    c.add_argument("--output-representative-fasta-directory-copy",
                   help="Copy representative genomes into this directory")
    c.add_argument("--output-representative-list",
                   help="Output file with one representative path per line")

    v = sub.add_parser(
        "cluster-validate", help="Verify clustering results",
        description="Re-check a cluster output file: every member must "
                    "reach the ANI threshold to its representative, and "
                    "no two representatives may reach it to each other")
    _add_verbosity(v)
    v.add_argument("--cluster-file",
                   help="Output of 'cluster' subcommand (required)")
    v.add_argument("--ani", type=float, default=99.0,
                   help="ANI to validate against (default: 99)")
    v.add_argument("--min-aligned-fraction", type=float, default=50.0,
                   help="Min aligned fraction of two genomes "
                        "(default: 50)")
    v.add_argument("--fragment-length", type=int,
                   default=Defaults.FRAGMENT_LENGTH,
                   help="Length of fragment used in fastANI-style "
                        "calculation (default: 3000)")
    v.add_argument("--ani-subsample", type=int,
                   default=Defaults.ANI_SUBSAMPLE,
                   help="FracMinHash compression of the exact ANI "
                        "re-check (see `cluster --full-help`; "
                        "default: 1)")
    v.add_argument("--hash-algorithm", default=Defaults.HASH_ALGO,
                   choices=sorted(HASH_ALGORITHMS),
                   help="k-mer hash for the validation profiles — use "
                        "the same value the clustering ran with so "
                        "near-threshold pairs score identically "
                        "(default: murmur3)")
    v.add_argument("--threads", "-t", type=int, default=1)

    dd = sub.add_parser(
        "dist",
        help="Calculate pairwise MinHash ANI between a set of genomes",
        description="All-pairs sketch-based ANI as a TSV — the "
                    "reference carries this subcommand disabled "
                    "(reference: src/main.rs:88-114); here the pair "
                    "matrix is one tiled device computation")
    _add_verbosity(dd)
    _add_genome_inputs(dd)
    dd.add_argument("--num-hashes", type=int,
                    default=Defaults.MINHASH_SKETCH_SIZE,
                    help="MinHash sketch size (default: 1000)")
    dd.add_argument("--kmer-length", type=int,
                    default=Defaults.MINHASH_KMER,
                    help="k-mer length (default: 21)")
    dd.add_argument("--hash-algorithm", default=Defaults.HASH_ALGO,
                    choices=HASH_ALGORITHMS,
                    help="Sketch hash (default: murmur3)")
    dd.add_argument("--min-ani", type=float, default=0.0,
                    help="Only report pairs at or above this ANI "
                         "(percent or fraction; default: report every "
                         "pair with any sketch overlap)")
    dd.add_argument("--output", help="Output TSV (default: stdout)")
    dd.add_argument("--sketch-cache",
                    help="Directory for the persistent sketch cache "
                         "(also via GALAH_TPU_CACHE)")
    dd.add_argument("--threads", "-t", type=int, default=1)

    li = sub.add_parser(
        "lint",
        help="Static analysis of the codebase: Pallas kernel "
             "contracts, tracer leaks, flag registry, shape "
             "contracts, lock discipline, numeric determinism, "
             "interprocedural effect auditors (GalahIR)",
        description="Run the galah-tpu static-analysis suite "
                    "(equivalent to `python -m galah_tpu.analysis`); "
                    "exits 1 on any unsuppressed finding at WARNING "
                    "or above")
    from galah_tpu.analysis import add_lint_arguments

    add_lint_arguments(li)

    rp = sub.add_parser(
        "report",
        help="Render or diff run_report.json files from past runs",
        description="Human-readable rendering of the machine-readable "
                    "run report a `cluster --run-report` run wrote "
                    "(stage wall-clock tree, dispatch/sync counts, "
                    "precluster funnel, flag snapshot, resilience "
                    "events); with --diff, per-stage and per-metric "
                    "deltas between two reports")
    _add_verbosity(rp)
    rp.add_argument("paths", nargs="+", metavar="REPORT",
                    help="run_report.json file(s) to render")
    rp.add_argument("--diff", action="store_true",
                    help="Compare exactly two reports: per-stage "
                         "wall-clock, dispatch/funnel, and per-metric "
                         "deltas")
    pf = sub.add_parser(
        "perf",
        help="Record, inspect, and gate on the cross-run performance "
             "ledger",
        description="The append-only perf ledger (JSONL, fed "
                    "automatically by runs with GALAH_OBS_LEDGER set) "
                    "keys every entry by backend, device topology, "
                    "workload fingerprint (N/K/P), and strategy. "
                    "`record` appends a run report's metrics, "
                    "`history` prints one metric's trajectory, and "
                    "`check` compares the newest entry against a "
                    "median±MAD noise band over the last entries of "
                    "the same key, exiting 1 on regression "
                    "(docs/observability.md)")
    _add_verbosity(pf)
    pf.add_argument("--ledger", default=None,
                    help="Ledger file (default: GALAH_OBS_LEDGER)")
    pfsub = pf.add_subparsers(dest="perf_action")
    pfr = pfsub.add_parser(
        "record", help="Append a run report's metrics to the ledger")
    pfr.add_argument("report", metavar="REPORT",
                     help="run_report.json to ingest")
    pfr.add_argument("--source", default="manual",
                     help="Key component naming what produced the "
                          "report (default: manual)")
    pfh = pfsub.add_parser(
        "history", help="Print one metric's cross-run trajectory")
    pfh.add_argument("metric", metavar="METRIC",
                     help="Metric name (e.g. run.duration_s, "
                          "bench.e2e_1000_genomes_per_sec)")
    pfh.add_argument("--key", default=None,
                     help="Only entries whose canonical key contains "
                          "this substring")
    pfc = pfsub.add_parser(
        "check",
        help="Gate: newest entry vs the same-key noise band "
             "(exit 1 on regression)")
    pfc.add_argument("--report", default=None,
                     help="Check this run_report.json against the "
                          "ledger instead of the ledger's own newest "
                          "entry (nothing is appended)")
    pfc.add_argument("--source", default="manual",
                     help="Key source component for --report entries")
    pfc.add_argument("--window", type=int, default=None,
                     help="Same-key history window (default: "
                          "GALAH_OBS_LEDGER_WINDOW)")
    pfc.add_argument("--mad-k", type=float, default=None,
                     help="Noise-band width in MADs (default: "
                          "GALAH_OBS_LEDGER_MAD_K)")
    pfc.add_argument("--min-history", type=int, default=None,
                     help="Entries required before a verdict "
                          "(default: 3)")
    pfc.add_argument("--soft", action="store_true",
                     help="Report regressions but exit 0 — the CI "
                          "mode while a key is still accumulating "
                          "trustworthy history")
    fl = sub.add_parser(
        "flow",
        help="Critical-path analysis of a run's flow telemetry",
        description="Item-level flow tracing over the overlapped "
                    "pipeline (GALAH_OBS_FLOW, on by default) records "
                    "per-stage service/wait time and inter-stage queue "
                    "latencies into the run report's `flow` section; "
                    "`analyze` recomputes the critical path from a "
                    "report and prints per-stage blame shares that sum "
                    "to the end-to-end wall "
                    "(docs/observability.md). Exit codes: 0 analysis "
                    "printed, 1 unreadable report or no flow "
                    "telemetry, 2 usage error")
    _add_verbosity(fl)
    flsub = fl.add_subparsers(dest="flow_action")
    fla = flsub.add_parser(
        "analyze",
        help="Print the critical path of one run report's flow "
             "telemetry")
    fla.add_argument("report", metavar="REPORT",
                     help="run_report.json carrying a `flow` section")
    fla.add_argument("--json", action="store_true",
                     help="Emit the critical-path attribution as JSON "
                          "instead of the rendered table")
    tp = sub.add_parser(
        "top",
        help="Live pipeline view from a run's heartbeat.jsonl (or a "
             "whole fleet dir)",
        description="Render the newest record of the heartbeat file a "
                    "run with GALAH_OBS_HEARTBEAT_S set writes beside "
                    "its run report: per-stage occupancy bars, queue "
                    "depths, and item throughput. Pointed at a fleet "
                    "dir (auto-detected from fleet_plan.json / "
                    "fleet_events.jsonl) it renders the per-shard "
                    "grid — state, attempt chain, beat age, occupancy, "
                    "rss — plus the scheduler event tail. Safe against "
                    "a run killed mid-write — a torn tail line is "
                    "skipped, never an error (docs/observability.md). "
                    "Exit codes: 0 rendered, 1 no heartbeat/fleet "
                    "data, 2 usage error")
    _add_verbosity(tp)
    tp.add_argument("directory", metavar="DIR",
                    help="Run artifact directory, a heartbeat.jsonl "
                         "path directly, or a fleet dir")
    tp.add_argument("--follow", action="store_true",
                    help="Keep refreshing until interrupted")
    tp.add_argument("--interval", type=float, default=2.0,
                    help="Refresh period in seconds with --follow "
                         "(default: 2.0)")
    tp.add_argument("--json", action="store_true",
                    help="Emit the latest beat (or, for a fleet dir, "
                         "the fleet grid) as JSON instead of the "
                         "rendered page")
    ix = sub.add_parser(
        "index",
        help="Build and incrementally maintain a persistent versioned "
             "sketch index (insert/query/remove without re-clustering)",
        description="Persistent versioned sketch index over a "
                    "dereplicated corpus: `build` clusters once and "
                    "persists the sketches, thresholded pairs, and "
                    "greedy decisions; `insert` adds new genomes "
                    "sketching only them and commits a new generation; "
                    "`query` answers which cluster a genome would join "
                    "without mutating anything; `remove` tombstones a "
                    "genome and locally re-elects; `fsck` audits the "
                    "on-disk state (docs/index.md)")
    _add_verbosity(ix)
    ix.add_argument("--index-dir",
                    help="Index directory (also via "
                         "GALAH_TPU_INDEX_DIR); created by `build`, "
                         "required by every action")
    ix.add_argument("--trace-events",
                    help="Write a Chrome-trace-format event timeline "
                         "to this file. Env equivalent: "
                         "GALAH_OBS_TRACE_EVENTS")
    ix.add_argument("--run-report",
                    help="Write run_report.json (with its `index` "
                         "section) to this file at run end. Env "
                         "equivalent: GALAH_OBS_REPORT")
    ixsub = ix.add_subparsers(dest="index_action")
    ixb = ixsub.add_parser(
        "build",
        help="Dereplicate a corpus once and persist it as generation 1")
    _add_genome_inputs(ixb)
    _add_index_quality(ixb)
    ixb.add_argument("--ani", type=float, default=Defaults.ANI,
                     help="ANI clustering threshold the index is bound "
                          "to (default: 95)")
    ixb.add_argument("--precluster-ani", type=float,
                     default=Defaults.PRETHRESHOLD_ANI,
                     help="Sketch-ANI floor for persisted pairs "
                          "(default: 90)")
    ixb.add_argument("--hash-algorithm", default=Defaults.HASH_ALGO,
                     choices=HASH_ALGORITHMS,
                     help="Sketch hash the index is bound to "
                          "(default: murmur3)")
    ixb.add_argument("--sketch-cache",
                     help="Directory for the persistent sketch cache "
                          "(also via GALAH_TPU_CACHE); index records "
                          "share its content-hash keys")
    ixb.add_argument("--threads", "-t", type=int, default=1)
    ixi = ixsub.add_parser(
        "insert",
        help="Insert new genomes, sketching only them, and commit the "
             "next generation")
    _add_genome_inputs(ixi)
    _add_index_quality(ixi)
    ixi.add_argument("--sketch-cache",
                     help="Directory for the persistent sketch cache "
                          "(also via GALAH_TPU_CACHE)")
    ixi.add_argument("--threads", "-t", type=int, default=1)
    ixi.add_argument("--batch", type=int, default=None,
                     help="Genomes per durable append batch — the "
                          "preemption safe-boundary granularity "
                          "(default: GALAH_TPU_INDEX_BATCH)")
    ixi.add_argument("--resume", action="store_true",
                     help="Continue an interrupted insert: uncommitted "
                          "appends past the last committed generation "
                          "are truncated and the insert redone, "
                          "converging to the same bytes as an "
                          "uninterrupted run. (A matching index "
                          "auto-resumes anyway; --resume records the "
                          "chain in the run report)")
    ixq = ixsub.add_parser(
        "query",
        help="Answer which cluster each genome would join, without "
             "mutating the index")
    _add_genome_inputs(ixq)
    ixq.add_argument("--sketch-cache",
                     help="Directory for the persistent sketch cache "
                          "(also via GALAH_TPU_CACHE)")
    ixq.add_argument("--threads", "-t", type=int, default=1)
    ixq.add_argument("--output",
                     help="Output TSV of query, decision, "
                          "representative, ANI (default: stdout)")
    ixr = ixsub.add_parser(
        "remove",
        help="Tombstone genomes and locally re-elect their clusters")
    _add_genome_inputs(ixr)
    ixsub.add_parser(
        "fsck",
        help="Audit the on-disk index: commit-pointer integrity, log "
             "checksums, cluster invariants (never mutates; jax-free)")
    ft = sub.add_parser(
        "fleet",
        help="Run one dereplication job across preemptible worker "
             "subprocesses (shard, supervise, reassign, merge)",
        description="Elastic preemptible-fleet execution: `run` shards "
                    "the quality-ordered genome set, supervises one "
                    "`galah-tpu cluster` worker per shard (exit 75, "
                    "SIGKILL and stale heartbeats all mean preemption "
                    "-> reassign; retry budget per shard), then merges "
                    "shard checkpoints into clusters byte-identical "
                    "to a single-process run; `status` renders a "
                    "fleet directory's plan/event/heartbeat state "
                    "(jax-free). See docs/resilience.md")
    _add_verbosity(ft)
    ftsub = ft.add_subparsers(dest="fleet_action")
    ftr = ftsub.add_parser(
        "run",
        help="Shard, supervise and merge one dereplication job")
    _add_genome_inputs(ftr)
    add_cluster_arguments(ftr)
    ftr.add_argument("--fleet-dir", required=True,
                     help="Fleet working directory: shard plan, event "
                          "log, per-shard checkpoints/reports live "
                          "here (the resume root)")
    ftr.add_argument("--workers", type=int,
                     help="Max live worker subprocesses (default: "
                          "GALAH_TPU_FLEET_WORKERS)")
    ftr.add_argument("--shards", type=int,
                     help="Shard count (default: GALAH_TPU_FLEET_SHARDS "
                          "or the worker cap)")
    ftr.add_argument("--stale-s", type=float,
                     help="Heartbeat staleness deadline in seconds "
                          "(default: GALAH_TPU_FLEET_STALE_S)")
    ftr.add_argument("--resume", action="store_true",
                     help="Require resuming the fleet at --fleet-dir: "
                          "fail if the plan is missing or belongs to "
                          "a different configuration. Without this "
                          "flag a matching plan still auto-resumes")
    ftr.add_argument("--sketch-cache",
                     help="Shared sketch/profile cache for workers and "
                          "the merge (default: <fleet-dir>/cache; also "
                          "via GALAH_TPU_CACHE)")
    ftr.add_argument("--run-report",
                     help="Write the supervisor's run_report.json "
                          "(with its `fleet` section) to this file. "
                          "Env equivalent: GALAH_OBS_REPORT")
    ftr.add_argument("--output-cluster-definition",
                     help="Output file of rep<TAB>member lines")
    ftr.add_argument("--output-representative-fasta-directory",
                     help="Symlink representative genomes into this "
                          "directory")
    ftr.add_argument("--output-representative-fasta-directory-copy",
                     help="Copy representative genomes into this "
                          "directory")
    ftr.add_argument("--output-representative-list",
                     help="Output file with one representative path "
                          "per line")
    fts = ftsub.add_parser(
        "status",
        help="Render a fleet directory's shard/event/heartbeat state "
             "(jax-free; usable while a fleet is live)")
    fts.add_argument("fleet_dir", help="Fleet working directory")
    fta = ftsub.add_parser(
        "analyze",
        help="Cross-shard critical path of a fleet dir: blame table "
             "(scheduler/compute/straggler/merge) summing to the "
             "fleet wall, and the named bottleneck (jax-free)",
        description="Aggregate fleet_events.jsonl + per-shard run "
                    "reports/heartbeats into the fleet_rollup blame "
                    "table, write fleet_report.json beside the plan, "
                    "and name the bottleneck. Tolerates torn tails, "
                    "shards missing mid-write, and v6-v8 shard "
                    "reports. Exit codes: 0 rollup printed, 1 "
                    "rollup-impossible dir (no event log), 2 usage "
                    "error")
    fta.add_argument("fleet_dir", help="Fleet working directory")
    fta.add_argument("--json", action="store_true",
                     help="Emit the rollup as JSON instead of the "
                          "blame table")
    fta.add_argument("--no-report", action="store_true",
                     help="Skip writing fleet_report.json (print "
                          "only)")
    parser._subcommand_parsers = {"cluster": c, "cluster-validate": v,
                                  "dist": dd, "lint": li, "report": rp,
                                  "perf": pf, "flow": fl, "top": tp,
                                  "index": ix, "fleet": ft}
    return parser


def run_dist(args) -> int:
    """All-pairs sketch ANI -> TSV of genome_a, genome_b, ani lines."""
    import sys as _sys

    from galah_tpu.backends.minhash_backend import SketchStore
    from galah_tpu.genome_inputs import parse_genome_inputs
    from galah_tpu.io import diskcache
    from galah_tpu.ops.minhash import sketch_matrix
    from galah_tpu.ops.pairwise import threshold_pairs

    genomes = parse_genome_inputs(
        genome_fasta_files=args.genome_fasta_files,
        genome_fasta_list=args.genome_fasta_list,
        genome_fasta_directory=args.genome_fasta_directory,
        genome_fasta_extension=args.genome_fasta_extension,
    )
    cache = diskcache.get_cache(getattr(args, "sketch_cache", None))
    store = SketchStore(args.num_hashes, args.kmer_length, cache=cache,
                        algo=args.hash_algorithm)
    logger.info("Sketching %d genomes ..", len(genomes))
    # host threads prefetch FASTA ingestion while the device sketches
    # (same idiom as MinHashPreclusterer.distances)
    from galah_tpu.io.fasta import read_genome
    from galah_tpu.io.prefetch import probe_and_prefetch

    by_path, miss_iter = probe_and_prefetch(
        genomes, store.get_cached, read_genome,
        depth=max(2, getattr(args, "threads", 1)))
    for p, genome in miss_iter:
        by_path[p] = store.put_from_genome(p, genome)
    mat = sketch_matrix([by_path[p] for p in genomes],
                        sketch_size=args.num_hashes)
    min_ani = (parse_percentage(args.min_ani, "--min-ani")
               if args.min_ani else 0.0)
    logger.info("Computing tiled all-pairs ANI ..")
    pairs = threshold_pairs(mat, k=args.kmer_length, min_ani=min_ani,
                            sketch_size=args.num_hashes)
    out = open(args.output, "w") if args.output else _sys.stdout
    try:
        for (i, j) in sorted(pairs):
            out.write(f"{genomes[i]}\t{genomes[j]}\t"
                      f"{pairs[(i, j)]:.6f}\n")
    finally:
        if args.output:
            out.close()
    logger.info("Wrote %d pairs", len(pairs))
    return 0


def run_cluster(args) -> int:
    import time as _time

    from galah_tpu import obs
    from galah_tpu.config import env_value
    from galah_tpu.resilience import interrupt

    # Telemetry lifecycle brackets the whole run: reset shared state,
    # open the trace sink if requested, and always finalize (write the
    # run report, close the trace) even when the run fails — a report
    # of a failed run is exactly when the stage tree matters most.
    # wall-clock stamp for the report header, not a duration measure
    started_at = _time.time()  # galah-lint: ignore[GL701]
    timing.reset()
    obs.reset_run()
    # Cooperative preemption: SIGTERM/SIGINT request a stop at the next
    # safe boundary (engine round edges / checkpoint flushes); the
    # finalize below then drains the report/ledger/trace writers before
    # the process exits with EXIT_PREEMPTED.
    interrupt.reset()
    interrupt.install()
    trace_path = (getattr(args, "trace_events", None)
                  or env_value("GALAH_OBS_TRACE_EVENTS"))
    if trace_path:
        obs.trace.start(trace_path)
    report_path = (getattr(args, "run_report", None)
                   or env_value("GALAH_OBS_REPORT"))
    # Liveness heartbeat beside the report sink, plus crash/preemption
    # flush hooks so an aborted run still leaves a final beat and a
    # closed trace behind.
    obs.install_crash_hooks()
    obs.heartbeat.maybe_start(report_path)
    try:
        return _run_cluster_inner(args)
    finally:
        interrupt.uninstall()
        obs.finalize("cluster", report_path=report_path,
                     started_at=started_at)


def _run_cluster_inner(args) -> int:
    from galah_tpu.genome_inputs import parse_genome_inputs
    from galah_tpu.io import diskcache
    from galah_tpu.outputs import setup_outputs, write_outputs
    from galah_tpu.parallel import distributed

    # Join the multi-host runtime when the standard JAX cluster env
    # vars are set (docs/DISTRIBUTED.md); a no-op otherwise. Every
    # host computes identical clusters; only process 0 writes outputs.
    distributed.initialize()

    from galah_tpu.resilience.quarantine import QuarantineManifest

    on_bad_genome = getattr(args, "on_bad_genome", "error") or "error"
    qmanifest = QuarantineManifest()
    genomes = parse_genome_inputs(
        genome_fasta_files=args.genome_fasta_files,
        genome_fasta_list=args.genome_fasta_list,
        genome_fasta_directory=args.genome_fasta_directory,
        genome_fasta_extension=args.genome_fasta_extension,
        on_bad_genome=on_bad_genome,
        manifest=qmanifest,
    )

    cache = diskcache.get_cache(getattr(args, "sketch_cache", None))
    if cache.enabled:
        logger.info("Using persistent sketch cache at %s", cache.path)

    # Quality filtering/ordering + backend construction live in the
    # embeddable factory (api.py, reference analog:
    # generate_galah_clusterer, src/cluster_argument_parsing.rs:897-1158)
    try:
        clusterer = generate_galah_clusterer(
            genomes, vars(args), cache=cache,
            quarantine_manifest=qmanifest)
    except ValueError as e:
        # User error (conflicting quality inputs, dRep + --genome-info):
        # a logged message and exit 1, not a traceback — the reference's
        # factory bails the same way.
        logger.error("%s", e)
        return 1
    genomes = clusterer.genome_paths

    # Open output handles before compute (fail fast). On multi-host
    # runs only process 0 writes — every host computes the identical
    # clusters, and N processes writing the same files would race.
    # Non-writers still VALIDATE the paths (without touching them) so
    # a bad output path fails every process before the first
    # collective instead of stalling the others in it.
    is_writer = distributed.process_index() == 0
    output_args = dict(
        cluster_definition=args.output_cluster_definition,
        representative_fasta_directory=(
            args.output_representative_fasta_directory),
        representative_fasta_directory_copy=(
            args.output_representative_fasta_directory_copy),
        representative_list=args.output_representative_list,
    )
    if is_writer:
        handles = setup_outputs(**output_args)
    else:
        from galah_tpu.outputs import validate_output_paths

        validate_output_paths(**output_args)
        handles = None

    ckpt = None
    if getattr(args, "resume", False) \
            and not getattr(args, "checkpoint_dir", None):
        logger.error("--resume requires --checkpoint-dir")
        return 1
    if getattr(args, "checkpoint_dir", None):
        from galah_tpu.cluster.checkpoint import (
            ClusterCheckpoint,
            fields_digest,
            fingerprint_fields,
        )
        from galah_tpu.resilience import interrupt

        # Multi-host: each process persists under its own subdirectory
        # — N processes appending to one shared checkpoint would
        # interleave/corrupt it, and gating persistence to one process
        # would desynchronize the collective-participating distance
        # pass on resume (the loader skips it, the others don't).
        ckpt_dir = args.checkpoint_dir
        if distributed.process_count() > 1:
            import os as _os

            ckpt_dir = _os.path.join(
                ckpt_dir, f"proc_{distributed.process_index()}")
        fields = fingerprint_fields(
            genomes, args.precluster_method, args.cluster_method,
            parse_percentage(args.ani, "--ani"),
            parse_percentage(args.precluster_ani, "--precluster-ani"),
            min_aligned_fraction=parse_percentage(
                args.min_aligned_fraction, "--min-aligned-fraction"),
            fragment_length=args.fragment_length,
            backend_params=clusterer.backend_params)
        ckpt = ClusterCheckpoint(
            ckpt_dir, fields_digest(fields), fields=fields,
            require_match=getattr(args, "resume", False))
        # Resume chain for the run report: a matching checkpoint with
        # recorded interruptions means this run continues a preempted
        # one (whether or not --resume was passed).
        prior = ckpt.load_interruptions()
        if ckpt.matched_existing and (prior
                                      or getattr(args, "resume",
                                                 False)):
            from galah_tpu.obs import events

            interrupt.note_resume(ckpt_dir, len(prior))
            events.record("resumed", checkpoint_dir=ckpt_dir,
                          prior_interruptions=len(prior))
        # All-or-nothing resume across hosts: a crash can land between
        # two hosts' checkpoint saves, and resuming from uneven state
        # would deadlock the collective-participating distance pass
        # (the host with a checkpoint skips it, the others enter it).
        # If the per-process states differ, every host drops its
        # resumable state and recomputes symmetrically.
        if distributed.process_count() > 1 and not \
                distributed.tokens_agree(ckpt.state_token()):
            logger.warning(
                "Checkpoint state differs across hosts; dropping it "
                "and recomputing so all hosts stay in lockstep")
            ckpt.reset_state()
        clusterer.checkpoint = ckpt

    from galah_tpu.resilience import interrupt

    logger.info("Clustering %d genomes ..", len(genomes))
    try:
        with timing.trace_context(
                getattr(args, "profile_trace_dir", None)):
            clusters = clusterer.cluster()
    except interrupt.PreemptionRequested as e:
        # Cooperative preemption: everything before the boundary is
        # already durable, so record the interruption, emit the event,
        # and exit EXIT_PREEMPTED — obs.finalize (run_cluster) drains
        # the report/trace/ledger writers on the way out.
        import time as _time

        from galah_tpu.obs import events

        events.record("preempted", signal=e.signame,
                      boundary=e.boundary)
        if ckpt is not None:
            ckpt.record_interruption({
                "signal": e.signame,
                "boundary": e.boundary,
                # wall-clock stamp for the chain record, not a duration
                "ts": _time.time(),  # galah-lint: ignore[GL701]
            })
        logger.warning(
            "Preempted (%s): stopped at safe boundary %r. The "
            "checkpoint%s is consistent; rerun with --resume to "
            "continue. Exiting %d.", e.signame, e.boundary,
            f" at {ckpt.path}" if ckpt is not None else "",
            interrupt.EXIT_PREEMPTED)
        return interrupt.EXIT_PREEMPTED
    logger.info("Found %d genome clusters", len(clusters))

    if is_writer:
        with timing.stage("write-outputs"):
            write_outputs(handles, clusters, genomes)
        logger.info("Finished printing genome clusters")
    else:
        logger.info("Non-zero process: outputs written by process 0")

    # Quarantined inputs (--on-bad-genome skip) land in a manifest next
    # to the outputs. Every host computed the identical quarantine set
    # (resilience/quarantine.py's OR-exchange); only the writer writes.
    if clusterer.quarantine is not None and len(clusterer.quarantine):
        from galah_tpu.resilience.quarantine import manifest_output_dir

        if is_writer:
            clusterer.quarantine.write(manifest_output_dir(
                cluster_definition=args.output_cluster_definition,
                representative_list=args.output_representative_list,
                checkpoint_dir=getattr(args, "checkpoint_dir", None)))

    # Any mid-run demotions (device dispatch -> CPU fallback) belong in
    # the run summary: the run completed, but not on the fast path.
    from galah_tpu.resilience import dispatch as rdispatch

    for dem in rdispatch.demotions():
        logger.warning("Dispatch site %s ran DEMOTED to its fallback "
                       "after persistent failures (%s)",
                       dem.site, dem.reason)
    timing.GLOBAL.report(logger)
    return 0


def run_fleet(args) -> int:
    """`galah-tpu fleet run`: same telemetry lifecycle as run_cluster
    (the supervisor writes its own run report, with a `fleet`
    section)."""
    import time as _time

    from galah_tpu import obs
    from galah_tpu.config import env_value
    from galah_tpu.resilience import interrupt

    # wall-clock stamp for the report header, not a duration measure
    started_at = _time.time()  # galah-lint: ignore[GL701]
    timing.reset()
    obs.reset_run()
    interrupt.reset()
    interrupt.install()
    trace_path = (getattr(args, "trace_events", None)
                  or env_value("GALAH_OBS_TRACE_EVENTS"))
    if trace_path:
        obs.trace.start(trace_path)
    report_path = (getattr(args, "run_report", None)
                   or env_value("GALAH_OBS_REPORT"))
    obs.install_crash_hooks()
    obs.heartbeat.maybe_start(report_path, role="scheduler")
    # Every heartbeat tick's OpenMetrics page carries the live
    # cross-shard rollup when the exporter flag is set (best-effort:
    # a not-yet-rollable dir just omits the fleet series).
    from galah_tpu.obs import fleet_view
    from galah_tpu.obs import openmetrics as obs_openmetrics

    fleet_dir = args.fleet_dir
    obs_openmetrics.set_rollup_provider(
        lambda: fleet_view.rollup(fleet_dir))
    try:
        return _run_fleet_inner(args)
    finally:
        interrupt.uninstall()
        obs.finalize("fleet", report_path=report_path,
                     started_at=started_at)


def _fleet_worker_argv(args, fleet_dir: str, cache_path: str):
    """Worker command-line builder: one `galah-tpu cluster` run per
    shard, genomes passed explicitly in (already quality-ordered)
    shard order so the worker never re-orders them."""
    from galah_tpu.fleet import scheduler as fleet_scheduler

    def worker_argv(spec, resume: bool):
        sid = spec.shard_id
        argv = [sys.executable, "-m", "galah_tpu.cli", "cluster",
                "--genome-fasta-files", *spec.genomes,
                "--ani", str(args.ani),
                "--precluster-ani", str(args.precluster_ani),
                "--min-aligned-fraction",
                str(args.min_aligned_fraction),
                "--fragment-length", str(args.fragment_length),
                "--precluster-method", args.precluster_method,
                "--cluster-method", args.cluster_method,
                "--ani-subsample", str(args.ani_subsample),
                "--hash-algorithm", args.hash_algorithm,
                "--threads", str(getattr(args, "threads", 1) or 1),
                "--checkpoint-dir",
                fleet_scheduler.shard_ckpt_dir(fleet_dir, sid),
                "--run-report",
                fleet_scheduler.shard_report_path(fleet_dir, sid),
                "--output-cluster-definition",
                fleet_scheduler.shard_tsv_path(fleet_dir, sid)]
        if cache_path:
            argv += ["--sketch-cache", cache_path]
        if resume:
            argv.append("--resume")
        return argv

    return worker_argv


def _run_fleet_inner(args) -> int:
    import time as _time

    from galah_tpu import fleet as fleet_pkg
    from galah_tpu.cluster.checkpoint import fingerprint_fields
    from galah_tpu.config import env_value
    from galah_tpu.fleet import merge as fleet_merge
    from galah_tpu.fleet import plan as fleet_plan
    from galah_tpu.fleet.scheduler import FleetScheduler, append_stamp
    from galah_tpu.genome_inputs import parse_genome_inputs
    from galah_tpu.io import atomic, diskcache
    from galah_tpu.obs import events
    from galah_tpu.outputs import setup_outputs, write_outputs
    from galah_tpu.resilience import interrupt
    from galah_tpu.resilience.quarantine import QuarantineManifest

    # v1 gate: the merge-determinism argument needs shard checkpoints
    # thresholded at the FINAL ANI, which is exactly the skani/skani
    # configuration (api.py pins precluster_ani = ani there). Other
    # method combinations shard correctly but merge approximately —
    # refuse rather than silently weaken the byte-identical contract.
    if (args.precluster_method != "skani"
            or args.cluster_method != "skani"):
        logger.error(
            "fleet run requires --precluster-method skani and "
            "--cluster-method skani (got %s/%s): the cross-shard "
            "merge is only byte-identical when shard checkpoints are "
            "thresholded at the final ANI", args.precluster_method,
            args.cluster_method)
        return 1

    fleet_dir = args.fleet_dir
    on_bad_genome = getattr(args, "on_bad_genome", "error") or "error"
    qmanifest = QuarantineManifest()
    genomes = parse_genome_inputs(
        genome_fasta_files=args.genome_fasta_files,
        genome_fasta_list=args.genome_fasta_list,
        genome_fasta_directory=args.genome_fasta_directory,
        genome_fasta_extension=args.genome_fasta_extension,
        on_bad_genome=on_bad_genome,
        manifest=qmanifest,
    )

    # One shared profile cache across workers and the merge: shard
    # profiling warms it, the merge's cross-shard pass reuses it.
    cache_path = (getattr(args, "sketch_cache", None)
                  or diskcache.default_cache_dir()
                  or os.path.join(fleet_dir, "cache"))
    cache = diskcache.get_cache(cache_path)
    logger.info("Using shared fleet sketch cache at %s", cache.path)

    try:
        clusterer = generate_galah_clusterer(
            genomes, vars(args), cache=cache,
            quarantine_manifest=qmanifest)
    except ValueError as e:
        logger.error("%s", e)
        return 1
    genomes = clusterer.genome_paths
    ani = parse_percentage(args.ani, "--ani")

    # only None means unset: `--workers 0` / `--shards 0` must be
    # rejected below, not silently coerced to the env/default value
    workers = (args.workers if args.workers is not None
               else int(env_value("GALAH_TPU_FLEET_WORKERS") or 2))
    n_shards = (args.shards if args.shards is not None
                else int(env_value("GALAH_TPU_FLEET_SHARDS") or 0)
                or workers)
    if workers < 1:
        logger.error("--workers must be >= 1, got %d", workers)
        return 1
    stale_s = (args.stale_s if args.stale_s is not None
               else float(env_value("GALAH_TPU_FLEET_STALE_S") or 30))
    poll_s = float(env_value("GALAH_TPU_FLEET_POLL_S") or 0.2)
    heartbeat_s = float(
        env_value("GALAH_TPU_FLEET_HEARTBEAT_S") or 1)

    fields = fingerprint_fields(
        genomes, args.precluster_method, args.cluster_method, ani,
        parse_percentage(args.precluster_ani, "--precluster-ani"),
        min_aligned_fraction=parse_percentage(
            args.min_aligned_fraction, "--min-aligned-fraction"),
        fragment_length=args.fragment_length,
        backend_params=clusterer.backend_params)
    try:
        shards = fleet_plan.ensure_plan(
            fleet_dir, genomes, fields, n_shards,
            require_match=getattr(args, "resume", False))
    except ValueError as e:
        logger.error("%s", e)
        return 1
    logger.info("Fleet plan: %d genomes in %d shard(s), %d worker(s)",
                len(genomes), len(shards), workers)

    # Open output handles before compute (fail fast), like cluster.
    handles = setup_outputs(
        cluster_definition=args.output_cluster_definition,
        representative_fasta_directory=(
            args.output_representative_fasta_directory),
        representative_fasta_directory_copy=(
            args.output_representative_fasta_directory_copy),
        representative_list=args.output_representative_list,
    )

    # Resume chain: prior fleet-interrupted events mean this run
    # continues a preempted supervisor.
    prior_records, _torn = atomic.read_jsonl(
        fleet_plan.events_path(fleet_dir))
    prior = [r for r in prior_records if isinstance(r, dict)
             and r.get("ev") == "fleet-interrupted"]
    if prior or getattr(args, "resume", False):
        if prior_records:
            interrupt.note_resume(fleet_dir, len(prior))
            events.record("resumed", fleet_dir=fleet_dir,
                          prior_interruptions=len(prior))

    sched = FleetScheduler(
        fleet_dir, shards,
        _fleet_worker_argv(args, fleet_dir, cache.path or cache_path),
        workers=workers, stale_s=stale_s, poll_s=poll_s,
        heartbeat_s=heartbeat_s)
    try:
        with timing.stage("fleet-supervise"):
            snap = sched.run()
    except interrupt.PreemptionRequested as e:
        events.record("preempted", signal=e.signame,
                      boundary=e.boundary)
        fleet_pkg.set_snapshot(sched.snapshot())
        logger.warning(
            "Fleet preempted (%s) at %r: worker checkpoints are "
            "consistent; rerun with --resume to continue. Exiting %d.",
            e.signame, e.boundary, interrupt.EXIT_PREEMPTED)
        return interrupt.EXIT_PREEMPTED

    if snap["shards_failed"]:
        fleet_pkg.set_snapshot(snap)
        logger.error(
            "%d shard(s) exhausted their retry budget (see "
            "fleet-shard-failed events at %s); not merging a partial "
            "fleet", snap["shards_failed"],
            fleet_plan.events_path(fleet_dir))
        return 1

    merge_t0 = _time.monotonic()
    with timing.stage("fleet-merge"):
        clusters = fleet_merge.merge(fleet_dir, genomes, shards,
                                     clusterer.preclusterer, ani)
    snap["merge_wall_s"] = round(_time.monotonic() - merge_t0, 6)
    snap["n_genomes"] = len(genomes)
    # rollup-ready stamp: fleet_view charges this window to merge
    # blame; appended to the event log (not only the report) so
    # `fleet analyze` works on dirs whose report never landed
    append_stamp(fleet_dir, "fleet-merge-done",
                 wall_s=snap["merge_wall_s"])
    fleet_pkg.set_snapshot(snap)
    logger.info("Found %d genome clusters", len(clusters))

    with timing.stage("write-outputs"):
        write_outputs(handles, clusters, genomes)
    logger.info("Finished printing genome clusters")

    if clusterer.quarantine is not None and len(clusterer.quarantine):
        from galah_tpu.resilience.quarantine import manifest_output_dir

        clusterer.quarantine.write(manifest_output_dir(
            cluster_definition=args.output_cluster_definition,
            representative_list=args.output_representative_list,
            checkpoint_dir=fleet_dir))
    timing.GLOBAL.report(logger)
    return 0


def run_fleet_status(args) -> int:
    """`galah-tpu fleet status`: jax-free rendering of a fleet dir."""
    from galah_tpu.fleet.scheduler import render_status

    sys.stdout.write(render_status(args.fleet_dir))
    return 0


def run_fleet_analyze(args) -> int:
    """`galah-tpu fleet analyze`: cross-shard critical path of a fleet
    dir — blame table summing to the fleet wall, fleet_report.json
    beside the plan, and the named bottleneck. Pure file I/O (jax-free,
    runs against live and half-written fleet dirs alike)."""
    import json as _json
    import time as _time

    from galah_tpu.obs import fleet_view

    # wall-clock stamp for the report header, not a duration measure
    started_at = _time.time()  # galah-lint: ignore[GL701]
    ru = fleet_view.rollup(args.fleet_dir)
    if ru is None:
        logger.error(
            "%s: rollup-impossible — no fleet event log (run "
            "`galah-tpu fleet run --fleet-dir %s` first)",
            args.fleet_dir, args.fleet_dir)
        return 1
    if not getattr(args, "no_report", False):
        try:
            path = fleet_view.write_fleet_report(
                args.fleet_dir, ru, argv=sys.argv,
                started_at=started_at)
            logger.info("Wrote %s", path)
        except Exception:  # rendering still succeeds without the file
            logger.warning("fleet_report.json write failed",
                           exc_info=True)
    if getattr(args, "json", False):
        print(_json.dumps(ru, indent=1, sort_keys=True))
        return 0
    for line in fleet_view.render_rollup(ru):
        print(line)
    return 0


def run_cluster_validate(args) -> int:
    from galah_tpu.backends import FastANIEquivalentClusterer, ProfileStore
    from galah_tpu.validate import validate_clusters

    if not args.cluster_file:
        logger.error("--cluster-file is required")
        return 1
    ani = parse_percentage(args.ani, "--ani")
    min_af = parse_percentage(args.min_aligned_fraction,
                              "--min-aligned-fraction")
    raw = getattr(args, "ani_subsample", None)
    subsample = int(raw if raw is not None else 1)
    if not 1 <= subsample <= 1000:
        logger.error("--ani-subsample must be in [1, 1000], got %s",
                     subsample)
        return 1
    clusterer = FastANIEquivalentClusterer(
        threshold=ani, min_aligned_fraction=min_af,
        fraglen=args.fragment_length,
        store=ProfileStore(fraglen=args.fragment_length,
                           subsample_c=subsample,
                           hash_algorithm=getattr(
                               args, "hash_algorithm",
                               Defaults.HASH_ALGO)))
    validate_clusters(args.cluster_file, clusterer)
    return 0


def run_report_cmd(args) -> int:
    """Render run_report.json files, or diff two of them."""
    from galah_tpu.obs import report as report_mod

    loaded = []
    for path in args.paths:
        try:
            rep = report_mod.load(path)
        except Exception as e:  # noqa: BLE001 — bad JSON, missing file
            logger.error("%s: cannot read run report (%s)", path, e)
            return 1
        problems = report_mod.validate(rep)
        if problems:
            logger.error("%s: not a valid run report: %s", path,
                         problems[0])
            return 1
        loaded.append((path, rep))
    if args.diff:
        if len(loaded) != 2:
            logger.error("report --diff takes exactly two reports, "
                         "got %d", len(loaded))
            return 1
        (pa, ra), (pb, rb) = loaded
        sys.stdout.write(report_mod.diff(ra, rb, label_a=pa, label_b=pb))
        return 0
    for i, (path, rep) in enumerate(loaded):
        if i:
            sys.stdout.write("\n")
        sys.stdout.write(report_mod.render(rep))
    return 0


def run_perf_cmd(args) -> int:
    """`galah-tpu perf record|history|check` over the JSONL ledger.
    Pure file I/O (like `report`): never touches jax."""
    from galah_tpu.config import env_value
    from galah_tpu.obs import ledger as ledger_mod
    from galah_tpu.obs import report as report_mod

    ledger_path = args.ledger or env_value("GALAH_OBS_LEDGER")
    if not ledger_path:
        logger.error("no ledger: pass --ledger or set "
                     "GALAH_OBS_LEDGER")
        return 1
    action = getattr(args, "perf_action", None)
    if action is None:
        logger.error("perf needs an action: record, history, or check")
        return 1

    if action == "record":
        try:
            rep = report_mod.load(args.report)
        except Exception as e:  # noqa: BLE001 — bad JSON, missing file
            logger.error("%s: cannot read run report (%s)",
                         args.report, e)
            return 1
        entry = ledger_mod.entry_from_report(rep, args.source)
        ledger_mod.append(ledger_path, entry)
        print(f"recorded {len(entry['metrics'])} metric(s) to "
              f"{ledger_path}")
        return 0

    entries, skipped = ledger_mod.read(ledger_path)
    if skipped:
        logger.warning("%s: skipped %d torn/corrupt line(s)",
                       ledger_path, skipped)

    if action == "history":
        rows = ledger_mod.history(entries, args.metric)
        if args.key:
            rows = [r for r in rows if args.key in r["key"]]
        if not rows:
            print(f"no entries carry metric {args.metric!r}")
            return 0
        for r in rows:
            ts = time.strftime("%Y-%m-%d %H:%M",
                               time.localtime(r["ts"] or 0))
            print(f"{ts}  {r['sha'] or '-':>9}  {r['value']:<14.6g} "
                  f"{r['key']}")
        return 0

    # check
    if getattr(args, "report", None):
        try:
            rep = report_mod.load(args.report)
        except Exception as e:  # noqa: BLE001
            logger.error("%s: cannot read run report (%s)",
                         args.report, e)
            return 1
        current = ledger_mod.entry_from_report(rep, args.source)
        history = entries
    else:
        if not entries:
            print("ledger is empty; nothing to check")
            return 0
        current, history = entries[-1], entries[:-1]
    window = (args.window if args.window is not None
              else int(env_value("GALAH_OBS_LEDGER_WINDOW")))
    mad_k = (args.mad_k if args.mad_k is not None
             else float(env_value("GALAH_OBS_LEDGER_MAD_K")))
    min_history = (args.min_history if args.min_history is not None
                   else ledger_mod.MIN_HISTORY)
    verdicts = ledger_mod.check(history, current, window=window,
                                mad_k=mad_k, min_history=min_history)
    bad = ledger_mod.regressions(verdicts)
    counts: dict = {}
    for v in verdicts:
        counts[v["verdict"]] = counts.get(v["verdict"], 0) + 1
    for v in verdicts:
        if v["verdict"] in ("ok", "insufficient-history"):
            continue
        band = v.get("band")
        band_s = (f" band=[{band[0]:.6g}, {band[1]:.6g}] "
                  f"(median {v['median']:.6g}, n={v['n_history']})"
                  if band else "")
        print(f"{v['verdict'].upper()}: {v['metric']} = "
              f"{v['value']:.6g}{band_s}")
    summary = ", ".join(f"{k}={counts[k]}" for k in sorted(counts)) \
        or "no comparable metrics"
    print(f"perf check [{ledger_mod.key_of(current)}]: {summary}")
    if bad and args.soft:
        print(f"--soft: {len(bad)} regression(s) reported, not gated")
        return 0
    return 1 if bad else 0


def run_flow_cmd(args) -> int:
    """`galah-tpu flow analyze`: critical-path attribution from a run
    report's flow section. Pure file I/O (like `report`): never
    touches jax."""
    import json as _json

    from galah_tpu.obs import flow as flow_mod
    from galah_tpu.obs import report as report_mod

    action = getattr(args, "flow_action", None)
    if action is None:
        logger.error("flow needs an action: analyze")
        return 1
    try:
        rep = report_mod.load(args.report)
    except Exception as e:  # noqa: BLE001 — bad JSON, missing file
        logger.error("%s: cannot read run report (%s)", args.report, e)
        return 1
    snap = rep.get("flow") or {}
    if not snap.get("stages"):
        logger.error("%s: no flow telemetry (run a pipelined "
                     "subcommand with GALAH_OBS_FLOW=1)", args.report)
        return 1
    wall = rep.get("run", {}).get("duration_s") or 0.0
    cp = flow_mod.critical_path(snap, float(wall))
    if getattr(args, "json", False):
        print(_json.dumps(cp, indent=1, sort_keys=True))
        return 0
    for line in flow_mod.render_critical_path(cp):
        print(line)
    return 0


def run_top_cmd(args) -> int:
    """`galah-tpu top <dir>`: render the newest heartbeat of a live
    (or finished) run — or, for a fleet dir (auto-detected from the
    plan/event log), the per-shard fleet grid. Pure file I/O: never
    touches jax, tolerates a torn tail line from a run killed
    mid-append. Exit codes: 0 rendered, 1 no data."""
    import json as _json

    from galah_tpu.obs import fleet_view
    from galah_tpu.obs import heartbeat as heartbeat_mod

    follow = bool(getattr(args, "follow", False))
    as_json = bool(getattr(args, "json", False))
    interval = max(float(getattr(args, "interval", 2.0) or 2.0), 0.1)
    fleet_mode = (os.path.isdir(args.directory)
                  and fleet_view.is_fleet_dir(args.directory))
    while True:
        if fleet_mode:
            grid = fleet_view.fleet_grid(args.directory)
            ok = bool(grid and (grid["shards"] or grid["events"]))
            if as_json:
                sys.stdout.write(_json.dumps(
                    grid or {}, indent=1, sort_keys=True) + "\n")
            else:
                sys.stdout.write(fleet_view.render_fleet_grid(
                    grid or {"fleet_dir": args.directory}))
        else:
            records, _torn = heartbeat_mod.load(args.directory)
            ok = bool(records)
            if as_json:
                latest = records[-1] if records else None
                sys.stdout.write(_json.dumps(
                    latest, indent=1, sort_keys=True) + "\n")
            else:
                sys.stdout.write(
                    heartbeat_mod.render_latest(args.directory))
        sys.stdout.flush()
        if not follow:
            return 0 if ok else 1
        try:
            time.sleep(interval)
        except KeyboardInterrupt:
            return 0


def _index_order_genomes(genomes, args):
    """Quality-order genomes for index build/insert; with no quality
    input, fall back to input order LOUDLY: a distinct warn_once key,
    a resilience event, and a counter (the run report shows the index
    was grown unranked — representative choice is input-order luck)."""
    from galah_tpu.api import quality_order_genomes

    ordered, used_quality = quality_order_genomes(
        genomes, vars(args),
        threads=int(getattr(args, "threads", 1) or 1),
        missing_key="index-quality-fallback",
        missing_msg="Since CheckM input is missing, genomes enter the "
                    "index in input order, not quality order — "
                    "representative selection is unranked. Pass "
                    "--checkm-tab-table / --checkm2-quality-report / "
                    "--genome-info to rank them")
    if not used_quality:
        from galah_tpu.obs import events
        from galah_tpu.obs import metrics as obs_metrics

        events.record("index-quality-fallback", n_genomes=len(ordered))
        obs_metrics.counter(
            "index.quality_fallback",
            help="Index build/insert batches ordered by input order "
                 "because no quality input was given",
            unit="batches").inc()
    return ordered


def _run_index_fsck(index_dir: str) -> int:
    # Pure file I/O + checksum math: usable on hosts with no
    # accelerator, so it must stay out of the jax-touching path below.
    from galah_tpu.index import store as index_store

    rep = index_store.fsck(index_dir)
    print(f"index {rep['path']}: generation {rep['generation']}, "
          f"{rep['genomes']} genome(s), {rep['clusters']} cluster(s), "
          f"{rep['pairs']} pair(s), {rep['tombstones']} tombstone(s)")
    for w in rep["warnings"]:
        print(f"  warning: {w}")
    for p in rep["problems"]:
        print(f"  PROBLEM: {p}")
    print("fsck: OK" if rep["ok"] else "fsck: FAILED")
    return 0 if rep["ok"] else 1


def run_index(args) -> int:
    import time as _time

    from galah_tpu import obs
    from galah_tpu.config import env_value
    from galah_tpu.resilience import interrupt

    action = getattr(args, "index_action", None)
    if action is None:
        logger.error("index needs an action: build, insert, query, "
                     "remove, or fsck")
        return 1
    index_dir = (getattr(args, "index_dir", None)
                 or env_value("GALAH_TPU_INDEX_DIR"))
    if not index_dir:
        logger.error("no index directory: pass --index-dir or set "
                     "GALAH_TPU_INDEX_DIR")
        return 1
    if action == "fsck":
        return _run_index_fsck(index_dir)
    # Same telemetry lifecycle as run_cluster: reset shared state, arm
    # cooperative preemption, always finalize the report/trace.
    # wall-clock stamp for the report header, not a duration measure
    started_at = _time.time()  # galah-lint: ignore[GL701]
    timing.reset()
    obs.reset_run()
    interrupt.reset()
    interrupt.install()
    trace_path = (getattr(args, "trace_events", None)
                  or env_value("GALAH_OBS_TRACE_EVENTS"))
    if trace_path:
        obs.trace.start(trace_path)
    report_path = (getattr(args, "run_report", None)
                   or env_value("GALAH_OBS_REPORT"))
    # Same heartbeat + crash-flush wiring as run_cluster.
    obs.install_crash_hooks()
    obs.heartbeat.maybe_start(report_path)
    try:
        return _run_index_inner(args, action, index_dir)
    finally:
        interrupt.uninstall()
        obs.finalize("index", report_path=report_path,
                     started_at=started_at)


def _run_index_inner(args, action: str, index_dir: str) -> int:
    import sys as _sys
    import time as _time

    from galah_tpu.genome_inputs import parse_genome_inputs
    from galah_tpu.index import incremental
    from galah_tpu.index.store import IndexStore
    from galah_tpu.resilience import interrupt

    genomes = parse_genome_inputs(
        genome_fasta_files=args.genome_fasta_files,
        genome_fasta_list=getattr(args, "genome_fasta_list", None),
        genome_fasta_directory=getattr(args, "genome_fasta_directory",
                                       None),
        genome_fasta_extension=getattr(args, "genome_fasta_extension",
                                       "fna"),
    )

    if action == "build":
        ordered = _index_order_genomes(genomes, args)
        info = incremental.build(
            index_dir, ordered,
            ani=parse_percentage(args.ani, "--ani"),
            precluster_ani=parse_percentage(args.precluster_ani,
                                            "--precluster-ani"),
            algo=args.hash_algorithm,
            cache_dir=getattr(args, "sketch_cache", None),
            threads=args.threads)
        logger.info("Built index at %s: generation %d, %d genomes in "
                    "%d clusters", index_dir, info["generation"],
                    info["genomes"], info["clusters"])
        return 0

    idx = IndexStore(index_dir)
    if action == "insert":
        ordered = _index_order_genomes(genomes, args)
        prior = idx.load_interruptions()
        if prior or getattr(args, "resume", False):
            from galah_tpu.obs import events

            interrupt.note_resume(index_dir, len(prior))
            events.record("resumed", index_dir=index_dir,
                          prior_interruptions=len(prior))
        try:
            info = incremental.insert(
                idx, ordered,
                cache_dir=getattr(args, "sketch_cache", None),
                threads=args.threads,
                batch=getattr(args, "batch", None))
        except interrupt.PreemptionRequested as e:
            from galah_tpu.obs import events

            events.record("preempted", signal=e.signame,
                          boundary=e.boundary)
            idx.record_interruption({
                "signal": e.signame,
                "boundary": e.boundary,
                # wall-clock stamp for the chain record, not a duration
                "ts": _time.time(),  # galah-lint: ignore[GL701]
            })
            logger.warning(
                "Preempted (%s): stopped at safe boundary %r. The "
                "index at %s is loadable at its last committed "
                "generation; rerun the same insert (--resume) to "
                "converge to the uninterrupted result. Exiting %d.",
                e.signame, e.boundary, index_dir,
                interrupt.EXIT_PREEMPTED)
            return interrupt.EXIT_PREEMPTED
        logger.info("Inserted %d genome(s) (%d skipped as already "
                    "present): generation %d, %d genomes in %d "
                    "clusters, %d new representative(s)",
                    info["inserted"], info["skipped"],
                    info["generation"], info["genomes"],
                    info["clusters"], info.get("new_reps", 0))
        return 0

    if action == "query":
        results = incremental.query(
            idx, genomes,
            cache_dir=getattr(args, "sketch_cache", None),
            threads=args.threads)
        out = open(args.output, "w") if args.output else _sys.stdout
        try:
            out.write("query\tdecision\trepresentative\tani\n")
            for r in results:
                ani = (f"{r['ani'] * 100:.4f}"
                       if r["ani"] is not None else "NA")
                out.write(f"{r['path']}\t{r['decision']}\t"
                          f"{r['rep'] or 'NA'}\t{ani}\n")
        finally:
            if args.output:
                out.close()
        return 0

    # remove
    for p in genomes:
        info = incremental.remove(idx, p)
        logger.info("Removed %s: generation %d, %d genomes in %d "
                    "clusters remain", p, info["generation"],
                    info["genomes"], info["clusters"])
    return 0


def main(argv=None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    if args.subcommand is None:
        parser.print_help()
        return 1
    if getattr(args, "full_help_roff", False):
        from galah_tpu.manpage import render_full_help_roff

        sys.stdout.write(render_full_help_roff(
            parser._subcommand_parsers[args.subcommand],
            args.subcommand))
        return 0
    if getattr(args, "full_help", False):
        from galah_tpu.manpage import print_full_help

        print_full_help(parser._subcommand_parsers[args.subcommand],
                        args.subcommand)
        return 0
    if args.subcommand == "lint":
        # CPU is all the lint needs (the shape harness only abstract-
        # evals); x64 keeps the uint64 ops tracing with real dtypes.
        # Both must land before any jax import.
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        os.environ.setdefault("JAX_ENABLE_X64", "1")
        from galah_tpu.analysis import main as lint_main

        return lint_main(args=args)
    set_log_level(verbose=getattr(args, "verbose", False),
                  quiet=getattr(args, "quiet", False))
    if args.subcommand == "report":
        # Pure file I/O — never touches jax, so it skips the platform
        # probe and works on hosts with no usable accelerator at all.
        return run_report_cmd(args)
    if args.subcommand == "perf":
        # Same discipline: the ledger gate must run on CI hosts and
        # laptops with no accelerator, so it never imports jax.
        return run_perf_cmd(args)
    if args.subcommand == "flow":
        # Critical-path math over an already-written report — jax-free.
        return run_flow_cmd(args)
    if args.subcommand == "top":
        # Tails heartbeat.jsonl — jax-free, usable while a run is live.
        return run_top_cmd(args)
    if args.subcommand == "fleet" and \
            getattr(args, "fleet_action", None) != "run":
        # `fleet status`/`fleet analyze` read plan/events/heartbeats/
        # shard reports — jax-free, so they work beside a live fleet
        # on accelerator-less hosts too.
        if getattr(args, "fleet_action", None) == "status":
            return run_fleet_status(args)
        if getattr(args, "fleet_action", None) == "analyze":
            return run_fleet_analyze(args)
        parser._subcommand_parsers["fleet"].print_help()
        return 1
    platform = (getattr(args, "platform", None)
                or os.environ.get("GALAH_TPU_PLATFORM"))
    if platform:
        # Must land before the first jax USE (backend init), which only
        # happens inside the subcommands — the lazy import layout above
        # guarantees that. jax.config wins over the JAX_PLATFORMS env
        # var even when an interpreter sitecustomize pinned it.
        import jax

        jax.config.update("jax_platforms", platform)
        try:
            # Probe now so a bad/unavailable platform is a clean
            # one-line user error, not a traceback at first device use.
            # jax surfaces this as RuntimeError or, with plugin-patched
            # bridges, a bare AssertionError — any failure here means
            # the forced platform cannot initialize.
            jax.default_backend()
        except Exception as e:  # noqa: BLE001
            msg = str(e).splitlines()[0] if str(e) else type(e).__name__
            logger.error("--platform %s: backend failed to initialize "
                         "(%s)", platform, msg)
            return 1
    logger.info("galah-tpu version %s", galah_tpu.__version__)
    # GALAH_SAN=1 arms the runtime concurrency sanitizer for this run
    # (the chaos harness and validation script set it); its summary
    # lands in the run report via obs.report.assemble.
    from galah_tpu.analysis import sanitizer as galah_san

    galah_san.maybe_install()
    try:
        if args.subcommand == "cluster":
            return run_cluster(args)
        elif args.subcommand == "dist":
            return run_dist(args)
        elif args.subcommand == "index":
            return run_index(args)
        elif args.subcommand == "fleet":
            return run_fleet(args)
        else:
            return run_cluster_validate(args)
    except (ValueError, OSError, KeyError) as e:
        # expected user errors: clean message, nonzero exit, no traceback.
        # str(e) for OS errors (args[0] would be the bare errno); args[0]
        # for KeyError/ValueError (str(KeyError) quotes the repr).
        if isinstance(e, OSError):
            logger.error("%s", e)
        else:
            logger.error("%s", e.args[0] if e.args else e)
        return 1


if __name__ == "__main__":
    sys.exit(main())
