"""Command-line interface: `galah-tpu cluster` / `galah-tpu cluster-validate`.

Flag surface mirrors the reference CLI (reference: src/main.rs:53-118 and
src/cluster_argument_parsing.rs:1265-1375); percentage arguments accept
either 1-100 or 0-1 and normalize to fractions (reference:
src/cluster_argument_parsing.rs:1160-1182). The compute path underneath is
the TPU-native pipeline.
"""

from __future__ import annotations

import argparse
import logging
import sys

import galah_tpu
from galah_tpu.config import (
    CLUSTER_METHODS,
    Defaults,
    PRECLUSTER_METHODS,
    QUALITY_FORMULAS,
    parse_percentage,
)
from galah_tpu.utils.logging import set_log_level

logger = logging.getLogger("galah_tpu")


def _add_verbosity(p: argparse.ArgumentParser) -> None:
    p.add_argument("-v", "--verbose", action="store_true",
                   help="Print extra debugging information")
    p.add_argument("-q", "--quiet", action="store_true",
                   help="Unless there is an error, do not print log messages")


def _add_genome_inputs(p: argparse.ArgumentParser) -> None:
    p.add_argument("-f", "--genome-fasta-files", nargs="+",
                   help="Path(s) to FASTA files of each genome")
    p.add_argument("--genome-fasta-list",
                   help="File containing FASTA file paths, one per line")
    p.add_argument("-d", "--genome-fasta-directory",
                   help="Directory containing FASTA files of each genome")
    p.add_argument("-x", "--genome-fasta-extension", default="fna",
                   help="File extension of genomes in the directory "
                        "(default: fna)")


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="galah-tpu",
        description="Metagenome assembled genome (MAG) dereplicator / "
                    "clusterer, TPU-native")
    parser.add_argument("--version", action="version",
                        version=galah_tpu.__version__)
    sub = parser.add_subparsers(dest="subcommand")

    c = sub.add_parser(
        "cluster",
        help="Cluster genomes by ANI, choosing quality-ranked "
             "representatives")
    _add_verbosity(c)
    _add_genome_inputs(c)
    c.add_argument("--ani", type=float, default=Defaults.ANI,
                   help="Average nucleotide identity threshold for "
                        "clustering (default: 95)")
    c.add_argument("--precluster-ani", type=float,
                   default=Defaults.PRETHRESHOLD_ANI,
                   help="Require at least this sketch-derived ANI for "
                        "preclustering (default: 90)")
    c.add_argument("--min-aligned-fraction", type=float,
                   default=Defaults.ALIGNED_FRACTION * 100,
                   help="Min aligned fraction of two genomes for "
                        "clustering (default: 15)")
    c.add_argument("--fragment-length", type=int,
                   default=Defaults.FRAGMENT_LENGTH,
                   help="Length of fragment used in fastANI-style "
                        "calculation (default: 3000)")
    c.add_argument("--precluster-method", default=Defaults.PRECLUSTER_METHOD,
                   choices=PRECLUSTER_METHODS,
                   help="Method of calculating rough ANI for "
                        "dereplication (default: skani)")
    c.add_argument("--cluster-method", default=Defaults.CLUSTER_METHOD,
                   choices=CLUSTER_METHODS,
                   help="Method of calculating exact ANI for "
                        "dereplication (default: skani)")
    c.add_argument("--checkm-tab-table",
                   help="Output of `checkm qa .. --tab_table`")
    c.add_argument("--checkm2-quality-report",
                   help="CheckM2 quality_report.tsv output")
    c.add_argument("--genome-info",
                   help="dRep-style genome info CSV "
                        "(genome,completeness,contamination)")
    c.add_argument("--min-completeness", type=float,
                   help="Ignore genomes with less completeness than this "
                        "percentage")
    c.add_argument("--max-contamination", type=float,
                   help="Ignore genomes with more contamination than this "
                        "percentage")
    c.add_argument("--quality-formula", default=Defaults.QUALITY_FORMULA,
                   choices=QUALITY_FORMULAS,
                   help="Quality formula for ranking genomes "
                        "(default: Parks2020_reduced)")
    c.add_argument("--threads", "-t", type=int, default=1,
                   help="Host threads for FASTA stats/IO fan-out; device "
                        "parallelism is managed by the mesh")
    c.add_argument("--output-cluster-definition",
                   help="Output file of rep<TAB>member lines")
    c.add_argument("--output-representative-fasta-directory",
                   help="Symlink representative genomes into this directory")
    c.add_argument("--output-representative-fasta-directory-copy",
                   help="Copy representative genomes into this directory")
    c.add_argument("--output-representative-list",
                   help="Output file with one representative path per line")

    v = sub.add_parser("cluster-validate", help="Verify clustering results")
    _add_verbosity(v)
    v.add_argument("--cluster-file", required=True,
                   help="Output of 'cluster' subcommand")
    v.add_argument("--ani", type=float, default=99.0,
                   help="ANI to validate against (default: 99)")
    v.add_argument("--min-aligned-fraction", type=float, default=50.0,
                   help="Min aligned fraction of two genomes "
                        "(default: 50)")
    v.add_argument("--fragment-length", type=int,
                   default=Defaults.FRAGMENT_LENGTH,
                   help="Length of fragment used in fastANI-style "
                        "calculation (default: 3000)")
    v.add_argument("--threads", "-t", type=int, default=1)
    return parser


def _build_backends(args, store=None):
    """Backend factory (reference: generate_galah_clusterer,
    src/cluster_argument_parsing.rs:897-1158)."""
    from galah_tpu.backends import (
        FastANIEquivalentClusterer,
        HLLPreclusterer,
        MinHashPreclusterer,
        ProfileStore,
        SkaniEquivalentClusterer,
        SkaniPreclusterer,
    )

    ani = parse_percentage(args.ani, "--ani")
    precluster_ani = parse_percentage(args.precluster_ani, "--precluster-ani")
    min_af = parse_percentage(args.min_aligned_fraction,
                              "--min-aligned-fraction")

    # skani+skani special case: precluster at the final ANI threshold
    # (unconditionally) so reused values reflect the real cutoff
    # (reference: src/cluster_argument_parsing.rs:983-1030, exercised by
    # the reference's test_skani_skani_clusterer with --precluster-ani 99
    # --ani 95 clustering everything at 95).
    if args.precluster_method == "skani" and args.cluster_method == "skani":
        if precluster_ani != ani:
            logger.info(
                "Preclustering at the final ANI threshold %.4f since "
                "precluster and cluster methods are both skani", ani)
        precluster_ani = ani

    store = store or ProfileStore(fraglen=args.fragment_length)
    if args.precluster_method == "finch":
        pre = MinHashPreclusterer(min_ani=precluster_ani)
    elif args.precluster_method == "skani":
        pre = SkaniPreclusterer(
            threshold=precluster_ani, min_aligned_fraction=min_af,
            store=store)
    elif args.precluster_method == "dashing":
        # HyperLogLog subprocess backend in the reference; here a device
        # HLL kernel (reference: src/dashing.rs:11-100).
        pre = HLLPreclusterer(min_ani=precluster_ani)
    else:
        raise ValueError(args.precluster_method)

    if args.cluster_method == "fastani":
        cl = FastANIEquivalentClusterer(
            threshold=ani, min_aligned_fraction=min_af,
            fraglen=args.fragment_length, store=store)
    elif args.cluster_method == "skani":
        cl = SkaniEquivalentClusterer(
            threshold=ani, min_aligned_fraction=min_af, store=store)
    else:
        raise ValueError(args.cluster_method)
    return pre, cl


def run_cluster(args) -> int:
    from galah_tpu import quality as quality_mod
    from galah_tpu.cluster import cluster as run_clustering
    from galah_tpu.genome_inputs import parse_genome_inputs
    from galah_tpu.outputs import setup_outputs, write_outputs

    genomes = parse_genome_inputs(
        genome_fasta_files=args.genome_fasta_files,
        genome_fasta_list=args.genome_fasta_list,
        genome_fasta_directory=args.genome_fasta_directory,
        genome_fasta_extension=args.genome_fasta_extension,
    )

    # Quality filter + ordering (reference: filter_genomes_through_checkm,
    # src/cluster_argument_parsing.rs:576-832)
    n_quality_inputs = sum(
        1 for x in (args.checkm_tab_table, args.checkm2_quality_report,
                    args.genome_info) if x)
    if n_quality_inputs > 1:
        logger.error("Specify at most one of --checkm-tab-table, "
                     "--checkm2-quality-report and --genome-info")
        return 1
    if n_quality_inputs == 0:
        logger.warning(
            "Since CheckM input is missing, genomes are not being ordered "
            "by quality. Instead the order of their input is being used")
    else:
        if args.checkm_tab_table:
            logger.info("Reading CheckM tab table ..")
            table = quality_mod.read_checkm1_tab_table(args.checkm_tab_table)
        elif args.checkm2_quality_report:
            logger.info("Reading CheckM2 Quality report ..")
            table = quality_mod.read_checkm2_quality_report(
                args.checkm2_quality_report)
        else:
            if args.quality_formula == "dRep":
                logger.error(
                    "The dRep quality formula cannot be used with "
                    "--genome-info")
                return 1
            logger.info("Reading genome info file %s", args.genome_info)
            table = quality_mod.read_genome_info_file(args.genome_info)
        genomes = quality_mod.filter_and_order_genomes(
            genomes, table,
            formula=args.quality_formula,
            min_completeness=(parse_percentage(
                args.min_completeness, "--min-completeness")
                if args.min_completeness is not None else None),
            max_contamination=(parse_percentage(
                args.max_contamination, "--max-contamination")
                if args.max_contamination is not None else None),
            threads=args.threads,
        )

    pre, cl = _build_backends(args)

    # Open output handles before compute (fail fast)
    handles = setup_outputs(
        cluster_definition=args.output_cluster_definition,
        representative_fasta_directory=(
            args.output_representative_fasta_directory),
        representative_fasta_directory_copy=(
            args.output_representative_fasta_directory_copy),
        representative_list=args.output_representative_list,
    )

    logger.info("Clustering %d genomes ..", len(genomes))
    clusters = run_clustering(genomes, pre, cl)
    logger.info("Found %d genome clusters", len(clusters))

    write_outputs(handles, clusters, genomes)
    logger.info("Finished printing genome clusters")
    return 0


def run_cluster_validate(args) -> int:
    from galah_tpu.backends import FastANIEquivalentClusterer, ProfileStore
    from galah_tpu.validate import validate_clusters

    ani = parse_percentage(args.ani, "--ani")
    min_af = parse_percentage(args.min_aligned_fraction,
                              "--min-aligned-fraction")
    clusterer = FastANIEquivalentClusterer(
        threshold=ani, min_aligned_fraction=min_af,
        fraglen=args.fragment_length,
        store=ProfileStore(fraglen=args.fragment_length))
    validate_clusters(args.cluster_file, clusterer)
    return 0


def main(argv=None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    if args.subcommand is None:
        parser.print_help()
        return 1
    set_log_level(verbose=getattr(args, "verbose", False),
                  quiet=getattr(args, "quiet", False))
    logger.info("galah-tpu version %s", galah_tpu.__version__)
    try:
        if args.subcommand == "cluster":
            return run_cluster(args)
        else:
            return run_cluster_validate(args)
    except (ValueError, OSError, KeyError) as e:
        # expected user errors: clean message, nonzero exit, no traceback.
        # str(e) for OS errors (args[0] would be the bare errno); args[0]
        # for KeyError/ValueError (str(KeyError) quotes the repr).
        if isinstance(e, OSError):
            logger.error("%s", e)
        else:
            logger.error("%s", e.args[0] if e.args else e)
        return 1


if __name__ == "__main__":
    sys.exit(main())
