"""Command-line interface: `galah-tpu cluster` / `galah-tpu cluster-validate`.

Mirrors the reference CLI surface (reference: src/main.rs:53-118,
src/cluster_argument_parsing.rs:1265-1375). Subcommands land incrementally;
unimplemented ones exit with a clear message rather than a traceback.
"""

from __future__ import annotations

import argparse
import sys

import galah_tpu


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="galah-tpu",
        description="TPU-native genome dereplication (ANI clustering with "
                    "quality-ranked representatives)")
    parser.add_argument("--version", action="version",
                        version=galah_tpu.__version__)
    sub = parser.add_subparsers(dest="subcommand")
    sub.add_parser("cluster", add_help=False)
    sub.add_parser("cluster-validate", add_help=False)
    return parser


def main(argv=None) -> int:
    args, _rest = build_parser().parse_known_args(argv)
    if args.subcommand is None:
        build_parser().print_help()
        return 1
    print(f"galah-tpu {args.subcommand}: not implemented yet in this build",
          file=sys.stderr)
    return 1


if __name__ == "__main__":
    sys.exit(main())
