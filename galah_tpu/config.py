"""Central configuration and compile-time defaults.

Mirrors the reference's defaults block (reference: src/lib.rs:39-47) — the
code defaults are authoritative (the reference README's 99/95 text is stale,
see BASELINE.md). Sketch parameters mirror the finch/skani parameter sets the
reference hard-codes (reference: src/finch.rs:33-45, src/skani.rs:131-163).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, Optional, Sequence, Tuple


class Defaults:
    """Compile-time defaults (reference: src/lib.rs:39-47)."""

    ALIGNED_FRACTION = 0.15          # --min-aligned-fraction 15%
    FRAGMENT_LENGTH = 3000           # --fragment-length
    ANI = 95.0                       # --ani (percent)
    PRETHRESHOLD_ANI = 90.0          # --precluster-ani (percent)
    QUALITY_FORMULA = "Parks2020_reduced"
    PRECLUSTER_METHOD = "skani"      # choices: skani, finch, dashing
    CLUSTER_METHOD = "skani"         # choices: skani, fastani

    # MinHash (finch-equivalent) sketch params (reference: src/finch.rs:33-45)
    MINHASH_KMER = 21
    MINHASH_SKETCH_SIZE = 1000
    MINHASH_SEED = 0
    # Sketch hash: "murmur3" is bit-compatible with the reference's finch
    # contract; "tpufast" is the multiply-free TPU-native mixer
    # (statistically equivalent MinHash/HLL estimates, ~20x faster on the
    # VPU, which has no fast integer multiply). --hash-algorithm.
    HASH_ALGO = "murmur3"

    # FracMinHash (skani-equivalent) params (reference: src/skani.rs:131-163)
    SKANI_C = 125                    # FracMinHash compression factor
    SKANI_MARKER_C = 1000            # marker sketch compression
    SKANI_KMER = 15
    SKANI_SCREEN_CONTAINMENT = 0.80  # candidate screening (src/skani.rs:59)
    # FracMinHash subsampling of the exact fragment-ANI stage: 1 keeps
    # every k-mer (dense; the pinned goldens/accuracy bounds use this);
    # higher values trade a little per-window variance for ~c-fold less
    # membership-test work (the reference's skani runs at c=125).
    ANI_SUBSAMPLE = 1

    # Quality-filter defaults: no filtering unless quality input given
    MIN_COMPLETENESS = None
    MAX_CONTAMINATION = None


# ---------------------------------------------------------------------------
# GALAH_* environment-flag registry
#
# Every environment variable the project reads is declared here, once,
# with its default and one-line documentation. The registry is the
# single source of truth three consumers share:
#   * call sites — read through ``env_value(name)`` (or keep a local
#     ``os.environ`` read, which the lint cross-checks against this
#     table);
#   * ``manpage.py`` — auto-renders the ENVIRONMENT section of every
#     --full-help page from this table (no hand-maintained list);
#   * ``galah_tpu.analysis`` — the flag checker AST-enumerates every
#     GALAH_* read in the tree and fails on flags missing from this
#     table or carrying a conflicting literal default at the read site.
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Flag:
    """One registered environment variable."""

    name: str                       # full env var name, GALAH_*
    help: str                       # one-line doc (manpage ENVIRONMENT)
    default: Optional[str] = None   # None == unset; always the string form
    kind: str = "str"               # str | int | float | bool | grammar
    # runtime|kernel|resilience|observability|bench|test|scripts
    section: str = "runtime"
    choices: Tuple[str, ...] = ()
    # Where the read happens outside the python tree the linter scans
    # (C sources, shell scripts) — suppresses the unread-flag notice.
    external_reader: Optional[str] = None


def _retry_family(prefix: str, section_help: str) -> Tuple[Flag, ...]:
    """The seven knobs RetryPolicy.from_env reads under `prefix`_*."""
    spec = (
        ("MAX_ATTEMPTS", "int", "attempts per dispatch before giving up"),
        ("BASE_DELAY", "float", "first backoff delay, seconds"),
        ("MAX_DELAY", "float", "backoff cap, seconds"),
        ("JITTER", "float", "+- fraction of each delay, in [0, 1]"),
        ("ATTEMPT_DEADLINE", "float",
         "seconds per attempt; a wedged attempt is abandoned"),
        ("TOTAL_BUDGET", "float",
         "overall retry wall-clock budget per call, seconds"),
        ("SEED", "int", "makes the backoff jitter bit-reproducible"),
    )
    return tuple(
        Flag(name=f"{prefix}_{suffix}", kind=kind, section="resilience",
             help=f"{section_help}: {doc}",
             external_reader="resilience/policy.py RetryPolicy.from_env "
                             "(dynamic f-string read)")
        for suffix, kind, doc in spec)


_FLAG_DEFS: Tuple[Flag, ...] = (
    # -- runtime / IO ------------------------------------------------------
    Flag("GALAH_TPU_PLATFORM", section="runtime",
         help="Force the JAX platform (cpu, tpu, ...); the --platform "
              "flag's env twin and loses to it"),
    Flag("GALAH_TPU_CACHE", section="runtime",
         help="Directory for the persistent sketch/profile cache; the "
              "--sketch-cache flag's env twin and loses to it. Unset "
              "disables caching"),
    Flag("GALAH_TPU_IR_CACHE", section="runtime",
         help="Directory for the lint IR cache (per-file GalahIR "
              "entries and the GL5xx shapes verdict, content-hash "
              "keyed); the `galah-tpu lint --ir-cache-dir` flag's env "
              "twin and loses to it. Unset disables caching",
         external_reader="analysis/ir.py default_cache_dir"),
    Flag("GALAH_TPU_INDEX_DIR", section="runtime",
         help="Directory of the persistent versioned sketch index; "
              "the --index-dir flag's env twin and loses to it"),
    Flag("GALAH_TPU_INDEX_BATCH", kind="int", default="32",
         section="resilience",
         help="Genomes per durable append batch of `index insert` "
              "(the preemption safe-boundary granularity: a kill "
              "loses at most one batch of uncommitted appends)"),
    # -- kernel / device policy -------------------------------------------
    Flag("GALAH_TPU_DENSE_PAIRS", kind="bool", section="kernel",
         help="Force the dense O(N^2) pairwise pass (skip the sparse "
              "collision screen) regardless of problem size"),
    Flag("GALAH_TPU_SPARSE_MIN_N", kind="int", default="1024",
         section="kernel",
         help="Genome count at which the sparse collision screen "
              "replaces dense all-pairs passes; malformed values are "
              "logged and ignored"),
    Flag("GALAH_TPU_PAIR_BATCH", kind="int", section="kernel",
         help="Candidate pairs per device dispatch of the screened "
              "pipeline; unset picks 8192 (CPU) or 32768 (TPU)"),
    Flag("GALAH_TPU_PAIRLIST_STRATEGY", section="kernel",
         choices=("blocked", "gather", "xla", "cpu"),
         help="Pin the survivor-evaluation strategy instead of the "
              "AUTO heuristic"),
    Flag("GALAH_TPU_PAIRLIST_BLOCK", kind="int", default="8",
         section="kernel",
         help="Pairs per program (P) for the blocked Mosaic pairlist "
              "kernel"),
    Flag("GALAH_TPU_FRAGMENT_STRATEGY", section="kernel",
         choices=("pallas", "xla", "c"),
         help="Pin the exact fragment-ANI membership strategy "
              "(blocked Mosaic kernel / vmapped searchsorted / "
              "compiled-C merge) instead of the AUTO heuristic"),
    Flag("GALAH_TPU_FRAGMENT_PAIRS", kind="int", section="kernel",
         help="Cap on genome pairs packed into one fragment-ANI "
              "Pallas launch; unset lets the job/volume caps decide"),
    Flag("GALAH_TPU_GREEDY_STRATEGY", section="kernel",
         choices=("device", "host"),
         help="Pin the greedy representative scan to the round-based "
              "device path or the per-precluster host scan instead of "
              "the AUTO heuristic (decisions are bit-identical; a "
              "pinned strategy's failures propagate instead of "
              "demoting)"),
    Flag("GALAH_TPU_SKETCH_STRATEGY", section="kernel",
         choices=("fused", "xla", "c"),
         help="Pin the sketch-stage strategy (fused Pallas "
              "hash+bottom-k kernel / chunked-XLA device path / C "
              "bottom-k sketcher) instead of the AUTO heuristic "
              "(sketches are bit-identical; a pinned strategy's "
              "failures propagate instead of demoting)"),
    Flag("GALAH_TPU_INGEST_DEPTH", kind="int", section="kernel",
         help="Look-ahead depth of the streaming ingest stage (parsed "
              "genomes in flight ahead of the sketch launches); unset "
              "uses max(2, threads)"),
    Flag("GALAH_TPU_OVERLAP", section="kernel", default="auto",
         choices=("auto", "0", "1"),
         help="Overlapped end-to-end dataflow (docs/dataflow.md): "
              "sketch -> pair screen -> speculative fragment-ANI -> "
              "eager greedy rounds run as one pipeline instead of "
              "four sequential drains. auto engages it where it is "
              "bit-identical to the stage-serial engine and demotes "
              "on failure; 1 forces it (failures propagate); 0 "
              "disables it"),
    Flag("GALAH_TPU_OVERLAP_DEPTH", kind="int", default="512",
         section="kernel",
         help="Survivor pairs buffered before a speculative "
              "fragment-ANI batch launches in the overlapped "
              "dataflow; bounds the in-flight window (memory stays "
              "O(depth))"),
    Flag("GALAH_TPU_MEGAKERNEL", section="kernel", default="auto",
         choices=("auto", "0", "1"),
         help="Fused device-resident greedy rounds (docs/dataflow.md "
              "'Persistent device rounds'): consecutive round windows "
              "fuse into one slab whose surviving pairs enqueue into "
              "the on-device work queue and resolve with one fused "
              "fold program — 2 dispatches per slab instead of one "
              "window fold each, bit-identical decisions. auto "
              "engages inside device greedy rounds and demotes to "
              "the per-window dense fold on failure; 1 forces it "
              "(failures and ineligibility propagate); 0 disables "
              "it"),
    Flag("GALAH_TPU_QUEUE_CAP", kind="int", default="4096",
         section="kernel",
         help="Capacity (pairs) of the on-device megakernel work "
              "queue, rounded up to a power of two. Slabs whose "
              "surviving-pair count exceeds it spill to the exact "
              "per-window dense path (megakernel-overflow-spills "
              "counter) — results are exact at any value; the knob "
              "only moves the spill boundary"),
    Flag("GALAH_TPU_MESH_SHAPE", section="kernel", default="auto",
         help="Device-mesh geometry for the all-pairs distance passes "
              "(docs/DISTRIBUTED.md): 'auto' picks the squarest RxC "
              "factorization of the device count (communication-"
              "avoiding 2D tiling — each sketch row is replicated "
              "along one mesh row and one mesh column instead of to "
              "every device), '1d' pins the single-axis mesh, and an "
              "explicit 'RxC' (e.g. '2x4') pins that shape. A shape "
              "that does not factor the device count demotes to 1-D "
              "with a mesh-demoted event"),
    Flag("GALAH_TPU_HLL_BUCKETS", section="kernel", default="auto",
         choices=("auto", "0", "1"),
         help="HLL cardinality-bucketed hierarchical precluster "
              "(docs/DISTRIBUTED.md): bucket genomes into overlapping "
              "log-cardinality bands sized so no pair that could "
              "reach the precluster threshold lands in disjoint "
              "bands, and schedule only same- and adjacent-band "
              "pairs. auto engages above the sparse-screen crossover; "
              "1 forces it at any N; 0 disables it"),
    Flag("GALAH_TPU_PAGESTORE", section="kernel", default="auto",
         choices=("auto", "0", "1"),
         help="Out-of-core tiered sketch memory (docs/memory.md): "
              "sketch rows live in an mmap-backed page store under "
              "the cache dir and only the active band window is "
              "resident, bounding peak RSS while clusterings stay "
              "bit-identical to the all-resident path. auto engages "
              "when the bucketed precluster is engaged and the "
              "projected sketch matrix exceeds the RAM budget; 1 "
              "forces paging whenever bucketing is engaged; 0 "
              "disables it"),
    Flag("GALAH_TPU_SKETCH_RAM_MB", kind="int", default="512",
         section="kernel",
         help="Hard byte budget, in MiB, for the resident (mmapped "
              "and LRU-pinned) page set of the out-of-core sketch "
              "store (docs/memory.md). Band-pinned pages are never "
              "evicted, so the effective floor is two bands' pages; "
              "malformed values are logged and ignored"),
    Flag("GALAH_TPU_PREFILTER", section="kernel", default="auto",
         choices=("auto", "0", "1"),
         help="Ingest-time probabilistic k-mer prefilter "
              "(docs/memory.md): computes HLL registers during the "
              "streamed ingest (C fast path) and screens exact-"
              "duplicate and degenerate (no valid k-mer window) "
              "genomes before full sketching under a provably "
              "conservative skip rule — pair sets and clusterings "
              "are bit-identical with the prefilter off. auto "
              "engages with the streamed single-process ingest; 1 "
              "forces it; 0 disables it"),
    Flag("GALAH_TPU_PALLAS_HASH", kind="bool", section="kernel",
         help="1 forces the quarantined Mosaic murmur3 kernel, 0 "
              "forces the XLA u64 emulation; unset uses the "
              "data-driven per-backend default"),
    Flag("GALAH_PACKED_TRANSFER", kind="bool", section="kernel",
         help="Force (1) or forbid (0) the packed-upload / batched "
              "transfer policy; unset defers to the backend probe"),
    Flag("GALAH_TPU_NO_CCOLLISION", kind="bool", section="kernel",
         help="Disable the C collision-counting fast path (numpy "
              "fallback)",
         external_reader="utils/cbuild.py (disable_env)"),
    Flag("GALAH_TPU_NO_CPAIRSTATS", kind="bool", section="kernel",
         help="Disable the C pair-stats fast path",
         external_reader="utils/cbuild.py (disable_env)"),
    Flag("GALAH_TPU_NO_CSKETCH", kind="bool", section="kernel",
         help="Disable the C sketch fast path",
         external_reader="utils/cbuild.py (disable_env)"),
    Flag("GALAH_TPU_NO_CINGEST", kind="bool", section="kernel",
         help="Disable the C FASTA-ingest fast path",
         external_reader="utils/cbuild.py (disable_env)"),
    Flag("GALAH_TPU_NO_AVX512", kind="bool", section="kernel",
         help="Keep the C merge counter off its AVX-512 kernel",
         external_reader="csrc/pairstats.c (getenv)"),
    # -- observability -----------------------------------------------------
    Flag("GALAH_OBS_REPORT", section="observability",
         help="Write the end-of-run run_report.json (stage tree, "
              "dispatch counts, precluster funnel, flag snapshot, "
              "resilience events) to this path; the --run-report "
              "flag's env twin and loses to it. Render or diff with "
              "`galah-tpu report` (docs/observability.md)"),
    Flag("GALAH_OBS_TRACE_EVENTS", section="observability",
         help="Write Chrome-trace-format span/events (stage spans, "
              "JAX compile events, resilience events; Perfetto-"
              "loadable) to this path; the --trace-events flag's env "
              "twin and loses to it"),
    Flag("GALAH_OBS_PROFILE", kind="bool", default="1",
         section="observability",
         help="Device-cost attribution for registered jit/Pallas "
              "entry points (XLA cost_analysis FLOPs/bytes, compile "
              "walls, HBM high-water, roofline utilization) into the "
              "run report's device_costs section; 0 disables the "
              "profiled-dispatch path entirely"),
    Flag("GALAH_OBS_FLOW", kind="bool", default="1",
         section="observability",
         help="Flow-level pipeline tracing (galah_tpu/obs/flow.py): "
              "flow ids on pipeline items, per-stage wait/service "
              "histograms with blocked-on attribution, Chrome-trace "
              "flow arrows, and the run report's flow section behind "
              "`galah-tpu flow analyze`; 0 turns every record call "
              "into a no-op"),
    Flag("GALAH_OBS_HEARTBEAT_S", kind="float", default="0",
         section="observability",
         help="Period in seconds for the liveness heartbeat thread "
              "(galah_tpu/obs/heartbeat.py): each beat durably "
              "appends counters/gauges/queue-depth/occupancy to "
              "heartbeat.jsonl beside the run report, rendered live "
              "by `galah-tpu top <dir>`. 0 (the default) disables it"),
    Flag("GALAH_OBS_OPENMETRICS", section="observability",
         help="Render the metrics registry — and, in a fleet run, the "
              "cross-shard blame rollup — to this path in Prometheus "
              "text exposition format on every heartbeat tick "
              "(galah_tpu/obs/openmetrics.py; atomically swapped, so "
              "a node-exporter textfile collector never reads a torn "
              "page). Needs GALAH_OBS_HEARTBEAT_S > 0 to tick; unset "
              "disables the exporter"),
    Flag("GALAH_OBS_LEDGER", section="observability",
         help="Append one entry per finalized run to this cross-run "
              "perf ledger (JSONL, keyed by backend/topology/"
              "workload/strategy); inspect and gate with the "
              "`galah-tpu perf` subcommand (docs/observability.md). "
              "Unset disables the ledger feed"),
    Flag("GALAH_OBS_LEDGER_WINDOW", kind="int", default="8",
         section="observability",
         help="How many most-recent same-key ledger entries form the "
              "`perf check` noise band"),
    Flag("GALAH_OBS_LEDGER_MAD_K", kind="float", default="4",
         section="observability",
         help="Width of the `perf check` noise band, in MADs around "
              "the window median (the MAD is floored at 1 percent of "
              "the "
              "median so an all-identical history cannot gate on "
              "epsilon)"),
    Flag("GALAH_SAN", kind="bool", section="observability",
         help="1 arms GalahSan, the runtime concurrency sanitizer "
              "(galah_tpu/analysis/sanitizer.py): wraps the threaded "
              "modules' declared locks, diffs the observed "
              "acquisition graph against LOCK_ORDER, and checks "
              "GUARDED_BY mutations for races. Tier-1 pytest and the "
              "chaos harness set it; the summary lands in "
              "run_report.json (docs/sanitizer.md)"),
    Flag("GALAH_SAN_REPORT", section="observability",
         help="Path for the standalone sanitizer_report.json (full "
              "lock graph + findings); default sanitizer_report.json "
              "in the working directory when the sanitizer writes "
              "one"),
    # -- resilience --------------------------------------------------------
    Flag("GALAH_FI", kind="grammar", section="resilience",
         help="Deterministic fault injection, e.g. "
              "'site=dispatch.ani;kind=raise;prob=0.3;seed=7;max=2'. "
              "Dispatch kinds: raise, device-lost, hang, garbage. "
              "Filesystem kinds (fire inside io/atomic.py at "
              "io.atomic.* sites): enospc, eio, torn-write, slow-io. "
              "'kill' fires at any site and os._exit()s the process "
              "mid-operation (the chaos harness primitive, "
              "scripts/chaos_run.py). See docs/resilience.md"),
    Flag("GALAH_TPU_FLEET_WORKERS", kind="int", default="2",
         section="resilience",
         help="Fleet supervisor (galah-tpu fleet run): maximum worker "
              "subprocesses live at once. Shards queue behind the "
              "worker cap and are reassigned on preemption "
              "(docs/resilience.md, Fleet execution)"),
    Flag("GALAH_TPU_FLEET_SHARDS", kind="int", section="resilience",
         help="Fleet shard count: contiguous quality-order slices of "
              "the genome set, one worker run each. Unset defaults to "
              "the worker cap"),
    Flag("GALAH_TPU_FLEET_STALE_S", kind="float", default="30",
         section="resilience",
         help="Heartbeat staleness deadline, seconds: a worker whose "
              "newest heartbeat record is older than this is killed "
              "and its shard reassigned (same treatment as exit 75 "
              "and SIGKILL). Requires a nonzero fleet heartbeat "
              "period"),
    Flag("GALAH_TPU_FLEET_POLL_S", kind="float", default="0.2",
         section="resilience",
         help="Fleet supervisor poll period, seconds"),
    Flag("GALAH_TPU_FLEET_HEARTBEAT_S", kind="float", default="1",
         section="resilience",
         help="GALAH_OBS_HEARTBEAT_S value injected into fleet "
              "workers (their liveness signal); 0 disables worker "
              "heartbeats AND staleness detection"),
    Flag("GALAH_TPU_FLEET_WORKER", section="resilience",
         help="Set BY the fleet supervisor in every worker "
              "subprocess's environment (value: the fleet dir's "
              "absolute path) — the orphan-adoption stamp it matches "
              "against /proc/<pid>/environ, and the marker the "
              "telemetry layer uses to brand worker heartbeats and "
              "shard ledger entries. Never set this by hand: a "
              "process carrying the stamp is killable by any "
              "scheduler supervising that fleet dir"),
) + _retry_family(
    "GALAH_RETRY", "Device-dispatch retry policy"
) + _retry_family(
    "GALAH_IO_RETRY", "FASTA/IO retry policy (defaults: 3 attempts, "
    "0.1 s base delay)"
) + _retry_family(
    "GALAH_TPU_FLEET_RETRY", "Per-shard fleet reassignment budget "
    "(max_attempts bounds worker-fault preemptions per shard before "
    "quarantine; delays pace the relaunch backoff)"
) + (
    # -- bench / test / scripts -------------------------------------------
    Flag("GALAH_BENCH_STAGE_CAP", kind="float", default="3000",
         section="bench",
         help="Per-stage wall-clock cap for bench.py, seconds; the "
              "TPU watcher derives it from BENCH_TIMEOUT"),
    Flag("GALAH_BENCH_N", kind="int", section="bench",
         help="Override the genome count of the bench.py ladder stage"),
    Flag("GALAH_BENCH_PROBE_TIMEOUT", kind="float", default="420",
         section="bench",
         help="Seconds the bench.py backend probe may take before the "
              "run records backend=cpu-fallback reason=probe-timeout "
              "and pins JAX_PLATFORMS=cpu (the retry probe gets a "
              "quarter of this)"),
    Flag("GALAH_RUN_SLOW", kind="bool", section="test",
         help="1 runs the slow/hardware test tier the default run "
              "skips"),
    Flag("GALAH_RUN_CAMPAIGN", kind="bool", section="test",
         help="1 runs the full abisko18 campaign combo matrix"),
    Flag("GALAH_TPU_TUNNEL_LOCK", section="scripts",
         default="/tmp/galah_tpu_tunnel.lock",
         help="Lock file serializing TPU tunnel clients (validation "
              "watcher)",
         external_reader="scripts/tpu_validation_run.sh"),
    Flag("GALAH_TUNNEL_LOCKED", section="scripts",
         help="Internal: set by the validation watcher once it holds "
              "the tunnel lock, to short-circuit the re-exec",
         external_reader="scripts/tpu_validation_run.sh"),
)

FLAGS: Dict[str, Flag] = {f.name: f for f in _FLAG_DEFS}

#: Dynamic-prefix families (read via f-strings, e.g. RetryPolicy.from_env).
FLAG_FAMILIES: Tuple[str, ...] = ("GALAH_RETRY", "GALAH_IO_RETRY",
                                  "GALAH_TPU_FLEET_RETRY")


def env_value(name: str) -> Optional[str]:
    """The registered flag's current value: the environment when set,
    else the registry default (None for unset). Reading an unregistered
    name raises — new flags must be declared in FLAGS first."""
    flag = FLAGS.get(name)
    if flag is None:
        raise KeyError(f"environment flag {name} is not registered in "
                       "galah_tpu.config.FLAGS")
    raw = os.environ.get(name)
    return raw if raw not in (None, "") else flag.default


PRECLUSTER_METHODS = ("skani", "finch", "dashing")
HASH_ALGORITHMS = ("murmur3", "tpufast")
CLUSTER_METHODS = ("skani", "fastani")
QUALITY_FORMULAS = (
    "Parks2020_reduced",
    "completeness-4contamination",
    "completeness-5contamination",
    "dRep",
)


def parse_percentage(value: float, name: str = "value") -> float:
    """Normalize a percentage argument to a fraction in [0, 1].

    Reference semantics (src/cluster_argument_parsing.rs:1160-1182):
    values in [1, 100] are percent (so exactly 1 means 1%, not 100%);
    values in [0, 1) are already fractions; anything else is an error.
    """
    v = float(value)
    if 1.0 <= v <= 100.0:
        return v / 100.0
    if 0.0 <= v < 1.0:
        return v
    raise ValueError(f"{name} must be within [0, 100], got {value}")


@dataclasses.dataclass
class ClusterConfig:
    """Everything `galah-tpu cluster` needs; the host-side config object.

    Thresholds are stored as *fractions* (0-1); backends that want percent
    units multiply by 100 themselves.
    """

    genome_paths: Sequence[str] = ()
    ani: float = Defaults.ANI / 100.0
    precluster_ani: float = Defaults.PRETHRESHOLD_ANI / 100.0
    min_aligned_fraction: float = Defaults.ALIGNED_FRACTION
    fragment_length: int = Defaults.FRAGMENT_LENGTH
    precluster_method: str = Defaults.PRECLUSTER_METHOD
    cluster_method: str = Defaults.CLUSTER_METHOD
    quality_formula: str = Defaults.QUALITY_FORMULA
    min_completeness: Optional[float] = None   # fraction
    max_contamination: Optional[float] = None  # fraction
    checkm_tab_table: Optional[str] = None
    checkm2_quality_report: Optional[str] = None
    genome_info: Optional[str] = None
    threads: int = 1
    # outputs
    output_cluster_definition: Optional[str] = None
    output_representative_fasta_directory: Optional[str] = None
    output_representative_fasta_directory_copy: Optional[str] = None
    output_representative_list: Optional[str] = None

    def __post_init__(self) -> None:
        if self.precluster_method not in PRECLUSTER_METHODS:
            raise ValueError(
                f"unknown precluster method {self.precluster_method!r}; "
                f"choices: {PRECLUSTER_METHODS}")
        if self.cluster_method not in CLUSTER_METHODS:
            raise ValueError(
                f"unknown cluster method {self.cluster_method!r}; "
                f"choices: {CLUSTER_METHODS}")
        if self.quality_formula not in QUALITY_FORMULAS:
            raise ValueError(
                f"unknown quality formula {self.quality_formula!r}; "
                f"choices: {QUALITY_FORMULAS}")
