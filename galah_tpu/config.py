"""Central configuration and compile-time defaults.

Mirrors the reference's defaults block (reference: src/lib.rs:39-47) — the
code defaults are authoritative (the reference README's 99/95 text is stale,
see BASELINE.md). Sketch parameters mirror the finch/skani parameter sets the
reference hard-codes (reference: src/finch.rs:33-45, src/skani.rs:131-163).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence


class Defaults:
    """Compile-time defaults (reference: src/lib.rs:39-47)."""

    ALIGNED_FRACTION = 0.15          # --min-aligned-fraction 15%
    FRAGMENT_LENGTH = 3000           # --fragment-length
    ANI = 95.0                       # --ani (percent)
    PRETHRESHOLD_ANI = 90.0          # --precluster-ani (percent)
    QUALITY_FORMULA = "Parks2020_reduced"
    PRECLUSTER_METHOD = "skani"      # choices: skani, finch, dashing
    CLUSTER_METHOD = "skani"         # choices: skani, fastani

    # MinHash (finch-equivalent) sketch params (reference: src/finch.rs:33-45)
    MINHASH_KMER = 21
    MINHASH_SKETCH_SIZE = 1000
    MINHASH_SEED = 0
    # Sketch hash: "murmur3" is bit-compatible with the reference's finch
    # contract; "tpufast" is the multiply-free TPU-native mixer
    # (statistically equivalent MinHash/HLL estimates, ~20x faster on the
    # VPU, which has no fast integer multiply). --hash-algorithm.
    HASH_ALGO = "murmur3"

    # FracMinHash (skani-equivalent) params (reference: src/skani.rs:131-163)
    SKANI_C = 125                    # FracMinHash compression factor
    SKANI_MARKER_C = 1000            # marker sketch compression
    SKANI_KMER = 15
    SKANI_SCREEN_CONTAINMENT = 0.80  # candidate screening (src/skani.rs:59)
    # FracMinHash subsampling of the exact fragment-ANI stage: 1 keeps
    # every k-mer (dense; the pinned goldens/accuracy bounds use this);
    # higher values trade a little per-window variance for ~c-fold less
    # membership-test work (the reference's skani runs at c=125).
    ANI_SUBSAMPLE = 1

    # Quality-filter defaults: no filtering unless quality input given
    MIN_COMPLETENESS = None
    MAX_CONTAMINATION = None


PRECLUSTER_METHODS = ("skani", "finch", "dashing")
HASH_ALGORITHMS = ("murmur3", "tpufast")
CLUSTER_METHODS = ("skani", "fastani")
QUALITY_FORMULAS = (
    "Parks2020_reduced",
    "completeness-4contamination",
    "completeness-5contamination",
    "dRep",
)


def parse_percentage(value: float, name: str = "value") -> float:
    """Normalize a percentage argument to a fraction in [0, 1].

    Reference semantics (src/cluster_argument_parsing.rs:1160-1182):
    values in [1, 100] are percent (so exactly 1 means 1%, not 100%);
    values in [0, 1) are already fractions; anything else is an error.
    """
    v = float(value)
    if 1.0 <= v <= 100.0:
        return v / 100.0
    if 0.0 <= v < 1.0:
        return v
    raise ValueError(f"{name} must be within [0, 100], got {value}")


@dataclasses.dataclass
class ClusterConfig:
    """Everything `galah-tpu cluster` needs; the host-side config object.

    Thresholds are stored as *fractions* (0-1); backends that want percent
    units multiply by 100 themselves.
    """

    genome_paths: Sequence[str] = ()
    ani: float = Defaults.ANI / 100.0
    precluster_ani: float = Defaults.PRETHRESHOLD_ANI / 100.0
    min_aligned_fraction: float = Defaults.ALIGNED_FRACTION
    fragment_length: int = Defaults.FRAGMENT_LENGTH
    precluster_method: str = Defaults.PRECLUSTER_METHOD
    cluster_method: str = Defaults.CLUSTER_METHOD
    quality_formula: str = Defaults.QUALITY_FORMULA
    min_completeness: Optional[float] = None   # fraction
    max_contamination: Optional[float] = None  # fraction
    checkm_tab_table: Optional[str] = None
    checkm2_quality_report: Optional[str] = None
    genome_info: Optional[str] = None
    threads: int = 1
    # outputs
    output_cluster_definition: Optional[str] = None
    output_representative_fasta_directory: Optional[str] = None
    output_representative_fasta_directory_copy: Optional[str] = None
    output_representative_list: Optional[str] = None

    def __post_init__(self) -> None:
        if self.precluster_method not in PRECLUSTER_METHODS:
            raise ValueError(
                f"unknown precluster method {self.precluster_method!r}; "
                f"choices: {PRECLUSTER_METHODS}")
        if self.cluster_method not in CLUSTER_METHODS:
            raise ValueError(
                f"unknown cluster method {self.cluster_method!r}; "
                f"choices: {CLUSTER_METHODS}")
        if self.quality_formula not in QUALITY_FORMULAS:
            raise ValueError(
                f"unknown quality formula {self.quality_formula!r}; "
                f"choices: {QUALITY_FORMULAS}")
