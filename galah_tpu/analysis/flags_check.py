"""Config-flag registry checker (GL4xx).

AST-enumerates every ``GALAH_*`` environment reference in the tree —
``os.environ.get/pop/[...]``, ``os.getenv``, ``config.env_value``,
pytest ``monkeypatch.setenv/delenv``, and ``disable_env=`` keywords —
and cross-checks them against the central registry in
``galah_tpu.config.FLAGS``:

  GL401  reference to an unregistered GALAH_* flag (typo or a new flag
         that skipped the registry)
  GL402  a read site supplies a literal default conflicting with the
         registry default — the default must be defined exactly once
  GL403  registered flag never referenced anywhere the linter scans
         (stale registration; flags read by C code or shell scripts
         declare ``external_reader`` instead)
  GL404  registered flag without documentation (empty help)
  GL405  registered flag missing from the manpage's auto-rendered
         ENVIRONMENT section (the render filter dropped it)

Dynamic reads through f-strings (RetryPolicy.from_env) are covered by
explicitly registering each family member with an ``external_reader``
note, so the enumerator only needs literal names.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from galah_tpu.analysis.core import (Finding, Severity, SourceFile,
                                     dotted_name, enclosing_functions)

_READ_CALLS = {
    "os.environ.get", "environ.get", "os.environ.pop", "environ.pop",
    "os.getenv", "os.environ.setdefault", "environ.setdefault",
}
_REGISTRY_CALLS = {"env_value", "config.env_value"}
_WRITE_CALLS = {"monkeypatch.setenv", "monkeypatch.delenv",
                "m.setenv", "m.delenv"}


def _literal_env_name(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str) \
            and node.value.startswith("GALAH_"):
        return node.value
    return None


def enumerate_references(src: SourceFile) -> \
        List[Tuple[str, int, str, Optional[ast.AST], str]]:
    """(flag, line, symbol, default_node, via) for every GALAH_*
    reference in one module. `default_node` is the literal second arg
    of a read call when present; `via` names the reference kind."""
    refs: List[Tuple[str, int, str, Optional[ast.AST], str]] = []
    owner = enclosing_functions(src.tree)

    def symbol_of(node: ast.AST) -> str:
        fn = owner.get(node)
        return fn.name if fn is not None else ""

    for node in src.walk():
        if isinstance(node, ast.Call):
            cname = dotted_name(node.func)
            tail = ".".join(cname.split(".")[-2:])
            if cname in _READ_CALLS or tail in _READ_CALLS:
                name = _literal_env_name(node.args[0]) if node.args \
                    else None
                if name:
                    default = node.args[1] if len(node.args) > 1 \
                        else None
                    refs.append((name, node.lineno, symbol_of(node),
                                 default, "read"))
            elif cname in _REGISTRY_CALLS \
                    or cname.split(".")[-1] == "env_value":
                name = _literal_env_name(node.args[0]) if node.args \
                    else None
                if name:
                    refs.append((name, node.lineno, symbol_of(node),
                                 None, "registry"))
            elif tail in _WRITE_CALLS \
                    or cname.split(".")[-1] in ("setenv", "delenv"):
                name = _literal_env_name(node.args[0]) if node.args \
                    else None
                if name:
                    refs.append((name, node.lineno, symbol_of(node),
                                 None, "write"))
            for kw in node.keywords:
                if kw.arg == "disable_env":
                    name = _literal_env_name(kw.value)
                    if name:
                        refs.append((name, kw.value.lineno,
                                     symbol_of(node), None,
                                     "disable_env"))
        elif isinstance(node, ast.Subscript):
            base = dotted_name(node.value)
            if base in ("os.environ", "environ"):
                name = _literal_env_name(node.slice)
                if name:
                    via = ("read" if isinstance(node.ctx, ast.Load)
                           else "write")
                    refs.append((name, node.lineno, symbol_of(node),
                                 None, via))
    return refs


def _default_matches(default_node: Optional[ast.AST],
                     registry_default: Optional[str]) -> bool:
    """Whether a read-site literal default agrees with the registry.

    None, '' and an absent second argument all mean 'unset'. Non-literal
    defaults (module constants) are accepted — the constant is the one
    definition and the registry mirrors it in string form.
    """
    if default_node is None:
        return True  # plain read; registry default applies afterwards
    if not isinstance(default_node, ast.Constant):
        return True  # name/attribute default: not a second literal
    value = default_node.value
    site = None if value in (None, "") else str(value)
    reg = None if registry_default in (None, "") else registry_default
    return site is None or site == reg


def check_flag_references(sources: List[SourceFile],
                          flags: Optional[Dict[str, object]] = None) -> \
        List[Finding]:
    """GL401/GL402 over the scanned tree + GL403/404/405 registry
    health. `flags` defaults to galah_tpu.config.FLAGS."""
    if flags is None:
        from galah_tpu.config import FLAGS
        flags = dict(FLAGS)
    findings: List[Finding] = []
    referenced = set()

    for src in sources:
        for name, line, symbol, default_node, via in \
                enumerate_references(src):
            referenced.add(name)
            flag = flags.get(name)
            if flag is None:
                findings.append(Finding(
                    "GL401", Severity.ERROR, src.path, line,
                    f"{via} of unregistered environment flag {name} — "
                    "declare it in galah_tpu.config.FLAGS", symbol))
                continue
            if via == "read" and not _default_matches(
                    default_node, flag.default):
                findings.append(Finding(
                    "GL402", Severity.ERROR, src.path, line,
                    f"read of {name} supplies a literal default "
                    f"{ast.literal_eval(default_node)!r} conflicting "
                    f"with the registry default {flag.default!r} — "
                    "the default must be defined once, in "
                    "config.FLAGS", symbol))

    rendered_env = None
    try:
        from galah_tpu.manpage import render_environment_section

        rendered_env = render_environment_section()
    except Exception:  # pragma: no cover - import cycle / refactor
        rendered_env = None

    for name, flag in sorted(flags.items()):
        if not getattr(flag, "help", ""):
            findings.append(Finding(
                "GL404", Severity.ERROR, "galah_tpu/config.py", 0,
                f"registered flag {name} has no help text "
                "(undocumented)", "FLAGS"))
        if name not in referenced \
                and not getattr(flag, "external_reader", None):
            findings.append(Finding(
                "GL403", Severity.WARNING, "galah_tpu/config.py", 0,
                f"registered flag {name} is never referenced in the "
                "scanned tree (stale registration? set "
                "external_reader if a C/shell reader owns it)",
                "FLAGS"))
        if rendered_env is not None and name not in rendered_env:
            findings.append(Finding(
                "GL405", Severity.ERROR, "galah_tpu/manpage.py", 0,
                f"registered flag {name} missing from the rendered "
                "ENVIRONMENT section", "render_environment_section"))
    return findings
