"""Abstract-eval shape-contract harness (GL5xx).

``jax.eval_shape`` traces every registered op over a dtype x
shape-quantum lattice on CPU — no compilation, no device — and diffs
the resulting output signatures against the committed snapshot
(``shape_contracts.json`` next to this module). A kernel signature
regression (an output dtype widened, a padding change leaking into the
public shape, an op that stops accepting a lattice point) fails tier-1
without any hardware:

  GL501  an op's output signature differs from the committed snapshot
  GL502  lattice drift: a computed case missing from the snapshot, a
         stale snapshot entry, or an op that now raises at trace time

Regenerate the snapshot after an *intentional* contract change with
``python -m galah_tpu.analysis --update-snapshots``.

The lattice points sit deliberately ON and OFF the TPU tiling quanta
(K = 128 vs 1000, pair counts 8 vs 9) so ragged-input padding behavior
is part of the pinned contract.
"""

from __future__ import annotations

import json
import os
from typing import Callable, Dict, List, Tuple

from galah_tpu.analysis.core import Finding, Severity

SNAPSHOT_PATH = os.path.join(os.path.dirname(__file__),
                             "shape_contracts.json")


def _sig(x) -> str:
    return f"{x.dtype}[{','.join(str(d) for d in x.shape)}]"


def _flatten_sig(out) -> str:
    import jax

    leaves = jax.tree_util.tree_leaves(out)
    return ", ".join(_sig(leaf) for leaf in leaves)


def _lattice() -> List[Tuple[str, str, Callable[[], object],
                             Tuple[object, ...], Dict[str, object]]]:
    """(op_name, case_key, fn_getter, args, kwargs) rows.

    fn_getter defers the ops import so building the lattice never pays
    for jax; args are ShapeDtypeStructs (eval_shape consumes abstract
    values only).
    """
    import jax
    import jax.numpy as jnp

    def sds(shape, dtype):
        return jax.ShapeDtypeStruct(shape, dtype)

    u64, f32 = jnp.uint64, jnp.float32
    rows: List[Tuple[str, str, Callable[[], object],
                     Tuple[object, ...], Dict[str, object]]] = []

    def add(op_name, case, getter, *args, **kwargs):
        rows.append((op_name, case, getter, args, kwargs))

    def get(module, attr):
        def getter():
            import importlib

            return getattr(importlib.import_module(module), attr)
        return getter

    tile_stats = get("galah_tpu.ops.pairwise", "tile_stats")
    tile_ani = get("galah_tpu.ops.pairwise", "tile_ani")
    tile_icount = get("galah_tpu.ops.pairwise", "tile_intersect_counts")
    tile_pallas = get("galah_tpu.ops.pallas_pairwise",
                      "tile_stats_pallas")
    tile_ipallas = get("galah_tpu.ops.pallas_pairwise",
                       "tile_intersect_pallas")
    pairlist = get("galah_tpu.ops.pallas_pairlist",
                   "pair_stats_pairs_pallas")
    murmur = get("galah_tpu.ops.pallas_sketch", "murmur3_k21_pallas")
    hll_tile = get("galah_tpu.ops.pallas_hll", "hll_union_stats_tile")
    hll_xla = get("galah_tpu.ops.hll", "_xla_union_stats")
    hll_card = get("galah_tpu.ops.hll", "hll_cardinality")

    # XLA pairwise tiles: the production sketch width, on- and
    # off-quantum (these trace in milliseconds)
    for br, bc, k in ((8, 128, 1000), (1, 1, 128), (16, 256, 1024)):
        case = f"br={br},bc={bc},K={k},uint64"
        add("pairwise.tile_stats", case, tile_stats,
            sds((br, k), u64), sds((bc, k), u64),
            sketch_size=k, k=21)
        add("pairwise.tile_ani", case, tile_ani,
            sds((br, k), u64), sds((bc, k), u64),
            sketch_size=k, k=21)
        add("pairwise.tile_intersect_counts", case, tile_icount,
            sds((br, k), u64), sds((bc, k), u64))

    # 2D-mesh tile wrapper: same stats as pairwise.tile_stats but with
    # the int32 output contract the lattice assembler depends on (no
    # new Pallas kernel — the 2D path reuses the 1-D tile kernels, so
    # Mosaic coverage is inherited from the rows above)
    tile2d = get("galah_tpu.parallel.mesh", "tile2d_stats")
    for br, bc, k in ((8, 128, 1000), (16, 256, 1024)):
        add("mesh.tile2d_stats", f"br={br},bc={bc},K={k},uint64",
            tile2d, sds((br, k), u64), sds((bc, k), u64),
            sketch_size=k, k=21)

    # Mosaic pairwise tiles: tracing cost scales with the unrolled
    # chunk count (~25 s at K=1000), so the lattice pins padding
    # behavior at small widths — on-quantum, off-quantum (K=200 pads
    # to 256; br/bc pad to the program/lane quanta)
    for br, bc, k in ((1, 1, 128), (4, 4, 200), (8, 16, 256)):
        case = f"br={br},bc={bc},K={k},uint64"
        add("pallas_pairwise.tile_stats_pallas", case, tile_pallas,
            sds((br, k), u64), sds((bc, k), u64), sketch_size=k)
        add("pallas_pairwise.tile_intersect_pallas", case, tile_ipallas,
            sds((br, k), u64), sds((bc, k), u64))

    # blocked pairlist kernel: ragged and block-aligned pair counts,
    # pinned block_pairs so the env flag cannot skew the contract
    for b, k in ((1, 128), (8, 136), (9, 136)):
        add("pallas_pairlist.pair_stats_pairs_pallas",
            f"B={b},K={k},P=8,uint64", pairlist,
            sds((b, k), u64), sds((b, k), u64),
            sketch_size=k, block_pairs=8)

    # blocked fragment window-match kernel: job-bucket x span-bucket
    # lattice points at the production geometry (8 sublanes x 128
    # lanes per job, u32 hi/lo planes — the u64 split happens on the
    # host, so the device boundary is 32-bit by construction)
    fragment = get("galah_tpu.ops.pallas_fragment", "_window_hits_jit")
    u32 = jnp.uint32
    for jobs, span in ((8, 1), (8, 2), (16, 4)):
        add("pallas_fragment._window_hits_jit",
            f"jobs={jobs},span={span},uint32", fragment,
            sds((jobs * 8, 128), u32), sds((jobs * 8, 128), u32),
            sds((jobs * span * 8, 128), u32),
            sds((jobs * span * 8, 128), u32),
            span=span, interpret=True)

    # quarantined murmur3 kernel keeps its boundary contract pinned too
    for n in (1, 1000, 65536):
        add("pallas_sketch.murmur3_k21_pallas", f"n={n},uint64",
            murmur, sds((n,), u64), sds((n,), u64), sds((n,), u64))

    # fused hash+bottom-k sketch kernel: job-bucket x span-bucket
    # lattice at the (BLOCK_SUB x LANES)-block geometry, both hash
    # algorithms (murmur3 ships 3 key words per position, tpufast 1);
    # output is the (jobs, R_REG, CAND_SUB * LANES) candidate file
    fused = get("galah_tpu.ops.pallas_sketch", "fused_sketch_candidates")
    _fb = 512 * 128  # BLOCK_SUB * LANES positions per kernel block
    for jobs, span, algo, n_words in ((8, 1, "murmur3", 3),
                                      (8, 2, "murmur3", 3),
                                      (16, 1, "tpufast", 1)):
        w = span * _fb
        add("pallas_sketch.fused_sketch_candidates",
            f"jobs={jobs},span={span},{algo},uint64", fused,
            tuple(sds((jobs, w), u64) for _ in range(n_words)),
            sds((jobs, w), jnp.bool_),
            algo=algo, seed=0, interpret=True)

    # HLL union tiles: Mosaic kernel and its XLA fallback twin must
    # keep identical signatures
    for br, bc, m in ((8, 8, 1024), (64, 128, 4096)):
        case = f"br={br},bc={bc},m={m},float32"
        add("pallas_hll.hll_union_stats_tile", case, hll_tile,
            sds((br, m), f32), sds((bc, m), f32), chunk=1024)
        add("hll._xla_union_stats", case, hll_xla,
            sds((br, m), f32), sds((bc, m), f32))
    add("hll.hll_cardinality", "m=4096,uint8", hll_card,
        sds((4096,), jnp.uint8))

    # greedy-selection window fold + membership argmax (f64 by
    # contract — the NaN >= thr comparison must match the host's
    # None-guarded float64 compare bit-for-bit); pow2 buckets from
    # greedy_select._bucket, bool flags alongside
    wsel = get("galah_tpu.ops.greedy_select", "_window_select_jit")
    margmax = get("galah_tpu.ops.greedy_select", "_membership_argmax_jit")
    f64, b8 = jnp.float64, jnp.bool_
    for w in (8, 64):
        add("greedy_select._window_select_jit", f"W={w},float64",
            wsel, sds((w, w), f64), sds((w,), b8), sds((w,), b8),
            sds((), f64))
    for gg, rr in ((8, 8), (64, 16)):
        add("greedy_select._membership_argmax_jit",
            f"G={gg},R={rr},float64", margmax, sds((gg, rr), f64))
    return rows


def compute_contracts() -> Tuple[Dict[str, Dict[str, str]],
                                 List[Finding]]:
    """op -> case -> output signature, tracing each lattice point."""
    import functools

    import jax

    findings: List[Finding] = []
    out: Dict[str, Dict[str, str]] = {}
    for op_name, case, getter, args, kwargs in _lattice():
        try:
            fn = getter()
            result = jax.eval_shape(functools.partial(fn, **kwargs),
                                    *args)
            out.setdefault(op_name, {})[case] = _flatten_sig(result)
        except Exception as e:  # noqa: BLE001 - reported as a finding
            findings.append(Finding(
                "GL502", Severity.ERROR, "galah_tpu/analysis/shapes.py",
                0,
                f"{op_name}[{case}] failed abstract eval: "
                f"{type(e).__name__}: {str(e).splitlines()[0] if str(e) else ''}",
                op_name))
    return out, findings


def load_snapshot() -> Dict[str, Dict[str, str]]:
    if not os.path.isfile(SNAPSHOT_PATH):
        return {}
    with open(SNAPSHOT_PATH, "r", encoding="utf-8") as fh:
        return json.load(fh).get("contracts", {})


def write_snapshot(contracts: Dict[str, Dict[str, str]]) -> None:
    with open(SNAPSHOT_PATH, "w", encoding="utf-8") as fh:
        json.dump({"version": 1, "contracts": contracts}, fh, indent=1,
                  sort_keys=True)
        fh.write("\n")


def _verdict_digest() -> str:
    """Content digest over everything the lattice outcome depends on:
    the op modules the lattice traces, this file (the lattice itself),
    the committed snapshot, and the jax version. Any edit to any of
    them changes the digest, so a cached verdict can never go stale —
    it can only be missed and recomputed."""
    import hashlib

    h = hashlib.sha256()
    try:
        import jax

        h.update(jax.__version__.encode())
    except Exception:  # noqa: BLE001 - no jax, no cached verdict reuse
        h.update(b"no-jax")
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    paths = [os.path.abspath(__file__), SNAPSHOT_PATH,
             os.path.join(pkg, "parallel", "mesh.py")]
    ops_dir = os.path.join(pkg, "ops")
    for base, _dirs, names in sorted(os.walk(ops_dir)):
        paths.extend(os.path.join(base, n) for n in sorted(names)
                     if n.endswith(".py"))
    for p in paths:
        h.update(os.path.basename(p).encode())
        try:
            with open(p, "rb") as fh:
                h.update(fh.read())
        except OSError:
            h.update(b"<absent>")
    return h.hexdigest()


def check_shape_contracts(cache_dir: str = None) -> List[Finding]:
    """GL501/GL502: computed lattice vs committed snapshot.

    With ``cache_dir`` the verdict (the finding list itself) is cached
    keyed by a digest over the op sources + lattice + snapshot + jax
    version — this family costs ~12 s of jax tracing per run, so a
    warm hit is what makes a cached ``galah-tpu lint`` fast."""
    cache = None
    if cache_dir:
        from galah_tpu.analysis.ir import IRCache

        cache = IRCache(cache_dir)
        digest = _verdict_digest()
        hit = cache.load_verdict("shapes", digest)
        if hit is not None:
            return [Finding(code, Severity[sev], path, line, msg, sym)
                    for code, sev, path, line, msg, sym
                    in hit["findings"]]
    findings = _check_shape_contracts_cold()
    if cache is not None:
        cache.store_verdict("shapes", digest, {
            "findings": [[f.code, f.severity.name, f.path, f.line,
                          f.message, f.symbol] for f in findings]})
    return findings


def _check_shape_contracts_cold() -> List[Finding]:
    computed, findings = compute_contracts()
    snapshot = load_snapshot()
    rel = "galah_tpu/analysis/shape_contracts.json"
    if not snapshot:
        findings.append(Finding(
            "GL502", Severity.ERROR, rel, 0,
            "no committed shape-contract snapshot; run "
            "`python -m galah_tpu.analysis --update-snapshots`", ""))
        return findings
    for op_name, cases in sorted(computed.items()):
        snap_cases = snapshot.get(op_name)
        if snap_cases is None:
            findings.append(Finding(
                "GL502", Severity.ERROR, rel, 0,
                f"op {op_name} missing from the snapshot "
                "(--update-snapshots after an intentional change)",
                op_name))
            continue
        for case, sig in sorted(cases.items()):
            want = snap_cases.get(case)
            if want is None:
                findings.append(Finding(
                    "GL502", Severity.ERROR, rel, 0,
                    f"{op_name}[{case}] missing from the snapshot",
                    op_name))
            elif want != sig:
                findings.append(Finding(
                    "GL501", Severity.ERROR, rel, 0,
                    f"{op_name}[{case}] signature changed: snapshot "
                    f"{want!r} vs computed {sig!r}", op_name))
        for case in sorted(set(snap_cases) - set(cases)):
            findings.append(Finding(
                "GL502", Severity.ERROR, rel, 0,
                f"{op_name}[{case}] is in the snapshot but no longer "
                "in the lattice (stale entry)", op_name))
    for op_name in sorted(set(snapshot) - set(computed)):
        findings.append(Finding(
            "GL502", Severity.ERROR, rel, 0,
            f"snapshot op {op_name} is no longer registered in the "
            "lattice (stale entry)", op_name))
    return findings
