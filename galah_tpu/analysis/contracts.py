"""TPU kernel-contract model: tiling quanta, VMEM budget, annotations.

The hardware facts the Pallas checker enforces (Mosaic's tile rules;
see the Pallas TPU guide):

  * the last (lane) dimension of every VMEM block is quantized to 128;
  * the second-to-last (sublane) dimension is quantized per dtype
    width — 8 for 4-byte, 16 for 2-byte, 32 for 1-byte elements;
  * VMEM is ~16 MiB per core; the checker budgets all of a program's
    resident blocks (in + out + scratch) against that with a safety
    factor, because Mosaic's double-buffered pipelining can hold two
    copies of the streamed blocks;
  * the TPU has no 64-bit integer unit: a u64/i64/f64 dtype at a
    kernel boundary is a latent hardware failure (this repo emulates
    u64 as hi/lo u32 planes on purpose — see ops/pallas_pairwise).

Kernel modules declare a machine-readable ``PALLAS_CONTRACT`` — a plain
dict literal (harvested from the AST via ``ast.literal_eval``, no
import) keyed by the function that issues each ``pl.pallas_call``:

    PALLAS_CONTRACT = {
        "my_kernel_caller": {
            # representative *maximum* values for call-site locals the
            # BlockSpec shape expressions reference
            "bindings": {"bc": 512, "k_pad": 1024},
            # dtype of each in_specs block, in order (u32 planes etc.)
            "in_dtypes": ["uint32", "uint32"],
            # functions whose bodies are (or build) the kernel body —
            # scanned for 64-bit dtype references
            "kernel_fns": ["_make_kernel"],
            # optional overrides
            "vmem_budget_bytes": 16777216,
            "vmem_safety": 0.5,
        },
    }
"""

from __future__ import annotations

import ast
from typing import Dict, Optional

#: Lane quantum: the last dim of every VMEM block tile.
LANE_QUANTUM = 128

#: Sublane quantum by element width in bytes (Mosaic min tiles:
#: float32 (8, 128), bfloat16 (16, 128), int8/fp8 (32, 128)).
SUBLANE_QUANTUM_BY_ITEMSIZE = {4: 8, 2: 16, 1: 32}

#: Per-core VMEM and the default fraction of it a single program's
#: resident blocks may claim (double buffering halves the usable half).
VMEM_BYTES = 16 * 1024 * 1024
VMEM_SAFETY_DEFAULT = 0.5

#: dtypes with no TPU hardware support — 64-bit anything.
BANNED_DTYPES = ("uint64", "int64", "float64")

ITEMSIZE = {
    "float32": 4, "int32": 4, "uint32": 4,
    "bfloat16": 2, "float16": 2, "int16": 2, "uint16": 2,
    "int8": 1, "uint8": 1, "bool": 1, "bool_": 1,
    "float64": 8, "int64": 8, "uint64": 8,
}


def dtype_itemsize(dtype: str) -> Optional[int]:
    return ITEMSIZE.get(dtype)


def sublane_quantum(dtype: str) -> int:
    size = ITEMSIZE.get(dtype, 4)
    return SUBLANE_QUANTUM_BY_ITEMSIZE.get(size, 8)


def dtype_from_node(node: ast.AST) -> Optional[str]:
    """'int32' from an AST reference like ``jnp.int32`` / ``np.uint8``
    / ``"float32"``; None when the node is not a recognizable dtype."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value if node.value in ITEMSIZE else None
    if isinstance(node, ast.Attribute) and node.attr in ITEMSIZE:
        return node.attr
    if isinstance(node, ast.Name) and node.id in ITEMSIZE:
        return node.id
    return None


def harvest_contract(tree: ast.Module) -> Optional[Dict[str, dict]]:
    """The module's ``PALLAS_CONTRACT`` dict literal, or None.

    literal_eval keeps the annotation machine-readable by construction:
    a contract that needs computed values is a smell (the checker's
    bindings exist precisely to stand in for runtime values).
    """
    for node in tree.body:
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = [node.target]
        else:
            continue
        for t in targets:
            if isinstance(t, ast.Name) and t.id == "PALLAS_CONTRACT":
                try:
                    value = ast.literal_eval(node.value)
                except (ValueError, SyntaxError):
                    return None
                if isinstance(value, dict):
                    return value
    return None


def module_int_constants(tree: ast.Module) -> Dict[str, int]:
    """Module-level ``NAME = <int literal>`` assignments — the tile
    constants (A_SUB, B_LANE, LANES, ...) BlockSpec shapes reference."""
    out: Dict[str, int] = {}
    for node in tree.body:
        if not isinstance(node, ast.Assign):
            continue
        if len(node.targets) != 1 or not isinstance(
                node.targets[0], ast.Name):
            continue
        try:
            value = ast.literal_eval(node.value)
        except (ValueError, SyntaxError):
            continue
        if isinstance(value, (int, bool)):
            out[node.targets[0].id] = int(value)
    return out
