"""Durable-write discipline (GL806): io/atomic.py is the only writer.

PR 9 collapsed five hand-rolled tmp+rename idioms (diskcache entries,
the quarantine manifest, checkpoint files, run reports, the perf-ledger
append) into the single crash-consistent primitive in
``galah_tpu/io/atomic.py`` — tmp + fsync + rename + dir-fsync for
files, O_APPEND checksum-framed single writes for JSONL. The chaos
harness (scripts/chaos_run.py) proves exactly THAT code path survives
kill-anywhere; a new ``open(path, "w")`` in a durable-artifact module
silently reopens the old failure class (torn files, lost renames)
without failing any test until a real preemption eats a checkpoint.

Same sanctioned-caller pattern as GL703 (device-cost reads belong to
obs/profile.py): the rule scopes to the modules that own durable
artifacts (``DURABLE_MODULES``) and flags, outside io/atomic.py:

  GL806  a write-mode ``open()`` / ``os.fdopen()`` call, or one of the
         hand-rolled-idiom fingerprints ``os.replace`` / ``os.rename``
         / ``tempfile.mkstemp`` / ``tempfile.NamedTemporaryFile`` —
         durable artifacts must be written through io/atomic.py.

Read-mode opens are fine (recovery code reads everything), and
``os.unlink`` is fine (deleting is atomic already). Legitimate
exceptions (an os.replace that is itself part of a recovery dance)
carry the usual inline suppression with a justification:

    os.replace(a, b)  # galah-lint: ignore[GL806] why this is safe
"""

from __future__ import annotations

import ast
from typing import List, Optional

from galah_tpu.analysis.core import (Finding, Severity, SourceFile,
                                     dotted_name)

#: Modules that own durable artifacts — the GL806 scope. Everything
#: else may open files however it likes (outputs, logs, test scratch).
DURABLE_MODULES = (
    "galah_tpu/io/diskcache.py",
    "galah_tpu/cluster/checkpoint.py",
    "galah_tpu/obs/report.py",
    "galah_tpu/obs/ledger.py",
    "galah_tpu/resilience/quarantine.py",
    "galah_tpu/index/store.py",
    "galah_tpu/index/incremental.py",
    "galah_tpu/fleet/plan.py",
    "galah_tpu/fleet/scheduler.py",
    "galah_tpu/fleet/merge.py",
)

#: The one sanctioned writer.
SANCTIONED = "galah_tpu/io/atomic.py"

#: Call fingerprints of a hand-rolled durable-write idiom.
_IDIOM_CALLS = frozenset({
    "os.replace",
    "os.rename",
    "tempfile.mkstemp",
    "tempfile.NamedTemporaryFile",
})

_WRITE_MODE_CHARS = frozenset("wax+")


def in_scope(path: str) -> bool:
    p = path.replace("\\", "/")
    return p in DURABLE_MODULES and p != SANCTIONED


def _literal_mode(node: ast.Call) -> Optional[str]:
    """The mode argument of an open()/os.fdopen() call when it is a
    string literal (positional arg 1 or mode=...); None otherwise."""
    mode_node: Optional[ast.AST] = None
    if len(node.args) >= 2:
        mode_node = node.args[1]
    for kw in node.keywords:
        if kw.arg == "mode":
            mode_node = kw.value
    if (isinstance(mode_node, ast.Constant)
            and isinstance(mode_node.value, str)):
        return mode_node.value
    return None


def _is_write_mode(mode: Optional[str]) -> bool:
    # no literal mode at all on an open() in a durable module is
    # treated as read-mode ("r" is the default)
    return mode is not None and any(c in _WRITE_MODE_CHARS
                                    for c in mode)


def check_fs_file(src: SourceFile) -> List[Finding]:
    """GL806 over one source file (no-op outside DURABLE_MODULES)."""
    if not in_scope(src.path):
        return []
    findings: List[Finding] = []
    for node in src.walk():
        if not isinstance(node, ast.Call):
            continue
        name = dotted_name(node.func)
        offender = None
        if name in _IDIOM_CALLS:
            offender = f"{name}()"
        elif name in ("open", "os.fdopen") and _is_write_mode(
                _literal_mode(node)):
            offender = f"write-mode {name}()"
        if offender is None:
            continue
        findings.append(Finding(
            "GL806", Severity.WARNING, src.path, node.lineno,
            f"{offender} in a durable-artifact module — write through "
            "galah_tpu/io/atomic.py (write_json/write_npz/append_jsonl"
            "/...) so the artifact stays crash-consistent (tmp + fsync "
            "+ rename + dir-fsync) and the GALAH_FI filesystem faults "
            "can reach it"))
    return findings
