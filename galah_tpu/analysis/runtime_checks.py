"""Host-sync / tracer-leak (GL2xx) and recompile-churn (GL3xx) checks.

Both walk functions decorated with ``@jax.jit`` (bare, ``@jit``, or
through ``functools.partial(jax.jit, ...)``) — the only places where
host/device sync bugs and trace-time captures hide:

  GL201  ``.item()`` / ``float()/int()/bool()`` on a traced argument —
         forces a device sync (or a TracerConversionError at trace
         time); hoist out of the jitted body
  GL202  ``np.asarray``/``np.array`` on a traced argument — silently
         pulls the value to host
  GL203  Python ``if``/``while`` on a traced argument — control flow
         must be ``lax.cond``/``lax.while_loop`` or the argument made
         static (``.shape``/``.dtype``/``.ndim``/``.size`` accesses
         are static and exempt)
  GL301  ``os.environ``/``os.getenv`` read inside a jitted body — the
         value is frozen at trace time: later env changes are silently
         ignored (and a hashable-captured read forces recompiles when
         it varies); resolve flags OUTSIDE the jit boundary, as
         ops/pallas_pairlist.pairlist_block_pairs does
  GL302  a static argument whose default is an unhashable literal
         (list/dict/set) — every call raises or recompiles

The detectors deliberately key on *direct parameter names*: values
derived from parameters would need dataflow analysis and, in this
codebase's idiom (shape unpacking before any branching), direct use is
exactly the bug signature.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set, Tuple

from galah_tpu.analysis.core import (Finding, Severity, SourceFile,
                                     dotted_name)

_STATIC_ATTRS = {"shape", "dtype", "ndim", "size", "at"}
_HOST_CASTS = {"float", "int", "bool", "complex"}
_NP_PULLS = {"np.asarray", "np.array", "numpy.asarray", "numpy.array",
             "onp.asarray", "onp.array"}


def _jit_decoration(fn: ast.FunctionDef) -> Optional[ast.AST]:
    """The jax.jit decorator node when `fn` is jitted, else None."""
    for dec in fn.decorator_list:
        name = dotted_name(dec)
        if name in ("jax.jit", "jit"):
            return dec
        if isinstance(dec, ast.Call):
            cname = dotted_name(dec.func)
            if cname in ("jax.jit", "jit"):
                return dec
            if cname in ("functools.partial", "partial") and dec.args \
                    and dotted_name(dec.args[0]) in ("jax.jit", "jit"):
                return dec
    return None


def _static_names(fn: ast.FunctionDef,
                  dec: ast.AST) -> Tuple[Set[str], Set[int]]:
    """Parameter names/positions declared static on the jit decorator."""
    names: Set[str] = set()
    nums: Set[int] = set()
    if isinstance(dec, ast.Call):
        for kw in dec.keywords:
            if kw.arg == "static_argnames":
                try:
                    v = ast.literal_eval(kw.value)
                except (ValueError, SyntaxError):
                    continue
                if isinstance(v, str):
                    names.add(v)
                else:
                    names.update(v)
            elif kw.arg == "static_argnums":
                try:
                    v = ast.literal_eval(kw.value)
                except (ValueError, SyntaxError):
                    continue
                if isinstance(v, int):
                    nums.add(v)
                else:
                    nums.update(v)
    return names, nums


def _traced_params(fn: ast.FunctionDef, dec: ast.AST) -> Set[str]:
    static_names, static_nums = _static_names(fn, dec)
    params = [a.arg for a in fn.args.posonlyargs + fn.args.args]
    traced = set()
    for i, p in enumerate(params):
        if p in static_names or i in static_nums or p == "self":
            continue
        traced.add(p)
    traced.update(a.arg for a in fn.args.kwonlyargs
                  if a.arg not in static_names)
    return traced


def _exempt_name_nodes(expr: ast.AST) -> Set[int]:
    """ids of Name nodes under a static attribute access (x.shape[0]
    is trace-static even when x is traced)."""
    exempt: Set[int] = set()
    for node in ast.walk(expr):
        if isinstance(node, ast.Attribute) \
                and node.attr in _STATIC_ATTRS:
            for sub in ast.walk(node.value):
                if isinstance(sub, ast.Name):
                    exempt.add(id(sub))
    return exempt


def _traced_name_in(expr: ast.AST, traced: Set[str]) -> Optional[str]:
    exempt = _exempt_name_nodes(expr)
    for node in ast.walk(expr):
        if isinstance(node, ast.Name) and node.id in traced \
                and id(node) not in exempt:
            return node.id
    return None


def _unhashable_static_defaults(fn: ast.FunctionDef, dec: ast.AST,
                                path: str,
                                findings: List[Finding]) -> None:
    static_names, static_nums = _static_names(fn, dec)
    args = fn.args.posonlyargs + fn.args.args
    defaults = fn.args.defaults
    offset = len(args) - len(defaults)
    for i, default in enumerate(defaults):
        arg = args[offset + i]
        if (arg.arg in static_names or (offset + i) in static_nums) \
                and isinstance(default, (ast.List, ast.Dict, ast.Set)):
            findings.append(Finding(
                "GL302", Severity.ERROR, path, default.lineno,
                f"static arg {arg.arg!r} defaults to an unhashable "
                f"{type(default).__name__.lower()} literal — jit "
                "hashes static args; use a tuple or None",
                fn.name))


def check_runtime_file(src: SourceFile) -> List[Finding]:
    """GL2xx/GL3xx over one module."""
    findings: List[Finding] = []
    for fn in src.walk():
        if not isinstance(fn, ast.FunctionDef):
            continue
        dec = _jit_decoration(fn)
        if dec is None:
            continue
        traced = _traced_params(fn, dec)
        _unhashable_static_defaults(fn, dec, src.path, findings)
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                cname = dotted_name(node.func)
                # .item() on anything inside a jit body
                if isinstance(node.func, ast.Attribute) \
                        and node.func.attr == "item" and not node.args:
                    findings.append(Finding(
                        "GL201", Severity.ERROR, src.path, node.lineno,
                        ".item() inside a jitted body forces a host "
                        "sync (TracerConversionError at trace time); "
                        "return the array and convert outside jit",
                        fn.name))
                elif cname in _HOST_CASTS and len(node.args) == 1 \
                        and isinstance(node.args[0], ast.Name) \
                        and node.args[0].id in traced:
                    findings.append(Finding(
                        "GL201", Severity.ERROR, src.path, node.lineno,
                        f"{cname}() on traced argument "
                        f"{node.args[0].id!r} inside a jitted body — "
                        "host conversion of a tracer", fn.name))
                elif cname in _NP_PULLS and node.args:
                    leak = _traced_name_in(node.args[0], traced)
                    if leak:
                        findings.append(Finding(
                            "GL202", Severity.WARNING, src.path,
                            node.lineno,
                            f"{cname}() on traced argument {leak!r} "
                            "inside a jitted body pulls the value to "
                            "host; use jnp instead", fn.name))
                elif cname in ("os.environ.get", "os.getenv",
                               "environ.get") \
                        or dotted_name(node.func).startswith(
                            "os.environ."):
                    findings.append(Finding(
                        "GL301", Severity.ERROR, src.path, node.lineno,
                        "environment read inside a jitted body is "
                        "frozen at trace time (silent staleness / "
                        "recompile churn); resolve the flag outside "
                        "the jit boundary", fn.name))
            elif isinstance(node, ast.Subscript) \
                    and dotted_name(node.value) == "os.environ":
                findings.append(Finding(
                    "GL301", Severity.ERROR, src.path, node.lineno,
                    "os.environ[...] inside a jitted body is frozen "
                    "at trace time; resolve the flag outside the jit "
                    "boundary", fn.name))
            elif isinstance(node, (ast.If, ast.While)):
                leak = _traced_name_in(node.test, traced)
                if leak:
                    kind = ("if" if isinstance(node, ast.If)
                            else "while")
                    findings.append(Finding(
                        "GL203", Severity.WARNING, src.path,
                        node.lineno,
                        f"python {kind} on traced argument {leak!r} "
                        "inside a jitted body — use lax.cond/"
                        "while_loop or declare the argument static",
                        fn.name))
    return findings
