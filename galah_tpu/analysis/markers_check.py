"""Hardware-test marker audit (GL6xx).

Tests that only pass (or only mean anything) on real TPU hardware must
never run in the default CPU tier-1 selection — the repo's convention
is a ``slow`` or ``hardware`` pytest marker, which conftest.py
default-skips. Two signals identify a hardware-only test module:

  * its filename matches ``test_tpu_hw*`` (the live-hardware campaign
    driver), or
  * it imports ``galah_tpu.ops.pallas_sketch`` — the quarantined
    Mosaic kernel whose parity tests need either interpret-mode
    minutes or a real TPU.

Every test function in such a module (including parametrized ones)
must carry the marker, either per-test (``@pytest.mark.slow``) or
module-wide (``pytestmark = pytest.mark.slow`` / a list containing
it):

  GL601  hardware-only test without a slow/hardware marker
"""

from __future__ import annotations

import ast
import os
from typing import List, Optional, Set

from galah_tpu.analysis.core import (Finding, Severity, SourceFile,
                                     dotted_name)

HW_MARKERS = {"slow", "hardware"}
_QUARANTINED_MODULES = ("galah_tpu.ops.pallas_sketch",)


def _marker_names(node: ast.AST) -> Set[str]:
    """Marker names in a decorator / pytestmark expression: handles
    pytest.mark.slow, pytest.mark.parametrize(...), and lists."""
    names: Set[str] = set()
    work = [node]
    while work:
        cur = work.pop()
        if isinstance(cur, (ast.List, ast.Tuple)):
            work.extend(cur.elts)
        elif isinstance(cur, ast.Call):
            work.append(cur.func)
        elif isinstance(cur, ast.Attribute):
            name = dotted_name(cur)
            if name.startswith("pytest.mark.") or name.startswith(
                    "mark."):
                names.add(name.split(".")[-1])
    return names


def _module_markers(tree: ast.Module) -> Set[str]:
    names: Set[str] = set()
    for stmt in tree.body:
        if isinstance(stmt, ast.Assign):
            targets = [t.id for t in stmt.targets
                       if isinstance(t, ast.Name)]
            if "pytestmark" in targets:
                names |= _marker_names(stmt.value)
    return names


def _imports_quarantined(tree: ast.Module) -> bool:
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            if any(a.name in _QUARANTINED_MODULES
                   for a in node.names):
                return True
        elif isinstance(node, ast.ImportFrom) and node.module:
            if node.module in _QUARANTINED_MODULES:
                return True
            # `from galah_tpu.ops import pallas_sketch`
            full = {f"{node.module}.{a.name}" for a in node.names}
            if full & set(_QUARANTINED_MODULES):
                return True
    return False


def is_hardware_module(src: SourceFile) -> bool:
    base = os.path.basename(src.path)
    if base.startswith("test_tpu_hw"):
        return True
    return base.startswith("test_") and _imports_quarantined(src.tree)


def check_markers_file(src: SourceFile,
                       force_hardware: Optional[bool] = None) -> \
        List[Finding]:
    """GL601 over one test module. `force_hardware` overrides the
    hardware-module heuristic (used by fixture tests)."""
    hardware = (is_hardware_module(src) if force_hardware is None
                else force_hardware)
    if not hardware:
        return []
    findings: List[Finding] = []
    module_marks = _module_markers(src.tree)
    if module_marks & HW_MARKERS:
        return []
    for node in src.walk():
        if not isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
            continue
        if not node.name.startswith("test"):
            continue
        marks: Set[str] = set()
        for dec in node.decorator_list:
            marks |= _marker_names(dec)
        if not marks & HW_MARKERS:
            findings.append(Finding(
                "GL601", Severity.ERROR, src.path, node.lineno,
                f"hardware-only test {node.name!r} has no "
                "slow/hardware marker — it would run (and hang or "
                "fail) in the default CPU tier-1 selection",
                node.name))
    return findings
