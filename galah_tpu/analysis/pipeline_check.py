"""Pipeline discipline (GL10xx): keep the dataflow actually streaming.

The ROADMAP's pipelining work depends on stages that *stay* streamed:
the parts outrun the whole by five orders of magnitude precisely
because stage boundaries drain. These auditors flag the antipatterns
that reintroduce draining, plus the telemetry contract that proves a
pipeline is overlapped (``workload.pipeline_occupancy``,
``obs.metrics.PIPELINE_OCCUPANCY_GAUGE``).

Annotation: a pipeline module opts in with a module-level literal

    PIPELINE_STAGE = {
        "streaming": ["iter_path_sketches"],          # generator stages
        "occupancy_gauge": "workload.pipeline_occupancy",
        "device_round": ["_slab_fold_jit"],           # sync-free bodies
    }

``streaming`` names this module's generator stages (GL1002 scope);
``occupancy_gauge`` contracts the module to emit that gauge (GL1004);
``device_round`` names functions that must stay device-resident —
bodies that run inside the persistent greedy round / megakernel and
therefore may never force a host round-trip (GL1006 scope).

Checks
  GL1001  full materialization of a streaming iterator:
          ``list(...)`` / ``sorted(...)`` / ``tuple(...)`` over a call
          to a streamed API (``iter_*`` / ``*_streamed`` /
          ``process_stream``) or a variable bound to one in the same
          function — the whole stream is buffered, so the stage drains
          before the next begins. Scope: pipeline modules (galah_tpu/
          minus utils/, obs/, analysis/ — the GL7xx scope).
  GL1002  host synchronization inside a declared streaming stage:
          ``block_until_ready`` / ``jax.device_get`` in a function
          listed in ``PIPELINE_STAGE["streaming"]`` serializes device
          and host work the stage exists to overlap.
  GL1003  unbounded queue/pool construction in a threaded module
          (one declaring GUARDED_BY/LOCK_ORDER): ``queue.Queue()``
          without a positive ``maxsize``, ``ThreadPoolExecutor()``
          without ``max_workers``. An unbounded handoff hides a
          stalled consumer until memory runs out (the prefetch layer's
          O(depth + workers) bound is the repo-wide contract).
  GL1004  the module declares ``occupancy_gauge`` but never emits it:
          no call carries the declared gauge name (string literal or
          the ``PIPELINE_OCCUPANCY_GAUGE`` constant), so the occupancy
          dashboard the pipelining work gates on stays dark.
  GL1005  malformed ``PIPELINE_STAGE`` annotation: not a dict literal,
          unknown keys, a ``streaming`` / ``device_round`` entry that
          is not a function defined in the module, or a non-string
          gauge name.
  GL1006  host synchronization inside a declared device-round body:
          ``np.asarray`` / ``.item()`` / ``jax.device_get`` /
          ``block_until_ready`` in a function listed in
          ``PIPELINE_STAGE["device_round"]``. Those bodies are traced
          into the persistent round program — a host sync there either
          fails tracing or, worse, silently splits the megakernel back
          into per-window dispatches and the dispatch-count win
          evaporates. Convert at the wrapper boundary instead.
  GL1007  a paged band-walk function holds a gathered sketch
          submatrix across band boundaries: in a function registered
          in ``PAGED_MODULES``, a value produced by ``gather()`` /
          ``band_gather()`` inside the band loop is either appended
          to a collection (accumulating every band) or referenced
          after the loop ends. The out-of-core tier's peak-RSS win
          (docs/memory.md) rests on at most two bands being resident
          at a time; a retained reference pins the backing pages past
          eviction and the paging schedule silently degrades to
          all-resident. The submatrix handed to a helper that retains
          it is the interprocedural GL1007 arm in effects_check
          (GalahIR retention chain in the message); the in-function
          cases stay lexical here — the two arms partition.

Suppression: the usual inline comment with a justification —

    pairs = list(iter_pairs(...))  # galah-lint: ignore[GL1001] tiny

Legitimate cases: materializing a bounded slice for a batch dispatch,
or a terminal collection the caller genuinely needs in memory.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional

from galah_tpu.analysis.concurrency_check import harvest_literal
from galah_tpu.analysis.core import (Finding, Severity, SourceFile,
                                     dotted_name)

#: Call names treated as streamed-API producers by GL1001.
STREAMING_SUFFIX = "_streamed"
STREAMING_PREFIX = "iter_"
STREAMING_NAMES = frozenset({"process_stream"})

#: The materializers GL1001 bans over a streamed producer.
MATERIALIZERS = frozenset({"list", "sorted", "tuple"})

#: Host-sync calls GL1002 bans inside declared streaming stages.
SYNC_CALLS = frozenset({"block_until_ready", "device_get"})

#: Host-sync calls GL1006 bans inside declared device-round bodies.
#: ``asarray`` covers the np.asarray(device_array) idiom and ``item``
#: the scalar pull — both force a transfer mid-trace.
DEVICE_ROUND_SYNC_CALLS = frozenset({
    "asarray", "item", "device_get", "block_until_ready"})

#: The one registered occupancy gauge (obs/metrics.py re-exports it).
OCCUPANCY_GAUGE = "workload.pipeline_occupancy"

_ANNOTATION_KEYS = frozenset({"streaming", "occupancy_gauge",
                              "device_round"})

_EXEMPT_PREFIXES = ("galah_tpu/utils/", "galah_tpu/obs/",
                    "galah_tpu/analysis/")

#: GL1007 scope: module -> the band-walk functions that consume the
#: paged sketch store (io/pagestore.py) and must release each band's
#: gathered submatrix before the next band pages in. A module joins
#: this registry when it grows a code path that drives `gather()` /
#: `band_gather()` over a paged view (docs/memory.md has the pinning
#: invariant the rule enforces).
PAGED_MODULES: Dict[str, List[str]] = {
    "galah_tpu/ops/bucketing.py": ["bucketed_threshold_pairs"],
    "galah_tpu/backends/minhash_backend.py": [
        "distances", "_paged_sketch_rows"],
    "galah_tpu/index/store.py": ["_load_generation"],
}

#: The calls whose results GL1007 tracks (kept identical to
#: ir.GATHER_LASTS so the interprocedural arm extends this one).
GATHER_NAMES = frozenset({"gather", "band_gather"})

#: Receiver methods that accumulate a gathered band in place.
RETAINER_METHODS = frozenset({"append", "add", "extend",
                              "appendleft", "setdefault"})


def in_scope(path: str) -> bool:
    """GL1001 scope: pipeline modules, same carve-out as GL7xx."""
    p = path.replace("\\", "/")
    if not p.startswith("galah_tpu/"):
        return False
    return not p.startswith(_EXEMPT_PREFIXES)


def _is_streaming_call(node: ast.AST) -> bool:
    """True when `node` is a call to a streamed-API producer."""
    if not isinstance(node, ast.Call):
        return False
    name = dotted_name(node.func).rsplit(".", 1)[-1]
    return (name.startswith(STREAMING_PREFIX)
            or name.endswith(STREAMING_SUFFIX)
            or name in STREAMING_NAMES)


def _producer_name(node: ast.Call) -> str:
    return dotted_name(node.func).rsplit(".", 1)[-1]


def _check_materialization(src: SourceFile) -> List[Finding]:
    """GL1001 over one file: direct ``list(iter_*(...))`` plus the
    two-step ``s = iter_*(...); list(s)`` (name binding resolved over
    the whole file — good enough for a lint heuristic)."""
    out: List[Finding] = []
    # names bound to a streamed producer anywhere in the file
    bound: Dict[str, str] = {}
    for node in src.walk():
        if (isinstance(node, ast.Assign)
                and _is_streaming_call(node.value)):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    bound[t.id] = _producer_name(node.value)
    for node in src.walk():
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id in MATERIALIZERS
                and node.args):
            continue
        arg = node.args[0]
        producer: Optional[str] = None
        if _is_streaming_call(arg):
            producer = _producer_name(arg)
        elif isinstance(arg, ast.Name) and arg.id in bound:
            producer = bound[arg.id]
        if producer is not None:
            out.append(Finding(
                code="GL1001", severity=Severity.WARNING,
                path=src.path, line=node.lineno,
                message=(f"{node.func.id}() materializes the "
                         f"streamed iterator {producer}(): the stage "
                         "drains instead of overlapping; consume "
                         "incrementally or bound the buffer"),
                symbol=producer))
    return out


def _function_defs(tree: ast.Module) -> Dict[str, ast.AST]:
    defs: Dict[str, ast.AST] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs.setdefault(node.name, node)
    return defs


def _check_streaming_sync(src: SourceFile, streaming: List[str],
                          defs: Dict[str, ast.AST]) -> List[Finding]:
    """GL1002: host sync inside a declared streaming stage."""
    out: List[Finding] = []
    for name in streaming:
        fn = defs.get(name)
        if fn is None:
            continue  # GL1005 reports the dangling annotation
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            called = dotted_name(node.func).rsplit(".", 1)[-1]
            if called in SYNC_CALLS:
                out.append(Finding(
                    code="GL1002", severity=Severity.WARNING,
                    path=src.path, line=node.lineno,
                    message=(f"{called}() inside streaming stage "
                             f"{name}(): a host sync serializes the "
                             "device/host overlap the stage is "
                             "declared to provide"),
                    symbol=name))
    return out


def _check_device_round_sync(src: SourceFile, device_round: List[str],
                             defs: Dict[str, ast.AST]) -> List[Finding]:
    """GL1006: host sync inside a declared device-round body."""
    out: List[Finding] = []
    for name in device_round:
        fn = defs.get(name)
        if fn is None:
            continue  # GL1005 reports the dangling annotation
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            called = dotted_name(node.func).rsplit(".", 1)[-1]
            if called in DEVICE_ROUND_SYNC_CALLS:
                out.append(Finding(
                    code="GL1006", severity=Severity.WARNING,
                    path=src.path, line=node.lineno,
                    message=(f"{called}() inside device-round body "
                             f"{name}(): a host round-trip here splits "
                             "the persistent round program back into "
                             "per-window dispatches; convert at the "
                             "wrapper boundary instead"),
                    symbol=name))
    return out


def _check_paged_retention(src: SourceFile) -> List[Finding]:
    """GL1007 (lexical arm) over one registered module: gathered band
    submatrices accumulated inside, or referenced after, a band loop
    in a ``PAGED_MODULES`` band-walk function."""
    names = PAGED_MODULES.get(src.path.replace("\\", "/"))
    if not names:
        return []
    defs = _function_defs(src.tree)
    hits: Dict[tuple, Finding] = {}
    for fname in names:
        fn = defs.get(fname)
        if fn is None:
            continue
        for loop in [n for n in ast.walk(fn)
                     if isinstance(n, (ast.For, ast.While))]:
            # names bound to a gather inside this loop
            bound: Dict[str, int] = {}
            for n in ast.walk(loop):
                if (isinstance(n, ast.Assign)
                        and isinstance(n.value, ast.Call)
                        and dotted_name(n.value.func).rsplit(".", 1)[-1]
                        in GATHER_NAMES):
                    for t in n.targets:
                        if isinstance(t, ast.Name):
                            bound[t.id] = n.lineno
            # arm 1: the gathered band lands in an accumulator that
            # outlives the iteration
            for n in ast.walk(loop):
                if not (isinstance(n, ast.Call)
                        and isinstance(n.func, ast.Attribute)
                        and n.func.attr in RETAINER_METHODS
                        and n.args):
                    continue
                for a in n.args:
                    kept: Optional[str] = None
                    if (isinstance(a, ast.Call)
                            and dotted_name(a.func).rsplit(".", 1)[-1]
                            in GATHER_NAMES):
                        kept = dotted_name(a.func).rsplit(".", 1)[-1]
                    elif isinstance(a, ast.Name) and a.id in bound:
                        kept = a.id
                    if kept is None:
                        continue
                    hits[(n.lineno, kept)] = Finding(
                        code="GL1007", severity=Severity.WARNING,
                        path=src.path, line=n.lineno,
                        message=(f".{n.func.attr}() accumulates the "
                                 f"gathered band submatrix {kept} "
                                 f"inside {fname}()'s band loop: "
                                 "every band stays referenced, the "
                                 "backing pages can never evict and "
                                 "the paging schedule degrades to "
                                 "all-resident; reduce the band to "
                                 "its result before accumulating"),
                        symbol=fname)
            # arm 2: a gather-bound name survives past the loop
            end = getattr(loop, "end_lineno", loop.lineno)
            for n in ast.walk(fn):
                if (isinstance(n, ast.Name)
                        and isinstance(n.ctx, ast.Load)
                        and n.id in bound
                        and n.lineno > end):
                    hits[(n.lineno, n.id)] = Finding(
                        code="GL1007", severity=Severity.WARNING,
                        path=src.path, line=n.lineno,
                        message=(f"gathered band submatrix {n.id} is "
                                 f"referenced after {fname}()'s band "
                                 "loop ends: the reference pins its "
                                 "pages across band boundaries "
                                 "(docs/memory.md allows at most two "
                                 "resident bands); copy the needed "
                                 "rows out or re-gather inside the "
                                 "loop"),
                        symbol=fname)
    return [hits[k] for k in sorted(hits)]


def _is_threaded(src: SourceFile) -> bool:
    """GL1003 scope: the module declares concurrency annotations."""
    return (harvest_literal(src.tree, "GUARDED_BY") is not None
            or harvest_literal(src.tree, "LOCK_ORDER") is not None)


def _kw(node: ast.Call, name: str) -> Optional[ast.expr]:
    for kw in node.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _check_unbounded(src: SourceFile) -> List[Finding]:
    """GL1003: queue/pool constructions without a depth bound."""
    out: List[Finding] = []
    for node in src.walk():
        if not isinstance(node, ast.Call):
            continue
        name = dotted_name(node.func).rsplit(".", 1)[-1]
        if name in ("Queue", "LifoQueue", "PriorityQueue",
                    "SimpleQueue"):
            bound = node.args[0] if node.args else _kw(node, "maxsize")
            unbounded = (
                bound is None
                or (isinstance(bound, ast.Constant)
                    and isinstance(bound.value, int)
                    and bound.value <= 0)
                or name == "SimpleQueue")
            if unbounded:
                out.append(Finding(
                    code="GL1003", severity=Severity.WARNING,
                    path=src.path, line=node.lineno,
                    message=(f"{name}() without a positive maxsize "
                             "in a threaded module: an unbounded "
                             "handoff hides a stalled consumer until "
                             "memory runs out; bound the depth"),
                    symbol=name))
        elif name in ("ThreadPoolExecutor", "ProcessPoolExecutor"):
            if not node.args and _kw(node, "max_workers") is None:
                out.append(Finding(
                    code="GL1003", severity=Severity.WARNING,
                    path=src.path, line=node.lineno,
                    message=(f"{name}() without max_workers in a "
                             "threaded module: the pool size defaults "
                             "to the host's CPU count, unbounded by "
                             "the pipeline's declared depth"),
                    symbol=name))
    return out


def _gauge_emitted(src: SourceFile, gauge: str) -> bool:
    """Any call in the file carrying the gauge name — as a string
    literal, via the PIPELINE_OCCUPANCY_GAUGE constant, or through
    the ``obs.metrics.pipeline_occupancy()`` helper."""
    for node in src.walk():
        if not isinstance(node, ast.Call):
            continue
        if (gauge == OCCUPANCY_GAUGE
                and dotted_name(node.func).rsplit(".", 1)[-1]
                == "pipeline_occupancy"):
            return True
        for arg in list(node.args) + [kw.value
                                      for kw in node.keywords]:
            if (isinstance(arg, ast.Constant)
                    and arg.value == gauge):
                return True
            ref = dotted_name(arg)
            if (gauge == OCCUPANCY_GAUGE and ref.rsplit(".", 1)[-1]
                    == "PIPELINE_OCCUPANCY_GAUGE"):
                return True
    return False


def _annotation_line(src: SourceFile) -> int:
    for node in src.tree.body:
        targets: list = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign):
            targets = [node.target]
        for t in targets:
            if isinstance(t, ast.Name) and t.id == "PIPELINE_STAGE":
                return node.lineno
    return 1


def check_pipeline_file(src: SourceFile) -> List[Finding]:
    """All GL10xx checks over one source file."""
    out: List[Finding] = []
    if in_scope(src.path):
        out.extend(_check_materialization(src))
    if _is_threaded(src):
        out.extend(_check_unbounded(src))
    out.extend(_check_paged_retention(src))

    stage = harvest_literal(src.tree, "PIPELINE_STAGE")
    has_decl = any(
        isinstance(t, ast.Name) and t.id == "PIPELINE_STAGE"
        for node in src.tree.body
        for t in (node.targets if isinstance(node, ast.Assign)
                  else [node.target]
                  if isinstance(node, ast.AnnAssign) else []))
    if not has_decl:
        return out
    line = _annotation_line(src)
    if not isinstance(stage, dict):
        out.append(Finding(
            code="GL1005", severity=Severity.WARNING, path=src.path,
            line=line,
            message="PIPELINE_STAGE must be a machine-readable dict "
                    "literal (module docstring has the shape)",
            symbol="PIPELINE_STAGE"))
        return out

    defs = _function_defs(src.tree)
    unknown = sorted(set(stage) - _ANNOTATION_KEYS)
    if unknown:
        out.append(Finding(
            code="GL1005", severity=Severity.WARNING, path=src.path,
            line=line,
            message=("unknown PIPELINE_STAGE key(s): "
                     + ", ".join(unknown)
                     + f" (known: {', '.join(sorted(_ANNOTATION_KEYS))})"),
            symbol="PIPELINE_STAGE"))

    streaming = stage.get("streaming", [])
    if (not isinstance(streaming, list)
            or not all(isinstance(s, str) for s in streaming)):
        out.append(Finding(
            code="GL1005", severity=Severity.WARNING, path=src.path,
            line=line,
            message="PIPELINE_STAGE['streaming'] must be a list of "
                    "function names",
            symbol="PIPELINE_STAGE"))
        streaming = []
    for name in streaming:
        if name not in defs:
            out.append(Finding(
                code="GL1005", severity=Severity.WARNING,
                path=src.path, line=line,
                message=(f"PIPELINE_STAGE['streaming'] names "
                         f"{name}(), which is not defined in this "
                         "module"),
                symbol=name))
    out.extend(_check_streaming_sync(src, streaming, defs))

    device_round = stage.get("device_round", [])
    if (not isinstance(device_round, list)
            or not all(isinstance(s, str) for s in device_round)):
        out.append(Finding(
            code="GL1005", severity=Severity.WARNING, path=src.path,
            line=line,
            message="PIPELINE_STAGE['device_round'] must be a list of "
                    "function names",
            symbol="PIPELINE_STAGE"))
        device_round = []
    for name in device_round:
        if name not in defs:
            out.append(Finding(
                code="GL1005", severity=Severity.WARNING,
                path=src.path, line=line,
                message=(f"PIPELINE_STAGE['device_round'] names "
                         f"{name}(), which is not defined in this "
                         "module"),
                symbol=name))
    out.extend(_check_device_round_sync(src, device_round, defs))

    gauge = stage.get("occupancy_gauge")
    if gauge is not None:
        if not isinstance(gauge, str):
            out.append(Finding(
                code="GL1005", severity=Severity.WARNING,
                path=src.path, line=line,
                message="PIPELINE_STAGE['occupancy_gauge'] must be a "
                        "gauge name string",
                symbol="PIPELINE_STAGE"))
        elif not _gauge_emitted(src, gauge):
            out.append(Finding(
                code="GL1004", severity=Severity.WARNING,
                path=src.path, line=line,
                message=(f"module is contracted to feed the "
                         f"{gauge!r} gauge but never emits it; "
                         "emit it (obs.metrics."
                         "PIPELINE_OCCUPANCY_GAUGE) or drop the "
                         "contract"),
                symbol=gauge))
    return out
