"""GalahIR: whole-program call-graph + effect IR for the GL11xx family.

Every exactness and performance guarantee this repo enforces statically
— the megakernel's "no host sync inside a device round" contract, the
durable-write protocol, the streaming-pipeline discipline — is audited
by *lexical* per-file checkers that a one-level helper indirection
silently defeats: a ``device_round`` body calling a local ``_sync()``
wrapper around ``.item()`` passes GL1006 today. GalahIR closes that
hole with a package-wide pass:

  1. **Per-file IR extraction** (:class:`ModuleIR`): one AST walk per
     file harvesting every function (methods and nested defs included),
     its *direct effect witnesses*, its outgoing call edges (plain
     calls, ``functools.partial`` targets, function references passed
     as arguments — ``jax.lax.while_loop`` bodies, ``map`` callables —
     and pool-submitted callbacks), the module's import/alias tables,
     and the machine-readable annotations the auditors key off
     (``PIPELINE_STAGE["device_round"]``, ``GUARDED_BY``).
  2. **Linking** (:class:`ProgramIR`): module-qualified name resolution
     across files (``import x as y``, ``from x import y as z``,
     module-level function aliases, class-instance method dispatch),
     decorator unwrapping (``@profiled``/``@jit`` never hide a body).
  3. **Effect propagation to fixpoint** over the call graph, with one
     provenance *witness chain* kept per (function, effect) so findings
     carry the exact ``caller -> helper -> sink file:line`` path.

Inferred effects (:data:`EFFECTS`):

  ``host_sync``        ``.item()`` / ``np.asarray`` / ``device_get`` /
                       ``block_until_ready`` — forces a device->host
                       round-trip (the GL1006/GL1101 sink set)
  ``device_dispatch``  a jit-decorated body or a ``pallas_call`` site
  ``fs_write``         write-mode ``open()``/``os.fdopen`` or a
                       tmp+rename idiom call (the GL806/GL1102 sink
                       set); never propagates OUT of the sanctioned
                       writer ``io/atomic.py``
  ``lock_acquire``     a bare ``.acquire()`` call
  ``blocking_io``      ``time.sleep``, ``subprocess.run/check_*``,
                       a Future ``.result()``, ``Event.wait``
  ``materialize``      ``list``/``sorted``/``tuple`` over a streamed
                       producer (the GL1001/GL1103 sink set)
  ``unseeded_rng``     global-state ``random.*`` / ``np.random.*`` or
                       a no-argument ``Random()``/``default_rng()``

Effects propagate across plain call edges and function-reference edges
(the callee runs on the caller's thread); they deliberately do NOT
propagate across pool-submit/Thread-target edges (the callee runs
later, elsewhere — GL1105 audits those separately).

**Caching**: per-file IR is content-hash keyed (sha256 of the source
text + :data:`IR_VERSION`) under the same discipline as the sketch
diskcache (``io/diskcache.py``): one JSON entry per file written
through ``io/atomic.py``, corrupt entries dropped and rebuilt, the
cache strictly optional (``IRCache(None)`` is a no-op). A warm cache
skips the per-file extraction walk; linking and the fixpoint always
run fresh (they are cross-file and cheap). The same cache directory
also holds the GL5xx shapes-family verdict (see ``shapes.py``), which
is what makes a warm ``galah-tpu lint`` wall a fraction of a cold one.

**Known precision limits** (documented, not bugs): dynamic dispatch
through ``getattr``/dicts-of-callables is invisible; a method call on
a value of unknown class (``obj.meth()`` where ``obj`` is a parameter)
does not resolve; effects of third-party code (numpy, jax) are only
modeled through the explicit sink sets above.
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import json
import logging
import os
from typing import Dict, List, Optional, Sequence, Set, Tuple

from galah_tpu.analysis.core import SourceFile, dotted_name

logger = logging.getLogger(__name__)

#: Bump on ANY change to extraction or the serialized shape: the cache
#: key includes it, so stale entries miss instead of lying.
IR_VERSION = 2  # v2: retained_params + gather_args (GL1007)

#: The effect lattice (a powerset over this alphabet; join = union).
EFFECTS = ("host_sync", "device_dispatch", "fs_write", "lock_acquire",
           "blocking_io", "materialize", "unseeded_rng")

# -- effect sink sets -------------------------------------------------------

#: Last-component call names that force a device->host transfer (kept
#: identical to pipeline_check.DEVICE_ROUND_SYNC_CALLS so GL1101 is an
#: exact transitive extension of lexical GL1006).
HOST_SYNC_LASTS = frozenset({"asarray", "item", "device_get",
                             "block_until_ready"})

#: Dotted call names of a hand-rolled durable-write idiom (GL806 set).
FS_IDIOM_CALLS = frozenset({
    "os.replace", "os.rename", "tempfile.mkstemp",
    "tempfile.NamedTemporaryFile",
})

#: Dotted call names that block the calling thread.
BLOCKING_CALLS = frozenset({
    "time.sleep", "subprocess.run", "subprocess.check_output",
    "subprocess.check_call", "select.select",
})
#: Last-component names that block when called on futures/events; kept
#: narrow (``.result``/``.wait`` on arbitrary objects is the common
#: blocking idiom in this codebase's pool code).
BLOCKING_LASTS = frozenset({"result"})

#: Materializers + the streamed-producer shape (GL1001's definitions).
MATERIALIZERS = frozenset({"list", "sorted", "tuple"})
STREAMING_PREFIX = "iter_"
STREAMING_SUFFIX = "_streamed"
STREAMING_NAMES = frozenset({"process_stream"})

#: Last-component call names that gather a paged band submatrix (the
#: GL1007 producer set; kept identical to pipeline_check.GATHER_NAMES
#: so the interprocedural arm is an exact transitive extension of the
#: lexical one).
GATHER_LASTS = frozenset({"gather", "band_gather"})

#: Receiver methods that retain their argument beyond the call (the
#: GL1007 retention sink set: the value outlives the band iteration).
RETAINER_METHODS = frozenset({"append", "add", "extend",
                              "appendleft", "setdefault"})

#: Global-state RNG (determinism_check's GL904 sets, minus seeded forms).
RANDOM_GLOBAL_FNS = frozenset({
    "random", "randint", "randrange", "uniform", "choice", "choices",
    "shuffle", "sample", "gauss", "normalvariate", "expovariate",
    "betavariate", "triangular", "getrandbits", "randbytes",
})
NP_RANDOM_GLOBAL_FNS = frozenset({
    "random", "rand", "randn", "randint", "random_sample", "ranf",
    "choice", "shuffle", "permutation", "uniform", "normal", "beta",
    "binomial", "poisson", "exponential", "standard_normal",
})

#: The one sanctioned durable writer: fs_write never propagates out of
#: functions defined here (callers *through* atomic are, by
#: construction, crash-consistent — that's the whole point of GL806).
SANCTIONED_WRITER = "galah_tpu/io/atomic.py"

_WRITE_MODE_CHARS = frozenset("wax+")

#: A function key: (repo-relative path, dotted qualname within file).
FuncKey = Tuple[str, str]


def _last(name: str) -> str:
    return name.rsplit(".", 1)[-1]


def _is_streaming_name(name: str) -> bool:
    n = _last(name)
    return (n.startswith(STREAMING_PREFIX)
            or n.endswith(STREAMING_SUFFIX) or n in STREAMING_NAMES)


def _root_name(node: ast.AST) -> Optional[str]:
    """The base identifier of an attribute/subscript chain
    (``self.cache[k]`` -> ``self``), or None for computed bases."""
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def _literal_open_mode(node: ast.Call) -> Optional[str]:
    mode_node: Optional[ast.AST] = None
    if len(node.args) >= 2:
        mode_node = node.args[1]
    for kw in node.keywords:
        if kw.arg == "mode":
            mode_node = kw.value
    if (isinstance(mode_node, ast.Constant)
            and isinstance(mode_node.value, str)):
        return mode_node.value
    return None


# ---------------------------------------------------------------------------
# Per-function IR
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class CallEdge:
    """One outgoing edge, unresolved (resolution is a link-time step).

    ``kind``: ``call`` (plain invocation), ``ref`` (a function
    reference/partial passed as an argument — runs on this thread,
    effects propagate), ``submit`` (pool.submit / Thread target — runs
    elsewhere, audited by GL1105 instead of propagated)."""

    name: str       # dotted callee expression as written
    line: int
    kind: str = "call"

    def to_list(self) -> list:
        return [self.name, self.line, self.kind]

    @classmethod
    def from_list(cls, raw: list) -> "CallEdge":
        return cls(name=raw[0], line=int(raw[1]), kind=raw[2])


@dataclasses.dataclass
class FuncIR:
    """IR for one function/method/nested def."""

    qualname: str                   # "f", "Cls.meth", "outer.inner"
    line: int
    # effect -> [line, detail] of the first direct witness in this body
    direct: Dict[str, List] = dataclasses.field(default_factory=dict)
    calls: List[CallEdge] = dataclasses.field(default_factory=list)
    params: List[str] = dataclasses.field(default_factory=list)
    # parameter names this body materializes directly (list(p)/...)
    materialized_params: List[str] = \
        dataclasses.field(default_factory=list)
    # [param, callee-name, arg-index, line]: p forwarded as positional
    # arg k of a call — the transitive half of GL1103
    forwarded_params: List[List] = \
        dataclasses.field(default_factory=list)
    # [callee-name, arg-index, line, producer]: a streamed-producer
    # value passed positionally into a call (the GL1103 pass sites)
    stream_args: List[List] = dataclasses.field(default_factory=list)
    # [param, line]: parameters this body stores beyond the call —
    # `self.x = p` / `obj[k] = p` / `acc.append(p)` — the direct half
    # of GL1007's retention query
    retained_params: List[List] = \
        dataclasses.field(default_factory=list)
    # [callee-name, arg-index, line, producer]: a gathered band
    # submatrix (gather()/band_gather() value) passed positionally
    # into a call (the GL1007 pass sites)
    gather_args: List[List] = dataclasses.field(default_factory=list)
    # body references timing.adopt/stage_token (the GL804/GL1105 mark)
    adopts: bool = False
    # decorator dotted names, outermost first (unwrapped for linking)
    decorators: List[str] = dataclasses.field(default_factory=list)
    # [line, receiver] of bare .acquire() calls not covered by a
    # try/finally release of the same receiver (the GL1104 witnesses)
    unsafe_acquires: List[List] = \
        dataclasses.field(default_factory=list)

    def to_dict(self) -> dict:
        return {
            "qualname": self.qualname,
            "line": self.line,
            "direct": self.direct,
            "calls": [c.to_list() for c in self.calls],
            "params": self.params,
            "materialized_params": self.materialized_params,
            "forwarded_params": self.forwarded_params,
            "stream_args": self.stream_args,
            "retained_params": self.retained_params,
            "gather_args": self.gather_args,
            "adopts": self.adopts,
            "decorators": self.decorators,
            "unsafe_acquires": self.unsafe_acquires,
        }

    @classmethod
    def from_dict(cls, raw: dict) -> "FuncIR":
        return cls(
            qualname=raw["qualname"], line=int(raw["line"]),
            direct={k: list(v) for k, v in raw["direct"].items()},
            calls=[CallEdge.from_list(c) for c in raw["calls"]],
            params=list(raw["params"]),
            materialized_params=list(raw["materialized_params"]),
            forwarded_params=[list(e) for e in raw["forwarded_params"]],
            stream_args=[list(e) for e in raw["stream_args"]],
            retained_params=[list(e)
                             for e in raw.get("retained_params", [])],
            gather_args=[list(e) for e in raw.get("gather_args", [])],
            adopts=bool(raw["adopts"]),
            decorators=list(raw["decorators"]),
            unsafe_acquires=[list(e) for e in raw["unsafe_acquires"]],
        )


# ---------------------------------------------------------------------------
# Per-module IR + extraction
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class ModuleIR:
    """IR for one source file: functions plus the resolution tables."""

    path: str
    content_hash: str
    functions: Dict[str, FuncIR] = dataclasses.field(default_factory=dict)
    # alias -> dotted module ("galah_tpu.ops.minhash") for `import x`
    # and the module interpretation of `from p import x`
    import_mods: Dict[str, str] = dataclasses.field(default_factory=dict)
    # alias -> [dotted module, attr] for `from p import x as y`
    import_attrs: Dict[str, List[str]] = \
        dataclasses.field(default_factory=dict)
    # module-level `name = other` function aliases: name -> dotted RHS
    aliases: Dict[str, str] = dataclasses.field(default_factory=dict)
    # module-level instance globals: name -> class name in this module
    instances: Dict[str, str] = dataclasses.field(default_factory=dict)
    classes: List[str] = dataclasses.field(default_factory=list)
    # harvested PIPELINE_STAGE["device_round"] / ["streaming"] lists
    device_round: List[str] = dataclasses.field(default_factory=list)
    streaming: List[str] = dataclasses.field(default_factory=list)
    # declares GUARDED_BY/LOCK_ORDER (the GL804/GL1105 threaded scope)
    annotated: bool = False

    def to_dict(self) -> dict:
        return {
            "ir_version": IR_VERSION,
            "path": self.path,
            "content_hash": self.content_hash,
            "functions": {q: f.to_dict()
                          for q, f in self.functions.items()},
            "import_mods": self.import_mods,
            "import_attrs": self.import_attrs,
            "aliases": self.aliases,
            "instances": self.instances,
            "classes": self.classes,
            "device_round": self.device_round,
            "streaming": self.streaming,
            "annotated": self.annotated,
        }

    @classmethod
    def from_dict(cls, raw: dict) -> "ModuleIR":
        return cls(
            path=raw["path"], content_hash=raw["content_hash"],
            functions={q: FuncIR.from_dict(f)
                       for q, f in raw["functions"].items()},
            import_mods=dict(raw["import_mods"]),
            import_attrs={k: list(v)
                          for k, v in raw["import_attrs"].items()},
            aliases=dict(raw["aliases"]),
            instances=dict(raw["instances"]),
            classes=list(raw["classes"]),
            device_round=list(raw["device_round"]),
            streaming=list(raw["streaming"]),
            annotated=bool(raw["annotated"]),
        )


def _harvest_literal(tree: ast.Module, name: str):
    for node in tree.body:
        targets: list = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = [node.target]
        else:
            continue
        for t in targets:
            if isinstance(t, ast.Name) and t.id == name:
                try:
                    return ast.literal_eval(node.value)
                except (ValueError, SyntaxError):
                    return None
    return None


class _Extractor:
    """One-pass AST -> ModuleIR extraction for a single file."""

    def __init__(self, src: SourceFile) -> None:
        self.src = src
        self.ir = ModuleIR(path=src.path.replace("\\", "/"),
                           content_hash=src.content_hash())

    def run(self) -> ModuleIR:
        ir, tree = self.ir, self.src.tree
        for key in ("GUARDED_BY", "LOCK_ORDER"):
            if _harvest_literal(tree, key) is not None:
                ir.annotated = True
        stage = _harvest_literal(tree, "PIPELINE_STAGE")
        if isinstance(stage, dict):
            for field, dst in (("device_round", ir.device_round),
                               ("streaming", ir.streaming)):
                val = stage.get(field, [])
                if isinstance(val, list):
                    dst.extend(s for s in val if isinstance(s, str))
        self._scan_toplevel(tree)
        # every function def, at any nesting, under its dotted qualname
        for node in tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._extract_function(node, prefix="")
            elif isinstance(node, ast.ClassDef):
                ir.classes.append(node.name)
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        self._extract_function(
                            item, prefix=node.name + ".")
        return ir

    def _scan_toplevel(self, tree: ast.Module) -> None:
        ir = self.ir
        class_names = {n.name for n in tree.body
                       if isinstance(n, ast.ClassDef)}
        func_names = {n.name for n in tree.body
                      if isinstance(n, (ast.FunctionDef,
                                        ast.AsyncFunctionDef))}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    ir.import_mods[a.asname or a.name] = a.name
            elif isinstance(node, ast.ImportFrom):
                if node.level:
                    continue  # no relative imports in this tree
                mod = node.module or ""
                for a in node.names:
                    alias = a.asname or a.name
                    # `from galah_tpu.obs import trace` imports a
                    # MODULE; `from ...policy import f` a function —
                    # record both, the linker decides by existence
                    ir.import_mods.setdefault(alias, f"{mod}.{a.name}")
                    ir.import_attrs.setdefault(alias, [mod, a.name])
        for node in tree.body:
            if not isinstance(node, ast.Assign):
                continue
            for t in node.targets:
                if not isinstance(t, ast.Name):
                    continue
                v = node.value
                if (isinstance(v, ast.Call)
                        and isinstance(v.func, ast.Name)
                        and v.func.id in class_names):
                    ir.instances[t.id] = v.func.id
                else:
                    rhs = dotted_name(v)
                    if rhs and (rhs in func_names or "." in rhs
                                or rhs in ir.import_mods
                                or rhs in ir.import_attrs):
                        # `slab_fold = _slab_fold_jit` style alias
                        ir.aliases[t.id] = rhs

    # -- one function ------------------------------------------------

    def _extract_function(self, node: ast.AST, prefix: str) -> None:
        qual = prefix + node.name
        fn = FuncIR(qualname=qual, line=node.lineno,
                    params=[a.arg for a in (node.args.posonlyargs
                                            + node.args.args)])
        for dec in node.decorator_list:
            dn = dotted_name(dec if not isinstance(dec, ast.Call)
                             else dec.func)
            if dn:
                fn.decorators.append(dn)
            if _last(dn) == "jit" or (
                    isinstance(dec, ast.Call) and dec.args
                    and _last(dotted_name(dec.args[0])) == "jit"):
                fn.direct.setdefault(
                    "device_dispatch",
                    [node.lineno, "jit-decorated body"])
        self.ir.functions[qual] = fn
        self._walk_body(node, fn, qual)

    def _walk_body(self, node: ast.AST, fn: FuncIR, qual: str) -> None:
        # names bound to a streamed producer / a band gather inside
        # this body
        bound_streams: Set[str] = set()
        bound_gathers: Set[str] = set()
        for sub in ast.walk(node):
            if not (isinstance(sub, ast.Assign)
                    and isinstance(sub.value, ast.Call)):
                continue
            cname = dotted_name(sub.value.func)
            if _is_streaming_name(cname):
                for t in sub.targets:
                    if isinstance(t, ast.Name):
                        bound_streams.add(t.id)
            if _last(cname) in GATHER_LASTS:
                for t in sub.targets:
                    if isinstance(t, ast.Name):
                        bound_gathers.add(t.id)
        # names bound in THIS body (nested defs excluded): a store
        # into a container rooted at one of these dies with the call,
        # so it is not retention
        local_stores: Set[str] = set()

        def collect_stores(n: ast.AST) -> None:
            if isinstance(n, (ast.FunctionDef,
                              ast.AsyncFunctionDef)) and n is not node:
                return
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store):
                local_stores.add(n.id)
            for child in ast.iter_child_nodes(n):
                collect_stores(child)

        collect_stores(node)

        def escapes(root: Optional[str]) -> bool:
            """The container outlives the call: self, a parameter, or
            a name this body never binds (a global/closure)."""
            return (root is not None
                    and (root == "self" or root in fn.params
                         or root not in local_stores))

        def visit(n: ast.AST) -> None:
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and n is not node:
                # nested def: its own FuncIR; the enclosing function
                # only reaches it through an explicit edge
                self._extract_function(n, prefix=fn.qualname + ".")
                return
            if isinstance(n, (ast.Attribute, ast.Name)):
                ident = n.attr if isinstance(n, ast.Attribute) else n.id
                if ident in ("adopt", "stage_token"):
                    fn.adopts = True
            if isinstance(n, ast.Assign):
                # `self.x = p` / `GLOBAL[k] = p`: the parameter's
                # value outlives the call (GL1007's direct retention
                # half); a store into a body-local container does not
                v = n.value
                if (isinstance(v, ast.Name) and v.id in fn.params
                        and any(isinstance(t, (ast.Attribute,
                                               ast.Subscript))
                                and escapes(_root_name(t))
                                for t in n.targets)):
                    fn.retained_params.append([v.id, n.lineno])
            if isinstance(n, ast.Call):
                self._extract_call(n, fn, bound_streams,
                                   bound_gathers, escapes)
            for child in ast.iter_child_nodes(n):
                visit(child)

        # body statements only: decorator expressions are def-time
        # machinery (handled in _extract_function), not body effects
        for child in node.body:
            visit(child)
        self._find_unsafe_acquires(node, fn)

    def _effect(self, fn: FuncIR, effect: str, line: int,
                detail: str) -> None:
        fn.direct.setdefault(effect, [line, detail])

    def _extract_call(self, call: ast.Call, fn: FuncIR,
                      bound_streams: Set[str],
                      bound_gathers: Set[str], escapes) -> None:
        name = dotted_name(call.func)
        last = _last(name)
        line = call.lineno

        # ---- direct effects ----
        if last in HOST_SYNC_LASTS:
            self._effect(fn, "host_sync", line, f"{name}()")
        if last == "pallas_call":
            self._effect(fn, "device_dispatch", line, f"{name}()")
        if name in FS_IDIOM_CALLS:
            self._effect(fn, "fs_write", line, f"{name}()")
        elif name in ("open", "os.fdopen"):
            mode = _literal_open_mode(call)
            if mode is not None and any(c in _WRITE_MODE_CHARS
                                        for c in mode):
                self._effect(fn, "fs_write", line,
                             f"write-mode {name}()")
        if last == "acquire" and "." in name:
            self._effect(fn, "lock_acquire", line, f"{name}()")
        if name in BLOCKING_CALLS or (last in BLOCKING_LASTS
                                      and "." in name):
            self._effect(fn, "blocking_io", line, f"{name}()")
        self._extract_rng(call, fn, name, last, line)

        # ---- materialization (direct + param forms) ----
        if (isinstance(call.func, ast.Name)
                and call.func.id in MATERIALIZERS and call.args):
            arg = call.args[0]
            if (isinstance(arg, ast.Call)
                    and _is_streaming_name(dotted_name(arg.func))):
                self._effect(
                    fn, "materialize", line,
                    f"{call.func.id}() over "
                    f"{_last(dotted_name(arg.func))}()")
            elif isinstance(arg, ast.Name):
                if arg.id in bound_streams:
                    self._effect(
                        fn, "materialize", line,
                        f"{call.func.id}() over streamed {arg.id}")
                if arg.id in fn.params and \
                        arg.id not in fn.materialized_params:
                    fn.materialized_params.append(arg.id)

        # ---- retention (GL1007's direct half) ----
        if (isinstance(call.func, ast.Attribute)
                and call.func.attr in RETAINER_METHODS and call.args
                and escapes(_root_name(call.func.value))):
            for arg in call.args:
                if isinstance(arg, ast.Name) and arg.id in fn.params:
                    fn.retained_params.append([arg.id, line])

        # ---- call edges ----
        if name:
            fn.calls.append(CallEdge(name=name, line=line))
        is_submit = (isinstance(call.func, ast.Attribute)
                     and call.func.attr == "submit")
        thread_target: Optional[ast.AST] = None
        if name in ("threading.Thread", "Thread"):
            for kw in call.keywords:
                if kw.arg == "target":
                    thread_target = kw.value
        arg_exprs = list(call.args) + [kw.value for kw in call.keywords]
        for idx, arg in enumerate(call.args):
            # a parameter forwarded positionally: the GL1103 half-edge
            if isinstance(arg, ast.Name) and arg.id in fn.params \
                    and name:
                fn.forwarded_params.append([arg.id, name, idx, line])
            # a streamed producer passed into a call: GL1103 pass site
            if name and call.func is not arg:
                if (isinstance(arg, ast.Call)
                        and _is_streaming_name(dotted_name(arg.func))):
                    fn.stream_args.append(
                        [name, idx, line,
                         _last(dotted_name(arg.func))])
                elif isinstance(arg, ast.Name) \
                        and arg.id in bound_streams:
                    fn.stream_args.append([name, idx, line, arg.id])
            # a gathered band submatrix passed into a call: GL1007
            # pass site
            if name and call.func is not arg:
                if (isinstance(arg, ast.Call)
                        and _last(dotted_name(arg.func))
                        in GATHER_LASTS):
                    fn.gather_args.append(
                        [name, idx, line,
                         _last(dotted_name(arg.func))])
                elif isinstance(arg, ast.Name) \
                        and arg.id in bound_gathers:
                    fn.gather_args.append([name, idx, line, arg.id])
        for arg in arg_exprs:
            target = arg
            kind = "ref"
            if (isinstance(arg, ast.Call)
                    and _last(dotted_name(arg.func)) == "partial"
                    and arg.args):
                target = arg.args[0]   # functools.partial(f, ...) -> f
            ref = dotted_name(target)
            if not ref or ref in ("self", "None", "True", "False"):
                continue
            if is_submit and arg is (call.args[0] if call.args
                                     else None):
                kind = "submit"
            elif thread_target is not None and arg is thread_target:
                kind = "submit"
            fn.calls.append(CallEdge(name=ref, line=arg.lineno
                                     if hasattr(arg, "lineno")
                                     else line, kind=kind))
        # pool.submit(wrapper(f), x): the wrapper call is the callable
        if is_submit and call.args and isinstance(call.args[0],
                                                  ast.Call):
            wname = dotted_name(call.args[0].func)
            if wname:
                fn.calls.append(CallEdge(name=wname,
                                         line=call.args[0].lineno,
                                         kind="submit"))

    def _extract_rng(self, call: ast.Call, fn: FuncIR, name: str,
                     last: str, line: int) -> None:
        parts = name.split(".")
        if len(parts) == 2 and parts[0] == "random" \
                and parts[1] in RANDOM_GLOBAL_FNS:
            self._effect(fn, "unseeded_rng", line, f"{name}()")
        elif len(parts) >= 2 and parts[-2] == "random" \
                and parts[0] in ("np", "numpy") \
                and parts[-1] in NP_RANDOM_GLOBAL_FNS:
            self._effect(fn, "unseeded_rng", line, f"{name}()")
        elif last in ("Random", "RandomState", "default_rng") \
                and not call.args and not call.keywords:
            self._effect(fn, "unseeded_rng", line, f"{name}() unseeded")

    # -- GL1104 witnesses --------------------------------------------

    def _find_unsafe_acquires(self, node: ast.AST, fn: FuncIR) -> None:
        """Bare ``X.acquire()`` statements not covered by a
        try/finally that releases the same receiver. Sanctioned
        shapes::

            lock.acquire()                 try:
            try:                               lock.acquire()
                ...                            ...
            finally:                       finally:
                lock.release()                 lock.release()

        A ``return self.acquire()`` passthrough (context-manager
        delegation) is exempt — the caller owns the release."""

        simple = (ast.Expr, ast.Assign, ast.AnnAssign, ast.AugAssign,
                  ast.Return, ast.Assert, ast.Raise)

        def acquires_in(n: ast.AST) -> List[Tuple[int, str]]:
            found: List[Tuple[int, str]] = []
            for c in ast.walk(n):
                if (isinstance(c, ast.Call)
                        and isinstance(c.func, ast.Attribute)
                        and c.func.attr == "acquire"):
                    recv = dotted_name(c.func.value)
                    if recv:
                        found.append((c.lineno, recv))
            return found

        def releases(try_node: ast.Try, receiver: str) -> bool:
            for sub in try_node.finalbody:
                for c in ast.walk(sub):
                    if (isinstance(c, ast.Call)
                            and isinstance(c.func, ast.Attribute)
                            and c.func.attr == "release"
                            and dotted_name(c.func.value) == receiver):
                        return True
            return False

        def scan(body: List[ast.stmt],
                 guard: Optional[ast.Try] = None) -> None:
            """guard: the enclosing Try whose finally may release an
            acquire made directly inside its body."""
            for i, stmt in enumerate(body):
                if isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef,
                                     ast.ClassDef)):
                    continue  # nested defs have their own FuncIR
                if isinstance(stmt, ast.Try):
                    scan(stmt.body, guard=stmt)
                    for h in stmt.handlers:
                        scan(h.body, guard=guard)
                    scan(stmt.orelse, guard=stmt)
                    scan(stmt.finalbody, guard=guard)
                    continue
                if isinstance(stmt, simple):
                    if isinstance(stmt, ast.Return):
                        continue  # passthrough delegation
                    for line, recv in acquires_in(stmt):
                        if guard is not None and releases(guard, recv):
                            continue
                        nxt = (body[i + 1]
                               if i + 1 < len(body) else None)
                        if isinstance(nxt, ast.Try) \
                                and releases(nxt, recv):
                            continue
                        fn.unsafe_acquires.append([line, recv])
                    continue
                # compound (If/For/While/With): expression parts are
                # never a sanctioned acquire position; recurse bodies
                for field in ("test", "iter"):
                    sub = getattr(stmt, field, None)
                    if sub is not None:
                        for line, recv in acquires_in(sub):
                            fn.unsafe_acquires.append([line, recv])
                for field in ("body", "orelse", "finalbody"):
                    sub = getattr(stmt, field, None)
                    if isinstance(sub, list):
                        scan(sub, guard=guard)

        scan(getattr(node, "body", []))


def extract_module_ir(src: SourceFile) -> ModuleIR:
    """Per-file IR from an already-parsed SourceFile (no caching)."""
    return _Extractor(src).run()


# ---------------------------------------------------------------------------
# IR cache (content-hash keyed, diskcache discipline)
# ---------------------------------------------------------------------------


class IRCache:
    """Per-file IR entries under ``dir``; ``IRCache(None)`` disables.

    Same discipline as io/diskcache.py: entries are keyed by content
    (sha256 of the source text + IR_VERSION), written through
    io/atomic.py so concurrent lint runs sharing a cache directory
    never observe torn entries, and any unreadable/mismatched entry is
    miss-and-repair — a corrupt cache costs a rebuild, never a wrong
    IR."""

    def __init__(self, path: Optional[str]) -> None:
        self.path = path
        self.hits = 0
        self.misses = 0
        if path:
            os.makedirs(path, exist_ok=True)

    @property
    def enabled(self) -> bool:
        return self.path is not None

    def _entry_path(self, path: str, content_hash: str) -> str:
        # the key covers the repo-relative path too: identical file
        # contents at two paths (empty __init__.py files) must not
        # share an entry, because the IR records the owning path
        key = hashlib.sha256(
            f"ir|v{IR_VERSION}|{path}|{content_hash}".encode()
        ).hexdigest()[:32]
        return os.path.join(self.path, f"ir-{key}.json")

    def load(self, path: str, content_hash: str) -> Optional[ModuleIR]:
        if not self.enabled:
            return None
        entry = self._entry_path(path, content_hash)
        try:
            with open(entry, "r", encoding="utf-8") as fh:
                raw = json.load(fh)
            if raw.get("ir_version") != IR_VERSION \
                    or raw.get("content_hash") != content_hash \
                    or raw.get("path") != path:
                raise ValueError("key mismatch")
            ir = ModuleIR.from_dict(raw)
        except FileNotFoundError:
            self.misses += 1
            return None
        except Exception as exc:  # corrupt entry: miss-and-repair
            logger.warning("Dropping corrupt IR cache entry %s (%s)",
                           entry, exc)
            try:
                os.unlink(entry)
            except OSError:
                pass
            self.misses += 1
            return None
        self.hits += 1
        return ir

    def store(self, ir: ModuleIR) -> None:
        if not self.enabled:
            return
        from galah_tpu.io import atomic

        atomic.write_json(self._entry_path(ir.path, ir.content_hash),
                          ir.to_dict(),
                          site="io.atomic.write[ir-cache]")

    # -- generic small-verdict entries (shapes family reuses this) ----

    def _verdict_path(self, kind: str, digest: str) -> str:
        return os.path.join(self.path, f"{kind}-{digest[:32]}.json")

    def load_verdict(self, kind: str, digest: str) -> Optional[dict]:
        if not self.enabled:
            return None
        try:
            with open(self._verdict_path(kind, digest), "r",
                      encoding="utf-8") as fh:
                raw = json.load(fh)
            if raw.get("digest") != digest:
                raise ValueError("key mismatch")
            return raw
        except FileNotFoundError:
            return None
        except Exception as exc:
            logger.warning("Dropping corrupt %s verdict entry (%s)",
                           kind, exc)
            try:
                os.unlink(self._verdict_path(kind, digest))
            except OSError:
                pass
            return None

    def store_verdict(self, kind: str, digest: str,
                      payload: dict) -> None:
        if not self.enabled:
            return
        from galah_tpu.io import atomic

        payload = dict(payload, digest=digest)
        atomic.write_json(self._verdict_path(kind, digest), payload,
                          site=f"io.atomic.write[{kind}-verdict]")


def default_cache_dir() -> Optional[str]:
    """Cache directory from the GALAH_TPU_IR_CACHE flag, or None
    (disabled). Name + default live once, in config.FLAGS."""
    from galah_tpu.config import env_value

    return env_value("GALAH_TPU_IR_CACHE") or None


# ---------------------------------------------------------------------------
# Linking + effect fixpoint
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class Witness:
    """Provenance of one (function, effect): either a direct sink in
    this body, or a call edge whose callee carries the effect."""

    line: int                       # line IN the owning function
    detail: str                     # sink description for direct
    callee: Optional[FuncKey] = None   # next hop, None when direct

    @property
    def direct(self) -> bool:
        return self.callee is None


def _module_path_to_dotted(path: str) -> Optional[str]:
    p = path.replace("\\", "/")
    if not p.endswith(".py"):
        return None
    p = p[:-3]
    if p.endswith("/__init__"):
        p = p[: -len("/__init__")]
    return p.replace("/", ".")


class ProgramIR:
    """All ModuleIRs linked: resolved call graph + effect fixpoint."""

    def __init__(self, modules: Sequence[ModuleIR]) -> None:
        self.modules: Dict[str, ModuleIR] = {
            m.path: m for m in modules}
        # dotted module name -> path (galah_tpu.ops.minhash -> file)
        self.by_dotted: Dict[str, str] = {}
        for m in modules:
            dotted = _module_path_to_dotted(m.path)
            if dotted:
                self.by_dotted[dotted] = m.path
        self.functions: Dict[FuncKey, FuncIR] = {}
        for m in modules:
            for qual, fn in m.functions.items():
                self.functions[(m.path, qual)] = fn
        self._resolved: Dict[FuncKey,
                             List[Tuple[FuncKey, int, str]]] = {}
        self._effects: Dict[FuncKey, Dict[str, Witness]] = {}
        self._adopts: Dict[FuncKey, bool] = {}
        self._mat_params: Dict[FuncKey, Dict[str, Witness]] = {}
        self._ret_params: Dict[FuncKey, Dict[str, Witness]] = {}
        self._link()
        self._fixpoint()

    # -- name resolution ---------------------------------------------

    def resolve(self, mod: ModuleIR, caller_qual: str,
                name: str) -> Optional[FuncKey]:
        """(path, qualname) for a dotted callee expression, or None.

        Resolution order: nested defs of the caller (innermost-out),
        module functions/classes, module-level aliases, imports (module
        and from-import interpretations), instance-method dispatch,
        absolute ``galah_tpu.x.y.f`` chains. ``self.meth`` resolves
        within the caller's class."""
        if not name:
            return None
        parts = name.split(".")
        # self.meth inside a method
        if parts[0] == "self" and len(parts) == 2 \
                and "." in caller_qual:
            cls = caller_qual.split(".", 1)[0]
            key = (mod.path, f"{cls}.{parts[1]}")
            if key in self.functions:
                return key
            return None
        if len(parts) == 1:
            n = parts[0]
            # nested def lookup, innermost enclosing scope outwards
            scope = caller_qual
            while scope:
                key = (mod.path, f"{scope}.{n}")
                if key in self.functions:
                    return key
                scope = scope.rsplit(".", 1)[0] if "." in scope else ""
            if (mod.path, n) in self.functions:
                return (mod.path, n)
            if n in mod.classes:
                key = (mod.path, f"{n}.__init__")
                return key if key in self.functions else None
            if n in mod.aliases and mod.aliases[n] != n:
                return self.resolve(mod, caller_qual, mod.aliases[n])
            if n in mod.import_attrs:
                dmod, attr = mod.import_attrs[n]
                target = self.by_dotted.get(dmod)
                if target and (target, attr) in self.functions:
                    return (target, attr)
                if target and attr in self.modules[target].classes:
                    key = (target, f"{attr}.__init__")
                    return key if key in self.functions else None
            if n in mod.import_mods:
                # `from galah_tpu.ops import minhash` then bare call?
                # (a module is not callable; nothing to resolve)
                return None
            return None
        # dotted: resolve the base, then the attribute
        base, rest = parts[0], parts[1:]
        if base in mod.instances and len(rest) == 1:
            key = (mod.path, f"{mod.instances[base]}.{rest[0]}")
            return key if key in self.functions else None
        # longest-prefix module match over the import table and
        # absolute dotted paths
        for cut in range(len(parts) - 1, 0, -1):
            prefix = ".".join(parts[:cut])
            dmod: Optional[str] = None
            if cut == 1 and base in mod.import_mods:
                dmod = mod.import_mods[base]
            elif prefix in self.by_dotted:
                dmod = prefix
            if dmod is None:
                continue
            target = self.by_dotted.get(dmod)
            if target is None:
                continue
            attr = ".".join(parts[cut:])
            tmod = self.modules[target]
            if (target, attr) in self.functions:
                return (target, attr)
            if attr in tmod.classes:
                key = (target, f"{attr}.__init__")
                return key if key in self.functions else None
            if attr.split(".")[0] in tmod.aliases:
                return self.resolve(tmod, "", attr)
        return None

    def _link(self) -> None:
        for (path, qual), fn in self.functions.items():
            mod = self.modules[path]
            out: List[Tuple[FuncKey, int, str]] = []
            seen: Set[Tuple[FuncKey, str]] = set()
            for edge in fn.calls:
                key = self.resolve(mod, qual, edge.name)
                if key is None or key == (path, qual):
                    continue
                if (key, edge.kind) in seen:
                    continue
                seen.add((key, edge.kind))
                out.append((key, edge.line, edge.kind))
            self._resolved[(path, qual)] = out

    # -- fixpoint ------------------------------------------------------

    def _fixpoint(self) -> None:
        for key, fn in self.functions.items():
            self._effects[key] = {
                eff: Witness(line=w[0], detail=w[1])
                for eff, w in fn.direct.items()}
            self._adopts[key] = fn.adopts
            self._mat_params[key] = {
                p: Witness(line=fn.line, detail="materialized here")
                for p in fn.materialized_params}
            # direct retention witnesses carry the storing line; the
            # transitive links below carry the callee's param name in
            # `detail` so render_retention_chain can keep walking
            self._ret_params[key] = {
                p: Witness(line=line, detail="")
                for p, line in fn.retained_params}
        keys = sorted(self.functions)
        changed = True
        while changed:
            changed = False
            for key in keys:
                mine = self._effects[key]
                for callee, line, kind in self._resolved[key]:
                    if kind == "submit":
                        continue  # runs elsewhere; GL1105's business
                    for eff, wit in self._effects[callee].items():
                        if eff in mine:
                            continue
                        if eff == "fs_write" and \
                                callee[0] == SANCTIONED_WRITER:
                            continue  # sanctioned boundary
                        mine[eff] = Witness(line=line, detail="",
                                            callee=callee)
                        changed = True
                    if not self._adopts[key] \
                            and self._adopts[callee] and kind == "call":
                        self._adopts[key] = True
                        changed = True
                # transitive materialized params: p forwarded as
                # positional arg k of a callee whose k-th param
                # materializes
                fn = self.functions[key]
                for p, cname, idx, line in fn.forwarded_params:
                    callee = self.resolve(self.modules[key[0]],
                                          key[1], cname)
                    if callee is None:
                        continue
                    cfn = self.functions[callee]
                    if idx >= len(cfn.params):
                        continue
                    if (p not in self._mat_params[key]
                            and cfn.params[idx]
                            in self._mat_params[callee]):
                        self._mat_params[key][p] = Witness(
                            line=line, detail="", callee=callee)
                        changed = True
                    # transitive retention, same walk: p forwarded as
                    # arg k of a callee whose k-th param is retained
                    if (p not in self._ret_params[key]
                            and cfn.params[idx]
                            in self._ret_params[callee]):
                        self._ret_params[key][p] = Witness(
                            line=line, detail=cfn.params[idx],
                            callee=callee)
                        changed = True

    # -- queries -------------------------------------------------------

    def effects_of(self, key: FuncKey) -> Dict[str, Witness]:
        return self._effects.get(key, {})

    def adopts(self, key: FuncKey) -> bool:
        return self._adopts.get(key, False)

    def materializing_param(self, key: FuncKey,
                            index: int) -> Optional[str]:
        """The name of callee param `index` when it is materialized
        (directly or transitively), else None."""
        fn = self.functions.get(key)
        if fn is None or index >= len(fn.params):
            return None
        p = fn.params[index]
        return p if p in self._mat_params.get(key, {}) else None

    def retaining_param(self, key: FuncKey,
                        index: int) -> Optional[str]:
        """The name of callee param `index` when its value is stored
        beyond the call (directly or transitively), else None."""
        fn = self.functions.get(key)
        if fn is None or index >= len(fn.params):
            return None
        p = fn.params[index]
        return p if p in self._ret_params.get(key, {}) else None

    def render_retention_chain(self, key: FuncKey, param: str) -> str:
        """'g -> h: parameter 'q' retained at path.py:30' for GL1007
        messages — the provenance walk from the function handed the
        gathered value down to the storing statement."""
        parts: List[str] = []
        seen: Set[Tuple[FuncKey, str]] = set()
        cur, p = key, param
        while (cur, p) not in seen:
            seen.add((cur, p))
            parts.append(cur[1])
            wit = self._ret_params.get(cur, {}).get(p)
            if wit is None:
                break
            if wit.callee is None:
                return (f"{' -> '.join(parts)}: parameter {p!r} "
                        f"retained at {cur[0]}:{wit.line}")
            cur, p = wit.callee, wit.detail
        return f"{' -> '.join(parts)}: parameter {p!r} retained"

    def witness_chain(self, key: FuncKey,
                      effect: str) -> List[Tuple[FuncKey, Witness]]:
        """The provenance path [(owner, witness), ...] from `key` down
        to the direct sink (bounded by the function count, so a cycle
        cannot loop forever)."""
        out: List[Tuple[FuncKey, Witness]] = []
        seen: Set[FuncKey] = set()
        cur: Optional[FuncKey] = key
        while cur is not None and cur not in seen:
            seen.add(cur)
            wit = self._effects.get(cur, {}).get(effect)
            if wit is None:
                break
            out.append((cur, wit))
            cur = wit.callee
        return out

    def render_chain(self, key: FuncKey, effect: str) -> str:
        """'f -> g -> h: np.asarray() at path.py:42' for messages."""
        chain = self.witness_chain(key, effect)
        if not chain:
            return ""
        names = " -> ".join(k[1] for k, _ in chain)
        owner, sink = chain[-1]
        return (f"{names}: {sink.detail or effect} at "
                f"{owner[0]}:{sink.line}")


# ---------------------------------------------------------------------------
# Build: sources (+ optional cache) -> ProgramIR
# ---------------------------------------------------------------------------


def build_program_ir(sources: Dict[str, SourceFile],
                     cache: Optional[IRCache] = None) -> ProgramIR:
    """ProgramIR over the loaded tree. With a cache, per-file
    extraction is skipped for content-hash hits; linking and the
    effect fixpoint always run fresh (cross-file, cheap)."""
    cache = cache or IRCache(None)
    modules: List[ModuleIR] = []
    for src in sources.values():
        path = src.path.replace("\\", "/")
        ir = cache.load(path, src.content_hash())
        if ir is None:
            ir = extract_module_ir(src)
            cache.store(ir)
        modules.append(ir)
    return ProgramIR(modules)
