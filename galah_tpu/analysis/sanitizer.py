"""GalahSan: runtime concurrency sanitizer for the threaded modules.

The GL8xx auditors check lock discipline *lexically*: GUARDED_BY and
LOCK_ORDER annotations are validated against the source text, so an
annotation that drifts from runtime behavior passes silently. GalahSan
closes that gap by instrumenting the declared locks themselves and
validating the contracts under the real workload:

  * every declared lock (module globals and per-instance ``Cls._lock``
    attributes) is wrapped in a :class:`SanLock` proxy that records the
    observed acquisition graph per thread — which lock was held when
    which other lock was taken, with the first call site;
  * undeclared module-level locks in the same modules are wrapped too,
    so a nested acquisition involving a lock the annotations never
    mention is caught ("undeclared acquisition");
  * GUARDED_BY-annotated attributes get mutation checks: container
    values (dict/list/set) are replaced with instrumented subclasses
    and attribute REbinding goes through a ``__setattr__`` shim on the
    annotated classes, so a mutation without the declared lock held is
    a race finding unless the object is still single-owner.

At report time the observed graph is diffed against the declared order:

  * ``undeclared_edge``  — a nested acquisition of two *declared* locks
    whose pair appears in no LOCK_ORDER (error);
  * ``inversion``        — the observed edge contradicts a declared
    pair, i.e. the canonical deadlock precursor (error);
  * ``undeclared_acquisition`` — a nested acquisition involving a lock
    absent from every annotation (error);
  * ``race``             — a guarded mutation without its lock (error);
  * ``unexercised``      — a declared pair never observed under the
    workload (info: coverage, not a bug).

Enable with ``GALAH_SAN=1`` (conftest sets it for tier-1 runs); the
report lands in ``sanitizer_report.json`` (``GALAH_SAN_REPORT``) and is
merged into run_report.json (schema v4) by obs.report.

Known limitations, by design (all covered lexically by GL8xx):
rebinding a module *global* from inside its own module bypasses module
``__setattr__`` (STORE_GLOBAL writes the dict directly), so scalar
latches like ``sketch_stream._DEMOTED`` are checked lexically only;
mutations of *nested* containers (a dict inside a guarded dict) are
one level too deep for the instrumented containers; and a module-level
guarded container that is re-*bound* (rather than mutated) sheds its
instrumentation until the next install().
"""

from __future__ import annotations

import importlib
import json
import os
import sys
import threading
import types
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

_LOCK_TYPES = (type(threading.Lock()),)

#: Where the standalone report goes when GALAH_SAN_REPORT is unset.
DEFAULT_REPORT = "sanitizer_report.json"

#: Cap on per-lock thread-id sets and per-edge site lists.
_MAX_THREADS_TRACKED = 64

_THIS_FILE = os.path.abspath(__file__)


def _caller_site() -> str:
    """file:line of the nearest frame outside the sanitizer itself."""
    try:
        f = sys._getframe(1)
    except ValueError:  # pragma: no cover - no frames
        return "?"
    while f is not None:
        fn = f.f_code.co_filename
        if os.path.abspath(fn) != _THIS_FILE:
            try:
                rel = os.path.relpath(fn)
            except ValueError:  # pragma: no cover - windows drives
                rel = fn
            if not rel.startswith(".."):
                fn = rel.replace(os.sep, "/")
            return f"{fn}:{f.f_lineno}"
        f = f.f_back
    return "?"  # pragma: no cover - sanitizer-internal call


class SanLock:
    """Proxy around a ``threading.Lock`` that reports to a Sanitizer.

    Supports the context-manager protocol plus acquire/release/locked,
    which covers every lock idiom in the repo (GL8xx bans the rest).
    """

    __slots__ = ("_inner", "name", "declared", "_san", "_threads",
                 "acquisitions")

    def __init__(self, inner, name: str, san: "Sanitizer",
                 declared: bool) -> None:
        self._inner = inner
        self.name = name
        self.declared = declared
        self._san = san
        self._threads: set = set()
        self.acquisitions = 0

    def acquire(self, blocking: bool = True,
                timeout: float = -1) -> bool:
        self._san._note_attempt(self)
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._san._held().append(self)
        return got

    def release(self) -> None:
        held = self._san._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i] is self:
                del held[i]
                break
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"<SanLock {self.name} declared={self.declared}>"


class _GuardMeta:
    """How one guarded container resolves its lock and owner."""

    __slots__ = ("san", "target", "get_lock", "get_owner")

    def __init__(self, san: "Sanitizer", target: str,
                 get_lock: Callable[[], Optional[SanLock]],
                 get_owner: Callable[[], Optional[int]]) -> None:
        self.san = san
        self.target = target
        self.get_lock = get_lock
        self.get_owner = get_owner


def _mutator(name):
    def method(self, *a, **kw):
        m = self._san_meta
        if m is not None:
            m.san._check_guarded(m.target, m.get_lock(), m.get_owner(),
                                 how=name)
        return getattr(self._san_base, name)(self, *a, **kw)
    method.__name__ = name
    return method


def _instrumented(base, mutators):
    ns = {"_san_meta": None, "_san_base": base}
    ns.update({m: _mutator(m) for m in mutators})
    cls = type(f"San{base.__name__.capitalize()}", (base,), ns)
    return cls


SanDict = _instrumented(dict, (
    "__setitem__", "__delitem__", "clear", "pop", "popitem",
    "setdefault", "update"))
SanList = _instrumented(list, (
    "__setitem__", "__delitem__", "__iadd__", "__imul__", "append",
    "extend", "insert", "pop", "remove", "clear", "sort", "reverse"))
SanSet = _instrumented(set, (
    "add", "discard", "remove", "pop", "clear", "update",
    "difference_update", "intersection_update",
    "symmetric_difference_update", "__ior__", "__iand__", "__isub__",
    "__ixor__"))

_CONTAINER_MAP = {dict: SanDict, list: SanList, set: SanSet}


class _ClassMeta:
    """Instrumentation plan for one annotated class."""

    __slots__ = ("cls", "modpath", "lock_attrs", "guarded")

    def __init__(self, cls: type, modpath: str) -> None:
        self.cls = cls
        self.modpath = modpath
        #: lock-valued attrs to wrap at construction, attr -> canon name
        self.lock_attrs: Dict[str, str] = {}
        #: guarded attrs, attr -> (("attr", lock attr) |
        #: ("name", canonical lock name), canonical target)
        self.guarded: Dict[str, Tuple[Tuple[str, str], str]] = {}


class Sanitizer:
    """Observed-vs-declared lock-graph recorder. One per process
    (:data:`GLOBAL`); tests build isolated instances over synthetic
    modules via :meth:`install_module`."""

    def __init__(self) -> None:
        # Internal lock. Invariant: no user (San-wrapped) lock is ever
        # acquired while _lock is held, so instrumentation can never
        # add an edge — or a deadlock — to the graph it audits.
        self._lock = threading.Lock()
        self._tls = threading.local()
        self.installed = False
        self.modules: List[str] = []
        #: canonical name -> first SanLock wrapped under that name
        self._lock_objs: Dict[str, SanLock] = {}
        #: id(inner) -> SanLock, so shared lock objects wrap once
        self._by_id: Dict[int, SanLock] = {}
        #: (held name, acquired name) -> {"count", "where"}
        self.edges: Dict[Tuple[str, str], Dict[str, Any]] = {}
        #: (outer name, inner name) -> declaring module path
        self.declared_pairs: Dict[Tuple[str, str], str] = {}
        self.declared_locks: set = set()
        self.races: List[Dict[str, Any]] = []
        self._race_keys: set = set()
        self._class_meta: Dict[type, _ClassMeta] = {}

    # -- thread-local held stack ------------------------------------

    def _held(self) -> List[SanLock]:
        held = getattr(self._tls, "held", None)
        if held is None:
            held = []
            self._tls.held = held
        return held

    # -- recording ---------------------------------------------------

    def _note_attempt(self, lock: SanLock) -> None:
        held = self._held()
        tid = threading.get_ident()
        with self._lock:
            lock.acquisitions += 1
            if (tid not in lock._threads
                    and len(lock._threads) < _MAX_THREADS_TRACKED):
                lock._threads.add(tid)
            for h in held:
                if h is lock or h.name == lock.name:
                    continue
                key = (h.name, lock.name)
                edge = self.edges.get(key)
                if edge is None:
                    self.edges[key] = {"count": 1,
                                       "where": _caller_site()}
                else:
                    edge["count"] += 1

    def _check_guarded(self, target: str, lock: Optional[SanLock],
                       owner: Optional[int], how: str) -> None:
        if not isinstance(lock, SanLock):
            return  # lock not instrumented: can't judge, stay silent
        held = self._held()
        for h in held:
            if h is lock:
                return
        tid = threading.get_ident()
        if tid == owner:
            # single-owner phase: the constructing thread may mutate
            # freely until any OTHER thread has touched the lock
            with self._lock:
                foreign = any(t != owner for t in lock._threads)
            if not foreign:
                return
        where = _caller_site()
        key = (target, where, how)
        with self._lock:
            if key in self._race_keys:
                return
            self._race_keys.add(key)
            self.races.append({
                "target": target,
                "lock": lock.name,
                "thread": tid,
                "where": where,
                "how": how,
            })

    # -- wrapping ----------------------------------------------------

    def _wrap_lock(self, obj, name: str, declared: bool) -> SanLock:
        if isinstance(obj, SanLock):
            if declared and not obj.declared:
                obj.declared = True
                self.declared_locks.add(obj.name)
            return obj
        with self._lock:
            got = self._by_id.get(id(obj))
            if got is not None:
                if declared and not got.declared:
                    got.declared = True
                    self.declared_locks.add(got.name)
                return got
            lock = SanLock(obj, name, self, declared)
            self._by_id[id(obj)] = lock
            self._lock_objs.setdefault(name, lock)
            if declared:
                self.declared_locks.add(name)
            return lock

    def _wrap_container(self, val, meta: _GuardMeta):
        cls = _CONTAINER_MAP.get(type(val))
        if cls is None:
            return val
        wrapped = cls(val)
        wrapped._san_meta = meta
        return wrapped

    def _resolve_lockref(self, inst,
                         lockref: Tuple[str, str]) -> Optional[SanLock]:
        kind, key = lockref
        if kind == "attr":  # the instance's own lock attribute
            lock = inst.__dict__.get(key)
        else:  # canonical name of a module-global (possibly remote)
            lock = self._lock_objs.get(key)
        return lock if isinstance(lock, SanLock) else None

    def _prepare_instance(self, inst, meta: _ClassMeta) -> None:
        for attr, canon in meta.lock_attrs.items():
            cur = inst.__dict__.get(attr)
            if cur is not None:
                object.__setattr__(
                    inst, attr, self._wrap_lock(cur, canon,
                                                declared=True))
        object.__setattr__(inst, "_san_owner", threading.get_ident())
        for attr, (lockref, target) in meta.guarded.items():
            val = inst.__dict__.get(attr)
            if type(val) in _CONTAINER_MAP:
                gmeta = _GuardMeta(
                    self, target,
                    lambda i=inst, r=lockref:
                        self._resolve_lockref(i, r),
                    lambda i=inst: i.__dict__.get("_san_owner"))
                object.__setattr__(
                    inst, attr, self._wrap_container(val, gmeta))
        object.__setattr__(inst, "_san_ctor", False)

    def _patch_class(self, cls: type, modpath: str) -> _ClassMeta:
        meta = self._class_meta.get(cls)
        if meta is not None:
            return meta
        meta = _ClassMeta(cls, modpath)
        self._class_meta[cls] = meta
        san = self
        orig_init = cls.__init__
        orig_setattr = cls.__setattr__

        def san_init(inst, *a, **kw):
            object.__setattr__(inst, "_san_ctor", True)
            try:
                orig_init(inst, *a, **kw)
            finally:
                san._prepare_instance(inst, meta)

        def san_setattr(inst, name, value):
            info = meta.guarded.get(name)
            if info is not None:
                d = inst.__dict__
                if name in d and not d.get("_san_ctor", True):
                    lockref, target = info
                    san._check_guarded(
                        target,
                        san._resolve_lockref(inst, lockref),
                        d.get("_san_owner"),
                        how=f"{name} rebind")
            orig_setattr(inst, name, value)

        san_init.__name__ = "__init__"
        san_init.__qualname__ = f"{cls.__qualname__}.__init__"
        san_init.__wrapped__ = orig_init
        cls.__init__ = san_init
        cls.__setattr__ = san_setattr
        return meta

    # -- installation ------------------------------------------------

    @staticmethod
    def _canon(decl: str, modpath: str) -> str:
        return decl if ":" in decl else f"{modpath}:{decl}"

    @staticmethod
    def _lockref(lockdecl: str, modpath: str) -> Tuple[str, str]:
        """("attr", attrname) for an instance lock, else
        ("name", canonical) for a module-global (possibly
        cross-module "path.py:NAME")."""
        if ":" in lockdecl:
            return ("name", lockdecl)
        if "." in lockdecl:
            return ("attr", lockdecl.split(".", 1)[1])
        return ("name", f"{modpath}:{lockdecl}")

    def install_module(self, mod: types.ModuleType,
                       modpath: Optional[str] = None) -> None:
        """Instrument one module's declared locks and guarded state.

        ``mod`` may be a real galah_tpu module or a synthetic
        ``types.ModuleType`` built by a test reproducer.
        """
        if modpath is None:
            modpath = (getattr(mod, "__name__", "mod")
                       .replace(".", "/") + ".py")
        gb: Dict[str, str] = dict(getattr(mod, "GUARDED_BY", None)
                                  or {})
        lo: List[str] = list(getattr(mod, "LOCK_ORDER", None) or [])
        decls = set(gb.values()) | set(lo)

        # Declared order: every (earlier, later) pair, like the lexical
        # checker's _declared_order.
        for i in range(len(lo)):
            for j in range(i + 1, len(lo)):
                pair = (self._canon(lo[i], modpath),
                        self._canon(lo[j], modpath))
                self.declared_pairs.setdefault(pair, modpath)

        # Classes named by any "Cls.attr" declaration.
        for decl in sorted(decls | set(gb)):
            if ":" in decl or "." not in decl:
                continue
            clsname, attr = decl.split(".", 1)
            cls = getattr(mod, clsname, None)
            if not isinstance(cls, type):
                continue
            meta = self._patch_class(cls, modpath)
            if decl in decls:  # it's a lock attribute
                meta.lock_attrs[attr] = self._canon(decl, modpath)
        for target, lockdecl in gb.items():
            if ":" in target or "." not in target:
                continue
            clsname, attr = target.split(".", 1)
            cls = getattr(mod, clsname, None)
            if not isinstance(cls, type):
                continue
            meta = self._patch_class(cls, modpath)
            meta.guarded[attr] = (self._lockref(lockdecl, modpath),
                                  self._canon(target, modpath))

        # Module-global locks: declared ones by name, then any other
        # module-level Lock (undeclared — visible to edge detection).
        for decl in sorted(decls):
            if ":" in decl or "." in decl:
                continue
            obj = getattr(mod, decl, None)
            if isinstance(obj, _LOCK_TYPES):
                setattr(mod, decl,
                        self._wrap_lock(obj, self._canon(decl, modpath),
                                        declared=True))
        for name, obj in sorted(vars(mod).items()):
            if isinstance(obj, _LOCK_TYPES):
                setattr(mod, name,
                        self._wrap_lock(obj, self._canon(name, modpath),
                                        declared=False))

        # Module-global guarded containers.
        owner_tid = threading.get_ident()
        for target, lockdecl in gb.items():
            if ":" in target or "." in target:
                continue
            val = getattr(mod, target, None)
            lockref = self._lockref(lockdecl, modpath)
            if lockref[0] != "name":
                continue
            gmeta = _GuardMeta(
                self, self._canon(target, modpath),
                lambda n=lockref[1]: self._lock_objs.get(n),
                lambda t=owner_tid: t)
            wrapped = self._wrap_container(val, gmeta)
            if wrapped is not val:
                setattr(mod, target, wrapped)

        # Pre-existing instances of the patched classes: module globals,
        # plus one container level down (profile._REGISTRY list,
        # metrics.GLOBAL._metrics dict).
        patched = tuple(self._class_meta)
        if patched:
            for inst in self._iter_instances(mod, patched):
                if "_san_ctor" not in inst.__dict__:
                    meta = self._class_meta.get(type(inst))
                    if meta is not None:
                        self._prepare_instance(inst, meta)

        self.modules.append(modpath)

    @staticmethod
    def _iter_instances(mod: types.ModuleType, patched: tuple):
        def scan(val, depth: int):
            if isinstance(val, patched):
                yield val
                val = getattr(val, "__dict__", None)
                if not isinstance(val, dict):
                    return
            if depth <= 0:
                return
            if isinstance(val, dict):
                items: Sequence = list(val.values())
            elif isinstance(val, (list, tuple)):
                items = list(val)
            else:
                return
            for item in items:
                yield from scan(item, depth - 1)

        for val in list(vars(mod).values()):
            yield from scan(val, 2)

    def install(self,
                modules: Optional[Sequence[str]] = None) -> None:
        """Instrument the repo's THREADED_MODULES (idempotent)."""
        if self.installed:
            return
        if modules is None:
            from galah_tpu.analysis.concurrency_check import \
                THREADED_MODULES
            modules = THREADED_MODULES
        for modpath in modules:
            modname = modpath[:-3].replace("/", ".")
            self.install_module(importlib.import_module(modname),
                                modpath)
        self.installed = True

    # -- reporting ---------------------------------------------------

    def findings(self) -> List[Dict[str, Any]]:
        """Diff observed graph vs declarations. Race findings included."""
        out: List[Dict[str, Any]] = []
        with self._lock:
            edges = {k: dict(v) for k, v in self.edges.items()}
            races = [dict(r) for r in self.races]
        exercised = set()
        for (a, b), edge in sorted(edges.items()):
            locks = [a, b]
            if (a, b) in self.declared_pairs:
                exercised.add((a, b))
                continue
            a_decl = a in self.declared_locks
            b_decl = b in self.declared_locks
            if a_decl and b_decl:
                kind = ("inversion" if (b, a) in self.declared_pairs
                        else "undeclared_edge")
                detail = (f"acquired {b} while holding {a}, but "
                          f"LOCK_ORDER declares {b} before {a}"
                          if kind == "inversion" else
                          f"acquired {b} while holding {a}; no "
                          f"LOCK_ORDER declares this pair")
            else:
                kind = "undeclared_acquisition"
                undecl = [n for n, d in ((a, a_decl), (b, b_decl))
                          if not d]
                detail = (f"nested acquisition {a} -> {b} involves "
                          f"lock(s) absent from every annotation: "
                          + ", ".join(undecl))
            out.append({"kind": kind, "severity": "error",
                        "locks": locks, "count": edge["count"],
                        "where": edge["where"], "detail": detail})
        for race in races:
            out.append({
                "kind": "race", "severity": "error",
                "locks": [race["lock"]], "where": race["where"],
                "detail": (f"{race['target']} mutated "
                           f"({race['how']}) without holding "
                           f"{race['lock']} (thread "
                           f"{race['thread']})")})
        for (a, b), modpath in sorted(self.declared_pairs.items()):
            if (a, b) not in exercised:
                out.append({
                    "kind": "unexercised", "severity": "info",
                    "locks": [a, b], "where": modpath,
                    "detail": (f"declared order {a} -> {b} never "
                               f"exercised under this workload")})
        return out

    def summary(self) -> Dict[str, Any]:
        """Small dict for run_report.json / terminal summaries."""
        findings = self.findings()
        counts: Dict[str, int] = {}
        for f in findings:
            counts[f["kind"]] = counts.get(f["kind"], 0) + 1
        with self._lock:
            acquisitions = sum(l.acquisitions
                               for l in self._lock_objs.values())
            n_locks = len(self._lock_objs)
        return {
            "enabled": True,
            "modules": len(self.modules),
            "locks": n_locks,
            "declared_locks": len(self.declared_locks),
            "acquisitions": acquisitions,
            "edges_observed": len(self.edges),
            "edges_declared": len(self.declared_pairs),
            "undeclared_acquisitions":
                counts.get("undeclared_acquisition", 0),
            "undeclared_edges": counts.get("undeclared_edge", 0),
            "inversions": counts.get("inversion", 0),
            "races": counts.get("race", 0),
            "unexercised": counts.get("unexercised", 0),
        }

    def errors(self) -> List[Dict[str, Any]]:
        """Only the error-severity findings (the must-be-zero set)."""
        return [f for f in self.findings()
                if f["severity"] == "error"]

    def report(self) -> Dict[str, Any]:
        with self._lock:
            locks = {
                name: {"declared": l.declared,
                       "acquisitions": l.acquisitions,
                       "threads": len(l._threads)}
                for name, l in sorted(self._lock_objs.items())}
            edges = [{"held": a, "acquired": b,
                      "count": e["count"], "where": e["where"]}
                     for (a, b), e in sorted(self.edges.items())]
        return {
            "version": 1,
            "summary": self.summary(),
            "modules": list(self.modules),
            "locks": locks,
            "edges": edges,
            "declared_order": [
                {"outer": a, "inner": b, "module": m}
                for (a, b), m in sorted(self.declared_pairs.items())],
            "findings": self.findings(),
        }

    def write_report(self, path: Optional[str] = None) -> str:
        path = path or os.environ.get("GALAH_SAN_REPORT",
                                      DEFAULT_REPORT)
        from galah_tpu.io import atomic
        atomic.write_json(path, self.report(), indent=1,
                          site="io.atomic.write[sanitizer]")
        return path

    def reset_observations(self) -> None:
        """Drop observed edges/races (instrumentation stays armed)."""
        with self._lock:
            self.edges.clear()
            self.races.clear()
            self._race_keys.clear()
            for lock in self._lock_objs.values():
                lock.acquisitions = 0
                lock._threads.clear()


GLOBAL = Sanitizer()


def enabled() -> bool:
    """True when GALAH_SAN asks for the sanitizer (see config.FLAGS)."""
    return os.environ.get("GALAH_SAN", "") not in ("", "0")


def maybe_install() -> bool:
    """Install the process-wide sanitizer iff GALAH_SAN is set."""
    if not enabled():
        return False
    GLOBAL.install()
    return True


def summary_if_enabled() -> Optional[Dict[str, Any]]:
    """The GLOBAL summary when installed, else None (for obs.report)."""
    if not GLOBAL.installed:
        return None
    return GLOBAL.summary()


def main(argv: Optional[Sequence[str]] = None) -> int:
    """``python -m galah_tpu.analysis.sanitizer``: install, import the
    threaded modules, exercise nothing, dump the (empty) report — a
    wiring smoke test; real coverage comes from tier-1 under
    GALAH_SAN=1."""
    GLOBAL.install()
    path = GLOBAL.write_report()
    print(json.dumps(GLOBAL.summary(), indent=1))
    print(f"wrote {path}")
    return 1 if GLOBAL.errors() else 0


if __name__ == "__main__":  # pragma: no cover
    raise SystemExit(main())
