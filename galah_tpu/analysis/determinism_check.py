"""Numeric determinism (GL9xx): bit-stable reductions by contract.

The north-star items both lean on exact reproducibility: incremental
dereplication must reproduce clusters over an unchanged catalogue, and
multi-host all-pairs must reduce bit-identically across hosts. PR 5's
ulp drift — ``np.where(mask, x, 0)`` summed with ``np.add.reduceat``
instead of compressing to ``x[mask]`` first — is the canonical bug:
reduceat/pairwise summation groups by RUN LENGTH, so zero-filling
masked slots shifts the block boundaries and drifts the float. That
class, and its neighbors, are what this family flags.

Strategy modules declare a machine-readable contract (a plain literal,
harvested from the AST like PALLAS_CONTRACT — never imported):

    DETERMINISM_CONTRACT = {
        "family": "fragment",        # pairlist | fragment | greedy_select
        "dtype": "float64",          # the accumulation dtype promised
        "functions": ["directed_ani_batch", "_seq_sum", ...],
    }

Checks
  GL901  (contract functions) a sum / reduceat over an operand that is
         a masked ZERO-FILLED ``np.where``/``jnp.where`` array — the
         exact PR 5 class. Compress first (``x[mask]``, or
         ``_segment_compressed_sums`` for batched segments); a
         subscript-compressed operand is recognized as clean.
  GL902  (pipeline modules) iteration over a ``set``/``frozenset``
         value — or materializing one via list/tuple/np.array — whose
         order is hash-seed-dependent and must not feed device buffers
         or pair ordering; wrap in ``sorted(...)``. dict iteration is
         insertion-ordered and deliberately NOT flagged.
  GL903  (contract functions, float64 contracts) an f64->f32 narrowing
         (``.astype(float32)``, ``np.float32(x)``, ``dtype=float32``)
         inside a function the contract promises accumulates in f64.
  GL904  (pipeline modules) unseeded RNG: the ``random`` module's
         global functions, ``random.Random()`` / ``np.random.*`` /
         ``default_rng()`` / ``RandomState()`` without a seed. Seeded
         constructions (``random.Random(f"site:{seed}")``) pass.
  GL905  contract hygiene: a strategy module without a
         DETERMINISM_CONTRACT, a malformed contract, or an entry
         naming a function that no longer exists.

Scope: GL902/GL904 use the GL7xx pipeline-module scope (galah_tpu/
minus utils/, obs/, analysis/); GL901/GL903 run wherever a contract is
declared, so fixtures fire regardless of path.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional

from galah_tpu.analysis.concurrency_check import harvest_literal
from galah_tpu.analysis.contracts import dtype_from_node
from galah_tpu.analysis.core import (Finding, Severity, SourceFile,
                                     dotted_name)
from galah_tpu.analysis.obs_check import in_scope

#: Modules that MUST declare a DETERMINISM_CONTRACT (GL905 if absent):
#: the strategy families whose variants must stay bit-identical.
STRATEGY_MODULES = (
    "galah_tpu/ops/pallas_pairlist.py",
    "galah_tpu/ops/sparse_device.py",
    "galah_tpu/ops/fragment_ani.py",
    "galah_tpu/ops/pallas_fragment.py",
    "galah_tpu/ops/greedy_select.py",
    "galah_tpu/ops/sketch_stream.py",
    "galah_tpu/ops/bucketing.py",
    "galah_tpu/parallel/mesh.py",
)

_WHERE_CALLS = frozenset({
    "np.where", "jnp.where", "numpy.where", "jax.numpy.where",
})
_SUM_CALLS = frozenset({
    "np.sum", "jnp.sum", "numpy.sum", "math.fsum", "sum",
    "np.add.reduceat", "jnp.add.reduceat", "numpy.add.reduceat",
})
_ARRAY_BUILDERS = frozenset({
    "list", "tuple", "np.array", "np.asarray", "numpy.array",
    "numpy.asarray", "jnp.array", "jnp.asarray", "np.fromiter",
})
#: The stdlib `random` module's global-state functions.
_RANDOM_GLOBAL_FNS = frozenset({
    "random", "randint", "randrange", "uniform", "choice", "choices",
    "shuffle", "sample", "gauss", "normalvariate", "expovariate",
    "betavariate", "triangular", "getrandbits", "randbytes",
})
#: numpy's legacy global-state RNG functions.
_NP_RANDOM_GLOBAL_FNS = frozenset({
    "random", "rand", "randn", "randint", "random_sample", "ranf",
    "choice", "shuffle", "permutation", "uniform", "normal", "beta",
    "binomial", "poisson", "exponential", "standard_normal",
})


def _is_zero(node: ast.AST) -> bool:
    return (isinstance(node, ast.Constant)
            and not isinstance(node.value, bool)
            and node.value == 0)


def _is_zero_fill_where(node: ast.AST) -> bool:
    return (isinstance(node, ast.Call)
            and dotted_name(node.func) in _WHERE_CALLS
            and len(node.args) == 3
            and _is_zero(node.args[2]))


def _function_defs(tree: ast.Module) -> Dict[str, List[ast.AST]]:
    """Every def in the module (any nesting) by simple name."""
    out: Dict[str, List[ast.AST]] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.setdefault(node.name, []).append(node)
    return out


# ---------------------------------------------------------------------------
# GL901 / GL903: contract-function checks
# ---------------------------------------------------------------------------


def _check_contract_function(fn: ast.AST, src: SourceFile,
                             contract_dtype: Optional[str]) -> \
        List[Finding]:
    findings: List[Finding] = []
    # local name -> lineno of its zero-filled np.where assignment
    zero_filled: Dict[str, int] = {}
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            if _is_zero_fill_where(node.value):
                zero_filled[node.targets[0].id] = node.lineno
            elif node.targets[0].id in zero_filled:
                del zero_filled[node.targets[0].id]  # rebound clean

    def summed_operand(call: ast.Call) -> Optional[ast.AST]:
        name = dotted_name(call.func)
        if name in _SUM_CALLS and call.args:
            return call.args[0]
        # x.sum() / x.sum(axis=...) — receiver is the operand
        if isinstance(call.func, ast.Attribute) and call.func.attr == "sum":
            return call.func.value
        return None

    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        operand = summed_operand(node)
        if operand is not None:
            bad = (_is_zero_fill_where(operand)
                   or (isinstance(operand, ast.Name)
                       and operand.id in zero_filled))
            # a Subscript operand (c_w[mask]) is the compressed form —
            # exactly what the contract wants — and never flags
            if bad:
                findings.append(Finding(
                    "GL901", Severity.ERROR, src.path, node.lineno,
                    "sum over a masked zero-filled array: "
                    "np.where(mask, x, 0) keeps the full run length, "
                    "so reduceat/pairwise summation blocks differ "
                    "from the compressed segment's and the float "
                    "drifts a ulp (the PR 5 class) — compress first "
                    "(x[mask] / _segment_compressed_sums)",
                    symbol=getattr(fn, "name", "")))
        if contract_dtype == "float64":
            narrow = None
            fname = dotted_name(node.func)
            if (isinstance(node.func, ast.Attribute)
                    and node.func.attr == "astype" and node.args
                    and dtype_from_node(node.args[0]) == "float32"):
                narrow = ".astype(float32)"
            elif fname.endswith(".float32") or fname == "float32":
                narrow = "float32() cast"
            for kw in node.keywords:
                if (kw.arg == "dtype"
                        and dtype_from_node(kw.value) == "float32"):
                    narrow = "dtype=float32"
            if narrow is not None:
                findings.append(Finding(
                    "GL903", Severity.WARNING, src.path, node.lineno,
                    f"{narrow} inside a function whose "
                    "DETERMINISM_CONTRACT promises float64 "
                    "accumulation — narrowing changes rounding and "
                    "breaks cross-strategy bit-identity",
                    symbol=getattr(fn, "name", "")))
    return findings


# ---------------------------------------------------------------------------
# GL902 / GL904: pipeline-module checks
# ---------------------------------------------------------------------------


def _set_typed(node: ast.AST, set_names: Dict[str, int]) -> bool:
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    if isinstance(node, ast.Call) and dotted_name(node.func) in (
            "set", "frozenset"):
        return True
    if isinstance(node, ast.Name):
        return node.id in set_names
    return False


def _check_hash_order(src: SourceFile) -> List[Finding]:
    findings: List[Finding] = []
    # module-wide linear map of names assigned set-typed values; a
    # later non-set rebind clears the entry (lexical, good enough)
    set_names: Dict[str, int] = {}
    for node in src.walk():
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            if _set_typed(node.value, set_names):
                set_names[node.targets[0].id] = node.lineno
            else:
                set_names.pop(node.targets[0].id, None)

    def flag(lineno: int, how: str) -> None:
        findings.append(Finding(
            "GL902", Severity.WARNING, src.path, lineno,
            f"{how} a set — its order is hash-dependent and must not "
            "feed device buffers or pair ordering; wrap in sorted() "
            "(dict iteration is insertion-ordered and fine)"))

    for node in src.walk():
        if isinstance(node, ast.For) and _set_typed(node.iter,
                                                    set_names):
            flag(node.lineno, "for-loop over")
        elif isinstance(node, (ast.ListComp, ast.GeneratorExp,
                               ast.DictComp, ast.SetComp)):
            for gen in node.generators:
                if _set_typed(gen.iter, set_names):
                    flag(node.lineno, "comprehension over")
        elif isinstance(node, ast.Call):
            if (dotted_name(node.func) in _ARRAY_BUILDERS
                    and len(node.args) == 1
                    and _set_typed(node.args[0], set_names)):
                flag(node.lineno,
                     f"{dotted_name(node.func)}() materializes")
    return findings


def _check_rng(src: SourceFile) -> List[Finding]:
    findings: List[Finding] = []
    for node in src.walk():
        if not isinstance(node, ast.Call):
            continue
        name = dotted_name(node.func)
        parts = name.split(".")
        unseeded = None
        if len(parts) == 2 and parts[0] == "random" \
                and parts[1] in _RANDOM_GLOBAL_FNS:
            unseeded = f"{name}() uses the global random state"
        elif len(parts) == 3 and parts[0] in ("np", "numpy") \
                and parts[1] == "random" \
                and parts[2] in _NP_RANDOM_GLOBAL_FNS:
            unseeded = f"{name}() uses numpy's legacy global state"
        elif name in ("random.Random", "np.random.RandomState",
                      "numpy.random.RandomState",
                      "np.random.default_rng",
                      "numpy.random.default_rng", "default_rng") \
                and not node.args and not node.keywords:
            unseeded = f"{name}() constructed without a seed"
        if unseeded is not None:
            findings.append(Finding(
                "GL904", Severity.WARNING, src.path, node.lineno,
                f"unseeded RNG in a pipeline module: {unseeded}; "
                "seed it (random.Random(f'site:{seed}') / "
                "default_rng(seed)) so re-runs reproduce"))
    return findings


# ---------------------------------------------------------------------------
# The checker
# ---------------------------------------------------------------------------


def check_determinism_file(src: SourceFile) -> List[Finding]:
    findings: List[Finding] = []
    path = src.path.replace("\\", "/")
    contract = harvest_literal(src.tree, "DETERMINISM_CONTRACT")

    # GL905: registry coverage + contract hygiene
    if contract is None and path in STRATEGY_MODULES:
        findings.append(Finding(
            "GL905", Severity.WARNING, path, 1,
            "strategy module lacks a DETERMINISM_CONTRACT "
            "annotation (family/dtype/functions)"))
    fn_names: List[str] = []
    dtype: Optional[str] = None
    if contract is not None:
        if not isinstance(contract, dict) or not isinstance(
                contract.get("functions"), list) or not all(
                isinstance(f, str) for f in contract["functions"]):
            findings.append(Finding(
                "GL905", Severity.WARNING, path, 1,
                "DETERMINISM_CONTRACT must be a literal dict with a "
                "'functions' list of names (plus family/dtype)"))
            contract = None
        else:
            fn_names = list(contract["functions"])
            dtype = contract.get("dtype")

    defs = _function_defs(src.tree)
    for name in fn_names:
        nodes = defs.get(name)
        if not nodes:
            findings.append(Finding(
                "GL905", Severity.WARNING, path, 1,
                f"stale DETERMINISM_CONTRACT entry {name!r}: no such "
                "function in this module"))
            continue
        for fn in nodes:
            findings.extend(_check_contract_function(fn, src, dtype))

    if in_scope(path) or contract is not None:
        findings.extend(_check_hash_order(src))
        findings.extend(_check_rng(src))
    return findings
