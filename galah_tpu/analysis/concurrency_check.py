"""Concurrency discipline (GL8xx): lock annotations, order, adoption.

The threaded modules (obs emission, IO prefetch, the resilience layer,
the stage timer) each guard shared state with explicit locks. PR 4's
worker-thread stage misattribution was found late and by hand; this
family makes the discipline *declared* and machine-checked.

Annotated modules carry two module-level literals (harvested from the
AST via ``ast.literal_eval``, never imported):

    GUARDED_BY = {
        # "ClassName.attr" or "_MODULE_GLOBAL"  ->  the lock that must
        # be held for every MUTATION (reads are a caller's judgment:
        # snapshot methods deliberately tolerate torn reads)
        "StageTimer._shared": "StageTimer._lock",
        "_EVENTS": "_LOCK",
    }
    LOCK_ORDER = ["_WARN_ONCE_LOCK", "_LOCK"]   # outermost first

Checks
  GL801  a ``GUARDED_BY`` target mutated outside a ``with <lock>:``
         block holding its declared lock. Mutation = assignment /
         augmented assignment / deletion of the attribute (through any
         subscript depth) or a mutating method call (append, pop,
         clear, write, ...). ``__init__`` of the owning class and
         module top level are exempt (single-threaded construction).
  GL802  a lock acquired while holding another lock that the merged
         ``LOCK_ORDER`` declarations say must be acquired LATER —
         the classic AB/BA inversion, caught lexically and through
         calls (a function called under lock A that acquires lock B
         creates the same edge).
  GL803  a cycle in the observed acquisition graph — including the
         length-1 cycle of re-acquiring a held non-reentrant Lock
         (self-deadlock). A lock constructed as ``threading.RLock()``
         is reentrant by contract, so its length-1 cycle is exempt
         (the under-lock-helper idiom: a public method holds the lock
         and calls a ``*_locked`` helper that re-enters it); longer
         cycles still report — reentrancy never excuses an AB/BA.
  GL804  a thread-pool ``submit`` or ``threading.Thread(target=...)``
         whose callable is not adopt-wrapped: worker threads must
         capture ``timing.stage_token()`` in the spawning thread and
         run under ``timing.adopt(token)`` or their telemetry lands on
         an empty thread-local stage stack (the PR 4 bug class).
  GL805  annotation hygiene: a module in the threaded-module registry
         without annotations, a stale ``GUARDED_BY`` entry (class /
         attribute / global that no longer exists), an undeclarable
         lock name, or LOCK_ORDER declarations that contradict each
         other across modules.

The checks run on every module that carries annotations (fixtures
included); the registry below only drives the GL805 missing-annotation
finding. Scope is intentionally the eight threaded modules — e.g. the
fragment-ANI C-merge pool is engine-side and out of scope here.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from galah_tpu.analysis.core import (Finding, Severity, SourceFile,
                                     dotted_name)

#: Modules that MUST declare GUARDED_BY/LOCK_ORDER (GL805 when absent).
THREADED_MODULES = (
    "galah_tpu/obs/metrics.py",
    "galah_tpu/obs/trace.py",
    "galah_tpu/obs/events.py",
    "galah_tpu/obs/profile.py",
    "galah_tpu/obs/flow.py",
    "galah_tpu/obs/heartbeat.py",
    "galah_tpu/io/prefetch.py",
    "galah_tpu/resilience/dispatch.py",
    "galah_tpu/resilience/policy.py",
    "galah_tpu/resilience/faults.py",
    "galah_tpu/utils/timing.py",
    "galah_tpu/ops/sketch_stream.py",
    "galah_tpu/index/store.py",
    "galah_tpu/index/incremental.py",
    "galah_tpu/fleet/scheduler.py",
)

#: Method calls that mutate their receiver in place.
MUTATORS = frozenset({
    "append", "appendleft", "extend", "insert", "add", "update",
    "setdefault", "pop", "popleft", "popitem", "remove", "discard",
    "clear", "sort", "reverse", "write", "writelines", "flush",
    "close", "truncate",
})

#: (module path, canonical lock name) — the global lock identity.
LockId = Tuple[str, str]


def harvest_literal(tree: ast.Module, name: str):
    """A module-level ``NAME = <literal>`` value, or None (same
    machine-readable-by-construction rule as PALLAS_CONTRACT)."""
    for node in tree.body:
        targets: list = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = [node.target]
        else:
            continue
        for t in targets:
            if isinstance(t, ast.Name) and t.id == name:
                try:
                    return ast.literal_eval(node.value)
                except (ValueError, SyntaxError):
                    return None
    return None


def _dotted_to_path(mod: str) -> str:
    return mod.replace(".", "/") + ".py"


class _Module:
    """Per-module model: annotations, classes, functions, instances,
    galah-internal imports."""

    def __init__(self, src: SourceFile) -> None:
        self.src = src
        self.path = src.path.replace("\\", "/")
        self.guarded = harvest_literal(src.tree, "GUARDED_BY")
        self.lock_order = harvest_literal(src.tree, "LOCK_ORDER")
        self.classes: Dict[str, ast.ClassDef] = {}
        self.functions: Dict[str, ast.FunctionDef] = {}
        self.methods: Dict[str, ast.FunctionDef] = {}  # "Cls.meth"
        self.instances: Dict[str, str] = {}   # global -> class name
        self.globals_assigned: Set[str] = set()
        self.import_mods: Dict[str, str] = {}   # alias -> module path
        self.import_funcs: Dict[str, Tuple[str, str]] = {}
        # lock names bound to threading.RLock() — reentrant, so the
        # GL803 length-1 self-cycle does not apply to them
        self.reentrant_locks: Set[str] = set()
        self._scan()

    @property
    def annotated(self) -> bool:
        return self.guarded is not None or self.lock_order is not None

    def _scan(self) -> None:
        for node in self.src.tree.body:
            if isinstance(node, ast.ClassDef):
                self.classes[node.name] = node
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        self.methods[f"{node.name}.{item.name}"] = item
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                self.functions[node.name] = node
            elif isinstance(node, (ast.Assign, ast.AnnAssign)):
                targets = (node.targets
                           if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    if not isinstance(t, ast.Name):
                        continue
                    self.globals_assigned.add(t.id)
                    v = node.value
                    if (v is not None and isinstance(v, ast.Call)
                            and isinstance(v.func, ast.Name)
                            and v.func.id in self.classes):
                        self.instances[t.id] = v.func.id
        for node in self.src.walk():
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name.startswith("galah_tpu"):
                        self.import_mods[a.asname or a.name] = \
                            _dotted_to_path(a.name)
            elif isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                if not mod.startswith("galah_tpu"):
                    continue
                for a in node.names:
                    child = f"{mod}.{a.name}"
                    alias = a.asname or a.name
                    # `from galah_tpu.obs import trace` imports a
                    # MODULE; `from ...policy import call_with_retry`
                    # imports a function — decide by existence later,
                    # record both interpretations.
                    self.import_mods.setdefault(
                        alias, _dotted_to_path(child))
                    self.import_funcs.setdefault(
                        alias, (_dotted_to_path(mod), a.name))
        self._scan_reentrant()

    def _scan_reentrant(self) -> None:
        def is_rlock(value: ast.AST) -> bool:
            return (isinstance(value, ast.Call)
                    and dotted_name(value.func).rsplit(".", 1)[-1]
                    == "RLock")

        for cname, cnode in self.classes.items():
            for n in ast.walk(cnode):
                if not (isinstance(n, ast.Assign)
                        and is_rlock(n.value)):
                    continue
                for t in n.targets:
                    if (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"):
                        self.reentrant_locks.add(f"{cname}.{t.attr}")
        for node in self.src.tree.body:
            if not (isinstance(node, ast.Assign)
                    and is_rlock(node.value)):
                continue
            for t in node.targets:
                if isinstance(t, ast.Name):
                    self.reentrant_locks.add(t.id)

    # -- canonicalization --------------------------------------------

    def canon_lock_decl(self, decl: str) -> Optional[LockId]:
        """'_LOCK' / 'Cls._lock' / 'other/module.py:_LOCK' -> LockId,
        or None when it names nothing in this module."""
        if ":" in decl:
            path, name = decl.split(":", 1)
            return (path, name)
        if "." in decl:
            cls, attr = decl.split(".", 1)
            if cls in self.classes and _class_has_attr(
                    self.classes[cls], attr):
                return (self.path, decl)
            return None
        if decl in self.globals_assigned:
            return (self.path, decl)
        return None

    def lock_of_expr(self, expr: ast.AST,
                     cls: Optional[str]) -> Optional[LockId]:
        """Canonical lock for a ``with`` context expression."""
        name = dotted_name(expr)
        if not name:
            return None
        parts = name.split(".")
        if len(parts) == 1:
            if parts[0] in self.globals_assigned:
                return (self.path, parts[0])
            return None
        if len(parts) == 2:
            if parts[0] == "self" and cls is not None:
                return (self.path, f"{cls}.{parts[1]}")
            if parts[0] in self.instances:
                return (self.path,
                        f"{self.instances[parts[0]]}.{parts[1]}")
        return None


def _class_has_attr(cls_node: ast.ClassDef, attr: str) -> bool:
    for node in ast.walk(cls_node):
        if (isinstance(node, ast.Attribute) and node.attr == attr
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"):
            return True
    return False


def _mutation_root(expr: ast.AST, m: _Module,
                   cls: Optional[str]) -> Optional[str]:
    """The GUARDED_BY candidate key a mutation of `expr` touches:
    descends through subscripts/attribute chains (mutating
    ``self._tree[path][0]`` mutates ``self._tree``)."""
    node = expr
    while True:
        if isinstance(node, ast.Subscript):
            node = node.value
            continue
        if isinstance(node, ast.Attribute):
            base = node.value
            if isinstance(base, ast.Name):
                if base.id == "self" and cls is not None:
                    return f"{cls}.{node.attr}"
                if base.id in m.instances:
                    return f"{m.instances[base.id]}.{node.attr}"
            node = base
            continue
        if isinstance(node, ast.Name):
            return node.id
        return None


class _FuncInfo:
    def __init__(self, module: _Module, qualname: str,
                 node: ast.AST, cls: Optional[str]) -> None:
        self.module = module
        self.qualname = qualname
        self.node = node
        self.cls = cls
        self.direct_acquires: Set[LockId] = set()
        self.calls: List[Tuple[Tuple[str, str], int]] = []
        self.may_acquire: Set[LockId] = set()


def _callee_keys(call: ast.Call, m: _Module, cls: Optional[str],
                 registry: Dict[Tuple[str, str], _FuncInfo]) -> \
        List[Tuple[str, str]]:
    f = call.func
    if isinstance(f, ast.Name):
        n = f.id
        if n in m.functions:
            return [(m.path, n)]
        if n in m.classes and (m.path, f"{n}.__init__") in registry:
            return [(m.path, f"{n}.__init__")]
        if n in m.import_funcs and m.import_funcs[n] in registry:
            return [m.import_funcs[n]]
        return []
    if isinstance(f, ast.Attribute):
        base = dotted_name(f.value)
        meth = f.attr
        if base == "self" and cls is not None:
            key = (m.path, f"{cls}.{meth}")
            return [key] if key in registry else []
        if base in m.instances:
            key = (m.path, f"{m.instances[base]}.{meth}")
            return [key] if key in registry else []
        if base in m.import_mods:
            key = (m.import_mods[base], meth)
            return [key] if key in registry else []
    return []


def _collect_funcinfo(modules: Sequence[_Module]) -> \
        Dict[Tuple[str, str], _FuncInfo]:
    registry: Dict[Tuple[str, str], _FuncInfo] = {}
    for m in modules:
        for name, node in m.functions.items():
            registry[(m.path, name)] = _FuncInfo(m, name, node, None)
        for qual, node in m.methods.items():
            cls = qual.split(".", 1)[0]
            registry[(m.path, qual)] = _FuncInfo(m, qual, node, cls)
    # direct acquisitions + resolvable call sites, then the transitive
    # may-acquire fixpoint (what makes the order check interprocedural)
    for info in registry.values():
        m, cls = info.module, info.cls
        for node in ast.walk(info.node):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    lock = m.lock_of_expr(item.context_expr, cls)
                    if lock is not None:
                        info.direct_acquires.add(lock)
            elif isinstance(node, ast.Call):
                for key in _callee_keys(node, m, cls, registry):
                    info.calls.append((key, node.lineno))
        info.may_acquire |= info.direct_acquires
    changed = True
    while changed:
        changed = False
        for info in registry.values():
            for key, _ in info.calls:
                callee = registry.get(key)
                if callee is None:
                    continue
                new = callee.may_acquire - info.may_acquire
                if new:
                    info.may_acquire |= new
                    changed = True
    return registry


def _adopting_defs(tree: ast.Module) -> Dict[str, bool]:
    """Every FunctionDef (any nesting) by simple name -> whether its
    body references the stage-adoption API (adopt / stage_token)."""
    out: Dict[str, bool] = {}
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        adopting = False
        for sub in ast.walk(node):
            name = ""
            if isinstance(sub, ast.Attribute):
                name = sub.attr
            elif isinstance(sub, ast.Name):
                name = sub.id
            if name in ("adopt", "stage_token"):
                adopting = True
                break
        out[node.name] = out.get(node.name, False) or adopting
    return out


def _callable_is_adopting(arg: ast.AST,
                          defs: Dict[str, bool]) -> Optional[bool]:
    """True/False when the submitted callable can be resolved to a
    local def; None when it cannot be resolved at all."""
    if isinstance(arg, ast.Call):
        # wrapper(f) — adopting iff the wrapper's def adopts
        fname = dotted_name(arg.func).split(".")[-1]
        if fname in defs:
            return defs[fname]
        return None
    name = dotted_name(arg)
    if name:
        simple = name.split(".")[-1]
        if simple in defs:
            return defs[simple]
    return None


# ---------------------------------------------------------------------------
# The checker
# ---------------------------------------------------------------------------


def check_concurrency(sources: Dict[str, SourceFile]) -> List[Finding]:
    findings: List[Finding] = []
    modules = [_Module(src) for src in sources.values()]
    annotated = [m for m in modules if m.annotated]
    by_path = {m.path: m for m in modules}

    # GL805: registry coverage
    for path in THREADED_MODULES:
        m = by_path.get(path)
        if m is not None and not m.annotated:
            findings.append(Finding(
                "GL805", Severity.WARNING, path, 1,
                "threaded module lacks GUARDED_BY/LOCK_ORDER "
                "annotations (declare them — empty literals are a "
                "valid 'no locked shared state here' statement)"))

    findings.extend(_check_annotations(annotated))

    declared_order, order_findings = _declared_order(annotated)
    findings.extend(order_findings)

    registry = _collect_funcinfo(annotated)

    edges: Dict[Tuple[LockId, LockId], Tuple[str, int, str]] = {}
    for info in registry.values():
        findings.extend(_walk_function(info, registry, edges))

    findings.extend(_order_violations(edges, declared_order))
    reentrant = {(m.path, name) for m in annotated
                 for name in m.reentrant_locks}
    findings.extend(_cycles(edges, reentrant))

    for m in annotated:
        findings.extend(_check_adoption(m))
    return findings


def _check_annotations(annotated: Sequence[_Module]) -> List[Finding]:
    out: List[Finding] = []
    for m in annotated:
        if m.guarded is not None and not (
                isinstance(m.guarded, dict)
                and all(isinstance(k, str) and isinstance(v, str)
                        for k, v in m.guarded.items())):
            out.append(Finding(
                "GL805", Severity.WARNING, m.path, 1,
                "GUARDED_BY must be a literal {str: str} dict of "
                "guarded target -> lock"))
            m.guarded = {}
        if m.lock_order is not None and not (
                isinstance(m.lock_order, list)
                and all(isinstance(e, str) for e in m.lock_order)):
            out.append(Finding(
                "GL805", Severity.WARNING, m.path, 1,
                "LOCK_ORDER must be a literal [str, ...] list, "
                "outermost lock first"))
            m.lock_order = []
        for key, lock in (m.guarded or {}).items():
            if "." in key:
                cls, attr = key.split(".", 1)
                if cls not in m.classes:
                    out.append(Finding(
                        "GL805", Severity.WARNING, m.path, 1,
                        f"stale GUARDED_BY entry {key!r}: class "
                        f"{cls!r} does not exist in this module"))
                    continue
                if not _class_has_attr(m.classes[cls], attr):
                    out.append(Finding(
                        "GL805", Severity.WARNING, m.path, 1,
                        f"stale GUARDED_BY entry {key!r}: "
                        f"self.{attr} never appears in class {cls}"))
                    continue
            elif key not in m.globals_assigned:
                out.append(Finding(
                    "GL805", Severity.WARNING, m.path, 1,
                    f"stale GUARDED_BY entry {key!r}: no such "
                    "module-level global"))
                continue
            if m.canon_lock_decl(lock) is None:
                out.append(Finding(
                    "GL805", Severity.WARNING, m.path, 1,
                    f"GUARDED_BY[{key!r}] names unknown lock "
                    f"{lock!r} (want 'ClassName._lock', a module "
                    "global, or 'path.py:NAME')"))
    return out


def _declared_order(annotated: Sequence[_Module]) -> \
        Tuple[Dict[Tuple[LockId, LockId], str], List[Finding]]:
    declared: Dict[Tuple[LockId, LockId], str] = {}
    out: List[Finding] = []
    for m in sorted(annotated, key=lambda m: m.path):
        locks: List[LockId] = []
        for decl in (m.lock_order or []):
            lock = m.canon_lock_decl(decl)
            if lock is None:
                out.append(Finding(
                    "GL805", Severity.WARNING, m.path, 1,
                    f"LOCK_ORDER entry {decl!r} names no lock in "
                    "this module"))
                continue
            locks.append(lock)
        for i, a in enumerate(locks):
            for b in locks[i + 1:]:
                if (b, a) in declared:
                    out.append(Finding(
                        "GL805", Severity.WARNING, m.path, 1,
                        f"LOCK_ORDER conflict: this module declares "
                        f"{a[1]} before {b[1]} but "
                        f"{declared[(b, a)]} declares the reverse"))
                    continue
                declared.setdefault((a, b), m.path)
    return declared, out


def _walk_function(
    info: _FuncInfo,
    registry: Dict[Tuple[str, str], _FuncInfo],
    edges: Dict[Tuple[LockId, LockId], Tuple[str, int, str]],
) -> List[Finding]:
    """GL801 mutation discipline + acquisition-edge collection for one
    function, tracking the lexically-held lock set."""
    m, cls = info.module, info.cls
    guarded: Dict[str, str] = m.guarded or {}
    findings: List[Finding] = []
    is_init = info.qualname.endswith(".__init__")

    def required_lock(candidate: str) -> Optional[LockId]:
        decl = guarded.get(candidate)
        return None if decl is None else m.canon_lock_decl(decl)

    def check_mutation(target: ast.AST, lineno: int,
                       held: frozenset, how: str) -> None:
        candidate = _mutation_root(target, m, cls)
        if candidate is None or candidate not in guarded:
            return
        if is_init and cls and candidate.startswith(f"{cls}."):
            return  # construction is single-threaded
        lock = required_lock(candidate)
        if lock is None or lock in held:
            return
        findings.append(Finding(
            "GL801", Severity.ERROR, m.path, lineno,
            f"{how} of {candidate!r} outside its declared lock "
            f"{guarded[candidate]!r} (GUARDED_BY)",
            symbol=info.qualname))

    def visit(node: ast.AST, held: frozenset) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            # a nested def runs LATER, not under these locks
            for child in ast.iter_child_nodes(node):
                visit(child, frozenset())
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            acquired = []
            for item in node.items:
                lock = m.lock_of_expr(item.context_expr, cls)
                if lock is not None:
                    acquired.append((lock, item.context_expr))
            inner = held
            for lock, expr in acquired:
                for h in inner:
                    edges.setdefault(
                        (h, lock),
                        (m.path, node.lineno, info.qualname))
                inner = inner | {lock}
            for item in node.items:
                visit(item.context_expr, held)
                if item.optional_vars is not None:
                    visit(item.optional_vars, held)
            for child in node.body:
                visit(child, inner)
            return
        if isinstance(node, ast.Assign):
            for t in node.targets:
                check_mutation(t, node.lineno, held, "assignment")
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            check_mutation(node.target, node.lineno, held,
                           "assignment")
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                check_mutation(t, node.lineno, held, "deletion")
        elif isinstance(node, ast.Call):
            fn = node.func
            if (isinstance(fn, ast.Attribute)
                    and fn.attr in MUTATORS):
                check_mutation(fn.value, node.lineno, held,
                               f".{fn.attr}() call")
            if held:
                for key in _callee_keys(node, m, cls, registry):
                    callee = registry.get(key)
                    if callee is None:
                        continue
                    for lock in callee.may_acquire:
                        for h in held:
                            edges.setdefault(
                                (h, lock),
                                (m.path, node.lineno,
                                 info.qualname))
        for child in ast.iter_child_nodes(node):
            visit(child, held)

    for child in ast.iter_child_nodes(info.node):
        visit(child, frozenset())
    return findings


def _order_violations(
    edges: Dict[Tuple[LockId, LockId], Tuple[str, int, str]],
    declared: Dict[Tuple[LockId, LockId], str],
) -> List[Finding]:
    out: List[Finding] = []
    for (held, acquired), (path, lineno, symbol) in sorted(
            edges.items(), key=lambda kv: (kv[1][0], kv[1][1])):
        if (acquired, held) in declared:
            out.append(Finding(
                "GL802", Severity.ERROR, path, lineno,
                f"acquires {acquired[1]!r} while holding "
                f"{held[1]!r}, but LOCK_ORDER (declared in "
                f"{declared[(acquired, held)]}) requires "
                f"{acquired[1]!r} to be taken first",
                symbol=symbol))
    return out


def _cycles(
    edges: Dict[Tuple[LockId, LockId], Tuple[str, int, str]],
    reentrant: Optional[Set[LockId]] = None,
) -> List[Finding]:
    """DFS cycle detection over the observed acquisition graph; each
    cycle reported once, anchored at its lexically first edge. A
    length-1 cycle on a lock in `reentrant` (threading.RLock) is the
    sanctioned under-lock-helper idiom, not a self-deadlock."""
    reentrant = reentrant or set()
    graph: Dict[LockId, List[LockId]] = {}
    for held, acquired in edges:
        if held == acquired and held in reentrant:
            continue
        graph.setdefault(held, []).append(acquired)
    out: List[Finding] = []
    seen_cycles: Set[Tuple[LockId, ...]] = set()

    def dfs(node: LockId, stack: List[LockId],
            on_stack: Set[LockId]) -> None:
        for nxt in sorted(graph.get(node, [])):
            if nxt in on_stack:
                cycle = tuple(stack[stack.index(nxt):]) + (nxt,)
                key = tuple(sorted(set(cycle)))
                if key in seen_cycles:
                    continue
                seen_cycles.add(key)
                path, lineno, symbol = edges[(node, nxt)]
                chain = " -> ".join(lk[1] for lk in cycle)
                out.append(Finding(
                    "GL803", Severity.ERROR, path, lineno,
                    ("lock re-acquired while already held "
                     f"(self-deadlock for a non-reentrant Lock): "
                     f"{chain}" if len(set(cycle)) == 1 else
                     f"lock acquisition cycle: {chain} — a "
                     "deadlock under the right interleaving"),
                    symbol=symbol))
            elif nxt not in visited:
                visited.add(nxt)
                stack.append(nxt)
                on_stack.add(nxt)
                dfs(nxt, stack, on_stack)
                on_stack.discard(nxt)
                stack.pop()

    visited: Set[LockId] = set()
    for start in sorted(graph):
        if start not in visited:
            visited.add(start)
            dfs(start, [start], {start})
    return out


def _check_adoption(m: _Module) -> List[Finding]:
    """GL804 over one annotated module."""
    out: List[Finding] = []
    defs = _adopting_defs(m.src.tree)
    for node in m.src.walk():
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        target: Optional[ast.AST] = None
        what = ""
        if isinstance(fn, ast.Attribute) and fn.attr == "submit":
            if node.args:
                target, what = node.args[0], "pool.submit() callable"
        elif dotted_name(fn) in ("threading.Thread", "Thread"):
            for kw in node.keywords:
                if kw.arg == "target":
                    target, what = kw.value, "Thread target"
        if target is None:
            continue
        adopting = _callable_is_adopting(target, defs)
        if adopting is True:
            continue
        detail = ("does not capture stage context"
                  if adopting is False else
                  "cannot be verified to capture stage context")
        out.append(Finding(
            "GL804", Severity.WARNING, m.path, node.lineno,
            f"{what} {detail}: capture timing.stage_token() in the "
            "spawning thread and run the worker under "
            "timing.adopt(token), or its telemetry lands on an "
            "empty thread-local stage stack"))
    return out
