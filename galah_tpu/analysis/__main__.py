"""``python -m galah_tpu.analysis`` — run the lint suite standalone.

Pins the platform to CPU (the shape harness only abstract-evals, no
device needed) and enables x64 so the uint64 sketch ops trace with
their real dtypes, BEFORE jax is imported anywhere.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("JAX_ENABLE_X64", "1")

from galah_tpu.analysis import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
