"""galah-tpu lint: static analysis for the JAX/Pallas codebase.

Run as ``python -m galah_tpu.analysis`` or ``galah-tpu lint``. Exit
status is 1 iff any unsuppressed finding at WARNING or above remains
(INFO notes never fail the run).

Checker families
  GL0xx  suppression hygiene (expired ``expires=`` dates)
  GL1xx  Pallas kernel contracts (tiling quanta, VMEM budget, 64-bit)
  GL2xx  host-sync / tracer leaks inside jitted bodies
  GL3xx  recompile churn (env reads in jit, unhashable static args)
  GL4xx  GALAH_* config-flag registry consistency
  GL5xx  abstract-eval shape contracts vs committed snapshot
  GL6xx  hardware-test marker audit
  GL7xx  observability discipline (ad-hoc timing outside obs/)
  GL8xx  concurrency discipline (GUARDED_BY/LOCK_ORDER annotations)
         and durable-write discipline (GL806: durable artifacts are
         written only through io/atomic.py)
  GL9xx  numeric determinism (DETERMINISM_CONTRACT annotations)
  GL10xx pipeline discipline (streamed stages must stay streamed:
         materialized iterators, host sync in streaming stages,
         unbounded queues/pools, missing occupancy-gauge emission);
         the runtime complement is the GalahSan sanitizer
         (galah_tpu/analysis/sanitizer.py, GALAH_SAN=1)

Suppression: ``# galah-lint: ignore[GL103]`` on the flagged line or
the line above (optionally ``... expires=YYYY-MM-DD``; past the date
the comment stops suppressing and GL001 flags it), or an entry in the
committed baseline (``galah_tpu/analysis/baseline.json``, regenerated
with ``--update-baseline``).
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
import time
from typing import Dict, List, Optional, Sequence, Set

from galah_tpu.analysis import core
from galah_tpu.analysis.core import Finding, Severity, SourceFile

CHECK_NAMES = ("pallas", "runtime", "flags", "markers", "shapes",
               "obs", "concurrency", "fs", "determinism", "pipeline",
               "suppressions")
DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__),
                                "baseline.json")


def repo_root() -> str:
    """The directory holding the galah_tpu package (repo checkout)."""
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.dirname(pkg)


def load_sources(root: str) -> Dict[str, SourceFile]:
    sources: Dict[str, SourceFile] = {}
    for path in core.iter_python_files(root):
        try:
            src = SourceFile.load(path, rel_to=root)
        except SyntaxError:
            continue  # not lintable; the test suite will catch it
        sources[src.path] = src
    return sources


def run_checks(sources: Dict[str, SourceFile],
               checks: Sequence[str] = CHECK_NAMES) -> List[Finding]:
    """All requested checkers over the loaded tree (no suppression
    applied yet)."""
    findings: List[Finding] = []
    if "pallas" in checks:
        from galah_tpu.analysis.pallas_check import check_pallas_file
        for src in sources.values():
            findings.extend(check_pallas_file(src))
    if "runtime" in checks:
        from galah_tpu.analysis.runtime_checks import check_runtime_file
        for src in sources.values():
            findings.extend(check_runtime_file(src))
    if "flags" in checks:
        from galah_tpu.analysis.flags_check import check_flag_references
        findings.extend(check_flag_references(list(sources.values())))
    if "markers" in checks:
        from galah_tpu.analysis.markers_check import check_markers_file
        for src in sources.values():
            findings.extend(check_markers_file(src))
    if "shapes" in checks:
        from galah_tpu.analysis.shapes import check_shape_contracts
        findings.extend(check_shape_contracts())
    if "obs" in checks:
        from galah_tpu.analysis.obs_check import check_obs_file
        for src in sources.values():
            findings.extend(check_obs_file(src))
    if "concurrency" in checks:
        from galah_tpu.analysis.concurrency_check import \
            check_concurrency
        findings.extend(check_concurrency(sources))
    if "fs" in checks:
        from galah_tpu.analysis.fs_check import check_fs_file
        for src in sources.values():
            findings.extend(check_fs_file(src))
    if "determinism" in checks:
        from galah_tpu.analysis.determinism_check import \
            check_determinism_file
        for src in sources.values():
            findings.extend(check_determinism_file(src))
    if "pipeline" in checks:
        from galah_tpu.analysis.pipeline_check import \
            check_pipeline_file
        for src in sources.values():
            findings.extend(check_pipeline_file(src))
    if "suppressions" in checks:
        for src in sources.values():
            findings.extend(core.check_suppression_expiry(src))
    return findings


def run_lint(root: Optional[str] = None,
             checks: Sequence[str] = CHECK_NAMES,
             baseline_path: Optional[str] = None) -> List[Finding]:
    """Full lint pass with suppressions applied; the library entry
    point used by tests and the CLI."""
    root = root or repo_root()
    sources = load_sources(root)
    findings = run_checks(sources, checks)
    baseline = core.load_baseline(baseline_path or DEFAULT_BASELINE)
    core.apply_suppressions(findings, sources, baseline)
    return findings


def changed_files(root: str) -> Optional[Set[str]]:
    """Repo-relative paths git considers changed (staged + unstaged vs
    HEAD, plus untracked), or None when git can't answer — the caller
    falls back to a full scan rather than silently linting nothing.

    Deleted and renamed-away paths are skipped (``--diff-filter=d``
    plus an existence check for the rename source in the staged half):
    they have no content to lint, and feeding vanished files to the
    checkers used to crash the pre-commit gate mid-rename."""
    paths: Set[str] = set()
    for cmd in (["git", "diff", "--name-only", "--diff-filter=d",
                 "HEAD"],
                ["git", "ls-files", "--others", "--exclude-standard"]):
        try:
            proc = subprocess.run(cmd, cwd=root, capture_output=True,
                                  text=True, timeout=30)
        except (OSError, subprocess.SubprocessError):
            return None
        if proc.returncode != 0:
            return None
        paths.update(line.strip().replace("\\", "/")
                     for line in proc.stdout.splitlines()
                     if line.strip())
    # --diff-filter=d keeps a rename's old path when git reports it as
    # an unpaired modify; only paths that still exist are lintable.
    return {p for p in paths
            if os.path.isfile(os.path.join(root, p))}


def add_lint_arguments(parser: argparse.ArgumentParser) -> None:
    parser.add_argument("--json", action="store_true",
                        help="machine-readable JSON report on stdout")
    parser.add_argument("--root", default=None,
                        help="repo root to scan (default: the checkout "
                             "containing this package)")
    parser.add_argument("--check", action="append", default=None,
                        choices=CHECK_NAMES, dest="checks",
                        metavar="NAME",
                        help="run only the named checker family "
                             "(repeatable; default: all of "
                             + ", ".join(CHECK_NAMES) + ")")
    parser.add_argument("--baseline", default=None,
                        help="baseline file of accepted findings "
                             "(default: galah_tpu/analysis/"
                             "baseline.json)")
    parser.add_argument("--update-baseline", action="store_true",
                        help="rewrite the baseline to accept every "
                             "current finding, then exit 0")
    parser.add_argument("--update-snapshots", action="store_true",
                        help="recompute and commit the abstract-eval "
                             "shape-contract snapshot, then exit 0")
    parser.add_argument("--show-suppressed", action="store_true",
                        help="include suppressed findings in the "
                             "human report")
    parser.add_argument("--changed-only", action="store_true",
                        help="report only findings in files git "
                             "considers changed (staged, unstaged, or "
                             "untracked) — the pre-commit gate mode "
                             "(scripts/lint_gate.sh); checkers still "
                             "see the whole tree so cross-module "
                             "rules stay sound")
    parser.add_argument("--run-report", default=None,
                        help="write run_report.json with the lint "
                             "summary attached (per-family counts, "
                             "suppressed count) so `galah-tpu report "
                             "--diff` shows lint drift between runs. "
                             "Env equivalent: GALAH_OBS_REPORT")


def main(argv: Optional[Sequence[str]] = None,
         args: Optional[argparse.Namespace] = None) -> int:
    if args is None:
        parser = argparse.ArgumentParser(
            prog="galah-tpu lint",
            description=__doc__,
            formatter_class=argparse.RawDescriptionHelpFormatter)
        add_lint_arguments(parser)
        args = parser.parse_args(argv)

    t0 = time.monotonic()
    # wall-clock stamp for the run-report header, not a measurement
    started_at = time.time()  # galah-lint: ignore[GL701]
    if args.update_snapshots:
        from galah_tpu.analysis import shapes
        contracts, errors = shapes.compute_contracts()
        if errors:
            sys.stderr.write(core.render_human(errors) + "\n")
            return 1
        shapes.write_snapshot(contracts)
        n = sum(len(v) for v in contracts.values())
        print(f"wrote {n} shape contracts for {len(contracts)} ops "
              f"to {shapes.SNAPSHOT_PATH}")
        return 0

    root = args.root or repo_root()
    checks = tuple(args.checks) if args.checks else CHECK_NAMES
    changed: Optional[Set[str]] = None
    if getattr(args, "changed_only", False):
        changed = changed_files(root)
        if changed is None:
            sys.stderr.write("galah-tpu lint: --changed-only needs a "
                             "git checkout; scanning everything\n")
        elif not args.checks and not any(
                p.startswith("galah_tpu/ops/")
                or p == "galah_tpu/analysis/shapes.py"
                for p in changed):
            # the shapes family traces every op through jax — skip it
            # when no kernel/op code changed (seconds per commit)
            checks = tuple(c for c in checks if c != "shapes")
    sources = load_sources(root)
    findings = run_checks(sources, checks)
    baseline_path = args.baseline or DEFAULT_BASELINE

    if args.update_baseline:
        # inline suppressions still apply; the baseline absorbs the rest
        core.apply_suppressions(findings, sources, {})
        remaining = [f for f in findings if not f.suppressed]
        core.write_baseline(baseline_path, remaining)
        print(f"baselined {len(remaining)} finding(s) to "
              f"{baseline_path}")
        return 0

    baseline = core.load_baseline(baseline_path)
    core.apply_suppressions(findings, sources, baseline)
    if changed is not None:
        findings = [f for f in findings if f.path in changed]
    bad = core.failing(findings)

    report_path = (getattr(args, "run_report", None)
                   or os.environ.get("GALAH_OBS_REPORT"))
    if report_path:
        from galah_tpu import obs
        obs.finalize("lint", report_path=report_path,
                     started_at=started_at,
                     lint=core.lint_summary(findings))

    if args.json:
        print(core.render_json(findings))
    else:
        print(core.render_human(
            findings, show_suppressed=args.show_suppressed))
        dt = time.monotonic() - t0
        print(f"scanned {len(sources)} files with "
              f"{len(checks)} checker families in {dt:.1f}s")
    return 1 if bad else 0
