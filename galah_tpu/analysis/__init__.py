"""galah-tpu lint: static analysis for the JAX/Pallas codebase.

Run as ``python -m galah_tpu.analysis`` or ``galah-tpu lint``. Exit
status is 1 iff any unsuppressed finding at WARNING or above remains
(INFO notes never fail the run).

Checker families
  GL0xx  suppression hygiene (expired ``expires=`` dates)
  GL1xx  Pallas kernel contracts (tiling quanta, VMEM budget, 64-bit)
  GL2xx  host-sync / tracer leaks inside jitted bodies
  GL3xx  recompile churn (env reads in jit, unhashable static args)
  GL4xx  GALAH_* config-flag registry consistency
  GL5xx  abstract-eval shape contracts vs committed snapshot
  GL6xx  hardware-test marker audit
  GL7xx  observability discipline (ad-hoc timing outside obs/)
  GL8xx  concurrency discipline (GUARDED_BY/LOCK_ORDER annotations)
         and durable-write discipline (GL806: durable artifacts are
         written only through io/atomic.py)
  GL9xx  numeric determinism (DETERMINISM_CONTRACT annotations)
  GL10xx pipeline discipline (streamed stages must stay streamed:
         materialized iterators, host sync in streaming stages,
         unbounded queues/pools, missing occupancy-gauge emission);
         the runtime complement is the GalahSan sanitizer
         (galah_tpu/analysis/sanitizer.py, GALAH_SAN=1)
  GL11xx interprocedural effect auditors over GalahIR (analysis/ir.py):
         the whole-program call graph with per-function inferred
         effect sets propagated to fixpoint, so the contracts above
         hold through helper indirection too — transitive host sync
         from a device-round body (GL1101), durable writes around
         io/atomic.py (GL1102), transitive stream materialization
         (GL1103), lock leaks on raising paths (GL1104), effectful
         pool callbacks without stage-token adoption (GL1105).
         Per-file IR is content-hash cached (--ir-cache-dir /
         GALAH_TPU_IR_CACHE), as is the GL5xx shapes verdict, so a
         warm lint run costs a fraction of a cold one.

Suppression: ``# galah-lint: ignore[GL103]`` on the flagged line or
the line above (optionally ``... expires=YYYY-MM-DD``; past the date
the comment stops suppressing and GL001 flags it), or an entry in the
committed baseline (``galah_tpu/analysis/baseline.json``, regenerated
with ``--update-baseline``).
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
import time
from typing import Dict, List, Optional, Sequence, Set

from galah_tpu.analysis import core
from galah_tpu.analysis.core import Finding, Severity, SourceFile

CHECK_NAMES = ("pallas", "runtime", "flags", "markers", "shapes",
               "obs", "concurrency", "fs", "determinism", "pipeline",
               "effects", "suppressions")
DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__),
                                "baseline.json")


def repo_root() -> str:
    """The directory holding the galah_tpu package (repo checkout)."""
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.dirname(pkg)


def load_sources(root: str) -> Dict[str, SourceFile]:
    sources: Dict[str, SourceFile] = {}
    for path in core.iter_python_files(root):
        try:
            src = SourceFile.load(path, rel_to=root)
        except SyntaxError:
            continue  # not lintable; the test suite will catch it
        sources[src.path] = src
    return sources


def run_checks(sources: Dict[str, SourceFile],
               checks: Sequence[str] = CHECK_NAMES,
               ir_cache_dir: Optional[str] = None,
               timings: Optional[Dict[str, float]] = None
               ) -> List[Finding]:
    """All requested checkers over the loaded tree (no suppression
    applied yet). The parse is shared: every family reads the same
    ``SourceFile`` objects (one read + one ``ast.parse`` per file per
    invocation, memoized node lists via ``SourceFile.walk``).

    ``ir_cache_dir`` enables the content-hash IR/verdict cache for the
    effects and shapes families; ``timings``, when passed, is filled
    with per-family wall seconds."""
    findings: List[Finding] = []

    def timed(name: str, produce) -> None:
        t0 = time.monotonic()
        findings.extend(produce())
        if timings is not None:
            timings[name] = time.monotonic() - t0

    def per_file(check_file):
        return lambda: [f for src in sources.values()
                        for f in check_file(src)]

    if "pallas" in checks:
        from galah_tpu.analysis.pallas_check import check_pallas_file
        timed("pallas", per_file(check_pallas_file))
    if "runtime" in checks:
        from galah_tpu.analysis.runtime_checks import check_runtime_file
        timed("runtime", per_file(check_runtime_file))
    if "flags" in checks:
        from galah_tpu.analysis.flags_check import check_flag_references
        timed("flags",
              lambda: check_flag_references(list(sources.values())))
    if "markers" in checks:
        from galah_tpu.analysis.markers_check import check_markers_file
        timed("markers", per_file(check_markers_file))
    if "shapes" in checks:
        from galah_tpu.analysis.shapes import check_shape_contracts
        timed("shapes",
              lambda: check_shape_contracts(cache_dir=ir_cache_dir))
    if "obs" in checks:
        from galah_tpu.analysis.obs_check import check_obs_file
        timed("obs", per_file(check_obs_file))
    if "concurrency" in checks:
        from galah_tpu.analysis.concurrency_check import \
            check_concurrency
        timed("concurrency", lambda: check_concurrency(sources))
    if "fs" in checks:
        from galah_tpu.analysis.fs_check import check_fs_file
        timed("fs", per_file(check_fs_file))
    if "determinism" in checks:
        from galah_tpu.analysis.determinism_check import \
            check_determinism_file
        timed("determinism", per_file(check_determinism_file))
    if "pipeline" in checks:
        from galah_tpu.analysis.pipeline_check import \
            check_pipeline_file
        timed("pipeline", per_file(check_pipeline_file))
    if "effects" in checks:
        from galah_tpu.analysis.effects_check import check_effects
        from galah_tpu.analysis.ir import IRCache
        timed("effects",
              lambda: check_effects(sources,
                                    cache=IRCache(ir_cache_dir)))
    if "suppressions" in checks:
        timed("suppressions", per_file(core.check_suppression_expiry))
    return findings


def run_lint(root: Optional[str] = None,
             checks: Sequence[str] = CHECK_NAMES,
             baseline_path: Optional[str] = None,
             ir_cache_dir: Optional[str] = None) -> List[Finding]:
    """Full lint pass with suppressions applied; the library entry
    point used by tests and the CLI."""
    root = root or repo_root()
    sources = load_sources(root)
    findings = run_checks(sources, checks, ir_cache_dir=ir_cache_dir)
    baseline = core.load_baseline(baseline_path or DEFAULT_BASELINE)
    core.apply_suppressions(findings, sources, baseline)
    return findings


def changed_files(root: str) -> Optional[Set[str]]:
    """Repo-relative paths git considers changed (staged + unstaged vs
    HEAD, plus untracked), or None when git can't answer — the caller
    falls back to a full scan rather than silently linting nothing.

    Deleted and renamed-away paths are skipped (``--diff-filter=d``
    plus an existence check for the rename source in the staged half):
    they have no content to lint, and feeding vanished files to the
    checkers used to crash the pre-commit gate mid-rename."""
    paths: Set[str] = set()
    for cmd in (["git", "diff", "--name-only", "--diff-filter=d",
                 "HEAD"],
                ["git", "ls-files", "--others", "--exclude-standard"]):
        try:
            proc = subprocess.run(cmd, cwd=root, capture_output=True,
                                  text=True, timeout=30)
        except (OSError, subprocess.SubprocessError):
            return None
        if proc.returncode != 0:
            return None
        paths.update(line.strip().replace("\\", "/")
                     for line in proc.stdout.splitlines()
                     if line.strip())
    # --diff-filter=d keeps a rename's old path when git reports it as
    # an unpaired modify; only paths that still exist are lintable.
    return {p for p in paths
            if os.path.isfile(os.path.join(root, p))}


def add_lint_arguments(parser: argparse.ArgumentParser) -> None:
    parser.add_argument("--json", action="store_true",
                        help="machine-readable JSON report on stdout")
    parser.add_argument("--root", default=None,
                        help="repo root to scan (default: the checkout "
                             "containing this package)")
    parser.add_argument("--check", action="append", default=None,
                        choices=CHECK_NAMES, dest="checks",
                        metavar="NAME",
                        help="run only the named checker family "
                             "(repeatable; default: all of "
                             + ", ".join(CHECK_NAMES) + ")")
    parser.add_argument("--baseline", default=None,
                        help="baseline file of accepted findings "
                             "(default: galah_tpu/analysis/"
                             "baseline.json)")
    parser.add_argument("--update-baseline", action="store_true",
                        help="rewrite the baseline to accept every "
                             "current finding, then exit 0")
    parser.add_argument("--update-snapshots", action="store_true",
                        help="recompute and commit the abstract-eval "
                             "shape-contract snapshot, then exit 0")
    parser.add_argument("--show-suppressed", action="store_true",
                        help="include suppressed findings in the "
                             "human report")
    parser.add_argument("--changed-only", action="store_true",
                        help="report only findings in files git "
                             "considers changed (staged, unstaged, or "
                             "untracked) — the pre-commit gate mode "
                             "(scripts/lint_gate.sh); checkers still "
                             "see the whole tree so cross-module "
                             "rules stay sound")
    parser.add_argument("--run-report", default=None,
                        help="write run_report.json with the lint "
                             "summary attached (per-family counts, "
                             "suppressed count, per-family timings) "
                             "so `galah-tpu report --diff` shows lint "
                             "drift between runs. Env equivalent: "
                             "GALAH_OBS_REPORT")
    parser.add_argument("--sarif", default=None, metavar="PATH",
                        help="additionally write the findings as a "
                             "SARIF 2.1.0 log to PATH so CI systems "
                             "can annotate them inline (suppressed "
                             "findings are carried with SARIF "
                             "suppressions rather than dropped)")
    parser.add_argument("--ir-cache-dir", default=None, metavar="DIR",
                        help="content-hash cache directory for the "
                             "GalahIR per-file entries (effects "
                             "family) and the GL5xx shapes verdict; a "
                             "warm cache cuts lint wall time by the "
                             "whole jax-tracing cost. Env equivalent: "
                             "GALAH_TPU_IR_CACHE. Unset disables "
                             "caching")


def main(argv: Optional[Sequence[str]] = None,
         args: Optional[argparse.Namespace] = None) -> int:
    if args is None:
        parser = argparse.ArgumentParser(
            prog="galah-tpu lint",
            description=__doc__,
            formatter_class=argparse.RawDescriptionHelpFormatter)
        add_lint_arguments(parser)
        args = parser.parse_args(argv)

    t0 = time.monotonic()
    # wall-clock stamp for the run-report header, not a measurement
    started_at = time.time()  # galah-lint: ignore[GL701]
    if args.update_snapshots:
        from galah_tpu.analysis import shapes
        contracts, errors = shapes.compute_contracts()
        if errors:
            sys.stderr.write(core.render_human(errors) + "\n")
            return 1
        shapes.write_snapshot(contracts)
        n = sum(len(v) for v in contracts.values())
        print(f"wrote {n} shape contracts for {len(contracts)} ops "
              f"to {shapes.SNAPSHOT_PATH}")
        return 0

    root = args.root or repo_root()
    checks = tuple(args.checks) if args.checks else CHECK_NAMES
    ir_cache_dir = getattr(args, "ir_cache_dir", None)
    if ir_cache_dir is None:
        from galah_tpu.analysis.ir import default_cache_dir
        ir_cache_dir = default_cache_dir()
    changed: Optional[Set[str]] = None
    if getattr(args, "changed_only", False):
        changed = changed_files(root)
        if changed is None:
            sys.stderr.write("galah-tpu lint: --changed-only needs a "
                             "git checkout; scanning everything\n")
        elif not args.checks and not ir_cache_dir and not any(
                p.startswith("galah_tpu/ops/")
                or p == "galah_tpu/analysis/shapes.py"
                for p in changed):
            # the shapes family traces every op through jax — skip it
            # when no kernel/op code changed (seconds per commit); a
            # configured IR cache makes the warm verdict cheap enough
            # to always run instead
            checks = tuple(c for c in checks if c != "shapes")
    sources = load_sources(root)
    timings: Dict[str, float] = {}
    findings = run_checks(sources, checks, ir_cache_dir=ir_cache_dir,
                          timings=timings)
    baseline_path = args.baseline or DEFAULT_BASELINE

    if args.update_baseline:
        # inline suppressions still apply; the baseline absorbs the rest
        core.apply_suppressions(findings, sources, {})
        remaining = [f for f in findings if not f.suppressed]
        core.write_baseline(baseline_path, remaining)
        print(f"baselined {len(remaining)} finding(s) to "
              f"{baseline_path}")
        return 0

    baseline = core.load_baseline(baseline_path)
    core.apply_suppressions(findings, sources, baseline)
    if changed is not None:
        findings = [f for f in findings if f.path in changed]
    bad = core.failing(findings)

    report_path = (getattr(args, "run_report", None)
                   or os.environ.get("GALAH_OBS_REPORT"))
    if report_path:
        from galah_tpu import obs
        obs.finalize("lint", report_path=report_path,
                     started_at=started_at,
                     lint=core.lint_summary(findings,
                                            timings=timings))

    sarif_path = getattr(args, "sarif", None)
    if sarif_path:
        import json as _json

        from galah_tpu import __version__
        with open(sarif_path, "w", encoding="utf-8") as fh:
            _json.dump(core.render_sarif(findings,
                                         tool_version=__version__),
                       fh, indent=1, sort_keys=True)
            fh.write("\n")

    if args.json:
        print(core.render_json(findings))
    else:
        print(core.render_human(
            findings, show_suppressed=args.show_suppressed))
        dt = time.monotonic() - t0
        slowest = sorted(timings.items(), key=lambda kv: -kv[1])[:3]
        per_family = " ".join(f"{k}={v:.1f}s" for k, v in slowest)
        print(f"scanned {len(sources)} files with "
              f"{len(checks)} checker families in {dt:.1f}s"
              + (f" (slowest: {per_family})" if per_family else ""))
    return 1 if bad else 0
