"""galah-tpu lint: static analysis for the JAX/Pallas codebase.

Run as ``python -m galah_tpu.analysis`` or ``galah-tpu lint``. Exit
status is 1 iff any unsuppressed finding at WARNING or above remains
(INFO notes never fail the run).

Checker families
  GL1xx  Pallas kernel contracts (tiling quanta, VMEM budget, 64-bit)
  GL2xx  host-sync / tracer leaks inside jitted bodies
  GL3xx  recompile churn (env reads in jit, unhashable static args)
  GL4xx  GALAH_* config-flag registry consistency
  GL5xx  abstract-eval shape contracts vs committed snapshot
  GL6xx  hardware-test marker audit
  GL7xx  observability discipline (ad-hoc timing outside obs/)

Suppression: ``# galah-lint: ignore[GL103]`` on the flagged line or
the line above, or an entry in the committed baseline
(``galah_tpu/analysis/baseline.json``, regenerated with
``--update-baseline``).
"""

from __future__ import annotations

import argparse
import os
import sys
import time
from typing import Dict, List, Optional, Sequence

from galah_tpu.analysis import core
from galah_tpu.analysis.core import Finding, Severity, SourceFile

CHECK_NAMES = ("pallas", "runtime", "flags", "markers", "shapes",
               "obs")
DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__),
                                "baseline.json")


def repo_root() -> str:
    """The directory holding the galah_tpu package (repo checkout)."""
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.dirname(pkg)


def load_sources(root: str) -> Dict[str, SourceFile]:
    sources: Dict[str, SourceFile] = {}
    for path in core.iter_python_files(root):
        try:
            src = SourceFile.load(path, rel_to=root)
        except SyntaxError:
            continue  # not lintable; the test suite will catch it
        sources[src.path] = src
    return sources


def run_checks(sources: Dict[str, SourceFile],
               checks: Sequence[str] = CHECK_NAMES) -> List[Finding]:
    """All requested checkers over the loaded tree (no suppression
    applied yet)."""
    findings: List[Finding] = []
    if "pallas" in checks:
        from galah_tpu.analysis.pallas_check import check_pallas_file
        for src in sources.values():
            findings.extend(check_pallas_file(src))
    if "runtime" in checks:
        from galah_tpu.analysis.runtime_checks import check_runtime_file
        for src in sources.values():
            findings.extend(check_runtime_file(src))
    if "flags" in checks:
        from galah_tpu.analysis.flags_check import check_flag_references
        findings.extend(check_flag_references(list(sources.values())))
    if "markers" in checks:
        from galah_tpu.analysis.markers_check import check_markers_file
        for src in sources.values():
            findings.extend(check_markers_file(src))
    if "shapes" in checks:
        from galah_tpu.analysis.shapes import check_shape_contracts
        findings.extend(check_shape_contracts())
    if "obs" in checks:
        from galah_tpu.analysis.obs_check import check_obs_file
        for src in sources.values():
            findings.extend(check_obs_file(src))
    return findings


def run_lint(root: Optional[str] = None,
             checks: Sequence[str] = CHECK_NAMES,
             baseline_path: Optional[str] = None) -> List[Finding]:
    """Full lint pass with suppressions applied; the library entry
    point used by tests and the CLI."""
    root = root or repo_root()
    sources = load_sources(root)
    findings = run_checks(sources, checks)
    baseline = core.load_baseline(baseline_path or DEFAULT_BASELINE)
    core.apply_suppressions(findings, sources, baseline)
    return findings


def add_lint_arguments(parser: argparse.ArgumentParser) -> None:
    parser.add_argument("--json", action="store_true",
                        help="machine-readable JSON report on stdout")
    parser.add_argument("--root", default=None,
                        help="repo root to scan (default: the checkout "
                             "containing this package)")
    parser.add_argument("--check", action="append", default=None,
                        choices=CHECK_NAMES, dest="checks",
                        metavar="NAME",
                        help="run only the named checker family "
                             "(repeatable; default: all of "
                             + ", ".join(CHECK_NAMES) + ")")
    parser.add_argument("--baseline", default=None,
                        help="baseline file of accepted findings "
                             "(default: galah_tpu/analysis/"
                             "baseline.json)")
    parser.add_argument("--update-baseline", action="store_true",
                        help="rewrite the baseline to accept every "
                             "current finding, then exit 0")
    parser.add_argument("--update-snapshots", action="store_true",
                        help="recompute and commit the abstract-eval "
                             "shape-contract snapshot, then exit 0")
    parser.add_argument("--show-suppressed", action="store_true",
                        help="include suppressed findings in the "
                             "human report")


def main(argv: Optional[Sequence[str]] = None,
         args: Optional[argparse.Namespace] = None) -> int:
    if args is None:
        parser = argparse.ArgumentParser(
            prog="galah-tpu lint",
            description=__doc__,
            formatter_class=argparse.RawDescriptionHelpFormatter)
        add_lint_arguments(parser)
        args = parser.parse_args(argv)

    t0 = time.monotonic()
    if args.update_snapshots:
        from galah_tpu.analysis import shapes
        contracts, errors = shapes.compute_contracts()
        if errors:
            sys.stderr.write(core.render_human(errors) + "\n")
            return 1
        shapes.write_snapshot(contracts)
        n = sum(len(v) for v in contracts.values())
        print(f"wrote {n} shape contracts for {len(contracts)} ops "
              f"to {shapes.SNAPSHOT_PATH}")
        return 0

    root = args.root or repo_root()
    checks = tuple(args.checks) if args.checks else CHECK_NAMES
    sources = load_sources(root)
    findings = run_checks(sources, checks)
    baseline_path = args.baseline or DEFAULT_BASELINE

    if args.update_baseline:
        # inline suppressions still apply; the baseline absorbs the rest
        core.apply_suppressions(findings, sources, {})
        remaining = [f for f in findings if not f.suppressed]
        core.write_baseline(baseline_path, remaining)
        print(f"baselined {len(remaining)} finding(s) to "
              f"{baseline_path}")
        return 0

    baseline = core.load_baseline(baseline_path)
    core.apply_suppressions(findings, sources, baseline)
    bad = core.failing(findings)

    if args.json:
        print(core.render_json(findings))
    else:
        print(core.render_human(
            findings, show_suppressed=args.show_suppressed))
        dt = time.monotonic() - t0
        print(f"scanned {len(sources)} files with "
              f"{len(checks)} checker families in {dt:.1f}s")
    return 1 if bad else 0
