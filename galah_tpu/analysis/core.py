"""Lint framework: findings, severities, suppressions, baseline, reports.

Every checker in this package produces :class:`Finding` objects; this
module owns everything around them — the severity lattice, inline
suppression comments (``# galah-lint: ignore[GL103]`` on the flagged
line or the line above), the committed baseline file (fingerprints of
accepted findings, stable across unrelated line moves), and the human /
JSON renderings.

Checkers are purely static where possible (AST over source text); the
abstract-eval harness (shapes.py) is the one checker that imports the
ops, but still never compiles or executes a kernel.
"""

from __future__ import annotations

import ast
import dataclasses
import datetime
import enum
import hashlib
import json
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Tuple


class Severity(enum.IntEnum):
    INFO = 0
    WARNING = 1
    ERROR = 2

    def __str__(self) -> str:  # noqa: D105 - render as lowercase word
        return self.name.lower()


@dataclasses.dataclass
class Finding:
    """One lint finding, anchored to a file/line."""

    code: str              # e.g. "GL103"
    severity: Severity
    path: str              # repo-relative
    line: int              # 1-based; 0 for file-level findings
    message: str
    symbol: str = ""       # enclosing function/class, "" at module level
    suppressed: bool = False
    suppression: str = ""  # "inline" | "baseline" | ""

    def fingerprint(self) -> str:
        """Line-number-independent identity for the baseline file:
        unrelated edits above a finding must not invalidate its
        baseline entry, so the line is excluded on purpose."""
        ident = f"{self.code}|{self.path}|{self.symbol}|{self.message}"
        return hashlib.sha256(ident.encode()).hexdigest()[:16]

    def to_dict(self) -> dict:
        return {
            "code": self.code,
            "severity": str(self.severity),
            "path": self.path,
            "line": self.line,
            "symbol": self.symbol,
            "message": self.message,
            "suppressed": self.suppressed,
            "suppression": self.suppression,
            "fingerprint": self.fingerprint(),
        }


# ---------------------------------------------------------------------------
# Source files
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class SourceFile:
    """A parsed python source plus the per-line suppression index."""

    path: str          # as given (repo-relative when scanning the repo)
    text: str
    tree: ast.Module
    # line -> (codes, expiry date or None, raw expires= text). An
    # expired (or unparseable-date) entry no longer suppresses;
    # check_suppression_expiry turns it into a GL001 finding.
    _ignores: Dict[int, Tuple[frozenset, Optional[datetime.date],
                              str]] = \
        dataclasses.field(default_factory=dict)
    # lazily-computed preorder node list shared by every checker
    # family (walk()) and the lazily-computed content digest shared by
    # the IR cache (content_hash()); both belong to THIS parse so a
    # lint invocation traverses/hashes each file once, not once per
    # family.
    _walk_cache: Optional[list] = dataclasses.field(
        default=None, repr=False, compare=False)
    _hash_cache: Optional[str] = dataclasses.field(
        default=None, repr=False, compare=False)

    @classmethod
    def load(cls, path: str, rel_to: Optional[str] = None) -> "SourceFile":
        with open(path, "r", encoding="utf-8", errors="replace") as fh:
            text = fh.read()
        rel = os.path.relpath(path, rel_to) if rel_to else path
        tree = ast.parse(text, filename=rel)
        src = cls(path=rel, text=text, tree=tree)
        src._index_suppressions()
        return src

    def walk(self) -> list:
        """Preorder list of every AST node, computed once per parse.

        ``ast.walk`` re-traverses (and re-allocates the BFS queue for)
        the whole tree on every call; with eleven-plus checker families
        each walking every file, the shared list is the cheapest way to
        pay the traversal once per lint invocation. Callers must not
        mutate the returned list."""
        if self._walk_cache is None:
            self._walk_cache = list(ast.walk(self.tree))
        return self._walk_cache

    def content_hash(self) -> str:
        """sha256 of the source text — the IR-cache key component, so a
        byte-identical file maps to the same cached per-file IR no
        matter where the checkout lives."""
        if self._hash_cache is None:
            self._hash_cache = hashlib.sha256(
                self.text.encode("utf-8", "replace")).hexdigest()
        return self._hash_cache

    _IGNORE_RE = re.compile(
        r"#\s*galah-lint:\s*ignore\[([A-Z0-9,\s*]+)\]"
        r"(?:\s+expires=(\S+))?")

    def _index_suppressions(self) -> None:
        for lineno, line in enumerate(self.text.splitlines(), start=1):
            m = self._IGNORE_RE.search(line)
            if not m:
                continue
            codes = frozenset(
                c.strip() for c in m.group(1).split(",") if c.strip())
            raw = m.group(2) or ""
            expiry: Optional[datetime.date] = None
            if raw:
                try:
                    expiry = datetime.date.fromisoformat(raw)
                except ValueError:
                    # unparseable dates never suppress;
                    # check_suppression_expiry reports them as GL001
                    expiry = datetime.date.min
            self._ignores[lineno] = (codes, expiry, raw)

    def is_ignored(self, code: str, line: int,
                   today: Optional[datetime.date] = None) -> bool:
        """Inline suppression: a matching ignore comment on the flagged
        line or the line directly above it (``*`` matches any code).
        A comment whose ``expires=YYYY-MM-DD`` date has passed no
        longer suppresses anything."""
        today = today or datetime.date.today()
        for ln in (line, line - 1):
            entry = self._ignores.get(ln)
            if entry is None:
                continue
            codes, expiry, _ = entry
            if expiry is not None and expiry < today:
                continue
            if code in codes or "*" in codes:
                return True
        return False


def check_suppression_expiry(src: SourceFile,
                             today: Optional[datetime.date] = None) -> \
        List[Finding]:
    """GL001: suppression comments whose ``expires=`` date has passed.

    An expired comment has already stopped suppressing (is_ignored
    skips it), so the original finding resurfaces on its own; this
    finding additionally points at the stale comment itself so it gets
    cleaned up or re-justified rather than silently ignored forever.
    """
    today = today or datetime.date.today()
    out: List[Finding] = []
    for lineno in sorted(src._ignores):
        codes, expiry, raw = src._ignores[lineno]
        if expiry is None:
            continue
        if expiry == datetime.date.min and raw != expiry.isoformat():
            msg = (f"suppression for {', '.join(sorted(codes))} has "
                   f"unparseable expires={raw!r} (want YYYY-MM-DD); "
                   "it no longer suppresses anything")
        elif expiry < today:
            msg = (f"suppression for {', '.join(sorted(codes))} "
                   f"expired on {expiry.isoformat()}; remove the "
                   "comment or re-justify with a new date")
        else:
            continue
        out.append(Finding(
            code="GL001", severity=Severity.WARNING, path=src.path,
            line=lineno, message=msg))
    return out


def iter_python_files(root: str,
                      subdirs: Sequence[str] = ("galah_tpu", "scripts",
                                                "tests"),
                      extra_files: Sequence[str] = ("bench.py",)) -> \
        List[str]:
    """Absolute paths of the repo's first-party python sources."""
    out: List[str] = []
    for sub in subdirs:
        base = os.path.join(root, sub)
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = [d for d in dirnames
                           if d not in ("__pycache__", "data")]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    out.append(os.path.join(dirpath, fn))
    for fn in extra_files:
        p = os.path.join(root, fn)
        if os.path.isfile(p):
            out.append(p)
    return out


# ---------------------------------------------------------------------------
# AST helpers shared by checkers
# ---------------------------------------------------------------------------


def dotted_name(node: ast.AST) -> str:
    """'jax.experimental.pallas.pallas_call' for a Name/Attribute
    chain, '' for anything else."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def enclosing_functions(tree: ast.Module) -> Dict[ast.AST, ast.AST]:
    """Map every node to its innermost enclosing FunctionDef (or None)."""
    owner: Dict[ast.AST, ast.AST] = {}

    def walk(node: ast.AST, fn: Optional[ast.AST]) -> None:
        for child in ast.iter_child_nodes(node):
            nfn = child if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef)) else fn
            owner[child] = fn
            walk(child, nfn)

    owner[tree] = None
    walk(tree, None)
    return owner


SAFE_BINOPS = {
    ast.Add: lambda a, b: a + b,
    ast.Sub: lambda a, b: a - b,
    ast.Mult: lambda a, b: a * b,
    ast.FloorDiv: lambda a, b: a // b,
    ast.Div: lambda a, b: a / b,
    ast.Mod: lambda a, b: a % b,
    ast.Pow: lambda a, b: a ** b,
}


class SymbolicEvalError(Exception):
    """A shape expression the restricted evaluator cannot resolve."""


def safe_eval(node: ast.AST, env: Dict[str, object]):
    """Evaluate a shape-arithmetic expression over `env` bindings.

    Supports names, int/float/str constants, +-*//%** and unary ops,
    tuples/lists, and negative ceil-division idioms (-(-a // b)).
    Anything else raises SymbolicEvalError — callers downgrade that to
    a 'could not evaluate statically' finding rather than guessing.
    """
    if isinstance(node, ast.Constant):
        return node.value
    if isinstance(node, ast.Name):
        if node.id in env:
            return env[node.id]
        raise SymbolicEvalError(f"unbound name {node.id!r}")
    if isinstance(node, ast.BinOp):
        op = SAFE_BINOPS.get(type(node.op))
        if op is None:
            raise SymbolicEvalError(
                f"unsupported operator {type(node.op).__name__}")
        return op(safe_eval(node.left, env), safe_eval(node.right, env))
    if isinstance(node, ast.UnaryOp):
        v = safe_eval(node.operand, env)
        if isinstance(node.op, ast.USub):
            return -v
        if isinstance(node.op, ast.UAdd):
            return +v
        raise SymbolicEvalError(
            f"unsupported unary {type(node.op).__name__}")
    if isinstance(node, (ast.Tuple, ast.List)):
        return tuple(safe_eval(e, env) for e in node.elts)
    if isinstance(node, ast.Call):
        fname = dotted_name(node.func)
        if fname in ("max", "min", "int", "abs") and not node.keywords:
            fn = {"max": max, "min": min, "int": int, "abs": abs}[fname]
            return fn(*(safe_eval(a, env) for a in node.args))
        if fname == "math.gcd" and not node.keywords:
            import math

            return math.gcd(*(safe_eval(a, env) for a in node.args))
        raise SymbolicEvalError(f"unsupported call {fname or '<expr>'}()")
    raise SymbolicEvalError(
        f"unsupported expression {type(node).__name__}")


# ---------------------------------------------------------------------------
# Baseline
# ---------------------------------------------------------------------------


def load_baseline(path: str) -> Dict[str, dict]:
    """fingerprint -> entry from a committed baseline file (empty when
    the file is absent)."""
    if not os.path.isfile(path):
        return {}
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    return {e["fingerprint"]: e for e in data.get("findings", [])}


def write_baseline(path: str, findings: Iterable[Finding]) -> None:
    entries = [
        {
            "fingerprint": f.fingerprint(),
            "code": f.code,
            "path": f.path,
            "symbol": f.symbol,
            "message": f.message,
        }
        for f in findings if not f.suppressed
    ]
    entries.sort(key=lambda e: (e["path"], e["code"], e["message"]))
    with open(path, "w", encoding="utf-8") as fh:
        json.dump({"version": 1, "findings": entries}, fh, indent=1,
                  sort_keys=True)
        fh.write("\n")


def apply_suppressions(findings: List[Finding],
                       sources: Dict[str, SourceFile],
                       baseline: Dict[str, dict]) -> None:
    """Mark findings covered by inline comments or the baseline."""
    for f in findings:
        src = sources.get(f.path)
        if src is not None and f.line and src.is_ignored(f.code, f.line):
            f.suppressed, f.suppression = True, "inline"
        elif f.fingerprint() in baseline:
            f.suppressed, f.suppression = True, "baseline"


# ---------------------------------------------------------------------------
# Reports
# ---------------------------------------------------------------------------


def render_human(findings: Sequence[Finding],
                 show_suppressed: bool = False) -> str:
    lines: List[str] = []
    shown = [f for f in findings if show_suppressed or not f.suppressed]
    for f in sorted(shown, key=lambda f: (f.path, f.line, f.code)):
        sup = f" (suppressed: {f.suppression})" if f.suppressed else ""
        sym = f" [{f.symbol}]" if f.symbol else ""
        lines.append(f"{f.path}:{f.line}: {f.severity} {f.code} "
                     f"{f.message}{sym}{sup}")
    active = [f for f in findings if not f.suppressed]
    n_err = sum(1 for f in active if f.severity == Severity.ERROR)
    n_warn = sum(1 for f in active if f.severity == Severity.WARNING)
    n_info = sum(1 for f in active if f.severity == Severity.INFO)
    n_sup = sum(1 for f in findings if f.suppressed)
    lines.append(f"{n_err} error(s), {n_warn} warning(s), "
                 f"{n_info} note(s), {n_sup} suppressed")
    return "\n".join(lines)


def family_of(code: str) -> str:
    """'GL103' -> 'GL1xx'; four-digit codes group by their leading two
    digits ('GL1001' -> 'GL10xx'), so the GL10xx pipeline family does
    not collide with the GL1xx Pallas family."""
    if (len(code) == 6 and code[:2] == "GL"
            and code[2:].isdigit()):
        return f"GL{code[2:4]}xx"
    if len(code) >= 3 and code[:2] == "GL":
        return f"GL{code[2]}xx"
    return code


def lint_summary(findings: Sequence[Finding],
                 timings: Optional[Dict[str, float]] = None) -> dict:
    """Counts block shared by --json output and run_report.json.
    ``timings`` (checker family -> wall seconds) rides along when the
    caller measured it, so run-report diffs expose lint-stage drift."""
    active = [f for f in findings if not f.suppressed]
    by_family: Dict[str, int] = {}
    for f in active:
        fam = family_of(f.code)
        by_family[fam] = by_family.get(fam, 0) + 1
    out = {
        "errors": sum(1 for f in active
                      if f.severity == Severity.ERROR),
        "warnings": sum(1 for f in active
                        if f.severity == Severity.WARNING),
        "notes": sum(1 for f in active
                     if f.severity == Severity.INFO),
        "suppressed": sum(1 for f in findings if f.suppressed),
        "by_family": dict(sorted(by_family.items())),
    }
    if timings is not None:
        out["timings_s"] = {k: round(v, 3)
                            for k, v in sorted(timings.items())}
    return out


def render_json(findings: Sequence[Finding]) -> str:
    return json.dumps({
        "version": 1,
        "findings": [f.to_dict() for f in findings],
        "summary": lint_summary(findings),
    }, indent=1, sort_keys=True)


#: SARIF 2.1.0 constants for --sarif output (consumed by CI annotators).
SARIF_VERSION = "2.1.0"
SARIF_SCHEMA_URI = ("https://raw.githubusercontent.com/oasis-tcs/"
                    "sarif-spec/master/Schemata/sarif-schema-2.1.0.json")

_SARIF_LEVEL = {Severity.ERROR: "error", Severity.WARNING: "warning",
                Severity.INFO: "note"}


def render_sarif(findings: Sequence[Finding],
                 tool_version: str = "0") -> dict:
    """The findings as a SARIF 2.1.0 log dict (one run, one result per
    finding). Suppressed findings are carried with a populated SARIF
    ``suppressions`` array rather than dropped, so CI systems show them
    greyed out instead of losing the paper trail; ``line`` 0
    (file-level findings) maps to startLine 1, the smallest region
    SARIF allows."""
    rules: Dict[str, dict] = {}
    results: List[dict] = []
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.code)):
        rules.setdefault(f.code, {
            "id": f.code,
            "name": f.code,
            "shortDescription": {"text": f"galah-tpu lint {f.code} "
                                         f"({family_of(f.code)} family)"},
        })
        result = {
            "ruleId": f.code,
            "level": _SARIF_LEVEL[f.severity],
            "message": {"text": (f"{f.message} [{f.symbol}]"
                                 if f.symbol else f.message)},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {
                        "uri": f.path.replace("\\", "/"),
                        "uriBaseId": "SRCROOT",
                    },
                    "region": {"startLine": max(f.line, 1)},
                },
            }],
            "partialFingerprints": {
                "galahLintFingerprint/v1": f.fingerprint(),
            },
        }
        if f.suppressed:
            result["suppressions"] = [{
                "kind": ("inSource" if f.suppression == "inline"
                         else "external"),
            }]
        results.append(result)
    return {
        "$schema": SARIF_SCHEMA_URI,
        "version": SARIF_VERSION,
        "runs": [{
            "tool": {"driver": {
                "name": "galah-tpu lint",
                "informationUri":
                    "docs/static_analysis.md",
                "version": tool_version,
                "rules": [rules[c] for c in sorted(rules)],
            }},
            "columnKind": "utf16CodeUnits",
            "results": results,
        }],
    }


def failing(findings: Sequence[Finding],
            threshold: Severity = Severity.WARNING) -> List[Finding]:
    """Unsuppressed findings at or above the failure threshold."""
    return [f for f in findings
            if not f.suppressed and f.severity >= threshold]
