"""Interprocedural effect auditors (GL11xx) over GalahIR.

The lexical families (GL1006, GL806, GL1001, GL8xx) see one function
body at a time; a single helper indirection defeats them. This family
re-audits the same contracts over the whole-program call graph built
by :mod:`galah_tpu.analysis.ir`, so a ``device_round`` body calling a
local ``_sync()`` wrapper around ``.item()`` is caught with the full
provenance chain in the message.

Checks
  GL1101  transitive host sync reachable from a declared
          ``PIPELINE_STAGE["device_round"]`` function through at least
          one call edge (the direct case stays lexical GL1006 — the
          two rules partition, never double-report).
  GL1102  transitive filesystem write reachable from a function in a
          durable module (fs_check.DURABLE_MODULES) without routing
          through io/atomic.py. Effects never propagate OUT of
          atomic's own functions, so the sanctioned path is silent by
          construction; direct writes stay lexical GL806.
  GL1103  a streamed producer (``iter_*`` / ``*_streamed`` /
          ``process_stream``) passed into a function that materializes
          that parameter — directly (``list(p)``) or transitively
          (forwards it to a materializer). The direct-call case stays
          lexical GL1001.
  GL1007  (interprocedural arm; the in-function cases stay lexical in
          pipeline_check) a gathered band submatrix — a ``gather()``
          / ``band_gather()`` value inside a ``PAGED_MODULES``
          band-walk function — passed into a callee that retains its
          parameter (stores it on ``self``/a container, directly or
          through further forwarding). The retained reference pins
          the band's backing pages past eviction, so the out-of-core
          tier silently degrades to all-resident; the message carries
          the GalahIR retention chain down to the storing statement.
  GL1104  a lock acquired as a bare ``.acquire()`` statement with no
          ``with`` block and no try/finally releasing the same
          receiver: any raise between acquire and release leaks the
          lock. A ``return self.acquire()`` passthrough (context-
          manager delegation) is exempt — the caller owns the release.
  GL1105  a callback submitted to a pool (``pool.submit`` /
          ``Thread(target=...)``) in an annotated threaded module
          whose target carries inferred effects but never adopts a
          stage token (``timing.adopt`` / ``stage_token``): its
          duration and failures escape stage attribution (the
          interprocedural completion of GL804).

Every finding's message carries the witness chain down to the direct
sink (``f -> g -> h: np.asarray() at path.py:42``), so the report is
actionable without re-deriving the path by hand.

Suppression: the usual inline comment on the flagged line, e.g.
``# galah-lint: ignore[GL1104] <why>``.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from galah_tpu.analysis import ir as girt
from galah_tpu.analysis import pipeline_check
from galah_tpu.analysis.core import Finding, Severity, SourceFile
from galah_tpu.analysis.fs_check import DURABLE_MODULES

#: GL1104/GL1105 scope: the package itself, minus the analysis
#: tooling (whose sanitizer implements lock plumbing on purpose).
_EFFECT_SCOPE_PREFIX = "galah_tpu/"
_EFFECT_EXEMPT_PREFIXES = ("galah_tpu/analysis/",)


def _in_effect_scope(path: str) -> bool:
    p = path.replace("\\", "/")
    return (p.startswith(_EFFECT_SCOPE_PREFIX)
            and not p.startswith(_EFFECT_EXEMPT_PREFIXES))


def _check_device_round_sync(program: girt.ProgramIR,
                             out: List[Finding]) -> None:
    """GL1101: host sync reaches a device_round body transitively."""
    for mod in program.modules.values():
        for name in mod.device_round:
            key = (mod.path, name)
            chain = program.witness_chain(key, "host_sync")
            if len(chain) < 2:
                continue  # absent, or direct (lexical GL1006's case)
            _, first = chain[0]
            out.append(Finding(
                code="GL1101", severity=Severity.WARNING,
                path=mod.path, line=first.line,
                message=("device-round body reaches a host sync "
                         "through its call graph ("
                         + program.render_chain(key, "host_sync")
                         + "); a transfer mid-trace splits the "
                         "persistent round program back into "
                         "per-window dispatches"),
                symbol=name))


def _check_durable_writes(program: girt.ProgramIR,
                          out: List[Finding]) -> None:
    """GL1102: a durable module writes through a helper that is not
    io/atomic.py."""
    for mod in program.modules.values():
        if mod.path not in DURABLE_MODULES:
            continue
        for qual in sorted(mod.functions):
            key = (mod.path, qual)
            chain = program.witness_chain(key, "fs_write")
            if len(chain) < 2:
                continue  # absent, or direct (lexical GL806's case)
            _, first = chain[0]
            out.append(Finding(
                code="GL1102", severity=Severity.WARNING,
                path=mod.path, line=first.line,
                message=("durable module writes through a non-atomic "
                         "helper ("
                         + program.render_chain(key, "fs_write")
                         + "); route the write through io/atomic.py "
                         "so a killed writer can't leave a torn "
                         "artifact"),
                symbol=qual))


def _check_stream_materialization(program: girt.ProgramIR,
                                  out: List[Finding]) -> None:
    """GL1103: a streamed producer handed to a materializing callee."""
    for mod in program.modules.values():
        if not pipeline_check.in_scope(mod.path):
            continue
        for qual in sorted(mod.functions):
            fn = mod.functions[qual]
            for cname, idx, line, producer in fn.stream_args:
                if cname.rsplit(".", 1)[-1] in girt.MATERIALIZERS:
                    continue  # lexical GL1001's case
                callee = program.resolve(mod, qual, cname)
                if callee is None:
                    continue
                param = program.materializing_param(callee, idx)
                if param is None:
                    continue
                out.append(Finding(
                    code="GL1103", severity=Severity.WARNING,
                    path=mod.path, line=line,
                    message=(f"streamed iterator {producer}() is "
                             f"materialized by {callee[1]}() "
                             f"(parameter {param!r}, defined at "
                             f"{callee[0]}:"
                             f"{program.functions[callee].line}): "
                             "the stage drains instead of "
                             "overlapping; consume incrementally or "
                             "bound the buffer"),
                    symbol=producer))


def _check_paged_retention(program: girt.ProgramIR,
                           out: List[Finding]) -> None:
    """GL1007 (interprocedural): a gathered band submatrix handed to
    a callee that retains it — the helper indirection the lexical arm
    in pipeline_check cannot see."""
    for mod in program.modules.values():
        names = pipeline_check.PAGED_MODULES.get(mod.path)
        if not names:
            continue
        for qual in sorted(mod.functions):
            if qual.split(".")[-1] not in names:
                continue
            fn = mod.functions[qual]
            for cname, idx, line, producer in fn.gather_args:
                callee = program.resolve(mod, qual, cname)
                if callee is None:
                    continue
                param = program.retaining_param(callee, idx)
                if param is None:
                    continue
                out.append(Finding(
                    code="GL1007", severity=Severity.WARNING,
                    path=mod.path, line=line,
                    message=(f"band submatrix from {producer}() is "
                             f"retained by {callee[1]}() ("
                             + program.render_retention_chain(
                                 callee, param)
                             + "): the reference pins the band's "
                             "backing pages past eviction and the "
                             "paging schedule silently degrades to "
                             "all-resident (docs/memory.md); reduce "
                             "the band to its result instead of "
                             "storing it"),
                    symbol=producer))


def _check_unsafe_acquires(program: girt.ProgramIR,
                           out: List[Finding]) -> None:
    """GL1104: bare acquire with no release on the raising path."""
    for mod in program.modules.values():
        if not _in_effect_scope(mod.path):
            continue
        for qual in sorted(mod.functions):
            for line, recv in mod.functions[qual].unsafe_acquires:
                out.append(Finding(
                    code="GL1104", severity=Severity.WARNING,
                    path=mod.path, line=line,
                    message=(f"{recv}.acquire() in {qual}() has no "
                             "with-block or try/finally release: any "
                             "raise before the release leaks the "
                             "lock; use `with` or move the acquire "
                             "directly above a try/finally that "
                             "releases it"),
                    symbol=qual))


def _check_submit_adoption(program: girt.ProgramIR,
                           out: List[Finding]) -> None:
    """GL1105: effectful pool callbacks without stage-token adoption."""
    for mod in program.modules.values():
        if not mod.annotated or not _in_effect_scope(mod.path):
            continue
        for qual in sorted(mod.functions):
            fn = mod.functions[qual]
            for edge in fn.calls:
                if edge.kind != "submit":
                    continue
                callee = program.resolve(mod, qual, edge.name)
                if callee is None:
                    continue
                if program.adopts(callee):
                    continue
                effects = sorted(program.effects_of(callee))
                if not effects:
                    continue
                out.append(Finding(
                    code="GL1105", severity=Severity.WARNING,
                    path=mod.path, line=edge.line,
                    message=(f"callback {callee[1]}() (defined at "
                             f"{callee[0]}:"
                             f"{program.functions[callee].line}) is "
                             "submitted to a pool carrying effects "
                             f"[{', '.join(effects)}] but never "
                             "adopts a stage token: its duration and "
                             "failures escape stage attribution; "
                             "adopt the submitter's token "
                             "(obs.timing.adopt) inside the callback"),
                    symbol=callee[1]))


def check_effects(sources: Dict[str, SourceFile],
                  cache: Optional[girt.IRCache] = None,
                  program: Optional[girt.ProgramIR] = None
                  ) -> List[Finding]:
    """All GL11xx checks over the whole loaded tree.

    Pass ``cache`` to reuse per-file IR across runs (content-hash
    keyed); pass ``program`` to reuse an already-built ProgramIR."""
    if program is None:
        program = girt.build_program_ir(sources, cache=cache)
    out: List[Finding] = []
    _check_device_round_sync(program, out)
    _check_durable_writes(program, out)
    _check_stream_materialization(program, out)
    _check_paged_retention(program, out)
    _check_unsafe_acquires(program, out)
    _check_submit_adoption(program, out)
    return out
