"""Observability discipline (GL7xx): no ad-hoc timing in the pipeline.

The telemetry layer (galah_tpu/obs, docs/observability.md) is where
durations belong: stage spans via ``utils/timing.stage``, everything
else via an ``obs.metrics`` histogram's ``.time()`` context manager.
A raw ``time.perf_counter()`` pair whose delta only ever reaches a log
line is invisible to the run report and to ``galah-tpu report --diff``
— exactly the number a regression hunt needs.

Pipeline modules are everything under ``galah_tpu/`` EXCEPT the
infrastructure that implements the telemetry itself:

  * ``galah_tpu/utils/``     — timing.py IS the sanctioned timer
  * ``galah_tpu/obs/``       — the metrics/trace/report layer
  * ``galah_tpu/analysis/``  — the lint suite (host-side tooling)

(scripts/, tests/, and bench.py are outside the GL7xx scope entirely:
they are harnesses, not the pipeline.)

Checks
  GL701  direct wall-clock timing call (``time.time`` /
         ``time.perf_counter`` / ``time.perf_counter_ns`` /
         ``time.process_time``) in a pipeline module — import aliases
         (``import time as _t``, ``from time import perf_counter``)
         are resolved, so renaming does not evade the check.
         ``time.monotonic`` is deliberately NOT flagged: it is the
         deadline/budget accounting clock (resilience/policy.py), not
         a measurement primitive. ``time.sleep`` is not timing at all.
  GL702  logging call whose literal message embeds a formatted
         seconds figure (``%.2fs`` / f-string ``{dt:.1f}s``) — the
         signature of a measured duration that lives only in the log.
  GL703  direct device-cost introspection (``.memory_stats()`` /
         ``.cost_analysis()``) in a pipeline module. Device cost
         attribution belongs to ``obs/profile.py`` (the ``@profiled``
         registry + ``sample_memory``): an ad-hoc ``memory_stats()``
         read is invisible to the run report's ``device_costs``
         section and to the perf ledger, and an ad-hoc
         ``cost_analysis()`` forces a second trace/lowering of a
         function the profiler already compiled. obs/ is exempt by
         scope, so profile.py itself is the one sanctioned caller.
  GL704  flow discipline for pipeline-stage modules: a module that
         declares a ``PIPELINE_STAGE`` contract must emit its queue
         telemetry through ``obs/flow.py`` — (a) the module never
         imports/calls ``galah_tpu.obs.flow`` at all (anchored at the
         ``PIPELINE_STAGE`` line), or (b) it hand-rolls queue-wait
         timing: an assignment to a ``*wait*`` name computed from a
         raw clock read (``time.monotonic`` included here — it is the
         sanctioned deadline clock, but a wait accumulated from it
         bypasses the flow recorder's blocked-on attribution and the
         report's critical path). Wrap the dequeue in
         ``obs.flow.blocked(stage, reason)`` and read ``.seconds``.

Suppression: the usual inline comment on the flagged line or the line
above, with a justification —

    started = time.time()  # galah-lint: ignore[GL701] wall-clock stamp

Legitimate cases are timestamps (not durations) and log lines whose
seconds figure is ALSO recorded in the registry.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List

from galah_tpu.analysis.core import (Finding, Severity, SourceFile,
                                     dotted_name)

# The measurement clocks GL701 bans from pipeline modules.
TIMING_CALLS = frozenset({
    "time.time",
    "time.perf_counter",
    "time.perf_counter_ns",
    "time.process_time",
})

# Device-cost introspection methods GL703 reserves for obs/profile.py.
# Matched as attribute calls (``<anything>.memory_stats()``) because
# the receiver is a runtime Device / Compiled object the AST cannot
# type; the method names are specific enough that a pipeline-module
# hit is a real bypass of the profiler.
DEVICE_COST_CALLS = frozenset({"memory_stats", "cost_analysis"})

# The clocks GL704 treats as hand-rolled queue timing when they feed a
# ``*wait*`` accumulator in a PIPELINE_STAGE module. time.monotonic is
# allowed everywhere else (deadline/budget accounting) but a wait
# derived from it bypasses obs/flow.py's blocked-on attribution.
_QUEUE_CLOCKS = TIMING_CALLS | frozenset({"time.monotonic"})

_EXEMPT_PREFIXES = ("galah_tpu/utils/", "galah_tpu/obs/",
                    "galah_tpu/analysis/")

# "%.2fs", "%.1f s", "%fs" inside a %-format log message.
_PCT_SECONDS_RE = re.compile(r"%\.?\d*f\s?s\b")
# ".2f"-style format_spec; the following literal must start with "s".
_SPEC_SECONDS_RE = re.compile(r"^\.\d+f$")

_LOG_METHODS = frozenset({"debug", "info", "warning", "error",
                          "critical", "exception", "log"})


def in_scope(path: str) -> bool:
    """True for pipeline modules: galah_tpu/ minus the telemetry and
    tooling packages (module docstring)."""
    p = path.replace("\\", "/")
    if not p.startswith("galah_tpu/"):
        return False
    return not p.startswith(_EXEMPT_PREFIXES)


def _time_aliases(tree: ast.Module,
                  banned: frozenset = TIMING_CALLS) -> Dict[str, str]:
    """name-as-written -> canonical dotted name for the time module
    and its banned members, resolving import aliases."""
    alias: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "time":
                    alias[a.asname or a.name] = "time"
        elif isinstance(node, ast.ImportFrom) and node.module == "time":
            for a in node.names:
                full = f"time.{a.name}"
                if full in banned:
                    alias[a.asname or a.name] = full
    return alias


def _resolve_clock(call: ast.Call, aliases: Dict[str, str],
                   banned: frozenset) -> "str | None":
    """Canonical dotted name of a banned clock call, alias-resolved;
    None when the call is not one."""
    name = dotted_name(call.func)
    if name in banned:
        return name
    if "." in name:
        head, _, tail = name.partition(".")
        if aliases.get(head) == "time" and f"time.{tail}" in banned:
            return f"time.{tail}"
        return None
    full = aliases.get(name)
    return full if full in banned else None


def _is_log_call(node: ast.Call) -> bool:
    """logger.warning(...), logging.info(...), self._log.debug(...)."""
    fn = node.func
    if not isinstance(fn, ast.Attribute) or fn.attr not in _LOG_METHODS:
        return False
    owner = dotted_name(fn.value)
    base = owner.split(".")[-1].lower()
    return "log" in base


def _literal_has_seconds(node: ast.AST) -> bool:
    """A string literal (plain or f-string) formatting a seconds
    figure: '%.2fs' or f'{dt:.1f}s'."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return bool(_PCT_SECONDS_RE.search(node.value))
    if isinstance(node, ast.JoinedStr):
        parts = node.values
        for i, part in enumerate(parts):
            if not isinstance(part, ast.FormattedValue):
                continue
            spec = part.format_spec
            if not (isinstance(spec, ast.JoinedStr) and spec.values):
                continue
            s0 = spec.values[0]
            if not (isinstance(s0, ast.Constant)
                    and isinstance(s0.value, str)
                    and _SPEC_SECONDS_RE.match(s0.value)):
                continue
            nxt = parts[i + 1] if i + 1 < len(parts) else None
            if (isinstance(nxt, ast.Constant)
                    and isinstance(nxt.value, str)
                    and nxt.value.startswith("s")):
                return True
    return False


def check_obs_file(src: SourceFile) -> List[Finding]:
    """GL701/GL702/GL703 over one source file (no-op outside the
    scope)."""
    if not in_scope(src.path):
        return []
    findings: List[Finding] = []
    aliases = _time_aliases(src.tree)
    for node in src.walk():
        if not isinstance(node, ast.Call):
            continue
        resolved = _resolve_clock(node, aliases, TIMING_CALLS)
        if resolved is not None:
            findings.append(Finding(
                "GL701", Severity.WARNING, src.path, node.lineno,
                f"direct {resolved}() in a pipeline module — measure "
                "durations with an obs.metrics histogram's .time() "
                "(or a utils/timing stage) so they land in the run "
                "report, not only in locals"))
            continue
        if (isinstance(node.func, ast.Attribute)
                and node.func.attr in DEVICE_COST_CALLS):
            findings.append(Finding(
                "GL703", Severity.WARNING, src.path, node.lineno,
                f"direct .{node.func.attr}() in a pipeline module — "
                "device-cost introspection belongs to obs/profile.py "
                "(@profiled entry points + profile.sample_memory), "
                "where the numbers reach the run report's "
                "device_costs section and the perf ledger"))
            continue
        if _is_log_call(node) and any(
                _literal_has_seconds(a) for a in node.args):
            # anchor at the message literal so a suppression comment
            # sits next to the offending format, not the call head
            lit = next(a for a in node.args if _literal_has_seconds(a))
            findings.append(Finding(
                "GL702", Severity.WARNING, src.path, lit.lineno,
                "log message formats a seconds figure — a measured "
                "duration that lives only in the log; record it in "
                "the obs.metrics registry (and log it too if useful) "
                "so `galah-tpu report --diff` can see it"))
    findings.extend(_check_flow_discipline(src))
    return findings


def _flow_imports(tree: ast.Module):
    """(module-alias names, directly imported function names) bound to
    galah_tpu.obs.flow anywhere in the file — module-level or the
    lazy function-level imports the pipeline modules use."""
    mod_names = set()
    fn_names = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "galah_tpu.obs.flow" and a.asname:
                    mod_names.add(a.asname)
        elif isinstance(node, ast.ImportFrom):
            if node.module == "galah_tpu.obs":
                for a in node.names:
                    if a.name == "flow":
                        mod_names.add(a.asname or "flow")
            elif node.module == "galah_tpu.obs.flow":
                for a in node.names:
                    fn_names.add(a.asname or a.name)
    return mod_names, fn_names


def _check_flow_discipline(src: SourceFile) -> List[Finding]:
    """GL704 over one in-scope file: only fires on modules declaring a
    module-level ``PIPELINE_STAGE`` contract."""
    stage_line = None
    for node in src.tree.body:
        if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "PIPELINE_STAGE"
                for t in node.targets):
            stage_line = node.lineno
            break
    if stage_line is None:
        return []
    findings: List[Finding] = []
    mod_names, fn_names = _flow_imports(src.tree)
    uses_flow = False
    for node in src.walk():
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if isinstance(fn, ast.Attribute):
            if dotted_name(fn.value).partition(".")[0] in mod_names:
                uses_flow = True
                break
        elif isinstance(fn, ast.Name) and fn.id in fn_names:
            uses_flow = True
            break
    if not uses_flow:
        findings.append(Finding(
            "GL704", Severity.WARNING, src.path, stage_line,
            "module declares PIPELINE_STAGE but never emits flow "
            "spans — bracket its dequeues with obs.flow.blocked() and "
            "its work with obs.flow.record_service()/span() so the "
            "run report's critical path can attribute this stage"))
    aliases = _time_aliases(src.tree, banned=_QUEUE_CLOCKS)
    for node in src.walk():
        if not isinstance(node, (ast.Assign, ast.AugAssign)):
            continue
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target])
        waitish = any(
            "wait" in (t.id if isinstance(t, ast.Name) else
                       t.attr if isinstance(t, ast.Attribute) else
                       "").lower()
            for t in targets)
        if not waitish:
            continue
        clock = next(
            (_resolve_clock(c, aliases, _QUEUE_CLOCKS)
             for c in ast.walk(node.value)
             if isinstance(c, ast.Call)
             and _resolve_clock(c, aliases, _QUEUE_CLOCKS)), None)
        if clock is not None:
            findings.append(Finding(
                "GL704", Severity.WARNING, src.path, node.lineno,
                f"hand-rolled queue-wait timing ({clock}() feeding a "
                "wait accumulator) in a PIPELINE_STAGE module — wrap "
                "the dequeue in obs.flow.blocked(stage, reason) and "
                "accumulate its .seconds so the wait carries blocked-"
                "on attribution in the report's critical path"))
    return findings
