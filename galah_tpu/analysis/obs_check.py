"""Observability discipline (GL7xx): no ad-hoc timing in the pipeline.

The telemetry layer (galah_tpu/obs, docs/observability.md) is where
durations belong: stage spans via ``utils/timing.stage``, everything
else via an ``obs.metrics`` histogram's ``.time()`` context manager.
A raw ``time.perf_counter()`` pair whose delta only ever reaches a log
line is invisible to the run report and to ``galah-tpu report --diff``
— exactly the number a regression hunt needs.

Pipeline modules are everything under ``galah_tpu/`` EXCEPT the
infrastructure that implements the telemetry itself:

  * ``galah_tpu/utils/``     — timing.py IS the sanctioned timer
  * ``galah_tpu/obs/``       — the metrics/trace/report layer
  * ``galah_tpu/analysis/``  — the lint suite (host-side tooling)

(scripts/, tests/, and bench.py are outside the GL7xx scope entirely:
they are harnesses, not the pipeline.)

Checks
  GL701  direct wall-clock timing call (``time.time`` /
         ``time.perf_counter`` / ``time.perf_counter_ns`` /
         ``time.process_time``) in a pipeline module — import aliases
         (``import time as _t``, ``from time import perf_counter``)
         are resolved, so renaming does not evade the check.
         ``time.monotonic`` is deliberately NOT flagged: it is the
         deadline/budget accounting clock (resilience/policy.py), not
         a measurement primitive. ``time.sleep`` is not timing at all.
  GL702  logging call whose literal message embeds a formatted
         seconds figure (``%.2fs`` / f-string ``{dt:.1f}s``) — the
         signature of a measured duration that lives only in the log.
  GL703  direct device-cost introspection (``.memory_stats()`` /
         ``.cost_analysis()``) in a pipeline module. Device cost
         attribution belongs to ``obs/profile.py`` (the ``@profiled``
         registry + ``sample_memory``): an ad-hoc ``memory_stats()``
         read is invisible to the run report's ``device_costs``
         section and to the perf ledger, and an ad-hoc
         ``cost_analysis()`` forces a second trace/lowering of a
         function the profiler already compiled. obs/ is exempt by
         scope, so profile.py itself is the one sanctioned caller.

Suppression: the usual inline comment on the flagged line or the line
above, with a justification —

    started = time.time()  # galah-lint: ignore[GL701] wall-clock stamp

Legitimate cases are timestamps (not durations) and log lines whose
seconds figure is ALSO recorded in the registry.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List

from galah_tpu.analysis.core import (Finding, Severity, SourceFile,
                                     dotted_name)

# The measurement clocks GL701 bans from pipeline modules.
TIMING_CALLS = frozenset({
    "time.time",
    "time.perf_counter",
    "time.perf_counter_ns",
    "time.process_time",
})

# Device-cost introspection methods GL703 reserves for obs/profile.py.
# Matched as attribute calls (``<anything>.memory_stats()``) because
# the receiver is a runtime Device / Compiled object the AST cannot
# type; the method names are specific enough that a pipeline-module
# hit is a real bypass of the profiler.
DEVICE_COST_CALLS = frozenset({"memory_stats", "cost_analysis"})

_EXEMPT_PREFIXES = ("galah_tpu/utils/", "galah_tpu/obs/",
                    "galah_tpu/analysis/")

# "%.2fs", "%.1f s", "%fs" inside a %-format log message.
_PCT_SECONDS_RE = re.compile(r"%\.?\d*f\s?s\b")
# ".2f"-style format_spec; the following literal must start with "s".
_SPEC_SECONDS_RE = re.compile(r"^\.\d+f$")

_LOG_METHODS = frozenset({"debug", "info", "warning", "error",
                          "critical", "exception", "log"})


def in_scope(path: str) -> bool:
    """True for pipeline modules: galah_tpu/ minus the telemetry and
    tooling packages (module docstring)."""
    p = path.replace("\\", "/")
    if not p.startswith("galah_tpu/"):
        return False
    return not p.startswith(_EXEMPT_PREFIXES)


def _time_aliases(tree: ast.Module) -> Dict[str, str]:
    """name-as-written -> canonical dotted name for the time module
    and its banned members, resolving import aliases."""
    alias: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "time":
                    alias[a.asname or a.name] = "time"
        elif isinstance(node, ast.ImportFrom) and node.module == "time":
            for a in node.names:
                full = f"time.{a.name}"
                if full in TIMING_CALLS:
                    alias[a.asname or a.name] = full
    return alias


def _is_log_call(node: ast.Call) -> bool:
    """logger.warning(...), logging.info(...), self._log.debug(...)."""
    fn = node.func
    if not isinstance(fn, ast.Attribute) or fn.attr not in _LOG_METHODS:
        return False
    owner = dotted_name(fn.value)
    base = owner.split(".")[-1].lower()
    return "log" in base


def _literal_has_seconds(node: ast.AST) -> bool:
    """A string literal (plain or f-string) formatting a seconds
    figure: '%.2fs' or f'{dt:.1f}s'."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return bool(_PCT_SECONDS_RE.search(node.value))
    if isinstance(node, ast.JoinedStr):
        parts = node.values
        for i, part in enumerate(parts):
            if not isinstance(part, ast.FormattedValue):
                continue
            spec = part.format_spec
            if not (isinstance(spec, ast.JoinedStr) and spec.values):
                continue
            s0 = spec.values[0]
            if not (isinstance(s0, ast.Constant)
                    and isinstance(s0.value, str)
                    and _SPEC_SECONDS_RE.match(s0.value)):
                continue
            nxt = parts[i + 1] if i + 1 < len(parts) else None
            if (isinstance(nxt, ast.Constant)
                    and isinstance(nxt.value, str)
                    and nxt.value.startswith("s")):
                return True
    return False


def check_obs_file(src: SourceFile) -> List[Finding]:
    """GL701/GL702/GL703 over one source file (no-op outside the
    scope)."""
    if not in_scope(src.path):
        return []
    findings: List[Finding] = []
    aliases = _time_aliases(src.tree)
    for node in ast.walk(src.tree):
        if not isinstance(node, ast.Call):
            continue
        name = dotted_name(node.func)
        resolved = None
        if name in TIMING_CALLS:
            resolved = name
        elif "." in name:
            head, _, tail = name.partition(".")
            if aliases.get(head) == "time" and f"time.{tail}" in \
                    TIMING_CALLS:
                resolved = f"time.{tail}"
        elif aliases.get(name) in TIMING_CALLS:
            resolved = aliases[name]
        if resolved is not None:
            findings.append(Finding(
                "GL701", Severity.WARNING, src.path, node.lineno,
                f"direct {resolved}() in a pipeline module — measure "
                "durations with an obs.metrics histogram's .time() "
                "(or a utils/timing stage) so they land in the run "
                "report, not only in locals"))
            continue
        if (isinstance(node.func, ast.Attribute)
                and node.func.attr in DEVICE_COST_CALLS):
            findings.append(Finding(
                "GL703", Severity.WARNING, src.path, node.lineno,
                f"direct .{node.func.attr}() in a pipeline module — "
                "device-cost introspection belongs to obs/profile.py "
                "(@profiled entry points + profile.sample_memory), "
                "where the numbers reach the run report's "
                "device_costs section and the perf ledger"))
            continue
        if _is_log_call(node) and any(
                _literal_has_seconds(a) for a in node.args):
            # anchor at the message literal so a suppression comment
            # sits next to the offending format, not the call head
            lit = next(a for a in node.args if _literal_has_seconds(a))
            findings.append(Finding(
                "GL702", Severity.WARNING, src.path, lit.lineno,
                "log message formats a seconds figure — a measured "
                "duration that lives only in the log; record it in "
                "the obs.metrics registry (and log it too if useful) "
                "so `galah-tpu report --diff` can see it"))
    return findings
