"""Pallas contract checker (GL1xx): BlockSpec tiling, VMEM, 64-bit.

For every ``pl.pallas_call`` site this checker statically evaluates the
BlockSpec / scratch shape expressions — using the enclosing module's
integer constants plus the representative bindings its
``PALLAS_CONTRACT`` annotation declares — and verifies:

  GL101  pallas_call site without a contract entry (or module without
         a PALLAS_CONTRACT at all)
  GL102  contract entry naming a function with no pallas_call (stale)
  GL103  block last dim not a multiple of the 128-lane quantum
  GL104  block sublane dim not a multiple of the dtype's quantum
  GL105  estimated resident VMEM (in + out + scratch blocks) exceeds
         the budget x safety factor
  GL106  64-bit dtype at the kernel boundary or inside a kernel body
         (TPU has no u64/i64/f64; this repo emulates via u32 planes)
  GL107  a shape expression the restricted evaluator cannot resolve

All checks run on CPU with zero compilation — the point is failing
tier-1 before a TPU ever sees the code.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from galah_tpu.analysis import contracts
from galah_tpu.analysis.core import (Finding, Severity, SourceFile,
                                     SymbolicEvalError, dotted_name,
                                     enclosing_functions, safe_eval)


def _is_call_to(node: ast.AST, suffix: str) -> bool:
    return (isinstance(node, ast.Call)
            and dotted_name(node.func).split(".")[-1] == suffix)


def _keywords(call: ast.Call) -> Dict[str, ast.AST]:
    return {kw.arg: kw.value for kw in call.keywords if kw.arg}


def _local_assignments(fn: Optional[ast.AST]) -> Dict[str, ast.AST]:
    """name -> value for simple ``name = expr`` statements in `fn`."""
    out: Dict[str, ast.AST] = {}
    if fn is None:
        return out
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            out[node.targets[0].id] = node.value
    return out


def _resolve(node: ast.AST, local: Dict[str, ast.AST],
             depth: int = 0) -> ast.AST:
    """Follow simple local ``spec = pl.BlockSpec(...)`` indirections."""
    while isinstance(node, ast.Name) and node.id in local and depth < 5:
        node = local[node.id]
        depth += 1
    return node


def _flatten_spec_list(node: ast.AST, local: Dict[str, ast.AST],
                       env: Dict[str, object]) -> List[ast.AST]:
    """Elements of an in_specs/out_specs expression: handles list
    literals, ``[spec] * 6`` replication, local-name indirection, a
    bare single spec, and conditional expressions (both branches of an
    IfExp are unioned — the checker must cover every variant)."""
    node = _resolve(node, local)
    if isinstance(node, (ast.List, ast.Tuple)):
        out: List[ast.AST] = []
        for elt in node.elts:
            out.extend(_flatten_spec_list(elt, local, env))
        return out
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Mult):
        for seq, count in ((node.left, node.right),
                           (node.right, node.left)):
            if isinstance(_resolve(seq, local), (ast.List, ast.Tuple)):
                try:
                    n = int(safe_eval(count, env))
                except SymbolicEvalError:
                    n = 1
                return _flatten_spec_list(seq, local, env) * n
        return []
    if isinstance(node, ast.IfExp):
        return (_flatten_spec_list(node.body, local, env)
                + _flatten_spec_list(node.orelse, local, env))
    return [node]


def _block_shape(spec: ast.Call, env: Dict[str, object]) -> \
        Optional[Tuple[int, ...]]:
    """The evaluated block shape of a BlockSpec / VMEM scratch call,
    or None when the spec declares no shape (whole-array block)."""
    shape_node: Optional[ast.AST] = None
    if spec.args:
        shape_node = spec.args[0]
    else:
        kw = _keywords(spec)
        shape_node = kw.get("block_shape")
    if shape_node is None or (isinstance(shape_node, ast.Constant)
                              and shape_node.value is None):
        return None
    value = safe_eval(shape_node, env)
    if not isinstance(value, tuple):
        raise SymbolicEvalError("block shape is not a tuple")
    return tuple(int(v) for v in value)


def _check_block(shape: Tuple[int, ...], dtype: Optional[str],
                 where: str, path: str, line: int, symbol: str,
                 findings: List[Finding]) -> int:
    """Tiling + dtype checks for one VMEM block; returns its bytes."""
    dtype = dtype or "int32"
    if dtype in contracts.BANNED_DTYPES:
        findings.append(Finding(
            "GL106", Severity.ERROR, path, line,
            f"{where} uses {dtype}: TPU has no 64-bit unit — emulate "
            "via hi/lo 32-bit planes (see ops/pallas_pairwise)",
            symbol))
    if len(shape) >= 1 and shape[-1] % contracts.LANE_QUANTUM:
        findings.append(Finding(
            "GL103", Severity.ERROR, path, line,
            f"{where} block shape {shape}: last dim {shape[-1]} is not "
            f"a multiple of the {contracts.LANE_QUANTUM}-lane quantum",
            symbol))
    if len(shape) >= 2:
        q = contracts.sublane_quantum(dtype)
        if shape[-2] % q:
            findings.append(Finding(
                "GL104", Severity.ERROR, path, line,
                f"{where} block shape {shape}: sublane dim {shape[-2]} "
                f"is not a multiple of the {dtype} quantum {q}",
                symbol))
    size = contracts.dtype_itemsize(dtype) or 4
    n = 1
    for d in shape:
        n *= max(1, int(d))
    return n * size


def _scan_kernel_fns(tree: ast.Module, names: List[str], path: str,
                     symbol: str, findings: List[Finding]) -> None:
    """GL106 inside declared kernel-body functions: any reference to a
    64-bit dtype in code that will lower through Mosaic."""
    wanted = set(names)
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and node.name in wanted:
            for sub in ast.walk(node):
                ref = None
                if isinstance(sub, ast.Attribute) \
                        and sub.attr in contracts.BANNED_DTYPES:
                    ref = sub.attr
                elif isinstance(sub, ast.Constant) \
                        and sub.value in contracts.BANNED_DTYPES:
                    ref = sub.value
                if ref:
                    findings.append(Finding(
                        "GL106", Severity.ERROR, path, sub.lineno,
                        f"kernel body {node.name}() references {ref}: "
                        "no 64-bit unit on TPU", symbol or node.name))


def check_pallas_file(src: SourceFile,
                      contract: Optional[Dict[str, dict]] = None) -> \
        List[Finding]:
    """Run the GL1xx checks over one module."""
    findings: List[Finding] = []
    tree = src.tree
    if contract is None:
        contract = contracts.harvest_contract(tree)
    consts = contracts.module_int_constants(tree)
    owner = enclosing_functions(tree)

    sites: List[Tuple[ast.Call, str]] = []
    for node in ast.walk(tree):
        if _is_call_to(node, "pallas_call"):
            fn = owner.get(node)
            sites.append((node, fn.name if fn is not None else ""))

    if not sites:
        if contract:
            for name in contract:
                findings.append(Finding(
                    "GL102", Severity.ERROR, src.path, 1,
                    f"PALLAS_CONTRACT entry {name!r} but the module "
                    "has no pallas_call site", name))
        return findings

    if contract is None:
        for call, symbol in sites:
            findings.append(Finding(
                "GL101", Severity.ERROR, src.path, call.lineno,
                "pallas_call site without a PALLAS_CONTRACT "
                "annotation (module-level dict literal; see "
                "analysis/contracts.py)", symbol))
        return findings

    seen_fns = set()
    for call, symbol in sites:
        seen_fns.add(symbol)
        entry = contract.get(symbol)
        if entry is None:
            findings.append(Finding(
                "GL101", Severity.ERROR, src.path, call.lineno,
                f"pallas_call in {symbol}() has no PALLAS_CONTRACT "
                "entry", symbol))
            continue
        env: Dict[str, object] = dict(consts)
        env.update(entry.get("bindings", {}))
        budget = int(entry.get("vmem_budget_bytes",
                               contracts.VMEM_BYTES))
        safety = float(entry.get("vmem_safety",
                                 contracts.VMEM_SAFETY_DEFAULT))
        in_dtypes = list(entry.get("in_dtypes", []))
        fn_node = next(
            (n for n in ast.walk(tree)
             if isinstance(n, ast.FunctionDef) and n.name == symbol),
            None)
        local = _local_assignments(fn_node)
        kw = _keywords(call)

        total_bytes = 0
        unevaluated = False

        def eval_specs(node: ast.AST, dtypes: List[Optional[str]],
                       where: str) -> None:
            nonlocal total_bytes, unevaluated
            specs = _flatten_spec_list(node, local, env)
            for i, spec_node in enumerate(specs):
                spec_node = _resolve(spec_node, local)
                if not isinstance(spec_node, ast.Call):
                    continue
                dtype = dtypes[i] if i < len(dtypes) else None
                try:
                    shape = _block_shape(spec_node, env)
                except SymbolicEvalError as e:
                    unevaluated = True
                    findings.append(Finding(
                        "GL107", Severity.WARNING, src.path,
                        spec_node.lineno,
                        f"{where}[{i}] block shape not statically "
                        f"evaluable ({e}); add the missing symbol to "
                        "the contract's bindings", symbol))
                    continue
                if shape is None:
                    continue
                total_bytes += _check_block(
                    shape, dtype, f"{where}[{i}]", src.path,
                    spec_node.lineno, symbol, findings)

        # out dtypes come from the out_shape ShapeDtypeStructs
        out_dtypes: List[Optional[str]] = []
        out_shape_node = kw.get("out_shape")
        if out_shape_node is not None:
            resolved = _resolve(out_shape_node, local)
            elts = (resolved.elts
                    if isinstance(resolved, (ast.List, ast.Tuple))
                    else [resolved])
            for elt in elts:
                elt = _resolve(elt, local)
                if isinstance(elt, ast.Call) and len(elt.args) >= 2:
                    out_dtypes.append(
                        contracts.dtype_from_node(elt.args[1]))
                else:
                    out_dtypes.append(None)

        if "in_specs" in kw:
            eval_specs(kw["in_specs"], in_dtypes, "in_specs")
        if "out_specs" in kw:
            eval_specs(kw["out_specs"], out_dtypes, "out_specs")

        # banned dtypes in out_shape even when out_specs are shapeless
        for i, d in enumerate(out_dtypes):
            if d in contracts.BANNED_DTYPES:
                findings.append(Finding(
                    "GL106", Severity.ERROR, src.path, call.lineno,
                    f"out_shape[{i}] declares {d}: TPU has no 64-bit "
                    "unit", symbol))

        # scratch: pltpu.VMEM((shape), dtype) entries
        scratch_node = kw.get("scratch_shapes")
        if scratch_node is not None:
            for i, s in enumerate(_flatten_spec_list(
                    scratch_node, local, env)):
                s = _resolve(s, local)
                if not (isinstance(s, ast.Call)
                        and dotted_name(s.func).endswith("VMEM")):
                    continue
                dtype = (contracts.dtype_from_node(s.args[1])
                         if len(s.args) >= 2 else None)
                try:
                    shape = _block_shape(s, env)
                except SymbolicEvalError as e:
                    unevaluated = True
                    findings.append(Finding(
                        "GL107", Severity.WARNING, src.path, s.lineno,
                        f"scratch_shapes[{i}] not statically evaluable "
                        f"({e})", symbol))
                    continue
                if shape is not None:
                    total_bytes += _check_block(
                        shape, dtype, f"scratch_shapes[{i}]", src.path,
                        s.lineno, symbol, findings)

        limit = int(budget * safety)
        if not unevaluated and total_bytes > limit:
            findings.append(Finding(
                "GL105", Severity.ERROR, src.path, call.lineno,
                f"estimated resident VMEM {total_bytes} B exceeds "
                f"budget {budget} B x safety {safety} = {limit} B at "
                "the contract's representative bindings", symbol))

        _scan_kernel_fns(tree, list(entry.get("kernel_fns", [])),
                         src.path, symbol, findings)

    for name in contract:
        if name not in seen_fns:
            findings.append(Finding(
                "GL102", Severity.ERROR, src.path, 1,
                f"PALLAS_CONTRACT entry {name!r} names a function "
                "with no pallas_call site (stale contract)", name))
    return findings
