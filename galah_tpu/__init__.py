"""galah-tpu: TPU-native genome dereplication.

A brand-new JAX/XLA framework with the capabilities of AroneyS/galah
(reference surveyed in SURVEY.md): cluster genomes by ANI with a two-stage
precluster -> exact-ANI pipeline and pick one quality-ranked representative
per cluster. The compute path is TPU-first: vectorized k-mer hashing,
bottom-k / FracMinHash sketching, and tiled all-pairs similarity sharded
over a device mesh, instead of the reference's rayon thread pool and
external C++ binaries.
"""

__version__ = "0.1.0"

from galah_tpu.config import ClusterConfig, Defaults  # noqa: F401


def __getattr__(name):
    # Lazy re-exports of the embeddable API (api.py) so `import
    # galah_tpu` stays cheap (no jax import) for --version/--help.
    if name in ("GalahClusterer", "ClustererCommandDefinition",
                "add_cluster_arguments", "generate_galah_clusterer"):
        from galah_tpu import api

        return getattr(api, name)
    # NB: no lazy alias for the cluster() function — it would collide
    # with the galah_tpu.cluster subpackage; use galah_tpu.cluster.cluster.
    raise AttributeError(f"module 'galah_tpu' has no attribute {name!r}")
