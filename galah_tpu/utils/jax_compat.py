"""Version-drift shims for the JAX surface this framework leans on.

The deployment images pin different jax releases than dev boxes, and the
shard_map entry point has moved twice (jax.experimental.shard_map ->
jax.shard_map) with a keyword rename (check_rep -> check_vma) along the
way. Kernel modules import `shard_map` from here so a version bump never
takes the whole sharded pairwise path (and its test tier) down with an
ImportError at module import time.
"""

from __future__ import annotations

import functools

import jax

if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:  # pre-0.6 releases: experimental entry point, check_rep keyword
    from jax.experimental.shard_map import shard_map as _shard_map_exp

    @functools.wraps(_shard_map_exp)
    def shard_map(f, *args, **kwargs):
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _shard_map_exp(f, *args, **kwargs)


def pcast_varying(x, axis_name: str):
    """jax.lax.pcast(x, axis, to="varying") where it exists.

    Releases without pcast predate the vma type system entirely, so
    constants inside shard_map bodies need no varying marker there —
    the identity is the correct no-op.
    """
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(x, axis_name, to="varying")
    return x


__all__ = ["shard_map", "pcast_varying"]
