"""Shared ctypes builder/loader for the native C kernels.

One implementation of the compile-on-first-import idiom used by
io/_cingest.py, ops/_cpairstats.py, and ops/_csketch.py: mtime-checked
rebuild, pid-suffixed temp + atomic os.replace (concurrent importers
never dlopen a half-written library), and a process-wide failure cache
so a broken toolchain or read-only package dir raises ImportError
instantly on every retry instead of re-spawning the compiler per call
(the caller modules are evicted from sys.modules when their import
fails, so without this cache each fallback call would re-run cc).
Every failure mode — including a corrupt/incompatible existing library
(dlopen OSError) — surfaces as ImportError, the contract the callers'
JAX/numpy fallbacks catch.
"""

from __future__ import annotations

import ctypes
import os
import pathlib
import subprocess
import sysconfig

_CSRC = pathlib.Path(__file__).resolve().parent.parent.parent / "csrc"
_SOSUFFIX = sysconfig.get_config_var("EXT_SUFFIX") or ".so"

_FAILED: dict[str, str] = {}


def build_and_load(src_name: str, lib_stem: str, out_dir,
                   extra_flags: tuple = (),
                   disable_env: str | None = None) -> ctypes.CDLL:
    """Compile csrc/<src_name> into <out_dir>/<lib_stem><EXT_SUFFIX>
    (when stale) and dlopen it. Raises ImportError on any failure —
    cached, so repeated attempts are cheap."""
    if disable_env and os.environ.get(disable_env):
        raise ImportError(f"native kernel disabled via {disable_env}")
    if src_name in _FAILED:
        raise ImportError(_FAILED[src_name])
    try:
        src = _CSRC / src_name
        if not src.is_file():
            raise ImportError(f"native source missing: {src}")
        lib = pathlib.Path(out_dir) / f"{lib_stem}{_SOSUFFIX}"
        if not (lib.is_file()
                and lib.stat().st_mtime >= src.stat().st_mtime):
            cc = os.environ.get("CC", "cc")
            tmp = lib.with_name(f"{lib.stem}.{os.getpid()}{lib.suffix}")
            cmd = [cc, "-O3", "-shared", "-fPIC", "-o", str(tmp),
                   str(src), *extra_flags]
            try:
                proc = subprocess.run(cmd, capture_output=True,
                                      text=True, timeout=120)
                if proc.returncode != 0:
                    raise ImportError(
                        f"native build failed: {' '.join(cmd)}\n"
                        f"{proc.stderr}")
                os.replace(tmp, lib)
            except (OSError, subprocess.TimeoutExpired) as e:
                raise ImportError(f"native build failed to run: {e}")
            finally:
                tmp.unlink(missing_ok=True)
        try:
            return ctypes.CDLL(str(lib))
        except OSError as e:
            raise ImportError(f"native library load failed ({lib}): {e}")
    except ImportError as e:
        _FAILED[src_name] = str(e)
        raise
