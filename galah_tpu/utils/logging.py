"""Leveled logging, mirroring the reference's -v/-q semantics.

Reference: bird_tool_utils::clap_utils::set_log_level as used from
src/main.rs:17 and src/cluster_argument_parsing.rs:402.
"""

from __future__ import annotations

import logging


def warn_once(logger: logging.Logger, msg: str, *args,
              key=None) -> None:
    """Back-compat delegate: the canonical warn-once lives in
    obs/events.py (process-scoped dedupe + suppressed-repeat events)."""
    from galah_tpu.obs import events

    events.warn_once(logger, msg, *args, key=key)


def reset_warn_once() -> None:
    """Back-compat delegate (tests import it from here)."""
    from galah_tpu.obs import events

    events.reset_warn_once()


def set_log_level(verbose: bool = False, quiet: bool = False) -> None:
    level = logging.INFO
    if verbose:
        level = logging.DEBUG
    if quiet:
        level = logging.ERROR
    logging.basicConfig(
        level=level,
        format="[%(asctime)s %(levelname)s %(name)s] %(message)s",
        datefmt="%Y-%m-%dT%H:%M:%S",
        force=True,
    )
