"""Leveled logging, mirroring the reference's -v/-q semantics.

Reference: bird_tool_utils::clap_utils::set_log_level as used from
src/main.rs:17 and src/cluster_argument_parsing.rs:402.
"""

from __future__ import annotations

import logging
import threading
from typing import Set, Tuple

_WARN_ONCE_LOCK = threading.Lock()
_WARNED: Set[Tuple[str, str]] = set()


def warn_once(logger: logging.Logger, msg: str, *args) -> None:
    """Emit `msg` at WARNING once per process per (logger, message).

    For warnings whose repetition carries no information — e.g. the
    missing-CheckM-input notice fires once per clusterer construction,
    which in bench/ladder runs means once per rung. Repeats are still
    counted as a structured event (obs/events.py) so the run report
    records the suppressed multiplicity."""
    key = (logger.name, msg)
    with _WARN_ONCE_LOCK:
        first = key not in _WARNED
        if first:
            _WARNED.add(key)
    if first:
        logger.warning(msg, *args)
    else:
        from galah_tpu.obs import events

        events.record("warn-once-suppressed", logger=logger.name,
                      message=msg % args if args else msg)


def reset_warn_once() -> None:
    """Forget emitted warnings (tests)."""
    with _WARN_ONCE_LOCK:
        _WARNED.clear()


def set_log_level(verbose: bool = False, quiet: bool = False) -> None:
    level = logging.INFO
    if verbose:
        level = logging.DEBUG
    if quiet:
        level = logging.ERROR
    logging.basicConfig(
        level=level,
        format="[%(asctime)s %(levelname)s %(name)s] %(message)s",
        datefmt="%Y-%m-%dT%H:%M:%S",
        force=True,
    )
