"""Per-stage timing + optional XLA profiler traces.

The reference has no profiling subsystem at all (SURVEY.md §5 —
observability is leveled logging only). Here every pipeline stage is
wrapped in a `stage(...)` span; spans nest, accumulate by name, and the
final report logs one line per stage so a 50k-genome run shows where
wall-clock went (sketching vs pairwise vs ANI refinement vs host
clustering).

`trace_context(dir)` additionally captures a TensorBoard-loadable XLA
profile via jax.profiler (device timelines, HLO cost, HBM traffic) when
the user passes --profile-trace-dir.
"""

from __future__ import annotations

import contextlib
import logging
import threading as _threading
import time
from typing import Dict, Iterator, List, Optional, Tuple

logger = logging.getLogger(__name__)


class StageTimer:
    """Accumulating named wall-clock spans (nesting allowed)."""

    def __init__(self) -> None:
        self._acc: Dict[str, float] = {}
        self._counts: Dict[str, int] = {}
        self._order: List[str] = []
        self._counters: Dict[str, int] = {}
        self._counter_order: List[str] = []
        self._t0 = time.perf_counter()
        self._active = _threading.local()
        self._lock = _threading.Lock()

    def _stack(self) -> List[str]:
        st = getattr(self._active, "stack", None)
        if st is None:
            st = self._active.stack = []
        return st

    def dispatch(self, n: int = 1, sync: bool = False) -> None:
        """Record `n` device dispatches (jit executions / uploads)
        attributed to the innermost active stage — with sync=True they
        are host materializations (each a device->host round trip).
        On a remote-attached device every round trip costs real RTT;
        these counters let the stage report show round trips alongside
        wall-clock, so dispatch-bound stages are visible as such."""
        st = self._stack()
        where = st[-1] if st else "?"
        self.counter(f"{'sync' if sync else 'disp'}[{where}]", n)

    def counter(self, name: str, delta: int) -> None:
        """Accumulate a named integer (work counts, waste counts, ...);
        counters appear at the end of the stage report."""
        with self._lock:  # dispatch counts arrive from worker threads
            if name not in self._counters:
                self._counters[name] = 0
                self._counter_order.append(name)
            self._counters[name] += int(delta)

    def counters(self) -> Dict[str, int]:
        return dict(self._counters)

    @contextlib.contextmanager
    def stage(self, name: str) -> Iterator[None]:
        start = time.perf_counter()
        self._stack().append(name)
        try:
            yield
        finally:
            self._stack().pop()
            dt = time.perf_counter() - start
            if name not in self._acc:
                self._acc[name] = 0.0
                self._counts[name] = 0
                self._order.append(name)
            self._acc[name] += dt
            self._counts[name] += 1
            logger.debug("stage %s: %.3fs", name, dt)

    def items(self) -> List[Tuple[str, float, int]]:
        return [(n, self._acc[n], self._counts[n]) for n in self._order]

    def report(self, log: Optional[logging.Logger] = None) -> str:
        log = log or logger
        total = time.perf_counter() - self._t0
        lines = []
        for name, acc, count in self.items():
            share = 100.0 * acc / total if total > 0 else 0.0
            suffix = f" x{count}" if count > 1 else ""
            lines.append(f"{name}: {acc:.2f}s ({share:.0f}%){suffix}")
        text = "; ".join(lines) + f"; total {total:.2f}s"
        if self._counters:
            text += "; " + "; ".join(
                f"{n}={self._counters[n]}" for n in self._counter_order)
        log.info("Stage timings: %s", text)
        return text


# Process-wide timer: backends and the engine record into this by
# default so the CLI gets a full report without threading a timer
# through every constructor.
GLOBAL = StageTimer()


def stage(name: str):
    return GLOBAL.stage(name)


def counter(name: str, delta: int) -> None:
    GLOBAL.counter(name, delta)


def dispatch(n: int = 1, sync: bool = False) -> None:
    GLOBAL.dispatch(n, sync=sync)


def reset() -> None:
    global GLOBAL
    GLOBAL = StageTimer()


@contextlib.contextmanager
def trace_context(trace_dir: Optional[str]) -> Iterator[None]:
    """jax.profiler trace of the enclosed block when trace_dir is set."""
    if not trace_dir:
        yield
        return
    import jax

    logger.info("Writing XLA profiler trace to %s", trace_dir)
    with jax.profiler.trace(trace_dir):
        yield
