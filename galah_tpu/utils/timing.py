"""Per-stage timing + optional XLA profiler traces.

The reference has no profiling subsystem at all (SURVEY.md §5 —
observability is leveled logging only). Here every pipeline stage is
wrapped in a `stage(...)` span; spans nest, accumulate by name, and the
final report logs one line per stage so a 50k-genome run shows where
wall-clock went (sketching vs pairwise vs ANI refinement vs host
clustering).

The StageTimer is the emission surface of the telemetry layer
(galah_tpu/obs/): every closed span also records into the stage
wall-clock TREE the run report serializes (obs/report.py) and, when a
trace recorder is active (--trace-events), lands as a Chrome-trace
span on the Perfetto timeline (obs/trace.py).

Worker-thread attribution: the active-stage stack is thread-local, but
dispatches can arrive from worker threads (IO prefetch pools, per-
genome sketching workers). A thread with an empty local stack inherits
the innermost stage any thread currently has open (the shared fallback
stack), and thread pools that want exact attribution capture
``stage_token()`` in the spawning thread and run workers under
``adopt(token)``.

`trace_context(dir)` additionally captures a TensorBoard-loadable XLA
profile via jax.profiler (device timelines, HLO cost, HBM traffic) when
the user passes --profile-trace-dir.
"""

from __future__ import annotations

import contextlib
import logging
import threading as _threading
import time
from typing import Dict, Iterator, List, Optional, Tuple

from galah_tpu.obs import trace as _obs_trace

logger = logging.getLogger(__name__)

# Concurrency contract, machine-checked by `galah-tpu lint` (GL8xx):
# every post-construction mutation of these must hold the timer's
# lock — spans close and counters arrive from prefetch/sketching
# worker threads. `_active` is thread-local by design and `_t0` is
# construction-only, so neither is locked shared state.
GUARDED_BY = {
    "StageTimer._acc": "StageTimer._lock",
    "StageTimer._counts": "StageTimer._lock",
    "StageTimer._order": "StageTimer._lock",
    "StageTimer._counters": "StageTimer._lock",
    "StageTimer._counter_order": "StageTimer._lock",
    "StageTimer._tree": "StageTimer._lock",
    "StageTimer._tree_order": "StageTimer._lock",
    "StageTimer._shared": "StageTimer._lock",
}
LOCK_ORDER = ["StageTimer._lock"]


class StageTimer:
    """Accumulating named wall-clock spans (nesting allowed)."""

    def __init__(self) -> None:
        self._acc: Dict[str, float] = {}
        self._counts: Dict[str, int] = {}
        self._order: List[str] = []
        self._counters: Dict[str, int] = {}
        self._counter_order: List[str] = []
        # wall-clock tree: stage path tuple -> [seconds, count], in
        # first-appearance order (the run report serializes this)
        self._tree: Dict[Tuple[str, ...], List[float]] = {}
        self._tree_order: List[Tuple[str, ...]] = []
        self._t0 = time.perf_counter()
        self._active = _threading.local()
        # Shared fallback stack: mirrors every open stage across ALL
        # threads so dispatch() from a bare worker thread (whose
        # thread-local stack is empty) inherits the spawning stage
        # instead of landing under "?".
        self._shared: List[str] = []
        self._lock = _threading.Lock()

    def _stack(self) -> List[str]:
        st = getattr(self._active, "stack", None)
        if st is None:
            st = self._active.stack = []
        return st

    def current_stage(self) -> Optional[str]:
        """Innermost stage for THIS thread, falling back to the
        innermost stage open on any thread."""
        st = self._stack()
        if st:
            return st[-1]
        with self._lock:
            return self._shared[-1] if self._shared else None

    def stage_token(self) -> Tuple[str, ...]:
        """Capture the current stage path for a worker thread to
        `adopt` — the pass-through form of worker attribution (the
        shared-stack fallback is the implicit one)."""
        st = self._stack()
        if st:
            return tuple(st)
        with self._lock:
            return tuple(self._shared[-1:])

    @contextlib.contextmanager
    def adopt(self, token: Tuple[str, ...]) -> Iterator[None]:
        """Run this thread with `token` as its stage context; restores
        the thread's own stack on exit."""
        st = self._stack()
        saved = st[:]
        st[:] = list(token)
        try:
            yield
        finally:
            st[:] = saved

    def dispatch(self, n: int = 1, sync: bool = False) -> None:
        """Record `n` device dispatches (jit executions / uploads)
        attributed to the innermost active stage — with sync=True they
        are host materializations (each a device->host round trip).
        On a remote-attached device every round trip costs real RTT;
        these counters let the stage report show round trips alongside
        wall-clock, so dispatch-bound stages are visible as such."""
        where = self.current_stage() or "?"
        self.counter(f"{'sync' if sync else 'disp'}[{where}]", n)

    def counter(self, name: str, delta: int) -> None:
        """Accumulate a named integer (work counts, waste counts, ...);
        counters appear at the end of the stage report."""
        with self._lock:  # dispatch counts arrive from worker threads
            if name not in self._counters:
                self._counters[name] = 0
                self._counter_order.append(name)
            self._counters[name] += int(delta)

    def counters(self) -> Dict[str, int]:
        return dict(self._counters)

    @contextlib.contextmanager
    def stage(self, name: str) -> Iterator[None]:
        start = time.perf_counter()
        st = self._stack()
        st.append(name)
        with self._lock:
            self._shared.append(name)
        try:
            yield
        finally:
            st.pop()
            path = tuple(st) + (name,)
            with self._lock:
                # drop the most recent matching entry — concurrent
                # stages on other threads may have pushed above it
                for k in range(len(self._shared) - 1, -1, -1):
                    if self._shared[k] == name:
                        del self._shared[k]
                        break
            dt = time.perf_counter() - start
            with self._lock:
                if name not in self._acc:
                    self._acc[name] = 0.0
                    self._counts[name] = 0
                    self._order.append(name)
                self._acc[name] += dt
                self._counts[name] += 1
                if path not in self._tree:
                    self._tree[path] = [0.0, 0]
                    self._tree_order.append(path)
                self._tree[path][0] += dt
                self._tree[path][1] += 1
            _obs_trace.emit_complete(name, start, dt, cat="stage")
            logger.debug("stage %s: %.3fs", name, dt)

    def items(self) -> List[Tuple[str, float, int]]:
        return [(n, self._acc[n], self._counts[n]) for n in self._order]

    def elapsed(self) -> float:
        """Wall-clock seconds since this timer was created/reset."""
        return time.perf_counter() - self._t0

    def tree(self) -> List[dict]:
        """The nested stage wall-clock tree, JSON-ready: each node is
        {name, total_s, count, children}, in first-entry order."""
        with self._lock:
            paths = list(self._tree_order)
            data = {p: tuple(v) for p, v in self._tree.items()}
        nodes: Dict[Tuple[str, ...], dict] = {}
        roots: List[dict] = []

        def node_for(path: Tuple[str, ...]) -> dict:
            # Inner stages close (and register) before their parents,
            # so a parent may not exist yet when its child arrives:
            # create it on demand — its totals are in `data` already
            # if it ever closed, zero if it is still open (crash).
            node = nodes.get(path)
            if node is None:
                acc, count = data.get(path, (0.0, 0))
                node = {"name": path[-1], "total_s": round(acc, 6),
                        "count": count, "children": []}
                nodes[path] = node
                if len(path) == 1:
                    roots.append(node)
                else:
                    node_for(path[:-1])["children"].append(node)
            return node

        for path in paths:
            node_for(path)
        return roots

    def report(self, log: Optional[logging.Logger] = None) -> str:
        log = log or logger
        total = self.elapsed()
        lines = []
        for name, acc, count in self.items():
            share = 100.0 * acc / total if total > 0 else 0.0
            suffix = f" x{count}" if count > 1 else ""
            lines.append(f"{name}: {acc:.2f}s ({share:.0f}%){suffix}")
        text = "; ".join(lines) + f"; total {total:.2f}s"
        if self._counters:
            text += "; " + "; ".join(
                f"{n}={self._counters[n]}" for n in self._counter_order)
        log.info("Stage timings: %s", text)
        return text


# Process-wide timer: backends and the engine record into this by
# default so the CLI gets a full report without threading a timer
# through every constructor.
GLOBAL = StageTimer()


def stage(name: str):
    return GLOBAL.stage(name)


def counter(name: str, delta: int) -> None:
    GLOBAL.counter(name, delta)


def dispatch(n: int = 1, sync: bool = False) -> None:
    GLOBAL.dispatch(n, sync=sync)


def stage_token() -> Tuple[str, ...]:
    return GLOBAL.stage_token()


def adopt(token: Tuple[str, ...]):
    return GLOBAL.adopt(token)


def reset() -> None:
    global GLOBAL
    GLOBAL = StageTimer()


@contextlib.contextmanager
def trace_context(trace_dir: Optional[str]) -> Iterator[None]:
    """jax.profiler trace of the enclosed block when trace_dir is set."""
    if not trace_dir:
        yield
        return
    import jax

    logger.info("Writing XLA profiler trace to %s", trace_dir)
    with jax.profiler.trace(trace_dir):
        yield
