"""Genome quality: parsers, formulas, filtering, and ordering.

Covers the reference's quality layer (reference:
src/cluster_argument_parsing.rs:576-894 plus the checkm crate surface it
consumes, and src/genome_info_file.rs:20-80):

  * three input formats — CheckM1 tab table, CheckM2 quality report,
    dRep-style genomeInfo CSV — all keyed by FASTA basename stem;
  * completeness/contamination stored as fractions (inputs are 0-100);
  * min-completeness / max-contamination filtering;
  * four quality formulas ordering genomes descending:
      - Parks2020_reduced (default):
          comp*100 - 5*cont*100 - 5*num_contigs/100 - 5*num_ambiguous/1e5
      - completeness-4contamination: comp - 4*cont
      - completeness-5contamination: comp - 5*cont
      - dRep: comp*100 - 5*cont*100 + cont*strain_het + 0.5*log10(N50)
        (CheckM1 only — needs strain heterogeneity;
         reference: src/cluster_argument_parsing.rs:780-812)

Ties keep input order (stable sort), matching the reference's stable
`sort_by` on the appraisal list.
"""

from __future__ import annotations

import csv
import dataclasses
import logging
import math
import os
from typing import Callable, Dict, List, Optional, Sequence

from galah_tpu.io.fasta import GenomeStats, calculate_genome_stats

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class GenomeQuality:
    completeness: float                # fraction 0-1
    contamination: float               # fraction 0-1
    strain_heterogeneity: Optional[float] = None  # raw 0-100, CheckM1 only


QualityTable = Dict[str, GenomeQuality]


def fasta_stem(path: str) -> str:
    """Basename minus the last extension — the quality-table key
    (mirrors the checkm crate's retrieve_via_fasta_path)."""
    return os.path.splitext(os.path.basename(path))[0]


def _read_quality_tsv(path: str, kind: str, name_header: str,
                      het_header: Optional[str]) -> QualityTable:
    """Shared TSV quality-table reader: columns by header name, duplicate
    genome names rejected, percentages stored as fractions."""
    out: QualityTable = {}
    with open(path, newline="") as fh:
        reader = csv.reader(fh, delimiter="\t")
        header = next(reader, None)
        if header is None:
            raise ValueError(f"empty {kind} {path}")
        try:
            name_col = header.index(name_header)
            comp_col = header.index("Completeness")
            cont_col = header.index("Contamination")
        except ValueError as e:
            raise ValueError(
                f"malformed {kind} header in {path}: {e}") from e
        het_col = (header.index(het_header)
                   if het_header and het_header in header else None)
        min_cols = max(name_col, comp_col, cont_col,
                       het_col if het_col is not None else 0) + 1
        for row in reader:
            if not row:
                continue
            if len(row) < min_cols:
                raise ValueError(
                    f"malformed {kind} row in {path}: expected at least "
                    f"{min_cols} columns, got {len(row)}: {row!r}")
            name = row[name_col]
            if name in out:
                raise ValueError(
                    f"The genome {name} was found multiple times in the "
                    f"checkm file {path}")
            out[name] = GenomeQuality(
                completeness=float(row[comp_col]) / 100.0,
                contamination=float(row[cont_col]) / 100.0,
                strain_heterogeneity=(
                    float(row[het_col]) if het_col is not None else None),
            )
    logger.debug("Read %d genomes from %s", len(out), path)
    return out


def read_checkm1_tab_table(path: str) -> QualityTable:
    """CheckM v1 `checkm qa` tab table: columns located by header name
    (Bin Id / Completeness / Contamination / Strain heterogeneity)."""
    return _read_quality_tsv(path, "CheckM tab table", "Bin Id",
                             "Strain heterogeneity")


def read_checkm2_quality_report(path: str) -> QualityTable:
    """CheckM2 quality_report.tsv: Name / Completeness / Contamination."""
    return _read_quality_tsv(path, "CheckM2 quality report", "Name", None)


def read_genome_info_file(path: str) -> QualityTable:
    """dRep-style CSV: exactly genome,completeness,contamination headers
    (reference: src/genome_info_file.rs:20-80)."""
    out: QualityTable = {}
    with open(path, newline="") as fh:
        reader = csv.reader(fh)
        header = next(reader, None)
        if header != ["genome", "completeness", "contamination"]:
            raise ValueError("Incorrect headers found in genomeInfo file")
        for row in reader:
            if not row:
                continue
            if len(row) != 3:
                raise ValueError(
                    "Parsing error in genomeInfo file - didn't find 3 "
                    f"columns in line {row!r}")
            name = row[0]
            if name in out:
                raise ValueError(
                    f"The genome {name} was found multiple times in the "
                    f"checkm file {path}")
            out[name] = GenomeQuality(
                completeness=float(row[1]) / 100.0,
                contamination=float(row[2]) / 100.0,
            )
    return out


def retrieve(table: QualityTable, fasta_path: str) -> GenomeQuality:
    stem = fasta_stem(fasta_path)
    try:
        return table[stem]
    except KeyError:
        raise KeyError(
            f"Failed to find CheckM statistics for {fasta_path}") from None


def filter_and_order_genomes(
    genome_paths: Sequence[str],
    table: QualityTable,
    formula: str = "Parks2020_reduced",
    min_completeness: Optional[float] = None,   # fraction
    max_contamination: Optional[float] = None,  # fraction
    stats_fn: Callable[[str], GenomeStats] = calculate_genome_stats,
    threads: int = 1,
) -> List[str]:
    """Filter by quality thresholds, then order descending by formula.

    `stats_fn` computes assembly stats for the formulas that need them
    (Parks2020_reduced, dRep); injectable for tests. With threads > 1,
    stats are computed concurrently (the reference fans this out over its
    rayon pool, reference: src/cluster_argument_parsing.rs:853-894).
    """
    kept: List[str] = []
    for p in genome_paths:
        q = retrieve(table, p)
        if min_completeness is not None and q.completeness < min_completeness:
            continue
        if max_contamination is not None and q.contamination > max_contamination:
            continue
        kept.append(p)

    def map_stats(paths: Sequence[str]) -> List[GenomeStats]:
        if threads > 1 and len(paths) > 1:
            from concurrent.futures import ThreadPoolExecutor

            with ThreadPoolExecutor(max_workers=threads) as pool:
                return list(pool.map(stats_fn, paths))
        return [stats_fn(p) for p in paths]

    stats_cache: Dict[str, GenomeStats] = {}
    needs_stats = formula in ("Parks2020_reduced", "dRep")
    if needs_stats and kept:
        from galah_tpu.parallel import distributed

        if distributed.process_count() > 1:
            # Host-split the stats pass (it reads every FASTA): each
            # host stats its strided shard, the 3-int rows are
            # exchanged, and every host ranks identically. A failing
            # host propagates through the status exchange instead of
            # stranding its peers inside the allgather.
            import numpy as np

            mine = distributed.host_shard(kept)
            err = None
            local_stats: List[GenomeStats] = []
            try:
                local_stats = map_stats(mine)
            except Exception as e:  # noqa: BLE001 - re-raised below
                err = e
            distributed.raise_if_any_host_failed(err)
            local = np.array(
                [[s.num_contigs, s.num_ambiguous_bases, s.n50]
                 for s in local_stats],
                dtype=np.int64).reshape(len(mine), 3)
            full = distributed.allgather_host_rows(
                len(kept), local, fill=np.int64(0))
            for i, p in enumerate(kept):
                stats_cache[p] = GenomeStats(
                    num_contigs=int(full[i, 0]),
                    num_ambiguous_bases=int(full[i, 1]),
                    n50=int(full[i, 2]))
        elif threads > 1:
            for p, s in zip(kept, map_stats(kept)):
                stats_cache[p] = s

    def get_stats(p: str) -> GenomeStats:
        if p not in stats_cache:
            stats_cache[p] = stats_fn(p)
        return stats_cache[p]

    def score(p: str) -> float:
        q = retrieve(table, p)
        if formula == "completeness-4contamination":
            return q.completeness - 4.0 * q.contamination
        if formula == "completeness-5contamination":
            return q.completeness - 5.0 * q.contamination
        if formula == "Parks2020_reduced":
            s = get_stats(p)
            return (q.completeness * 100.0
                    - 5.0 * q.contamination * 100.0
                    - 5.0 * s.num_contigs / 100.0
                    - 5.0 * s.num_ambiguous_bases / 100000.0)
        if formula == "dRep":
            if q.strain_heterogeneity is None:
                raise ValueError(
                    "dRep quality formula only works with CheckM v1 "
                    "quality scoring since it includes strain heterogeneity")
            s = get_stats(p)
            return (q.completeness * 100.0
                    - 5.0 * q.contamination * 100.0
                    + q.contamination * q.strain_heterogeneity
                    + 0.5 * math.log10(max(s.n50, 1)))
        raise ValueError(f"unknown quality formula {formula!r}")

    scored = [(p, score(p)) for p in kept]
    scored.sort(key=lambda t: -t[1])  # stable: ties keep input order
    logger.info(
        "Read in genome qualities for %d genomes. %d passed quality "
        "thresholds", len(table), len(scored))
    return [p for p, _ in scored]
