"""Persistent, versioned on-disk sketch index (the serving layer's state).

Layout under one single-owner directory (docs/index.md has the format
spec; every byte goes through io/atomic.py, enforced by GL806):

  fingerprint.json    immutable sketch/threshold parameters + digest —
                      written once by ``index build``; every later open
                      verifies it (an index is data, never silently
                      wiped on mismatch, unlike a checkpoint)
  genomes.jsonl       append-only framed records {i, path, key}; ``key``
                      is the same (path, size, mtime_ns, kind, params)
                      sha256 identity the disk cache keys entries by
  sketches.jsonl      append-only framed records {i, hashes} — the
                      bottom-k MinHash hashes, so reopening the index
                      never re-reads a FASTA
  pairs.jsonl         append-only framed records {i, j, ani}: every
                      sketch-ANI pair at or above the precluster
                      threshold among indexed genomes
  gen-NNNNNN.json     one generation manifest: committed log lengths,
                      representatives, memberships, tombstones
  MANIFEST.json       the commit pointer {generation: N} — readers load
                      exactly the state it names; log bytes past the
                      committed lengths are an uncommitted tail
  interruptions.jsonl preemption chain (non-authoritative; excluded
                      from byte-identity comparisons)

Crash discipline: log appends are durable per record (append_jsonl
fsyncs), a generation commits by writing gen-N.json then swapping
MANIFEST.json — both atomic whole-file replaces. A writer killed at ANY
instant leaves the index loadable at the prior generation; the next
mutating open truncates the uncommitted log tails (single-owner
directory, like a checkpoint dir), so an interrupted-then-resumed
mutation converges to the exact bytes an uninterrupted one writes.

No timestamps live in any committed file for the same reason — two
runs that perform the same mutation must produce identical bytes.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import logging
import os
import threading
import zlib
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from galah_tpu.io import atomic

logger = logging.getLogger(__name__)

INDEX_FORMAT = "galah-tpu-index"
INDEX_VERSION = 1

_FINGERPRINT = "fingerprint.json"
_MANIFEST = "MANIFEST.json"
_GENOMES = "genomes.jsonl"
_SKETCHES = "sketches.jsonl"
_PAIRS = "pairs.jsonl"
_INTERRUPTIONS = "interruptions.jsonl"

# Concurrency contract, machine-checked by `galah-tpu lint` (GL8xx) and
# the runtime sanitizer (GALAH_SAN): the in-memory state cache is read
# and replaced under the store lock, so a query service may share one
# IndexStore across threads.
GUARDED_BY = {"IndexStore._state": "IndexStore._lock"}
LOCK_ORDER = ["IndexStore._lock"]


class IndexCorrupt(ValueError):
    """Committed index state failed validation (see `fsck`)."""


def index_params(ani: float, precluster_ani: float, sketch_size: int,
                 k: int, seed: int, algo: str) -> Dict[str, Any]:
    """The semantic parameter set an index is bound to.

    Deliberately excludes the tool version: an index is a persistent
    artifact, and sketches/ANIs are bit-stable contracts (the golden
    oracle tests pin them), so upgrades must not orphan it. Thresholds
    are fractions in [0, 1].
    """
    return {
        "method": "finch",
        "ani": float(ani),
        "precluster_ani": float(precluster_ani),
        "sketch_size": int(sketch_size),
        "k": int(k),
        "seed": int(seed),
        "algo": str(algo),
    }


def params_digest(params: Dict[str, Any]) -> str:
    return hashlib.sha256(
        json.dumps(params, sort_keys=True).encode()).hexdigest()


def genome_key(path: str, sketch_params: Dict[str, Any]) -> str:
    """Content-hash identity of one genome record — the SAME
    (path, size, mtime_ns, kind, params) sha256 scheme the disk cache
    names its entries with (io/diskcache.py ``_entry_path``), so an
    index record and the cache entry for the same sketch share a key."""
    st = os.stat(path)
    ident = json.dumps({
        "path": os.path.abspath(path),
        "size": st.st_size,
        "mtime_ns": st.st_mtime_ns,
        "kind": "minhash",
        "params": {k: sketch_params[k] for k in sorted(sketch_params)},
    }, sort_keys=True)
    return hashlib.sha256(ident.encode()).hexdigest()[:32]


def _gen_name(generation: int) -> str:
    return f"gen-{generation:06d}.json"


@dataclasses.dataclass
class IndexState:
    """One committed generation, fully materialized."""

    generation: int
    genomes: List[str]                      # paths, greedy order
    keys: List[str]                         # content-hash per genome
    # uint64 bottom-k hashes; a PagedSketchList above the out-of-core
    # threshold (list-compatible, rows served from the mmap pagestore)
    sketches: "List[np.ndarray]"
    pairs: Dict[Tuple[int, int], float]     # i<j, precluster-hit ANIs
    reps: List[int]                         # sorted ascending, live
    membership: Dict[int, int]              # live non-rep -> its rep
    tombstones: Set[int]

    @property
    def n_genomes(self) -> int:
        return len(self.genomes)

    @property
    def live(self) -> List[int]:
        return [g for g in range(len(self.genomes))
                if g not in self.tombstones]


def _empty_state() -> IndexState:
    return IndexState(generation=0, genomes=[], keys=[], sketches=[],
                      pairs={}, reps=[], membership={}, tombstones=set())


class PagedSketchList:
    """List-compatible facade over an mmap-backed page store
    (io/pagestore.py): ``[i]`` / ``append`` / ``len`` / iteration —
    exactly the surface IndexState.sketches consumers use — while
    only the LRU-budgeted resident page set occupies RAM, so `index
    build/insert` inherit the out-of-core bound (docs/memory.md).
    Reads hand back zero-copy views of the true (unpadded) hash
    arrays, bit-identical to the materialized list."""

    def __init__(self, pagestore) -> None:
        self._ps = pagestore

    def __len__(self) -> int:
        return len(self._ps)

    def __getitem__(self, i):
        n = len(self._ps)
        if isinstance(i, slice):
            return [self[j] for j in range(*i.indices(n))]
        if i < 0:
            i += n
        return self._ps.hashes(i)

    def append(self, hashes) -> None:
        self._ps.append("", np.asarray(hashes, dtype=np.uint64))

    def __iter__(self):
        for i in range(len(self._ps)):
            yield self._ps.hashes(i)


def _paged_sketch_spill(n_genomes: int, sketch_size: int):
    """A fresh pagestore-backed sketch list when the out-of-core tier
    engages for this index size, else None (plain list loading)."""
    import atexit
    import shutil
    import tempfile

    from galah_tpu.io import pagestore as pagestore_mod

    if not pagestore_mod.pagestore_engaged(n_genomes, sketch_size):
        return None
    d = tempfile.mkdtemp(prefix="galah-index-pages-")
    atexit.register(shutil.rmtree, d, ignore_errors=True)
    return PagedSketchList(
        pagestore_mod.SketchPageStore(d, cols=sketch_size))


def _valid_frames(path: str) -> List[bytes]:
    """Raw bytes of each checksum-valid framed line, in file order.

    The byte-level twin of atomic.read_jsonl: truncation must preserve
    the exact committed bytes, not re-serialize them.
    """
    if not os.path.exists(path):
        return []
    out: List[bytes] = []
    with open(path, "rb") as fh:
        for raw in fh:
            line = raw.rstrip(b"\r\n")
            if not line.strip():
                continue
            payload, sep, crc_hex = line.rpartition(
                atomic.FRAME_SEP.encode())
            if not sep:
                continue
            try:
                want = int(crc_hex, 16)
            except ValueError:
                continue
            if (zlib.crc32(payload) & 0xFFFFFFFF) != want:
                continue
            out.append(payload + sep + crc_hex + b"\n")
    return out


class IndexStore:
    """One index directory: committed-state loader + durable writer.

    Single-owner, like a checkpoint dir: opening for mutation sweeps
    ``*.tmp`` debris and truncates uncommitted log tails, so every
    mutation starts from exactly the committed state.
    """

    def __init__(self, path: str,
                 params: Optional[Dict[str, Any]] = None,
                 create: bool = False) -> None:
        self.path = os.path.abspath(path)
        self._lock = threading.Lock()
        self._state: Optional[IndexState] = None
        fp_file = os.path.join(self.path, _FINGERPRINT)
        if create:
            if params is None:
                raise ValueError("creating an index requires params")
            os.makedirs(self.path, exist_ok=True)
            atomic.sweep_tmp(self.path)
            if os.path.exists(fp_file):
                stored = self._read_fingerprint()
                if stored["params"] != params:
                    diffs = [k for k in sorted(set(stored["params"])
                                               | set(params))
                             if stored["params"].get(k) != params.get(k)]
                    raise ValueError(
                        f"index at {self.path} was built with different "
                        f"parameters (mismatched: {', '.join(diffs)}); "
                        "delete the directory to rebuild")
            else:
                atomic.write_json(
                    fp_file,
                    {"format": INDEX_FORMAT, "version": INDEX_VERSION,
                     "params": params,
                     "digest": params_digest(params)},
                    indent=1, site="io.atomic.write[index.fingerprint]")
            self.params = params
            return
        if not os.path.exists(fp_file):
            raise ValueError(
                f"no index at {self.path} (missing {_FINGERPRINT}); "
                "run `galah-tpu index build` first")
        stored = self._read_fingerprint()
        if params is not None and stored["params"] != params:
            raise ValueError(
                f"index at {self.path} was built with different "
                "parameters; delete the directory to rebuild")
        self.params = stored["params"]

    def _read_fingerprint(self) -> Dict[str, Any]:
        fp_file = os.path.join(self.path, _FINGERPRINT)
        try:
            with open(fp_file) as f:
                stored = json.load(f)
        except (OSError, ValueError) as e:
            raise IndexCorrupt(
                f"unreadable index fingerprint at {fp_file}: {e}")
        if stored.get("format") != INDEX_FORMAT:
            raise IndexCorrupt(
                f"{fp_file} is not a {INDEX_FORMAT} fingerprint")
        if stored.get("digest") != params_digest(stored.get("params",
                                                            {})):
            raise IndexCorrupt(
                f"index fingerprint digest mismatch at {fp_file}")
        return stored

    @property
    def sketch_params(self) -> Dict[str, Any]:
        return {"sketch_size": self.params["sketch_size"],
                "k": self.params["k"], "seed": self.params["seed"],
                "algo": self.params["algo"]}

    # -- committed-state loader ---------------------------------------

    def generation(self) -> int:
        """The committed generation (0 = built but never committed)."""
        mf = os.path.join(self.path, _MANIFEST)
        if not os.path.exists(mf):
            return 0
        try:
            with open(mf) as f:
                manifest = json.load(f)
        except (OSError, ValueError) as e:
            raise IndexCorrupt(f"unreadable {mf}: {e}")
        gen = int(manifest.get("generation", 0))
        if gen < 1:
            raise IndexCorrupt(f"{mf} names invalid generation {gen}")
        return gen

    def load(self) -> IndexState:
        """The state MANIFEST.json points at (cached; see `reload`)."""
        with self._lock:
            if self._state is None:
                self._state = self._load_generation(self.generation())
            return self._state

    def reload(self) -> IndexState:
        """Drop the cache and re-read the committed state (stale
        readers pick up a newer generation this way)."""
        with self._lock:
            self._state = None
        return self.load()

    def _gen_manifest(self, generation: int) -> Dict[str, Any]:
        gf = os.path.join(self.path, _gen_name(generation))
        try:
            with open(gf) as f:
                return json.load(f)
        except (OSError, ValueError) as e:
            raise IndexCorrupt(
                f"generation manifest {gf} unreadable: {e}")

    def _load_generation(self, generation: int) -> IndexState:
        if generation == 0:
            return _empty_state()
        gen = self._gen_manifest(generation)
        n_genomes = int(gen["n_genomes"])
        n_pairs = int(gen["n_pairs"])

        grecs = self._committed(_GENOMES, n_genomes)
        srecs = self._committed(_SKETCHES, n_genomes)
        precs = self._committed(_PAIRS, n_pairs)

        genomes, keys = [], []
        # Out-of-core tier: above the paging threshold the parsed
        # sketch rows spill straight to an mmap-backed page store
        # instead of accumulating as N resident arrays; the facade is
        # list-compatible so every consumer is unchanged.
        spill = _paged_sketch_spill(
            n_genomes, int(self.params["sketch_size"]))
        sketches = spill if spill is not None else []
        for n, (g, s) in enumerate(zip(grecs, srecs)):
            if int(g["i"]) != n or int(s["i"]) != n:
                raise IndexCorrupt(
                    f"genome/sketch record {n} carries index "
                    f"{g['i']}/{s['i']}")
            genomes.append(g["path"])
            keys.append(g["key"])
            sketches.append(np.asarray(s["hashes"], dtype=np.uint64))
        pairs: Dict[Tuple[int, int], float] = {}
        for p in precs:
            i, j = int(p["i"]), int(p["j"])
            if not 0 <= i < j < n_genomes:
                raise IndexCorrupt(
                    f"pair record ({i}, {j}) out of range "
                    f"(n_genomes={n_genomes})")
            pairs[(i, j)] = float(p["ani"])
        return IndexState(
            generation=generation, genomes=genomes, keys=keys,
            sketches=sketches, pairs=pairs,
            reps=sorted(int(r) for r in gen["reps"]),
            membership={int(k): int(v)
                        for k, v in gen["membership"].items()},
            tombstones={int(t) for t in gen["tombstones"]})

    def _committed(self, name: str, count: int) -> List[Any]:
        """The first `count` records of a log — the committed region.
        Anything past it is an uncommitted tail and is ignored here."""
        fn = os.path.join(self.path, name)
        records, bad = atomic.read_jsonl(fn)
        if len(records) < count:
            raise IndexCorrupt(
                f"{fn} holds {len(records)} intact record(s) but the "
                f"committed generation requires {count}")
        if bad:
            # torn frames can only be uncommitted-tail debris (the
            # committed region was fsynced before its commit); the next
            # mutation truncates them
            logger.debug("%s: %d torn frame(s) past the committed "
                         "region", fn, bad)
        return records[:count]

    # -- mutation: tail truncation, appends, commit -------------------

    def begin_mutation(self) -> IndexState:
        """Open for writing: sweep tmp debris, truncate every log to
        its committed length, and return the committed state."""
        atomic.sweep_tmp(self.path)
        gen = self.generation()
        counts = {_GENOMES: 0, _SKETCHES: 0, _PAIRS: 0}
        if gen:
            m = self._gen_manifest(gen)
            counts[_GENOMES] = counts[_SKETCHES] = int(m["n_genomes"])
            counts[_PAIRS] = int(m["n_pairs"])
        for name, count in counts.items():
            self._truncate(name, count)
        # drop committed-but-orphaned future generation manifests a
        # kill between gen-write and MANIFEST-swap left behind
        for fn in os.listdir(self.path):
            if fn.startswith("gen-") and fn.endswith(".json"):
                try:
                    g = int(fn[4:-5])
                except ValueError:
                    continue
                if g > gen:
                    os.unlink(os.path.join(self.path, fn))
        with self._lock:
            self._state = None
        return self.load()

    def _truncate(self, name: str, count: int) -> None:
        fn = os.path.join(self.path, name)
        if not os.path.exists(fn):
            if count:
                raise IndexCorrupt(
                    f"{fn} is missing but the committed generation "
                    f"requires {count} record(s)")
            return
        frames = _valid_frames(fn)
        if len(frames) < count:
            raise IndexCorrupt(
                f"{fn} holds {len(frames)} intact record(s) but the "
                f"committed generation requires {count}")
        want = b"".join(frames[:count])
        with open(fn, "rb") as f:
            have = f.read()
        if have == want:
            return
        logger.info("Discarding uncommitted tail of %s (%d committed "
                    "record(s) kept)", fn, count)
        atomic.write_bytes(fn, want,
                           site="io.atomic.write[index.truncate]")

    def append_genome(self, i: int, path: str, key: str) -> None:
        atomic.append_jsonl(
            os.path.join(self.path, _GENOMES),
            {"i": i, "path": os.path.abspath(path), "key": key},
            site="io.atomic.append[index.genomes]")

    def append_sketch(self, i: int, hashes: np.ndarray) -> None:
        atomic.append_jsonl(
            os.path.join(self.path, _SKETCHES),
            {"i": i, "hashes": [int(h) for h in hashes]},
            site="io.atomic.append[index.sketches]")

    def append_pairs(
            self, pairs: Sequence[Tuple[int, int, float]]) -> None:
        fn = os.path.join(self.path, _PAIRS)
        for i, j, ani in pairs:
            atomic.append_jsonl(fn, {"i": int(i), "j": int(j),
                                     "ani": float(ani)},
                                site="io.atomic.append[index.pairs]")

    def commit(self, state: IndexState) -> int:
        """Commit `state` as the next generation: write its manifest,
        then swap the MANIFEST pointer (the atomic commit point)."""
        generation = self.generation() + 1
        gen = {
            "generation": generation,
            "n_genomes": len(state.genomes),
            "n_pairs": len(state.pairs),
            "reps": sorted(state.reps),
            "membership": {str(k): int(v) for k, v in
                           sorted(state.membership.items())},
            "tombstones": sorted(state.tombstones),
        }
        atomic.write_json(
            os.path.join(self.path, _gen_name(generation)), gen,
            indent=1, site="io.atomic.write[index.generation]")
        atomic.write_json(
            os.path.join(self.path, _MANIFEST),
            {"format": INDEX_FORMAT, "version": INDEX_VERSION,
             "generation": generation},
            indent=1, site="io.atomic.write[index.manifest]")
        state.generation = generation
        with self._lock:
            self._state = state
        return generation

    # -- interruption / resume chain ----------------------------------

    def record_interruption(self, info: Dict[str, Any]) -> None:
        atomic.append_jsonl(
            os.path.join(self.path, _INTERRUPTIONS), info,
            site="io.atomic.append[index.interrupts]")

    def load_interruptions(self) -> List[Dict[str, Any]]:
        records, bad = atomic.read_jsonl(
            os.path.join(self.path, _INTERRUPTIONS))
        if bad:
            logger.warning("Dropped %d torn interruption record(s) in "
                           "%s", bad, self.path)
        return records


# -- fsck --------------------------------------------------------------


def fsck(path: str) -> Dict[str, Any]:
    """Structural audit of an index directory; never mutates it.

    Returns {"ok", "problems", "warnings", "generation", ...}. Torn or
    extra records PAST the committed lengths are warnings (a killed
    writer's uncommitted tail — the next mutation discards them);
    anything wrong INSIDE the committed state is a problem.
    """
    path = os.path.abspath(path)
    problems: List[str] = []
    warnings: List[str] = []
    out: Dict[str, Any] = {"path": path, "ok": False,
                           "problems": problems, "warnings": warnings,
                           "generation": None, "genomes": 0,
                           "clusters": 0, "tombstones": 0, "pairs": 0}
    try:
        store = IndexStore(path)
    except (ValueError, IndexCorrupt) as e:
        problems.append(str(e))
        return out
    tmp = [f for f in os.listdir(path) if f.endswith(".tmp")]
    if tmp:
        warnings.append(f"{len(tmp)} .tmp debris file(s) "
                        "(sweep happens at next mutating open)")
    try:
        gen = store.generation()
    except IndexCorrupt as e:
        problems.append(str(e))
        return out
    out["generation"] = gen
    try:
        state = store.load()
    except IndexCorrupt as e:
        problems.append(str(e))
        return out
    # uncommitted tails + torn frames, per log
    for name, committed in ((_GENOMES, state.n_genomes),
                            (_SKETCHES, state.n_genomes),
                            (_PAIRS, len(state.pairs))):
        fn = os.path.join(path, name)
        records, bad = atomic.read_jsonl(fn)
        extra = len(records) - committed
        if extra:
            warnings.append(f"{name}: {extra} uncommitted tail "
                            "record(s)")
        if bad:
            warnings.append(f"{name}: {bad} torn/corrupt frame(s) "
                            "past the committed region")
    for fn in os.listdir(path):
        if fn.startswith("gen-") and fn.endswith(".json"):
            try:
                g = int(fn[4:-5])
            except ValueError:
                problems.append(f"unparseable generation file {fn}")
                continue
            if g > gen:
                warnings.append(f"orphan generation manifest {fn} "
                                "(commit pointer never reached it)")
    # decision-state invariants
    live = set(state.live)
    rep_set = set(state.reps)
    if not rep_set <= live:
        problems.append("representatives include tombstoned genomes")
    for g, r in state.membership.items():
        if g not in live:
            problems.append(f"membership recorded for dead genome {g}")
        if r not in rep_set:
            problems.append(
                f"genome {g} assigned to non-representative {r}")
        if g in rep_set:
            problems.append(f"representative {g} also has a "
                            "membership record")
    assigned = rep_set | set(state.membership)
    if gen and assigned != live:
        missing = sorted(live - assigned)[:5]
        extra_m = sorted(assigned - live)[:5]
        if missing:
            problems.append(f"live genomes without an assignment: "
                            f"{missing}")
        if extra_m:
            problems.append(f"assignments for unknown genomes: "
                            f"{extra_m}")
    for i, s in enumerate(state.sketches):
        # direct comparison: uint64 diff would wrap on out-of-order
        if s.size > 1 and not bool(np.all(s[1:] > s[:-1])):
            problems.append(f"sketch {i} is not sorted-distinct")
    out.update(genomes=len(live), clusters=len(state.reps),
               tombstones=len(state.tombstones),
               pairs=len(state.pairs))
    out["ok"] = not problems
    return out
