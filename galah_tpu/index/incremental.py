"""Incremental update engine over the persistent sketch index.

The soundness argument, in one place: with the MinHash preclusterer and
the sketch-ANI clusterer sharing a method (the engine's
``skip_clusterer`` path), every greedy decision the cluster engine
makes is served from the precluster pair cache — genome ``i`` is a
representative iff no earlier representative with a cached pair has
ANI >= threshold (cluster/engine.py ``_find_representatives``), and a
non-representative joins the argmax-ANI representative with ties to the
lowest index (``_find_memberships``). Decisions are therefore pure
functions of (greedy genome order, thresholded pair set). The index
persists exactly those two things, so:

  * *insert* appends new genomes AFTER every existing one in the greedy
    order. Existing genomes' representative decisions only ever looked
    at lower indices — they are untouched — and each new genome needs
    only its own pairs, screened against representatives first
    (rep-first screening is sound precisely because of the greedy
    order). The only existing state that can change is membership:
    an existing non-representative re-homes to a NEW representative iff
    its ANI there is strictly higher (the engine's ascending-rep argmax
    with strict improvement). Only those clusters are touched.
  * *query* runs the same screen against the live representatives
    without appending anything.
  * *remove* tombstones one genome; if it was a representative, its
    cluster re-elects the lowest-index remaining member locally (a
    deliberate local repair — documented in docs/index.md as not
    equivalent to a from-scratch run).

New-pair ANIs are computed host-side by an exact numpy mirror of the
device merge statistics (ops/pairwise.py ``_pair_stats``): integer
(common, total) plus the shared f64 ``stats_to_ani_f64`` formula, so an
inserted index is BYTE-IDENTICAL to a from-scratch build over the same
corpus (tests/test_index.py plants the proof).
"""

from __future__ import annotations

import logging
import os
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from galah_tpu.cluster.partition import partition_preclusters
from galah_tpu.index import store as index_store
from galah_tpu.index.store import IndexState, IndexStore
from galah_tpu.ops.pairwise import ani_to_jaccard, stats_to_ani_f64

logger = logging.getLogger(__name__)

# Pipeline contract, machine-checked by `galah-tpu lint` (GL10xx): the
# insert sketch stage is a generator over ops/sketch_stream's streaming
# pipeline and must stay streamed (GL1001/GL1002).
PIPELINE_STAGE = {
    # the occupancy gauge is emitted by ops/sketch_stream.py, which
    # this stage delegates to — declaring it here too would contract
    # this module to emit it a second time (GL1004)
    "streaming": ["iter_insert_sketches"],
}

# Concurrency contract (GL805/GalahSan): this module holds no locked
# shared state of its own — mutation is serialized by the single-writer
# IndexStore (see index/store.py's GUARDED_BY), and the streamed sketch
# stage's locks live in ops/sketch_stream.py.
GUARDED_BY: Dict[str, str] = {}
LOCK_ORDER: List[str] = []


class SketchANIClusterer:
    """Clusterer shim that names the preclusterer's own method so the
    engine takes the ``skip_clusterer`` path: sketch ANI IS the exact
    ANI, every decision comes from the precluster pair cache, and a
    persisted pair set can re-derive the engine's output exactly."""

    def __init__(self, ani_threshold: float) -> None:
        self.ani_threshold = float(ani_threshold)

    def method_name(self) -> str:
        return "finch"


def _default_batch() -> int:
    from galah_tpu.config import env_value

    return max(1, int(env_value("GALAH_TPU_INDEX_BATCH")))


def _sketch_store(index: IndexStore, cache_dir: Optional[str]):
    from galah_tpu.backends.minhash_backend import SketchStore
    from galah_tpu.io import diskcache

    p = index.sketch_params
    return SketchStore(p["sketch_size"], p["k"], seed=p["seed"],
                       cache=diskcache.get_cache(cache_dir),
                       algo=p["algo"])


def iter_insert_sketches(
        paths: Sequence[str], sketch_store,
        threads: int = 1) -> Iterator[Tuple[str, Any]]:
    """The insert/query sketch stage: (path, sketch) over the streaming
    ingest->sketch pipeline. Genomes already in the run's sketch store
    or the disk cache yield without touching FASTA — the property the
    "resketch only the new genomes" acceptance counter measures."""
    from galah_tpu.obs import flow as obs_flow
    from galah_tpu.ops.sketch_stream import iter_path_sketches

    it = iter_path_sketches(paths, sketch_store, threads=threads)
    while True:
        # blocked on the shared sketch pipeline: obs/flow attributes
        # the index stage's starvation upstream (GL704 discipline)
        with obs_flow.blocked("index-sketch", "upstream-empty"):
            try:
                path, sk = next(it)
            except StopIteration:
                break
        yield path, sk


# -- exact host-side pair statistics -----------------------------------


def merge_stats(a: np.ndarray, b: np.ndarray,
                sketch_size: int) -> Tuple[int, int]:
    """Integer (common, total) of two sorted-distinct bottom-k sketches
    over the first ``min(sketch_size, |union|)`` union elements — the
    exact numpy twin of the device kernel's ``_pair_stats``
    (ops/pairwise.py), so host-computed insert pairs are bit-identical
    to the device-computed build pairs."""
    na, nb = len(a), len(b)
    if na == 0 or nb == 0:
        return 0, min(sketch_size, na + nb)
    pos = np.searchsorted(b, a)
    safe = np.minimum(pos, nb - 1)
    match = (pos < nb) & (b[safe] == a)
    n_common = int(match.sum())
    total = min(sketch_size, na + nb - n_common)
    # union rank of a[i]: a-elements before it + b-elements below it -
    # matches already counted once
    urank = np.arange(na) + pos - (np.cumsum(match) - match)
    common = int((match & (urank < total)).sum())
    return common, total


def pair_ani(a: np.ndarray, b: np.ndarray, sketch_size: int, k: int,
             j_thr: float) -> Optional[float]:
    """ANI of a sketch pair under the precluster keep rule, or None if
    the pair falls below it — mirrors ops/pairwise.threshold_pairs:
    keep iff common > 0 and common >= jaccard_threshold * total."""
    common, total = merge_stats(a, b, sketch_size)
    if common <= 0 or float(common) < j_thr * total:
        return None
    return float(stats_to_ani_f64(np.asarray([common]),
                                  np.asarray([total]), k)[0])


# -- decision re-derivation (the engine's greedy semantics) ------------


def screen_new_genomes(state: IndexState, new_start: int,
                       thr: float) -> Dict[str, int]:
    """Extend representatives/membership for genomes ``[new_start, n)``
    and re-home affected existing members, mutating `state` in place.

    Replicates the engine's decisions exactly (see the module
    docstring); returns counters {new_reps, new_members, reassigned}.
    """
    pairs = state.pairs
    tomb = state.tombstones
    # ascending live rep list: state.reps is sorted and new genomes are
    # screened in ascending index order, so appends keep it sorted —
    # no hash-ordered set iteration anywhere near pair decisions
    rep_list = [r for r in state.reps if r not in tomb]
    rep_all = set(state.reps)
    new_reps: List[int] = []
    joiners: List[int] = []
    # pass 1 — representative decisions. Genome g's candidate set is
    # the representatives chosen before it, and the greedy order means
    # those all have lower indices (rep-first screening is sound).
    for g in range(new_start, state.n_genomes):
        if g in tomb:
            continue
        if not any(pairs[(r, g)] >= thr for r in rep_list
                   if (r, g) in pairs):
            rep_all.add(g)
            rep_list.append(g)
            new_reps.append(g)
        else:
            joiners.append(g)
    # pass 2 — membership. The engine's argmax visits the FULL final
    # rep list (a non-rep can join a rep with a higher index), so this
    # must run after every rep decision: ascending reps, strict
    # improvement (ties to the lowest rep index), no threshold.
    for g in joiners:
        best_r, best_ani = None, None
        for r in rep_list:
            ani = pairs.get((min(g, r), max(g, r)))
            if ani is not None and (best_ani is None or ani > best_ani):
                best_r, best_ani = r, ani
        state.membership[g] = best_r
    new_members = len(joiners)
    # existing non-reps with a pair to a NEW representative: the
    # engine's argmax visits reps ascending with strict >, and every
    # new rep index exceeds every old one — so re-home iff strictly
    # better than the current best
    reassigned = 0
    if new_reps:
        for m, cur in list(state.membership.items()):
            if m >= new_start or m in tomb:
                continue
            cur_key = (min(m, cur), max(m, cur))
            best_r, best_ani = cur, pairs.get(cur_key)
            for r in new_reps:
                ani = pairs.get((m, r))
                if ani is not None and (best_ani is None
                                        or ani > best_ani):
                    best_r, best_ani = r, ani
            if best_r != cur:
                state.membership[m] = best_r
                reassigned += 1
    state.reps = sorted(rep_all)
    return {"new_reps": len(new_reps), "new_members": new_members,
            "reassigned": reassigned}


def clusters_from_state(state: IndexState) -> List[List[int]]:
    """The engine-ordered cluster list: preclusters biggest-first (ties
    to the lowest genome index), representatives ascending within one,
    each cluster ``[rep] + members ascending`` — exactly how
    cluster/engine.py assembles its output, so a from-scratch run and
    an index roundtrip compare byte-identical."""
    live = set(state.live)
    keys = [kk for kk in state.pairs
            if kk[0] in live and kk[1] in live]
    rep_set = set(state.reps)
    members: Dict[int, List[int]] = {}
    for g, r in state.membership.items():
        members.setdefault(r, []).append(g)
    out: List[List[int]] = []
    for comp in partition_preclusters(state.n_genomes, keys):
        for r in comp:
            if r in rep_set:
                out.append([r] + sorted(members.get(r, [])))
    return out


def cluster_paths(state: IndexState) -> List[List[str]]:
    return [[state.genomes[g] for g in c]
            for c in clusters_from_state(state)]


# -- operations --------------------------------------------------------


def _publish(state: IndexState, op: str,
             extra: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """Gauges + run-report snapshot after any index operation."""
    from galah_tpu import index as index_pkg
    from galah_tpu.obs import metrics as obs_metrics

    live = len(state.live)
    obs_metrics.gauge(
        "index.generation",
        help="Committed generation of the persistent sketch index",
        unit="generation").set(float(state.generation))
    obs_metrics.gauge(
        "index.genomes",
        help="Live (non-tombstoned) genomes in the sketch index",
        unit="genomes").set(float(live))
    obs_metrics.gauge(
        "index.clusters",
        help="Clusters (representatives) in the sketch index",
        unit="clusters").set(float(len(state.reps)))
    snap: Dict[str, Any] = {
        "op": op,
        "generation": state.generation,
        "genomes": live,
        "clusters": len(state.reps),
        "tombstones": len(state.tombstones),
        "pairs": len(state.pairs),
    }
    if extra:
        snap.update(extra)
    index_pkg.set_snapshot(snap)
    return snap


def build(path: str, ordered_paths: Sequence[str], ani: float,
          precluster_ani: float,
          sketch_size: Optional[int] = None, k: Optional[int] = None,
          seed: Optional[int] = None, algo: Optional[str] = None,
          cache_dir: Optional[str] = None,
          threads: int = 1) -> Dict[str, Any]:
    """Build (or finish a killed build of) the index at `path` from the
    quality-ordered `ordered_paths`, committing generation 1.

    The pair pass runs the SAME device pipeline a cluster run uses
    (backends/minhash_backend.distances), so the persisted ANIs carry
    the pipeline's bit-exactness guarantees verbatim.
    """
    from galah_tpu.backends.minhash_backend import MinHashPreclusterer
    from galah_tpu.config import Defaults
    from galah_tpu.io import diskcache

    params = index_store.index_params(
        ani=ani, precluster_ani=precluster_ani,
        sketch_size=(Defaults.MINHASH_SKETCH_SIZE
                     if sketch_size is None else sketch_size),
        k=Defaults.MINHASH_KMER if k is None else k,
        seed=Defaults.MINHASH_SEED if seed is None else seed,
        algo=Defaults.HASH_ALGO if algo is None else algo)
    idx = IndexStore(path, params=params, create=True)
    if idx.generation():
        raise ValueError(
            f"index at {path} is already built (generation "
            f"{idx.generation()}); use `galah-tpu index insert`")
    state = idx.begin_mutation()

    paths = [os.path.abspath(p) for p in ordered_paths]
    if len(set(os.path.realpath(p) for p in paths)) != len(paths):
        raise ValueError("duplicate genome paths in index build input")

    pre = MinHashPreclusterer(
        min_ani=params["precluster_ani"],
        sketch_size=params["sketch_size"], k=params["k"],
        cache=diskcache.get_cache(cache_dir),
        hash_algo=params["algo"], threads=threads)
    pair_cache = pre.distances(paths)

    for g, p in enumerate(paths):
        sk = pre.store.get_cached(p)
        if sk is None:  # pragma: no cover - distances always fills it
            sk = pre.store.get(p)
        key = index_store.genome_key(p, idx.sketch_params)
        idx.append_genome(g, p, key)
        idx.append_sketch(g, sk.hashes)
        state.genomes.append(p)
        state.keys.append(key)
        state.sketches.append(np.asarray(sk.hashes, dtype=np.uint64))
    # grouped by the higher index — the order insert appends in, so a
    # grown index and a from-scratch build are byte-identical
    pair_rows = sorted(
        ((i, j, ani_val) for (i, j), ani_val in pair_cache.items()),
        key=lambda row: (row[1], row[0]))
    idx.append_pairs(pair_rows)
    state.pairs = {(i, j): v for i, j, v in pair_rows}

    counts = screen_new_genomes(state, 0, params["ani"])
    generation = idx.commit(state)
    logger.info(
        "Built index at %s: generation %d, %d genomes, %d clusters, "
        "%d pairs", path, generation, len(state.genomes),
        len(state.reps), len(state.pairs))
    return _publish(state, "build", counts)


def insert(idx: IndexStore, new_paths: Sequence[str],
           cache_dir: Optional[str] = None, threads: int = 1,
           batch: Optional[int] = None) -> Dict[str, Any]:
    """Insert quality-ordered `new_paths`, committing one new
    generation. Only the new genomes are sketched (streamed through
    ops/sketch_stream); only their pairs are computed (host-side exact
    merge statistics); only clusters a new representative borders can
    change. Appends are durable per record and the sketch cache is
    warm after a kill, so an interrupted insert resumed from the prior
    generation converges to the same bytes as an uninterrupted one.
    """
    from galah_tpu.obs import metrics as obs_metrics
    from galah_tpu.resilience import interrupt

    state = idx.begin_mutation()
    if state.generation == 0:
        raise ValueError(
            f"index at {idx.path} has no committed generation; run "
            "`galah-tpu index build` first")
    known = {os.path.realpath(p) for p in state.genomes}
    fresh: List[str] = []
    skipped = 0
    for p in new_paths:
        rp = os.path.realpath(p)
        if rp in known:
            skipped += 1
            continue
        known.add(rp)
        fresh.append(os.path.abspath(p))
    if skipped:
        logger.info("Skipping %d genome(s) already in the index",
                    skipped)
    if not fresh:
        return _publish(state, "insert",
                        {"inserted": 0, "skipped": skipped})

    params = idx.params
    j_thr = ani_to_jaccard(params["precluster_ani"], params["k"])
    sk_store = _sketch_store(idx, cache_dir)
    batch = _default_batch() if batch is None else max(1, int(batch))
    new_start = state.n_genomes
    hist = obs_metrics.histogram(
        "index.insert_seconds",
        help="Wall seconds per index insert operation", unit="s")
    with hist.time():
        for b0 in range(0, len(fresh), batch):
            chunk = fresh[b0:b0 + batch]
            for p, sk in iter_insert_sketches(chunk, sk_store,
                                              threads=threads):
                g = len(state.genomes)
                hashes = np.asarray(sk.hashes, dtype=np.uint64)
                key = index_store.genome_key(p, idx.sketch_params)
                rows = []
                for u in range(g):
                    if u in state.tombstones:
                        continue
                    ani_val = pair_ani(state.sketches[u], hashes,
                                       params["sketch_size"],
                                       params["k"], j_thr)
                    if ani_val is not None:
                        rows.append((u, g, ani_val))
                idx.append_genome(g, p, key)
                idx.append_sketch(g, hashes)
                idx.append_pairs(rows)
                state.genomes.append(p)
                state.keys.append(key)
                state.sketches.append(hashes)
                for u, gg, v in rows:
                    state.pairs[(u, gg)] = v
            # safe boundary: this batch's records are durable (per-
            # record fsync); a preemption here leaves the index
            # loadable at the prior generation and a resume redoes
            # only the uncommitted work, with every sketch cache-warm
            interrupt.check("index-batch-saved")
        counts = screen_new_genomes(state, new_start, params["ani"])
        generation = idx.commit(state)
    logger.info(
        "Inserted %d genome(s) into %s: generation %d, %d clusters "
        "(%d new rep(s), %d reassigned)", len(fresh), idx.path,
        generation, len(state.reps), counts["new_reps"],
        counts["reassigned"])
    counts.update({"inserted": len(fresh), "skipped": skipped})
    return _publish(state, "insert", counts)


def query(idx: IndexStore, paths: Sequence[str],
          cache_dir: Optional[str] = None,
          threads: int = 1) -> List[Dict[str, Any]]:
    """Answer "which cluster would this genome join" for each path
    against the committed state, mutating nothing.

    The decision replays the insert screen for a single genome: join
    the argmax-ANI representative if any pair reaches the cluster
    threshold, otherwise the genome would found a new cluster.
    """
    from galah_tpu.obs import metrics as obs_metrics

    state = idx.load()
    params = idx.params
    j_thr = ani_to_jaccard(params["precluster_ani"], params["k"])
    reps = [r for r in state.reps if r not in state.tombstones]
    hist = obs_metrics.histogram(
        "index.query_seconds",
        help="Wall seconds per single-genome index query", unit="s")
    sk_store = _sketch_store(idx, cache_dir)
    sketches: Dict[str, Any] = {}
    for p, sk in iter_insert_sketches(
            [os.path.abspath(p) for p in paths], sk_store,
            threads=threads):
        sketches[p] = np.asarray(sk.hashes, dtype=np.uint64)
    out: List[Dict[str, Any]] = []
    for p in (os.path.abspath(q) for q in paths):
        with hist.time():
            hashes = sketches[p]
            best_r, best_ani, hits = None, None, 0
            for r in reps:
                ani_val = pair_ani(state.sketches[r], hashes,
                                   params["sketch_size"], params["k"],
                                   j_thr)
                if ani_val is None:
                    continue
                hits += 1
                if best_ani is None or ani_val > best_ani:
                    best_r, best_ani = r, ani_val
            joins = best_ani is not None and best_ani >= params["ani"]
            out.append({
                "path": p,
                "decision": "member" if joins else "novel",
                "rep": state.genomes[best_r] if joins else None,
                "rep_index": best_r if joins else None,
                "ani": best_ani,
                "candidates": hits,
            })
    return out


def remove(idx: IndexStore, path: str) -> Dict[str, Any]:
    """Tombstone one genome and repair only its own cluster: a removed
    representative's cluster re-elects its lowest-index remaining
    member; every other cluster is untouched (local repair, not a
    from-scratch equivalence — see docs/index.md)."""
    state = idx.begin_mutation()
    if state.generation == 0:
        raise ValueError(
            f"index at {idx.path} has no committed generation; run "
            "`galah-tpu index build` first")
    rp = os.path.realpath(path)
    target = next((g for g, p in enumerate(state.genomes)
                   if os.path.realpath(p) == rp
                   and g not in state.tombstones), None)
    if target is None:
        raise ValueError(f"{path} is not a live genome of the index "
                         f"at {idx.path}")
    state.tombstones.add(target)
    reelected: Optional[int] = None
    if target in state.membership:
        del state.membership[target]
    else:  # a representative: local re-election
        orphans = sorted(g for g, r in state.membership.items()
                         if r == target)
        state.reps = [r for r in state.reps if r != target]
        if orphans:
            reelected = orphans[0]
            state.membership.pop(reelected)
            state.reps = sorted(state.reps + [reelected])
            for g in orphans[1:]:
                state.membership[g] = reelected
    generation = idx.commit(state)
    logger.info(
        "Removed genome %d (%s) from %s: generation %d%s", target, rp,
        idx.path, generation,
        f", re-elected {reelected}" if reelected is not None else "")
    return _publish(state, "remove",
                    {"removed": target, "reelected": reelected})
