"""Persistent versioned sketch index: the incremental serving layer.

``store`` is the durable on-disk format (framed-JSONL logs, generation
manifests, commit pointer, fsck); ``incremental`` is the update engine
(build / insert / query / remove) that re-derives the cluster engine's
greedy decisions from persisted sketches and pairs. See docs/index.md.

This package module stays stdlib-only at import: the run-report
assembler reads the snapshot below on hosts with no accelerator, and
must never drag jax (or even numpy) in through it.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

#: Last index operation's summary, mirrored into the run report's
#: ``index`` section by obs/report.assemble (reset with reset_run).
_SNAPSHOT: Optional[Dict[str, Any]] = None


def set_snapshot(snap: Dict[str, Any]) -> None:
    global _SNAPSHOT
    _SNAPSHOT = dict(snap)


def snapshot() -> Optional[Dict[str, Any]]:
    return dict(_SNAPSHOT) if _SNAPSHOT is not None else None


def reset() -> None:
    global _SNAPSHOT
    _SNAPSHOT = None
