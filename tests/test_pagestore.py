"""Out-of-core paged sketch store (io/pagestore.py).

The NVMe tier of the sketch memory hierarchy (docs/memory.md): packed
u64 pages committed with the io/atomic.py discipline, an LRU resident
set bounded by a hard byte budget, zero-copy row views, and a
directory whose records are appended only after the page body is
durable — so a record always names an intact page, even across
SIGKILL (the torture test below proves it with a real killed writer).
"""

import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from galah_tpu.io import atomic
from galah_tpu.io.pagestore import (
    DIR_NAME,
    PageStoreError,
    PagedRowView,
    SketchPageStore,
    pagestore_engaged,
)
from galah_tpu.ops.constants import SENTINEL


def _rows(n, cols, seed=0, short_every=3):
    """Deterministic test rows; every `short_every`-th row is short
    (fewer than `cols` hashes) to exercise fill padding."""
    rng = np.random.default_rng(seed)
    out = []
    for i in range(n):
        width = cols - 2 if short_every and i % short_every == 0 else cols
        out.append(rng.integers(0, 1 << 62, size=width, dtype=np.uint64))
    return out


# ---------------------------------------------------------------------------
# Page format / round trip
# ---------------------------------------------------------------------------


def test_roundtrip_padding_and_key_lookup(tmp_path):
    store = SketchPageStore(str(tmp_path), cols=8, page_rows=4,
                            fill=SENTINEL)
    rows = _rows(10, 8, seed=1)
    rids = [store.append(f"g{i}", r) for i, r in enumerate(rows)]
    assert rids == list(range(10))
    store.flush()
    assert len(store) == 10
    assert store.shape == (10, 8)
    for i, r in enumerate(rows):
        np.testing.assert_array_equal(store.hashes(i), r)
        assert store.n_valid(i) == r.size
        full = store.row(i)
        assert full.shape == (8,)
        # Short rows are SENTINEL-padded: the MinHash pair kernels
        # must never count a pad slot as a shared hash.
        np.testing.assert_array_equal(
            full[r.size:], np.full(8 - r.size, SENTINEL, np.uint64))
        assert store.rid_for(f"g{i}") == i
        np.testing.assert_array_equal(store.get(f"g{i}"), r)
    assert store.rid_for("nope") is None and store.get("nope") is None
    np.testing.assert_array_equal(
        store.valid_counts(), np.asarray([r.size for r in rows]))


def test_open_page_rows_readable_before_flush(tmp_path):
    store = SketchPageStore(str(tmp_path), cols=4, page_rows=100)
    r = np.arange(3, dtype=np.uint64)
    rid = store.append("k", r)
    # Nothing committed yet: no page files, but the row is readable.
    assert [f for f in os.listdir(tmp_path) if f.endswith(".gpg")] == []
    assert len(store) == 1
    np.testing.assert_array_equal(store.hashes(rid), r)
    assert store.n_valid(rid) == 3
    assert store.rid_for("k") == rid
    store.flush()
    assert [f for f in os.listdir(tmp_path) if f.endswith(".gpg")]
    np.testing.assert_array_equal(store.hashes(rid), r)


def test_page_boundary_auto_commit(tmp_path):
    store = SketchPageStore(str(tmp_path), cols=4, page_rows=2)
    for i in range(5):
        store.append(f"g{i}", np.full(4, i, np.uint64))
    pages = [f for f in os.listdir(tmp_path) if f.endswith(".gpg")]
    assert len(pages) == 2          # rows 0..3 committed, row 4 open
    assert len(store) == 5


def test_oversized_row_rejected(tmp_path):
    store = SketchPageStore(str(tmp_path), cols=4)
    with pytest.raises(ValueError):
        store.append("big", np.zeros(5, np.uint64))


# ---------------------------------------------------------------------------
# Zero-copy views
# ---------------------------------------------------------------------------


def test_committed_views_are_zero_copy_and_readonly(tmp_path):
    store = SketchPageStore(str(tmp_path), cols=8, page_rows=4)
    rows = _rows(4, 8, seed=2, short_every=0)
    for i, r in enumerate(rows):
        store.append(f"g{i}", r)
    store.flush()
    a, b = store.row(1), store.hashes(1)
    assert not a.flags.writeable          # mmap is ACCESS_READ
    assert np.shares_memory(a, b)         # views, not copies
    assert np.shares_memory(a, store.row(1))


def test_eviction_never_invalidates_live_views(tmp_path):
    # One page per budget: reading page 1 evicts page 0, but the view
    # handed out for page 0 must stay valid (eviction drops the store's
    # reference, never closes the map).
    cols, page_rows = 8, 2
    page_bytes = cols * page_rows * 8
    store = SketchPageStore(str(tmp_path), cols=cols, page_rows=page_rows,
                            budget_bytes=page_bytes)
    rows = _rows(6, cols, seed=3, short_every=0)
    for i, r in enumerate(rows):
        store.append(f"g{i}", r)
    store.flush()
    view0 = store.row(0)
    for rid in (2, 4):                    # touch pages 1 and 2
        store.row(rid)
    assert store.resident_bytes <= page_bytes
    np.testing.assert_array_equal(view0, rows[0])


# ---------------------------------------------------------------------------
# LRU / budget / pins
# ---------------------------------------------------------------------------


def test_lru_eviction_respects_budget(tmp_path):
    cols, page_rows = 8, 2
    page_bytes = cols * page_rows * 8
    store = SketchPageStore(str(tmp_path), cols=cols, page_rows=page_rows,
                            budget_bytes=2 * page_bytes)
    rows = _rows(10, cols, seed=4, short_every=0)
    for i, r in enumerate(rows):
        store.append(f"g{i}", r)
    store.flush()                         # 5 pages on disk
    ins0 = store._c_page_ins.value
    outs0 = store._c_page_outs.value
    for rid in range(10):
        np.testing.assert_array_equal(store.hashes(rid), rows[rid])
        assert store.resident_bytes <= 2 * page_bytes
    assert store._c_page_ins.value - ins0 == 5
    assert store._c_page_outs.value - outs0 == 3
    # Re-reading an evicted page re-maps it — and the data survives
    # the page-out/page-in cycle bit for bit.
    np.testing.assert_array_equal(store.hashes(0), rows[0])
    assert store._c_page_ins.value - ins0 == 6
    assert store._g_resident.value == store.resident_bytes


def test_gather_pins_beat_budget_then_release(tmp_path):
    # gather() touches every page at once under a one-page budget: the
    # pins let residency exceed the budget for the copy, then the
    # final eviction pass brings it back under.
    cols, page_rows = 8, 2
    page_bytes = cols * page_rows * 8
    store = SketchPageStore(str(tmp_path), cols=cols, page_rows=page_rows,
                            budget_bytes=page_bytes)
    rows = _rows(8, cols, seed=5, short_every=0)
    for i, r in enumerate(rows):
        store.append(f"g{i}", r)
    idx = np.asarray([7, 0, 3, 5, 3])
    sub = store.gather(idx)               # also flushes the open page
    np.testing.assert_array_equal(sub, np.vstack([rows[i] for i in idx]))
    assert sub.flags.writeable            # a copy, caller-owned
    assert store.resident_bytes <= page_bytes
    # band_gather is the duck-typed alias the bucketed scheduler calls
    np.testing.assert_array_equal(store.band_gather(idx), sub)


def test_paged_row_view_maps_positions_to_rids(tmp_path):
    store = SketchPageStore(str(tmp_path), cols=4, page_rows=2)
    rows = _rows(3, 4, seed=6, short_every=0)
    for i, r in enumerate(rows):
        store.append(f"g{i}", r)
    store.flush()
    # Positions 1 and 3 share store row 1 (duplicate paths alias one
    # sketch row) — the facade's job.
    view = PagedRowView(store, [0, 1, 2, 1])
    assert view.shape == (4, 4)
    got = view.band_gather([3, 0, 1])
    np.testing.assert_array_equal(
        got, np.vstack([rows[1], rows[0], rows[1]]))


# ---------------------------------------------------------------------------
# Cross-writer adoption / durability
# ---------------------------------------------------------------------------


def test_refresh_adopts_second_writer(tmp_path):
    a = SketchPageStore(str(tmp_path), cols=4, page_rows=2)
    r0 = np.arange(4, dtype=np.uint64)
    a.append("g0", r0)
    a.flush()
    b = SketchPageStore(str(tmp_path), cols=4, page_rows=2)
    assert len(b) == 1                    # adopted at construction
    np.testing.assert_array_equal(b.get("g0"), r0)
    r1 = np.arange(4, 8, dtype=np.uint64)
    a.append("g1", r1)
    a.flush()
    assert b.rid_for("g1") is None
    assert b.refresh() == 1
    np.testing.assert_array_equal(b.get("g1"), r1)
    assert b.refresh() == 0               # idempotent


def test_orphan_page_ignored_and_torn_directory_tail_healed(tmp_path):
    store = SketchPageStore(str(tmp_path), cols=4, page_rows=1)
    store.append("g0", np.arange(4, dtype=np.uint64))
    store.flush()
    # A crash between page write and directory append leaves an orphan
    # page body with no record: invisible to readers.
    with open(tmp_path / "page-deadbeef-000000.gpg", "wb") as f:
        f.write(b"orphan")
    # A crash mid directory append leaves a torn tail: healed on read.
    with open(tmp_path / DIR_NAME, "ab") as f:
        f.write(b'{"page": "page-trunc')
    fresh = SketchPageStore(str(tmp_path), cols=4, page_rows=1)
    assert len(fresh) == 1
    np.testing.assert_array_equal(
        fresh.get("g0"), np.arange(4, dtype=np.uint64))


def test_corrupt_payload_detected(tmp_path):
    store = SketchPageStore(str(tmp_path), cols=4, page_rows=1)
    store.append("g0", np.arange(4, dtype=np.uint64))
    store.flush()
    name = next(f for f in os.listdir(tmp_path) if f.endswith(".gpg"))
    p = os.path.join(str(tmp_path), name)
    data = bytearray(open(p, "rb").read())
    data[-1] ^= 0xFF                      # flip a payload byte
    with open(p, "wb") as f:
        f.write(data)
    fresh = SketchPageStore(str(tmp_path), cols=4, page_rows=1)
    with pytest.raises(PageStoreError, match="crc"):
        fresh.row(0)


def test_corrupt_header_detected(tmp_path):
    store = SketchPageStore(str(tmp_path), cols=4, page_rows=1)
    store.append("g0", np.arange(4, dtype=np.uint64))
    store.flush()
    name = next(f for f in os.listdir(tmp_path) if f.endswith(".gpg"))
    p = os.path.join(str(tmp_path), name)
    data = open(p, "rb").read()
    with open(p, "wb") as f:
        f.write(b"x" + data[1:])          # break the header frame crc
    fresh = SketchPageStore(str(tmp_path), cols=4, page_rows=1)
    with pytest.raises(PageStoreError, match="header"):
        fresh.row(0)


def test_inconsistent_directory_record_detected(tmp_path):
    store = SketchPageStore(str(tmp_path), cols=4, page_rows=1)
    store.append("g0", np.arange(4, dtype=np.uint64))
    store.flush()
    name = next(f for f in os.listdir(tmp_path) if f.endswith(".gpg"))
    atomic.append_jsonl(os.path.join(str(tmp_path), DIR_NAME),
                        {"page": name + ".bogus", "rows": 2, "cols": 4,
                         "keys": ["a"], "valid": [4]})
    with pytest.raises(PageStoreError, match="inconsistent"):
        SketchPageStore(str(tmp_path), cols=4, page_rows=1)


# ---------------------------------------------------------------------------
# Engagement gate
# ---------------------------------------------------------------------------


def test_pagestore_engaged_tristate(monkeypatch):
    monkeypatch.setenv("GALAH_TPU_PAGESTORE", "0")
    assert not pagestore_engaged(10**9, 1000)
    monkeypatch.setenv("GALAH_TPU_PAGESTORE", "1")
    assert pagestore_engaged(2, 1000)
    assert not pagestore_engaged(1, 1000)  # nothing to page
    monkeypatch.setenv("GALAH_TPU_PAGESTORE", "auto")
    monkeypatch.setenv("GALAH_TPU_SKETCH_RAM_MB", "1")
    # auto: engage when the all-resident matrix would exceed half the
    # RAM budget — 1 MiB budget, 0.5 MiB threshold = 65536 u64 slots.
    assert pagestore_engaged(100, 1000)
    assert not pagestore_engaged(8, 1000)
    monkeypatch.setenv("GALAH_TPU_SKETCH_RAM_MB", "banana")
    assert not pagestore_engaged(100, 1000)  # falls back to 512 MiB


# ---------------------------------------------------------------------------
# Two-process torture: evictions racing reads, SIGKILL mid page-out
# ---------------------------------------------------------------------------

_WRITER = r"""
import os, sys
import numpy as np
from galah_tpu.io.pagestore import SketchPageStore

d, seed = sys.argv[1], int(sys.argv[2])
store = SketchPageStore(d, cols=16, page_rows=4, budget_bytes=16 * 4 * 8)
rng = np.random.default_rng(seed)
i = 0
while True:
    # Row content is a pure function of the key so any reader can
    # verify every adopted row without a side channel.
    row = np.full(16, np.uint64(i * 1000 + seed), dtype=np.uint64)
    store.append(f"w{i}", row)
    if i % 4 == 3:
        store.flush()
        print(i, flush=True)
    i += 1
"""


def test_two_process_torture_never_torn_rows(tmp_path):
    """A second process writes pages continuously and is SIGKILLed
    mid-stream; this process races refresh()+reads against its
    commits under a one-page budget (evictions on every page-in).
    Every row any reader ever sees must be exactly the writer's
    deterministic content — a torn or partial page would either be
    invisible (no directory record) or fail the crc, never misread."""
    seed = 7
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, "-c", _WRITER, str(tmp_path), str(seed)],
        stdout=subprocess.PIPE, env=env)
    reader = SketchPageStore(str(tmp_path), cols=16, page_rows=4,
                             budget_bytes=16 * 4 * 8)

    def check_all():
        n = len(reader)
        for rid in range(n):
            row = reader.row(rid)
            expect = row[0]               # key index * 1000 + seed
            np.testing.assert_array_equal(
                row, np.full(16, expect, np.uint64))
            assert (int(expect) - seed) % 1000 == 0
        return n

    try:
        # Wait for the writer's first committed page, then race reads
        # against further commits for a few cycles.
        assert proc.stdout.readline().strip()
        seen = 0
        for _ in range(10):
            reader.refresh()
            seen = max(seen, check_all())
            proc.stdout.readline()
        assert seen >= 4
    finally:
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=30)

    # After the kill — possibly mid page-out — a fresh store adopts
    # only committed pages, all intact.
    time.sleep(0.1)
    fresh = SketchPageStore(str(tmp_path), cols=16, page_rows=4)
    n = len(fresh)
    assert n >= 4
    for rid in range(n):
        row = fresh.row(rid)
        np.testing.assert_array_equal(
            row, np.full(16, row[0], np.uint64))
    # No temp debris survives the next store's sweep beyond the age
    # threshold; committed pages all parse.
    counts = fresh.valid_counts()
    assert counts.shape == (n,) and (counts == 16).all()
