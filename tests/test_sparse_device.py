"""Device-path sparse screen: bit-identity with the dense tiled path,
sharded batch evaluation, and gate selection on device backends."""

import numpy as np
import pytest

import galah_tpu.ops.collision as collision
import galah_tpu.ops.sparse_device as sparse_device
from galah_tpu.ops.constants import SENTINEL
from galah_tpu.ops.pairwise import (
    _threshold_pairs_single,
    screen_pairs,
    threshold_pairs,
)
from galah_tpu.ops.sparse_device import (
    pair_stats_for_pairs,
    threshold_pairs_sparse,
)


def _family_sketches(n=1100, width=48, n_fam=80, seed=91,
                     mutations=25):
    """Family-structured sorted sketch matrix with ragged/empty rows."""
    rng = np.random.default_rng(seed)
    base = rng.integers(0, 1 << 62, size=(n_fam, width), dtype=np.uint64)
    mat = np.empty((n, width), dtype=np.uint64)
    for i in range(n):
        row = base[i % n_fam].copy()
        n_mut = int(rng.integers(0, mutations))
        idx = rng.choice(width, size=n_mut, replace=False)
        row[idx] = rng.integers(0, 1 << 62, size=n_mut, dtype=np.uint64)
        row.sort()
        mat[i] = row
    mat[3, 10:] = np.uint64(SENTINEL)   # ragged
    mat[9] = np.uint64(SENTINEL)        # empty
    mat.sort(axis=1)
    return mat


def test_sparse_equals_dense_threshold_sweep():
    mat = _family_sketches()
    for thr in (0.90, 0.95, 0.99):
        dense = _threshold_pairs_single(
            mat, k=21, min_ani=thr, sketch_size=mat.shape[1],
            row_tile=64, col_tile=128, use_pallas=False, cap_per_row=64)
        sparse = threshold_pairs_sparse(mat, k=21, min_ani=thr)
        assert sparse == dense, thr


def test_sparse_batched_partial_batches():
    """A batch size that does not divide the candidate count exercises
    the pad-and-trim path; results unchanged."""
    mat = _family_sketches(n=300, n_fam=30, seed=17)
    full = threshold_pairs_sparse(mat, k=21, min_ani=0.95)
    small = threshold_pairs_sparse(mat, k=21, min_ani=0.95, batch=37)
    assert small == full
    assert len(full) > 0


def test_pair_stats_for_pairs_sharded_equals_single():
    from galah_tpu.parallel.mesh import make_mesh

    mat = _family_sketches(n=200, n_fam=20, seed=23)
    rng = np.random.default_rng(5)
    pi = rng.integers(0, 199, size=501).astype(np.int64)
    pj = np.minimum(pi + 1 + rng.integers(0, 50, size=501), 199)
    c1, t1 = pair_stats_for_pairs(mat, pi, pj, mat.shape[1])
    mesh = make_mesh()
    c2, t2 = pair_stats_for_pairs(mat, pi, pj, mat.shape[1], mesh=mesh)
    np.testing.assert_array_equal(c1, c2)
    np.testing.assert_array_equal(t1, t2)


def test_no_collisions_returns_empty():
    rng = np.random.default_rng(7)
    n = 64
    mat = np.sort(
        rng.choice(1 << 62, size=(n, 32), replace=False)
        .astype(np.uint64), axis=1)
    assert threshold_pairs_sparse(mat, k=21, min_ani=0.95) == {}


def test_public_gate_selects_sparse_path(monkeypatch):
    """Above the crossover with no knobs pinned, threshold_pairs routes
    to the sparse device pipeline (with the auto mesh on a multi-device
    runtime) and returns the dense-identical result."""
    mat = _family_sketches(n=160, n_fam=16, seed=29)
    monkeypatch.setattr(collision, "SPARSE_SCREEN_MIN_N", 100)

    calls = {}
    real = sparse_device.threshold_pairs_sparse

    def spy(*args, **kwargs):
        calls["mesh"] = kwargs.get("mesh")
        calls["hit"] = True
        return real(*args, **kwargs)

    monkeypatch.setattr(sparse_device, "threshold_pairs_sparse", spy)
    got = threshold_pairs(mat, k=21, min_ani=0.95)
    assert calls.get("hit"), "sparse path must be selected"
    import jax

    if jax.device_count() > 1:
        assert calls["mesh"] is not None and calls["mesh"].devices.size > 1

    dense = _threshold_pairs_single(
        mat, k=21, min_ani=0.95, sketch_size=mat.shape[1],
        row_tile=64, col_tile=128, use_pallas=False, cap_per_row=64)
    assert got == dense


def test_public_gate_dense_env_pins_dense(monkeypatch):
    mat = _family_sketches(n=160, n_fam=16, seed=29)
    monkeypatch.setattr(collision, "SPARSE_SCREEN_MIN_N", 100)
    monkeypatch.setenv("GALAH_TPU_DENSE_PAIRS", "1")

    def boom(*a, **k):  # the sparse path must NOT be taken
        raise AssertionError("sparse path selected despite env pin")

    monkeypatch.setattr(sparse_device, "threshold_pairs_sparse", boom)
    got = threshold_pairs(mat, k=21, min_ani=0.95)
    assert len(got) > 0


def test_screen_pairs_sparse_on_any_backend(monkeypatch):
    """The marker screen's collision path is exact and now engages on
    every backend (the conftest runtime is an 8-device CPU mesh)."""
    rng = np.random.default_rng(41)
    n, m = 150, 40
    n_fam = 15
    base = rng.integers(0, 1 << 62, size=(n_fam, m), dtype=np.uint64)
    mat = np.empty((n, m), dtype=np.uint64)
    for i in range(n):
        row = base[i % n_fam].copy()
        n_mut = int(rng.integers(0, 12))
        idx = rng.choice(m, size=n_mut, replace=False)
        row[idx] = rng.integers(0, 1 << 62, size=n_mut, dtype=np.uint64)
        row.sort()
        mat[i] = row
    counts = np.full(n, m, dtype=np.int64)

    monkeypatch.setattr(collision, "SPARSE_SCREEN_MIN_N", 100)
    sparse = screen_pairs(mat, counts, 0.8)

    from galah_tpu.ops.pairwise import _screen_pairs_single

    dense = _screen_pairs_single(mat, counts, 0.8, 64, 128, 256, False)
    assert sorted(sparse) == sorted(dense)
    assert len(sparse) > 0


def test_sparse_device_equals_c_kernel_at_scale():
    """Cross-implementation equivalence on a large family matrix: the
    screened device pipeline (collision screen + gathered XLA pair
    stats) and the compiled-C merged walk (its own screen + C walk)
    must produce the identical pair dict — two independent
    implementations of the same contract."""
    cps = pytest.importorskip("galah_tpu.ops._cpairstats")

    mat = _family_sketches(n=5000, width=64, n_fam=250, seed=101,
                           mutations=30)
    via_device = threshold_pairs_sparse(mat, k=21, min_ani=0.95,
                                        sketch_size=mat.shape[1])
    via_c = cps.threshold_pairs_c(mat, mat.shape[1], 21, 0.95)
    # identical pair SETS exactly (the keep-check is rational f64 on
    # both sides); ANI values via approx — np.log and libm log are
    # independent transcendental implementations (repo precedent:
    # tests/test_cpairstats.py)
    assert set(via_device) == set(via_c)
    for key, v in via_device.items():
        assert via_c[key] == pytest.approx(v, abs=1e-12), key
    assert len(via_c) > 1000


class _LazyFail:
    """A device-future stand-in whose host materialization raises — the
    settle-time Mosaic failure shape (dispatch enqueues fine; the error
    surfaces when the ordered sync reads the buffer back)."""

    def __array__(self, dtype=None, copy=None):
        raise RuntimeError("injected Mosaic runtime failure (settle)")


def _inject_mosaic_failure(monkeypatch, mode, fail_at, log):
    """Patch the module-level _batch_pair_stats with a fake whose pallas
    path computes the real XLA integers (the two paths are bit-identical
    by contract) but fails on the fail_at-th pallas dispatch — raising at
    enqueue, or returning lazily-failing buffers for the settle site.
    Every call appends ("pallas"|"xla") to `log`."""
    real = sparse_device._batch_pair_stats

    def fake(jmat, pi, pj, sketch_size, use_pallas=False, interpret=False):
        exact = real(jmat, pi, pj, sketch_size=sketch_size,
                     use_pallas=False, interpret=False)
        if not use_pallas:
            log.append("xla")
            return exact
        n_before = log.count("pallas")
        log.append("pallas")
        if n_before == fail_at:
            if mode == "enqueue":
                raise RuntimeError(
                    "injected Mosaic runtime failure (enqueue)")
            return _LazyFail(), _LazyFail()
        return exact

    monkeypatch.setattr(sparse_device, "_batch_pair_stats", fake)
    import galah_tpu.ops.hll as hll

    monkeypatch.setattr(hll, "use_pallas_default", lambda: True)


def _fault_pairs(n=240, n_pairs=600, seed=3):
    mat = _family_sketches(n=n, n_fam=24, seed=seed)
    rng = np.random.default_rng(seed)
    pi = rng.integers(0, n - 1, size=n_pairs).astype(np.int64)
    pj = np.minimum(pi + 1 + rng.integers(0, 40, size=n_pairs), n - 1)
    return mat, pi, pj


@pytest.mark.parametrize("mode", ["enqueue", "settle"])
def test_mosaic_midstream_failure_downgrades_once(monkeypatch, mode):
    """A Mosaic runtime failure mid-pipeline — at dispatch enqueue or at
    host materialization of an in-flight batch — must downgrade the run
    to the XLA path exactly once and still produce integers bit-identical
    to a pure-XLA run (the downgrade_and_redo contract,
    ops/sparse_device.py). Analog of the reference's finish_command_safely
    fail-safe (reference: src/dashing.rs:101)."""
    mat, pi, pj = _fault_pairs()
    want_c, want_t = pair_stats_for_pairs(
        mat, pi, pj, mat.shape[1], batch=32, use_pallas=False)

    log = []
    _inject_mosaic_failure(monkeypatch, mode, fail_at=5, log=log)
    got_c, got_t = pair_stats_for_pairs(mat, pi, pj, mat.shape[1],
                                        batch=32)
    np.testing.assert_array_equal(got_c, want_c)
    np.testing.assert_array_equal(got_t, want_t)

    # The failing batch ran on pallas; everything after the failure ran
    # XLA-only — a single pallas->xla transition, never a re-upgrade.
    assert log.count("pallas") >= 6  # batch 0 + pipeline up to the fault
    assert "xla" in log
    first_xla = log.index("xla")
    assert all(p == "xla" for p in log[first_xla:]), \
        "pallas dispatch after the downgrade: use_pallas re-upgraded"
    # Enqueue-time failure is detected immediately: the faulting call is
    # the last pallas dispatch. (Settle-time surfaces only when the
    # ordered sync drains the batch, so later pallas enqueues are
    # expected there.)
    if mode == "enqueue":
        assert log.index("xla") == 6


@pytest.mark.parametrize("mode", ["enqueue", "settle"])
def test_mosaic_midstream_failure_explicit_pin_raises(monkeypatch, mode):
    """With use_pallas pinned explicitly, a mid-stream Mosaic failure
    must propagate — parity tests must never silently compare XLA to
    XLA (ops/_fallback.py policy)."""
    mat, pi, pj = _fault_pairs(seed=11)
    log = []
    _inject_mosaic_failure(monkeypatch, mode, fail_at=3, log=log)
    with pytest.raises(RuntimeError, match="injected Mosaic"):
        pair_stats_for_pairs(mat, pi, pj, mat.shape[1], batch=32,
                             use_pallas=True)


@pytest.mark.parametrize("shape", ["padded", "ragged"])
def test_gather_dense_strategy_parity(shape, monkeypatch):
    """The gather-dense strategy (dense tiles over permuted survivor
    rows, ops/sparse_device._gather_dense_pair_stats) is bit-identical
    to the XLA route, for a survivor list that exactly fills the tile
    caps and for a ragged one spanning multiple row blocks and column
    pieces."""
    from galah_tpu.utils import timing

    rng = np.random.default_rng(17)
    n = 80
    mat = _family_sketches(n=n, width=48, n_fam=10, seed=17,
                           mutations=8)
    if shape == "padded":
        # one full tile: GATHER_ROWS distinct a's, each paired once
        pi = np.arange(sparse_device.GATHER_ROWS, dtype=np.int64) % n
        pj = (pi + 1) % n
    else:
        # > GATHER_ROWS unique a's (second row block) and pair counts
        # that are not multiples of anything convenient
        pi = rng.integers(0, n - 1, size=333).astype(np.int64)
        pj = np.minimum(pi + 1 + rng.integers(0, 30, size=333), n - 1)
    want_c, want_t = pair_stats_for_pairs(mat, pi, pj, mat.shape[1],
                                          use_pallas=False)
    import jax
    import jax.numpy as jnp

    timing.reset()
    got = sparse_device._gather_dense_pair_stats(
        jax.device_put(jnp.asarray(mat)),
        pi.astype(np.int32), pj.astype(np.int32), mat.shape[1],
        interpret=True, explicit=True)
    assert got is not None
    np.testing.assert_array_equal(got[0], want_c)
    np.testing.assert_array_equal(got[1], want_t)
    counters = timing.GLOBAL.counters()
    assert counters["pairlist-gather-used"] == pi.shape[0]
    assert counters["pairlist-gather-cells"] >= pi.shape[0]


@pytest.mark.slow
def test_strategy_env_pins_every_route(monkeypatch):
    """GALAH_TPU_PAIRLIST_STRATEGY pins each route end-to-end through
    pair_stats_for_pairs with identical integers, and the decision
    counter records the pick. Slow tier: three interpret-mode kernel
    traces; tier-1 keeps per-route bit-identity (boundaries/gather
    parity tests) and the AUTO selection test below."""
    from galah_tpu.utils import timing

    mat = _family_sketches(n=90, width=48, n_fam=9, seed=23,
                           mutations=8)
    rng = np.random.default_rng(23)
    # 40 pairs: enough for multiple blocked grid steps while keeping
    # the interpret-mode grid walk short (gather parity across tile
    # shapes is pinned separately above)
    pi = rng.integers(0, 89, size=40).astype(np.int64)
    pj = np.minimum(pi + 1 + rng.integers(0, 20, size=40), 89)
    monkeypatch.delenv("GALAH_TPU_PAIRLIST_STRATEGY", raising=False)
    want_c, want_t = pair_stats_for_pairs(mat, pi, pj, mat.shape[1],
                                          use_pallas=False)
    for strat in ("cpu", "gather", "blocked"):
        monkeypatch.setenv("GALAH_TPU_PAIRLIST_STRATEGY", strat)
        timing.reset()
        got_c, got_t = pair_stats_for_pairs(
            mat, pi, pj, mat.shape[1], use_pallas=True, interpret=True)
        np.testing.assert_array_equal(got_c, want_c)
        np.testing.assert_array_equal(got_t, want_t)
        counters = timing.GLOBAL.counters()
        assert counters.get(f"pairlist-strategy-{strat}") == 1, strat


def test_auto_strategy_selection_regimes(monkeypatch):
    """The AUTO heuristic (ops/sparse_device._resolve_pairlist_strategy)
    picks cpu for tiny lists, gather only for duplication-heavy lists
    whose planned tile fill clears the rate crossover, blocked
    otherwise — and never deviates when the caller pinned a shape."""
    monkeypatch.delenv("GALAH_TPU_PAIRLIST_STRATEGY", raising=False)
    resolve = sparse_device._resolve_pairlist_strategy

    tiny = np.arange(10, dtype=np.int32)
    assert resolve(tiny, tiny + 1, True, False, None, None) == "cpu"
    assert resolve(tiny, tiny + 1, False, False, None, None) == "xla"
    # caller pins (explicit use_pallas / batch) keep the batched path
    assert resolve(tiny, tiny + 1, True, True, None, None) == "blocked"
    assert resolve(tiny, tiny + 1, True, False, None, 64) == "blocked"

    # low duplication at scale: scattered pairs over many rows
    rng = np.random.default_rng(7)
    pi = np.arange(2000, dtype=np.int32)
    pj = (pi + 1 + rng.integers(0, 5, size=2000).astype(np.int32))
    assert resolve(pi, pj, True, False, None, None) == "blocked"

    # 32-member family cliques: dup ~15.5 but each planned tile is
    # only ~12% full — not enough to beat the blocked kernel's design
    # rate, so AUTO stays blocked despite the duplication
    m = 32
    ii, jj = np.meshgrid(np.arange(m, dtype=np.int32),
                         np.arange(m, dtype=np.int32), indexing="ij")
    keep = ii < jj
    cpi = np.concatenate([ii[keep] + f * m for f in range(8)])
    cpj = np.concatenate([jj[keep] + f * m for f in range(8)])
    assert resolve(cpi, cpj, True, False, None, None) == "blocked"
    # ...unless the blocked kernel were slow enough that even 12%-full
    # dense tiles out-run it (rate crossover is live, not vestigial)
    monkeypatch.setattr(sparse_device, "BLOCKED_RATE_EST", 20_000.0)
    assert resolve(cpi, cpj, True, False, None, None) == "gather"
    monkeypatch.setattr(sparse_device, "BLOCKED_RATE_EST", 200_000.0)

    # dense bipartite block (GATHER_ROWS x GATHER_COLS all-pairs):
    # fill 1.0 — the regime gather-dense exists for
    ga = np.repeat(np.arange(sparse_device.GATHER_ROWS,
                             dtype=np.int32),
                   sparse_device.GATHER_COLS)
    gb = np.tile(np.arange(sparse_device.GATHER_COLS,
                           dtype=np.int32)
                 + sparse_device.GATHER_ROWS,
                 sparse_device.GATHER_ROWS)
    assert resolve(ga, gb, True, False, None, None) == "gather"

    monkeypatch.setenv("GALAH_TPU_PAIRLIST_STRATEGY", "gather")
    assert resolve(tiny, tiny + 1, True, False, None, None) == "gather"


def test_dispatch_counters_recorded(monkeypatch):
    """The sparse device pipeline records disp/sync counters under the
    active stage — the per-stage round-trip visibility the TPU e2e
    analysis relies on (utils/timing.dispatch)."""
    from galah_tpu.utils import timing

    mat = _family_sketches(n=64, width=48, n_fam=8, mutations=6)
    monkeypatch.setenv("GALAH_TPU_SPARSE_MIN_N", "2")
    timing.reset()
    with timing.stage("unit-pairwise"):
        threshold_pairs_sparse(mat, k=21, min_ani=0.90)
    counters = timing.GLOBAL.counters()
    assert counters.get("disp[unit-pairwise]", 0) >= 1
    assert counters.get("sync[unit-pairwise]", 0) >= 1
    assert counters["screen-candidates"] >= counters["screen-kept-pairs"]
