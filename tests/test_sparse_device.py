"""Device-path sparse screen: bit-identity with the dense tiled path,
sharded batch evaluation, and gate selection on device backends."""

import numpy as np
import pytest

import galah_tpu.ops.collision as collision
import galah_tpu.ops.sparse_device as sparse_device
from galah_tpu.ops.constants import SENTINEL
from galah_tpu.ops.pairwise import (
    _threshold_pairs_single,
    screen_pairs,
    threshold_pairs,
)
from galah_tpu.ops.sparse_device import (
    pair_stats_for_pairs,
    threshold_pairs_sparse,
)


def _family_sketches(n=1100, width=48, n_fam=80, seed=91,
                     mutations=25):
    """Family-structured sorted sketch matrix with ragged/empty rows."""
    rng = np.random.default_rng(seed)
    base = rng.integers(0, 1 << 62, size=(n_fam, width), dtype=np.uint64)
    mat = np.empty((n, width), dtype=np.uint64)
    for i in range(n):
        row = base[i % n_fam].copy()
        n_mut = int(rng.integers(0, mutations))
        idx = rng.choice(width, size=n_mut, replace=False)
        row[idx] = rng.integers(0, 1 << 62, size=n_mut, dtype=np.uint64)
        row.sort()
        mat[i] = row
    mat[3, 10:] = np.uint64(SENTINEL)   # ragged
    mat[9] = np.uint64(SENTINEL)        # empty
    mat.sort(axis=1)
    return mat


def test_sparse_equals_dense_threshold_sweep():
    mat = _family_sketches()
    for thr in (0.90, 0.95, 0.99):
        dense = _threshold_pairs_single(
            mat, k=21, min_ani=thr, sketch_size=mat.shape[1],
            row_tile=64, col_tile=128, use_pallas=False, cap_per_row=64)
        sparse = threshold_pairs_sparse(mat, k=21, min_ani=thr)
        assert sparse == dense, thr


def test_sparse_batched_partial_batches():
    """A batch size that does not divide the candidate count exercises
    the pad-and-trim path; results unchanged."""
    mat = _family_sketches(n=300, n_fam=30, seed=17)
    full = threshold_pairs_sparse(mat, k=21, min_ani=0.95)
    small = threshold_pairs_sparse(mat, k=21, min_ani=0.95, batch=37)
    assert small == full
    assert len(full) > 0


def test_pair_stats_for_pairs_sharded_equals_single():
    from galah_tpu.parallel.mesh import make_mesh

    mat = _family_sketches(n=200, n_fam=20, seed=23)
    rng = np.random.default_rng(5)
    pi = rng.integers(0, 199, size=501).astype(np.int64)
    pj = np.minimum(pi + 1 + rng.integers(0, 50, size=501), 199)
    c1, t1 = pair_stats_for_pairs(mat, pi, pj, mat.shape[1])
    mesh = make_mesh()
    c2, t2 = pair_stats_for_pairs(mat, pi, pj, mat.shape[1], mesh=mesh)
    np.testing.assert_array_equal(c1, c2)
    np.testing.assert_array_equal(t1, t2)


def test_no_collisions_returns_empty():
    rng = np.random.default_rng(7)
    n = 64
    mat = np.sort(
        rng.choice(1 << 62, size=(n, 32), replace=False)
        .astype(np.uint64), axis=1)
    assert threshold_pairs_sparse(mat, k=21, min_ani=0.95) == {}


def test_public_gate_selects_sparse_path(monkeypatch):
    """Above the crossover with no knobs pinned, threshold_pairs routes
    to the sparse device pipeline (with the auto mesh on a multi-device
    runtime) and returns the dense-identical result."""
    mat = _family_sketches(n=160, n_fam=16, seed=29)
    monkeypatch.setattr(collision, "SPARSE_SCREEN_MIN_N", 100)

    calls = {}
    real = sparse_device.threshold_pairs_sparse

    def spy(*args, **kwargs):
        calls["mesh"] = kwargs.get("mesh")
        calls["hit"] = True
        return real(*args, **kwargs)

    monkeypatch.setattr(sparse_device, "threshold_pairs_sparse", spy)
    got = threshold_pairs(mat, k=21, min_ani=0.95)
    assert calls.get("hit"), "sparse path must be selected"
    import jax

    if jax.device_count() > 1:
        assert calls["mesh"] is not None and calls["mesh"].devices.size > 1

    dense = _threshold_pairs_single(
        mat, k=21, min_ani=0.95, sketch_size=mat.shape[1],
        row_tile=64, col_tile=128, use_pallas=False, cap_per_row=64)
    assert got == dense


def test_public_gate_dense_env_pins_dense(monkeypatch):
    mat = _family_sketches(n=160, n_fam=16, seed=29)
    monkeypatch.setattr(collision, "SPARSE_SCREEN_MIN_N", 100)
    monkeypatch.setenv("GALAH_TPU_DENSE_PAIRS", "1")

    def boom(*a, **k):  # the sparse path must NOT be taken
        raise AssertionError("sparse path selected despite env pin")

    monkeypatch.setattr(sparse_device, "threshold_pairs_sparse", boom)
    got = threshold_pairs(mat, k=21, min_ani=0.95)
    assert len(got) > 0


def test_screen_pairs_sparse_on_any_backend(monkeypatch):
    """The marker screen's collision path is exact and now engages on
    every backend (the conftest runtime is an 8-device CPU mesh)."""
    rng = np.random.default_rng(41)
    n, m = 150, 40
    n_fam = 15
    base = rng.integers(0, 1 << 62, size=(n_fam, m), dtype=np.uint64)
    mat = np.empty((n, m), dtype=np.uint64)
    for i in range(n):
        row = base[i % n_fam].copy()
        n_mut = int(rng.integers(0, 12))
        idx = rng.choice(m, size=n_mut, replace=False)
        row[idx] = rng.integers(0, 1 << 62, size=n_mut, dtype=np.uint64)
        row.sort()
        mat[i] = row
    counts = np.full(n, m, dtype=np.int64)

    monkeypatch.setattr(collision, "SPARSE_SCREEN_MIN_N", 100)
    sparse = screen_pairs(mat, counts, 0.8)

    from galah_tpu.ops.pairwise import _screen_pairs_single

    dense = _screen_pairs_single(mat, counts, 0.8, 64, 128, 256, False)
    assert sorted(sparse) == sorted(dense)
    assert len(sparse) > 0


def test_sparse_device_equals_c_kernel_at_scale():
    """Cross-implementation equivalence on a large family matrix: the
    screened device pipeline (collision screen + gathered XLA pair
    stats) and the compiled-C merged walk (its own screen + C walk)
    must produce the identical pair dict — two independent
    implementations of the same contract."""
    cps = pytest.importorskip("galah_tpu.ops._cpairstats")

    mat = _family_sketches(n=5000, width=64, n_fam=250, seed=101,
                           mutations=30)
    via_device = threshold_pairs_sparse(mat, k=21, min_ani=0.95,
                                        sketch_size=mat.shape[1])
    via_c = cps.threshold_pairs_c(mat, mat.shape[1], 21, 0.95)
    # identical pair SETS exactly (the keep-check is rational f64 on
    # both sides); ANI values via approx — np.log and libm log are
    # independent transcendental implementations (repo precedent:
    # tests/test_cpairstats.py)
    assert set(via_device) == set(via_c)
    for key, v in via_device.items():
        assert via_c[key] == pytest.approx(v, abs=1e-12), key
    assert len(via_c) > 1000


class _LazyFail:
    """A device-future stand-in whose host materialization raises — the
    settle-time Mosaic failure shape (dispatch enqueues fine; the error
    surfaces when the ordered sync reads the buffer back)."""

    def __array__(self, dtype=None, copy=None):
        raise RuntimeError("injected Mosaic runtime failure (settle)")


def _inject_mosaic_failure(monkeypatch, mode, fail_at, log):
    """Patch the module-level _batch_pair_stats with a fake whose pallas
    path computes the real XLA integers (the two paths are bit-identical
    by contract) but fails on the fail_at-th pallas dispatch — raising at
    enqueue, or returning lazily-failing buffers for the settle site.
    Every call appends ("pallas"|"xla") to `log`."""
    real = sparse_device._batch_pair_stats

    def fake(jmat, pi, pj, sketch_size, use_pallas=False, interpret=False):
        exact = real(jmat, pi, pj, sketch_size=sketch_size,
                     use_pallas=False, interpret=False)
        if not use_pallas:
            log.append("xla")
            return exact
        n_before = log.count("pallas")
        log.append("pallas")
        if n_before == fail_at:
            if mode == "enqueue":
                raise RuntimeError(
                    "injected Mosaic runtime failure (enqueue)")
            return _LazyFail(), _LazyFail()
        return exact

    monkeypatch.setattr(sparse_device, "_batch_pair_stats", fake)
    import galah_tpu.ops.hll as hll

    monkeypatch.setattr(hll, "use_pallas_default", lambda: True)


def _fault_pairs(n=240, n_pairs=600, seed=3):
    mat = _family_sketches(n=n, n_fam=24, seed=seed)
    rng = np.random.default_rng(seed)
    pi = rng.integers(0, n - 1, size=n_pairs).astype(np.int64)
    pj = np.minimum(pi + 1 + rng.integers(0, 40, size=n_pairs), n - 1)
    return mat, pi, pj


@pytest.mark.parametrize("mode", ["enqueue", "settle"])
def test_mosaic_midstream_failure_downgrades_once(monkeypatch, mode):
    """A Mosaic runtime failure mid-pipeline — at dispatch enqueue or at
    host materialization of an in-flight batch — must downgrade the run
    to the XLA path exactly once and still produce integers bit-identical
    to a pure-XLA run (the downgrade_and_redo contract,
    ops/sparse_device.py). Analog of the reference's finish_command_safely
    fail-safe (reference: src/dashing.rs:101)."""
    mat, pi, pj = _fault_pairs()
    want_c, want_t = pair_stats_for_pairs(
        mat, pi, pj, mat.shape[1], batch=32, use_pallas=False)

    log = []
    _inject_mosaic_failure(monkeypatch, mode, fail_at=5, log=log)
    got_c, got_t = pair_stats_for_pairs(mat, pi, pj, mat.shape[1],
                                        batch=32)
    np.testing.assert_array_equal(got_c, want_c)
    np.testing.assert_array_equal(got_t, want_t)

    # The failing batch ran on pallas; everything after the failure ran
    # XLA-only — a single pallas->xla transition, never a re-upgrade.
    assert log.count("pallas") >= 6  # batch 0 + pipeline up to the fault
    assert "xla" in log
    first_xla = log.index("xla")
    assert all(p == "xla" for p in log[first_xla:]), \
        "pallas dispatch after the downgrade: use_pallas re-upgraded"
    # Enqueue-time failure is detected immediately: the faulting call is
    # the last pallas dispatch. (Settle-time surfaces only when the
    # ordered sync drains the batch, so later pallas enqueues are
    # expected there.)
    if mode == "enqueue":
        assert log.index("xla") == 6


@pytest.mark.parametrize("mode", ["enqueue", "settle"])
def test_mosaic_midstream_failure_explicit_pin_raises(monkeypatch, mode):
    """With use_pallas pinned explicitly, a mid-stream Mosaic failure
    must propagate — parity tests must never silently compare XLA to
    XLA (ops/_fallback.py policy)."""
    mat, pi, pj = _fault_pairs(seed=11)
    log = []
    _inject_mosaic_failure(monkeypatch, mode, fail_at=3, log=log)
    with pytest.raises(RuntimeError, match="injected Mosaic"):
        pair_stats_for_pairs(mat, pi, pj, mat.shape[1], batch=32,
                             use_pallas=True)


def test_dispatch_counters_recorded(monkeypatch):
    """The sparse device pipeline records disp/sync counters under the
    active stage — the per-stage round-trip visibility the TPU e2e
    analysis relies on (utils/timing.dispatch)."""
    from galah_tpu.utils import timing

    mat = _family_sketches(n=64, width=48, n_fam=8, mutations=6)
    monkeypatch.setenv("GALAH_TPU_SPARSE_MIN_N", "2")
    timing.reset()
    with timing.stage("unit-pairwise"):
        threshold_pairs_sparse(mat, k=21, min_ani=0.90)
    counters = timing.GLOBAL.counters()
    assert counters.get("disp[unit-pairwise]", 0) >= 1
    assert counters.get("sync[unit-pairwise]", 0) >= 1
    assert counters["screen-candidates"] >= counters["screen-kept-pairs"]
