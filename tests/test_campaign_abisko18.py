"""Accuracy campaign goldens: the full 18-MAG abisko4 fixture set.

The reference's own tests cover only 4-5 of the 18 abisko4 MAGs
(reference: src/clusterer.rs:481-663); this campaign clusters ALL 18
with every backend combo. Goldens derived 2026-07-29 via
scripts/campaign_abisko18.py: all four combos (finch+skani,
finch+fastani, skani+skani, dashing+skani) produced IDENTICAL
compositions at both 95% and 99% ANI — pinned below.

The default suite runs one combo per threshold (about 3 minutes each on
the CPU mesh); set GALAH_RUN_CAMPAIGN=1 to run the full combo matrix.
"""

import glob
import os

import pytest

DATA = "/root/reference/tests/data/abisko4"

GOLDEN_95 = [sorted([
    "73.20110600_S2D.10.fna", "73.20110600_S3M.17.fna",
    "73.20110700_S2D.12.fna", "73.20110700_S2M.14.fna",
    "73.20110800_S1D.9.fna", "73.20110800_S2D.13.fna",
    "73.20110800_S2M.16.fna", "73.20110800_S3D.14.fna",
    "73.20120600_E3D.30.fna", "73.20120600_S2D.19.fna",
    "73.20120700_S1D.20.fna", "73.20120700_S1X.9.fna",
    "73.20120700_S2X.9.fna", "73.20120700_S3D.12.fna",
    "73.20120700_S3X.12.fna", "73.20120800_S1D.21.fna",
    "73.20120800_S1X.13.fna", "73.20120800_S2X.9.fna",
])]

GOLDEN_99 = sorted([
    sorted([
        "73.20110600_S2D.10.fna", "73.20110700_S2D.12.fna",
        "73.20110700_S2M.14.fna", "73.20110800_S2D.13.fna",
        "73.20110800_S2M.16.fna", "73.20110800_S3D.14.fna",
        "73.20120600_S2D.19.fna", "73.20120700_S1D.20.fna",
        "73.20120800_S1D.21.fna", "73.20120800_S1X.13.fna",
        "73.20120800_S2X.9.fna",
    ]),
    ["73.20110600_S3M.17.fna"],
    sorted([
        "73.20110800_S1D.9.fna", "73.20120700_S1X.9.fna",
        "73.20120700_S2X.9.fna", "73.20120700_S3D.12.fna",
        "73.20120700_S3X.12.fna",
    ]),
    ["73.20120600_E3D.30.fna"],
])

_FULL = os.environ.get("GALAH_RUN_CAMPAIGN") == "1"
COMBOS_95 = ([("finch", "skani"), ("finch", "fastani"),
              ("skani", "skani"), ("dashing", "skani")]
             if _FULL else [("dashing", "skani")])
COMBOS_99 = (COMBOS_95 if _FULL else [("finch", "skani")])


def _run(paths, pre, cl, ani, extra=None):
    from galah_tpu.api import generate_galah_clusterer

    values = {
        "ani": ani, "precluster_ani": 90.0,
        "min_aligned_fraction": 15.0, "fragment_length": 3000,
        "precluster_method": pre, "cluster_method": cl, "threads": 1,
        "checkm_tab_table": f"{DATA}/abisko4.csv",
        "quality_formula": "Parks2020_reduced",
    }
    values.update(extra or {})
    clusterer = generate_galah_clusterer(list(paths), values)
    clusters = clusterer.cluster()
    names = [p.rsplit("/", 1)[1] for p in clusterer.genome_paths]
    return sorted(sorted(names[i] for i in cluster)
                  for cluster in clusters)


@pytest.fixture(scope="module")
def mag_paths(ref_data):
    paths = sorted(glob.glob(f"{DATA}/*.fna"))
    if len(paths) != 18:
        pytest.skip("abisko4 fixture incomplete")
    return paths


@pytest.mark.slow
@pytest.mark.parametrize("pre,cl", COMBOS_95)
def test_all18_at_95(mag_paths, pre, cl):
    assert _run(mag_paths, pre, cl, 95.0) == GOLDEN_95


@pytest.mark.slow
@pytest.mark.parametrize("pre,cl", COMBOS_99)
def test_all18_at_99(mag_paths, pre, cl):
    assert _run(mag_paths, pre, cl, 99.0) == GOLDEN_99


FAST = {"hash_algorithm": "tpufast", "ani_subsample": 16}


def test_all18_fast_mode_matches_dense_goldens(mag_paths):
    """The fast path (--hash-algorithm tpufast --ani-subsample 16)
    must reproduce the dense murmur3 golden composition. The suite pins
    the discriminative 99% threshold (4 clusters); set
    GALAH_RUN_CAMPAIGN=1 to also pin 95%."""
    assert _run(mag_paths, "finch", "skani", 99.0, extra=FAST) \
        == GOLDEN_99
    if _FULL:
        assert _run(mag_paths, "finch", "skani", 95.0, extra=FAST) \
            == GOLDEN_95


def test_windowed_waste_bounded_on_abisko18(mag_paths, monkeypatch):
    """Force the windowed rep scan (dense warm pass off) over all 18
    MAGs and bound the measured speculative waste: the membership
    argmax consults every (non-rep, rep) pair anyway, so the window's
    extra ANIs are almost all consumed — the counter proves the
    docstring's claim instead of asserting it."""
    monkeypatch.setenv("GALAH_TPU_GREEDY_STRATEGY", "host")
    from galah_tpu.api import generate_galah_clusterer
    from galah_tpu.cluster import cluster as engine_cluster
    from galah_tpu.utils import timing

    values = {
        "ani": 99.0, "precluster_ani": 90.0,
        "min_aligned_fraction": 15.0, "fragment_length": 3000,
        "precluster_method": "finch", "cluster_method": "skani",
        "threads": 1, "checkm_tab_table": f"{DATA}/abisko4.csv",
        "quality_formula": "Parks2020_reduced",
    }
    values.update(FAST)
    gc = generate_galah_clusterer(list(mag_paths), values)
    before = timing.GLOBAL.counters()
    clusters = engine_cluster(gc.genome_paths, gc.preclusterer,
                              gc.clusterer, dense_precluster_cap=0)
    after = timing.GLOBAL.counters()
    computed = (after.get("exact-ani-computed", 0)
                - before.get("exact-ani-computed", 0))
    wasted = (after.get("exact-ani-wasted", 0)
              - before.get("exact-ani-wasted", 0))
    assert computed > 0
    # measured 2026-07-30: 62 computed, 0 wasted (the membership argmax
    # consults every (non-rep, rep) pair, consuming the speculation);
    # bound at 25% so a regression in the policy trips loudly
    assert wasted <= 0.25 * computed, (wasted, computed)

    names = [p.rsplit("/", 1)[1] for p in gc.genome_paths]
    got = sorted(sorted(names[i] for i in c) for c in clusters)
    assert got == GOLDEN_99
