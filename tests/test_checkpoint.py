"""Checkpoint/resume (cluster/checkpoint.py).

The reference has no resume capability (SURVEY.md §5); here an
interrupted run must (a) not recompute the distance pass, (b) skip
finished preclusters, and (c) produce identical clusters to an
uninterrupted run.
"""

from typing import List, Sequence

import pytest

from galah_tpu.backends.base import ClusterBackend, PreclusterBackend
from galah_tpu.cluster import cluster
from galah_tpu.cluster.cache import PairDistanceCache
from galah_tpu.cluster.checkpoint import ClusterCheckpoint, run_fingerprint


class FakePre(PreclusterBackend):
    """Synthetic preclusterer over integer 'paths': pairs within the same
    decade are preclustered (used only by engine/checkpoint tests —
    production tests use the real backends)."""

    def __init__(self):
        self.calls = 0

    def method_name(self):
        return "fake"

    def distances(self, paths: Sequence[str]) -> PairDistanceCache:
        self.calls += 1
        cache = PairDistanceCache()
        vals = [int(p) for p in paths]
        for i in range(len(vals)):
            for j in range(i + 1, len(vals)):
                if vals[i] // 10 == vals[j] // 10:
                    cache.insert((i, j), 0.95)
        return cache


class FakeCl(ClusterBackend):
    """ANI = 1 - |a-b|/100 over integer 'paths'."""

    def __init__(self, threshold: float):
        self._thr = threshold
        self.pairs_computed: List = []

    def method_name(self):
        return "fakecl"

    @property
    def ani_threshold(self):
        return self._thr

    def calculate_ani_batch(self, pairs):
        self.pairs_computed.extend(pairs)
        return [1.0 - abs(int(a) - int(b)) / 100.0 for a, b in pairs]


GENOMES = ["1", "3", "9", "11", "19", "40", "42", "77"]


def test_resume_skips_distance_pass_and_done_preclusters(tmp_path):
    fp = run_fingerprint(GENOMES, "fake", "fakecl", 0.95, 0.9)

    pre1 = FakePre()
    ck1 = ClusterCheckpoint(str(tmp_path / "ck"), fp)
    ref = cluster(GENOMES, pre1, FakeCl(0.95), checkpoint=ck1)
    assert pre1.calls == 1

    # resume: distances loaded from disk, every precluster already done
    pre2 = FakePre()
    cl2 = FakeCl(0.95)
    ck2 = ClusterCheckpoint(str(tmp_path / "ck"), fp)
    out = cluster(GENOMES, pre2, cl2, checkpoint=ck2)
    assert pre2.calls == 0
    assert cl2.pairs_computed == []
    assert out == ref


def test_changed_fingerprint_starts_fresh(tmp_path):
    fp1 = run_fingerprint(GENOMES, "fake", "fakecl", 0.95, 0.9)
    ck1 = ClusterCheckpoint(str(tmp_path / "ck"), fp1)
    cluster(GENOMES, FakePre(), FakeCl(0.95), checkpoint=ck1)

    fp2 = run_fingerprint(GENOMES, "fake", "fakecl", 0.99, 0.9)
    pre = FakePre()
    ck2 = ClusterCheckpoint(str(tmp_path / "ck"), fp2)
    cluster(GENOMES, pre, FakeCl(0.99), checkpoint=ck2)
    assert pre.calls == 1  # stale checkpoint discarded, distances re-run


def test_checkpointed_equals_uncheckpointed(tmp_path):
    plain = cluster(GENOMES, FakePre(), FakeCl(0.95))
    fp = run_fingerprint(GENOMES, "fake", "fakecl", 0.95, 0.9)
    ck = ClusterCheckpoint(str(tmp_path / "ck"), fp)
    with_ck = cluster(GENOMES, FakePre(), FakeCl(0.95), checkpoint=ck)
    assert plain == with_ck


def test_distance_cache_none_values_roundtrip(tmp_path):
    fp = run_fingerprint(["a"], "x", "y", 0.9, 0.8)
    ck = ClusterCheckpoint(str(tmp_path / "ck"), fp)
    cache = PairDistanceCache()
    cache.insert((0, 1), 0.97)
    cache.insert((1, 2), None)  # gated-out pair: computed but None
    ck.save_distances(cache)
    back = ck.load_distances()
    assert back == cache
    assert back.contains((1, 2)) and back.get((1, 2)) is None


def test_dense_precluster_single_dispatch_same_result(monkeypatch):
    """Small preclusters warm ALL hit pairs in one backend call; the
    clusters must equal the per-genome dispatch path's exactly. (The
    dense-warm pass is a host-strategy mechanism — pin it.)"""
    monkeypatch.setenv("GALAH_TPU_GREEDY_STRATEGY", "host")
    from galah_tpu.cluster.engine import cluster as eng_cluster

    pre = FakePre()
    cl_dense = FakeCl(0.95)
    dense = eng_cluster(GENOMES, pre, cl_dense, dense_precluster_cap=64)

    cl_lazy = FakeCl(0.95)
    lazy = eng_cluster(GENOMES, FakePre(), cl_lazy,
                       dense_precluster_cap=0)
    assert dense == lazy
    # dense path: one calculate_ani_batch call per precluster with hits;
    # count the calls via a wrapper
    calls = []
    cl_counted = FakeCl(0.95)
    orig = cl_counted.calculate_ani_batch
    cl_counted.calculate_ani_batch = lambda p: (calls.append(len(p)),
                                                orig(p))[1]
    eng_cluster(GENOMES, FakePre(), cl_counted, dense_precluster_cap=64)
    n_preclusters_with_pairs = 3  # decades 0,1,4 have >=2 members
    assert len(calls) == n_preclusters_with_pairs


def test_torn_record_dropped_on_resume(tmp_path, caplog):
    """A kill mid-append leaves a half-written last line in
    clusters.jsonl; load_completed drops exactly that record (with a
    warning) and keeps the intact ones."""
    import logging

    fp = run_fingerprint(GENOMES, "fake", "fakecl", 0.95, 0.9)
    ck1 = ClusterCheckpoint(str(tmp_path / "ck"), fp)
    cluster(GENOMES, FakePre(), FakeCl(0.95), checkpoint=ck1)

    fn = tmp_path / "ck" / "clusters.jsonl"
    lines = fn.read_text().splitlines(keepends=True)
    assert len(lines) >= 2
    fn.write_text("".join(lines[:-1])
                  + lines[-1][: len(lines[-1]) // 2].rstrip("\n"))

    ck2 = ClusterCheckpoint(str(tmp_path / "ck"), fp)
    with caplog.at_level(logging.WARNING):
        done = ck2.load_completed()
    assert len(done) == len(lines) - 1
    assert "torn checkpoint record" in caplog.text


def test_torn_record_resume_identical_clusters(tmp_path):
    """Resuming over a torn tail recomputes only that precluster and
    produces clusters identical to the uninterrupted run."""
    fp = run_fingerprint(GENOMES, "fake", "fakecl", 0.95, 0.9)
    ck1 = ClusterCheckpoint(str(tmp_path / "ck"), fp)
    ref = cluster(GENOMES, FakePre(), FakeCl(0.95), checkpoint=ck1)

    fn = tmp_path / "ck" / "clusters.jsonl"
    lines = fn.read_text().splitlines(keepends=True)
    fn.write_text("".join(lines[:-1])
                  + lines[-1][: len(lines[-1]) // 2].rstrip("\n"))

    pre = FakePre()
    cl = FakeCl(0.95)
    ck2 = ClusterCheckpoint(str(tmp_path / "ck"), fp)
    out = cluster(GENOMES, pre, cl, checkpoint=ck2)
    assert out == ref
    assert pre.calls == 0  # distance pass still resumed from disk


# -- fingerprint path normalization + --resume strictness -------------


def test_fingerprint_insensitive_to_path_spelling(tmp_path):
    """./a.fna, a.fna, an absolute path, and a symlinked spelling of
    the same file must fingerprint identically — a resume launched
    from a different cwd must not discard a valid checkpoint."""
    import os

    from galah_tpu.cluster.checkpoint import fingerprint_fields

    g = tmp_path / "a.fna"
    g.write_text(">c\nACGT\n")
    link = tmp_path / "ln.fna"
    os.symlink(g, link)
    old = os.getcwd()
    os.chdir(tmp_path)
    try:
        spellings = ["a.fna", "./a.fna", str(g), "ln.fna", str(link)]
        fields = [fingerprint_fields([s], "p", "c", 0.95, 0.9)
                  for s in spellings]
    finally:
        os.chdir(old)
    assert all(f == fields[0] for f in fields[1:])
    assert fields[0]["genomes"] == [str(g)]


def test_fingerprint_differs_for_different_files(tmp_path):
    from galah_tpu.cluster.checkpoint import (fields_digest,
                                              fingerprint_fields)

    a = fields_digest(fingerprint_fields(["a"], "p", "c", 0.95, 0.9))
    b = fields_digest(fingerprint_fields(["b"], "p", "c", 0.95, 0.9))
    assert a != b


def test_mismatch_logs_differing_field_names(tmp_path, caplog):
    """Operators get the CHANGED FIELD by name, not just two sha256s."""
    import logging

    from galah_tpu.cluster.checkpoint import (fields_digest,
                                              fingerprint_fields)

    f1 = fingerprint_fields(GENOMES, "fake", "fakecl", 0.95, 0.9)
    ClusterCheckpoint(str(tmp_path / "ck"), fields_digest(f1),
                      fields=f1)
    f2 = fingerprint_fields(GENOMES, "fake", "fakecl", 0.99, 0.9)
    with caplog.at_level(logging.WARNING):
        ClusterCheckpoint(str(tmp_path / "ck"), fields_digest(f2),
                          fields=f2)
    assert "mismatched fields: ani" in caplog.text
    assert "checkpoint=0.95" in caplog.text and "run=0.99" in caplog.text


def test_require_match_raises_on_mismatch_and_keeps_state(tmp_path):
    """--resume refuses to silently discard a checkpoint that belongs
    to a different configuration."""
    from galah_tpu.cluster.checkpoint import (fields_digest,
                                              fingerprint_fields)

    f1 = fingerprint_fields(GENOMES, "fake", "fakecl", 0.95, 0.9)
    ck = ClusterCheckpoint(str(tmp_path / "ck"), fields_digest(f1),
                           fields=f1)
    cluster(GENOMES, FakePre(), FakeCl(0.95), checkpoint=ck)

    f2 = fingerprint_fields(GENOMES, "fake", "fakecl", 0.99, 0.9)
    with pytest.raises(ValueError, match="different run configuration"):
        ClusterCheckpoint(str(tmp_path / "ck"), fields_digest(f2),
                          fields=f2, require_match=True)
    # the mismatching open must NOT have wiped the state
    assert (tmp_path / "ck" / "clusters.jsonl").exists()


def test_require_match_raises_on_empty_dir(tmp_path):
    from galah_tpu.cluster.checkpoint import (fields_digest,
                                              fingerprint_fields)

    f = fingerprint_fields(GENOMES, "fake", "fakecl", 0.95, 0.9)
    with pytest.raises(ValueError, match="no checkpoint fingerprint"):
        ClusterCheckpoint(str(tmp_path / "ck"), fields_digest(f),
                          fields=f, require_match=True)


def test_interruption_log_roundtrip(tmp_path):
    from galah_tpu.cluster.checkpoint import (fields_digest,
                                              fingerprint_fields)

    f = fingerprint_fields(GENOMES, "fake", "fakecl", 0.95, 0.9)
    ck = ClusterCheckpoint(str(tmp_path / "ck"), fields_digest(f),
                           fields=f)
    assert ck.load_interruptions() == []
    ck.record_interruption({"signal": "SIGTERM",
                            "boundary": "greedy-round-saved"})
    ck.record_interruption({"signal": "SIGTERM",
                            "boundary": "precluster-saved"})
    ck2 = ClusterCheckpoint(str(tmp_path / "ck"), fields_digest(f),
                            fields=f)
    chain = ck2.load_interruptions()
    assert [c["boundary"] for c in chain] == ["greedy-round-saved",
                                              "precluster-saved"]
