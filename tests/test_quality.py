"""Quality layer: parsers, formulas, filtering, ordering."""

import pytest

from galah_tpu.io.fasta import GenomeStats
from galah_tpu import quality


def test_read_genome_info(ref_data):
    table = quality.read_genome_info_file(
        str(ref_data / "set1" / "genomeInfo.csv"))
    assert table["1mbp"].completeness == pytest.approx(1.0)
    assert table["1mbp"].contamination == pytest.approx(0.0)
    assert table["500kb"].completeness == pytest.approx(0.5)
    assert table["500kb"].contamination == pytest.approx(0.01)


def test_read_genome_info_bad_headers(tmp_path):
    p = tmp_path / "bad.csv"
    p.write_text("genome,comp,cont\na,1,2\n")
    with pytest.raises(ValueError, match="Incorrect headers"):
        quality.read_genome_info_file(str(p))


def test_read_genome_info_duplicate(tmp_path):
    p = tmp_path / "dup.csv"
    p.write_text("genome,completeness,contamination\na,90,1\na,80,2\n")
    with pytest.raises(ValueError, match="multiple times"):
        quality.read_genome_info_file(str(p))


def test_read_checkm1(ref_data):
    table = quality.read_checkm1_tab_table(
        str(ref_data / "abisko4" / "abisko4.csv"))
    q = table["73.20110600_S2D.10"]
    assert q.completeness == pytest.approx(0.7854)
    assert q.contamination == pytest.approx(0.0065)
    assert q.strain_heterogeneity == pytest.approx(0.0)
    q2 = table["73.20110600_S3M.17"]
    assert q2.strain_heterogeneity == pytest.approx(33.33)


def test_read_checkm2(tmp_path):
    p = tmp_path / "quality_report.tsv"
    p.write_text("Name\tCompleteness\tContamination\tSomething\n"
                 "g1\t95.5\t2.5\tx\n")
    table = quality.read_checkm2_quality_report(str(p))
    assert table["g1"].completeness == pytest.approx(0.955)
    assert table["g1"].contamination == pytest.approx(0.025)
    assert table["g1"].strain_heterogeneity is None


def test_retrieve_by_stem():
    table = {"g1": quality.GenomeQuality(0.9, 0.01)}
    assert quality.retrieve(table, "/some/dir/g1.fna").completeness == 0.9
    with pytest.raises(KeyError, match="Failed to find CheckM statistics"):
        quality.retrieve(table, "/some/dir/g2.fna")


def _stats(mapping):
    return lambda p: mapping[p]


def test_formula_flip_4contamination_vs_parks(ref_data):
    """The reference's CLI goldens: completeness-4contamination ranks
    S1D.21 (95.21/0.00) above S2M.16 (95.92/0.65); Parks2020_reduced
    flips the order (reference: tests/test_cmdline.rs:8-57)."""
    table = quality.read_checkm1_tab_table(
        str(ref_data / "abisko4" / "abisko4.csv"))
    g1 = str(ref_data / "abisko4" / "73.20120800_S1D.21.fna")
    g2 = str(ref_data / "abisko4" / "73.20110800_S2M.16.fna")

    out4 = quality.filter_and_order_genomes(
        [g1, g2], table, formula="completeness-4contamination")
    assert out4 == [g1, g2]

    outp = quality.filter_and_order_genomes(
        [g1, g2], table, formula="Parks2020_reduced")
    assert outp == [g2, g1]


def test_min_completeness_filter():
    table = {
        "a": quality.GenomeQuality(0.9, 0.01),
        "b": quality.GenomeQuality(0.5, 0.01),
        "c": quality.GenomeQuality(0.95, 0.2),
    }
    out = quality.filter_and_order_genomes(
        ["a.fna", "b.fna", "c.fna"], table,
        formula="completeness-4contamination",
        min_completeness=0.7, max_contamination=0.1)
    assert out == ["a.fna"]


def test_drep_formula_requires_heterogeneity():
    table = {"a": quality.GenomeQuality(0.9, 0.01)}
    with pytest.raises(ValueError, match="dRep quality formula"):
        quality.filter_and_order_genomes(
            ["a.fna"], table, formula="dRep",
            stats_fn=_stats({"a.fna": GenomeStats(1, 0, 1000)}))


def test_drep_formula_score_order():
    table = {
        "a": quality.GenomeQuality(0.9, 0.05, strain_heterogeneity=100.0),
        "b": quality.GenomeQuality(0.9, 0.05, strain_heterogeneity=0.0),
    }
    stats = _stats({
        "a.fna": GenomeStats(10, 0, 10000),
        "b.fna": GenomeStats(10, 0, 10000),
    })
    # higher heterogeneity discounts contamination -> a scores higher
    out = quality.filter_and_order_genomes(
        ["b.fna", "a.fna"], table, formula="dRep", stats_fn=stats)
    assert out == ["a.fna", "b.fna"]
