"""Native ingestion kernel: build, golden stats, parity with numpy parser.

Goldens are the reference's (reference: src/genome_stats.rs:61-87): the
abisko4 MAG 73.20110600_S2D.10 has 161 contigs, 6506 ambiguous bases,
N50 8289.
"""

import gzip
import importlib

import numpy as np
import pytest

from galah_tpu.io import fasta


@pytest.fixture(scope="module")
def cingest():
    try:
        return importlib.import_module("galah_tpu.io._cingest")
    except ImportError as e:
        pytest.fail(f"native ingestion kernel failed to build: {e}")


def _numpy_read(path):
    """The pure-numpy reference parse, bypassing the C fast path."""
    return fasta.read_genome_numpy(str(path))


def _assert_parity(cingest, path):
    ref = _numpy_read(path)
    codes, offsets, n_amb, n50 = cingest.read_fasta(str(path))
    np.testing.assert_array_equal(codes, ref.codes)
    np.testing.assert_array_equal(offsets, ref.contig_offsets)
    assert n_amb == ref.stats.num_ambiguous_bases
    assert n50 == ref.stats.n50
    assert offsets.shape[0] - 1 == ref.stats.num_contigs


def test_golden_stats_native(cingest, ref_data):
    path = ref_data / "abisko4" / "73.20110600_S2D.10.fna"
    _, offsets, n_amb, n50 = cingest.read_fasta(str(path))
    assert offsets.shape[0] - 1 == 161
    assert n_amb == 6506
    assert n50 == 8289


def test_parity_reference_fixtures(cingest, ref_data):
    for rel in ["abisko4/73.20110600_S2D.10.fna",
                "set1/1mbp.fna",
                "set1/500kb.fna"]:
        _assert_parity(cingest, ref_data / rel)


def test_parity_edge_cases(cingest, tmp_path):
    cases = {
        "plain.fna": b">a\nACGT\nNNacgt\n>b\nTTTT\n",
        "crlf.fna": b">a desc\r\nAC GT\r\n\r\n>b\r\nNN\r\n",
        "leading_junk.fna": b"ACGT\n>a\nACGT\n",
        "empty_contig.fna": b">a\n>b\nACGT\n",
        "no_trailing_newline.fna": b">a\nACGTAC",
        "indented_header.fna": b"  >a\nACGT\n  >b\nTT\n",
    }
    for name, content in cases.items():
        p = tmp_path / name
        p.write_bytes(content)
        _assert_parity(cingest, p)


def test_parity_gzip(cingest, tmp_path):
    p = tmp_path / "g.fna.gz"
    with gzip.open(p, "wb") as fh:
        fh.write(b">a\nACGTN\n>b\nacgtacgt\n")
    _assert_parity(cingest, p)


def test_no_records_native(cingest, tmp_path):
    p = tmp_path / "empty.fna"
    p.write_bytes(b"\n\n")
    with pytest.raises(ValueError):
        cingest.read_fasta(str(p))


def test_read_genome_uses_c_path(ref_data):
    """read_genome must produce identical results whether or not the C
    fast path is active (it is active here if the build succeeded)."""
    path = str(ref_data / "set1" / "500kb.fna")
    g = fasta.read_genome(path)
    ref = _numpy_read(path)
    np.testing.assert_array_equal(g.codes, ref.codes)
    np.testing.assert_array_equal(g.contig_offsets, ref.contig_offsets)
    assert g.stats == ref.stats
