"""Multi-host scaffolding (parallel/distributed.py) on the single-process
8-device CPU mesh; the strided->contiguous permutation math is checked
by direct simulation since multiple processes can't run under pytest."""

import numpy as np

import jax

from galah_tpu.parallel import distributed, make_mesh


def test_initialize_noop_single_process(monkeypatch):
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    distributed.initialize()  # must not raise or block
    assert distributed.process_count() == 1
    assert distributed.process_index() == 0


def test_host_shard_single_process():
    items = list(range(10))
    assert distributed.host_shard(items) == items


def test_global_sketch_matrix_single_process_row_sharded():
    mesh = make_mesh(8)
    n, k = 16, 32
    mat = np.arange(n * k, dtype=np.uint64).reshape(n, k)
    arr = distributed.global_sketch_matrix(mat, n, mesh)
    np.testing.assert_array_equal(np.asarray(arr), mat)
    # row-sharded over the 8 devices: each shard is 2 rows
    shards = arr.addressable_shards
    assert len(shards) == 8
    assert all(s.data.shape == (2, k) for s in shards)


def test_strided_permutation_roundtrip():
    """host_shard hands host p rows [p, p+P, ...]; the inverse permutation
    used by global_sketch_matrix must restore contiguous global order."""
    for n_proc, per in [(4, 3), (2, 8), (8, 2)]:
        global_n = n_proc * per
        s_idx = np.arange(global_n)
        g_idx = (s_idx % per) * n_proc + (s_idx // per)
        inv = np.empty(global_n, dtype=np.int64)
        inv[g_idx] = s_idx

        # strided layout: host p's block holds rows [p, p+P, ...]
        strided = np.concatenate(
            [np.arange(global_n)[p::n_proc] for p in range(n_proc)])
        np.testing.assert_array_equal(strided[inv], np.arange(global_n))


def test_sharded_pipeline_from_global_matrix():
    """The assembled global matrix feeds the sharded pair counter."""
    from galah_tpu.parallel import sharded_pair_count

    mesh = make_mesh(8)
    rng = np.random.default_rng(0)
    mat = rng.integers(0, 1 << 63, size=(32, 64), dtype=np.uint64)
    mat.sort(axis=1)
    mat[5] = mat[2]
    count = sharded_pair_count(mat, k=21, min_ani=0.99, mesh=mesh,
                               col_tile=8)
    assert count == 1


def test_sharded_threshold_pairs_matches_single_device():
    """The 8-device column-sharded sparse extraction must produce the
    exact same pair dict as ops/pairwise.threshold_pairs."""
    from galah_tpu.ops.pairwise import threshold_pairs
    from galah_tpu.parallel import sharded_threshold_pairs

    mesh = make_mesh(8)
    rng = np.random.default_rng(3)
    n, width = 100, 256
    mat = rng.integers(0, 1 << 63, size=(n, width), dtype=np.uint64)
    # plant overlapping pairs at various ANI levels
    mat[10] = mat[4]
    mat[77, :200] = mat[30, :200]
    mat[99, :128] = mat[0, :128]
    mat.sort(axis=1)

    # mesh=make_mesh(1) pins the single-device implementation (on the
    # 8-device test runtime threshold_pairs would otherwise auto-shard)
    ref = threshold_pairs(mat, k=21, min_ani=0.9, row_tile=16,
                          col_tile=32, mesh=make_mesh(1))
    got = sharded_threshold_pairs(mat, k=21, min_ani=0.9, mesh=mesh,
                                  row_tile=16, col_tile=32)
    assert got == ref
    assert (4, 10) in got


def test_sharded_hll_threshold_pairs_matches_single_device():
    import jax.numpy as jnp

    from galah_tpu.ops import hll
    from galah_tpu.parallel.mesh import sharded_hll_threshold_pairs

    rng = np.random.default_rng(11)
    n, p = 50, 10
    mat = np.zeros((n, 1 << p), dtype=np.uint8)
    for i in range(n):
        h = rng.integers(0, 1 << 63, size=40_000, dtype=np.uint64) * 2 + 1
        mat[i] = np.asarray(hll._hll_update(
            jnp.zeros((1 << p,), dtype=jnp.uint8), jnp.asarray(h), p))
    mat[31] = mat[6]

    ref = hll.hll_threshold_pairs(mat, k=21, min_ani=0.95,
                                  mesh=make_mesh(1), use_pallas=False)
    got = sharded_hll_threshold_pairs(mat, k=21, min_ani=0.95,
                                      mesh=make_mesh(8))
    assert set(got) == set(ref)
    assert (6, 31) in got
    for key in got:
        assert abs(got[key] - ref[key]) < 1e-6


def test_allgather_host_rows_single_process():
    """Single-process: the exchange protocol is an identity (one shard
    holds every row)."""
    import numpy as np

    from galah_tpu.parallel import distributed

    rows = np.arange(12, dtype=np.uint64).reshape(4, 3)
    out = distributed.allgather_host_rows(4, rows, fill=np.uint64(0))
    np.testing.assert_array_equal(out, rows)


def test_tokens_agree_single_process():
    from galah_tpu.parallel import distributed

    assert distributed.tokens_agree(b"anything")


def test_checkpoint_state_token_and_reset(tmp_path):
    """The token changes with resumable state and reset drops it."""
    from galah_tpu.cluster.cache import PairDistanceCache
    from galah_tpu.cluster.checkpoint import ClusterCheckpoint

    ck = ClusterCheckpoint(str(tmp_path / "ck"), "fp")
    t0 = ck.state_token()
    cache = PairDistanceCache()
    cache.insert((0, 1), 0.99)
    ck.save_distances(cache)
    t1 = ck.state_token()
    assert t1 != t0
    ck.save_precluster(0, [[0, 1]])
    t2 = ck.state_token()
    assert t2 != t1
    ck.reset_state()
    assert ck.state_token() == t0
    assert ck.load_distances() is None
    assert ck.load_completed() == {}
