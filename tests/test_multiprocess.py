"""Two-process jax.distributed smoke test (real multi-process, CPU).

Round-1 review finding: the jax.distributed init path had never executed
with num_processes > 1. Here two actual OS processes rendezvous through
a local coordinator, each owning 4 virtual CPU devices (8 global),
assemble the globally-sharded sketch matrix from per-host strided
shards, and run the sharded pair count — whose result must match the
single-process value. This is the DCN scale-out path of SURVEY.md §5
exercised for real (reference analog: none — the reference is strictly
single-process, SURVEY.md §2.3).
"""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "_dist_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _expected_count() -> int:
    """Single-process reference for the worker's planted matrix."""
    from galah_tpu.ops.pairwise import threshold_pairs
    from galah_tpu.parallel import make_mesh

    rng = np.random.default_rng(0)
    mat = rng.integers(0, 1 << 63, size=(16, 64), dtype=np.uint64)
    mat.sort(axis=1)
    mat[9] = mat[2]
    mat[13] = mat[5]
    pairs = threshold_pairs(mat, k=21, min_ani=0.99, row_tile=8,
                            col_tile=8, mesh=make_mesh(1))
    assert (2, 9) in pairs and (5, 13) in pairs
    return len(pairs)


@pytest.mark.xfail(
    strict=False,
    reason="jax's CPU backend implements no multiprocess collectives "
           "(XlaRuntimeError: Multiprocess computations aren't "
           "implemented on the CPU backend); passes on real "
           "multi-host TPU")
def test_two_process_distributed_pair_count():
    coord = f"127.0.0.1:{_free_port()}"
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, coord, "2", str(pid)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, env=env, cwd=REPO)
        for pid in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=240)
            assert p.returncode == 0, (
                f"worker failed rc={p.returncode}\nstdout:{out}\n"
                f"stderr:{err[-2000:]}")
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()

    counts = {}
    for out in outs:
        for line in out.splitlines():
            if line.startswith("COUNT"):
                _, pid, count = line.split()
                counts[int(pid)] = int(count)
    assert set(counts) == {0, 1}, f"missing worker output: {outs}"
    expected = _expected_count()
    assert counts[0] == counts[1] == expected


def _write_family_genomes(root):
    """2 families x 2 members of 6 kb genomes -> expected [[0,1],[2,3]]."""
    rng = np.random.default_rng(7)
    bases = np.array(list("ACGT"))
    paths = []
    for fam in range(2):
        base = rng.integers(0, 4, size=6000)
        for member in range(2):
            codes = base.copy()
            if member:
                sites = rng.random(6000) < 0.005
                codes[sites] = (
                    codes[sites]
                    + rng.integers(1, 4, size=int(sites.sum()))) % 4
            p = os.path.join(root, f"fam{fam}_m{member}.fna")
            seq = "".join(bases[codes])
            with open(p, "w") as f:
                if member:  # 2 contigs: the stats-decisive quality tie
                    f.write(">c1\n" + seq[:3000] + "\n"
                            ">c2\n" + seq[3000:] + "\n")
                else:
                    f.write(">c1\n" + seq + "\n")
            paths.append(p)
    return paths


@pytest.mark.slow
def test_two_process_end_to_end_cluster(tmp_path):
    """Full cluster() across 2 real processes with per-host FASTA
    ingestion (the MinHash backend splits reading + sketching by
    host_shard and exchanges sketch rows): both processes must produce
    the identical, correct family composition."""
    import json

    gdir = str(tmp_path / "genomes")
    os.makedirs(gdir)
    paths = _write_family_genomes(gdir)
    # IDENTICAL quality for every genome: the ranking below must be
    # decided by the exchanged assembly stats alone (member-1 genomes
    # are written as two contigs; a broken stats exchange would leave
    # the order at input order and trip the assertion)
    with open(os.path.join(gdir, "info.csv"), "w") as f:
        f.write("genome,completeness,contamination\n")
        for p in paths:
            stem = os.path.splitext(os.path.basename(p))[0]
            f.write(f"{stem},90,1\n")

    coord = f"127.0.0.1:{_free_port()}"
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    # isolate the disk sketch cache per test run
    env["GALAH_TPU_CACHE"] = str(tmp_path / "cache")
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, coord, "2", str(pid), gdir],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, env=env, cwd=REPO)
        for pid in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=420)
            assert p.returncode == 0, (
                f"worker failed rc={p.returncode}\nstdout:{out}\n"
                f"stderr:{err[-2000:]}")
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()

    comps = {}
    comps_hll = {}
    comps_skani = {}
    for out in outs:
        for line in out.splitlines():
            if line.startswith("CLUSTERS_HLL"):
                _, pid, comp = line.split(None, 2)
                comps_hll[int(pid)] = json.loads(comp)
            elif line.startswith("CLUSTERS_SKANI"):
                _, pid, comp = line.split(None, 2)
                comps_skani[int(pid)] = json.loads(comp)
            elif line.startswith("CLUSTERS"):
                _, pid, comp = line.split(None, 2)
                comps[int(pid)] = json.loads(comp)
    assert set(comps) == {0, 1}, f"missing worker output: {outs}"
    assert comps[0] == comps[1] == [[0, 1], [2, 3]], comps
    assert set(comps_hll) == {0, 1}, f"missing HLL output: {outs}"
    assert comps_hll[0] == comps_hll[1] == [[0, 1], [2, 3]], comps_hll
    assert set(comps_skani) == {0, 1}, f"missing skani output: {outs}"
    assert comps_skani[0] == comps_skani[1] == [[0, 1], [2, 3]], \
        comps_skani
    fails = {}
    for out in outs:
        for line in out.splitlines():
            if line.startswith("FAILTEST"):
                _, pid, verdict = line.split()
                fails[int(pid)] = verdict
    assert fails == {0: "RAISED", 1: "RAISED"}, (
        f"failure must propagate to every host: {fails or outs}")
    orders = {}
    for out in outs:
        for line in out.splitlines():
            if line.startswith("ORDER"):
                _, pid, order = line.split(None, 2)
                orders[int(pid)] = json.loads(order)
    assert set(orders) == {0, 1}, f"missing order output: {outs}"
    # identical completeness/contamination: the exchanged contig
    # counts decide (1-contig m0 genomes outrank 2-contig m1; ties
    # keep input order)
    assert orders[0] == orders[1] == [
        "fam0_m0.fna", "fam1_m0.fna", "fam0_m1.fna", "fam1_m1.fna"]
