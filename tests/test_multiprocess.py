"""Two-process jax.distributed smoke test (real multi-process, CPU).

Round-1 review finding: the jax.distributed init path had never executed
with num_processes > 1. Here two actual OS processes rendezvous through
a local coordinator, each owning 4 virtual CPU devices (8 global),
assemble the globally-sharded sketch matrix from per-host strided
shards, and run the sharded pair count — whose result must match the
single-process value. This is the DCN scale-out path of SURVEY.md §5
exercised for real (reference analog: none — the reference is strictly
single-process, SURVEY.md §2.3).
"""

import os
import socket
import subprocess
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "_dist_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _expected_count() -> int:
    """Single-process reference for the worker's planted matrix."""
    from galah_tpu.ops.pairwise import threshold_pairs
    from galah_tpu.parallel import make_mesh

    rng = np.random.default_rng(0)
    mat = rng.integers(0, 1 << 63, size=(16, 64), dtype=np.uint64)
    mat.sort(axis=1)
    mat[9] = mat[2]
    mat[13] = mat[5]
    pairs = threshold_pairs(mat, k=21, min_ani=0.99, row_tile=8,
                            col_tile=8, mesh=make_mesh(1))
    assert (2, 9) in pairs and (5, 13) in pairs
    return len(pairs)


def test_two_process_distributed_pair_count():
    coord = f"127.0.0.1:{_free_port()}"
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, coord, "2", str(pid)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, env=env, cwd=REPO)
        for pid in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=240)
            assert p.returncode == 0, (
                f"worker failed rc={p.returncode}\nstdout:{out}\n"
                f"stderr:{err[-2000:]}")
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()

    counts = {}
    for out in outs:
        for line in out.splitlines():
            if line.startswith("COUNT"):
                _, pid, count = line.split()
                counts[int(pid)] = int(count)
    assert set(counts) == {0, 1}, f"missing worker output: {outs}"
    expected = _expected_count()
    assert counts[0] == counts[1] == expected
