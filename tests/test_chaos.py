"""Bounded kill-anywhere chaos smoke (scripts/chaos_run.py).

Runs the same harness as the full 25-iteration acceptance pass at ~10
kill points: seeded subprocess clustering runs interrupted by SIGTERM,
a GALAH_FI ``kill`` fault (os._exit at a random dispatch or durable-
write site), or a filesystem fault (enospc / eio / torn-write inside
io/atomic.py), then resumed until complete. Every iteration asserts
the resumed cluster definition is byte-identical to the uninterrupted
reference, no corrupt artifact or ``.tmp`` debris remains in the
checkpoint dir, and the run report records the interruption/resume
chain.

Slow tier (each iteration is 2-3 subprocess runs with a fresh
interpreter): select with ``-m chaos`` or ``GALAH_RUN_SLOW=1``.
"""

import importlib.util
import os
import pathlib

import pytest

_SCRIPT = (pathlib.Path(__file__).parent.parent / "scripts"
           / "chaos_run.py")


def _load_chaos_run():
    spec = importlib.util.spec_from_file_location("chaos_run",
                                                  str(_SCRIPT))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.mark.chaos
@pytest.mark.slow
def test_chaos_smoke_ten_kill_points(tmp_path):
    chaos_run = _load_chaos_run()
    failures = chaos_run.run_harness(iterations=10, seed=11,
                                     workdir=str(tmp_path),
                                     verbose=False)
    assert failures == 0


@pytest.mark.chaos
@pytest.mark.slow
def test_chaos_covers_every_interruption_mode():
    """The 10-iteration schedule must include every mode at least
    once — a smoke that only ever drew sigterm proves nothing about
    the fault kinds."""
    chaos_run = _load_chaos_run()
    schedule = [chaos_run.MODES[i % len(chaos_run.MODES)]
                for i in range(10)]
    assert set(schedule) == set(chaos_run.MODES)


@pytest.mark.chaos
@pytest.mark.slow
def test_chaos_fleet_smoke(tmp_path):
    """Bounded fleet chaos: one iteration per FLEET_MODES entry —
    SIGKILL a worker group, SIGKILL the scheduler, SIGTERM the
    scheduler — each resumed and held to byte-identical clusters,
    zero debris, and a coherent reassignment chain (the full
    10-iteration gate runs in scripts/tpu_validation_run.sh)."""
    chaos_run = _load_chaos_run()
    failures = chaos_run.run_fleet_harness(iterations=3, seed=11,
                                           workdir=str(tmp_path),
                                           verbose=False)
    assert failures == 0


def test_fleet_schedule_covers_scheduler_kills():
    """Any 3+ fleet iterations must kill the scheduler itself at
    least once — worker kills alone never exercise event-log replay
    or orphan adoption."""
    chaos_run = _load_chaos_run()
    for n in (3, 10):
        schedule = [chaos_run.FLEET_MODES[i % len(chaos_run.FLEET_MODES)]
                    for i in range(n)]
        assert "sched-kill" in schedule
        assert set(schedule) == set(chaos_run.FLEET_MODES)


def test_scan_artifacts_flags_debris_and_corruption(tmp_path):
    """The artifact audit itself (fast, not marked chaos): .tmp debris
    and unparseable json are findings; checksum-rejected torn jsonl
    lines are recoverable-by-design and are NOT."""
    chaos_run = _load_chaos_run()
    ck = tmp_path / "ck"
    ck.mkdir()
    assert chaos_run.scan_artifacts(str(ck)) == []

    from galah_tpu.io import atomic

    atomic.append_jsonl(str(ck / "clusters.jsonl"), {"i": 0})
    with open(ck / "clusters.jsonl", "ab") as f:
        f.write(b'{"torn')  # torn tail: readable-with-recovery, fine
    atomic.write_json(str(ck / "fingerprint.json"), {"ok": True})
    assert chaos_run.scan_artifacts(str(ck)) == []

    (ck / "fingerprint.json.123.tmp").write_bytes(b"debris")
    (ck / "bad.json").write_bytes(b"{not json")
    problems = chaos_run.scan_artifacts(str(ck))
    assert len(problems) == 2
    assert any(".tmp" in p for p in problems)
    assert any("bad.json" in p for p in problems)


def test_fault_env_specs_parse(monkeypatch):
    """Every GALAH_FI spec the harness generates must parse into an
    injector (a typo here would silently chaos-test nothing)."""
    chaos_run = _load_chaos_run()
    from galah_tpu.resilience import faults

    for mode in chaos_run.MODES:
        env = chaos_run.fault_env(mode, seed=3)
        if mode == "sigterm":
            assert env is None
            continue
        monkeypatch.setenv("GALAH_FI", env["GALAH_FI"])
        faults.reset()
        inj = faults.get_injector()
        assert inj is not None, mode
        kinds = {s.kind for s in inj._specs}
        assert kinds == {"kill" if mode == "kill" else mode}
    monkeypatch.delenv("GALAH_FI")
    faults.reset()
