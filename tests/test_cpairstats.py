"""Native C pair-stats kernel: parity with the numpy Mash reference and
the device extraction (reference analog: the compiled pair loop of
src/finch.rs:53-73)."""

import numpy as np
import pytest

from galah_tpu.ops.constants import SENTINEL

cps = pytest.importorskip("galah_tpu.ops._cpairstats")


def _mat(n, k, seed, ragged=False):
    rng = np.random.default_rng(seed)
    mat = rng.integers(0, 1 << 62, size=(n, k), dtype=np.uint64)
    mat.sort(axis=1)
    # plant near-duplicate rows so some pairs pass the threshold
    mat[3] = mat[0]
    if n > 7:
        mat[7, : k - 5] = mat[2, : k - 5]
        mat[7].sort()
    if ragged:
        mat[1, k // 2:] = np.uint64(SENTINEL)
        mat[5, 10:] = np.uint64(SENTINEL)
    return mat


@pytest.mark.parametrize("ragged", [False, True])
def test_c_matches_numpy_reference(ragged):
    from galah_tpu.ops import minhash_np

    k_sketch, kmer = 64, 21
    mat = _mat(12, k_sketch, seed=4, ragged=ragged)
    got = cps.threshold_pairs_c(mat, k_sketch, kmer, 0.9, threads=2)
    assert got, "planted duplicates must pass"
    for i in range(12):
        for j in range(i + 1, 12):
            ha = mat[i][mat[i] != np.uint64(SENTINEL)]
            hb = mat[j][mat[j] != np.uint64(SENTINEL)]
            a = minhash_np.MinHashSketch(ha, k_sketch, kmer)
            b = minhash_np.MinHashSketch(hb, k_sketch, kmer)
            ani = minhash_np.mash_ani(a, b)
            if ani >= 0.9:
                assert (i, j) in got
                assert got[(i, j)] == pytest.approx(ani, abs=1e-12)
            else:
                assert (i, j) not in got


def test_c_matches_device_extraction():
    from galah_tpu.ops.pairwise import threshold_pairs

    k_sketch, kmer = 128, 21
    mat = _mat(16, k_sketch, seed=9)
    got_c = cps.threshold_pairs_c(mat, k_sketch, kmer, 0.95)
    got_dev = threshold_pairs(mat, k=kmer, min_ani=0.95,
                              sketch_size=k_sketch)
    assert set(got_c) == set(got_dev)
    for key, ani in got_c.items():
        assert ani == pytest.approx(float(got_dev[key]), abs=1e-5)


def test_c_overflow_regrows():
    """A tiny initial capacity forces the overflow-retry path; the
    result must still be complete."""
    k_sketch = 32
    rng = np.random.default_rng(1)
    row = np.sort(rng.integers(0, 1 << 62, size=k_sketch,
                               dtype=np.uint64))
    mat = np.tile(row, (64, 1))  # all 2016 pairs pass
    got = cps.threshold_pairs_c(mat, k_sketch, 21, 0.95, initial_cap=8)
    assert len(got) == 64 * 63 // 2
    assert all(v == pytest.approx(1.0) for v in got.values())
    full = cps.threshold_pairs_c(mat, k_sketch, 21, 0.95)
    assert got == full


def test_c_empty_sketch_rows_never_pair():
    """Two all-SENTINEL rows (empty sketches) are not emitted, matching
    the device extraction's behavior on degenerate genomes."""
    k_sketch = 16
    rng = np.random.default_rng(3)
    mat = rng.integers(0, 1 << 62, size=(4, k_sketch), dtype=np.uint64)
    mat.sort(axis=1)
    mat[1] = np.uint64(SENTINEL)
    mat[2] = np.uint64(SENTINEL)
    got = cps.threshold_pairs_c(mat, k_sketch, 21, 0.0)
    assert (1, 2) not in got


def test_threshold_pairs_c_path_single_device(tmp_path):
    """On a single-device CPU runtime with no knobs pinned,
    threshold_pairs takes the C fast path and agrees with the XLA path.
    Runs in a subprocess because the suite itself uses an 8-device
    virtual mesh."""
    import os
    import subprocess
    import sys

    code = r"""
import os
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from galah_tpu.ops.pairwise import threshold_pairs

assert jax.device_count() == 1
rng = np.random.default_rng(2)
mat = rng.integers(0, 1 << 62, size=(10, 64), dtype=np.uint64)
mat.sort(axis=1)
mat[4] = mat[1]
c_path = threshold_pairs(mat, k=21, min_ani=0.9)
xla = threshold_pairs(mat, k=21, min_ani=0.9, use_pallas=False)
assert set(c_path) == set(xla), (c_path, xla)
for key in c_path:
    assert abs(c_path[key] - xla[key]) < 1e-6
assert (1, 4) in c_path
print("OK")
"""
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run([sys.executable, "-c", code],
                          capture_output=True, text=True, timeout=240,
                          cwd=repo, env=env)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "OK" in proc.stdout


def test_window_match_counts_matches_jax(tmp_path):
    """C membership counts equal the JAX searchsorted implementation on
    real profile windows."""
    import numpy as np

    from galah_tpu.io import read_genome
    from galah_tpu.ops import fragment_ani

    rng = np.random.default_rng(21)
    seq = "".join(rng.choice(list("ACGT"), size=30_000))
    mut = list(seq)
    for i in rng.choice(len(mut), size=600, replace=False):
        mut[i] = "ACGT"[(("ACGT".index(mut[i])) + 1) % 4]
    pa = tmp_path / "a.fna"
    pb = tmp_path / "b.fna"
    pa.write_text(f">c\n{seq}\n")
    pb.write_text(f">c\n{''.join(mut)}\n")
    q = fragment_ani.build_profile(read_genome(str(pa)), k=15,
                                   fraglen=3000)
    r = fragment_ani.build_profile(read_genome(str(pb)), k=15,
                                   fraglen=3000)

    m_c, t_c = cps.window_match_counts(q.windows(), r.ref_set)
    m_j, t_j = fragment_ani._window_match_counts(
        q.device_windows(), r.device_ref_set())
    w = q.windows().shape[0]
    np.testing.assert_array_equal(m_c, np.asarray(m_j)[:w])
    np.testing.assert_array_equal(t_c, np.asarray(t_j)[:w])

    # and the full directed result agrees through the batch entry
    out = fragment_ani.directed_ani_batch([(q, r), (r, q)])
    one = fragment_ani._directed_from_counts(
        m_c, t_c, q, 0.80, 0.5)
    assert out[0].frags_matching == one.frags_matching
    assert out[0].ani == pytest.approx(one.ani)


def test_sparse_screen_matches_dense(monkeypatch):
    """The inverted-index screened path returns exactly the dense
    result on family-structured sketches above the size cutoff."""
    rng = np.random.default_rng(33)
    n, k_sketch, kmer = 1200, 64, 21
    n_fam = 100
    base = rng.integers(0, 1 << 62, size=(n_fam, k_sketch),
                        dtype=np.uint64)
    mat = np.empty((n, k_sketch), dtype=np.uint64)
    for i in range(n):
        fam = i % n_fam
        row = base[fam].copy()
        # perturb a random subset so within-family jaccard varies
        n_mut = rng.integers(0, 20)
        idx = rng.choice(k_sketch, size=n_mut, replace=False)
        row[idx] = rng.integers(0, 1 << 62, size=n_mut, dtype=np.uint64)
        row.sort()
        mat[i] = row
    # a couple of ragged + empty rows
    mat[7, 32:] = np.uint64(SENTINEL)
    mat[11] = np.uint64(SENTINEL)
    mat.sort(axis=1)

    assert n >= cps.SPARSE_SCREEN_MIN_N
    sparse = cps.threshold_pairs_c(mat, k_sketch, kmer, 0.95)
    monkeypatch.setenv("GALAH_TPU_DENSE_PAIRS", "1")
    dense = cps.threshold_pairs_c(mat, k_sketch, kmer, 0.95)
    assert sparse == dense
    assert len(dense) > 100  # the families really do produce pairs


def test_sparse_screen_low_threshold(monkeypatch):
    """Conservativeness at a low threshold (weak screen bound): a small
    hash space forces genuine chance collisions, so partial overlaps
    near the count bound are actually exercised."""
    rng = np.random.default_rng(35)
    n, k_sketch = 1100, 32
    # 2^13 hash space, distinct within each row: cross-row collisions
    # abound, and at this threshold a single shared hash passes
    mat = np.stack([
        np.sort(rng.choice(1 << 13, size=k_sketch,
                           replace=False)).astype(np.uint64)
        for _ in range(n)
    ])
    sparse = cps.threshold_pairs_c(mat, k_sketch, 21, 0.7)
    monkeypatch.setenv("GALAH_TPU_DENSE_PAIRS", "1")
    dense = cps.threshold_pairs_c(mat, k_sketch, 21, 0.7)
    assert sparse == dense
    assert dense, "collision-rich matrix must produce passing pairs"


def test_sparse_screen_big_runs(monkeypatch):
    """Near-duplicate clusters (collision runs > _BIG_RUN genomes) take
    the dedup-group path: identical results, no O(K*m^2) blowup."""
    rng = np.random.default_rng(37)
    n, k_sketch = 1300, 48
    base = np.sort(rng.integers(0, 1 << 62, size=k_sketch,
                                dtype=np.uint64))
    mat = np.tile(base, (n, 1))
    # 200 rows perturbed lightly; the other 1100 are identical
    for i in range(200):
        row = base.copy()
        idx = rng.choice(k_sketch, size=3, replace=False)
        row[idx] = rng.integers(0, 1 << 62, size=3, dtype=np.uint64)
        row.sort()
        mat[i] = row
    sparse = cps.threshold_pairs_c(mat, k_sketch, 21, 0.9)
    monkeypatch.setenv("GALAH_TPU_DENSE_PAIRS", "1")
    dense = cps.threshold_pairs_c(mat, k_sketch, 21, 0.9)
    assert sparse == dense
    assert len(dense) >= 1100 * 1099 // 2


def test_e2e_clusters_sparse_equals_dense(tmp_path):
    """Above the screen cutoff, full cluster() compositions are
    identical with and without the sparse screen (single-device CPU
    subprocess; N > SPARSE_SCREEN_MIN_N)."""
    import os
    import subprocess
    import sys

    code = r"""
import os
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
import sys
sys.path.insert(0, os.getcwd())
import bench
from galah_tpu.api import generate_galah_clusterer

paths = bench._synth_families(n_genomes=1100, genome_len=20_000,
                              n_families=275, mut=0.03, seed=43)
values = {"ani": 95.0, "precluster_ani": 90.0,
          "min_aligned_fraction": 15.0, "fragment_length": 3000,
          "precluster_method": "finch", "cluster_method": "skani",
          "threads": 1, "hash_algorithm": "tpufast",
          "ani_subsample": 16}
a = generate_galah_clusterer(paths, values).cluster()
os.environ["GALAH_TPU_DENSE_PAIRS"] = "1"
b = generate_galah_clusterer(paths, values).cluster()
assert sorted(map(sorted, a)) == sorted(map(sorted, b))
assert len(a) == 275, len(a)
print("OK", len(a))
"""
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS",
                        "GALAH_TPU_DENSE_PAIRS")}
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run([sys.executable, "-c", code],
                          capture_output=True, text=True, timeout=600,
                          cwd=repo, env=env)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "OK" in proc.stdout


def test_compact_windows_matches_numpy_layout():
    """The two-pass C window compaction must reproduce the numpy
    stable-argsort layout bit-for-bit (same slots, same order, same
    boundary-k-mer drops) across ragged tails and densities."""
    import numpy as np

    from galah_tpu.ops import _cpairstats
    from galah_tpu.ops.constants import SENTINEL

    rng = np.random.default_rng(51)
    for trial in range(10):
        L = int(rng.integers(8, 200))
        k = int(rng.integers(2, min(L, 32)))
        n_flat = int(rng.integers(1, 6 * L))
        w = -(-n_flat // L)
        flat = rng.integers(0, 1 << 62, size=n_flat, dtype=np.uint64)
        # subsample-style masking at random density
        keep = rng.random(n_flat) < rng.uniform(0.02, 0.4)
        flat = np.where(keep, flat, np.uint64(SENTINEL))

        # numpy reference: the subsample_c > 1 branch of windows()
        pad = np.full(w * L, np.uint64(SENTINEL), dtype=np.uint64)
        pad[:n_flat] = flat
        wins = pad.reshape(w, L).copy()
        wins[:, L - (k - 1):] = np.uint64(SENTINEL)
        order = np.argsort(wins == np.uint64(SENTINEL), axis=1,
                           kind="stable")
        wins = np.take_along_axis(wins, order, axis=1)
        counts = (wins != np.uint64(SENTINEL)).sum(axis=1)
        slots = max(int(counts.max()) if counts.size else 1, 1)
        slots = -(-slots // 64) * 64
        want = wins[:, :slots].copy()

        got = _cpairstats.compact_windows(flat, w, L, k)
        np.testing.assert_array_equal(got, want)


def test_window_match_counts_merge_parity():
    """The sorted-merge membership counter must reproduce the matrix
    walker's matched counts exactly (and the profile totals must match
    its total output) across densities, duplicates, and empty edges."""
    import numpy as np

    from galah_tpu.ops import _cpairstats
    from galah_tpu.ops.constants import SENTINEL

    rng = np.random.default_rng(52)
    for trial in range(10):
        W = int(rng.integers(1, 40))
        slots = int(rng.integers(1, 80))
        wins = rng.integers(0, 200, size=(W, slots)).astype(np.uint64)
        kill = rng.random((W, slots)) < rng.uniform(0.1, 0.9)
        wins[kill] = np.uint64(SENTINEL)
        ref = np.unique(
            rng.integers(0, 200, size=int(rng.integers(1, 150)))
        ).astype(np.uint64)

        want_m, want_t = _cpairstats.window_match_counts(wins, ref)

        mask = wins != np.uint64(SENTINEL)
        totals = mask.sum(axis=1, dtype=np.int32)
        rows, _ = np.nonzero(mask)
        qh = wins[mask]
        order = np.argsort(qh)
        got_m = _cpairstats.window_match_counts_merge(
            qh[order], rows[order].astype(np.int32), W, ref)
        np.testing.assert_array_equal(got_m, want_m)
        np.testing.assert_array_equal(totals, want_t)


def test_windows_from_pairs_matches_compact_windows():
    """The O(n_valid) pair-based window assembly (profile-walk pos
    output) is bit-identical to compact_windows on the same flat
    array — incl. boundary-crossing drops, ragged last window, and
    the slots rounding."""
    import numpy as np

    from galah_tpu.ops import _cpairstats
    from galah_tpu.ops.constants import SENTINEL

    rng = np.random.default_rng(7)
    L, k = 300, 21
    flat = rng.integers(0, 1 << 64, size=2 * L + 57, dtype=np.uint64)
    keep = rng.random(flat.shape[0]) < 0.2
    flat[~keep] = np.uint64(SENTINEL)
    w = -(-flat.shape[0] // L)

    want = _cpairstats.compact_windows(flat, w, L, k)
    pos = np.nonzero(flat != np.uint64(SENTINEL))[0].astype(np.int64)
    got = _cpairstats.windows_from_pairs(
        pos, flat[pos], w, L, k)
    np.testing.assert_array_equal(got, want)


def test_profile_via_c_pairs_path_windows_parity(tmp_path):
    """A profile built by the new positional_hashes_profile walk
    (kept pairs stored) produces the same windows()/sorted_query()
    as one forced through the compact_windows fallback."""
    import numpy as np

    from galah_tpu.io.fasta import read_genome
    from galah_tpu.ops import fragment_ani

    rng = np.random.default_rng(3)
    seq = rng.choice(list(b"ACGT"), size=50_000).astype(np.uint8)
    p = tmp_path / "g.fna"
    p.write_bytes(b">c1\n" + seq.tobytes() + b"\n")
    g = read_genome(str(p))
    prof = fragment_ani.build_profile(g, k=21, fraglen=3000,
                                      subsample_c=16)
    if prof._kept_pos is None:
        pytest.skip("C profile walk unavailable on this backend")
    wins_pairs = prof.windows()
    sq_pairs = prof.sorted_query()

    prof2 = fragment_ani.build_profile(g, k=21, fraglen=3000,
                                       subsample_c=16)
    prof2._kept_pos = None
    prof2._kept_hashes = None
    wins_flat = prof2.windows()
    np.testing.assert_array_equal(wins_pairs, wins_flat)
    for a, b in zip(sq_pairs, prof2.sorted_query()):
        np.testing.assert_array_equal(a, b)


def test_merge_counter_avx512_scalar_identity(monkeypatch):
    """The AVX-512 block merge (csrc/pairstats.c merge_count_avx512)
    must be bit-identical to the scalar walk on BOTH entry points
    (single-pair and batch) across overlap regimes, duplicate-heavy
    queries, and sub-block / odd sizes. On a CPU without AVX-512 both
    runs would take the scalar path and the A/B below would silently
    compare scalar against scalar — so probe the dispatch first and
    SKIP with the reason on hosts where the SIMD path can't run."""
    import numpy as np

    from galah_tpu.ops import _cpairstats

    monkeypatch.delenv("GALAH_TPU_NO_AVX512", raising=False)
    if not _cpairstats.merge_uses_avx512():
        pytest.skip(
            "merge counter dispatches to the scalar kernel here "
            "(no avx512f CPU support or non-AVX-512 build); the "
            "A/B identity would compare scalar against itself")

    rng = np.random.default_rng(99)
    for trial, (nq, H, overlap) in enumerate(
            [(0, 0, 0.0), (3, 5, 1.0), (7, 8, 0.5), (8, 7, 0.5),
             (64, 64, 1.0), (1000, 1000, 0.65), (2000, 16, 0.9),
             (16, 2000, 0.9), (333, 777, 0.3)]):
        nw = max(1, nq // 4)
        ref = np.unique(rng.integers(
            0, 1 << 50, size=max(2 * H, 1), dtype=np.uint64))[:H]
        n_sh = int(nq * overlap) if H else 0
        qh = np.sort(np.concatenate([
            rng.choice(ref, size=n_sh, replace=True)
            if n_sh else np.empty(0, np.uint64),
            rng.integers(0, 1 << 50, size=nq - n_sh,
                         dtype=np.uint64)]).astype(np.uint64))
        qw = rng.integers(0, nw, size=nq, dtype=np.int32)

        monkeypatch.setenv("GALAH_TPU_NO_AVX512", "1")
        want = _cpairstats.window_match_counts_merge(qh, qw, nw, ref)
        monkeypatch.delenv("GALAH_TPU_NO_AVX512")
        got = _cpairstats.window_match_counts_merge(qh, qw, nw, ref)
        np.testing.assert_array_equal(got, want, err_msg=f"trial {trial}")

    # batch entry point: 200 random pairs over 8 genomes
    ng, nq, nw = 8, 500, 25
    pool = np.unique(rng.integers(0, 1 << 50, size=2000,
                                  dtype=np.uint64))
    qhs, qws, refs = [], [], []
    for _ in range(ng):
        qh = np.sort(np.concatenate([
            rng.choice(pool, size=nq // 2, replace=True),
            rng.integers(0, 1 << 50, size=nq - nq // 2,
                         dtype=np.uint64)]).astype(np.uint64))
        qhs.append(qh)
        qws.append(rng.integers(0, nw, size=nq, dtype=np.int32))
        refs.append(np.unique(np.concatenate([
            rng.choice(pool, size=300, replace=False),
            rng.integers(0, 1 << 50, size=100, dtype=np.uint64)])
            .astype(np.uint64)))
    qh_cat, qw_cat = np.concatenate(qhs), np.concatenate(qws)
    q_off = np.arange(ng + 1, dtype=np.int64) * nq
    ref_cat = np.concatenate(refs)
    r_off = np.zeros(ng + 1, dtype=np.int64)
    np.cumsum([len(r) for r in refs], out=r_off[1:])
    n_pairs = 200
    pair_q = rng.integers(0, ng, size=n_pairs, dtype=np.int32)
    pair_r = rng.integers(0, ng, size=n_pairs, dtype=np.int32)
    m_off = np.arange(n_pairs, dtype=np.int64) * nw

    monkeypatch.setenv("GALAH_TPU_NO_AVX512", "1")
    want = _cpairstats.window_match_counts_merge_batch(
        qh_cat, qw_cat, q_off, ref_cat, r_off, pair_q, pair_r,
        m_off, n_pairs * nw, threads=2)
    monkeypatch.delenv("GALAH_TPU_NO_AVX512")
    got = _cpairstats.window_match_counts_merge_batch(
        qh_cat, qw_cat, q_off, ref_cat, r_off, pair_q, pair_r,
        m_off, n_pairs * nw, threads=2)
    np.testing.assert_array_equal(got, want)
