"""Flow tracing + heartbeat telemetry tests (obs/flow, obs/heartbeat).

Covers flow-id minting and propagation across adopted worker threads,
bounded memory under a 10k-item stream, the critical-path verdict on a
deliberately starved synthetic pipeline, Chrome-trace s/f flow events,
heartbeat beats with a torn tail line, the occupancy time-series in
the run report, and the `galah-tpu flow analyze` / `galah-tpu top`
subcommands. The whole file runs under GALAH_SAN=1 (conftest arms the
concurrency sanitizer), so every lock discipline here is
runtime-checked too.
"""

import json
import threading
import time
from concurrent.futures import ThreadPoolExecutor

import pytest

from galah_tpu import obs
from galah_tpu.obs import flow as obs_flow
from galah_tpu.obs import heartbeat as obs_heartbeat
from galah_tpu.obs import metrics as obs_metrics
from galah_tpu.obs import report as report_mod
from galah_tpu.obs import trace as obs_trace
from galah_tpu.utils import timing


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    timing.reset()
    obs.reset_run()
    yield
    obs_trace.stop()
    timing.reset()
    obs.reset_run()


# -- flow ids and the boundary graph --------------------------------


def test_flow_ids_monotonic_and_kind_counted():
    a = obs_flow.begin("genome_batch")
    b = obs_flow.begin("sketch_block")
    c = obs_flow.begin("sketch_block")
    assert 0 < a < b < c
    snap = obs_flow.snapshot()
    assert snap["enabled"] is True
    assert snap["flows"]["created"] == 3
    assert snap["flows"]["kinds"] == {"genome_batch": 1,
                                      "sketch_block": 2}


def test_disabled_recorder_is_a_noop_but_blocked_still_measures():
    rec = obs_flow.FlowRecorder(enabled=False)
    assert rec.begin("sketch_block") == 0
    rec.emit("sketch", 1)
    assert rec.absorb("sketch", "pairs") is None
    with rec.blocked("pairs", "upstream-empty") as b:
        time.sleep(0.01)
    assert b.seconds >= 0.005  # occupancy math works with flow off
    snap = rec.snapshot()
    assert snap["enabled"] is False and snap["stages"] == {}


def test_emit_absorb_records_edge_and_consumer_items():
    for _ in range(3):
        fid = obs_flow.begin("sketch_block")
        obs_flow.emit("sketch", fid)
    got = [obs_flow.absorb("sketch", "pairs") for _ in range(3)]
    assert got == [1, 2, 3]  # FIFO order
    assert obs_flow.absorb("sketch", "pairs") is None  # drained
    obs_flow.record_service("pairs", 0.5)
    snap = obs_flow.snapshot()
    assert snap["edges"] == [{"from": "sketch", "to": "pairs",
                              "items": 3,
                              "queue": snap["edges"][0]["queue"]}]
    assert snap["edges"][0]["queue"]["count"] == 3
    assert snap["stages"]["pairs"]["items"] == 3
    assert snap["stages"]["pairs"]["service_s"] == 0.5
    assert snap["flows"]["completed"] == 3


def test_flow_context_propagates_to_adopted_worker_threads():
    seen = {}

    def worker(tok):
        with obs_flow.adopt(tok):
            seen["ctx"] = obs_flow.current()
            # stage=None resolves via the adopted context
            obs_flow.record_service(None, 0.25)

    fid = obs_flow.begin("edge_stripe")
    with obs_flow.span("pairs", fid):
        tok = obs_flow.token()
        with ThreadPoolExecutor(max_workers=1) as pool:
            pool.submit(worker, tok).result()
    assert seen["ctx"] == ("pairs", fid)
    snap = obs_flow.snapshot()
    # the worker's 0.25 s plus the span's own service observation
    assert snap["stages"]["pairs"]["service"]["count"] == 2
    assert snap["stages"]["pairs"]["service_s"] >= 0.25
    # outside every span the context is empty again
    assert obs_flow.current() == (None, None)


def test_bounded_memory_under_10k_item_stream():
    n = 10_000
    for _ in range(n):
        obs_flow.emit("sketch", obs_flow.begin("sketch_block"))
        obs_flow.record_service("sketch", 0.001)
    snap = obs_flow.snapshot()
    assert snap["flows"]["created"] == n
    assert snap["flows"]["dropped"] == n - obs_flow.BOUNDARY_CAP
    assert obs_flow.queue_depths() == {"sketch": obs_flow.BOUNDARY_CAP}
    # aggregates stay fixed-size: one histogram, sparse buckets
    hist = snap["stages"]["sketch"]["service"]
    assert hist["count"] == n
    assert len(hist["le_s"]) <= len(obs_flow._BUCKET_EDGES) + 1
    assert len(json.dumps(snap)) < 20_000  # report-safe payload


def test_unknown_blocked_reason_folds_into_host():
    obs_flow.record_wait("greedy", "cosmic-rays", 1.0)
    snap = obs_flow.snapshot()
    assert snap["stages"]["greedy"]["wait_s"] == {"host": 1.0}


def test_concurrent_emitters_race_free_under_sanitizer():
    def hammer(i):
        for _ in range(200):
            fid = obs_flow.begin("sketch_block")
            obs_flow.emit("sketch", fid)
            obs_flow.absorb("sketch", "pairs")
            obs_flow.record_service("pairs", 1e-6)

    threads = [threading.Thread(target=hammer, args=(i,))
               for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    snap = obs_flow.snapshot()
    assert snap["flows"]["created"] == 800
    from galah_tpu.analysis import sanitizer
    if sanitizer.GLOBAL.installed:
        s = sanitizer.GLOBAL.summary()
        assert s["races"] == 0 and s["inversions"] == 0


# -- chrome-trace flow events ---------------------------------------


def test_trace_carries_s_t_f_flow_events(tmp_path):
    path = tmp_path / "trace.json"
    obs_trace.start(str(path))
    fid = obs_flow.begin("sketch_block")
    obs_flow.emit("sketch", fid)
    with obs_flow.span("pairs", fid):
        pass
    obs_flow.absorb("sketch", "pairs")
    obs_trace.stop()
    events = json.loads(path.read_text())
    flows = [e for e in events if e.get("cat") == "flow"
             and e.get("ph") in ("s", "t", "f")]
    assert [e["ph"] for e in flows] == ["s", "t", "f"]
    assert all(e["id"] == fid for e in flows)
    assert flows[-1]["bp"] == "e"  # bind to enclosing slice


# -- critical path ---------------------------------------------------


def _starved_pipeline_snapshot():
    """Synthetic starved pipeline: sketch is slow (8 s service), pairs
    and greedy mostly sit in upstream-empty waits."""
    rec = obs_flow.FlowRecorder(enabled=True)
    rec.record_service("ingest", 0.5, items=10)
    rec.record_service("sketch", 8.0, items=10)
    rec.record_wait("sketch", "upstream-empty", 0.5)
    rec.record_service("pairs", 0.6, items=10)
    rec.record_wait("pairs", "upstream-empty", 8.0)
    rec.record_wait("pairs", "device-dispatch", 0.4)
    rec.record_service("greedy", 0.5)
    rec.record_wait("greedy", "upstream-empty", 9.0)
    for _ in range(10):
        rec.emit("ingest", rec.begin("genome_batch"))
        rec.absorb("ingest", "sketch")
        rec.emit("sketch", rec.begin("sketch_block"))
        rec.absorb("sketch", "pairs")
        rec.emit("pairs", rec.begin("edge_stripe"))
        rec.absorb("pairs", "greedy")
    return rec.snapshot()


def test_critical_path_blames_the_starving_producer():
    snap = _starved_pipeline_snapshot()
    cp = obs_flow.critical_path(snap, 10.0)
    assert cp["bottleneck"] == "sketch"
    shares = {s: e["share"] for s, e in cp["stages"].items()}
    assert shares["sketch"] == max(shares.values())
    assert shares["sketch"] > 0.5
    # conservation: blame shares sum to the e2e wall (>= 95% is the
    # acceptance bar; the pure decomposition is exact)
    total = sum(e["blame_s"] for e in cp["stages"].values())
    assert total == pytest.approx(10.0, rel=1e-6)
    assert sum(shares.values()) == pytest.approx(1.0, rel=1e-6)


def test_critical_path_renders_with_coverage_line():
    cp = obs_flow.critical_path(_starved_pipeline_snapshot(), 10.0)
    lines = obs_flow.render_critical_path(cp)
    assert lines[0].startswith("flow critical path")
    assert "bottleneck: sketch" in lines[1]
    assert any("blame shares cover 100% of the e2e wall" in ln
               for ln in lines)


def test_critical_path_empty_and_zero_wall_are_safe():
    assert obs_flow.critical_path({}, 10.0)["stages"] == {}
    snap = _starved_pipeline_snapshot()
    assert obs_flow.critical_path(snap, 0.0)["stages"] == {}
    lines = obs_flow.render_critical_path(
        obs_flow.critical_path({}, 0.0))
    assert any("no flow data" in ln for ln in lines)


# -- heartbeat -------------------------------------------------------


def test_heartbeat_beats_and_survives_a_torn_tail(tmp_path):
    obs_metrics.pipeline_occupancy(0.8, stage="sketch")
    hb = obs_heartbeat.start(str(tmp_path), 0.05)
    deadline = time.monotonic() + 5.0
    while hb.snapshot()["beats"] < 3:
        assert time.monotonic() < deadline, "heartbeat never beat"
        time.sleep(0.01)
    obs_heartbeat.stop()
    records, torn = obs_heartbeat.load(str(tmp_path))
    assert torn == 0 and len(records) >= 3
    assert records[-1]["beat"] == len(records)
    assert records[-1]["occupancy"]["sketch"] == 0.8
    # a run SIGKILLed mid-append leaves a torn tail: must read as one
    # record short, never an error
    with open(hb.path, "a") as fh:
        fh.write('{"beat": 99, "truncat')
    records2, torn2 = obs_heartbeat.load(str(tmp_path))
    assert len(records2) == len(records) and torn2 == 1
    page = obs_heartbeat.render_latest(str(tmp_path))
    assert "occupancy:" in page and "sketch" in page
    assert "1 torn" in page


def test_heartbeat_final_beat_is_written_once(tmp_path):
    hb = obs_heartbeat.start(str(tmp_path), 30.0)  # never fires alone
    obs_heartbeat.stop()
    obs_heartbeat.stop()  # idempotent: atexit + finalize both call it
    obs.flush_artifacts()
    records, _ = obs_heartbeat.load(str(tmp_path))
    assert len(records) == 1  # exactly one final flush beat


def test_heartbeat_occupancy_time_series_min_mean_last(tmp_path):
    hb = obs_heartbeat.Heartbeat(str(tmp_path), 60.0)
    for v in (0.2, 0.6, 1.0):
        obs_metrics.pipeline_occupancy(v, stage="pairs")
        hb.beat()
    series = hb.snapshot()["occupancy_series"]["pairs"]
    assert series == {"min": 0.2, "mean": 0.6, "last": 1.0,
                      "samples": 3}


def test_maybe_start_honors_env(tmp_path, monkeypatch):
    monkeypatch.delenv("GALAH_OBS_HEARTBEAT_S", raising=False)
    assert obs_heartbeat.maybe_start(str(tmp_path / "r.json")) is None
    monkeypatch.setenv("GALAH_OBS_HEARTBEAT_S", "0")
    assert obs_heartbeat.maybe_start(str(tmp_path / "r.json")) is None
    monkeypatch.setenv("GALAH_OBS_HEARTBEAT_S", "30")
    hb = obs_heartbeat.maybe_start(str(tmp_path / "r.json"))
    assert hb is not None
    assert hb.path == str(tmp_path / "heartbeat.jsonl")
    obs_heartbeat.stop(flush=False)


def test_top_subcommand_renders_and_signals_missing(tmp_path):
    from galah_tpu.cli import main

    assert main(["top", str(tmp_path)]) == 1  # no heartbeat yet
    hb = obs_heartbeat.Heartbeat(str(tmp_path), 60.0)
    obs_metrics.pipeline_occupancy(0.4, stage="greedy")
    hb.beat()
    assert main(["top", str(tmp_path)]) == 0
    assert main(["top", hb.path]) == 0  # direct file path works too


# -- run report v6 + flow analyze ------------------------------------


def _report_with_flow(tmp_path, name="run_report.json"):
    fid = obs_flow.begin("sketch_block")
    obs_flow.emit("sketch", fid)
    obs_flow.absorb("sketch", "pairs")
    obs_flow.record_service("sketch", 2.0, items=1)
    obs_flow.record_wait("pairs", "upstream-empty", 1.5)
    obs_flow.record_service("pairs", 0.5)
    rep = report_mod.assemble("cluster", started_at=0.0)
    path = tmp_path / name
    report_mod.write(str(path), rep)
    return rep, str(path)


def test_report_v6_carries_flow_section_and_validates(tmp_path):
    rep, _ = _report_with_flow(tmp_path)
    assert rep["version"] == report_mod.REPORT_VERSION
    flow = rep["flow"]
    assert flow["stages"]["pairs"]["items"] == 1
    cp = flow["critical_path"]
    assert cp["e2e_wall_s"] == pytest.approx(
        rep["run"]["duration_s"], rel=1e-6)
    assert set(cp["stages"]) == {"sketch", "pairs"}
    assert report_mod.validate(rep) == []
    jsonschema = pytest.importorskip("jsonschema")
    with open(report_mod.SCHEMA_PATH) as fh:
        jsonschema.Draft7Validator(json.load(fh)).validate(rep)
    page = report_mod.render(rep)
    assert "flow critical path" in page


def test_report_includes_heartbeat_series(tmp_path):
    hb = obs_heartbeat.start(str(tmp_path), 60.0)
    obs_metrics.pipeline_occupancy(0.3, stage="sketch")
    hb.beat()
    obs_flow.record_service("sketch", 1.0)
    rep = report_mod.assemble("cluster", started_at=0.0)
    series = rep["flow"]["heartbeat"]["occupancy_series"]
    assert series["sketch"]["last"] == 0.3
    page = report_mod.render(rep)
    assert "occupancy time-series" in page


def test_report_diff_shows_flow_drift(tmp_path):
    rep, _ = _report_with_flow(tmp_path)
    rep2 = json.loads(json.dumps(rep))
    cp2 = rep2["flow"]["critical_path"]
    cp2["bottleneck"] = "greedy"
    cp2["stages"]["pairs"]["share"] = 0.9
    out = report_mod.diff(rep, rep2)
    assert "flow drift:" in out
    assert "MIGRATED" in out


def test_flow_analyze_subcommand(tmp_path, capsys):
    from galah_tpu.cli import main

    _, path = _report_with_flow(tmp_path)
    assert main(["flow", "analyze", path]) == 0
    out = capsys.readouterr().out
    assert "flow critical path" in out and "bottleneck:" in out
    assert main(["flow", "analyze", path, "--json"]) == 0
    parsed = json.loads(capsys.readouterr().out)
    assert "bottleneck" in parsed and "stages" in parsed
    assert main(["flow", "analyze", str(tmp_path / "nope.json")]) == 1


def test_flow_analyze_rejects_flowless_report(tmp_path):
    from galah_tpu.cli import main

    obs.reset_run()  # no flow activity at all
    rep = report_mod.assemble("cluster", started_at=0.0)
    assert "flow" not in rep or not rep["flow"].get("stages")
    path = tmp_path / "bare.json"
    report_mod.write(str(path), rep)
    assert main(["flow", "analyze", str(path)]) == 1


def test_flow_metrics_feed_the_perf_ledger(tmp_path):
    from galah_tpu.obs import ledger as ledger_mod

    rep, _ = _report_with_flow(tmp_path)
    metrics = ledger_mod.metrics_of_report(rep)
    assert "flow.sketch.blame_s" in metrics
    assert "flow.pairs.share" in metrics
    # Per-stage blame partitions the wall clock exactly. flow.host.* is
    # a cross-cutting decomposition of the same blame (host vs device),
    # not an extra stage, so it stays out of the partition sum.
    total = sum(v for k, v in metrics.items()
                if k.startswith("flow.") and k.endswith(".blame_s")
                and not k.startswith("flow.host."))
    assert total == pytest.approx(rep["run"]["duration_s"], rel=1e-6)
    assert 0.0 <= metrics["flow.host.share"] <= 1.0
    assert ledger_mod.metric_direction("flow.host.share") == "lower"
