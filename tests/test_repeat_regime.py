"""Adversarial repeat-regime behavior: shared mobile-element content
across UNRELATED genomes (the collision screen's and the fragment-ANI
gate's worst case — the uniform-random scale rungs are their best case).

Generator: bench._synth_repeat_genomes — independent random backbones
with repeat_frac of their length replaced by elements from ONE shared
pool, so genomes share k-mers without sharing ancestry.

What these tests pin (all values MEASURED on this fixture, seed 23):

  * screen precision — at 10% repeats the conservative MinHash
    collision screen emits 3 candidate pairs of 120 (pairs that share
    BOTH their inserted elements); at 30% essentially everything
    collides (119/120). Sparse and dense extraction stay bit-identical
    on both.
  * _BIG_RUN dedup exactness on repeat-shaped hash runs (every pool
    hash spanning all n > 64 genomes): counts equal brute-force
    set intersections.
  * end-to-end: the repeat regime CAN merge unrelated genomes under
    the DEFAULT thresholds, and that is reference-parity semantics,
    not a screen bug — the bidirectional gate passes when EITHER
    direction's matched-fragment fraction >= min_aligned_fraction
    while the reported ANI is the MAX of the two directions
    (reference: src/fastani.rs:56-65, the issue-#7 semantics). With
    identical repeats, matched windows sit near 100% identity, so a
    repeat-share above the aligned-fraction threshold reports high
    ANI over low-but-passing aligned fraction. Raising
    --min-aligned-fraction is the documented defense (the flag exists
    for exactly this; reference README discusses AF semantics).

Reference analog: the dereplication-correctness claim on "many closely
related genomes" (reference: README.md:18-26), stressed with genomes
that are NOT related but share sequence.
"""

import os

import numpy as np
import pytest

import bench
from galah_tpu.ops.constants import SENTINEL

pytestmark = []


def _sketch_matrix_np(paths):
    from galah_tpu.io.fasta import read_genome
    from galah_tpu.ops import minhash_np

    sks = [minhash_np.sketch_genome(read_genome(p)) for p in paths]
    width = max(s.size for s in sks)
    mat = np.full((len(sks), width), np.uint64(SENTINEL), np.uint64)
    for i, s in enumerate(sks):
        mat[i, :s.size] = s.hashes
    lens = np.array([s.size for s in sks], np.int64)
    return mat, lens


def test_screen_precision_repeat_regimes():
    """Candidate volume and sparse/dense identity at 10% and 30%."""
    from galah_tpu.ops.collision import candidate_pairs_minhash
    from galah_tpu.ops.pairwise import ani_to_jaccard

    j_thr = ani_to_jaccard(0.90, 21)
    paths10 = bench._synth_repeat_genomes(
        n_genomes=16, genome_len=50_000, repeat_frac=0.1, seed=23)
    mat10, lens10 = _sketch_matrix_np(paths10)
    pi, pj = candidate_pairs_minhash(mat10, lens10, j_thr, 1000)
    # 3 of 120 possible: only pairs sharing BOTH their two inserted
    # elements clear the conservative bound — high screen precision
    assert len(pi) == 3, f"10%-repeat candidates changed: {len(pi)}"

    paths30 = bench._synth_repeat_genomes(
        n_genomes=16, genome_len=50_000, repeat_frac=0.3, seed=23)
    mat30, lens30 = _sketch_matrix_np(paths30)
    pi30, _pj30 = candidate_pairs_minhash(mat30, lens30, j_thr, 1000)
    # nothing screens out when ~every pair shares most of the pool
    assert len(pi30) >= 100, f"30%-repeat candidates: {len(pi30)}"


def test_sparse_equals_dense_on_repeat_input(monkeypatch):
    """The screened sparse path and the dense walk agree pair-for-pair
    (and ANI-for-ANI) on repeat-heavy input — the screen may only
    over-emit candidates, never change results."""
    from galah_tpu.ops._cpairstats import threshold_pairs_c

    paths = bench._synth_repeat_genomes(
        n_genomes=16, genome_len=50_000, repeat_frac=0.3, seed=23)
    mat, _lens = _sketch_matrix_np(paths)

    monkeypatch.setenv("GALAH_TPU_DENSE_PAIRS", "1")
    dense = threshold_pairs_c(mat, 1000, 21, 0.90)
    monkeypatch.delenv("GALAH_TPU_DENSE_PAIRS")
    monkeypatch.setenv("GALAH_TPU_SPARSE_MIN_N", "2")
    sparse = threshold_pairs_c(mat, 1000, 21, 0.90)
    assert dense == sparse
    assert len(dense) > 0  # the 30% regime genuinely passes precluster


def test_big_run_dedup_repeat_shaped():
    """Repeat-shaped runs (every pool hash spans ALL n > _BIG_RUN
    genomes) drive the group-signature dedup; counts must equal
    brute-force intersections. Checked against both the C and numpy
    counters."""
    from galah_tpu.ops import collision

    rng = np.random.default_rng(5)
    n, n_pool, n_uniq = 80, 200, 40
    assert n > collision._BIG_RUN
    pool = np.unique(rng.integers(1, 1 << 60, size=n_pool * 2,
                                  dtype=np.uint64))[:n_pool]
    rows = []
    for g in range(n):
        uniq = rng.integers(1 << 60, 1 << 62, size=n_uniq,
                            dtype=np.uint64)
        rows.append(np.unique(np.concatenate([pool, uniq])))
    width = max(r.shape[0] for r in rows)
    mat = np.full((n, width), np.uint64(SENTINEL), np.uint64)
    lens = np.zeros(n, np.int64)
    for i, r in enumerate(rows):
        mat[i, :r.shape[0]] = r
        lens[i] = r.shape[0]

    sets = [set(map(int, r)) for r in rows]
    for fn in (collision.collision_pair_counts,
               collision._collision_pair_counts_np):
        pi, pj, counts = fn(mat, lens)
        got = {(int(a), int(b)): int(c)
               for a, b, c in zip(pi, pj, counts)}
        for i in range(n):
            for j in range(i + 1, n):
                want = len(sets[i] & sets[j])
                assert got.get((i, j), 0) == want, (fn, i, j)


def _cluster(paths, **overrides):
    import jax

    jax.config.update("jax_platforms", "cpu")
    from galah_tpu.api import generate_galah_clusterer

    values = {"ani": 95.0, "precluster_ani": 90.0,
              "min_aligned_fraction": 15.0, "fragment_length": 3000,
              "precluster_method": "finch", "cluster_method": "skani",
              "threads": 1}
    values.update(overrides)
    return generate_galah_clusterer(paths, values).cluster()


def test_e2e_10pct_repeats_finch_default_no_merges():
    """10% shared repeats, default finch+skani at 95/90: every genome
    stays its own cluster — the aligned-fraction gate (both directions
    < 15%) holds the line."""
    paths = bench._synth_repeat_genomes(
        n_genomes=16, genome_len=50_000, repeat_frac=0.1, seed=23)
    assert len(_cluster(paths)) == 16


def test_repeat_merge_hazard_warning():
    """A marginal, direction-asymmetric gate pass (the pair-(9,14)
    signature above: AF 4/17 = 0.235 passing a 0.15 threshold while
    the other direction sits at 1/17) raises the repeat-merge hazard
    RuntimeWarning on the scalar combine path; symmetric passes and
    comfortable margins stay silent."""
    import warnings

    from galah_tpu.ops.fragment_ani import (
        DirectedANI,
        _combine_bidirectional,
    )

    hazard_ab = DirectedANI(0.973, 4 / 17, 4, 17)
    hazard_ba = DirectedANI(0.970, 1 / 17, 1, 17)
    with pytest.warns(RuntimeWarning, match="min-aligned-fraction"):
        got = _combine_bidirectional(hazard_ab, hazard_ba, 0.15)
    assert got == 0.973

    sym_ab = DirectedANI(0.99, 0.20, 4, 20)
    sym_ba = DirectedANI(0.99, 0.25, 5, 20)
    wide_ab = DirectedANI(0.99, 0.90, 18, 20)
    wide_ba = DirectedANI(0.99, 0.10, 2, 20)
    with warnings.catch_warnings():
        warnings.simplefilter("error", RuntimeWarning)
        assert _combine_bidirectional(sym_ab, sym_ba, 0.15) == 0.99
        assert _combine_bidirectional(wide_ab, wide_ba, 0.15) == 0.99


def test_repeat_merge_hazard_warning_arrays_path():
    """The batched-C arrays path in bidirectional_ani_values fires the
    same warning (it bypasses _combine_bidirectional entirely)."""
    from galah_tpu.io.fasta import read_genome
    from galah_tpu.ops.fragment_ani import (
        bidirectional_ani_values,
        build_profile,
    )

    pytest.importorskip("galah_tpu.ops._cpairstats")
    paths = bench._synth_repeat_genomes(
        n_genomes=16, genome_len=50_000, repeat_frac=0.1, seed=23)
    profs = [build_profile(read_genome(p), k=21, fraglen=3000)
             for p in paths]
    # all pairs: >= 64 directed jobs selects the arrays path on CPU
    pairs = [(profs[i], profs[j])
             for i in range(16) for j in range(i + 1, 16)]
    with pytest.warns(RuntimeWarning, match="min-aligned-fraction"):
        vals = bidirectional_ani_values(pairs, min_aligned_frac=0.15)
    assert any(v is not None for v in vals)


@pytest.mark.slow
def test_e2e_repeat_merge_behavior_pinned():
    """The RECORDED adversarial behavior (see module docstring): the
    skani+skani default path merges some 10%-repeat pairs whose
    straddling elements push one direction's window-quantized aligned
    fraction past 15% while the other direction carries ~97% identity
    over one window (reference-parity bidirectional-max semantics);
    raising --min-aligned-fraction to 50 restores full separation. At
    30% repeats merges persist even at 50 (measured AF reaches 0.65)
    — inherent to ANI-over-aligned-windows with identical repeats."""
    paths10 = bench._synth_repeat_genomes(
        n_genomes=16, genome_len=50_000, repeat_frac=0.1, seed=23)
    assert len(_cluster(paths10, precluster_method="skani",
                        cluster_method="skani")) == 13
    assert len(_cluster(paths10, precluster_method="skani",
                        cluster_method="skani",
                        min_aligned_fraction=50.0)) == 16

    paths30 = bench._synth_repeat_genomes(
        n_genomes=16, genome_len=50_000, repeat_frac=0.3, seed=23)
    assert len(_cluster(paths30)) == 10
    assert len(_cluster(paths30, min_aligned_fraction=50.0)) == 10
