"""HyperLogLog backend: estimator accuracy, pairwise ANI, cluster parity.

Exact dashing value parity is a non-goal (different hash, and dashing is
itself an estimator); what must hold is estimator accuracy and the same
cluster compositions as the other precluster backends on the golden MAGs
(reference: src/clusterer.rs:481-663 pins those compositions).
"""

import numpy as np
import pytest

from galah_tpu.backends import FastANIEquivalentClusterer, HLLPreclusterer
from galah_tpu.cluster import cluster
from galah_tpu.io.fasta import read_genome
from galah_tpu.ops import hll


def _random_regs(n_items, p, seed):
    """Registers from n_items random 64-bit hashes (numpy reference)."""
    rng = np.random.default_rng(seed)
    h = rng.integers(0, 1 << 63, size=n_items, dtype=np.uint64) * 2 + 1
    import jax.numpy as jnp

    regs = hll._hll_update(jnp.zeros((1 << p,), dtype=jnp.uint8),
                           jnp.asarray(h), p)
    return np.asarray(regs), h


@pytest.mark.parametrize("n_items", [500, 20_000, 300_000])
def test_cardinality_accuracy(n_items):
    regs, _ = _random_regs(n_items, p=12, seed=42)
    est = float(hll.hll_cardinality(np.asarray(regs)[None, :])[0])
    # standard error ~1.04/sqrt(4096) = 1.6%; allow 4 sigma
    assert abs(est - n_items) / n_items < 0.065


def test_union_and_jaccard():
    import jax.numpy as jnp

    p = 12
    rng = np.random.default_rng(7)
    a = rng.integers(0, 1 << 63, size=100_000, dtype=np.uint64) * 2 + 1
    b = np.concatenate([a[:50_000],
                        rng.integers(0, 1 << 63, size=50_000,
                                     dtype=np.uint64) * 2 + 1])
    zeros = jnp.zeros((1 << p,), dtype=jnp.uint8)
    ra = np.asarray(hll._hll_update(zeros, jnp.asarray(a), p))
    rb = np.asarray(hll._hll_update(zeros, jnp.asarray(b), p))
    union = np.maximum(ra, rb)
    u = float(hll.hll_cardinality(union[None, :])[0])
    # true union = 150k (to hash-collision approximation)
    assert abs(u - 150_000) / 150_000 < 0.065


def test_threshold_pairs_non_dividing_tiles():
    """Tile sizes that don't divide the padded N must not mis-attribute
    pairs (regression: dynamic_slice start clamping)."""
    import jax.numpy as jnp

    rng = np.random.default_rng(5)
    n, p = 70, 10
    mat = np.zeros((n, 1 << p), dtype=np.uint8)
    for i in range(n):
        h = rng.integers(0, 1 << 63, size=50_000, dtype=np.uint64) * 2 + 1
        mat[i] = np.asarray(hll._hll_update(
            jnp.zeros((1 << p,), dtype=jnp.uint8), jnp.asarray(h), p))
    mat[69] = mat[16]  # identical pair at the tail
    # use_pallas=False pins the single-device implementation (the
    # dynamic_slice clamping path this test guards); the default call
    # auto-shards on the 8-device test runtime, so this also checks
    # single-device vs sharded agreement.
    pairs = hll.hll_threshold_pairs(mat, k=21, min_ani=0.99,
                                    row_tile=64, col_tile=80,
                                    use_pallas=False)
    assert (16, 69) in pairs
    ref = hll.hll_threshold_pairs(mat, k=21, min_ani=0.99)
    assert set(pairs) == set(ref)


def test_identical_sketch_ani_is_one():
    regs, _ = _random_regs(100_000, p=12, seed=3)
    mat = np.stack([regs, regs])
    pairs = hll.hll_threshold_pairs(mat, k=21, min_ani=0.9)
    assert (0, 1) in pairs
    assert pairs[(0, 1)] > 0.999


def test_real_pair_ani_close_to_minhash_golden(ref_data):
    """set1 1mbp vs 500kb: HLL ANI must land near the exact MinHash
    golden 0.9808188 (reference: src/finch.rs:96) within estimator
    noise."""
    g1 = read_genome(str(ref_data / "set1" / "1mbp.fna"))
    g2 = read_genome(str(ref_data / "set1" / "500kb.fna"))
    r1 = hll.hll_sketch_genome(g1, p=12, k=21)
    r2 = hll.hll_sketch_genome(g2, p=12, k=21)
    pairs = hll.hll_threshold_pairs(np.stack([r1, r2]), k=21, min_ani=0.9)
    assert (0, 1) in pairs
    assert abs(pairs[(0, 1)] - 0.9808188) < 0.01


ABISKO = [
    "abisko4/73.20120800_S1X.13.fna",
    "abisko4/73.20120600_S2D.19.fna",
    "abisko4/73.20120700_S3X.12.fna",
    "abisko4/73.20110800_S2D.13.fna",
]


@pytest.mark.slow
def test_hll_fastani_golden_clusters(ref_data):
    """dashing-precluster + fastANI-cluster reproduces the reference's
    golden compositions (reference: src/clusterer.rs:481-533)."""
    paths = [str(ref_data / n) for n in ABISKO]
    pre = HLLPreclusterer(min_ani=0.9)
    out95 = cluster(paths, pre, FastANIEquivalentClusterer(
        threshold=0.95, min_aligned_fraction=0.2))
    assert sorted(sorted(c) for c in out95) == [[0, 1, 2, 3]]
    out98 = cluster(paths, pre, FastANIEquivalentClusterer(
        threshold=0.98, min_aligned_fraction=0.2))
    assert sorted(sorted(c) for c in out98) == [[0, 1, 3], [2]]


def test_hll_batch_sketch_matches_single(tmp_path):
    """hll_sketch_genomes_batch registers are bit-identical per genome."""
    import numpy as np

    from galah_tpu.io import read_genome
    from galah_tpu.ops import hll

    rng = np.random.default_rng(11)
    genomes = []
    for i, seq_len in enumerate([120, 4000, 70_000]):
        seq = "".join(rng.choice(list("ACGT"), size=seq_len))
        p = tmp_path / f"h{i}.fna"
        p.write_text(f">a\n{seq[: seq_len // 2]}N{seq[seq_len // 2:]}\n")
        genomes.append(read_genome(str(p)))
    batch = hll.hll_sketch_genomes_batch(genomes, p=10)
    for g, regs in zip(genomes, batch):
        single = hll.hll_sketch_genome(g, p=10)
        np.testing.assert_array_equal(single, regs)
