"""Embeddable library API (api.py) — the reference exports its
orchestration so CoverM can embed it with renamed flags (reference:
src/cluster_argument_parsing.rs:84-124)."""

import argparse

import pytest

from galah_tpu.api import (
    ClustererCommandDefinition,
    GalahClusterer,
    add_cluster_arguments,
    generate_galah_clusterer,
)

ABISKO = [
    "abisko4/73.20120800_S1X.13.fna",
    "abisko4/73.20120600_S2D.19.fna",
    "abisko4/73.20120700_S3X.12.fna",
    "abisko4/73.20110800_S2D.13.fna",
]


def test_renamed_flags_parse_and_build():
    defn = ClustererCommandDefinition(ani="dereplication-ani",
                                      precluster_ani="rough-ani")
    parser = argparse.ArgumentParser()
    add_cluster_arguments(parser, defn)
    args = parser.parse_args(["--dereplication-ani", "97",
                              "--rough-ani", "92",
                              "--cluster-method", "fastani"])
    assert args.dereplication_ani == 97.0
    clusterer = generate_galah_clusterer(["x.fna"], vars(args), defn)
    assert isinstance(clusterer, GalahClusterer)
    assert clusterer.clusterer.ani_threshold == pytest.approx(0.97)
    assert clusterer.clusterer.method_name() == "fastani"


def test_default_definition_matches_cli_flags():
    parser = argparse.ArgumentParser()
    add_cluster_arguments(parser)
    args = parser.parse_args([])
    assert args.ani == 95.0
    assert args.precluster_method == "skani"


def test_missing_checkm_warning_emits_once_across_builds(caplog):
    """Repeated clusterer construction (bench rungs, embedding tools)
    emits the quality-ordering warning exactly once per process; later
    constructions record warn-once-suppressed events instead
    (reference: src/cluster_argument_parsing.rs:318 warns per call)."""
    import logging

    from galah_tpu.obs import events as obs_events
    from galah_tpu.utils.logging import reset_warn_once

    reset_warn_once()
    obs_events.reset()
    parser = argparse.ArgumentParser()
    add_cluster_arguments(parser)
    args = parser.parse_args([])  # no quality input -> warning path
    with caplog.at_level(logging.WARNING, logger="galah_tpu.api"):
        for _ in range(3):
            generate_galah_clusterer(["x.fna"], vars(args))
    hits = [r for r in caplog.records
            if "Since CheckM input is missing" in r.getMessage()]
    assert len(hits) == 1
    suppressed = [e for e in obs_events.snapshot()
                  if e["kind"] == "warn-once-suppressed"
                  and "Since CheckM" in e["message"]]
    assert len(suppressed) == 2
    reset_warn_once()


def test_conflicting_quality_inputs_raise():
    parser = argparse.ArgumentParser()
    add_cluster_arguments(parser)
    args = parser.parse_args(["--checkm-tab-table", "a.tsv",
                              "--genome-info", "b.csv"])
    with pytest.raises(ValueError, match="at most one"):
        generate_galah_clusterer(["x.fna"], vars(args))


def test_end_to_end_via_api(ref_data):
    """Embedding-style use: build from parsed args, run, golden clusters
    (reference: src/clusterer.rs:481-533 pins [[0,1,3],[2]] at 98)."""
    parser = argparse.ArgumentParser()
    add_cluster_arguments(parser)
    args = parser.parse_args([
        "--ani", "98", "--precluster-ani", "90",
        "--precluster-method", "finch", "--cluster-method", "fastani",
        "--min-aligned-fraction", "20",
    ])
    paths = [str(ref_data / n) for n in ABISKO]
    clusterer = generate_galah_clusterer(paths, vars(args))
    out = clusterer.cluster()
    assert sorted(sorted(c) for c in out) == [[0, 1, 3], [2]]


@pytest.mark.parametrize("pre", [
    "finch",
    # HLL default-tier coverage continues via test_hll.py and
    # test_synthetic_families[dashing]; this e2e variant is the
    # 30 s outlier of the file
    pytest.param("dashing", marks=pytest.mark.slow),
    "skani",
])
def test_degenerate_genomes_cluster_alone(tmp_path, pre):
    """All-N and shorter-than-k genomes survive every precluster backend
    end-to-end and land in singleton clusters (no reference analog —
    galah's backends would crash or skip; this build degrades to empty
    sketches)."""
    import numpy as np

    from galah_tpu.api import generate_galah_clusterer

    rng = np.random.default_rng(0)
    seq = "".join(rng.choice(list("ACGT"), size=50_000))
    paths = []
    for name, s in [("normal", seq), ("allN", "N" * 5000),
                    ("short", "ACGTACGT")]:
        p = tmp_path / f"{name}.fna"
        p.write_text(f">c\n{s}\n")
        paths.append(str(p))
    values = {"ani": 95.0, "precluster_ani": 90.0,
              "min_aligned_fraction": 15.0, "fragment_length": 3000,
              "precluster_method": pre, "cluster_method": "skani",
              "threads": 1}
    clusters = generate_galah_clusterer(paths, values).cluster()
    assert sorted(sorted(c) for c in clusters) == [[0], [1], [2]]


@pytest.mark.slow
def test_threads_parity_clusters(tmp_path):
    """--threads N produces identical clusters to --threads 1 (the
    threaded CPU sketch/profile fan-out is order-independent).
    Slow tier: compile-bound parity variant — two full cluster runs
    over six 30 kb genomes; the golden cluster tests pin the
    single-thread integers every run."""
    import numpy as np

    from galah_tpu.api import generate_galah_clusterer

    rng = np.random.default_rng(41)
    paths = []
    for f in range(3):
        base = rng.integers(0, 4, size=30_000)
        for m in range(2):
            seq = base.copy()
            if m:
                sites = rng.random(seq.shape[0]) < 0.02
                seq[sites] = (seq[sites]
                              + rng.integers(1, 4, size=int(sites.sum()))) % 4
            p = tmp_path / f"f{f}m{m}.fna"
            p.write_text(">c\n" + "".join("ACGT"[c] for c in seq) + "\n")
            paths.append(str(p))
    values = {"ani": 95.0, "precluster_ani": 90.0,
              "min_aligned_fraction": 15.0, "fragment_length": 3000,
              "precluster_method": "finch", "cluster_method": "skani"}
    one = generate_galah_clusterer(paths, {**values, "threads": 1}).cluster()
    many = generate_galah_clusterer(paths, {**values, "threads": 3}).cluster()
    assert sorted(map(sorted, one)) == sorted(map(sorted, many))


def test_hash_algorithm_reaches_profile_store():
    """--hash-algorithm selects the fragment-profile hash too (not just
    MinHash sketching): tpufast profiles build ~2.7x faster at real
    genome size and the campaign goldens pin equal clusterings."""
    from galah_tpu.api import generate_galah_clusterer

    DATA = "/root/reference/tests/data"
    parser = argparse.ArgumentParser()
    add_cluster_arguments(parser)
    args = parser.parse_args([
        "--hash-algorithm", "tpufast",
        "--precluster-method", "finch", "--cluster-method", "skani",
    ])
    cl = generate_galah_clusterer(
        [f"{DATA}/set1/1mbp.fna", f"{DATA}/set1/500kb.fna"],
        vars(args))
    assert cl.clusterer.store.hash_algorithm == "tpufast"
    # the cache key records non-default hashes so murmur3 and tpufast
    # profiles never collide on disk
    assert cl.clusterer.store._params().get("hash_algorithm") == "tpufast"
