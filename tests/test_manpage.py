"""--full-help man-style pages (manpage.py; the reference renders roff
through `man`, reference: src/cluster_argument_parsing.rs:1194-1263)."""

from galah_tpu import cli
from galah_tpu.manpage import render_full_help


def test_full_help_flag_exits_zero(capsys):
    assert cli.main(["cluster", "--full-help"]) == 0
    out = capsys.readouterr().out
    assert "GENOME INPUT" in out
    assert "--precluster-method" in out
    assert "EXAMPLES" in out


def test_full_help_validate(capsys):
    assert cli.main(["cluster-validate", "--full-help"]) == 0
    out = capsys.readouterr().out
    assert "--cluster-file" in out


def test_every_cluster_flag_appears_in_page():
    parser = cli.build_parser()
    sub = parser._subcommand_parsers["cluster"]
    page = render_full_help(sub, "cluster")
    for action in sub._actions:
        for flag in action.option_strings:
            if flag in ("-h", "--help"):
                continue
            assert flag in page, f"{flag} missing from full help"


def test_full_help_roff(capsys):
    """--full-help-roff prints groff man source (the reference renders
    its help through roff, reference: src/cluster_argument_parsing.rs
    --full-help-roff)."""
    from galah_tpu import cli

    assert cli.main(["cluster", "--full-help-roff"]) == 0
    out = capsys.readouterr().out
    assert out.startswith(".TH")
    assert ".SH NAME" in out
    assert ".SH CLUSTERING PARAMETERS" in out
    assert "\\-\\-precluster\\-method" in out
