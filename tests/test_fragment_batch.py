"""Batched fragment-ANI dispatch: bit-parity with the per-pair path.

The batched path (ops/fragment_ani.directed_ani_batch) must produce
byte-identical DirectedANI results to per-pair directed_ani — the vmap
computes the same per-row searchsorted, only dispatch granularity
changes.
"""

import numpy as np
import pytest

from galah_tpu.io.fasta import read_genome
from galah_tpu.ops import fragment_ani

ABISKO = [
    "abisko4/73.20120800_S1X.13.fna",
    "abisko4/73.20120600_S2D.19.fna",
    "abisko4/73.20120700_S3X.12.fna",
    "abisko4/73.20110800_S2D.13.fna",
]


@pytest.fixture(scope="module")
def profiles(ref_data):
    return [fragment_ani.build_profile(
        read_genome(str(ref_data / n)), k=15, fraglen=3000)
        for n in ABISKO]


def test_directed_batch_parity(profiles):
    queries = [(profiles[i], profiles[j])
               for i in range(4) for j in range(4) if i != j]
    batched = fragment_ani.directed_ani_batch(queries)
    for (q, r), got in zip(queries, batched):
        ref = fragment_ani.directed_ani(q, r)
        assert got == ref


def test_bidirectional_batch_parity(profiles):
    pairs = [(profiles[i], profiles[j])
             for i in range(4) for j in range(i + 1, 4)]
    batched = fragment_ani.bidirectional_ani_batch(
        pairs, min_aligned_frac=0.2)
    for (a, b), (ani, ab, ba) in zip(pairs, batched):
        ref_ani, ref_ab, ref_ba = fragment_ani.bidirectional_ani(
            a, b, min_aligned_frac=0.2)
        assert ab == ref_ab and ba == ref_ba
        if ref_ani is None:
            assert ani is None
        else:
            assert ani == ref_ani


def test_batch_respects_memory_cap(profiles, monkeypatch):
    """Tiny cap forces single-item chunks; results must not change."""
    queries = [(profiles[0], profiles[1]), (profiles[1], profiles[0]),
               (profiles[2], profiles[3])]
    full = fragment_ani.directed_ani_batch(queries)
    monkeypatch.setattr(fragment_ani, "_BATCH_ELEM_CAP", 1)
    single = fragment_ani.directed_ani_batch(queries)
    assert full == single


def test_mixed_shape_buckets(profiles, ref_data):
    """Genomes landing in different padded-shape buckets batch fine."""
    small = fragment_ani.build_profile(
        read_genome(str(ref_data / "set1" / "500kb.fna")),
        k=15, fraglen=3000)
    queries = [(profiles[0], small), (small, profiles[0]),
               (profiles[1], profiles[2])]
    batched = fragment_ani.directed_ani_batch(queries)
    for (q, r), got in zip(queries, batched):
        assert got == fragment_ani.directed_ani(q, r)


def test_build_profiles_batch_matches_single(tmp_path):
    """build_profiles_batch is bit-identical to per-genome build_profile
    (positional hashes, distinct set, markers), with and without
    FracMinHash subsampling."""
    import numpy as np

    from galah_tpu.io import read_genome
    from galah_tpu.ops import fragment_ani

    rng = np.random.default_rng(17)
    genomes = []
    for i, seq_len in enumerate([200, 5000, 70_000]):
        seq = "".join(rng.choice(list("ACGT"), size=seq_len))
        p = tmp_path / f"p{i}.fna"
        p.write_text(f">a\n{seq[: seq_len // 2]}N{seq[seq_len // 2:]}\n"
                     f">b\n{seq[:60]}\n")
        genomes.append(read_genome(str(p)))

    for c in (1, 16):
        batch = fragment_ani.build_profiles_batch(
            genomes, k=15, fraglen=3000, subsample_c=c)
        for g, prof in zip(genomes, batch):
            single = fragment_ani.build_profile(
                g, k=15, fraglen=3000, subsample_c=c)
            np.testing.assert_array_equal(single.flat_hashes,
                                          prof.flat_hashes)
            np.testing.assert_array_equal(single.ref_set, prof.ref_set)
            np.testing.assert_array_equal(single.markers, prof.markers)


def test_profile_store_get_many(tmp_path):
    """get_many returns the same profiles as repeated get(), fills the
    LRU, and survives mixed memory/disk/miss states."""
    import numpy as np

    from galah_tpu.backends.fragment_backend import ProfileStore
    from galah_tpu.io import diskcache

    rng = np.random.default_rng(23)
    paths = []
    for i in range(4):
        seq = "".join(rng.choice(list("ACGT"), size=2000 + 100 * i))
        p = tmp_path / f"s{i}.fna"
        p.write_text(f">c\n{seq}\n")
        paths.append(str(p))

    cache = diskcache.CacheDir(str(tmp_path / "cache"))
    store = ProfileStore(k=15, fraglen=3000, cache=cache)
    store.get(paths[0])          # memory hit
    profs = store.get_many(paths)
    for p, prof in zip(paths, profs):
        ref = store.get(p)
        np.testing.assert_array_equal(ref.flat_hashes, prof.flat_hashes)

    # disk-hit path: a fresh store over the same cache dir
    store2 = ProfileStore(k=15, fraglen=3000, cache=cache)
    profs2 = store2.get_many(paths)
    for a, b in zip(profs, profs2):
        np.testing.assert_array_equal(a.ref_set, b.ref_set)


def test_profile_store_get_many_batched_branch(tmp_path, monkeypatch):
    """GALAH_PACKED_TRANSFER=1 forces the TPU-policy batched branch of
    get_many; results must match the per-genome branch bit-for-bit."""
    import numpy as np

    from galah_tpu.backends.fragment_backend import ProfileStore
    from galah_tpu.io import diskcache

    rng = np.random.default_rng(29)
    paths = []
    for i in range(3):
        seq = "".join(rng.choice(list("ACGT"), size=3000 + 37 * i))
        p = tmp_path / f"b{i}.fna"
        p.write_text(f">c\n{seq}\n")
        paths.append(str(p))

    store_cpu = ProfileStore(
        k=15, fraglen=3000, cache=diskcache.CacheDir(str(tmp_path / "c1")))
    plain = store_cpu.get_many(paths)

    monkeypatch.setenv("GALAH_PACKED_TRANSFER", "1")
    store_tpu = ProfileStore(
        k=15, fraglen=3000, cache=diskcache.CacheDir(str(tmp_path / "c2")))
    batched = store_tpu.get_many(paths)
    for a, b in zip(plain, batched):
        np.testing.assert_array_equal(a.flat_hashes, b.flat_hashes)
        np.testing.assert_array_equal(a.ref_set, b.ref_set)


def test_generic_batch_path_matches_c_path(tmp_path):
    """The generic grouped-dispatch profile build (positional_hashes_batch
    + _profile_from_flat) must stay bit-identical to the C single-pass
    builder — on CPU the C path short-circuits build_profiles_batch, so
    this pins the generic path explicitly against it (regression
    coverage the auto-routing otherwise removes)."""
    import numpy as np
    import pytest

    pytest.importorskip("galah_tpu.ops._csketch")
    from galah_tpu.io.fasta import Genome, GenomeStats
    from galah_tpu.ops import fragment_ani as fa

    rng = np.random.default_rng(41)
    genomes = []
    for i in range(3):
        n = int(rng.integers(500, 40_000))
        codes = rng.integers(0, 4, size=n).astype(np.uint8)
        codes[n // 3: n // 3 + 10] = 255
        cut = int(rng.integers(1, n))
        genomes.append(Genome(
            path=f"g{i}.fna", codes=codes,
            contig_offsets=np.array([0, cut, n], dtype=np.int64),
            stats=GenomeStats(2, 10, n)))
    for c in (1, 16):
        assert fa._c_profile_available(15)
        via_c = [fa._profile_via_c(g, 15, 3000, c) for g in genomes]
        flats = fa.positional_hashes_batch(genomes, 15)
        generic = [fa._profile_from_flat(g.path, flat, 15, 3000, c)
                   for g, flat in zip(genomes, flats)]
        for a, b in zip(via_c, generic):
            np.testing.assert_array_equal(a.flat_hashes, b.flat_hashes)
            np.testing.assert_array_equal(a.ref_set, b.ref_set)
            np.testing.assert_array_equal(a.markers, b.markers)


@pytest.mark.slow
def test_directed_c_batch_path_parity(profiles, tmp_path):
    """>=64 uniform pairs trigger the batched C merge + vectorized
    post-math (_directed_ani_batch_c); every DirectedANI must be
    bit-identical to the per-pair device-walker path, including
    repeated profiles and an empty (zero-window) query."""
    empty_fa = tmp_path / "tiny.fna"
    empty_fa.write_bytes(b">c1\nACGTACGT\n")
    tiny = fragment_ani.build_profile(
        read_genome(str(empty_fa)), k=15, fraglen=3000)
    assert tiny.n_windows == 0

    queries = [(profiles[i % 4], profiles[(i + 1 + i // 4) % 4])
               for i in range(90) if i % 4 != (i + 1 + i // 4) % 4]
    queries += [(tiny, profiles[0]), (profiles[1], tiny)]
    assert len(queries) >= 64
    batched = fragment_ani.directed_ani_batch(queries)
    for (q, r), got in zip(queries, batched):
        assert got == fragment_ani.directed_ani(q, r)


def test_bidirectional_values_parity_subsampled(ref_data, tmp_path):
    """Default-tier twin of test_bidirectional_values_parity: the
    same <64 and >=64 (batched C array) paths, on subsample_c=16
    profiles so the per-pair walks cost ~16x less — the path
    selection in bidirectional_ani_values depends on pair count and
    concat volume, not the subsample, so coverage is equivalent. A
    zero-window profile rides in the >=64 batch so the empty-query
    edge of the C array path stays default-tier covered."""
    profs = [fragment_ani.build_profile(
        read_genome(str(ref_data / n)), k=15, fraglen=3000,
        subsample_c=16) for n in ABISKO]
    empty_fa = tmp_path / "tiny.fna"
    empty_fa.write_bytes(b">c1\nACGTACGT\n")
    tiny = fragment_ani.build_profile(
        read_genome(str(empty_fa)), k=15, fraglen=3000,
        subsample_c=16)
    assert tiny.n_windows == 0
    small = [(profs[i], profs[j])
             for i in range(4) for j in range(i + 1, 4)]
    big = (small * 12)[:68] + [(tiny, profs[0]), (profs[1], tiny)]
    for pairs in (small, big):
        want = [ani for ani, _, _ in fragment_ani.bidirectional_ani_batch(
            pairs, min_aligned_frac=0.2)]
        got = fragment_ani.bidirectional_ani_values(
            pairs, min_aligned_frac=0.2)
        assert got == want


@pytest.mark.slow
def test_bidirectional_values_parity(profiles):
    """bidirectional_ani_values == the ani column of
    bidirectional_ani_batch on both the per-pair (<64) and the
    array (>=64) paths."""
    small = [(profiles[i], profiles[j])
             for i in range(4) for j in range(i + 1, 4)]
    big = (small * 12)[:70]
    for pairs in (small, big):
        want = [ani for ani, _, _ in fragment_ani.bidirectional_ani_batch(
            pairs, min_aligned_frac=0.2)]
        got = fragment_ani.bidirectional_ani_values(
            pairs, min_aligned_frac=0.2)
        assert got == want
