"""Mosaic murmur3 sketch kernel: bit-parity with the XLA hash core,
run in interpreter mode on the CPU test mesh (hardware lowering is
covered by tests/test_tpu_hw.py).

Whole module is slow-tier: the kernel is QUARANTINED (hardware-retired
at 0.06x XLA, docs/artifacts/tpu_watch_20260801_0829/amortized.txt;
see ops/pallas_sketch.py) and reachable only via the
GALAH_TPU_PALLAS_HASH opt-in, so its parity no longer gates the
default per-commit loop."""

import numpy as np
import pytest

import jax.numpy as jnp

pytestmark = pytest.mark.slow

from galah_tpu.ops.hashing import _murmur3_k21_1d
from galah_tpu.ops.murmur3_np import murmur3_x64_128_h1 as mm3_np
from galah_tpu.ops.pallas_sketch import (
    assemble_k21_words,
    murmur3_k21_pallas,
)


def _random_byte_vectors(rng, n):
    """21 per-byte u64 vectors, the _hash_core cb[] shape."""
    raw = rng.integers(0, 256, size=(n, 21), dtype=np.uint64)
    return raw, [jnp.asarray(raw[:, j]) for j in range(21)]


@pytest.mark.parametrize("n,seed", [(1000, 0), (4097, 7)])
def test_kernel_matches_xla_hash_core(n, seed):
    rng = np.random.default_rng(31 + n)
    _, cb = _random_byte_vectors(rng, n)
    want = np.asarray(_murmur3_k21_1d(cb, seed))
    k1, k2, t = assemble_k21_words(cb)
    got = np.asarray(murmur3_k21_pallas(k1, k2, t, seed=seed,
                                        interpret=True))
    np.testing.assert_array_equal(got, want)


def test_kernel_matches_host_reference_on_ascii_kmers():
    """Against the numpy reference implementation on real ACGT k-mer
    bytes (the exact finch contract, reference: src/finch.rs:33-47)."""
    rng = np.random.default_rng(5)
    n = 512
    kmers = rng.choice(np.frombuffer(b"ACGT", dtype=np.uint8), size=(n, 21))
    want = mm3_np(kmers, seed=0)
    cb = [jnp.asarray(kmers[:, j].astype(np.uint64)) for j in range(21)]
    k1, k2, t = assemble_k21_words(cb)
    got = np.asarray(murmur3_k21_pallas(k1, k2, t, seed=0,
                                        interpret=True))
    np.testing.assert_array_equal(got, np.asarray(want, dtype=np.uint64))


def test_kernel_padding_boundaries():
    """Sizes straddling the block quantum pad and trim correctly."""
    rng = np.random.default_rng(9)
    for n in (1, 127, 128, 65536, 65537):
        _, cb = _random_byte_vectors(rng, n)
        want = np.asarray(_murmur3_k21_1d(cb, 0))
        k1, k2, t = assemble_k21_words(cb)
        got = np.asarray(murmur3_k21_pallas(k1, k2, t, interpret=True))
        assert got.shape == (n,)
        np.testing.assert_array_equal(got, want)


def test_env_optin_end_to_end_sketch_identical(monkeypatch):
    """GALAH_TPU_PALLAS_HASH=1 routes the chunk hashers through the
    Mosaic kernel (interpret mode off-TPU) with bit-identical sketches.
    The env is read at first trace, so the cache is cleared around it."""
    import jax

    from galah_tpu.io.fasta import read_genome
    from galah_tpu.ops.minhash import sketch_genome_device

    g = read_genome("/root/reference/tests/data/set1/500kb.fna")
    base = sketch_genome_device(g, sketch_size=1000, k=21, seed=0)

    monkeypatch.setenv("GALAH_TPU_PALLAS_HASH", "1")
    jax.clear_caches()
    try:
        via_kernel = sketch_genome_device(g, sketch_size=1000, k=21,
                                          seed=0)
    finally:
        monkeypatch.delenv("GALAH_TPU_PALLAS_HASH")
        jax.clear_caches()
    np.testing.assert_array_equal(via_kernel.hashes, base.hashes)


def test_tail_word_high_bytes_ignored():
    """The contract uses only the low 5 bytes of the tail word; bytes
    5-7 must not affect the hash (tests/test_tpu_hw.py feeds
    full-random words and relies on this)."""
    rng = np.random.default_rng(13)
    n = 256
    k1 = jnp.asarray(rng.integers(0, 1 << 64, size=n, dtype=np.uint64))
    k2 = jnp.asarray(rng.integers(0, 1 << 64, size=n, dtype=np.uint64))
    t_full = rng.integers(0, 1 << 64, size=n, dtype=np.uint64)
    t_masked = t_full & np.uint64(0xFFFFFFFFFF)
    a = np.asarray(murmur3_k21_pallas(k1, k2, jnp.asarray(t_full),
                                      interpret=True))
    b = np.asarray(murmur3_k21_pallas(k1, k2, jnp.asarray(t_masked),
                                      interpret=True))
    np.testing.assert_array_equal(a, b)
