"""galah-tpu lint: every checker demonstrated on seeded-violation
fixtures, the clean-fixture negative, suppression/baseline mechanics,
and the tier-1 gate that the repo itself lints clean."""

import json
import pathlib
import subprocess
import sys

import pytest

from galah_tpu.analysis import (DEFAULT_BASELINE, CHECK_NAMES,
                                load_sources, repo_root, run_checks,
                                run_lint)
from galah_tpu.analysis import core
from galah_tpu.analysis.core import Severity, SourceFile
from galah_tpu.analysis.flags_check import check_flag_references
from galah_tpu.analysis.markers_check import (check_markers_file,
                                              is_hardware_module)
from galah_tpu.analysis.pallas_check import check_pallas_file
from galah_tpu.analysis.runtime_checks import check_runtime_file

FIXTURES = pathlib.Path(__file__).parent / "data" / "lint_fixtures"


def load_fixture(name: str, path: str = None) -> SourceFile:
    src = SourceFile.load(str(FIXTURES / name))
    if path is not None:
        src.path = path
    return src


def codes(findings):
    return sorted({f.code for f in findings if not f.suppressed})


# ---------------------------------------------------------------------------
# GL1xx: Pallas contract checker
# ---------------------------------------------------------------------------


def test_bad_blockspec_fires_lane_and_sublane():
    found = check_pallas_file(load_fixture("bad_blockspec.py"))
    assert "GL103" in codes(found)
    assert "GL104" in codes(found)


def test_u64_boundary_and_kernel_body_fire():
    found = check_pallas_file(load_fixture("bad_u64.py"))
    gl106 = [f for f in found if f.code == "GL106"]
    # input boundary, out_shape, and the kernel-body reference
    assert len(gl106) >= 3


def test_vmem_budget_overflow_fires():
    found = check_pallas_file(load_fixture("bad_vmem.py"))
    assert "GL105" in codes(found)


def test_missing_contract_fires():
    found = check_pallas_file(load_fixture("missing_contract.py"))
    assert codes(found) == ["GL101"]


def test_stale_contract_entry_fires():
    src = load_fixture("missing_contract.py")
    contract = {"no_such_function": {"bindings": {}}}
    found = check_pallas_file(src, contract=contract)
    assert "GL101" in codes(found)  # the real site is still uncovered
    assert "GL102" in codes(found)  # and the entry is stale


# ---------------------------------------------------------------------------
# GL2xx/GL3xx: host-sync and recompile churn
# ---------------------------------------------------------------------------


def test_jit_fixture_fires_every_runtime_code():
    found = check_runtime_file(load_fixture("bad_jit.py"))
    got = codes(found)
    assert {"GL201", "GL202", "GL203", "GL301", "GL302"} <= set(got)


def test_shape_access_is_exempt():
    found = check_runtime_file(load_fixture("bad_jit.py"))
    assert not [f for f in found if f.symbol == "clean_shapes"]


# ---------------------------------------------------------------------------
# GL4xx: flag registry
# ---------------------------------------------------------------------------


def test_unregistered_and_conflicting_default_fire():
    found = check_flag_references([load_fixture("bad_flags.py")])
    by_code = {f.code: f for f in found if f.path.endswith("bad_flags.py")}
    assert "GL401" in by_code and "GALAH_TPU_CAHCE" in by_code["GL401"].message
    assert "GL402" in by_code
    assert "GALAH_TPU_PAIRLIST_BLOCK" in by_code["GL402"].message
    # the matching-default read must NOT fire
    assert not [f for f in found
                if f.code == "GL402"
                and "GALAH_TPU_SPARSE_MIN_N" in f.message]


def test_registry_is_documented_and_rendered():
    """GL403/404/405 health over the real repo tree: every registered
    flag referenced (or externally owned), documented, and present in
    the auto-rendered manpage ENVIRONMENT section."""
    sources = load_sources(repo_root())
    found = check_flag_references(list(sources.values()))
    assert not [f for f in found if f.code in ("GL403", "GL404", "GL405")], \
        [f.message for f in found]


def test_manpage_renders_every_flag():
    from galah_tpu.config import FLAGS
    from galah_tpu.manpage import render_environment_section

    section = render_environment_section()
    for name in FLAGS:
        assert name in section


# ---------------------------------------------------------------------------
# GL6xx: hardware-test marker audit
# ---------------------------------------------------------------------------


def test_unmarked_hardware_tests_fire():
    src = load_fixture("hw_unmarked_case.py",
                       path="tests/test_tpu_hw_seeded.py")
    assert is_hardware_module(src)
    found = check_markers_file(src)
    flagged = {f.symbol for f in found}
    assert flagged == {"test_kernel_on_hardware", "test_kernel_cases"}
    # the quarantined-import heuristic works without the filename too
    src2 = load_fixture("hw_unmarked_case.py",
                        path="tests/test_quarantined_seeded.py")
    assert is_hardware_module(src2)


def test_module_level_pytestmark_satisfies_audit():
    src = load_fixture("hw_unmarked_case.py",
                       path="tests/test_tpu_hw_seeded.py")
    src.text = "pytestmark = pytest.mark.slow\n" + src.text
    import ast

    src.tree = ast.parse(src.text)
    assert check_markers_file(src, force_hardware=True) == []


def test_repo_hardware_tests_are_marked():
    sources = load_sources(repo_root())
    found = []
    for src in sources.values():
        found.extend(check_markers_file(src))
    assert not found, [f.message for f in found]


# ---------------------------------------------------------------------------
# GL7xx: observability discipline (ad-hoc timing outside obs/)
# ---------------------------------------------------------------------------


def test_bad_timing_fixture_fires_gl701_and_gl702():
    from galah_tpu.analysis.obs_check import check_obs_file

    src = load_fixture("bad_timing.py",
                       path="galah_tpu/ops/bad_timing.py")
    found = check_obs_file(src)
    gl701 = sorted(f.line for f in found if f.code == "GL701")
    gl702 = sorted(f.line for f in found if f.code == "GL702")
    # direct calls, aliased-module call, from-import alias, and the
    # (later suppressed) wall-clock stamp; both log-literal shapes
    assert gl701 == [11, 13, 19, 21, 31]
    assert gl702 == [22, 23]
    assert all(f.severity is Severity.WARNING for f in found)


def test_bad_timing_inline_suppression_applies():
    from galah_tpu.analysis.obs_check import check_obs_file

    src = load_fixture("bad_timing.py",
                       path="galah_tpu/ops/bad_timing.py")
    found = check_obs_file(src)
    core.apply_suppressions(found, {src.path: src}, {})
    active = sorted(f.line for f in found if not f.suppressed)
    assert active == [11, 13, 19, 21, 22, 23]  # line 31 is justified


def test_obs_check_exempts_utils_obs_analysis_and_nonpackage():
    from galah_tpu.analysis.obs_check import check_obs_file, in_scope

    for path in ("galah_tpu/utils/timing.py",
                 "galah_tpu/obs/metrics.py",
                 "galah_tpu/analysis/obs_check.py",
                 "scripts/smoke.py",
                 "tests/test_obs.py",
                 "bench.py"):
        assert not in_scope(path)
        assert check_obs_file(load_fixture("bad_timing.py",
                                           path=path)) == []
    assert in_scope("galah_tpu/ops/bad_timing.py")


def test_bad_device_cost_fixture_fires_gl703():
    from galah_tpu.analysis.obs_check import check_obs_file

    src = load_fixture("bad_device_cost.py",
                       path="galah_tpu/ops/bad_device_cost.py")
    found = check_obs_file(src)
    gl703 = sorted(f.line for f in found if f.code == "GL703")
    # memory_stats() call, cost_analysis() call, and the (later
    # suppressed) capacity probe; the bare attribute access and the
    # locally defined method must not fire
    assert gl703 == [14, 16, 19]
    assert all(f.severity is Severity.WARNING for f in found)


def test_bad_device_cost_suppression_and_exemptions():
    from galah_tpu.analysis.obs_check import check_obs_file

    src = load_fixture("bad_device_cost.py",
                       path="galah_tpu/ops/bad_device_cost.py")
    found = check_obs_file(src)
    core.apply_suppressions(found, {src.path: src}, {})
    active = sorted(f.line for f in found if not f.suppressed)
    assert active == [14, 16]  # line 19 carries a justification
    # obs/profile.py is the sanctioned caller: out of GL7xx scope
    assert check_obs_file(load_fixture(
        "bad_device_cost.py", path="galah_tpu/obs/profile.py")) == []


def test_bad_flow_fixture_fires_gl704_exact_lines():
    from galah_tpu.analysis.obs_check import check_obs_file

    src = load_fixture("bad_flow.py",
                       path="galah_tpu/ops/bad_flow.py")
    found = check_obs_file(src)
    gl704 = sorted(f.line for f in found if f.code == "GL704")
    # the PIPELINE_STAGE anchor (no obs.flow usage at all), the +=
    # accumulator, the aliased from-import stamp, and the plain
    # assign; the budget arithmetic on a non-wait name must not fire
    assert gl704 == [8, 19, 24, 27]
    assert {f.code for f in found} == {"GL704"}
    assert all(f.severity is Severity.WARNING for f in found)


def test_gl704_scope_is_pipeline_stage_modules_only():
    from galah_tpu.analysis.obs_check import check_obs_file

    # no PIPELINE_STAGE declaration -> GL704 never fires, even on a
    # file full of timing sins (those are GL701/702's)
    src = load_fixture("bad_timing.py",
                       path="galah_tpu/ops/bad_timing.py")
    assert not [f for f in check_obs_file(src) if f.code == "GL704"]
    # outside the GL7xx scope entirely
    assert check_obs_file(load_fixture(
        "bad_flow.py", path="scripts/bad_flow.py")) == []


def test_gl704_real_pipeline_stage_modules_are_clean():
    from galah_tpu.analysis.obs_check import check_obs_file

    root = repo_root()
    for rel in ("galah_tpu/ops/pairwise.py",
                "galah_tpu/ops/sketch_stream.py",
                "galah_tpu/cluster/engine.py",
                "galah_tpu/index/incremental.py"):
        src = SourceFile.load(str(pathlib.Path(root) / rel))
        src.path = rel
        bad = [f for f in check_obs_file(src) if f.code == "GL704"]
        assert not bad, (rel, [(f.line, f.message) for f in bad])


def test_repo_has_no_unsuppressed_adhoc_timing():
    found = [f for f in run_lint(checks=("obs",))
             if not f.suppressed]
    assert not found, [(f.path, f.line, f.message) for f in found]


# ---------------------------------------------------------------------------
# GL8xx: concurrency discipline
# ---------------------------------------------------------------------------


def test_bad_concurrency_fires_every_rule():
    from galah_tpu.analysis.concurrency_check import check_concurrency

    src = load_fixture("bad_concurrency.py")
    found = check_concurrency({src.path: src})
    by_code = {}
    for f in found:
        by_code.setdefault(f.code, []).append(f.line)
    # mutation outside lock (method call, rebind, guarded global)
    assert sorted(by_code["GL801"]) == [28, 31, 35]
    assert all(f.severity is Severity.ERROR
               for f in found if f.code == "GL801")
    # B held while acquiring A, against LOCK_ORDER = [A, B]
    assert by_code["GL802"] == [40]
    # re-acquiring a held non-reentrant Lock
    assert by_code["GL803"] == [46]
    # unadopted pool.submit + Thread(target=...)
    assert sorted(by_code["GL804"]) == [55, 56]
    assert sorted(by_code) == ["GL801", "GL802", "GL803", "GL804"]


def test_clean_concurrency_is_silent():
    from galah_tpu.analysis.concurrency_check import check_concurrency

    src = load_fixture("clean_concurrency.py")
    assert check_concurrency({src.path: src}) == []


def test_threaded_module_without_annotations_fires_gl805():
    import ast

    from galah_tpu.analysis.concurrency_check import check_concurrency

    text = "import threading\n_L = threading.Lock()\n"
    src = SourceFile(path="galah_tpu/obs/metrics.py", text=text,
                     tree=ast.parse(text))
    found = check_concurrency({src.path: src})
    assert [f.code for f in found] == ["GL805"]
    assert "GUARDED_BY" in found[0].message


def test_repo_concurrency_discipline_holds():
    found = [f for f in run_lint(checks=("concurrency",))
             if not f.suppressed]
    assert not found, [(f.path, f.line, f.message) for f in found]


# ---------------------------------------------------------------------------
# GL9xx: numeric determinism
# ---------------------------------------------------------------------------


def test_masked_sum_regression_fixture_fires_gl901():
    """The PR 5 class: summing a zero-filled np.where instead of the
    compressed segment must be an ERROR in contract functions."""
    from galah_tpu.analysis.determinism_check import \
        check_determinism_file

    found = check_determinism_file(load_fixture("bad_masked_sum.py"))
    gl901 = sorted(f.line for f in found if f.code == "GL901")
    # reduceat over a zero-fill name, inline np.sum, .sum() method
    assert gl901 == [21, 25, 30]
    assert all(f.severity is Severity.ERROR
               for f in found if f.code == "GL901")
    # the compressed form (c[ok]) is the sanctioned shape
    assert not [f for f in found if f.symbol == "good_compressed"]


def test_bad_determinism_fires_set_narrowing_rng_and_stale():
    from galah_tpu.analysis.determinism_check import \
        check_determinism_file

    found = check_determinism_file(load_fixture("bad_determinism.py"))
    by_code = {}
    for f in found:
        by_code.setdefault(f.code, []).append(f.line)
    assert sorted(by_code["GL902"]) == [22, 23, 25]
    assert sorted(by_code["GL903"]) == [15, 16]
    assert sorted(by_code["GL904"]) == [30, 31]
    assert by_code["GL905"] == [1]  # stale 'gone_function' entry
    assert sorted(by_code) == ["GL902", "GL903", "GL904", "GL905"]
    # seeded default_rng + sorted(set(...)) stay silent
    assert not [f for f in found if f.line >= 34]


def test_strategy_module_without_contract_fires_gl905():
    from galah_tpu.analysis.determinism_check import (
        STRATEGY_MODULES, check_determinism_file)

    src = load_fixture("clean_case.py", path=STRATEGY_MODULES[0])
    found = check_determinism_file(src)
    assert any(f.code == "GL905" and "lacks" in f.message
               for f in found)


def test_repo_determinism_contracts_hold():
    found = [f for f in run_lint(checks=("determinism",))
             if not f.suppressed]
    assert not found, [(f.path, f.line, f.message) for f in found]


# ---------------------------------------------------------------------------
# Clean fixture, suppressions, baseline
# ---------------------------------------------------------------------------


def test_clean_fixture_has_zero_findings():
    src = load_fixture("clean_case.py")
    found = (check_pallas_file(src) + check_runtime_file(src)
             + [f for f in check_flag_references([src])
                if f.path == src.path]
             + check_markers_file(src))
    assert found == []


def test_inline_suppression_and_wildcard():
    import ast

    text = ("import os\n"
            "a = os.environ.get('GALAH_BOGUS')  "
            "# galah-lint: ignore[GL401]\n"
            "# galah-lint: ignore[*]\n"
            "b = os.environ.get('GALAH_BOGUS2')\n")
    src = SourceFile(path="x.py", text=text, tree=ast.parse(text))
    src._index_suppressions()
    found = [f for f in check_flag_references([src]) if f.path == "x.py"]
    core.apply_suppressions(found, {"x.py": src}, {})
    assert all(f.suppressed and f.suppression == "inline" for f in found)


def test_suppression_expires_future_past_and_unparseable():
    import ast

    # the marker token is split across adjacent literals so the lint
    # scan of THIS file does not index these as real suppressions
    mark = "# galah-li" "nt: ign" "ore[GL401]"
    text = ("import os\n"
            f"a = os.environ.get('GALAH_BOGUS')  "
            f"{mark} expires=2999-01-01\n"
            "\n"
            f"b = os.environ.get('GALAH_BOGUS2')  "
            f"{mark} expires=2001-01-01\n"
            "\n"
            f"c = os.environ.get('GALAH_BOGUS3')  "
            f"{mark} expires=not-a-date\n")
    src = SourceFile(path="x.py", text=text, tree=ast.parse(text))
    src._index_suppressions()
    found = [f for f in check_flag_references([src]) if f.path == "x.py"]
    core.apply_suppressions(found, {"x.py": src}, {})
    by_line = {f.line: f for f in found}
    assert by_line[2].suppressed          # future date still suppresses
    assert not by_line[4].suppressed      # expired
    assert not by_line[6].suppressed      # unparseable never suppresses
    expiry = core.check_suppression_expiry(src)
    assert sorted(f.line for f in expiry) == [4, 6]
    assert all(f.code == "GL001"
               and f.severity is Severity.WARNING for f in expiry)
    messages = {f.line: f.message for f in expiry}
    assert "expired" in messages[4]
    assert "unparseable" in messages[6]


def test_suppression_valid_on_its_expiry_date():
    import ast
    import datetime

    mark = "# galah-li" "nt: ign" "ore[GL401]"
    text = ("import os\n"
            f"a = os.environ.get('GALAH_BOGUS')  "
            f"{mark} expires=2030-06-01\n")
    src = SourceFile(path="x.py", text=text, tree=ast.parse(text))
    src._index_suppressions()
    on_date = datetime.date(2030, 6, 1)
    after = datetime.date(2030, 6, 2)
    assert src.is_ignored("GL401", 2, today=on_date)
    assert not src.is_ignored("GL401", 2, today=after)
    assert core.check_suppression_expiry(src, today=on_date) == []
    assert core.check_suppression_expiry(src, today=after) != []


def test_baseline_suppresses_by_fingerprint(tmp_path):
    src = load_fixture("bad_flags.py")
    found = [f for f in check_flag_references([src])
             if f.path.endswith("bad_flags.py")]
    assert found
    bl = tmp_path / "baseline.json"
    core.write_baseline(str(bl), found)
    baseline = core.load_baseline(str(bl))
    fresh = [f for f in check_flag_references([src])
             if f.path.endswith("bad_flags.py")]
    core.apply_suppressions(fresh, {}, baseline)
    assert all(f.suppressed and f.suppression == "baseline"
               for f in fresh)


# ---------------------------------------------------------------------------
# GL5xx: abstract-eval shape contracts
# ---------------------------------------------------------------------------


def test_shape_contracts_match_snapshot():
    from galah_tpu.analysis.shapes import check_shape_contracts

    found = check_shape_contracts()
    assert found == [], [f.message for f in found]


def test_shape_snapshot_drift_fires(monkeypatch, tmp_path):
    from galah_tpu.analysis import shapes

    snap = shapes.load_snapshot()
    assert snap, "committed snapshot must exist"
    # corrupt one entry and drop one op -> GL501 + GL502
    drifted = {op: dict(cases) for op, cases in snap.items()}
    first_op = sorted(drifted)[0]
    first_case = sorted(drifted[first_op])[0]
    drifted[first_op][first_case] = "float64[3,3]"
    drifted["ghost.op"] = {"case": "int32[1]"}
    p = tmp_path / "shape_contracts.json"
    p.write_text(json.dumps({"version": 1, "contracts": drifted}))
    monkeypatch.setattr(shapes, "SNAPSHOT_PATH", str(p))
    found = shapes.check_shape_contracts()
    assert "GL501" in codes(found)
    assert any(f.code == "GL502" and "ghost.op" in f.message
               for f in found)


# ---------------------------------------------------------------------------
# The tier-1 gate: the repo itself lints clean
# ---------------------------------------------------------------------------


def test_repo_lints_clean():
    """Zero unsuppressed findings at WARNING or above across every
    checker family — the same gate `galah-tpu lint` enforces."""
    findings = run_lint()
    bad = core.failing(findings, Severity.WARNING)
    assert bad == [], "\n" + core.render_human(bad)


def test_lint_cli_json_contract():
    """`galah-tpu lint --json` (via the module entry point, cheap
    checkers only) emits the machine-readable schema the validation
    script consumes."""
    proc = subprocess.run(
        [sys.executable, "-m", "galah_tpu.analysis", "--json",
         "--check", "pallas", "--check", "runtime",
         "--check", "markers"],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    report = json.loads(proc.stdout)
    assert report["version"] == 1
    assert set(report["summary"]) == {"errors", "warnings", "notes",
                                      "suppressed", "by_family"}
    assert report["summary"]["errors"] == 0


def test_baseline_file_is_committed_and_empty():
    baseline = core.load_baseline(DEFAULT_BASELINE)
    assert baseline == {}, "repo lints clean; baseline must stay empty"
    assert pathlib.Path(DEFAULT_BASELINE).is_file()


def test_fixture_dir_not_scanned():
    sources = load_sources(repo_root())
    assert not [p for p in sources if "lint_fixtures" in p]


# ---------------------------------------------------------------------------
# Lint summary, run-report wiring, --changed-only
# ---------------------------------------------------------------------------


def test_lint_summary_counts_by_family():
    from galah_tpu.analysis.determinism_check import \
        check_determinism_file

    assert core.family_of("GL103") == "GL1xx"
    assert core.family_of("GL901") == "GL9xx"
    found = check_determinism_file(load_fixture("bad_masked_sum.py"))
    summary = core.lint_summary(found)
    assert summary["errors"] == 3
    assert summary["by_family"] == {"GL9xx": 3}
    found[0].suppressed = True
    summary = core.lint_summary(found)
    assert summary["suppressed"] == 1
    assert summary["by_family"] == {"GL9xx": 2}


def test_lint_run_report_carries_summary(tmp_path):
    """`galah-tpu lint --run-report` writes a schema-valid report
    with the lint section `galah-tpu report --diff` consumes."""
    report_path = tmp_path / "lint_report.json"
    proc = subprocess.run(
        [sys.executable, "-m", "galah_tpu.analysis",
         "--check", "suppressions", "--run-report", str(report_path)],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    report = json.loads(report_path.read_text())
    assert report["version"] == 10
    assert report["run"]["subcommand"] == "lint"
    assert set(report["lint"]) == {"errors", "warnings", "notes",
                                   "suppressed", "by_family",
                                   "timings_s"}
    assert set(report["lint"]["timings_s"]) == {"suppressions"}
    from galah_tpu.obs import report as report_mod

    assert report_mod.validate(report) == []


def test_report_diff_shows_lint_drift():
    from galah_tpu.obs import report as report_mod

    def rep(errors, fams):
        return {"run": {"duration_s": 1.0},
                "lint": {"errors": errors, "warnings": 0, "notes": 0,
                         "suppressed": 0, "by_family": fams}}

    out = report_mod.diff(rep(0, {}), rep(2, {"GL9xx": 2}))
    assert "lint drift:" in out
    assert "errors: 0 -> 2 (+2)" in out
    assert "GL9xx: 0 -> 2 (+2)" in out


def test_changed_files_tracks_git_state(tmp_path):
    from galah_tpu.analysis import changed_files

    root = str(tmp_path)
    git = ["git", "-C", root, "-c", "user.name=t",
           "-c", "user.email=t@t"]
    subprocess.run(["git", "init", "-q", root], check=True)
    # no commits yet: git can't answer, caller falls back to full scan
    assert changed_files(root) is None
    (tmp_path / "tracked.py").write_text("x = 1\n")
    subprocess.run(git + ["add", "tracked.py"], check=True)
    subprocess.run(git + ["commit", "-q", "-m", "init"], check=True)
    assert changed_files(root) == set()
    (tmp_path / "tracked.py").write_text("x = 2\n")
    (tmp_path / "untracked.py").write_text("y = 1\n")
    assert changed_files(root) == {"tracked.py", "untracked.py"}
    subprocess.run(git + ["add", "-A"], check=True)
    subprocess.run(git + ["commit", "-q", "-m", "more"], check=True)
    subprocess.run(git + ["mv", "tracked.py", "renamed.py"],
                   check=True)
    subprocess.run(git + ["rm", "-q", "untracked.py"], check=True)
    # vanished paths (rename source, deletion) must be skipped —
    # feeding them to the checkers used to crash the pre-commit gate
    assert changed_files(root) == {"renamed.py"}


# -- GL806: durable-write discipline (fs_check) -----------------------


def test_bad_durable_write_fixture_fires_gl806():
    from galah_tpu.analysis.fs_check import DURABLE_MODULES, \
        check_fs_file

    src = load_fixture("bad_durable_write.py", path=DURABLE_MODULES[0])
    found = check_fs_file(src)
    gl806 = sorted(f.line for f in found if f.code == "GL806")
    # open("w"), open(mode="a"), mkstemp(), fdopen("wb"), os.replace()
    # — the read-mode open in read_back must NOT fire
    assert gl806 == [12, 18, 25, 26, 28]
    assert all(f.severity is Severity.WARNING for f in found)
    assert all("io/atomic.py" in f.message for f in found)


def test_gl806_exempts_atomic_and_out_of_scope_files():
    from galah_tpu.analysis.fs_check import (SANCTIONED, check_fs_file,
                                             in_scope)

    # the sanctioned writer itself, and anything outside the
    # durable-artifact modules, may open files however it likes
    for path in (SANCTIONED, "galah_tpu/cli.py",
                 "tests/test_atomic.py", "scripts/chaos_run.py"):
        assert not in_scope(path)
        assert check_fs_file(load_fixture("bad_durable_write.py",
                                          path=path)) == []


def test_gl806_suppression_applies():
    from galah_tpu.analysis.fs_check import DURABLE_MODULES, \
        check_fs_file

    src = load_fixture("bad_durable_write.py", path=DURABLE_MODULES[0])
    found = check_fs_file(src)
    core.apply_suppressions(found, {src.path: src}, {})
    assert all(not f.suppressed for f in found)  # fixture: none carry one


def test_repo_durable_modules_all_write_through_atomic():
    found = [f for f in run_lint(checks=("fs",)) if not f.suppressed]
    assert not found, [(f.path, f.line, f.message) for f in found]


# ---------------------------------------------------------------------------
# GL10xx: pipeline discipline
# ---------------------------------------------------------------------------


def test_bad_pipeline_fires_every_rule():
    from galah_tpu.analysis.pipeline_check import check_pipeline_file

    src = load_fixture("bad_pipeline.py",
                       path="galah_tpu/ops/bad_pipeline.py")
    found = check_pipeline_file(src)
    by_code = {}
    for f in found:
        by_code.setdefault(f.code, []).append(f.line)
    # direct list(iter_rows(...)) + sorted() over a bound stream +
    # tuple() materialization
    assert sorted(by_code["GL1001"]) == [36, 38, 50]
    # block_until_ready inside the declared streaming stage
    assert by_code["GL1002"] == [27]
    # Queue() no maxsize, SimpleQueue(), ThreadPoolExecutor() bare
    assert sorted(by_code["GL1003"]) == [43, 44, 45]
    # declared gauge never emitted (anchored at the annotation)
    assert by_code["GL1004"] == [14]
    # unknown key "depth" + dangling streaming name "missing_stage"
    assert sorted(by_code["GL1005"]) == [14, 14]
    assert sorted(by_code) == ["GL1001", "GL1002", "GL1003",
                               "GL1004", "GL1005"]
    assert all(f.severity is Severity.WARNING for f in found)


def test_clean_pipeline_is_silent():
    from galah_tpu.analysis.pipeline_check import check_pipeline_file

    src = load_fixture("clean_pipeline.py",
                       path="galah_tpu/ops/clean_pipeline.py")
    assert check_pipeline_file(src) == []


def test_gl1001_scope_excludes_utils_obs_analysis():
    from galah_tpu.analysis.pipeline_check import (check_pipeline_file,
                                                   in_scope)

    for path in ("galah_tpu/utils/timing.py", "galah_tpu/obs/report.py",
                 "galah_tpu/analysis/core.py", "tests/test_x.py",
                 "scripts/bench.py"):
        assert not in_scope(path)
    assert in_scope("galah_tpu/ops/sketch_stream.py")
    # out of GL1001 scope, the other families still apply
    src = load_fixture("bad_pipeline.py", path="tests/bad_pipeline.py")
    found = check_pipeline_file(src)
    assert "GL1001" not in codes(found)
    assert {"GL1002", "GL1003", "GL1004", "GL1005"} <= set(codes(found))


def test_gl1003_only_fires_in_threaded_modules():
    import ast

    from galah_tpu.analysis.pipeline_check import check_pipeline_file

    text = ("import queue\n"
            "q = queue.Queue()\n")
    src = SourceFile(path="galah_tpu/ops/x.py", text=text,
                     tree=ast.parse(text))
    assert check_pipeline_file(src) == []  # no lock annotations
    text_threaded = "GUARDED_BY = {}\nLOCK_ORDER = []\n" + text
    src = SourceFile(path="galah_tpu/ops/x.py", text=text_threaded,
                     tree=ast.parse(text_threaded))
    assert codes(check_pipeline_file(src)) == ["GL1003"]


def test_gl1004_accepts_constant_literal_and_helper_emission():
    import ast

    from galah_tpu.analysis.pipeline_check import check_pipeline_file

    head = ('PIPELINE_STAGE = {"streaming": ["iter_x"],\n'
            '    "occupancy_gauge": "workload.pipeline_occupancy"}\n'
            "def iter_x():\n    yield 1\n")
    for emit in ('m.gauge("workload.pipeline_occupancy").set(1)\n',
                 "m.gauge(metrics.PIPELINE_OCCUPANCY_GAUGE).set(1)\n",
                 "metrics.pipeline_occupancy(0.5)\n"):
        text = head + f"def done():\n    {emit}"
        src = SourceFile(path="galah_tpu/ops/x.py", text=text,
                         tree=ast.parse(text))
        assert "GL1004" not in codes(check_pipeline_file(src)), emit
    src = SourceFile(path="galah_tpu/ops/x.py", text=head,
                     tree=ast.parse(head))
    assert codes(check_pipeline_file(src)) == ["GL1004"]


def test_bad_megakernel_fires_gl1006_on_every_sync_idiom():
    from galah_tpu.analysis.pipeline_check import check_pipeline_file

    src = load_fixture("bad_megakernel.py",
                       path="galah_tpu/ops/bad_megakernel.py")
    found = check_pipeline_file(src)
    by_code = {}
    for f in found:
        by_code.setdefault(f.code, []).append(f.line)
    # np.asarray, .item(), jax.device_get, jax.block_until_ready —
    # one finding per sync call inside the annotated fold body
    assert sorted(by_code["GL1006"]) == [13, 14, 15, 16]
    # dangling device_round name "phantom_fold" (anchored at the
    # annotation)
    assert by_code["GL1005"] == [7]
    # the identical calls in the unannotated host_wrapper stay silent
    assert sorted(by_code) == ["GL1005", "GL1006"]
    assert all(f.severity is Severity.WARNING for f in found)
    assert all(f.symbol == "_fold_body"
               for f in found if f.code == "GL1006")


def test_gl1006_device_round_annotation_validation():
    import ast

    from galah_tpu.analysis.pipeline_check import check_pipeline_file

    # non-list device_round value is a GL1005, not a crash
    text = ('PIPELINE_STAGE = {"device_round": "fold"}\n'
            "def fold():\n    return 1\n")
    src = SourceFile(path="galah_tpu/ops/x.py", text=text,
                     tree=ast.parse(text))
    assert codes(check_pipeline_file(src)) == ["GL1005"]
    # a sync-free annotated body is silent
    text = ('PIPELINE_STAGE = {"device_round": ["fold"]}\n'
            "def fold(x):\n    return x + 1\n")
    src = SourceFile(path="galah_tpu/ops/x.py", text=text,
                     tree=ast.parse(text))
    assert check_pipeline_file(src) == []


def test_gl1007_paged_fixture_fires_both_lexical_arms():
    """The registered band-walk function may not accumulate a
    gathered band in the loop nor reference one after it."""
    from galah_tpu.analysis.pipeline_check import (PAGED_MODULES,
                                                   check_pipeline_file)

    path = "galah_tpu/ops/bucketing.py"
    assert "bucketed_threshold_pairs" in PAGED_MODULES[path]
    src = load_fixture("paged_bad.py", path=path)
    found = check_pipeline_file(src)
    assert [(f.code, f.line) for f in found] == \
        [("GL1007", 31), ("GL1007", 34)]
    # in-loop accumulation names the retainer method, the post-loop
    # reference names the surviving binding
    assert ".append() accumulates" in found[0].message
    assert "referenced after" in found[1].message
    assert all(f.symbol == "bucketed_threshold_pairs" for f in found)
    assert all(f.severity is Severity.WARNING for f in found)


def test_gl1007_scope_is_the_paged_registry():
    from galah_tpu.analysis.pipeline_check import check_pipeline_file

    # same source outside the registry: the rule stays dark
    src = load_fixture("paged_bad.py", path="galah_tpu/ops/other.py")
    assert "GL1007" not in codes(check_pipeline_file(src))


def test_gl1007_interprocedural_arm_renders_the_retention_chain():
    """The gather value handed to _fold() -> _keep_band() -> module
    global is invisible lexically; the GalahIR arm reports it with
    the full retention chain down to the storing statement."""
    from galah_tpu.analysis.effects_check import check_effects
    from galah_tpu.analysis.pipeline_check import check_pipeline_file

    path = "galah_tpu/ops/bucketing.py"
    src = load_fixture("paged_bad.py", path=path)
    # the lexical arm must NOT see the helper indirection at line 33
    assert 33 not in [f.line for f in check_pipeline_file(src)]
    found = [f for f in check_effects({src.path: src})
             if f.code == "GL1007"]
    assert [(f.line, f.symbol) for f in found] == [(33, "gather")]
    assert "retained by _fold()" in found[0].message
    assert "_fold -> _keep_band: parameter 'sub' retained at " \
        f"{path}:14" in found[0].message
    assert found[0].severity is Severity.WARNING


def test_gl10xx_family_and_suppression():
    from galah_tpu.analysis.core import family_of

    assert family_of("GL1001") == "GL10xx"
    assert family_of("GL101") == "GL1xx"  # no collision with Pallas
    src = load_fixture("bad_pipeline.py",
                       path="galah_tpu/ops/bad_pipeline.py")
    from galah_tpu.analysis.pipeline_check import check_pipeline_file

    found = check_pipeline_file(src)
    core.apply_suppressions(found, {src.path: src}, {})
    assert all(not f.suppressed for f in found)  # fixture carries none


def test_repo_pipeline_discipline_holds():
    found = [f for f in run_lint(checks=("pipeline",))
             if not f.suppressed]
    assert not found, [(f.path, f.line, f.message) for f in found]


# ---------------------------------------------------------------------------
# GL11xx: GalahIR interprocedural effect auditors (analysis/ir.py +
# effects_check.py)
# ---------------------------------------------------------------------------


def _sf(path, text):
    import ast
    import textwrap

    text = textwrap.dedent(text)
    return SourceFile(path=path, text=text, tree=ast.parse(text))


def test_gl1101_catches_the_gl1006_lexical_blind_spot():
    """The flagship case: a helper-wrapped .item() inside a declared
    device_round body. Lexical GL1006 must stay silent (the blind
    spot), GL1101 must report the body with the full witness chain."""
    from galah_tpu.analysis.effects_check import check_effects
    from galah_tpu.analysis.pipeline_check import check_pipeline_file

    src = load_fixture("bad_megakernel_indirect.py",
                       path="galah_tpu/ops/mk_indirect.py")
    lexical = check_pipeline_file(src)
    assert "GL1006" not in codes(lexical), \
        "the fixture must be invisible to the lexical rule"
    assert lexical == []  # annotation is well-formed too

    found = check_effects({src.path: src})
    assert [(f.code, f.line, f.symbol) for f in found] == \
        [("GL1101", 22, "_fold_round")]
    # the message carries the exact provenance chain down to the sink
    assert "_fold_round -> _pull_scalar" in found[0].message
    assert "galah_tpu/ops/mk_indirect.py:18" in found[0].message
    assert found[0].severity is Severity.WARNING


def test_bad_effects_durable_fixture_fires_1102_1104_1105():
    from galah_tpu.analysis.effects_check import check_effects
    from galah_tpu.analysis.fs_check import DURABLE_MODULES, \
        check_fs_file

    path = "galah_tpu/obs/ledger.py"
    assert path in DURABLE_MODULES
    src = load_fixture("bad_effects_durable.py", path=path)
    # lexical GL806 sees the direct open() in _dump...
    assert "GL806" in codes(check_fs_file(src))
    found = check_effects({src.path: src})
    got = sorted((f.code, f.line, f.symbol) for f in found)
    # ...but only GL1102 sees append_record reaching it transitively
    assert got == [("GL1102", 26, "append_record"),
                   ("GL1104", 30, "rotate"),
                   ("GL1105", 42, "_flush_cb")]
    by_code = {f.code: f for f in found}
    assert "append_record -> _dump" in by_code["GL1102"].message
    assert f"{path}:21" in by_code["GL1102"].message
    assert "io/atomic.py" in by_code["GL1102"].message
    assert "try/finally" in by_code["GL1104"].message
    assert "blocking_io" in by_code["GL1105"].message
    assert f"{path}:35" in by_code["GL1105"].message  # callee def line


def test_bad_effects_stream_fixture_fires_gl1103():
    from galah_tpu.analysis.effects_check import check_effects
    from galah_tpu.analysis.pipeline_check import check_pipeline_file

    src = load_fixture("bad_effects_stream.py",
                       path="galah_tpu/fleet/stage.py")
    assert "GL1001" not in codes(check_pipeline_file(src))
    found = check_effects({src.path: src})
    assert [(f.code, f.line, f.symbol) for f in found] == \
        [("GL1103", 17, "iter_windows")]
    assert "_collect()" in found[0].message
    assert "'items'" in found[0].message


def test_clean_effects_fixture_is_silent():
    from galah_tpu.analysis.effects_check import check_effects

    # durable AND pipeline-scope AND annotated: every rule is armed,
    # every idiom in the fixture is the sanctioned form
    src = load_fixture("clean_effects.py",
                       path="galah_tpu/index/store.py")
    assert check_effects({src.path: src}) == []


def test_gl1103_scope_excludes_non_pipeline_modules():
    from galah_tpu.analysis.effects_check import check_effects

    src = load_fixture("bad_effects_stream.py",
                       path="galah_tpu/obs/stage.py")
    assert "GL1103" not in codes(check_effects({src.path: src}))


def test_gl1104_return_passthrough_and_gl1105_adoption_are_exempt():
    from galah_tpu.analysis.effects_check import check_effects

    src = _sf("galah_tpu/fleet/x.py", '''
        GUARDED_BY = {"s": "LOCK"}

        class Guard:
            def acquire(self):
                return True

            def __enter__(self):
                return self.acquire()

        def adopting_cb(token, p):
            import time
            with timing.adopt(token):
                time.sleep(p)

        def drive(pool, token, items):
            for it in items:
                pool.submit(adopting_cb, token, it)
    ''')
    assert check_effects({src.path: src}) == []


# -- IR name resolution and effect propagation units ------------------


def _program(*mods):
    from galah_tpu.analysis import ir

    sources = {m.path: m for m in mods}
    return ir.build_program_ir(sources)


_SINK = _sf("galah_tpu/pkg/sink.py", '''
    def pull(v):
        return v.item()
''')


def test_ir_resolves_plain_module_import():
    prog = _program(_SINK, _sf("galah_tpu/pkg/user.py", '''
        import galah_tpu.pkg.sink
        def f(v):
            return galah_tpu.pkg.sink.pull(v)
    '''))
    effects = prog.effects_of(("galah_tpu/pkg/user.py", "f"))
    assert "host_sync" in effects


def test_ir_resolves_import_as_alias():
    prog = _program(_SINK, _sf("galah_tpu/pkg/user.py", '''
        import galah_tpu.pkg.sink as sk
        def f(v):
            return sk.pull(v)
    '''))
    assert "host_sync" in prog.effects_of(
        ("galah_tpu/pkg/user.py", "f"))


def test_ir_resolves_from_import_as():
    prog = _program(_SINK, _sf("galah_tpu/pkg/user.py", '''
        from galah_tpu.pkg.sink import pull as grab
        def f(v):
            return grab(v)
    '''))
    assert "host_sync" in prog.effects_of(
        ("galah_tpu/pkg/user.py", "f"))


def test_ir_resolves_module_level_function_alias():
    prog = _program(_SINK, _sf("galah_tpu/pkg/user.py", '''
        from galah_tpu.pkg.sink import pull
        fetch = pull
        def f(v):
            return fetch(v)
    '''))
    assert "host_sync" in prog.effects_of(
        ("galah_tpu/pkg/user.py", "f"))


def test_ir_unwraps_decorators():
    """A @profiled/@jit wrapper never hides the body's effects from
    callers, and a jit decoration IS a device_dispatch effect."""
    prog = _program(_sf("galah_tpu/pkg/deco.py", '''
        import functools
        import jax

        def profiled(fn):
            return fn

        @profiled
        def sync_inner(v):
            return v.item()

        @functools.partial(jax.jit, static_argnums=0)
        def fold(n, v):
            return v + n

        def caller(v):
            return sync_inner(v)
    '''))
    assert "host_sync" in prog.effects_of(
        ("galah_tpu/pkg/deco.py", "caller"))
    assert "device_dispatch" in prog.effects_of(
        ("galah_tpu/pkg/deco.py", "fold"))


def test_ir_partial_and_callback_refs_propagate_submit_does_not():
    from galah_tpu.analysis import ir

    prog = _program(_sf("galah_tpu/pkg/cb.py", '''
        import functools

        def sink(v):
            return v.item()

        def via_partial(run, v):
            return run(functools.partial(sink, v))

        def via_while_loop(lax, cond, v):
            return lax.while_loop(cond, sink, v)

        def via_submit(pool, v):
            return pool.submit(sink, v)
    '''))
    p = "galah_tpu/pkg/cb.py"
    # a partial target and a function reference run on this thread
    assert "host_sync" in prog.effects_of((p, "via_partial"))
    assert "host_sync" in prog.effects_of((p, "via_while_loop"))
    # a pool-submitted callee runs elsewhere: never propagated
    assert "host_sync" not in prog.effects_of((p, "via_submit"))
    fn = prog.functions[(p, "via_submit")]
    assert [e.kind for e in fn.calls if e.name == "sink"] == ["submit"]


def test_ir_call_graph_cycle_reaches_fixpoint():
    prog = _program(_sf("galah_tpu/pkg/cyc.py", '''
        def a(v, n):
            if n:
                return b(v, n - 1)
            return 0

        def b(v, n):
            v.item()
            return a(v, n)
    '''))
    p = "galah_tpu/pkg/cyc.py"
    assert "host_sync" in prog.effects_of((p, "a"))
    assert "host_sync" in prog.effects_of((p, "b"))
    # the witness chain is cycle-safe and ends at the direct sink
    chain = prog.witness_chain((p, "a"), "host_sync")
    assert chain[-1][1].direct


def test_ir_nested_defs_and_methods_resolve():
    prog = _program(_sf("galah_tpu/pkg/nest.py", '''
        class Folder:
            def pull(self, v):
                return v.item()

            def round(self, v):
                return self.pull(v)

        def outer(v):
            def inner(x):
                return x.item()
            return inner(v)
    '''))
    p = "galah_tpu/pkg/nest.py"
    assert "host_sync" in prog.effects_of((p, "Folder.round"))
    assert "host_sync" in prog.effects_of((p, "outer"))


def test_ir_fs_write_stops_at_the_sanctioned_writer():
    from galah_tpu.analysis import ir

    atomic_mod = _sf(ir.SANCTIONED_WRITER, '''
        def write_json(path, obj):
            with open(path, "w") as fh:
                fh.write(obj)
    ''')
    user = _sf("galah_tpu/obs/ledger.py", '''
        from galah_tpu.io.atomic import write_json
        def save(path, rec):
            write_json(path, rec)
    ''')
    prog = _program(atomic_mod, user)
    # atomic itself carries the effect; its callers do not inherit it
    assert "fs_write" in prog.effects_of(
        (ir.SANCTIONED_WRITER, "write_json"))
    assert "fs_write" not in prog.effects_of(
        ("galah_tpu/obs/ledger.py", "save"))


def test_ir_cache_round_trip_warm_hit_and_corruption_repair(tmp_path):
    from galah_tpu.analysis import ir

    src = _sf("galah_tpu/pkg/cached.py", '''
        def f(v):
            return v.item()
    ''')
    cache_dir = str(tmp_path / "irc")
    cold = ir.IRCache(cache_dir)
    ir.build_program_ir({src.path: src}, cache=cold)
    assert (cold.hits, cold.misses) == (0, 1)

    warm = ir.IRCache(cache_dir)
    prog = ir.build_program_ir({src.path: src}, cache=warm)
    assert (warm.hits, warm.misses) == (1, 0)
    assert "host_sync" in prog.effects_of(
        ("galah_tpu/pkg/cached.py", "f"))

    # corrupt the entry: next load is a miss-and-repair, never a crash
    entry = pathlib.Path(warm._entry_path(src.path, src.content_hash()))
    entry.write_text("{not json")
    repaired = ir.IRCache(cache_dir)
    prog = ir.build_program_ir({src.path: src}, cache=repaired)
    assert (repaired.hits, repaired.misses) == (0, 1)
    assert "host_sync" in prog.effects_of(
        ("galah_tpu/pkg/cached.py", "f"))
    assert json.loads(entry.read_text())["ir_version"] == ir.IR_VERSION


def test_ir_cache_disabled_is_a_noop(tmp_path):
    from galah_tpu.analysis import ir

    cache = ir.IRCache(None)
    assert not cache.enabled
    src = _sf("galah_tpu/pkg/nocache.py", "def f(v):\n    return v\n")
    ir.build_program_ir({src.path: src}, cache=cache)
    assert (cache.hits, cache.misses) == (0, 0)


def test_shapes_verdict_cache_round_trips(tmp_path):
    """The GL5xx warm path must replay the exact cold findings."""
    from galah_tpu.analysis import ir
    from galah_tpu.analysis.shapes import _verdict_digest

    digest = _verdict_digest()
    cache = ir.IRCache(str(tmp_path))
    assert cache.load_verdict("shapes", digest) is None
    payload = {"findings": [["GL501", "ERROR", "p.py", 3, "msg", "op"]]}
    cache.store_verdict("shapes", digest, payload)
    hit = ir.IRCache(str(tmp_path)).load_verdict("shapes", digest)
    assert hit["findings"] == payload["findings"]
    # a different digest (any op-file edit) misses
    assert cache.load_verdict("shapes", "0" * 64) is None


def test_repo_effects_clean():
    """Tier-1 gate: the package's own call graph carries no GL11xx
    violations — the interprocedural contracts hold transitively."""
    found = [f for f in run_lint(checks=("effects",))
             if not f.suppressed]
    assert not found, [(f.path, f.line, f.code, f.message)
                       for f in found]


def test_effects_family_is_registered():
    assert "effects" in CHECK_NAMES
    src = load_fixture("bad_effects_durable.py",
                       path="galah_tpu/obs/ledger.py")
    found = run_checks({src.path: src}, checks=("effects",))
    assert {"GL1102", "GL1104", "GL1105"} <= set(codes(found))
    assert core.family_of("GL1101") == "GL11xx"


def test_run_checks_timings_cover_requested_families():
    src = load_fixture("clean_case.py", path="galah_tpu/ops/clean.py")
    timings = {}
    run_checks({src.path: src}, checks=("pipeline", "effects"),
               timings=timings)
    assert set(timings) == {"pipeline", "effects"}
    assert all(t >= 0 for t in timings.values())
    summary = core.lint_summary([], timings=timings)
    assert set(summary["timings_s"]) == {"pipeline", "effects"}


# ---------------------------------------------------------------------------
# SARIF 2.1.0 output (--sarif)
# ---------------------------------------------------------------------------

# Structural subset of the SARIF 2.1.0 schema covering everything we
# emit (the full OASIS schema is not vendored; this pins the invariants
# CI annotators rely on: version, driver, rules, results with physical
# locations and fingerprints).
_SARIF_SUBSET_SCHEMA = {
    "type": "object",
    "required": ["version", "$schema", "runs"],
    "properties": {
        "version": {"const": "2.1.0"},
        "runs": {
            "type": "array", "minItems": 1, "maxItems": 1,
            "items": {
                "type": "object",
                "required": ["tool", "results"],
                "properties": {
                    "tool": {
                        "type": "object",
                        "required": ["driver"],
                        "properties": {"driver": {
                            "type": "object",
                            "required": ["name", "rules"],
                            "properties": {
                                "name": {"type": "string"},
                                "rules": {
                                    "type": "array",
                                    "items": {
                                        "type": "object",
                                        "required": ["id"],
                                    },
                                },
                            },
                        }},
                    },
                    "results": {
                        "type": "array",
                        "items": {
                            "type": "object",
                            "required": ["ruleId", "level", "message",
                                         "locations"],
                            "properties": {
                                "level": {"enum": ["error", "warning",
                                                   "note"]},
                                "message": {
                                    "type": "object",
                                    "required": ["text"],
                                },
                                "locations": {
                                    "type": "array", "minItems": 1,
                                    "items": {"type": "object"},
                                },
                            },
                        },
                    },
                },
            },
        },
    },
}


def test_sarif_rendering_is_schema_valid_and_complete():
    import jsonschema

    from galah_tpu.analysis.effects_check import check_effects

    src = load_fixture("bad_effects_durable.py",
                       path="galah_tpu/obs/ledger.py")
    found = check_effects({src.path: src})
    assert found
    found[0].suppressed = True
    found[0].suppression = "inline"
    log = core.render_sarif(found, tool_version="0.1.0")
    jsonschema.validate(log, _SARIF_SUBSET_SCHEMA)

    run = log["runs"][0]
    assert run["tool"]["driver"]["name"] == "galah-tpu lint"
    rule_ids = {r["id"] for r in run["tool"]["driver"]["rules"]}
    assert {f.code for f in found} == rule_ids
    assert len(run["results"]) == len(found)
    first = run["results"][0]
    loc = first["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"] == "galah_tpu/obs/ledger.py"
    assert loc["region"]["startLine"] >= 1
    assert "galahLintFingerprint/v1" in first["partialFingerprints"]
    # the suppressed finding is carried, marked, not dropped
    suppressed = [r for r in run["results"] if r.get("suppressions")]
    assert len(suppressed) == 1


def test_sarif_cli_writes_valid_log(tmp_path):
    import jsonschema

    sarif_path = tmp_path / "lint.sarif"
    proc = subprocess.run(
        [sys.executable, "-m", "galah_tpu.analysis",
         "--check", "suppressions", "--sarif", str(sarif_path)],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    log = json.loads(sarif_path.read_text())
    jsonschema.validate(log, _SARIF_SUBSET_SCHEMA)
    assert log["version"] == "2.1.0"
    assert log["runs"][0]["tool"]["driver"]["version"] == "0.1.0"


def test_lint_cli_warm_ir_cache_hits(tmp_path):
    """End-to-end cold-vs-warm: the second run must hit the per-file
    IR cache for every scanned file (same tree, same content hashes).
    The wall-clock acceptance (warm <= 60% of cold) is exercised by
    scripts/lint_gate.sh --self-check, which times real runs."""
    from galah_tpu.analysis import ir, load_sources, repo_root

    cache_dir = str(tmp_path / "irc")
    sources = load_sources(repo_root())
    cold = ir.IRCache(cache_dir)
    ir.build_program_ir(sources, cache=cold)
    assert cold.misses == len(sources) and cold.hits == 0
    warm = ir.IRCache(cache_dir)
    ir.build_program_ir(sources, cache=warm)
    assert warm.hits == len(sources) and warm.misses == 0
