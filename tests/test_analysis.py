"""galah-tpu lint: every checker demonstrated on seeded-violation
fixtures, the clean-fixture negative, suppression/baseline mechanics,
and the tier-1 gate that the repo itself lints clean."""

import json
import pathlib
import subprocess
import sys

import pytest

from galah_tpu.analysis import (DEFAULT_BASELINE, CHECK_NAMES,
                                load_sources, repo_root, run_checks,
                                run_lint)
from galah_tpu.analysis import core
from galah_tpu.analysis.core import Severity, SourceFile
from galah_tpu.analysis.flags_check import check_flag_references
from galah_tpu.analysis.markers_check import (check_markers_file,
                                              is_hardware_module)
from galah_tpu.analysis.pallas_check import check_pallas_file
from galah_tpu.analysis.runtime_checks import check_runtime_file

FIXTURES = pathlib.Path(__file__).parent / "data" / "lint_fixtures"


def load_fixture(name: str, path: str = None) -> SourceFile:
    src = SourceFile.load(str(FIXTURES / name))
    if path is not None:
        src.path = path
    return src


def codes(findings):
    return sorted({f.code for f in findings if not f.suppressed})


# ---------------------------------------------------------------------------
# GL1xx: Pallas contract checker
# ---------------------------------------------------------------------------


def test_bad_blockspec_fires_lane_and_sublane():
    found = check_pallas_file(load_fixture("bad_blockspec.py"))
    assert "GL103" in codes(found)
    assert "GL104" in codes(found)


def test_u64_boundary_and_kernel_body_fire():
    found = check_pallas_file(load_fixture("bad_u64.py"))
    gl106 = [f for f in found if f.code == "GL106"]
    # input boundary, out_shape, and the kernel-body reference
    assert len(gl106) >= 3


def test_vmem_budget_overflow_fires():
    found = check_pallas_file(load_fixture("bad_vmem.py"))
    assert "GL105" in codes(found)


def test_missing_contract_fires():
    found = check_pallas_file(load_fixture("missing_contract.py"))
    assert codes(found) == ["GL101"]


def test_stale_contract_entry_fires():
    src = load_fixture("missing_contract.py")
    contract = {"no_such_function": {"bindings": {}}}
    found = check_pallas_file(src, contract=contract)
    assert "GL101" in codes(found)  # the real site is still uncovered
    assert "GL102" in codes(found)  # and the entry is stale


# ---------------------------------------------------------------------------
# GL2xx/GL3xx: host-sync and recompile churn
# ---------------------------------------------------------------------------


def test_jit_fixture_fires_every_runtime_code():
    found = check_runtime_file(load_fixture("bad_jit.py"))
    got = codes(found)
    assert {"GL201", "GL202", "GL203", "GL301", "GL302"} <= set(got)


def test_shape_access_is_exempt():
    found = check_runtime_file(load_fixture("bad_jit.py"))
    assert not [f for f in found if f.symbol == "clean_shapes"]


# ---------------------------------------------------------------------------
# GL4xx: flag registry
# ---------------------------------------------------------------------------


def test_unregistered_and_conflicting_default_fire():
    found = check_flag_references([load_fixture("bad_flags.py")])
    by_code = {f.code: f for f in found if f.path.endswith("bad_flags.py")}
    assert "GL401" in by_code and "GALAH_TPU_CAHCE" in by_code["GL401"].message
    assert "GL402" in by_code
    assert "GALAH_TPU_PAIRLIST_BLOCK" in by_code["GL402"].message
    # the matching-default read must NOT fire
    assert not [f for f in found
                if f.code == "GL402"
                and "GALAH_TPU_SPARSE_MIN_N" in f.message]


def test_registry_is_documented_and_rendered():
    """GL403/404/405 health over the real repo tree: every registered
    flag referenced (or externally owned), documented, and present in
    the auto-rendered manpage ENVIRONMENT section."""
    sources = load_sources(repo_root())
    found = check_flag_references(list(sources.values()))
    assert not [f for f in found if f.code in ("GL403", "GL404", "GL405")], \
        [f.message for f in found]


def test_manpage_renders_every_flag():
    from galah_tpu.config import FLAGS
    from galah_tpu.manpage import render_environment_section

    section = render_environment_section()
    for name in FLAGS:
        assert name in section


# ---------------------------------------------------------------------------
# GL6xx: hardware-test marker audit
# ---------------------------------------------------------------------------


def test_unmarked_hardware_tests_fire():
    src = load_fixture("hw_unmarked_case.py",
                       path="tests/test_tpu_hw_seeded.py")
    assert is_hardware_module(src)
    found = check_markers_file(src)
    flagged = {f.symbol for f in found}
    assert flagged == {"test_kernel_on_hardware", "test_kernel_cases"}
    # the quarantined-import heuristic works without the filename too
    src2 = load_fixture("hw_unmarked_case.py",
                        path="tests/test_quarantined_seeded.py")
    assert is_hardware_module(src2)


def test_module_level_pytestmark_satisfies_audit():
    src = load_fixture("hw_unmarked_case.py",
                       path="tests/test_tpu_hw_seeded.py")
    src.text = "pytestmark = pytest.mark.slow\n" + src.text
    import ast

    src.tree = ast.parse(src.text)
    assert check_markers_file(src, force_hardware=True) == []


def test_repo_hardware_tests_are_marked():
    sources = load_sources(repo_root())
    found = []
    for src in sources.values():
        found.extend(check_markers_file(src))
    assert not found, [f.message for f in found]


# ---------------------------------------------------------------------------
# GL7xx: observability discipline (ad-hoc timing outside obs/)
# ---------------------------------------------------------------------------


def test_bad_timing_fixture_fires_gl701_and_gl702():
    from galah_tpu.analysis.obs_check import check_obs_file

    src = load_fixture("bad_timing.py",
                       path="galah_tpu/ops/bad_timing.py")
    found = check_obs_file(src)
    gl701 = sorted(f.line for f in found if f.code == "GL701")
    gl702 = sorted(f.line for f in found if f.code == "GL702")
    # direct calls, aliased-module call, from-import alias, and the
    # (later suppressed) wall-clock stamp; both log-literal shapes
    assert gl701 == [11, 13, 19, 21, 31]
    assert gl702 == [22, 23]
    assert all(f.severity is Severity.WARNING for f in found)


def test_bad_timing_inline_suppression_applies():
    from galah_tpu.analysis.obs_check import check_obs_file

    src = load_fixture("bad_timing.py",
                       path="galah_tpu/ops/bad_timing.py")
    found = check_obs_file(src)
    core.apply_suppressions(found, {src.path: src}, {})
    active = sorted(f.line for f in found if not f.suppressed)
    assert active == [11, 13, 19, 21, 22, 23]  # line 31 is justified


def test_obs_check_exempts_utils_obs_analysis_and_nonpackage():
    from galah_tpu.analysis.obs_check import check_obs_file, in_scope

    for path in ("galah_tpu/utils/timing.py",
                 "galah_tpu/obs/metrics.py",
                 "galah_tpu/analysis/obs_check.py",
                 "scripts/smoke.py",
                 "tests/test_obs.py",
                 "bench.py"):
        assert not in_scope(path)
        assert check_obs_file(load_fixture("bad_timing.py",
                                           path=path)) == []
    assert in_scope("galah_tpu/ops/bad_timing.py")


def test_repo_has_no_unsuppressed_adhoc_timing():
    found = [f for f in run_lint(checks=("obs",))
             if not f.suppressed]
    assert not found, [(f.path, f.line, f.message) for f in found]


# ---------------------------------------------------------------------------
# Clean fixture, suppressions, baseline
# ---------------------------------------------------------------------------


def test_clean_fixture_has_zero_findings():
    src = load_fixture("clean_case.py")
    found = (check_pallas_file(src) + check_runtime_file(src)
             + [f for f in check_flag_references([src])
                if f.path == src.path]
             + check_markers_file(src))
    assert found == []


def test_inline_suppression_and_wildcard():
    import ast

    text = ("import os\n"
            "a = os.environ.get('GALAH_BOGUS')  "
            "# galah-lint: ignore[GL401]\n"
            "# galah-lint: ignore[*]\n"
            "b = os.environ.get('GALAH_BOGUS2')\n")
    src = SourceFile(path="x.py", text=text, tree=ast.parse(text))
    src._index_suppressions()
    found = [f for f in check_flag_references([src]) if f.path == "x.py"]
    core.apply_suppressions(found, {"x.py": src}, {})
    assert all(f.suppressed and f.suppression == "inline" for f in found)


def test_baseline_suppresses_by_fingerprint(tmp_path):
    src = load_fixture("bad_flags.py")
    found = [f for f in check_flag_references([src])
             if f.path.endswith("bad_flags.py")]
    assert found
    bl = tmp_path / "baseline.json"
    core.write_baseline(str(bl), found)
    baseline = core.load_baseline(str(bl))
    fresh = [f for f in check_flag_references([src])
             if f.path.endswith("bad_flags.py")]
    core.apply_suppressions(fresh, {}, baseline)
    assert all(f.suppressed and f.suppression == "baseline"
               for f in fresh)


# ---------------------------------------------------------------------------
# GL5xx: abstract-eval shape contracts
# ---------------------------------------------------------------------------


def test_shape_contracts_match_snapshot():
    from galah_tpu.analysis.shapes import check_shape_contracts

    found = check_shape_contracts()
    assert found == [], [f.message for f in found]


def test_shape_snapshot_drift_fires(monkeypatch, tmp_path):
    from galah_tpu.analysis import shapes

    snap = shapes.load_snapshot()
    assert snap, "committed snapshot must exist"
    # corrupt one entry and drop one op -> GL501 + GL502
    drifted = {op: dict(cases) for op, cases in snap.items()}
    first_op = sorted(drifted)[0]
    first_case = sorted(drifted[first_op])[0]
    drifted[first_op][first_case] = "float64[3,3]"
    drifted["ghost.op"] = {"case": "int32[1]"}
    p = tmp_path / "shape_contracts.json"
    p.write_text(json.dumps({"version": 1, "contracts": drifted}))
    monkeypatch.setattr(shapes, "SNAPSHOT_PATH", str(p))
    found = shapes.check_shape_contracts()
    assert "GL501" in codes(found)
    assert any(f.code == "GL502" and "ghost.op" in f.message
               for f in found)


# ---------------------------------------------------------------------------
# The tier-1 gate: the repo itself lints clean
# ---------------------------------------------------------------------------


def test_repo_lints_clean():
    """Zero unsuppressed findings at WARNING or above across every
    checker family — the same gate `galah-tpu lint` enforces."""
    findings = run_lint()
    bad = core.failing(findings, Severity.WARNING)
    assert bad == [], "\n" + core.render_human(bad)


def test_lint_cli_json_contract():
    """`galah-tpu lint --json` (via the module entry point, cheap
    checkers only) emits the machine-readable schema the validation
    script consumes."""
    proc = subprocess.run(
        [sys.executable, "-m", "galah_tpu.analysis", "--json",
         "--check", "pallas", "--check", "runtime",
         "--check", "markers"],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    report = json.loads(proc.stdout)
    assert report["version"] == 1
    assert set(report["summary"]) == {"errors", "warnings", "notes",
                                      "suppressed"}
    assert report["summary"]["errors"] == 0


def test_baseline_file_is_committed_and_empty():
    baseline = core.load_baseline(DEFAULT_BASELINE)
    assert baseline == {}, "repo lints clean; baseline must stay empty"
    assert pathlib.Path(DEFAULT_BASELINE).is_file()


def test_fixture_dir_not_scanned():
    sources = load_sources(repo_root())
    assert not [p for p in sources if "lint_fixtures" in p]
