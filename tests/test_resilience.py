"""Resilience layer units (galah_tpu/resilience/).

Retry policy, deterministic fault injector, and the dispatch
supervisor's retry -> validate -> demote machinery — all exercised on
CPU with seeded faults, no hardware misbehavior required.
"""

import threading
import time

import pytest

from galah_tpu.resilience import dispatch as rdispatch
from galah_tpu.resilience import faults
from galah_tpu.resilience.dispatch import (
    DispatchSupervisor,
    expect_ani_values,
    expect_len,
)
from galah_tpu.resilience.faults import FaultInjector, FaultSpec, parse_spec
from galah_tpu.resilience.policy import (
    DeadlineExceeded,
    DeviceLostError,
    GarbageResultError,
    RetryPolicy,
    TransientDispatchError,
    call_with_retry,
    is_retryable,
    run_with_deadline,
)
from galah_tpu.utils import timing

pytestmark = pytest.mark.fault_injection


@pytest.fixture(autouse=True)
def _clean_state():
    faults.reset()
    rdispatch.reset(RetryPolicy(max_attempts=3, base_delay=0.0,
                                jitter=0.0))
    timing.reset()
    yield
    faults.reset()
    rdispatch.reset()
    timing.reset()


# -- RetryPolicy ----------------------------------------------------


def test_delay_schedule_exponential_capped():
    p = RetryPolicy(max_attempts=5, base_delay=0.1, max_delay=0.5,
                    jitter=0.0)
    assert p.delay(0) == pytest.approx(0.1)
    assert p.delay(1) == pytest.approx(0.2)
    assert p.delay(2) == pytest.approx(0.4)
    assert p.delay(3) == pytest.approx(0.5)  # capped
    assert p.delay(10) == pytest.approx(0.5)


def test_seeded_jitter_is_deterministic_per_site_attempt():
    a = RetryPolicy(seed=7, jitter=0.5)
    b = RetryPolicy(seed=7, jitter=0.5)
    assert a.delay(1, "dispatch.ani") == b.delay(1, "dispatch.ani")
    # different site or attempt decorrelates, same bounds hold
    d = a.delay(1, "dispatch.ani")
    lo, hi = 0.05, 0.15  # base 0.05 * 2^1 = 0.1, jitter 0.5
    assert lo <= d <= hi


def test_policy_validation():
    with pytest.raises(ValueError):
        RetryPolicy(max_attempts=0)
    with pytest.raises(ValueError):
        RetryPolicy(jitter=1.5)


def test_from_env_overrides(monkeypatch):
    monkeypatch.setenv("GALAH_RETRY_MAX_ATTEMPTS", "7")
    monkeypatch.setenv("GALAH_RETRY_BASE_DELAY", "0.25")
    monkeypatch.setenv("GALAH_RETRY_SEED", "3")
    p = RetryPolicy.from_env()
    assert p.max_attempts == 7
    assert p.base_delay == 0.25
    assert p.seed == 3
    # explicit keyword wins over env
    assert RetryPolicy.from_env(max_attempts=2).max_attempts == 2


def test_is_retryable_taxonomy():
    assert is_retryable(TransientDispatchError("x"))
    assert is_retryable(DeviceLostError("x"))
    assert is_retryable(GarbageResultError("x"))
    assert is_retryable(OSError("flake"))
    assert is_retryable(DeadlineExceeded("slow"))
    assert not is_retryable(FileNotFoundError("gone"))
    assert not is_retryable(ValueError("deterministic"))

    class XlaRuntimeError(Exception):  # matched by NAME, not import
        pass

    assert is_retryable(XlaRuntimeError("jax runtime"))


# -- call_with_retry ------------------------------------------------


def test_retry_recovers_after_transients():
    calls = {"n": 0}

    def fn():
        calls["n"] += 1
        if calls["n"] < 3:
            raise TransientDispatchError("flaky")
        return "ok"

    pol = RetryPolicy(max_attempts=3, base_delay=0.0, jitter=0.0)
    assert call_with_retry(fn, pol, sleep=lambda _d: None) == "ok"
    assert calls["n"] == 3


def test_retry_exhaustion_reraises_last():
    pol = RetryPolicy(max_attempts=2, base_delay=0.0, jitter=0.0)
    calls = {"n": 0}

    def fn():
        calls["n"] += 1
        raise TransientDispatchError(f"attempt {calls['n']}")

    with pytest.raises(TransientDispatchError, match="attempt 2"):
        call_with_retry(fn, pol, sleep=lambda _d: None)


def test_non_retryable_propagates_immediately():
    calls = {"n": 0}

    def fn():
        calls["n"] += 1
        raise ValueError("deterministic bug")

    pol = RetryPolicy(max_attempts=5, base_delay=0.0)
    with pytest.raises(ValueError):
        call_with_retry(fn, pol, sleep=lambda _d: None)
    assert calls["n"] == 1


def test_total_budget_stops_retry_loop():
    pol = RetryPolicy(max_attempts=10, base_delay=10.0, jitter=0.0,
                      total_budget=0.5)
    calls = {"n": 0}

    def fn():
        calls["n"] += 1
        raise TransientDispatchError("flaky")

    with pytest.raises(TransientDispatchError):
        call_with_retry(fn, pol, sleep=lambda _d: None)
    # first delay (10 s) already exceeds the 0.5 s budget: one attempt
    assert calls["n"] == 1


def test_on_retry_fires_per_backoff():
    seen = []
    calls = {"n": 0}

    def fn():
        calls["n"] += 1
        if calls["n"] < 3:
            raise TransientDispatchError("flaky")
        return 1

    pol = RetryPolicy(max_attempts=5, base_delay=0.0, jitter=0.0)
    call_with_retry(fn, pol, on_retry=lambda a, e: seen.append(a),
                    sleep=lambda _d: None)
    assert seen == [0, 1]


def test_attempt_deadline_abandons_hang():
    t0 = time.monotonic()
    with pytest.raises(DeadlineExceeded):
        run_with_deadline(lambda: time.sleep(5.0), deadline=0.05)
    assert time.monotonic() - t0 < 2.0


def test_deadline_passthrough_value_and_error():
    assert run_with_deadline(lambda: 42, deadline=1.0) == 42
    with pytest.raises(KeyError):
        run_with_deadline(lambda: {}["x"], deadline=1.0)


# -- fault injector -------------------------------------------------


def test_parse_spec_grammar():
    specs = parse_spec(
        "site=dispatch.ani;kind=raise;prob=0.5;seed=7;max=2"
        "|site=collective.;kind=hang;hang=1.5")
    assert len(specs) == 2
    assert specs[0] == FaultSpec(site="dispatch.ani", kind="raise",
                                 prob=0.5, seed=7, max_faults=2)
    assert specs[1].kind == "hang"
    assert specs[1].hang_seconds == 1.5


def test_parse_spec_rejects_garbage():
    with pytest.raises(ValueError):
        parse_spec("site=x;frequency=9")
    with pytest.raises(ValueError):
        parse_spec("kind=explode")
    with pytest.raises(ValueError):
        FaultSpec(prob=1.5)


def test_injector_deterministic_and_capped():
    def fire_log(inj, n=20):
        log = []
        for _ in range(n):
            try:
                inj.before_dispatch("dispatch.ani")
                log.append(0)
            except TransientDispatchError:
                log.append(1)
        return log

    spec = FaultSpec(site="dispatch.ani", kind="raise", prob=0.4,
                     seed=11)
    a = fire_log(FaultInjector([spec]))
    b = fire_log(FaultInjector([spec]))
    assert a == b and sum(a) > 0
    capped = FaultInjector([FaultSpec(site="dispatch.ani", prob=1.0,
                                      max_faults=3)])
    assert sum(fire_log(capped)) == 3
    assert capped.fired() == 3


def test_injector_site_prefix_match():
    inj = FaultInjector([FaultSpec(site="dispatch.", prob=1.0)])
    with pytest.raises(TransientDispatchError):
        inj.before_dispatch("dispatch.sketch-minhash")
    inj.before_dispatch("collective.host-rows")  # no fault


def test_injector_kinds():
    with pytest.raises(DeviceLostError):
        FaultInjector([FaultSpec(kind="device-lost")]).before_dispatch("x")
    slept = []
    inj = FaultInjector([FaultSpec(kind="hang", hang_seconds=7.0)],
                        sleep=slept.append)
    inj.before_dispatch("x")
    assert slept == [7.0]
    garb = FaultInjector([FaultSpec(kind="garbage")])
    garb.before_dispatch("x")  # garbage never raises pre-dispatch
    assert garb.corrupt("x", [1, 2, 3]) == [1, 2]


def test_env_discovery(monkeypatch):
    monkeypatch.setenv("GALAH_FI", "site=dispatch.ani;kind=raise")
    faults.reset()
    inj = faults.get_injector()
    assert inj is not None
    with pytest.raises(TransientDispatchError):
        inj.before_dispatch("dispatch.ani")
    faults.reset()
    monkeypatch.delenv("GALAH_FI")
    assert faults.get_injector() is None


# -- dispatch supervisor --------------------------------------------


def test_supervisor_transient_fault_retried_to_success():
    faults.install(FaultInjector(
        [FaultSpec(site="s", kind="raise", prob=1.0, max_faults=2)]))
    sup = DispatchSupervisor(RetryPolicy(max_attempts=3, base_delay=0.0,
                                         jitter=0.0))
    out = sup.run("s", lambda: [0.5], validate=expect_ani_values(1))
    assert out == [0.5]
    assert not sup.demotions()
    assert timing.GLOBAL.counters().get("retries[s]") == 2


def test_supervisor_persistent_fault_demotes_to_fallback():
    faults.install(FaultInjector(
        [FaultSpec(site="s", kind="raise", prob=1.0)]))
    sup = DispatchSupervisor(RetryPolicy(max_attempts=2, base_delay=0.0,
                                         jitter=0.0))
    primary_calls = {"n": 0}

    def primary():
        primary_calls["n"] += 1
        return [0.5]

    out = sup.run("s", primary, fallback=lambda: [0.25])
    assert out == [0.25]
    dems = sup.demotions()
    assert [d.site for d in dems] == ["s"]
    assert "TransientDispatchError" in dems[0].reason
    assert timing.GLOBAL.counters().get("demoted[s]") == 1
    # demoted site routes straight to the fallback; the primary (and
    # the injector) are never consulted again
    out2 = sup.run("s", primary, fallback=lambda: [0.75])
    assert out2 == [0.75]
    assert primary_calls["n"] == 0


def test_supervisor_no_fallback_reraises():
    faults.install(FaultInjector([FaultSpec(site="s", prob=1.0)]))
    sup = DispatchSupervisor(RetryPolicy(max_attempts=2, base_delay=0.0,
                                         jitter=0.0))
    with pytest.raises(TransientDispatchError):
        sup.run("s", lambda: 1)
    assert not sup.demotions()  # nothing to demote TO


def test_supervisor_garbage_result_caught_by_validator():
    faults.install(FaultInjector(
        [FaultSpec(site="s", kind="garbage", prob=1.0, max_faults=1)]))
    sup = DispatchSupervisor(RetryPolicy(max_attempts=3, base_delay=0.0,
                                         jitter=0.0))
    out = sup.run("s", lambda: [0.1, 0.2], validate=expect_len(2))
    assert out == [0.1, 0.2]  # truncated result rejected, retry clean
    assert timing.GLOBAL.counters().get("retries[s]") == 1


def test_supervisor_hang_caught_by_attempt_deadline():
    faults.install(FaultInjector(
        [FaultSpec(site="s", kind="hang", hang_seconds=30.0,
                   max_faults=1)]))
    sup = DispatchSupervisor(RetryPolicy(
        max_attempts=2, base_delay=0.0, jitter=0.0,
        attempt_deadline=0.1))
    t0 = time.monotonic()
    assert sup.run("s", lambda: "done") == "done"
    assert time.monotonic() - t0 < 5.0
    assert timing.GLOBAL.counters().get("retries[s]") == 1


def test_validators():
    expect_len(2)([1, 2])
    with pytest.raises(GarbageResultError):
        expect_len(2)([1])
    with pytest.raises(GarbageResultError):
        expect_len(1)(object())
    v = expect_ani_values(3)
    v([None, 0.0, 1.0])
    with pytest.raises(GarbageResultError):
        v([None, 0.5, 1.5])  # out of range
    with pytest.raises(GarbageResultError):
        v([None, float("nan"), 0.5])  # NaN
    with pytest.raises(GarbageResultError):
        v([0.5, 0.5])  # wrong length


def test_supervisor_thread_safety_single_demotion():
    faults.install(FaultInjector([FaultSpec(site="s", prob=1.0)]))
    sup = DispatchSupervisor(RetryPolicy(max_attempts=1, base_delay=0.0))
    results = []

    def worker():
        results.append(sup.run("s", lambda: "p", fallback=lambda: "f"))

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert results == ["f"] * 8
    assert len(sup.demotions()) == 1  # demoted exactly once
