"""Ingest prefilter (ops/prefilter.py): conservative by construction.

The acceptance bar for every skip the prefilter takes is *provable
bit-identity* — prefilter on vs off must produce the same pair cache
and the same clustering on any corpus, including corpora planted with
the cases it screens (byte-duplicate paths, degenerate genomes with
no valid k-mer window). The parity test runs the real
MinHashPreclusterer end to end both ways, on a planted-family corpus
and on a dense single-family corpus, with and without the paged
sketch tier underneath (docs/memory.md) — in ONE clean single-device
subprocess: the conftest's 8-device mesh puts a multi-second
collective dispatch under every distances() call, which is mesh
overhead, not parity signal, and a child process runs all seven arms
in a couple of seconds on the C pair path.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

from galah_tpu.ops import prefilter


class _G:
    """Stub genome: just the fields the screen functions read."""

    def __init__(self, codes, offsets=None):
        self.codes = np.asarray(codes, dtype=np.uint8)
        self.contig_offsets = np.asarray(
            offsets if offsets is not None else [0, len(codes)],
            dtype=np.int64)


# ---------------------------------------------------------------------------
# Screen predicates
# ---------------------------------------------------------------------------


def test_has_valid_window_cases():
    k = 5
    # a clean run of k unambiguous bases -> has a window
    assert prefilter._has_valid_window(_G([0, 1, 2, 3, 0]), k)
    # genome shorter than k -> provably empty k-mer set
    assert not prefilter._has_valid_window(_G([0, 1, 2]), k)
    # every contig shorter than k, though the total is not
    assert not prefilter._has_valid_window(
        _G([0, 1, 2, 0, 1, 2], offsets=[0, 3, 6]), k)
    # ambiguous bases (255) break the run below k everywhere
    assert not prefilter._has_valid_window(
        _G([0, 1, 255, 2, 3, 255, 0, 1]), k)
    # ... but a k-run on either side of an N is a window
    assert prefilter._has_valid_window(
        _G([255, 0, 1, 2, 3, 0, 255]), k)
    # exact-length boundary: run of exactly k counts
    assert prefilter._has_valid_window(_G([255] + [0] * 5 + [255]), k)
    assert not prefilter._has_valid_window(_G([255] + [0] * 4 + [255]), k)


def test_digest_separates_content_not_paths():
    a = _G([0, 1, 2, 3] * 10)
    b = _G([0, 1, 2, 3] * 10)
    c = _G([0, 1, 2, 3] * 10, offsets=[0, 20, 40])  # same codes, 2 contigs
    assert prefilter._digest(a) == prefilter._digest(b)
    assert prefilter._digest(a) != prefilter._digest(c)


def test_engagement_tristate(monkeypatch):
    monkeypatch.setenv("GALAH_TPU_PREFILTER", "0")
    assert not prefilter.prefilter_engaged()
    monkeypatch.setenv("GALAH_TPU_PREFILTER", "1")
    assert prefilter.prefilter_engaged()
    monkeypatch.setenv("GALAH_TPU_PREFILTER", "auto")
    assert prefilter.prefilter_engaged()  # tests run single-process


# ---------------------------------------------------------------------------
# End-to-end parity (subprocess driver)
# ---------------------------------------------------------------------------

_PARITY_DRIVER = r"""
import os
import sys

import numpy as np

root = sys.argv[1]
os.environ["GALAH_TPU_SKETCH_STRATEGY"] = "c"

from galah_tpu.backends.minhash_backend import MinHashPreclusterer
from galah_tpu.obs import metrics as obs_metrics

BASES = np.array(list("ACGT"))


def _write(path, seq):
    with open(path, "w") as f:
        f.write(">c1\n")
        for i in range(0, len(seq), 70):
            f.write(seq[i:i + 70] + "\n")


def _planted_corpus(root, families=2, members=3, length=12_000, seed=11):
    # Family corpus (test_synthetic_families.py recipe) salted with the
    # prefilter's screen cases: a byte-duplicate of fam0_m1 under a new
    # path, a degenerate all-N genome, and a degenerate genome whose
    # contigs are all shorter than k.
    rng = np.random.default_rng(seed)
    paths = []
    for fam in range(families):
        base = rng.integers(0, 4, size=length)
        for member in range(members):
            codes = base.copy()
            if member:
                sites = rng.random(length) < 0.005
                codes[sites] = (codes[sites] + rng.integers(
                    1, 4, size=int(sites.sum()))) % 4
            p = os.path.join(root, f"fam{fam}_m{member}.fna")
            _write(p, "".join(BASES[codes]))
            paths.append(p)
    dup = os.path.join(root, "dup_of_fam0_m1.fna")
    with open(paths[1], "rb") as src, open(dup, "wb") as dst:
        dst.write(src.read())
    paths.append(dup)
    all_n = os.path.join(root, "degenerate_n.fna")
    _write(all_n, "N" * 500)
    paths.append(all_n)
    shorty = os.path.join(root, "degenerate_short.fna")
    with open(shorty, "w") as f:
        for c in range(6):
            f.write(f">c{c}\nACGTACGTAC\n")  # 10 bp < k=21 per contig
    paths.append(shorty)
    return paths


def _dense_corpus(root, members=8, length=9_000, seed=13):
    # One family, everything within ~99.8% ANI: the dense regime where
    # nothing screens out except the planted duplicate.
    rng = np.random.default_rng(seed)
    base = rng.integers(0, 4, size=length)
    paths = []
    for member in range(members):
        codes = base.copy()
        if member:
            sites = rng.random(length) < 0.002
            codes[sites] = (codes[sites] + rng.integers(
                1, 4, size=int(sites.sum()))) % 4
        p = os.path.join(root, f"dense_m{member}.fna")
        _write(p, "".join(BASES[codes]))
        paths.append(p)
    dup = os.path.join(root, "dense_dup.fna")
    with open(paths[0], "rb") as src, open(dup, "wb") as dst:
        dst.write(src.read())
    paths.append(dup)
    return paths


def _distances(paths, **env):
    for key in ("GALAH_TPU_PREFILTER", "GALAH_TPU_PAGESTORE",
                "GALAH_TPU_HLL_BUCKETS", "GALAH_TPU_SKETCH_RAM_MB"):
        os.environ.pop(key, None)
    os.environ.update(env)
    return MinHashPreclusterer(min_ani=0.9).distances(list(paths))


def _skipped():
    snap = obs_metrics.snapshot().get("prefilter.skipped", {})
    return snap.get("value", 0)


pd = os.path.join(root, "planted")
dd = os.path.join(root, "dense")
os.makedirs(pd)
os.makedirs(dd)
planted = _planted_corpus(pd)
dense = _dense_corpus(dd)

# prefilter on/off bit-parity on both corpora, and the screens fired:
# at least the duplicate skipped (plus both degenerates on planted).
base_planted = _distances(planted, GALAH_TPU_PREFILTER="0")
assert len(base_planted) > 0
before = _skipped()
on_planted = _distances(planted, GALAH_TPU_PREFILTER="1")
assert on_planted == base_planted          # PairDistanceCache bit-parity
assert _skipped() - before >= 3

base_dense = _distances(dense, GALAH_TPU_PREFILTER="0")
assert len(base_dense) > 0
before = _skipped()
on_dense = _distances(dense, GALAH_TPU_PREFILTER="1")
assert on_dense == base_dense
assert _skipped() - before >= 1

# The tiered path agrees with the all-resident baseline bit for bit:
# paged band walk (bucketed pass over the page store under a 1 MiB
# resident budget), with and without the prefilter on top.
# Bucketed-unpaged parity is ops/bucketing's own test surface.
paged_env = dict(GALAH_TPU_HLL_BUCKETS="1", GALAH_TPU_PAGESTORE="1",
                 GALAH_TPU_SKETCH_RAM_MB="1")
paged = _distances(planted, GALAH_TPU_PREFILTER="0", **paged_env)
assert paged == base_planted
paged_pre = _distances(planted, GALAH_TPU_PREFILTER="1", **paged_env)
assert paged_pre == base_planted

print("PARITY_OK")
"""


def test_prefilter_parity_end_to_end(tmp_path):
    """All seven parity arms in one clean child: prefilter on/off on
    the planted and dense corpora, then the paged tier (with and
    without the prefilter) against the all-resident baseline."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    # Drop the conftest's 8-fake-device forcing: the child measures
    # parity, and the single-device C pair path is bit-identical to
    # the mesh path by the strategy contract (tested elsewhere).
    env.pop("XLA_FLAGS", None)
    proc = subprocess.run(
        [sys.executable, "-c", _PARITY_DRIVER, str(tmp_path)],
        capture_output=True, text=True, timeout=240,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        env=env)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "PARITY_OK" in proc.stdout
