"""End-to-end clustering on synthetic genome families.

Beyond the 4-MAG goldens: generate families of genomes by mutating a
base sequence at controlled rates, run the full pipeline (every
precluster/cluster method combination), and require that clusters
recover the family structure — same-family genomes (~99% ANI) cluster
together, cross-family pairs (different random bases) never do.
"""

import numpy as np
import pytest

from galah_tpu.backends import (
    FastANIEquivalentClusterer,
    HLLPreclusterer,
    MinHashPreclusterer,
    ProfileStore,
    SkaniEquivalentClusterer,
    SkaniPreclusterer,
)
from galah_tpu.cluster import cluster

BASES = np.array(list("ACGT"))


def _write(path, seq_codes, line=70):
    seq = "".join(BASES[seq_codes])
    with open(path, "w") as f:
        f.write(">contig1\n")
        for i in range(0, len(seq), line):
            f.write(seq[i:i + line] + "\n")


@pytest.fixture(scope="module")
def families(tmp_path_factory):
    """3 families x 4 members, 60 kb, ~0.5% within-family divergence."""
    root = tmp_path_factory.mktemp("families")
    rng = np.random.default_rng(42)
    length = 60_000
    paths, labels = [], []
    for fam in range(3):
        base = rng.integers(0, 4, size=length)
        for member in range(4):
            codes = base.copy()
            if member:  # member 0 is the unmutated base
                sites = rng.random(length) < 0.005
                codes[sites] = (codes[sites]
                                + rng.integers(1, 4, size=int(sites.sum()))
                                ) % 4
            p = str(root / f"fam{fam}_m{member}.fna")
            _write(p, codes)
            paths.append(p)
            labels.append(fam)
    return paths, labels


def _family_partition(paths, labels, clusters):
    got = sorted(sorted(c) for c in clusters)
    want = sorted(
        sorted(i for i, l in enumerate(labels) if l == fam)
        for fam in set(labels))
    return got, want


@pytest.mark.parametrize("pre_name", ["finch", "dashing", "skani"])
def test_families_recovered_all_preclusterers(families, pre_name):
    paths, labels = families
    store = ProfileStore(k=15)
    pre = {
        "finch": lambda: MinHashPreclusterer(min_ani=0.9),
        "dashing": lambda: HLLPreclusterer(min_ani=0.9),
        "skani": lambda: SkaniPreclusterer(
            threshold=0.9, min_aligned_fraction=0.2, store=store),
    }[pre_name]()
    cl = FastANIEquivalentClusterer(
        threshold=0.97, min_aligned_fraction=0.2, store=store)
    got, want = _family_partition(paths, labels, cluster(paths, pre, cl))
    assert got == want


def test_families_recovered_skani_skani(families):
    paths, labels = families
    store = ProfileStore(k=15)
    out = cluster(
        paths,
        SkaniPreclusterer(threshold=0.97, min_aligned_fraction=0.2,
                          store=store),
        SkaniEquivalentClusterer(threshold=0.97, min_aligned_fraction=0.2,
                                 store=store),
    )
    got, want = _family_partition(paths, labels, out)
    assert got == want


def test_representative_is_first_member(families):
    """Quality order = input order here, so each cluster's representative
    must be its family's first (lowest-index) member."""
    paths, labels = families
    store = ProfileStore(k=15)
    out = cluster(
        paths,
        MinHashPreclusterer(min_ani=0.9),
        FastANIEquivalentClusterer(threshold=0.97,
                                   min_aligned_fraction=0.2, store=store),
    )
    for c in out:
        assert c[0] == min(c)
