"""The shared explicit-pin / default-fallback policy for Mosaic kernels.

One helper (ops/_fallback.py) now carries the contract that four call
sites (threshold_pairs, screen_pairs, hll_threshold_pairs, and the
sparse pairlist batcher) previously duplicated: an explicitly pinned
path fails loudly — parity tests must never vacuously compare XLA to
XLA — while the default path downgrades to the XLA twin with a logged
warning when Mosaic lowering fails.
"""

import logging

import pytest

from galah_tpu.ops._fallback import run_with_pallas_fallback


class _Boom(RuntimeError):
    pass


def test_pallas_success_returns_result_and_flag():
    result, used = run_with_pallas_fallback(
        "test kernel", explicit=False, use_pallas=True,
        run=lambda p: ("ran", p))
    assert result == ("ran", True)
    assert used is True


def test_default_fallback_runs_xla_and_warns(caplog):
    calls = []

    def run(p):
        calls.append(p)
        if p:
            raise _Boom("no lowering")
        return "xla"

    with caplog.at_level(logging.WARNING, "galah_tpu.ops._fallback"):
        result, used = run_with_pallas_fallback(
            "test kernel", explicit=False, use_pallas=True, run=run)
    assert result == "xla"
    assert used is False
    assert calls == [True, False]
    assert any("test kernel" in r.message and "falling back" in r.message
               for r in caplog.records)


def test_explicit_pin_propagates_failure():
    def run(p):
        raise _Boom("no lowering")

    with pytest.raises(_Boom):
        run_with_pallas_fallback(
            "test kernel", explicit=True, use_pallas=True, run=run)


def test_use_pallas_false_skips_mosaic_entirely():
    calls = []
    result, used = run_with_pallas_fallback(
        "test kernel", explicit=True, use_pallas=False,
        run=lambda p: calls.append(p) or "xla")
    assert result == "xla"
    assert used is False
    assert calls == [False]


def test_xla_failure_always_propagates():
    with pytest.raises(_Boom):
        run_with_pallas_fallback(
            "test kernel", explicit=False, use_pallas=False,
            run=lambda p: (_ for _ in ()).throw(_Boom()))


def test_downgrade_loop_pattern():
    """The sparse batcher's loop: after one failure the returned flag
    keeps later batches off the Mosaic path without retrying it."""
    attempts = []

    def run(p):
        attempts.append(p)
        if p:
            raise _Boom()
        return "xla"

    use_pallas = True
    for _ in range(3):
        _, use_pallas = run_with_pallas_fallback(
            "test kernel", explicit=False, use_pallas=use_pallas,
            run=run)
    assert attempts == [True, False, False, False]
