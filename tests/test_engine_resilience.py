"""Engine-level fault injection (the tentpole's acceptance criteria).

(a) A transient device-dispatch fault is retried and the run produces
    bit-identical clusters to a fault-free run.
(b) Persistent device faults demote the site to its CPU fallback
    mid-run; the run completes, records the demotion in the stage
    report, and still produces identical clusters.

Uses the checkpoint tests' fake backends so the dispatch.ani site fires
deterministically, plus one real-backend run over tiny FASTA to prove
the fragment-ANI site is guarded end-to-end.
"""

import numpy as np
import pytest

from galah_tpu.cluster import cluster
from galah_tpu.resilience import dispatch as rdispatch
from galah_tpu.resilience import faults
from galah_tpu.resilience.faults import FaultInjector, FaultSpec
from galah_tpu.resilience.policy import RetryPolicy
from galah_tpu.utils import timing
from tests.test_checkpoint import GENOMES, FakeCl, FakePre

pytestmark = pytest.mark.fault_injection

FAST = RetryPolicy(max_attempts=3, base_delay=0.001, jitter=0.0)


@pytest.fixture(autouse=True)
def _clean_state():
    faults.reset()
    rdispatch.reset(FAST)
    timing.reset()
    yield
    faults.reset()
    rdispatch.reset()
    timing.reset()


def test_transient_ani_fault_retried_bit_identical():
    """Acceptance (a): two injected transient faults at the ANI batch
    dispatch are absorbed by retries; clusters match fault-free."""
    reference = cluster(GENOMES, FakePre(), FakeCl(0.95))

    faults.install(FaultInjector([FaultSpec(
        site="dispatch.ani", kind="raise", prob=1.0, max_faults=2)]))
    out = cluster(GENOMES, FakePre(), FakeCl(0.95))

    assert out == reference
    injector = faults.get_injector()
    assert injector.fired() == 2
    assert timing.GLOBAL.counters().get("retries[dispatch.ani]") == 2
    assert not rdispatch.demotions()


def test_persistent_ani_fault_demotes_and_completes():
    """Acceptance (b): every batched ANI dispatch fails; the site is
    demoted to the per-pair CPU fallback, the run completes, the
    demotion lands in the stage report, clusters match fault-free."""
    reference = cluster(GENOMES, FakePre(), FakeCl(0.95))

    faults.install(FaultInjector([FaultSpec(
        site="dispatch.ani", kind="raise", prob=1.0)]))
    cl = FakeCl(0.95)
    out = cluster(GENOMES, FakePre(), cl)

    assert out == reference
    dems = rdispatch.demotions()
    assert [d.site for d in dems] == ["dispatch.ani"]
    counters = timing.GLOBAL.counters()
    assert counters.get("demoted[dispatch.ani]") == 1
    assert counters.get("retries[dispatch.ani]") == 2
    report = timing.GLOBAL.report()
    assert "demoted[dispatch.ani]=1" in report
    # the fallback actually computed ANI (per-pair, outside injection)
    assert cl.pairs_computed


def test_device_lost_then_recovered():
    """The tunnel-drop signature (DeviceLostError) is retryable too;
    one drop does not demote."""
    faults.install(FaultInjector([FaultSpec(
        site="dispatch.ani", kind="device-lost", prob=1.0,
        max_faults=1)]))
    out = cluster(GENOMES, FakePre(), FakeCl(0.95))
    assert out == cluster(GENOMES, FakePre(), FakeCl(0.95))
    assert not rdispatch.demotions()


def test_garbage_ani_batch_rejected_by_validator():
    """A truncated device result is caught by the shape validator and
    retried — it must never silently mis-cluster."""
    reference = cluster(GENOMES, FakePre(), FakeCl(0.95))
    faults.install(FaultInjector([FaultSpec(
        site="dispatch.ani", kind="garbage", prob=1.0, max_faults=1)]))
    out = cluster(GENOMES, FakePre(), FakeCl(0.95))
    assert out == reference
    assert timing.GLOBAL.counters().get(
        "retries[dispatch.ani]", 0) >= 1


def _write_genomes(tmp_path):
    rng = np.random.default_rng(21)
    base = rng.integers(0, 4, size=30_000)
    paths = []
    for name, seq in [
        ("a", base),
        ("b", _mutate(base, rng, 0.02)),
        ("far", rng.integers(0, 4, size=30_000)),
    ]:
        p = tmp_path / f"{name}.fna"
        p.write_text(">c\n" + "".join("ACGT"[c] for c in seq) + "\n")
        paths.append(str(p))
    return paths


def _mutate(base, rng, rate):
    seq = np.array(base, copy=True)
    sites = rng.random(seq.shape[0]) < rate
    seq[sites] = (seq[sites]
                  + rng.integers(1, 4, size=int(sites.sum()))) % 4
    return seq


def test_real_backend_fragment_ani_site_guarded(tmp_path):
    """End-to-end over real FASTA: persistent faults at the fragment-ANI
    dispatch (skani precluster distances) demote to the per-pair
    fallback and the clustering still matches the fault-free run."""
    from galah_tpu.api import generate_galah_clusterer

    paths = _write_genomes(tmp_path)
    values = {"ani": 95.0, "precluster_ani": 90.0,
              "min_aligned_fraction": 15.0, "fragment_length": 3000,
              "precluster_method": "skani", "cluster_method": "skani",
              "threads": 1}

    def run():
        cl = generate_galah_clusterer(paths, values)
        return sorted(sorted(cl.genome_paths[i] for i in c)
                      for c in cl.cluster())

    reference = run()
    timing.reset()
    rdispatch.reset(FAST)
    faults.install(FaultInjector([FaultSpec(
        site="dispatch.fragment-ani", kind="raise", prob=1.0)]))
    out = run()

    assert out == reference
    assert [d.site for d in rdispatch.demotions()] == [
        "dispatch.fragment-ani"]
    assert timing.GLOBAL.counters().get(
        "demoted[dispatch.fragment-ani]") == 1
