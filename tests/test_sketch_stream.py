"""Streaming ingest->sketch pipeline (ops/sketch_stream) and the
overlapped pair pass it feeds.

The bit-identity gate: all three sketch strategies (fused Pallas /
chunked XLA / C bottom-k) must produce byte-identical uint64 sketches,
gzipped input included, and the streamed pair pass must reproduce the
staged threshold_pairs dict exactly.
"""

import gzip

import numpy as np
import pytest

from galah_tpu.io import read_genome
from galah_tpu.ops import minhash_np
from galah_tpu.ops import sketch_stream


def _write_fasta(tmp_path, name, body):
    p = tmp_path / name
    p.write_text(body)
    return str(p)


def _rand_seq(rng, n):
    return "".join(rng.choice(list("ACGT"), size=n))


def _fresh_store(tmp_path, name, sketch_size=64):
    from galah_tpu.backends.minhash_backend import SketchStore
    from galah_tpu.io.diskcache import CacheDir

    return SketchStore(sketch_size, 21,
                       cache=CacheDir(str(tmp_path / name)))


def test_resolver_auto_and_pins(monkeypatch):
    """AUTO keeps the historical winners; an env pin always wins and
    marks itself explicit (so its failures propagate)."""
    monkeypatch.delenv("GALAH_TPU_SKETCH_STRATEGY", raising=False)
    resolve = sketch_stream.resolve_sketch_strategy
    assert resolve("cpu", 1, True) == ("c", False)
    assert resolve("cpu", 1, False) == ("xla", False)
    assert resolve("cpu", 8, True) == ("xla", False)
    from galah_tpu.ops import hll

    monkeypatch.setattr(hll, "use_pallas_default", lambda: True)
    assert resolve("tpu", 8, True) == ("fused", False)
    monkeypatch.setattr(hll, "use_pallas_default", lambda: False)
    assert resolve("tpu", 8, True) == ("xla", False)
    for s in sketch_stream.SKETCH_STRATEGIES:
        monkeypatch.setenv("GALAH_TPU_SKETCH_STRATEGY", s)
        assert resolve("cpu", 1, True) == (s, True)


def test_fused_parity_vs_numpy(tmp_path):
    """The fused kernel (interpret mode) is bit-identical to the numpy
    oracle across the edge shapes: sub-k contigs, all-ambiguous
    genomes, fewer-than-sketch_size distinct k-mers (sentinel-padded
    rows)."""
    rng = np.random.default_rng(11)
    bodies = {
        # two contigs, an N, a short tail contig
        "normal.fna": (f">a\n{_rand_seq(rng, 1500)}N"
                       f"{_rand_seq(rng, 1500)}\n>b\n"
                       f"{_rand_seq(rng, 40)}\n"),
        # a contig shorter than k contributes zero windows
        "subk.fna": (f">tiny\n{_rand_seq(rng, 10)}\n>real\n"
                     f"{_rand_seq(rng, 800)}\n"),
        # all-ambiguous: every window masked, empty sketch
        "alln.fna": ">n\n" + "N" * 500 + "\n",
        # shorter than k entirely: zero windows at all
        "short.fna": ">s\nACGTA\n",
        # yields far fewer than sketch_size distinct k-mers
        "sparse.fna": f">p\n{_rand_seq(rng, 60)}\n",
    }
    genomes = [read_genome(_write_fasta(tmp_path, n, b))
               for n, b in sorted(bodies.items())]
    fused = sketch_stream.sketch_genomes_fused(
        genomes, sketch_size=64, interpret=True)
    for g, s in zip(genomes, fused):
        ref = minhash_np.sketch_genome(g, sketch_size=64)
        np.testing.assert_array_equal(ref.hashes, s.hashes)


@pytest.mark.slow
def test_fused_parity_span_bucket_edge(tmp_path):
    """A genome crossing one kernel-block boundary lands in the span=2
    bucket and still matches the numpy oracle bit-for-bit. Slow tier:
    interpret-mode Pallas walks the multi-block grid serially (~5 min
    on the host VM); the span logic itself also runs on every TPU
    hardware session via the fused strategy."""
    rng = np.random.default_rng(17)
    g = read_genome(_write_fasta(
        tmp_path, "span2.fna",
        f">big\n{_rand_seq(rng, sketch_stream._BLOCK + 1000)}\n"))
    (s,) = sketch_stream.sketch_genomes_fused([g], sketch_size=64,
                                              interpret=True)
    ref = minhash_np.sketch_genome(g, sketch_size=64)
    np.testing.assert_array_equal(ref.hashes, s.hashes)


def test_gzip_plain_identical_all_strategies(tmp_path):
    """Gzipped and plain copies of the same sequence sketch to the
    same bytes through the full streaming pipeline, under every
    strategy, and all strategies agree with the numpy oracle."""
    from galah_tpu.obs import metrics as obs_metrics

    rng = np.random.default_rng(12)
    body = (f">a\n{_rand_seq(rng, 2500)}N{_rand_seq(rng, 2500)}\n"
            f">b\n{_rand_seq(rng, 120)}\n")
    plain = _write_fasta(tmp_path, "g.fna", body)
    gz = tmp_path / "g.fna.gz"
    with gzip.open(gz, "wt") as fh:
        fh.write(body)
    ref = minhash_np.sketch_genome(read_genome(plain), sketch_size=64)

    strategies = list(sketch_stream.SKETCH_STRATEGIES)
    if not sketch_stream._c_sketcher_available():
        strategies.remove("c")
    for strategy in strategies:
        store = _fresh_store(tmp_path, f"cache_{strategy}")
        got = dict(sketch_stream.iter_path_sketches(
            [plain, str(gz)], store, strategy=strategy))
        assert set(got) == {plain, str(gz)}
        for s in got.values():
            np.testing.assert_array_equal(ref.hashes, s.hashes)
    snap = obs_metrics.snapshot()
    assert snap["workload.ingest_mbp"]["value"] > 0
    assert snap["workload.ingest_mbp_s"]["value"] > 0


def test_iter_path_sketches_order_dedupe_and_cache_hits(tmp_path):
    """Unique paths come back in original order; a warm store serves
    hits without re-reading the files."""
    rng = np.random.default_rng(13)
    paths = [_write_fasta(tmp_path, f"g{i}.fna",
                          f">c\n{_rand_seq(rng, 400 + 31 * i)}\n")
             for i in range(5)]
    store = _fresh_store(tmp_path, "cache_order")
    order = [p for p, _ in sketch_stream.iter_path_sketches(
        [paths[2], paths[0], paths[2], paths[4], paths[0]], store)]
    assert order == [paths[2], paths[0], paths[4]]
    # warm pass: everything is a cache hit, files need not exist
    for p in paths:
        (tmp_path / p.split("/")[-1]).rename(tmp_path / (
            p.split("/")[-1] + ".moved"))
    warm = [p for p, _ in sketch_stream.iter_path_sketches(
        [paths[2], paths[0], paths[4]], store)]
    assert warm == [paths[2], paths[0], paths[4]]


def test_streamed_pair_pass_matches_staged(tmp_path, monkeypatch):
    """The overlapped streamed pair pass produces the same pair dict
    as the historical staged path (sketch everything, then
    threshold_pairs) on a two-family workload."""
    from galah_tpu.backends.minhash_backend import MinHashPreclusterer
    from galah_tpu.io.diskcache import CacheDir

    rng = np.random.default_rng(14)
    base = rng.choice(list("ACGT"), size=6000)
    paths = []
    for i in range(6):
        seq = base.copy()
        if i >= 3:  # second family
            sites = rng.random(seq.shape[0]) < 0.03
            seq[sites] = rng.choice(list("ACGT"), size=int(sites.sum()))
        paths.append(_write_fasta(tmp_path, f"m{i}.fna",
                                  ">c\n" + "".join(seq) + "\n"))

    monkeypatch.delenv("GALAH_TPU_SKETCH_STRATEGY", raising=False)
    streamed = MinHashPreclusterer(
        0.95, sketch_size=64,
        cache=CacheDir(str(tmp_path / "c1"))).distances(paths)
    # a "c" pin routes the backend down the historical staged path
    monkeypatch.setenv("GALAH_TPU_SKETCH_STRATEGY",
                       "c" if sketch_stream._c_sketcher_available()
                       else "xla")
    sp = MinHashPreclusterer(
        0.95, sketch_size=64, cache=CacheDir(str(tmp_path / "c2")))
    monkeypatch.setattr(sp, "_streamed_pair_pass",
                        lambda _paths: None)
    staged = sp.distances(paths)
    assert dict(streamed.items()) == dict(staged.items())
    assert len(dict(staged.items())) >= 3  # both families pair up


def test_threshold_pairs_streamed_unit():
    """threshold_pairs_streamed over row blocks == threshold_pairs
    over the full matrix, including sentinel-padded and empty rows,
    at a block size that does not divide n."""
    from galah_tpu.ops.constants import SENTINEL
    from galah_tpu.ops.pairwise import (
        threshold_pairs,
        threshold_pairs_streamed,
    )

    rng = np.random.default_rng(15)
    n, ss = 70, 64
    pool = rng.integers(0, 1 << 63, size=200, dtype=np.uint64)
    mat = np.empty((n, ss), dtype=np.uint64)
    for i in range(n):
        mat[i] = np.sort(rng.choice(pool, size=ss, replace=False))
    mat[7, :] = np.uint64(SENTINEL)              # empty sketch
    mat[9, 10:] = np.uint64(SENTINEL)            # sentinel-padded row
    mat[9, :10] = np.sort(mat[9, :10])

    want = threshold_pairs(mat, k=21, min_ani=0.75, sketch_size=ss)

    def blocks(b):
        for r0 in range(0, n, b):
            yield r0, mat[r0:r0 + b]

    for b in (32, 37):
        got = threshold_pairs_streamed(
            blocks(b), n, k=21, min_ani=0.75, sketch_size=ss, block=b)
        assert got == want
    assert want  # the pool overlap produces real pairs


def test_backpressure_bounded_under_slow_io(tmp_path, monkeypatch):
    """With a slow-io fault at the io.ingest site and a slow consumer,
    the stream still completes, the injector fires, and the number of
    parsed genomes in flight never exceeds the depth bound — memory
    stays O(depth + workers), not O(corpus)."""
    import threading
    import time

    from galah_tpu.io import fasta
    from galah_tpu.resilience import faults

    rng = np.random.default_rng(16)
    paths = [_write_fasta(tmp_path, f"b{i}.fna",
                          f">c\n{_rand_seq(rng, 300)}\n")
             for i in range(12)]
    lock = threading.Lock()
    state = {"loaded": 0, "consumed": 0, "max_ahead": 0}
    real_read = fasta.read_genome

    def counting_read(path, *a, **kw):
        with lock:
            state["loaded"] += 1
            ahead = state["loaded"] - state["consumed"]
            state["max_ahead"] = max(state["max_ahead"], ahead)
        return real_read(path, *a, **kw)

    monkeypatch.setattr(fasta, "read_genome", counting_read)
    monkeypatch.setenv("GALAH_TPU_INGEST_DEPTH", "2")
    injector = faults.FaultInjector(faults.parse_spec(
        "site=io.ingest;kind=slow-io;prob=1.0;hang=0.01;max=4"))
    faults.install(injector)
    try:
        store = _fresh_store(tmp_path, "cache_bp")
        for _p, _s in sketch_stream.iter_path_sketches(
                paths, store,
                strategy="c" if sketch_stream._c_sketcher_available()
                else "xla"):
            time.sleep(0.005)  # slow consumer: forces backpressure
            with lock:
                state["consumed"] += 1
    finally:
        faults.reset()
    assert state["loaded"] == 12
    assert injector.fired() == 4
    # depth=2 look-ahead + the one being consumed + one in handoff
    assert state["max_ahead"] <= 5


def test_corrupt_gzip_error_names_path(tmp_path):
    """A corrupt .gz propagates as BadGenomeError carrying the path —
    through read_genome and through the streaming pipeline."""
    from galah_tpu.io.fasta import BadGenomeError

    bad = tmp_path / "bad.fna.gz"
    bad.write_bytes(b"\x1f\x8b\x08\x00garbage-not-a-gzip-stream")
    with pytest.raises(BadGenomeError) as ei:
        read_genome(str(bad))
    assert str(bad) in str(ei.value)
    assert ei.value.reason == "corrupt"

    store = _fresh_store(tmp_path, "cache_corrupt")
    with pytest.raises(BadGenomeError, match="corrupt"):
        list(sketch_stream.iter_path_sketches([str(bad)], store))


def test_c_fallback_observability(tmp_path, monkeypatch):
    """When the C ingest fast path is unavailable, the numpy fallback
    is visible: a warn_once, an ingest-c-fallback event, and the
    ingest.c_fallback counter — never a silent 10x slowdown."""
    from galah_tpu.io import fasta
    from galah_tpu.obs import events
    from galah_tpu.obs import metrics as obs_metrics

    p = _write_fasta(tmp_path, "cf.fna", ">c\nACGTACGTACGT\n")
    monkeypatch.setattr(fasta, "_get_cingest", lambda: None)
    monkeypatch.setattr(fasta, "_CINGEST_ERR",
                        [RuntimeError("no compiler")])
    before = obs_metrics.snapshot().get(
        "ingest.c_fallback", {}).get("value", 0)
    g = read_genome(p)
    assert g.length == 12
    after = obs_metrics.snapshot()["ingest.c_fallback"]["value"]
    assert after >= before + 1
    evs = [e for e in events.snapshot()
           if e["kind"] == "ingest-c-fallback"
           and e["what"] == "build/load failed"]
    assert evs and "no compiler" in evs[-1]["error"]
