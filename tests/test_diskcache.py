"""Persistent sketch/profile cache (io/diskcache.py).

The reference re-sketches every genome on every run (SURVEY.md §5 notes
no checkpoint/caching subsystem exists); the cache must be a pure
speedup — identical results, keyed on file identity, invalidated when
the FASTA changes.
"""

import os
import shutil

import numpy as np

from galah_tpu.backends.fragment_backend import ProfileStore
from galah_tpu.backends.minhash_backend import SketchStore
from galah_tpu.io import diskcache


def _write_fasta(path, seq):
    with open(path, "w") as f:
        f.write(">c1\n")
        f.write(seq + "\n")


def test_cachedir_roundtrip(tmp_path):
    fasta = tmp_path / "g.fna"
    _write_fasta(str(fasta), "ACGT" * 500)
    cache = diskcache.CacheDir(str(tmp_path / "cache"))
    params = {"k": 21, "seed": 0}
    assert cache.load(str(fasta), "x", params) is None
    arrays = {"a": np.arange(5, dtype=np.uint64),
              "b": np.ones((2, 3), dtype=np.uint8)}
    cache.store(str(fasta), "x", params, arrays)
    back = cache.load(str(fasta), "x", params)
    np.testing.assert_array_equal(back["a"], arrays["a"])
    np.testing.assert_array_equal(back["b"], arrays["b"])
    # different params -> different entry
    assert cache.load(str(fasta), "x", {"k": 15, "seed": 0}) is None


def test_cache_invalidated_on_file_change(tmp_path):
    fasta = tmp_path / "g.fna"
    _write_fasta(str(fasta), "ACGT" * 500)
    cache = diskcache.CacheDir(str(tmp_path / "cache"))
    cache.store(str(fasta), "x", {}, {"a": np.zeros(1)})
    assert cache.load(str(fasta), "x", {}) is not None
    # rewrite with different content (size changes)
    _write_fasta(str(fasta), "ACGTA" * 500)
    assert cache.load(str(fasta), "x", {}) is None


def test_disabled_cache_is_noop(tmp_path):
    fasta = tmp_path / "g.fna"
    _write_fasta(str(fasta), "ACGT" * 50)
    cache = diskcache.CacheDir(None)
    cache.store(str(fasta), "x", {}, {"a": np.zeros(1)})
    assert cache.load(str(fasta), "x", {}) is None


def test_sketchstore_cache_identical_sketches(tmp_path, ref_data):
    src = str(ref_data / "set1" / "500kb.fna")
    fasta = str(tmp_path / "500kb.fna")
    shutil.copy(src, fasta)
    cache = diskcache.CacheDir(str(tmp_path / "cache"))

    s1 = SketchStore(sketch_size=1000, k=21, cache=cache).get(fasta)
    assert cache.misses == 1 and cache.hits == 0
    # fresh store, same cache dir: must hit and return identical hashes
    s2 = SketchStore(sketch_size=1000, k=21, cache=cache).get(fasta)
    assert cache.hits == 1
    np.testing.assert_array_equal(s1.hashes, s2.hashes)


def test_profilestore_cache_identical_profiles(tmp_path, ref_data):
    src = str(ref_data / "set1" / "500kb.fna")
    fasta = str(tmp_path / "500kb.fna")
    shutil.copy(src, fasta)
    cache = diskcache.CacheDir(str(tmp_path / "cache"))

    p1 = ProfileStore(k=15, fraglen=3000, cache=cache).get(fasta)
    p2 = ProfileStore(k=15, fraglen=3000, cache=cache).get(fasta)
    assert cache.hits == 1
    np.testing.assert_array_equal(p1.flat_hashes, p2.flat_hashes)
    np.testing.assert_array_equal(p1.ref_set, p2.ref_set)
    np.testing.assert_array_equal(p1.markers, p2.markers)


# -- corruption recovery (miss-and-repair, never a wrong sketch) ------


def _seed_entry(tmp_path, arrays=None):
    fasta = tmp_path / "g.fna"
    _write_fasta(str(fasta), "ACGT" * 500)
    cache = diskcache.CacheDir(str(tmp_path / "cache"))
    cache.store(str(fasta), "x", {},
                arrays or {"a": np.arange(8, dtype=np.uint64)})
    (entry,) = [f for f in (tmp_path / "cache").iterdir()
                if f.suffix == ".npz"]
    return fasta, cache, entry


def test_truncated_entry_is_miss_and_repair(tmp_path, caplog):
    import logging

    fasta, cache, entry = _seed_entry(tmp_path)
    entry.write_bytes(entry.read_bytes()[: entry.stat().st_size // 2])
    with caplog.at_level(logging.WARNING):
        assert cache.load(str(fasta), "x", {}) is None
    assert "corrupt cache entry" in caplog.text
    assert not entry.exists()  # dropped, ready for restore
    cache.store(str(fasta), "x", {}, {"a": np.arange(8,
                                                     dtype=np.uint64)})
    back = cache.load(str(fasta), "x", {})
    np.testing.assert_array_equal(back["a"], np.arange(8))


def test_flipped_data_byte_is_miss_not_wrong_sketch(tmp_path):
    """Corrupting actual array bytes must never return wrong data —
    either the zip member CRC or the embedded __check__ rejects it."""
    fasta, cache, entry = _seed_entry(tmp_path)
    raw = bytearray(entry.read_bytes())
    # the array payload sits after the npz member header; flip a byte
    # inside the stored uint64 data
    idx = raw.find((3).to_bytes(8, "little"))
    assert idx > 0
    raw[idx] ^= 0xFF
    entry.write_bytes(bytes(raw))
    assert cache.load(str(fasta), "x", {}) is None
    assert not entry.exists()


def test_bad_embedded_checksum_is_miss(tmp_path):
    """An entry whose __check__ disagrees with its content is dropped
    (covers semantic corruption zipfile-level CRCs can't see)."""
    fasta, cache, entry = _seed_entry(tmp_path)
    with np.load(entry) as z:
        payload = {name: z[name] for name in z.files}
    payload["__check__"] = np.array([12345], dtype=np.uint64)
    from galah_tpu.io import atomic

    atomic.write_npz(str(entry), payload)
    assert cache.load(str(fasta), "x", {}) is None
    assert cache.misses == 1


def test_legacy_entry_without_checksum_still_loads(tmp_path):
    """Pre-checksum entries (no __check__ member) stay readable."""
    fasta, cache, entry = _seed_entry(tmp_path)
    with np.load(entry) as z:
        payload = {n: z[n] for n in z.files if n != "__check__"}
    from galah_tpu.io import atomic

    atomic.write_npz(str(entry), payload)
    back = cache.load(str(fasta), "x", {})
    np.testing.assert_array_equal(back["a"], np.arange(8))


def test_stale_tmp_debris_swept_on_open(tmp_path):
    import os
    import time as _time

    cachedir = tmp_path / "cache"
    cachedir.mkdir()
    stale = cachedir / "x-deadbeef.npz.123.tmp"
    stale.write_bytes(b"half-written entry")
    os.utime(stale, (1, 1))  # older than the shared-dir age gate
    fresh = cachedir / "y-cafef00d.npz.456.tmp"
    fresh.write_bytes(b"maybe a live concurrent writer")
    diskcache.CacheDir(str(cachedir))
    assert not stale.exists()
    assert fresh.exists()  # age gate: young .tmp left alone


def test_reserved_check_key_rejected(tmp_path):
    fasta = tmp_path / "g.fna"
    _write_fasta(str(fasta), "ACGT" * 50)
    cache = diskcache.CacheDir(str(tmp_path / "cache"))
    import pytest

    with pytest.raises(ValueError, match="reserved"):
        cache.store(str(fasta), "x", {},
                    {"__check__": np.zeros(1), "a": np.zeros(1)})


def test_crash_during_put_leaves_no_entry(tmp_path):
    """A writer killed mid-store (GALAH_FI kill inside the atomic
    write) must leave no entry under the final name — the next run
    misses and recomputes instead of loading a torn file."""
    import subprocess
    import sys

    fasta = tmp_path / "g.fna"
    _write_fasta(str(fasta), "ACGT" * 500)
    cachedir = tmp_path / "cache"
    code = (
        "import numpy as np\n"
        "from galah_tpu.io import diskcache\n"
        f"cache = diskcache.CacheDir({str(cachedir)!r})\n"
        f"cache.store({str(fasta)!r}, 'x', {{}},\n"
        "            {'a': np.arange(8, dtype=np.uint64)})\n"
    )
    env = dict(os.environ)
    env["GALAH_FI"] = "site=io.atomic.write[cache.x];kind=kill;prob=1"
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run([sys.executable, "-c", code], env=env,
                          capture_output=True, timeout=120)
    assert proc.returncode == 137, proc.stderr.decode()
    assert list(cachedir.glob("*.npz")) == []  # nothing committed
    cache = diskcache.CacheDir(str(cachedir))
    assert cache.load(str(fasta), "x", {}) is None  # clean miss
    cache.store(str(fasta), "x", {},
                {"a": np.arange(8, dtype=np.uint64)})
    back = cache.load(str(fasta), "x", {})
    np.testing.assert_array_equal(back["a"], np.arange(8))
