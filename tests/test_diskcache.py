"""Persistent sketch/profile cache (io/diskcache.py).

The reference re-sketches every genome on every run (SURVEY.md §5 notes
no checkpoint/caching subsystem exists); the cache must be a pure
speedup — identical results, keyed on file identity, invalidated when
the FASTA changes.
"""

import shutil

import numpy as np

from galah_tpu.backends.fragment_backend import ProfileStore
from galah_tpu.backends.minhash_backend import SketchStore
from galah_tpu.io import diskcache


def _write_fasta(path, seq):
    with open(path, "w") as f:
        f.write(">c1\n")
        f.write(seq + "\n")


def test_cachedir_roundtrip(tmp_path):
    fasta = tmp_path / "g.fna"
    _write_fasta(str(fasta), "ACGT" * 500)
    cache = diskcache.CacheDir(str(tmp_path / "cache"))
    params = {"k": 21, "seed": 0}
    assert cache.load(str(fasta), "x", params) is None
    arrays = {"a": np.arange(5, dtype=np.uint64),
              "b": np.ones((2, 3), dtype=np.uint8)}
    cache.store(str(fasta), "x", params, arrays)
    back = cache.load(str(fasta), "x", params)
    np.testing.assert_array_equal(back["a"], arrays["a"])
    np.testing.assert_array_equal(back["b"], arrays["b"])
    # different params -> different entry
    assert cache.load(str(fasta), "x", {"k": 15, "seed": 0}) is None


def test_cache_invalidated_on_file_change(tmp_path):
    fasta = tmp_path / "g.fna"
    _write_fasta(str(fasta), "ACGT" * 500)
    cache = diskcache.CacheDir(str(tmp_path / "cache"))
    cache.store(str(fasta), "x", {}, {"a": np.zeros(1)})
    assert cache.load(str(fasta), "x", {}) is not None
    # rewrite with different content (size changes)
    _write_fasta(str(fasta), "ACGTA" * 500)
    assert cache.load(str(fasta), "x", {}) is None


def test_disabled_cache_is_noop(tmp_path):
    fasta = tmp_path / "g.fna"
    _write_fasta(str(fasta), "ACGT" * 50)
    cache = diskcache.CacheDir(None)
    cache.store(str(fasta), "x", {}, {"a": np.zeros(1)})
    assert cache.load(str(fasta), "x", {}) is None


def test_sketchstore_cache_identical_sketches(tmp_path, ref_data):
    src = str(ref_data / "set1" / "500kb.fna")
    fasta = str(tmp_path / "500kb.fna")
    shutil.copy(src, fasta)
    cache = diskcache.CacheDir(str(tmp_path / "cache"))

    s1 = SketchStore(sketch_size=1000, k=21, cache=cache).get(fasta)
    assert cache.misses == 1 and cache.hits == 0
    # fresh store, same cache dir: must hit and return identical hashes
    s2 = SketchStore(sketch_size=1000, k=21, cache=cache).get(fasta)
    assert cache.hits == 1
    np.testing.assert_array_equal(s1.hashes, s2.hashes)


def test_profilestore_cache_identical_profiles(tmp_path, ref_data):
    src = str(ref_data / "set1" / "500kb.fna")
    fasta = str(tmp_path / "500kb.fna")
    shutil.copy(src, fasta)
    cache = diskcache.CacheDir(str(tmp_path / "cache"))

    p1 = ProfileStore(k=15, fraglen=3000, cache=cache).get(fasta)
    p2 = ProfileStore(k=15, fraglen=3000, cache=cache).get(fasta)
    assert cache.hits == 1
    np.testing.assert_array_equal(p1.flat_hashes, p2.flat_hashes)
    np.testing.assert_array_equal(p1.ref_set, p2.ref_set)
    np.testing.assert_array_equal(p1.markers, p2.markers)
