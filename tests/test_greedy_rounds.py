"""Round-based device greedy selection (ops/greedy_select.py).

The device strategy must be DECISION-IDENTICAL to the host scan: same
representatives, same memberships, ties to the lowest index, on every
workload — speculative rounds and the jitted window fold change only
when ANIs are computed, never what is decided. These tests pin that
parity on the planted-family rung shape, the dense single-family worst
case, and a seeded conflict window that forces the host-order
fallback, plus the round-granular checkpoint replay.
"""

import json
from typing import List, Optional, Sequence

import numpy as np
import pytest

from galah_tpu.backends.base import ClusterBackend, PreclusterBackend
from galah_tpu.cluster import cluster
from galah_tpu.cluster.cache import PairDistanceCache
from galah_tpu.cluster.checkpoint import ClusterCheckpoint, run_fingerprint
from galah_tpu.utils import timing


class TablePre(PreclusterBackend):
    def __init__(self, pairs):
        self.pairs = pairs

    def method_name(self):
        return "stub-pre"

    def distances(self, genome_paths):
        cache = PairDistanceCache()
        for (i, j), ani in self.pairs.items():
            cache.insert((i, j), ani)
        return cache


class TableCl(ClusterBackend):
    """Exact ANI from a lookup table; absent pairs are gated (None)."""

    def __init__(self, table, threshold, fail_on_call=None):
        self.table = {frozenset(k): v for k, v in table.items()}
        self.threshold = threshold
        self.calls: List[list] = []
        self.pairs_computed: List[tuple] = []
        self.fail_on_call = fail_on_call

    def method_name(self):
        return "stub-exact"

    @property
    def ani_threshold(self):
        return self.threshold

    def calculate_ani_batch(
            self, pairs: Sequence[tuple]) -> List[Optional[float]]:
        self.calls.append(list(pairs))
        if (self.fail_on_call is not None
                and len(self.calls) >= self.fail_on_call):
            raise RuntimeError("injected backend failure")
        self.pairs_computed.extend(pairs)
        return [self.table.get(frozenset(p)) for p in pairs]


def g(n):
    return [f"g{i}.fna" for i in range(n)]


def _family_workload(n_families, fam_size, seed, none_rate=0.05,
                     thr=0.95):
    """Planted families with randomized exact ANIs straddling the
    threshold (and a few gated-None pairs), the stub twin of the bench
    ladder's e2e rung shape."""
    rng = np.random.default_rng(seed)
    pre, table = {}, {}
    for f in range(n_families):
        base = f * fam_size
        for a in range(fam_size):
            for b in range(a + 1, fam_size):
                i, j = base + a, base + b
                pre[(i, j)] = 0.96
                if rng.random() < none_rate:
                    table[(f"g{i}.fna", f"g{j}.fna")] = None
                else:
                    table[(f"g{i}.fna", f"g{j}.fna")] = round(
                        float(rng.uniform(thr - 0.05, thr + 0.04)), 6)
    return pre, table


def _run(monkeypatch, strategy, n, pre, table, thr=0.95, **kw):
    monkeypatch.setenv("GALAH_TPU_GREEDY_STRATEGY", strategy)
    return cluster(g(n), TablePre(pre), TableCl(table, thr), **kw)


def test_planted_families_1000_parity(monkeypatch):
    """Golden-cluster equality on the 1000-genome rung shape: 250
    families x 4, randomized near-threshold ANIs with gated pairs."""
    pre, table = _family_workload(250, 4, seed=11)
    host = _run(monkeypatch, "host", 1000, pre, table)
    dev = _run(monkeypatch, "device", 1000, pre, table)
    assert dev == host


def test_dense_single_family_parity(monkeypatch):
    """The mega-family worst case: ONE precluster bigger than
    DENSE_PRECLUSTER_CAP with every pair a hit, ANIs straddling the
    threshold so rep chains and argmax ties both occur."""
    rng = np.random.default_rng(3)
    n = 96
    pre, table = {}, {}
    for i in range(n):
        for j in range(i + 1, n):
            pre[(i, j)] = 0.96
            table[(f"g{i}.fna", f"g{j}.fna")] = round(
                float(rng.uniform(0.90, 0.99)), 6)
    host = _run(monkeypatch, "host", n, pre, table)
    dev = _run(monkeypatch, "device", n, pre, table)
    assert dev == host


def test_randomized_sparse_parity_sweep(monkeypatch):
    """Fuzz across precluster topologies: random hit graphs (not just
    cliques), random sizes, 10% gated pairs."""
    for seed in range(8):
        rng = np.random.default_rng(100 + seed)
        n = int(rng.integers(5, 40))
        pre, table = {}, {}
        for i in range(n):
            for j in range(i + 1, n):
                if rng.random() < 0.35:
                    pre[(i, j)] = 0.96
                    table[(f"g{i}.fna", f"g{j}.fna")] = (
                        None if rng.random() < 0.10
                        else round(float(rng.uniform(0.88, 0.99)), 6))
        host = _run(monkeypatch, "host", n, pre, table)
        dev = _run(monkeypatch, "device", n, pre, table)
        assert dev == host, f"seed {seed}"


def test_rep_rounds_width_invariance(monkeypatch):
    """The round width K changes batching only — every width yields
    the host clustering."""
    pre, table = _family_workload(6, 4, seed=7)
    host = _run(monkeypatch, "host", 24, pre, table)
    for width in (1, 2, 3, 7, 64):
        dev = _run(monkeypatch, "device", 24, pre, table,
                   rep_rounds=width)
        assert dev == host, f"rep_rounds={width}"


def test_seeded_conflict_window_falls_back(monkeypatch):
    """A precluster whose rep chain is deeper than MAX_SUBROUNDS (every
    pair sub-threshold -> every genome its own rep) must be counted as
    a conflict window, finish on the host-order scan, and still match
    the host clustering."""
    n = 40  # one precluster, chain depth 40 > MAX_SUBROUNDS (16)
    pre, table = {}, {}
    for i in range(n):
        for j in range(i + 1, n):
            pre[(i, j)] = 0.96
            table[(f"g{i}.fna", f"g{j}.fna")] = 0.90  # all below thr
    host = _run(monkeypatch, "host", n, pre, table)
    before = timing.GLOBAL.counters()
    dev = _run(monkeypatch, "device", n, pre, table)
    after = timing.GLOBAL.counters()
    assert dev == host
    assert after.get("greedy-conflict-windows", 0) > before.get(
        "greedy-conflict-windows", 0)
    assert after.get("greedy-host-fallback-windows", 0) > before.get(
        "greedy-host-fallback-windows", 0)


def test_device_strategy_counter_and_rounds(monkeypatch):
    pre, table = _family_workload(4, 4, seed=5)
    before = timing.GLOBAL.counters()
    _run(monkeypatch, "device", 16, pre, table)
    after = timing.GLOBAL.counters()
    assert after.get("greedy-strategy-device", 0) == before.get(
        "greedy-strategy-device", 0) + 1
    assert after.get("greedy-rounds", 0) > before.get(
        "greedy-rounds", 0)


def test_interrupted_device_run_replays_rounds(monkeypatch, tmp_path):
    """Round-granular resume: a run that dies mid-selection replays the
    already-saved round ANIs from greedy_rounds.jsonl instead of
    recomputing them, and finishes with the uninterrupted clustering.
    Each backend-computed pair is paid for exactly once across both
    runs."""
    pre, table = _family_workload(10, 4, seed=9, none_rate=0.0)
    n = 40
    ref = _run(monkeypatch, "device", n, pre, table, rep_rounds=6)
    ref_cl = TableCl(table, 0.95)
    monkeypatch.setenv("GALAH_TPU_GREEDY_STRATEGY", "device")
    cluster(g(n), TablePre(pre), ref_cl)  # count of a full run's pairs
    n_total = len(ref_cl.pairs_computed)

    fp = run_fingerprint(g(n), "stub-pre", "stub-exact", 0.95, 0.9)
    ck1 = ClusterCheckpoint(str(tmp_path / "ck"), fp)
    cl1 = TableCl(table, 0.95, fail_on_call=4)
    with pytest.raises(RuntimeError, match="injected backend failure"):
        # explicit device pin: the injected failure must propagate,
        # not demote to a host run that would finish the clustering
        cluster(g(n), TablePre(pre), cl1, checkpoint=ck1,
                rep_rounds=6)
    assert (tmp_path / "ck" / "greedy_rounds.jsonl").exists()
    assert len(cl1.pairs_computed) > 0

    before = timing.GLOBAL.counters()
    ck2 = ClusterCheckpoint(str(tmp_path / "ck"), fp)
    cl2 = TableCl(table, 0.95)
    out = cluster(g(n), TablePre(pre), cl2, checkpoint=ck2,
                  rep_rounds=6)
    after = timing.GLOBAL.counters()
    assert out == ref
    replayed = after.get("greedy-replayed-pairs", 0) - before.get(
        "greedy-replayed-pairs", 0)
    assert replayed > 0
    # no pair is recomputed: run1's saved rounds + run2's delta cover
    # the full run exactly (run1 pairs past the last completed round
    # were lost with the crash and are legitimately recomputed)
    assert len(set(map(frozenset, cl2.pairs_computed))
               | set(map(frozenset, cl1.pairs_computed))) == n_total
    assert len(cl2.pairs_computed) < n_total
    # a finished device run clears the round log
    assert not (tmp_path / "ck" / "greedy_rounds.jsonl").exists()


def test_greedy_round_log_torn_tail_tolerated(tmp_path):
    fp = run_fingerprint(["a", "b"], "p", "c", 0.95, 0.9)
    ck = ClusterCheckpoint(str(tmp_path / "ck"), fp)
    ck.save_greedy_round("d1", [(0, 1, 0.97), (1, 2, None)])
    path = tmp_path / "ck" / "greedy_rounds.jsonl"
    with open(path, "a") as fh:
        fh.write('{"digest": "d1", "pairs": [[3, 4, 0.9')  # torn write
    back = ClusterCheckpoint(str(tmp_path / "ck"), fp)
    assert back.load_greedy_rounds("d1") == [(0, 1, 0.97), (1, 2, None)]
    assert back.load_greedy_rounds("other") == []
    # the log is digest-scoped: records for a different pending set
    # are ignored, not replayed
    with open(path) as fh:
        rows = [json.loads(line) for line in fh if line.strip()
                and line.strip().endswith("}")]
    assert all(r["digest"] == "d1" for r in rows)


def test_window_select_matches_host_fold():
    """Unit pin of the jitted fold on a hand-built window: 0 is a rep,
    1 joins it, 2 fails the gate against 1's CLUSTER but 1 is a member
    (not a rep) so 2 becomes a rep, 3 joins 2."""
    from galah_tpu.ops import greedy_select

    nan = float("nan")
    thr = 0.95
    ani = np.array([
        [nan, 0.97, nan, 0.90],
        [nan, nan, 0.96, nan],
        [nan, nan, nan, 0.98],
        [nan, nan, nan, nan],
    ], dtype=np.float64)
    ext = np.zeros(4, dtype=bool)
    rep, converged = greedy_select.window_select(ani, ext, thr)
    assert converged
    assert rep.tolist() == [True, False, True, False]


def test_window_select_ext_members_never_rep():
    """A window genome with an over-threshold ANI to an EXISTING rep
    (ext flag) is a member regardless of intra-window edges."""
    from galah_tpu.ops import greedy_select

    nan = float("nan")
    ani = np.array([[nan, 0.99], [nan, nan]], dtype=np.float64)
    ext = np.array([True, False])
    rep, converged = greedy_select.window_select(ani, ext, 0.95)
    assert converged
    # 0 joins its existing rep; 1's only edge is to non-rep 0 -> rep
    assert rep.tolist() == [False, True]


def test_membership_argmax_ties_and_gaps():
    from galah_tpu.ops import greedy_select

    nan = float("nan")
    ani = np.array([
        [0.97, 0.97, 0.90],   # tie -> lowest rep index (argmax first)
        [nan, 0.91, 0.96],    # gated against rep 0
        [nan, nan, nan],      # no candidate at all
    ], dtype=np.float64)
    best, has = greedy_select.membership_argmax(ani)
    assert best.tolist()[:2] == [0, 2]
    assert has.tolist() == [True, True, False]


def test_resolve_strategy_env(monkeypatch):
    from galah_tpu.ops.greedy_select import resolve_greedy_strategy

    monkeypatch.delenv("GALAH_TPU_GREEDY_STRATEGY", raising=False)
    assert resolve_greedy_strategy() == ("device", False)
    monkeypatch.setenv("GALAH_TPU_GREEDY_STRATEGY", "host")
    assert resolve_greedy_strategy() == ("host", True)
    monkeypatch.setenv("GALAH_TPU_GREEDY_STRATEGY", "DEVICE")
    assert resolve_greedy_strategy() == ("device", True)
    monkeypatch.setenv("GALAH_TPU_GREEDY_STRATEGY", "bogus")
    assert resolve_greedy_strategy() == ("device", False)
