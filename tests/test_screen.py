"""Blocked marker-containment screening (ops/pairwise.screen_pairs).

The skani-equivalent candidate screen (reference: src/skani.rs:54-70)
must (a) match a straightforward numpy reference on containment
semantics, (b) agree between the single-device and column-sharded
implementations, and (c) issue ONE device dispatch per row block —
the O((N/tile)^2)-dispatch host loop it replaced is the pattern the
round-1 review flagged as latency-bound.
"""

import numpy as np
import pytest

from galah_tpu.ops.constants import SENTINEL
from galah_tpu.ops import pairwise
from galah_tpu.parallel import make_mesh
from galah_tpu.parallel.mesh import sharded_screen_pairs


def _marker_fixture(n=50, m=128, seed=5):
    """Random sorted marker rows with planted high-containment pairs."""
    rng = np.random.default_rng(seed)
    mat = np.full((n, m), np.uint64(SENTINEL), dtype=np.uint64)
    counts = np.zeros(n, dtype=np.int64)
    for i in range(n):
        cnt = int(rng.integers(m // 2, m))
        vals = rng.choice(1 << 20, size=cnt, replace=False).astype(
            np.uint64) * 7919
        mat[i, :cnt] = np.sort(vals)
        counts[i] = cnt
    # plant: 12 subset of 3 (full containment), 30 shares 90% with 8
    sub = mat[3, :counts[3] // 2].copy()
    mat[12] = np.uint64(SENTINEL)
    mat[12, :sub.shape[0]] = sub
    counts[12] = sub.shape[0]
    take = int(counts[8] * 0.9)
    shared = mat[8, :take]
    extra = (np.arange(counts[30] - take, dtype=np.uint64) * 7919 + 3)
    row = np.sort(np.concatenate([shared, extra]))
    mat[30] = np.uint64(SENTINEL)
    mat[30, :row.shape[0]] = row
    return mat, counts


def _numpy_screen(mat, counts, c_floor):
    n = mat.shape[0]
    out = []
    for i in range(n):
        a = mat[i, :counts[i]]
        for j in range(i + 1, n):
            b = mat[j, :counts[j]]
            inter = np.intersect1d(a, b).shape[0]
            denom = min(counts[i], counts[j])
            if denom > 0 and inter >= c_floor * denom:
                out.append((i, j))
    return out


@pytest.mark.parametrize("c_floor", [0.5, 0.8**15])
def test_screen_pairs_matches_numpy(c_floor):
    mat, counts = _marker_fixture()
    got = pairwise.screen_pairs(mat, counts, c_floor, row_tile=16,
                                col_tile=32, mesh=make_mesh(1))
    assert got == _numpy_screen(mat, counts, c_floor)
    assert (3, 12) in got  # planted full-containment pair


def test_sharded_screen_pairs_matches_single_device():
    mat, counts = _marker_fixture(n=70, seed=9)
    c_floor = 0.6
    ref = pairwise.screen_pairs(mat, counts, c_floor, row_tile=16,
                                col_tile=32, mesh=make_mesh(1))
    got = sharded_screen_pairs(mat, counts, c_floor, mesh=make_mesh(8),
                               row_tile=16, col_tile=32)
    assert got == ref


def test_screen_dispatch_count_scales_with_row_blocks(monkeypatch):
    """One device dispatch per row block: N=128 rows at row_tile=32 must
    issue exactly 4 dispatches (not the 16+ a per-tile loop would)."""
    calls = []
    real = pairwise._rowblock_screen

    def counting(*args, **kwargs):
        calls.append(1)
        return real(*args, **kwargs)

    monkeypatch.setattr(pairwise, "_rowblock_screen", counting)
    mat, counts = _marker_fixture(n=128, seed=2)
    pairwise.screen_pairs(mat, counts, 0.8, row_tile=32, col_tile=32,
                          mesh=make_mesh(1))
    assert len(calls) == 128 // 32


@pytest.mark.slow
def test_skani_preclusterer_uses_blocked_screen(ref_data):
    """The backend end-to-end: screening via the blocked path still finds
    the known closely-related abisko4 MAG pairs."""
    from galah_tpu.backends.fragment_backend import SkaniPreclusterer

    paths = [
        str(ref_data / "abisko4" / n) for n in (
            "73.20120800_S1X.13.fna",
            "73.20120600_S2D.19.fna",
            "73.20120700_S3X.12.fna",
            "73.20110800_S2D.13.fna",
        )
    ]
    pre = SkaniPreclusterer(threshold=0.95, min_aligned_fraction=0.15)
    cache = pre.distances(paths)
    # the 95%-ANI golden cluster [[0,1,3],[2]] implies 0-1, 0-3, 1-3 hits
    assert cache.contains((0, 1))
    assert cache.contains((0, 3))
    assert cache.contains((1, 3))


def test_screen_pairs_pallas_interpret_matches_xla(monkeypatch):
    """screen_pairs with the Mosaic intersect kernel (interpret mode on
    the CPU mesh) must equal the XLA searchsorted path exactly."""
    import galah_tpu.ops.pallas_pairwise as pp

    orig = pp.tile_intersect_pallas
    monkeypatch.setattr(
        pp, "tile_intersect_pallas",
        lambda rows, cols, interpret=False: orig(rows, cols,
                                                 interpret=True))
    mat, counts = _marker_fixture(n=60, seed=13)
    via_pallas = pairwise.screen_pairs(
        mat, counts, 0.6, row_tile=16, col_tile=32,
        mesh=make_mesh(1), use_pallas=True)
    via_xla = pairwise.screen_pairs(
        mat, counts, 0.6, row_tile=16, col_tile=32,
        mesh=make_mesh(1), use_pallas=False)
    assert via_pallas == via_xla


def test_sparse_marker_screen_matches_dense():
    """The CPU inverted-index marker screen returns exactly the tiled
    XLA screen's pairs on family-structured marker sets (runs in a
    single-device subprocess; the suite itself holds 8 devices)."""
    import os
    import subprocess
    import sys

    code = r"""
import os
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from galah_tpu.ops.constants import SENTINEL
from galah_tpu.ops.pairwise import screen_pairs

assert jax.device_count() == 1
rng = np.random.default_rng(51)
n, m_width = 1100, 64
n_fam = 90
base = rng.integers(0, 1 << 62, size=(n_fam, m_width), dtype=np.uint64)
mat = np.full((n, m_width), np.uint64(SENTINEL), dtype=np.uint64)
counts = np.zeros(n, dtype=np.int64)
for i in range(n):
    fam = i % n_fam
    cnt = int(rng.integers(20, m_width))
    row = base[fam, :cnt].copy()
    n_mut = int(rng.integers(0, 10))
    idx = rng.choice(cnt, size=n_mut, replace=False)
    row[idx] = rng.integers(0, 1 << 62, size=n_mut, dtype=np.uint64)
    mat[i, :cnt] = np.sort(row)
    counts[i] = cnt
mat[5] = np.uint64(SENTINEL)   # zero-marker genome
counts[5] = 0

sparse = screen_pairs(mat, counts, 0.8 ** 15)
os.environ["GALAH_TPU_DENSE_PAIRS"] = "1"
dense = screen_pairs(mat, counts, 0.8 ** 15)
assert sorted(sparse) == sorted(dense), (
    len(sparse), len(dense),
    set(map(tuple, sparse)) ^ set(map(tuple, dense)))
assert len(dense) > 100
assert not any(5 in p for p in dense)  # zero-marker genome never pairs
print("OK")
"""
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run([sys.executable, "-c", code],
                          capture_output=True, text=True, timeout=300,
                          cwd=repo, env=env)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "OK" in proc.stdout
