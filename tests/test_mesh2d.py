"""2D-tiled all-pairs mesh (GALAH_TPU_MESH_SHAPE) and the HLL
cardinality-bucketed precluster (GALAH_TPU_HLL_BUCKETS).

The contract under test is bit-identity: the 2D tiled pair pass must
return exactly the host / 1-D pair set for every mesh geometry, the
upper-triangle tile schedule must cover each i<j cell exactly once,
and the cardinality-band prefilter must never prune a pair the full
pass would emit — including pairs planted exactly at the threshold
with adversarial cardinality skew."""

import numpy as np
import pytest

from galah_tpu.obs import events as obs_events
from galah_tpu.obs import metrics as obs_metrics
from galah_tpu.ops.bucketing import (assign_bands, band_width,
                                     bucketed_threshold_pairs,
                                     bucketing_engaged)
from galah_tpu.ops.pairwise import threshold_pairs
from galah_tpu.parallel.mesh import (_dcn_crossings, auto_mesh,
                                     make_mesh, make_mesh_2d,
                                     mesh_is_2d, resolve_mesh_shape,
                                     sharded_hll_threshold_pairs,
                                     sharded_screen_pairs,
                                     sharded_stripe_stats,
                                     sharded_threshold_pairs)


def _sketches(n, k, seed=0, planted=((4, 10), (4, 33), (5, 77))):
    rng = np.random.default_rng(seed)
    mat = rng.integers(0, 1 << 62, size=(n, k), dtype=np.uint64)
    for src, dst in planted:
        mat[dst] = mat[src]
    mat.sort(axis=1)
    return mat


# -- mesh shape resolution -------------------------------------------


def test_resolve_auto_squarest(monkeypatch):
    monkeypatch.setenv("GALAH_TPU_MESH_SHAPE", "auto")
    assert resolve_mesh_shape(8) == (2, 4)
    assert resolve_mesh_shape(16) == (4, 4)
    assert resolve_mesh_shape(12) == (3, 4)
    assert resolve_mesh_shape(1) is None


def test_resolve_explicit_and_1d(monkeypatch):
    monkeypatch.setenv("GALAH_TPU_MESH_SHAPE", "4x2")
    assert resolve_mesh_shape(8) == (4, 2)
    monkeypatch.setenv("GALAH_TPU_MESH_SHAPE", "1d")
    assert resolve_mesh_shape(8) is None


def test_resolve_prime_demotes_with_event(monkeypatch):
    monkeypatch.setenv("GALAH_TPU_MESH_SHAPE", "auto")
    obs_events.reset()
    assert resolve_mesh_shape(7) is None
    demoted = [e for e in obs_events.snapshot()
               if e["kind"] == "mesh-demoted"]
    assert len(demoted) == 1 and demoted[0]["n_devices"] == 7


def test_resolve_bad_shape_demotes_with_event(monkeypatch):
    obs_events.reset()
    for raw in ("3x3", "banana", "0x8"):
        monkeypatch.setenv("GALAH_TPU_MESH_SHAPE", raw)
        assert resolve_mesh_shape(8) is None
    demoted = [e for e in obs_events.snapshot()
               if e["kind"] == "mesh-demoted"]
    assert [e["shape"] for e in demoted] == ["3x3", "banana", "0x8"]


def test_auto_mesh_is_2d_on_8_devices(monkeypatch):
    monkeypatch.setenv("GALAH_TPU_MESH_SHAPE", "auto")
    mesh = auto_mesh()
    assert mesh_is_2d(mesh) and mesh.devices.shape == (2, 4)
    monkeypatch.setenv("GALAH_TPU_MESH_SHAPE", "1d")
    assert not mesh_is_2d(auto_mesh())


# -- upper-triangle tile schedule audit ------------------------------


@pytest.mark.parametrize("r,c,n", [(2, 4, 100), (4, 2, 64), (1, 8, 50),
                                   (2, 2, 37)])
def test_tile_schedule_covers_upper_triangle_exactly_once(r, c, n):
    """Replay the 2D schedule's skip rule (tile computed iff its global
    column tile gt >= t_first, the diagonal tile of the row block) and
    check every i<j lattice cell lands in exactly one computed tile."""
    import math

    row_tile, col_tile = 16, 32
    quantum = math.lcm(r * row_tile, c * col_tile)
    n_pad = -(-n // quantum) * quantum
    rows_per_dev, cols_per_dev = n_pad // r, n_pad // c
    tiles_per_chunk = cols_per_dev // col_tile
    cover = np.zeros((n, n), dtype=np.int64)
    for mi in range(r):
        for lb in range(0, min(rows_per_dev, n), row_tile):
            r0 = mi * rows_per_dev + lb
            t_first = r0 // col_tile
            for mj in range(c):
                col0 = mj * cols_per_dev
                for t in range(tiles_per_chunk):
                    gt = col0 // col_tile + t
                    if gt < t_first:
                        continue  # the skipped lower-triangle tile
                    c0 = gt * col_tile
                    for gi in range(r0, min(r0 + row_tile, n)):
                        for gj in range(max(c0, gi + 1),
                                        min(c0 + col_tile, n)):
                            cover[gi, gj] += 1
    iu = np.triu_indices(n, k=1)
    assert cover[iu].min() == 1 and cover[iu].max() == 1


# -- 2D pair-pass parity ---------------------------------------------


@pytest.mark.parametrize("shape", [(1, 1), (1, 8), (2, 4), (4, 2)])
def test_threshold_pairs_2d_matches_host_and_1d(shape):
    mat = _sketches(100, 64, seed=1)
    host = threshold_pairs(mat, k=21, min_ani=0.9)
    ref = sharded_threshold_pairs(mat, 21, 0.9, make_mesh(8),
                                  row_tile=16, col_tile=32,
                                  use_pallas=False)
    got = sharded_threshold_pairs(mat, 21, 0.9, make_mesh_2d(shape),
                                  row_tile=16, col_tile=32,
                                  use_pallas=False)
    assert host == ref == got
    assert {(4, 10), (4, 33), (10, 33), (5, 77)} <= set(got)


def test_stripe_stats_2d_matches_1d():
    from galah_tpu.ops.constants import SENTINEL

    rng = np.random.default_rng(5)
    rows = np.sort(rng.integers(0, 1 << 62, size=(96, 64),
                                dtype=np.uint64), axis=1)
    cols = np.concatenate([rows[:16], np.full((16, 64), SENTINEL,
                                              dtype=np.uint64)])
    ref_c, ref_t = sharded_stripe_stats(rows, cols, 64, 21,
                                        make_mesh(8), row_tile=16,
                                        r_pad=128)
    got_c, got_t = sharded_stripe_stats(rows, cols, 64, 21,
                                        make_mesh_2d((2, 4)),
                                        row_tile=16, r_pad=128)
    np.testing.assert_array_equal(np.asarray(ref_c), np.asarray(got_c))
    np.testing.assert_array_equal(np.asarray(ref_t), np.asarray(got_t))


def test_screen_pairs_2d_matches_1d():
    rng = np.random.default_rng(6)
    marker = rng.random((60, 256)) < 0.05
    marker[7] = marker[3]
    counts = marker.sum(axis=1).astype(np.int32)
    ref = sharded_screen_pairs(marker, counts, 0.6, make_mesh(8),
                               row_tile=16, col_tile=32,
                               cap_per_row=64, use_pallas=False)
    got = sharded_screen_pairs(marker, counts, 0.6, make_mesh_2d((2, 4)),
                               row_tile=16, col_tile=32,
                               cap_per_row=64, use_pallas=False)
    assert sorted(ref) == sorted(got) and (3, 7) in got


def test_hll_threshold_pairs_2d_matches_1d():
    rng = np.random.default_rng(7)
    regs = rng.integers(0, 30, size=(64, 4096), dtype=np.uint8)
    regs[11] = regs[2]
    ref = sharded_hll_threshold_pairs(regs, 21, 0.95, make_mesh(8),
                                      row_tile=16, col_tile=32,
                                      cap_per_row=32)
    got = sharded_hll_threshold_pairs(regs, 21, 0.95,
                                      make_mesh_2d((2, 4)),
                                      row_tile=16, col_tile=32,
                                      cap_per_row=32)
    assert ref == got and (2, 11) in got


def test_dcn_gauge_2d_below_sqrt_bound():
    """Acceptance bound: per-row interconnect bytes on the 2x4 mesh
    must be <= 2*sqrt(8)/8 of the 1-D mesh's."""
    mat = _sketches(64, 64, seed=8, planted=((4, 10),))
    sharded_threshold_pairs(mat, 21, 0.9, make_mesh(8), row_tile=16,
                            col_tile=32, use_pallas=False)
    one_d = obs_metrics.snapshot()["mesh.dcn_bytes_per_row"]["value"]
    sharded_threshold_pairs(mat, 21, 0.9, make_mesh_2d((2, 4)),
                            row_tile=16, col_tile=32, use_pallas=False)
    two_d = obs_metrics.snapshot()["mesh.dcn_bytes_per_row"]["value"]
    assert two_d / one_d <= 2.0 * np.sqrt(8.0) / 8.0
    assert _dcn_crossings(make_mesh_2d((2, 4))) == 4
    assert _dcn_crossings(make_mesh(8)) == 7


# -- HLL cardinality bucketing ---------------------------------------


def _skewed_corpus(n=240, size=1024, seed=9, n_planted=4):
    """Random sketches with log-uniform cardinalities 1e3..1e8 and
    planted near-duplicate pairs whose cardinalities sit at the band
    boundary (worst-case skew the filter must tolerate)."""
    rng = np.random.default_rng(seed)
    mat = np.sort(rng.integers(0, 1 << 62, size=(n, size),
                               dtype=np.uint64), axis=1)
    cards = np.exp(rng.uniform(np.log(1e3), np.log(1e8), size=n))
    planted = []
    for i in range(n_planted):
        a, b = 2 * i, n - 1 - 2 * i
        mat[b] = mat[a].copy()
        mat[b, :40] = rng.integers(0, 1 << 62, size=40,
                                   dtype=np.uint64)
        mat[b] = np.sort(mat[b])
        # adversarial skew: put the twin right at the admissible edge
        cards[b] = cards[a] * 1.2
        planted.append((min(a, b), max(a, b)))
    return mat, cards, planted


def test_bucketed_pairs_bit_identical_with_pruning():
    mat, cards, planted = _skewed_corpus()
    ref = threshold_pairs(mat, k=21, min_ani=0.95)
    got = bucketed_threshold_pairs(mat, cards, k=21, min_ani=0.95)
    assert got == ref
    assert set(planted) <= set(got)
    snap = obs_metrics.snapshot()
    assert snap["precluster.bucket_count"]["value"] > 1
    # acceptance: >= 30% of the lattice pruned on the skewed corpus
    assert snap["precluster.bucket_pruned_fraction"]["value"] >= 0.30
    evs = [e for e in obs_events.snapshot()
           if e["kind"] == "hll-buckets"]
    assert evs and evs[-1]["pruned"] > 0


def test_boundary_pairs_never_pruned_across_band_offsets():
    """Pairs planted at every band-boundary offset (cardinality ratios
    sweeping the full admissible range) must always land within one
    band of each other."""
    width = band_width(0.95, 21, 12, 1024)
    assert np.isfinite(width)
    base = 1e5
    for frac in (0.999, 0.5, 0.01):
        ratio = np.exp(width * frac)
        cards = np.array([base, base * ratio])
        bands = assign_bands(cards, 0.95, 21, 12, 1024)
        assert abs(int(bands[1]) - int(bands[0])) <= 1, frac


def test_degenerate_margin_single_band_still_exact():
    """Tiny sketches: the MinHash margin swallows the threshold, the
    band width goes infinite, everything lands in band 0 — zero
    pruning, still the exact pair set."""
    assert band_width(0.9, 21, 12, 128) == np.inf
    mat = _sketches(80, 128, seed=11)
    cards = np.exp(np.random.default_rng(11).uniform(
        np.log(1e3), np.log(1e8), size=80))
    bands = assign_bands(cards, 0.9, 21, 12, 128)
    assert np.all(bands == 0)
    ref = threshold_pairs(mat, k=21, min_ani=0.9)
    assert bucketed_threshold_pairs(mat, cards, k=21, min_ani=0.9) \
        == ref


def test_bucketing_engaged_flag(monkeypatch):
    monkeypatch.setenv("GALAH_TPU_HLL_BUCKETS", "0")
    assert not bucketing_engaged(10 ** 9)
    monkeypatch.setenv("GALAH_TPU_HLL_BUCKETS", "1")
    assert bucketing_engaged(2) and not bucketing_engaged(1)
    monkeypatch.setenv("GALAH_TPU_HLL_BUCKETS", "auto")
    monkeypatch.setenv("GALAH_TPU_SPARSE_MIN_N", "100")
    assert bucketing_engaged(100) and not bucketing_engaged(99)
