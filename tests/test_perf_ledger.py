"""Cross-run perf ledger tests (galah_tpu/obs/ledger.py + `perf` CLI).

Covers the JSONL record/history/check round-trip, the median±MAD
verdict taxonomy (regression, improvement, drift, insufficient
history), torn-tail recovery after a mid-append crash, key isolation
across device topologies, report-driven entry construction, and the
jax-free `galah-tpu perf` subcommand including the --soft CI mode.
No accelerator work: everything here is file I/O and arithmetic.
"""

import json

import pytest

from galah_tpu.obs import ledger


def _entry(value, *, n_devices=1, backend="cpu", metric="run.duration_s",
           ts=0.0, sha="abc1234", extra=None):
    metrics = {metric: value}
    if extra:
        metrics.update(extra)
    return {
        "v": ledger.LEDGER_VERSION, "ts": ts, "sha": sha,
        "key": {"backend": backend, "device_kind": backend,
                "n_devices": n_devices,
                "workload": {"n": 100, "k": 1000, "p": None},
                "strategy": "auto/auto/auto", "source": "test"},
        "metrics": metrics,
    }


# -- file format ------------------------------------------------------


def test_append_read_roundtrip(tmp_path):
    path = str(tmp_path / "ledger.jsonl")
    for i in range(4):
        ledger.append(path, _entry(10.0 + i, ts=float(i)))
    entries, skipped = ledger.read(path)
    assert skipped == 0
    assert [e["metrics"]["run.duration_s"] for e in entries] == \
        [10.0, 11.0, 12.0, 13.0]
    # every line is one complete checksum-framed JSON object
    # (<compact-json>\t<crc32hex>, the io/atomic.py append framing)
    from galah_tpu.io import atomic

    with open(path) as fh:
        for line in fh:
            payload, sep, _crc = line.rstrip("\n").rpartition(
                atomic.FRAME_SEP)
            assert sep == atomic.FRAME_SEP
            assert isinstance(json.loads(payload), dict)


def test_read_missing_file_is_empty_ledger(tmp_path):
    entries, skipped = ledger.read(str(tmp_path / "absent.jsonl"))
    assert entries == [] and skipped == 0


def test_torn_tail_and_junk_lines_recovered(tmp_path):
    path = str(tmp_path / "ledger.jsonl")
    for i in range(3):
        ledger.append(path, _entry(5.0 + i))
    with open(path, "a") as fh:
        fh.write('{"v": 1, "ts": 99, "key": {}, "metri')  # torn append
    with open(path, "a") as fh:
        fh.write('\n[1, 2, 3]\n')      # parseable but not an entry
        fh.write('{"no_metrics": 1}\n')
    entries, skipped = ledger.read(path)
    assert len(entries) == 3
    assert skipped == 3
    # and the ledger is still appendable after the tear
    ledger.append(path, _entry(8.0))
    entries, skipped = ledger.read(path)
    assert len(entries) == 4 and skipped == 3


def test_append_keeps_newline_values_on_one_line(tmp_path):
    # json.dumps escapes control characters, so a newline inside a
    # value must still serialize to exactly one physical line
    path = str(tmp_path / "l.jsonl")
    entry = _entry(1.0)
    entry["note"] = "line one\nline two"
    ledger.append(path, entry)
    with open(path) as fh:
        lines = fh.readlines()
    assert len(lines) == 1
    entries, skipped = ledger.read(path)
    assert skipped == 0
    assert entries[0]["note"] == "line one\nline two"


# -- direction inference ---------------------------------------------


def test_metric_direction_families():
    assert ledger.metric_direction("bench.pairs_per_sec") == "higher"
    assert ledger.metric_direction("cache.hit_rate") == "higher"
    assert ledger.metric_direction("run.duration_s") == "lower"
    assert ledger.metric_direction(
        "profile.hbm_peak_bytes") == "lower"
    assert ledger.metric_direction("bench.errors") == "lower"
    assert ledger.metric_direction("funnel.kept") == "neutral"
    # exact-name overrides: host-blame share is the megakernel's
    # headline gauge — "share" matches no substring family, but host
    # orchestration migrating back up is a regression
    assert ledger.metric_direction("flow.host.share") == "lower"
    assert ledger.metric_direction("flow.host.blame_s") == "lower"
    assert ledger.metric_direction(
        "bench.megakernel_host_share") == "lower"
    # the per-stage blame shares stay neutral (blame moving between
    # stages is drift to look at, not a regression by itself)
    assert ledger.metric_direction("flow.pairs.share") == "neutral"


# -- check(): verdict taxonomy ---------------------------------------


def test_check_ok_on_unchanged_history():
    hist = [_entry(10.0, ts=float(i)) for i in range(5)]
    verdicts = ledger.check(hist, _entry(10.0))
    assert [v["verdict"] for v in verdicts] == ["ok"]


def test_check_regression_and_improvement_lower_better():
    hist = [_entry(10.0 + 0.01 * i, ts=float(i)) for i in range(6)]
    worse = ledger.check(hist, _entry(20.0))
    assert worse[0]["verdict"] == "regression"
    better = ledger.check(hist, _entry(5.0))
    assert better[0]["verdict"] == "improvement"
    assert ledger.regressions(worse) and not ledger.regressions(better)


def test_check_regression_higher_better_flips():
    m = "bench.production_pairs_per_sec"
    hist = [_entry(1000.0, metric=m, ts=float(i)) for i in range(5)]
    assert ledger.check(hist, _entry(500.0, metric=m))[0][
        "verdict"] == "regression"
    assert ledger.check(hist, _entry(2000.0, metric=m))[0][
        "verdict"] == "improvement"


def test_check_neutral_metric_drifts_but_never_gates():
    m = "funnel.kept"
    hist = [_entry(40.0, metric=m, ts=float(i)) for i in range(5)]
    verdicts = ledger.check(hist, _entry(400.0, metric=m))
    assert verdicts[0]["verdict"] == "drift"
    assert ledger.regressions(verdicts) == []


def test_check_insufficient_history():
    hist = [_entry(10.0), _entry(11.0)]  # below MIN_HISTORY
    verdicts = ledger.check(hist, _entry(99.0))
    assert verdicts[0]["verdict"] == "insufficient-history"
    assert verdicts[0]["band"] is None
    assert ledger.regressions(verdicts) == []


def test_check_mad_floor_tolerates_epsilon_on_flat_history():
    # identical history => MAD 0; the 1%-of-median floor must keep a
    # tiny wobble inside the band instead of calling it a regression
    hist = [_entry(100.0, ts=float(i)) for i in range(5)]
    verdicts = ledger.check(hist, _entry(100.5))
    assert verdicts[0]["verdict"] == "ok"
    verdicts = ledger.check(hist, _entry(102.0))
    assert verdicts[0]["verdict"] == "regression"


def test_check_window_limits_history():
    old = [_entry(100.0, ts=float(i)) for i in range(10)]
    recent = [_entry(10.0, ts=float(10 + i)) for i in range(8)]
    verdicts = ledger.check(old + recent, _entry(10.0), window=8)
    assert verdicts[0]["verdict"] == "ok"  # old regime aged out


def test_check_key_isolation_across_topologies():
    # 1-device history must not gate an 8-device run, and vice versa
    hist = ([_entry(10.0, n_devices=1, ts=float(i)) for i in range(5)]
            + [_entry(50.0, n_devices=8, ts=float(i)) for i in range(5)])
    v1 = ledger.check(hist, _entry(10.0, n_devices=1))
    v8 = ledger.check(hist, _entry(50.0, n_devices=8))
    assert v1[0]["verdict"] == "ok" and v8[0]["verdict"] == "ok"
    # 8-device band applied to the 1-device value would regress; the
    # key split is what keeps it ok
    cross = ledger.check(hist, _entry(50.0, n_devices=1))
    assert cross[0]["verdict"] == "regression"
    few = ledger.check(hist, _entry(1.0, backend="tpu"))
    assert few[0]["verdict"] == "insufficient-history"


# -- entries from run reports ----------------------------------------


def _report(duration=12.0, n=256, extra_metrics=None):
    rep = {
        "version": 5,
        "run": {"subcommand": "cluster", "duration_s": duration},
        "device": {"backend": "cpu", "device_count": 1,
                   "devices": [{"device_kind": "cpu"}]},
        "flags": {"GALAH_TPU_PAIRLIST_BLOCK": {"value": "8"},
                  "GALAH_TPU_GREEDY_STRATEGY": {"value": "device"}},
        "metrics": {"workload.n_genomes": {"value": n},
                    "workload.sketch_k": {"value": 1000}},
        "stages": {"tree": [
            {"name": "precluster-distances", "total_s": 7.5,
             "children": [{"name": "sketch", "total_s": 3.0}]},
            {"name": "greedy-cluster", "total_s": 4.0},
        ]},
        "dispatch": {"total_dispatches": 42, "total_syncs": 2},
        "device_costs": {
            "profiling_enabled": True,
            "entries": {"pairwise.tile_stats_pallas": {
                "calls": 5, "signatures": 1,
                "dispatch_wall_s": 1.25, "compile_wall_s": 0.5}},
            "hbm": {"peak_bytes": 1 << 20, "source": "live_arrays",
                    "per_stage": {}},
            "peaks": None,
        },
    }
    if extra_metrics:
        rep["metrics"].update(extra_metrics)
    return rep


def test_entry_from_report_key_and_metrics():
    entry = ledger.entry_from_report(_report(), "cluster", ts=1.0,
                                     sha="deadbee")
    key = entry["key"]
    assert key["backend"] == "cpu" and key["n_devices"] == 1
    assert key["workload"] == {"n": 256, "k": 1000, "p": 8}
    # pairlist / fragment / greedy / sketch / overlap / mesh-shape
    # pins, in order
    assert key["strategy"] == "auto/auto/device/auto/auto/auto"
    assert key["source"] == "cluster"
    m = entry["metrics"]
    assert m["run.duration_s"] == 12.0
    assert m["stage.precluster-distances_s"] == 7.5
    assert m["stage.precluster-distances/sketch_s"] == 3.0
    assert m["dispatch.total_dispatches"] == 42.0
    assert m["profile.pairwise.tile_stats_pallas.dispatch_wall_s"] \
        == 1.25
    assert m["profile.hbm_peak_bytes"] == float(1 << 20)
    # the sha is recorded but NOT part of the comparison key
    assert "deadbee" not in ledger.key_of(entry)


def test_workload_fingerprint_nulls_when_unsaid():
    rep = _report()
    rep["metrics"] = {}
    rep["flags"] = {}
    assert ledger.workload_fingerprint(rep) == \
        {"n": None, "k": None, "p": None}


def test_record_report_never_raises(tmp_path, caplog):
    # an unwritable path must log, not crash the finalizing run
    bad_path = str(tmp_path / "dir")
    (tmp_path / "dir").mkdir()
    assert ledger.record_report(bad_path, _report(), "cluster") is False
    ok_path = str(tmp_path / "ok.jsonl")
    assert ledger.record_report(ok_path, _report(), "cluster") is True
    entries, _ = ledger.read(ok_path)
    assert len(entries) == 1


# -- `galah-tpu perf` subcommand (jax-free) --------------------------


def _cli(tmp_path):
    from galah_tpu.cli import main
    return main


def _write_report(tmp_path, name, duration):
    p = tmp_path / name
    p.write_text(json.dumps(_report(duration=duration)))
    return str(p)


def test_perf_record_history_check_roundtrip(tmp_path, capsys):
    main = _cli(tmp_path)
    led = str(tmp_path / "ledger.jsonl")
    for i, dur in enumerate((10.0, 10.2, 9.9, 10.1)):
        rp = _write_report(tmp_path, f"r{i}.json", dur)
        assert main(["perf", "--ledger", led, "record", rp,
                     "--source", "cluster"]) == 0
    capsys.readouterr()

    assert main(["perf", "--ledger", led, "history",
                 "run.duration_s"]) == 0
    out = capsys.readouterr().out
    assert out.count("\n") == 4  # one row per entry
    assert "10.2" in out

    # unchanged rerun: newest vs the prior three => all ok, exit 0
    assert main(["perf", "--ledger", led, "check"]) == 0
    out = capsys.readouterr().out
    assert "regression=0" not in out  # no regression bucket at all
    assert "ok=" in out


def test_perf_check_gates_on_seeded_regression(tmp_path, capsys):
    main = _cli(tmp_path)
    led = str(tmp_path / "ledger.jsonl")
    for i, dur in enumerate((10.0, 10.2, 9.9, 10.1)):
        rp = _write_report(tmp_path, f"r{i}.json", dur)
        assert main(["perf", "--ledger", led, "record", rp,
                     "--source", "cluster"]) == 0
    slow = _write_report(tmp_path, "slow.json", 30.0)
    # --report checks without appending
    assert main(["perf", "--ledger", led, "check", "--report", slow,
                 "--source", "cluster"]) == 1
    out = capsys.readouterr().out
    assert "REGRESSION: run.duration_s" in out
    entries, _ = ledger.read(led)
    assert len(entries) == 4  # check --report appended nothing
    # --soft reports but exits 0 (the CI mode)
    assert main(["perf", "--ledger", led, "check", "--report", slow,
                 "--source", "cluster", "--soft"]) == 0
    assert "not gated" in capsys.readouterr().out


def test_perf_check_empty_and_missing_ledger(tmp_path, capsys):
    main = _cli(tmp_path)
    led = str(tmp_path / "never_written.jsonl")
    assert main(["perf", "--ledger", led, "check"]) == 0
    assert "empty" in capsys.readouterr().out
    # no ledger anywhere => error exit, not a crash
    assert main(["perf", "check"]) == 1


def test_perf_record_rejects_bad_report(tmp_path):
    main = _cli(tmp_path)
    led = str(tmp_path / "ledger.jsonl")
    bad = tmp_path / "bad.json"
    bad.write_text("{not json")
    assert main(["perf", "--ledger", led, "record", str(bad)]) == 1
    assert main(["perf", "--ledger", led, "record",
                 str(tmp_path / "missing.json")]) == 1


def test_finalize_feeds_ledger_when_flag_set(tmp_path, monkeypatch):
    from galah_tpu import obs
    from galah_tpu.utils import timing

    led = str(tmp_path / "auto.jsonl")
    monkeypatch.setenv("GALAH_OBS_LEDGER", led)
    timing.reset()
    obs.reset_run()
    with timing.stage("precluster-distances"):
        timing.dispatch(1)
    out = obs.finalize("cluster", started_at=0.0)
    assert out is not None
    entries, skipped = ledger.read(led)
    assert skipped == 0 and len(entries) == 1
    assert entries[0]["key"]["source"] == "cluster"
    assert "stage.precluster-distances_s" in entries[0]["metrics"]
