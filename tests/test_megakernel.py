"""Device-resident megakernel (ops/device_queue.py + ops/megakernel.py).

The contract under test: with GALAH_TPU_MEGAKERNEL engaged, a slab of
consecutive greedy round windows resolves through the on-device pair
queue and ONE fused fold program — and the clustering is BIT-IDENTICAL
to the per-window dense fold on every workload, at every queue
capacity (overflow spills to the exact dense path, never half-runs).
These tests pin the queue invariants (compaction, bounded exact
overflow, pow2 bucketing), the fused fold's decision parity with
window_select, the auto/0/1 engagement-and-demotion matrix, the
dispatch-count win, and round-granular crash resume under the pin.
"""

from typing import List, Optional, Sequence

import numpy as np
import pytest

from galah_tpu.backends.base import ClusterBackend, PreclusterBackend
from galah_tpu.cluster import cluster
from galah_tpu.cluster.cache import PairDistanceCache
from galah_tpu.cluster.checkpoint import ClusterCheckpoint, run_fingerprint
from galah_tpu.ops import device_queue, megakernel
from galah_tpu.utils import timing


class TablePre(PreclusterBackend):
    def __init__(self, pairs):
        self.pairs = pairs

    def method_name(self):
        return "stub-pre"

    def distances(self, genome_paths):
        cache = PairDistanceCache()
        for (i, j), ani in self.pairs.items():
            cache.insert((i, j), ani)
        return cache


class StreamTablePre(TablePre):
    """Blockwise streamed pair pass (same contract as
    tests/test_overlap.py::StreamTablePre)."""

    def __init__(self, pairs, n, block=7):
        super().__init__(pairs)
        self.n = n
        self.block = block

    def distances_streamed(self, genome_paths):
        by_row = {}
        for (i, j), ani in self.pairs.items():
            by_row.setdefault(max(i, j), {})[(i, j)] = ani

        def gen():
            r1 = 0
            while r1 < self.n:
                r0, r1 = r1, min(r1 + self.block, self.n)
                inc = {}
                for r in range(r0, r1):
                    inc.update(by_row.get(r, {}))
                yield r1, inc

        return gen()


class TableCl(ClusterBackend):
    def __init__(self, table, threshold, fail_on_call=None):
        self.table = {frozenset(k): v for k, v in table.items()}
        self.threshold = threshold
        self.calls: List[list] = []
        self.pairs_computed: List[tuple] = []
        self.fail_on_call = fail_on_call

    def method_name(self):
        return "stub-exact"

    @property
    def ani_threshold(self):
        return self.threshold

    def calculate_ani_batch(
            self, pairs: Sequence[tuple]) -> List[Optional[float]]:
        self.calls.append(list(pairs))
        if (self.fail_on_call is not None
                and len(self.calls) >= self.fail_on_call):
            raise RuntimeError("injected backend failure")
        self.pairs_computed.extend(pairs)
        return [self.table.get(frozenset(p)) for p in pairs]


def g(n):
    return [f"g{i}.fna" for i in range(n)]


def _family_workload(n_families, fam_size, seed, none_rate=0.05,
                     thr=0.95):
    rng = np.random.default_rng(seed)
    pre, table = {}, {}
    for f in range(n_families):
        base = f * fam_size
        for a in range(fam_size):
            for b in range(a + 1, fam_size):
                i, j = base + a, base + b
                pre[(i, j)] = 0.96
                if rng.random() < none_rate:
                    table[(f"g{i}.fna", f"g{j}.fna")] = None
                else:
                    table[(f"g{i}.fna", f"g{j}.fna")] = round(
                        float(rng.uniform(thr - 0.05, thr + 0.04)), 6)
    return pre, table


def _reference(monkeypatch, n, pre, table, thr=0.95, **kw):
    """The independent baseline: stage-serial device rounds, no mega."""
    monkeypatch.setenv("GALAH_TPU_GREEDY_STRATEGY", "device")
    monkeypatch.setenv("GALAH_TPU_OVERLAP", "0")
    monkeypatch.setenv("GALAH_TPU_MEGAKERNEL", "0")
    return cluster(g(n), TablePre(pre), TableCl(table, thr), **kw)


def _overlapped(monkeypatch, n, pre, table, mega, thr=0.95, block=7,
                cap=None, cl=None, **kw):
    monkeypatch.setenv("GALAH_TPU_GREEDY_STRATEGY", "device")
    monkeypatch.setenv("GALAH_TPU_OVERLAP", "1")
    monkeypatch.setenv("GALAH_TPU_MEGAKERNEL", mega)
    if cap is not None:
        monkeypatch.setenv("GALAH_TPU_QUEUE_CAP", str(cap))
    return cluster(g(n), StreamTablePre(pre, n, block=block),
                   cl or TableCl(table, thr), **kw)


def _counter(name):
    return timing.GLOBAL.counters().get(name, 0)


# ---------------------------------------------------------------------------
# PairQueue unit lattice
# ---------------------------------------------------------------------------


def test_queue_enqueue_drain_roundtrip():
    q = device_queue.PairQueue(cap=32)
    i = np.array([0, 1, 2], dtype=np.int32)
    j = np.array([3, 4, 5], dtype=np.int32)
    v = np.array([0.97, 0.91, 0.955], dtype=np.float64)
    assert q.enqueue(i, j, v) == 3
    assert q.count == 3 and q.overflow == 0
    oi, oj, ov = q.drain()
    np.testing.assert_array_equal(oi, i)
    np.testing.assert_array_equal(oj, j)
    # stored verbatim: the exact IEEE bits, no transform
    np.testing.assert_array_equal(ov, v)
    assert q.count == 0  # drain resets


def test_queue_batches_compact_to_dense_prefix():
    q = device_queue.PairQueue(cap=16)
    q.enqueue(np.array([0, 1]), np.array([2, 3]),
              np.array([0.9, 0.91]))
    q.enqueue(np.array([4, 5, 6]), np.array([7, 8, 9]),
              np.array([0.92, 0.93, 0.94]))
    assert q.count == 5
    oi, oj, ov = q.drain()
    np.testing.assert_array_equal(oi, [0, 1, 4, 5, 6])
    np.testing.assert_array_equal(oj, [2, 3, 7, 8, 9])
    np.testing.assert_array_equal(ov, [0.9, 0.91, 0.92, 0.93, 0.94])


def test_queue_overflow_is_bounded_and_exact():
    q = device_queue.PairQueue(cap=8)
    assert q.cap == 8
    i = np.arange(12, dtype=np.int32)
    stored = q.enqueue(i, i + 100, i.astype(np.float64) / 100.0)
    # the prefix that fits is stored, the rest counted — never dropped
    # silently
    assert stored == 8
    assert q.count == 8 and q.overflow == 4
    oi, _, ov = q.drain()
    np.testing.assert_array_equal(oi, np.arange(8))
    np.testing.assert_array_equal(ov, np.arange(8) / 100.0)
    # overflow is cumulative per run; reset(clear_overflow) zeroes it
    assert q.overflow == 4
    q.reset(clear_overflow=True)
    assert q.overflow == 0
    # the queue is reusable after overflow + reset
    assert q.enqueue(np.array([1]), np.array([2]),
                     np.array([0.99])) == 1
    assert q.count == 1 and q.overflow == 0


def test_queue_empty_drain_and_pow2_cap():
    q = device_queue.PairQueue(cap=5)
    assert q.cap == 8  # pow2-rounded, floor _MIN_CAP
    oi, oj, ov = q.drain()
    assert len(oi) == len(oj) == len(ov) == 0
    assert q.enqueue(np.array([], dtype=np.int32),
                     np.array([], dtype=np.int32),
                     np.array([], dtype=np.float64)) == 0


def test_resolve_queue_cap_parsing(monkeypatch):
    monkeypatch.delenv("GALAH_TPU_QUEUE_CAP", raising=False)
    assert device_queue.resolve_queue_cap() == 4096
    monkeypatch.setenv("GALAH_TPU_QUEUE_CAP", "1000")
    assert device_queue.resolve_queue_cap() == 1024  # pow2-rounded
    monkeypatch.setenv("GALAH_TPU_QUEUE_CAP", "8")
    assert device_queue.resolve_queue_cap() == 8
    monkeypatch.setenv("GALAH_TPU_QUEUE_CAP", "3")
    assert device_queue.resolve_queue_cap() == 8  # floor
    for bad in ("0", "-16", "many"):
        monkeypatch.setenv("GALAH_TPU_QUEUE_CAP", bad)
        assert device_queue.resolve_queue_cap() == 4096


# ---------------------------------------------------------------------------
# Fused slab fold: unit parity with the dense window fold
# ---------------------------------------------------------------------------


def test_slab_fold_matches_window_select_randomized():
    """The edge-list recurrence IS the matrix recurrence restricted to
    existing edges: same reps, same convergence flag, over random
    sparse windows with NaN-gated pairs and pre-clustered positions."""
    from galah_tpu.ops.greedy_select import window_select

    q = device_queue.PairQueue(cap=1024)
    for seed in range(25):
        rng = np.random.default_rng(1000 + seed)
        w = int(rng.integers(2, 40))
        mat = np.full((w, w), np.nan, dtype=np.float64)
        ei, ej, ev = [], [], []
        for a in range(w):
            for b in range(a + 1, w):
                if rng.random() < 0.4:
                    val = float(rng.uniform(0.9, 0.99))
                    mat[a, b] = val
                    ei.append(a)
                    ej.append(b)
                    ev.append(val)
        ext = rng.random(w) < 0.2
        dense_rep, dense_conv = window_select(mat, ext, 0.95)
        rep, conv = megakernel.slab_select(
            q, np.asarray(ei, dtype=np.int32),
            np.asarray(ej, dtype=np.int32),
            np.asarray(ev, dtype=np.float64), ext, 0.95)
        assert conv == dense_conv, f"seed {seed}"
        np.testing.assert_array_equal(rep, dense_rep,
                                      err_msg=f"seed {seed}")
        assert q.count == 0  # fold leaves the queue reset


def test_slab_select_spills_on_capacity():
    q = device_queue.PairQueue(cap=8)
    n = 12
    ei, ej = np.triu_indices(6, k=1)  # 15 edges > cap
    ev = np.full(len(ei), 0.97)
    rep, conv = megakernel.slab_select(
        q, ei.astype(np.int32)[:n], ej.astype(np.int32)[:n],
        ev[:n], np.zeros(6, dtype=bool), 0.95)
    assert rep is None and conv is False
    assert q.count == 0  # spill leaves the queue clean for reuse


def test_resolve_megakernel_modes(monkeypatch):
    monkeypatch.delenv("GALAH_TPU_MEGAKERNEL", raising=False)
    assert megakernel.resolve_megakernel() == ("auto", False)
    for mode in ("auto", "0", "1"):
        monkeypatch.setenv("GALAH_TPU_MEGAKERNEL", mode)
        assert megakernel.resolve_megakernel() == (mode, True)
    monkeypatch.setenv("GALAH_TPU_MEGAKERNEL", "always")
    assert megakernel.resolve_megakernel() == ("auto", False)


def test_megakernel_flags_registered():
    from galah_tpu import config

    mk = config.FLAGS["GALAH_TPU_MEGAKERNEL"]
    assert mk.default == "auto"
    assert set(mk.choices) == {"auto", "0", "1"}
    assert "GALAH_TPU_QUEUE_CAP" in config.FLAGS


# ---------------------------------------------------------------------------
# Bit-identity: end-to-end clusterings
# ---------------------------------------------------------------------------


def test_megakernel_planted_families_1000_parity(monkeypatch):
    """Golden-cluster equality on the 1000-genome rung shape, and the
    slab-fold counter proves the fused path actually ran."""
    pre, table = _family_workload(250, 4, seed=11)
    ref = _reference(monkeypatch, 1000, pre, table)
    before = _counter("megakernel-slab-folds")
    out = _overlapped(monkeypatch, 1000, pre, table, mega="auto",
                      block=64)
    assert out == ref
    assert _counter("megakernel-slab-folds") > before


def test_megakernel_dense_96_parity(monkeypatch):
    """The mega-family worst case: ONE precluster, every pair
    materialized — 4560 edges need an explicit capacity raise, and the
    decisions must still match exactly."""
    rng = np.random.default_rng(3)
    n = 96
    pre, table = {}, {}
    for i in range(n):
        for j in range(i + 1, n):
            pre[(i, j)] = 0.96
            table[(f"g{i}.fna", f"g{j}.fna")] = round(
                float(rng.uniform(0.90, 0.99)), 6)
    ref = _reference(monkeypatch, n, pre, table)
    out = _overlapped(monkeypatch, n, pre, table, mega="auto",
                      block=16, cap=8192, rep_rounds=16)
    assert out == ref


def test_megakernel_capacity_block_width_sweep(monkeypatch):
    """Exactness at ANY capacity: a queue too small for a slab spills
    that slab to the dense path, so every (cap, block, width) cell
    yields the reference clustering. cap=8 forces spills and the
    counter proves the spill path ran."""
    pre, table = _family_workload(12, 4, seed=21)
    n = 48
    ref = _reference(monkeypatch, n, pre, table)
    for cap in (8, 256, 4096):
        for block in (5, 48):
            for width in (4, 16):
                out = _overlapped(monkeypatch, n, pre, table,
                                  mega="auto", block=block, cap=cap,
                                  rep_rounds=width)
                assert out == ref, \
                    f"cap={cap} block={block} rep_rounds={width}"
    before = _counter("megakernel-overflow-spills")
    out = _overlapped(monkeypatch, n, pre, table, mega="auto",
                      block=48, cap=8, rep_rounds=16)
    assert out == ref
    assert _counter("megakernel-overflow-spills") > before


def test_megakernel_dispatch_reduction_at_least_4x(monkeypatch):
    """The acceptance ratio: fused slabs cut greedy-select dispatches
    (enqueue + fold per slab vs one fold per window) by >= 4x on the
    rung shape at full slab fusion."""
    pre, table = _family_workload(64, 4, seed=33)
    n = 256
    ref = _reference(monkeypatch, n, pre, table)
    b0 = _counter("greedy-select-dispatches")
    out_off = _overlapped(monkeypatch, n, pre, table, mega="0",
                          block=n, rep_rounds=4)
    d_off = _counter("greedy-select-dispatches") - b0
    b1 = _counter("greedy-select-dispatches")
    out_on = _overlapped(monkeypatch, n, pre, table, mega="auto",
                         block=n, rep_rounds=4)
    d_on = _counter("greedy-select-dispatches") - b1
    assert out_off == ref and out_on == ref
    assert d_on > 0
    assert d_off / d_on >= 4, (d_off, d_on)


# ---------------------------------------------------------------------------
# auto / 0 / 1: engagement, demotion, pinned-failure propagation
# ---------------------------------------------------------------------------


def test_megakernel_off_never_folds(monkeypatch):
    pre, table = _family_workload(8, 4, seed=2)
    ref = _reference(monkeypatch, 32, pre, table)
    before = _counter("megakernel-slab-folds")
    out = _overlapped(monkeypatch, 32, pre, table, mega="0")
    assert out == ref
    assert _counter("megakernel-slab-folds") == before


def test_megakernel_pin_requires_device_strategy(monkeypatch):
    pre, table = _family_workload(4, 4, seed=2)
    monkeypatch.setenv("GALAH_TPU_GREEDY_STRATEGY", "host")
    monkeypatch.setenv("GALAH_TPU_MEGAKERNEL", "1")
    with pytest.raises(RuntimeError, match="device greedy strategy"):
        cluster(g(16), TablePre(pre), TableCl(table, 0.95))


def test_megakernel_auto_demotes_on_failure(monkeypatch):
    """AUTO: a runtime failure inside the fused fold demotes to the
    per-window dense path for the run — counted, event-logged, and
    still the exact clustering."""
    pre, table = _family_workload(8, 4, seed=6)
    ref = _reference(monkeypatch, 32, pre, table)

    def boom(*a, **k):
        raise ValueError("injected fold failure")

    monkeypatch.setattr(megakernel, "slab_select", boom)
    before = _counter("megakernel-demoted")
    out = _overlapped(monkeypatch, 32, pre, table, mega="auto")
    assert out == ref
    assert _counter("megakernel-demoted") == before + 1


def test_megakernel_pin_propagates_failure(monkeypatch):
    """GALAH_TPU_MEGAKERNEL=1: the same injected failure must
    propagate, never demote — parity runs must not compare a silent
    fallback to itself."""
    pre, table = _family_workload(8, 4, seed=6)

    def boom(*a, **k):
        raise ValueError("injected fold failure")

    monkeypatch.setattr(megakernel, "slab_select", boom)
    monkeypatch.setenv("GALAH_TPU_GREEDY_STRATEGY", "device")
    monkeypatch.setenv("GALAH_TPU_OVERLAP", "1")
    monkeypatch.setenv("GALAH_TPU_MEGAKERNEL", "1")
    with pytest.raises(ValueError, match="injected fold failure"):
        cluster(g(32), StreamTablePre(pre, 32),
                TableCl(table, 0.95))


# ---------------------------------------------------------------------------
# Crash resume under the pin
# ---------------------------------------------------------------------------


def test_megakernel_pinned_crash_resume_parity(monkeypatch, tmp_path):
    """Round-granular resume with the megakernel pinned in the
    stage-serial engine: a run that dies mid-selection resumes from
    greedy_rounds.jsonl and finishes with the uninterrupted
    clustering — slab fusion changes the round cadence, not the
    durable-replay contract."""
    pre, table = _family_workload(10, 4, seed=9, none_rate=0.0)
    n = 40

    def _pin():
        monkeypatch.setenv("GALAH_TPU_GREEDY_STRATEGY", "device")
        monkeypatch.setenv("GALAH_TPU_OVERLAP", "0")
        monkeypatch.setenv("GALAH_TPU_MEGAKERNEL", "1")
        # a small queue keeps slabs narrow => several rounds to replay
        monkeypatch.setenv("GALAH_TPU_QUEUE_CAP", "16")

    ref = _reference(monkeypatch, n, pre, table, rep_rounds=4)
    _pin()
    full_cl = TableCl(table, 0.95)
    assert cluster(g(n), TablePre(pre), full_cl, rep_rounds=4) == ref
    n_calls = len(full_cl.calls)
    assert n_calls >= 2  # need a mid-run crash point

    fp = run_fingerprint(g(n), "stub-pre", "stub-exact", 0.95, 0.9)
    ck1 = ClusterCheckpoint(str(tmp_path / "ck"), fp)
    cl1 = TableCl(table, 0.95, fail_on_call=max(2, n_calls // 2))
    with pytest.raises(RuntimeError, match="injected backend failure"):
        cluster(g(n), TablePre(pre), cl1, checkpoint=ck1, rep_rounds=4)
    assert (tmp_path / "ck" / "greedy_rounds.jsonl").exists()

    before = _counter("greedy-replayed-pairs")
    ck2 = ClusterCheckpoint(str(tmp_path / "ck"), fp)
    cl2 = TableCl(table, 0.95)
    out = cluster(g(n), TablePre(pre), cl2, checkpoint=ck2,
                  rep_rounds=4)
    assert out == ref
    assert _counter("greedy-replayed-pairs") > before
    # a finished run clears the round log
    assert not (tmp_path / "ck" / "greedy_rounds.jsonl").exists()
