"""Real-TPU (Mosaic, non-interpret) kernel validation.

The CPU test mesh (conftest.py) can only exercise Pallas kernels in
interpreter mode; round-1 review correctly flagged that interpret-mode
parity says nothing about whether the kernels LOWER on hardware. This
module spawns a subprocess WITHOUT the forced-CPU environment: if a TPU
backend comes up there, the Mosaic-compiled kernels must match the XLA
reference paths bit-for-bit; if no TPU is reachable the test skips.
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_TPU_CODE = r"""
import numpy as np
import jax, jax.numpy as jnp

if jax.default_backend() != "tpu":
    print("NOTPU")
    raise SystemExit(0)

from galah_tpu.ops.pairwise import threshold_pairs, tile_stats
from galah_tpu.ops.pallas_pairwise import tile_stats_pallas
from galah_tpu.ops import hll
from galah_tpu.ops.constants import SENTINEL

rng = np.random.default_rng(3)
K = 1000
mat = rng.integers(0, 1 << 63, size=(64, K), dtype=np.uint64)
for i in range(64):
    cut = rng.integers(K // 2, K + 1)
    mat[i, cut:] = np.uint64(SENTINEL)
mat.sort(axis=1)
mat[10] = mat[4]
mat[33, :600] = mat[7, :600]
mat.sort(axis=1)

rows = jnp.asarray(mat[:32])
cols = jnp.asarray(mat[32:])
c_p, t_p = tile_stats_pallas(rows, cols, K)       # Mosaic compile
c_x, t_x = tile_stats(rows, cols, K, 21)
assert np.array_equal(np.asarray(c_p), np.asarray(c_x)), "common mismatch"
assert np.array_equal(np.asarray(t_p), np.asarray(t_x)), "total mismatch"

# end-to-end sparse extraction: auto path (pallas) vs pinned XLA
auto = threshold_pairs(mat, k=21, min_ani=0.9)
via_xla = threshold_pairs(mat, k=21, min_ani=0.9, use_pallas=False)
assert auto == via_xla, f"{len(auto)} vs {len(via_xla)} pairs"
assert (4, 10) in auto

# HLL Mosaic kernel against the XLA union stats
regs = rng.integers(0, 20, size=(32, 4096)).astype(np.uint8)
pr = jnp.asarray(np.exp2(-regs.astype(np.float32)))
from galah_tpu.ops.pallas_hll import hll_union_stats_tile
ps_p, z_p = hll_union_stats_tile(pr, pr, chunk=1024)
ps_x, z_x = hll._xla_union_stats(pr, pr)
assert np.allclose(np.asarray(ps_p), np.asarray(ps_x), rtol=1e-5)
assert np.array_equal(np.asarray(z_p), np.asarray(z_x))

# Mosaic pairlist kernel (ops/pallas_pairlist.py) lowers and matches
# the vmapped XLA pair stats bit-for-bit on gathered pairs
from galah_tpu.ops.pairwise import _pair_stats
from galah_tpu.ops.pallas_pairlist import pair_stats_pairs_pallas
pr = rng.integers(0, 64, size=200)
pc = rng.integers(0, 64, size=200)
pa, pb = jnp.asarray(mat[pr]), jnp.asarray(mat[pc])
gc_, gt_ = pair_stats_pairs_pallas(pa, pb, K)
wc_, wt_ = jax.vmap(lambda a, b: _pair_stats(a, b, K))(pa, pb)
assert np.array_equal(np.asarray(gc_), np.asarray(wc_)), "pairlist common"
assert np.array_equal(np.asarray(gt_), np.asarray(wt_)), "pairlist total"

# range_skip variant: pl.when-guarded chunk windows + scratch refs are
# a distinct Mosaic lowering surface (the round-3 session proved
# interpret parity cannot stand in for it)
sc_, st_ = pair_stats_pairs_pallas(pa, pb, K, range_skip=True)
assert np.array_equal(np.asarray(sc_), np.asarray(wc_)), "skip common"
assert np.array_equal(np.asarray(st_), np.asarray(wt_)), "skip total"

# Mosaic murmur3 state machine (ops/pallas_sketch.py) lowers and
# matches the XLA u64-emulated hash core bit-for-bit
from galah_tpu.ops.hashing import _murmur3_k21_1d
from galah_tpu.ops.pallas_sketch import murmur3_k21_pallas
n = 70000  # > one 512x128 block, forces a multi-program grid
kw = [jnp.asarray(rng.integers(0, 1 << 64, size=n, dtype=np.uint64))
      for _ in range(3)]
cb = [(kw[0] >> jnp.uint64(8 * b)) & jnp.uint64(0xFF) for b in range(8)]
cb += [(kw[1] >> jnp.uint64(8 * b)) & jnp.uint64(0xFF) for b in range(8)]
cb += [(kw[2] >> jnp.uint64(8 * b)) & jnp.uint64(0xFF) for b in range(5)]
want = np.asarray(_murmur3_k21_1d(cb, 0))
got = np.asarray(murmur3_k21_pallas(kw[0], kw[1], kw[2], seed=0))
assert np.array_equal(got, want), "mosaic murmur mismatch"
print("TPUOK")
"""


@pytest.mark.slow  # its wedged-tunnel probe alone can wait 420 s; the
# watcher (scripts/tpu_validation_run.sh) runs it with GALAH_RUN_SLOW=1
def test_mosaic_kernels_on_tpu_hardware():
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    # Bounded probe first: skip fast when the tunnel is wedged, but give
    # the real validation generous room — it performs several fresh
    # Mosaic + XLA compiles, each slow through the remote-compile tunnel.
    try:
        probe = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.default_backend())"],
            capture_output=True, text=True, timeout=420, env=env,
            cwd=REPO)
    except subprocess.TimeoutExpired:
        pytest.skip("TPU backend probe timed out (tunnel down?)")
    if probe.returncode != 0:
        pytest.fail("backend probe crashed rc="
                    f"{probe.returncode}: {probe.stderr[-1000:]}")
    if "tpu" not in probe.stdout:
        pytest.skip("no TPU backend available")
    try:
        proc = subprocess.run(
            [sys.executable, "-c", _TPU_CODE], capture_output=True,
            text=True, timeout=1800, env=env, cwd=REPO)
    except subprocess.TimeoutExpired:
        pytest.skip("TPU kernel validation exceeded its time budget "
                    "(remote compile backlog?)")
    if "NOTPU" in proc.stdout:
        pytest.skip("no TPU backend available")
    assert proc.returncode == 0, (
        f"TPU kernel validation failed rc={proc.returncode}\n"
        f"stdout:{proc.stdout}\nstderr:{proc.stderr[-3000:]}")
    assert "TPUOK" in proc.stdout
