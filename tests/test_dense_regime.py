"""Dense-similarity worst case: one mega-family where NOTHING screens
out.

Every measured rung before round 4 used planted families where ~all
pairs screen out — the regime the sparse screen's headline depends on.
The reference's own advertised strength is the opposite regime: "many
closely related genomes (>95% ANI)" (reference: README.md:18-26).
These tests pin the screened paths in that regime: all N sketches are
light mutations of ONE base, so the collision screen's mega-run dedup
(csrc/collision.c big-run logic) carries ~N^2/2 candidates, and the
result must still be bit-identical to the dense evaluation with
bounded candidate volume (no blowup past the true pair count).
"""

import os
import time

import numpy as np
import pytest

from galah_tpu.ops.constants import SENTINEL


def _mega_family(n, width=64, seed=3, mutations=4):
    """All rows are near-copies of one base sketch: every pair shares
    most hashes, i.e. the dense-similarity regime."""
    rng = np.random.default_rng(seed)
    base = rng.integers(0, 1 << 62, size=width, dtype=np.uint64)
    mat = np.empty((n, width), dtype=np.uint64)
    for i in range(n):
        row = base.copy()
        n_mut = int(rng.integers(0, mutations + 1))
        idx = rng.choice(width, size=n_mut, replace=False)
        row[idx] = rng.integers(0, 1 << 62, size=n_mut,
                                dtype=np.uint64)
        row.sort()
        mat[i] = row
    return mat


def test_mega_family_sparse_equals_dense(monkeypatch):
    """Sparse screen == dense path, bit-identical, when all pairs
    survive — and the candidate list is exactly the all-pairs set,
    proving the mega-run dedup emits each pair once."""
    from galah_tpu.ops.pairwise import ani_to_jaccard
    from galah_tpu.ops.sparse_device import threshold_pairs_sparse
    from galah_tpu.ops.collision import candidate_pairs_minhash
    from galah_tpu.ops.pairwise import threshold_pairs
    from galah_tpu.utils import timing

    n = 256
    mat = _mega_family(n)
    lens = (mat != np.uint64(SENTINEL)).sum(axis=1).astype(np.int64)

    # The screen must produce each colliding pair exactly once even
    # though every hash value occurs in ~all rows (one giant run).
    j_thr = ani_to_jaccard(0.95, 21)
    pi, pj = candidate_pairs_minhash(mat, lens, j_thr, 64)
    pairs = set(zip(pi.tolist(), pj.tolist()))
    assert len(pairs) == pi.shape[0], "duplicate candidate emitted"
    assert len(pairs) == n * (n - 1) // 2, "mega-family must survive"
    assert all(a < b for a, b in pairs)

    monkeypatch.setenv("GALAH_TPU_SPARSE_MIN_N", "2")
    timing.reset()
    sparse = threshold_pairs_sparse(mat, k=21, min_ani=0.95)
    counters = timing.GLOBAL.counters()
    assert counters["screen-candidates"] == n * (n - 1) // 2
    assert counters["screen-kept-pairs"] == len(sparse)

    monkeypatch.setenv("GALAH_TPU_DENSE_PAIRS", "1")
    dense = threshold_pairs(mat, k=21, min_ani=0.95)
    assert sparse == dense
    assert len(sparse) > 0


@pytest.mark.slow
def test_mega_family_screen_bounded_at_scale():
    """Timed bound for the screen itself in the dense regime: N=2048
    (2.1M candidate pairs, every hash a 2048-long run) must complete
    the collision count + dedup in bounded wall and return the exact
    all-pairs candidate list."""
    from galah_tpu.ops.pairwise import ani_to_jaccard
    from galah_tpu.ops.collision import candidate_pairs_minhash

    n = 2048
    mat = _mega_family(n, width=64)
    lens = (mat != np.uint64(SENTINEL)).sum(axis=1).astype(np.int64)
    j_thr = ani_to_jaccard(0.95, 21)
    t0 = time.perf_counter()
    pi, pj = candidate_pairs_minhash(mat, lens, j_thr, 64)
    dt = time.perf_counter() - t0
    assert pi.shape[0] == n * (n - 1) // 2
    # one core processes the 2.1M-pair mega-run in a few seconds; 60 s
    # is the regression alarm, not the expectation
    assert dt < 60.0, f"dense-regime screen took {dt:.1f}s"


def test_mega_family_cluster_end_to_end(monkeypatch, tmp_path):
    """Tiny end-to-end mega-family through the DEFAULT skani+skani
    config: one cluster out, sparse and dense paths agree."""
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import bench
    from galah_tpu.api import generate_galah_clusterer

    paths = bench._synth_families(
        n_genomes=12, genome_len=30_000, n_families=1, mut=0.02,
        seed=5, outdir=str(tmp_path))
    values = {"ani": 95.0, "precluster_ani": 90.0,
              "min_aligned_fraction": 15.0, "fragment_length": 3000,
              "precluster_method": "skani", "cluster_method": "skani",
              "threads": 1}
    clusters = generate_galah_clusterer(paths, values).cluster()
    assert len(clusters) == 1
    assert sum(len(c) for c in clusters) == 12
