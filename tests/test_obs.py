"""Unified telemetry layer tests (galah_tpu/obs + utils/timing).

Covers the metrics registry, worker-thread stage attribution (the
dispatch-from-a-pool-thread regression), warn_once dedup, Chrome-trace
output, run-report assembly against the committed JSON Schema, the
`galah-tpu report` subcommand (render + --diff), and fault-injected
resilience events landing in the report.
"""

import json
import threading

import pytest

from galah_tpu import obs
from galah_tpu.obs import events as obs_events
from galah_tpu.obs import metrics as obs_metrics
from galah_tpu.obs import report as report_mod
from galah_tpu.obs import trace as obs_trace
from galah_tpu.utils import timing
from galah_tpu.utils.logging import reset_warn_once, warn_once


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    timing.reset()
    obs.reset_run()
    reset_warn_once()
    yield
    obs_trace.stop()
    timing.reset()
    obs.reset_run()
    reset_warn_once()


# -- metrics registry -----------------------------------------------


def test_counter_gauge_histogram_basics():
    c = obs_metrics.counter("t.count", help="h", unit="u")
    c.inc()
    c.inc(4)
    assert c.value == 5
    with pytest.raises(ValueError):
        c.inc(-1)
    g = obs_metrics.gauge("t.gauge")
    g.set(0.25)
    assert g.value == 0.25
    h = obs_metrics.histogram("t.hist", unit="s")
    for v in (1.0, 3.0):
        h.observe(v)
    h.observe(float("nan"))  # skipped, must not poison aggregates
    assert h.count == 2 and h.min == 1.0 and h.max == 3.0
    assert h.mean == 2.0
    snap = obs_metrics.snapshot()
    assert snap["t.count"] == {"kind": "counter", "unit": "u",
                               "help": "h", "value": 5}
    assert snap["t.hist"]["mean"] == 2.0


def test_registry_is_get_or_create_and_kind_checked():
    a = obs_metrics.counter("t.same")
    b = obs_metrics.counter("t.same")
    assert a is b
    with pytest.raises(TypeError):
        obs_metrics.gauge("t.same")


def test_histogram_time_context():
    h = obs_metrics.histogram("t.timer", unit="s")
    with h.time():
        pass
    assert h.count == 1 and h.min >= 0.0


def test_counter_thread_safety():
    c = obs_metrics.counter("t.mt")

    def work():
        for _ in range(1000):
            c.inc()

    threads = [threading.Thread(target=work) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == 8000


# -- worker-thread stage attribution (the regression satellite) ------


def test_dispatch_from_worker_thread_inherits_spawning_stage():
    """A pool thread with an empty thread-local stack used to count its
    dispatches under "?"; it must inherit the stage open on the
    spawning thread."""
    t = timing.StageTimer()
    done = threading.Event()

    def worker():
        t.dispatch()
        t.dispatch(sync=True)
        done.set()

    with t.stage("sketch"):
        th = threading.Thread(target=worker)
        th.start()
        assert done.wait(5)
        th.join()
    counters = t.counters()
    assert counters.get("disp[sketch]") == 1
    assert counters.get("sync[sketch]") == 1
    assert "disp[?]" not in counters


def test_dispatch_with_no_stage_anywhere_is_unattributed():
    t = timing.StageTimer()
    t.dispatch()
    assert t.counters() == {"disp[?]": 1}


def test_stage_token_adopt_passthrough():
    t = timing.StageTimer()
    results = {}

    def worker(token):
        with t.adopt(token):
            results["stage"] = t.current_stage()
            t.dispatch()

    with t.stage("outer"):
        with t.stage("inner"):
            token = t.stage_token()
            th = threading.Thread(target=worker, args=(token,))
            th.start()
            th.join()
    assert results["stage"] == "inner"
    assert t.counters().get("disp[inner]") == 1


def test_stage_tree_nests_and_accumulates():
    t = timing.StageTimer()
    with t.stage("a"):
        with t.stage("b"):
            pass
        with t.stage("b"):
            pass
    with t.stage("c"):
        pass
    tree = t.tree()
    assert [n["name"] for n in tree] == ["a", "c"]
    (a, c) = tree
    assert [ch["name"] for ch in a["children"]] == ["b"]
    assert a["children"][0]["count"] == 2
    assert c["children"] == []
    assert a["total_s"] >= a["children"][0]["total_s"]


# -- warn_once (dedup satellite) -------------------------------------


def test_warn_once_dedupes_and_counts_suppressed(caplog):
    import logging

    lg = logging.getLogger("galah_tpu.test_warn_once")
    msg = ("Since CheckM input is missing, genomes are not being "
           "ordered by quality. Instead the order of their input is "
           "being used")
    with caplog.at_level(logging.WARNING,
                         logger="galah_tpu.test_warn_once"):
        for _ in range(3):
            warn_once(lg, msg)
    emitted = [r for r in caplog.records if r.getMessage() == msg]
    assert len(emitted) == 1
    suppressed = [e for e in obs_events.snapshot()
                  if e["kind"] == "warn-once-suppressed"]
    assert len(suppressed) == 2
    assert suppressed[0]["message"] == msg


def test_warn_once_distinct_messages_both_emit(caplog):
    import logging

    lg = logging.getLogger("galah_tpu.test_warn_once2")
    with caplog.at_level(logging.WARNING,
                         logger="galah_tpu.test_warn_once2"):
        warn_once(lg, "first %s", "a")
        warn_once(lg, "second")
    assert {r.getMessage() for r in caplog.records} == {"first a",
                                                        "second"}


# -- trace recorder --------------------------------------------------


def test_trace_file_is_valid_json_with_stage_spans(tmp_path):
    path = tmp_path / "trace.json"
    obs_trace.start(str(path))
    with timing.stage("traced-stage"):
        pass
    obs_events.record("demotion", site="dispatch.test")
    obs_trace.stop()
    events = json.loads(path.read_text())
    assert isinstance(events, list)
    names = {e.get("name") for e in events}
    assert "traced-stage" in names
    assert "demotion" in names
    span = next(e for e in events if e.get("name") == "traced-stage")
    assert span["ph"] == "X" and span["dur"] >= 0
    inst = next(e for e in events if e.get("name") == "demotion")
    assert inst["ph"] == "i"
    assert inst["args"]["site"] == "dispatch.test"


def test_trace_emission_noop_when_inactive():
    # must not raise with no recorder installed
    obs_trace.emit_complete("x", 0.0, 1.0)
    obs_trace.emit_instant("y")
    assert obs_trace.active() is False


# -- run report ------------------------------------------------------


def _populate_run_state():
    with timing.stage("precluster-distances"):
        timing.dispatch(3)
        timing.dispatch(sync=True)
    with timing.stage("greedy-cluster"):
        with timing.stage("write-outputs"):
            pass
    timing.counter("screen-possible-pairs", 100)
    timing.counter("screen-candidates", 40)
    timing.counter("screen-kept-pairs", 10)
    timing.counter("exact-ani-computed", 10)
    timing.counter("exact-ani-wasted", 2)
    obs_metrics.counter("cache.hits").inc(3)
    obs_metrics.counter("cache.misses").inc(1)
    obs_metrics.histogram("ani.batch_seconds", unit="s").observe(0.5)


def test_assembled_report_is_schema_valid():
    jsonschema = pytest.importorskip("jsonschema")
    _populate_run_state()
    rep = report_mod.assemble("cluster", argv=["galah-tpu", "cluster"],
                              started_at=1.0)
    problems = report_mod.validate(rep)
    assert problems == []
    # cross-check validate() against a direct jsonschema pass
    with open(report_mod.SCHEMA_PATH) as fh:
        schema = json.load(fh)
    jsonschema.Draft7Validator(schema).validate(rep)
    assert rep["funnel"]["possible_pairs"] == 100
    assert rep["funnel"]["cache"]["hit_rate"] == 0.75
    assert rep["dispatch"]["total_dispatches"] == 3
    assert rep["dispatch"]["dispatches"][
        "precluster-distances"] == 3
    names = [n["name"] for n in rep["stages"]["tree"]]
    assert names == ["precluster-distances", "greedy-cluster"]


def test_validate_flags_broken_report():
    rep = report_mod.assemble("cluster")
    rep.pop("funnel")
    rep["version"] = 99
    problems = report_mod.validate(rep)
    assert problems  # both defects reported by the schema pass
    assert any("funnel" in p for p in problems)


def test_report_write_load_roundtrip_and_render(tmp_path):
    _populate_run_state()
    rep = report_mod.assemble("cluster", started_at=0.0)
    path = tmp_path / "run_report.json"
    report_mod.write(str(path), rep)
    loaded = report_mod.load(str(path))
    assert loaded == json.loads(json.dumps(rep))  # JSON-clean
    page = report_mod.render(loaded)
    assert "precluster funnel" in page
    assert "greedy-cluster" in page


def test_finalize_writes_validated_report(tmp_path):
    _populate_run_state()
    path = tmp_path / "report.json"
    out = obs.finalize("cluster", report_path=str(path), started_at=0.0)
    assert out is not None
    assert report_mod.validate(report_mod.load(str(path))) == []


# -- `galah-tpu report` subcommand -----------------------------------


def _write_two_reports(tmp_path):
    _populate_run_state()
    a = report_mod.assemble("cluster", started_at=0.0)
    b = json.loads(json.dumps(a))
    b["run"]["duration_s"] = a["run"]["duration_s"] + 2.0
    b["funnel"]["kept_pairs"] += 5
    b["metrics"]["cache.hits"]["value"] = 9
    pa, pb = tmp_path / "a.json", tmp_path / "b.json"
    report_mod.write(str(pa), a)
    report_mod.write(str(pb), b)
    return str(pa), str(pb)


def test_report_subcommand_renders(tmp_path, capsys):
    from galah_tpu.cli import main

    pa, _ = _write_two_reports(tmp_path)
    assert main(["report", pa]) == 0
    out = capsys.readouterr().out
    assert "galah-tpu run report" in out


def test_report_subcommand_diff_roundtrip(tmp_path, capsys):
    from galah_tpu.cli import main

    pa, pb = _write_two_reports(tmp_path)
    assert main(["report", "--diff", pa, pb]) == 0
    out = capsys.readouterr().out
    assert "(+2.00s)" in out
    assert "kept_pairs" in out and "(+5)" in out
    assert "cache.hits" in out


def test_report_subcommand_rejects_invalid(tmp_path):
    from galah_tpu.cli import main

    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"version": 1}))
    assert main(["report", str(bad)]) == 1
    missing = tmp_path / "missing.json"
    assert main(["report", str(missing)]) == 1
    pa, pb = _write_two_reports(tmp_path)
    assert main(["report", "--diff", pa]) == 1  # needs exactly two


# -- fault-injected resilience events land in the report -------------


@pytest.mark.fault_injection
def test_injected_faults_appear_in_report(monkeypatch):
    from galah_tpu.resilience import dispatch as rdispatch
    from galah_tpu.resilience import faults
    from galah_tpu.resilience.policy import RetryPolicy

    monkeypatch.setenv("GALAH_FI", "site=dispatch.ani;kind=raise")
    faults.reset()
    sup = rdispatch.DispatchSupervisor(
        RetryPolicy(max_attempts=2, base_delay=0.0, jitter=0.0))
    try:
        # make the module-level GLOBAL the one assemble() reads
        monkeypatch.setattr(rdispatch, "GLOBAL", sup)
        out = sup.run("dispatch.ani", lambda: [0.5],
                      fallback=lambda: [0.25])
        assert out == [0.25]
        rep = report_mod.assemble("cluster")
    finally:
        # env first: faults.reset() re-reads GALAH_FI, and resetting
        # with it still set would leak the injector into later tests
        monkeypatch.delenv("GALAH_FI", raising=False)
        faults.reset()
    sites = [d["site"] for d in rep["resilience"]["demotions"]]
    assert sites == ["dispatch.ani"]
    kinds = [e["kind"] for e in rep["events"]]
    assert "retry" in kinds and "demotion" in kinds
    demo = next(e for e in rep["events"] if e["kind"] == "demotion")
    assert demo["site"] == "dispatch.ani"
    assert rep["resilience"]["retries"].get("dispatch.ani") == 1
    assert report_mod.validate(rep) == []


def test_flag_snapshot_marks_env_set(monkeypatch):
    monkeypatch.setenv("GALAH_OBS_REPORT", "/tmp/r.json")
    monkeypatch.delenv("GALAH_OBS_TRACE_EVENTS", raising=False)
    snap = report_mod.flag_snapshot()
    assert snap["GALAH_OBS_REPORT"]["set"] is True
    assert snap["GALAH_OBS_REPORT"]["value"] == "/tmp/r.json"
    assert snap["GALAH_OBS_TRACE_EVENTS"]["set"] is False
    assert snap["GALAH_OBS_TRACE_EVENTS"]["section"] == "observability"


# -- schema v3: device_costs section ---------------------------------


def test_report_v3_carries_populated_device_costs():
    """A run that dispatched through a @profiled entry point must land
    cost/wall numbers in the report's device_costs section — the
    section the perf ledger reads its profile.* metrics from."""
    jsonschema = pytest.importorskip("jsonschema")
    import jax.numpy as jnp

    from galah_tpu.obs import profile as obs_profile
    from galah_tpu.obs.profile import profiled

    import jax

    fn = profiled("test.v3_entry")(jax.jit(lambda x: x * 2.0 + 1.0))
    with timing.stage("precluster-distances"):
        for _ in range(3):
            fn(jnp.ones((8, 8), jnp.float32))
    rep = report_mod.assemble("cluster", started_at=0.0)
    assert rep["version"] == 10
    dc = rep["device_costs"]
    assert dc["profiling_enabled"] is True
    entry = dc["entries"]["test.v3_entry"]
    assert entry["calls"] == 3
    assert entry["signatures"] == 1
    assert entry["flops"] > 0
    assert dc["hbm"]["peak_bytes"] > 0
    assert dc["hbm"]["source"] in ("memory_stats", "live_arrays")
    assert report_mod.validate(rep) == []
    with open(report_mod.SCHEMA_PATH) as fh:
        jsonschema.Draft7Validator(json.load(fh)).validate(rep)
    page = report_mod.render(rep)
    assert "device costs" in page
    assert "test.v3_entry" in page
    # drop the one registry entry this test added
    obs_profile._REGISTRY[:] = [
        f for f in obs_profile._REGISTRY if f.name != "test.v3_entry"]


def test_profile_disabled_flag_yields_plain_calls(monkeypatch):
    import jax.numpy as jnp

    from galah_tpu.obs import profile as obs_profile
    from galah_tpu.obs.profile import profiled

    monkeypatch.setenv("GALAH_OBS_PROFILE", "0")
    fn = profiled("test.disabled_entry")(lambda x: x + 1)
    assert float(fn(jnp.float32(1.0))) == 2.0  # still correct
    snap = obs_profile.snapshot()
    assert snap["profiling_enabled"] is False
    assert "test.disabled_entry" not in snap["entries"]
    obs_profile._REGISTRY[:] = [
        f for f in obs_profile._REGISTRY
        if f.name != "test.disabled_entry"]


def test_report_diff_v2_v3_is_additive_compatible(tmp_path, capsys):
    """`report --diff` across a v2 report (no device_costs) and a v3
    report must not crash — the section is optional and additive."""
    from galah_tpu.cli import main

    _populate_run_state()
    v3 = report_mod.assemble("cluster", started_at=0.0)
    v3.setdefault("device_costs", {"profiling_enabled": True,
                                   "entries": {}, "hbm": {
                                       "peak_bytes": 0, "source": None,
                                       "per_stage": {}},
                                   "peaks": None})
    v2 = json.loads(json.dumps(v3))
    del v2["device_costs"]
    v2["version"] = 2
    pa, pb = tmp_path / "v2.json", tmp_path / "v3.json"
    pa.write_text(json.dumps(v2))
    pb.write_text(json.dumps(v3))
    # v2 stays schema-valid (the enum admits both) and diff runs both
    # directions without touching the missing section
    assert report_mod.validate(v2) == []
    assert main(["report", "--diff", str(pa), str(pb)]) == 0
    assert main(["report", "--diff", str(pb), str(pa)]) == 0
    out = capsys.readouterr().out
    assert "galah-tpu report diff" in out or out  # rendered, no crash
