"""Overlapped end-to-end dataflow (cluster/engine._cluster_overlapped).

The contract under test: with GALAH_TPU_OVERLAP engaged, sketch ->
pair-screen -> speculative fragment-ANI -> eager greedy rounds run as
ONE pipeline, and the clustering is BIT-IDENTICAL to the stage-serial
engine on every workload — the frontier rule only changes WHEN work
runs, never what is decided. These tests pin that parity on the
planted-family rung shape and the dense single-family worst case,
the frontier/window soundness cases, forced-vs-auto engagement
semantics, the quiesce-at-checkpoint protocol, and the bounded
speculative buffer under injected slow ingest (docs/dataflow.md).
"""

from typing import List, Optional, Sequence

import numpy as np
import pytest

from galah_tpu.backends.base import ClusterBackend, PreclusterBackend
from galah_tpu.cluster import cluster
from galah_tpu.cluster.cache import PairDistanceCache
from galah_tpu.cluster.checkpoint import ClusterCheckpoint, run_fingerprint
from galah_tpu.obs import metrics as obs_metrics
from galah_tpu.resilience import interrupt
from galah_tpu.utils import timing


class TablePre(PreclusterBackend):
    def __init__(self, pairs):
        self.pairs = pairs

    def method_name(self):
        return "stub-pre"

    def distances(self, genome_paths):
        cache = PairDistanceCache()
        for (i, j), ani in self.pairs.items():
            cache.insert((i, j), ani)
        return cache


class StreamTablePre(TablePre):
    """TablePre plus the streamed pair pass the overlapped engine
    consumes: hit pairs arrive in blocks of `block` rows, each yield
    completing the pair neighborhood of the prefix [0, r1) — the same
    contract as MinHashPreclusterer.distances_streamed (a pair (i, j)
    becomes known when its LATER row is screened)."""

    def __init__(self, pairs, n, block=7, fail_at_row=None):
        super().__init__(pairs)
        self.n = n
        self.block = block
        self.fail_at_row = fail_at_row

    def distances_streamed(self, genome_paths):
        assert len(genome_paths) == self.n
        by_row = {}
        for (i, j), ani in self.pairs.items():
            by_row.setdefault(max(i, j), {})[(i, j)] = ani

        def gen():
            r1 = 0
            while r1 < self.n:
                r0, r1 = r1, min(r1 + self.block, self.n)
                if (self.fail_at_row is not None
                        and r1 > self.fail_at_row):
                    raise RuntimeError("injected stream failure")
                inc = {}
                for r in range(r0, r1):
                    inc.update(by_row.get(r, {}))
                yield r1, inc

        return gen()


class TableCl(ClusterBackend):
    """Exact ANI from a lookup table; absent pairs are gated (None)."""

    def __init__(self, table, threshold):
        self.table = {frozenset(k): v for k, v in table.items()}
        self.threshold = threshold
        self.calls: List[list] = []
        self.pairs_computed: List[tuple] = []

    def method_name(self):
        return "stub-exact"

    @property
    def ani_threshold(self):
        return self.threshold

    def calculate_ani_batch(
            self, pairs: Sequence[tuple]) -> List[Optional[float]]:
        self.calls.append(list(pairs))
        self.pairs_computed.extend(pairs)
        return [self.table.get(frozenset(p)) for p in pairs]


class ConstCl(ClusterBackend):
    """Every pair at a fixed ANI — for real-backend workloads where the
    pair table is not known up front."""

    def __init__(self, threshold=0.95, ani=0.97):
        self.threshold = threshold
        self.ani = ani

    def method_name(self):
        return "stub-exact"

    @property
    def ani_threshold(self):
        return self.threshold

    def calculate_ani_batch(self, pairs):
        return [self.ani] * len(pairs)


def g(n):
    return [f"g{i}.fna" for i in range(n)]


def _family_workload(n_families, fam_size, seed, none_rate=0.05,
                     thr=0.95):
    """Planted families with randomized exact ANIs straddling the
    threshold (and a few gated-None pairs) — the bench rung shape,
    same generator as tests/test_greedy_rounds.py."""
    rng = np.random.default_rng(seed)
    pre, table = {}, {}
    for f in range(n_families):
        base = f * fam_size
        for a in range(fam_size):
            for b in range(a + 1, fam_size):
                i, j = base + a, base + b
                pre[(i, j)] = 0.96
                if rng.random() < none_rate:
                    table[(f"g{i}.fna", f"g{j}.fna")] = None
                else:
                    table[(f"g{i}.fna", f"g{j}.fna")] = round(
                        float(rng.uniform(thr - 0.05, thr + 0.04)), 6)
    return pre, table


def _serial(monkeypatch, n, pre, table, thr=0.95, **kw):
    monkeypatch.setenv("GALAH_TPU_GREEDY_STRATEGY", "device")
    monkeypatch.setenv("GALAH_TPU_OVERLAP", "0")
    return cluster(g(n), TablePre(pre), TableCl(table, thr), **kw)


def _overlapped(monkeypatch, n, pre, table, thr=0.95, block=7,
                pre_backend=None, cl=None, **kw):
    monkeypatch.setenv("GALAH_TPU_GREEDY_STRATEGY", "device")
    monkeypatch.setenv("GALAH_TPU_OVERLAP", "1")
    backend = pre_backend or StreamTablePre(pre, n, block=block)
    return cluster(g(n), backend, cl or TableCl(table, thr), **kw)


def test_overlap_planted_families_1000_parity(monkeypatch):
    """Golden-cluster equality on the 1000-genome rung shape, and the
    engagement counter proves the overlapped engine actually ran."""
    pre, table = _family_workload(250, 4, seed=11)
    serial = _serial(monkeypatch, 1000, pre, table)
    before = timing.GLOBAL.counters()
    over = _overlapped(monkeypatch, 1000, pre, table, block=64)
    after = timing.GLOBAL.counters()
    assert over == serial
    assert after.get("overlap-engaged", 0) == before.get(
        "overlap-engaged", 0) + 1
    assert after.get("overlap-eager-rounds", 0) > before.get(
        "overlap-eager-rounds", 0)


def test_overlap_dense_single_family_parity(monkeypatch):
    """The mega-family worst case: ONE precluster, every pair a hit,
    ANIs straddling the threshold so rep chains and argmax ties both
    occur — the union-find grouping must keep decisions identical."""
    rng = np.random.default_rng(3)
    n = 96
    pre, table = {}, {}
    for i in range(n):
        for j in range(i + 1, n):
            pre[(i, j)] = 0.96
            table[(f"g{i}.fna", f"g{j}.fna")] = round(
                float(rng.uniform(0.90, 0.99)), 6)
    serial = _serial(monkeypatch, n, pre, table)
    over = _overlapped(monkeypatch, n, pre, table, block=5)
    assert over == serial


def test_overlap_block_and_width_invariance(monkeypatch):
    """Arrival granularity and round width change batching only —
    every (block, rep_rounds) combination yields the stage-serial
    clustering."""
    pre, table = _family_workload(6, 4, seed=7)
    serial = _serial(monkeypatch, 24, pre, table)
    for block in (1, 3, 5, 24):
        for width in (1, 3, 7, 64):
            over = _overlapped(monkeypatch, 24, pre, table, block=block,
                               rep_rounds=width)
            assert over == serial, f"block={block} rep_rounds={width}"


def test_overlap_rounds_run_before_stream_ends(monkeypatch):
    """Genuine overlap: greedy/fragment dispatches happen while the
    pair stream is still producing (backend calls strictly before the
    final block is delivered), and one eager round runs per window."""
    pre, table = _family_workload(6, 4, seed=5, none_rate=0.0)
    n = 24
    serial = _serial(monkeypatch, n, pre, table)

    pre_backend = StreamTablePre(pre, n, block=4)
    cl = TableCl(table, 0.95)
    trace = []
    inner = pre_backend.distances_streamed

    def traced(paths, _inner=inner):
        stream = _inner(paths)

        def gen():
            for r1, inc in stream:
                trace.append((r1, len(cl.calls)))
                yield r1, inc

        return gen()

    pre_backend.distances_streamed = traced
    before = timing.GLOBAL.counters()
    over = _overlapped(monkeypatch, n, pre, table,
                       pre_backend=pre_backend, cl=cl, rep_rounds=4)
    after = timing.GLOBAL.counters()
    assert over == serial
    # dispatches before the last block arrived = overlapped execution
    assert any(calls > 0 for r1, calls in trace if r1 < n)
    assert after.get("overlap-eager-rounds", 0) - before.get(
        "overlap-eager-rounds", 0) == n // 4  # one per window
    assert after.get("overlap-spec-pairs", 0) > before.get(
        "overlap-spec-pairs", 0)


def test_overlap_late_genome_joins_early_precluster(monkeypatch):
    """Frontier rule: a genome whose only hit edge arrives long after
    its partner's window was eagerly resolved still joins that early
    rep's cluster."""
    n = 24
    pre = {(0, 23): 0.96}  # the ONLY hit edge; the rest are singletons
    table = {("g0.fna", "g23.fna"): 0.97}
    serial = _serial(monkeypatch, n, pre, table)
    over = _overlapped(monkeypatch, n, pre, table, block=2,
                       rep_rounds=2)
    assert over == serial
    assert [0, 23] in over


def test_overlap_late_rep_wins_membership_argmax(monkeypatch):
    """Membership must wait for stream completion: non-rep 1 is
    claimed by early rep 0 but a LATER rep 2 has the higher ANI, so
    the final argmax assigns 1 to 2 — identically in both engines."""
    pre = {(0, 1): 0.96, (1, 2): 0.96}
    table = {("g0.fna", "g1.fna"): 0.96, ("g1.fna", "g2.fna"): 0.98}
    serial = _serial(monkeypatch, 3, pre, table)
    over = _overlapped(monkeypatch, 3, pre, table, block=1,
                       rep_rounds=1)
    assert over == serial == [[0], [2, 1]]


def test_overlap_forced_requires_stream_and_device(monkeypatch):
    """GALAH_TPU_OVERLAP=1 propagates ineligibility: a preclusterer
    without a streamed pair pass, or a pinned host greedy strategy,
    is a hard error instead of a silent serial run."""
    pre, table = _family_workload(2, 3, seed=1)
    monkeypatch.setenv("GALAH_TPU_OVERLAP", "1")
    monkeypatch.setenv("GALAH_TPU_GREEDY_STRATEGY", "device")
    with pytest.raises(RuntimeError, match="did not engage"):
        cluster(g(6), TablePre(pre), TableCl(table, 0.95))
    monkeypatch.setenv("GALAH_TPU_GREEDY_STRATEGY", "host")
    with pytest.raises(RuntimeError, match="device greedy"):
        cluster(g(6), StreamTablePre(pre, 6), TableCl(table, 0.95))


def test_overlap_auto_demotes_on_stream_failure(monkeypatch):
    """AUTO mode: a mid-stream failure demotes to the stage-serial
    engine from scratch and still produces the correct clustering;
    forced mode propagates the same failure."""
    pre, table = _family_workload(6, 4, seed=13)
    n = 24
    serial = _serial(monkeypatch, n, pre, table)
    monkeypatch.setenv("GALAH_TPU_GREEDY_STRATEGY", "device")
    monkeypatch.setenv("GALAH_TPU_OVERLAP", "auto")
    before = timing.GLOBAL.counters()
    out = cluster(g(n), StreamTablePre(pre, n, block=4, fail_at_row=10),
                  TableCl(table, 0.95))
    after = timing.GLOBAL.counters()
    assert out == serial
    assert after.get("overlap-demoted", 0) == before.get(
        "overlap-demoted", 0) + 1
    monkeypatch.setenv("GALAH_TPU_OVERLAP", "1")
    with pytest.raises(RuntimeError, match="injected stream failure"):
        cluster(g(n), StreamTablePre(pre, n, block=4, fail_at_row=10),
                TableCl(table, 0.95))


def test_overlap_checkpoint_completes_and_clears_rounds(
        monkeypatch, tmp_path):
    """A checkpointed overlapped run quiesces before every durable
    write, finishes with the stage-serial clustering, clears
    greedy_rounds.jsonl, and a resume serves everything from the
    completed-precluster log with ZERO backend calls."""
    pre, table = _family_workload(10, 4, seed=9, none_rate=0.0)
    n = 40
    serial = _serial(monkeypatch, n, pre, table)
    fp = run_fingerprint(g(n), "stub-pre", "stub-exact", 0.95, 0.9)
    ck = ClusterCheckpoint(str(tmp_path / "ck"), fp)
    over = _overlapped(monkeypatch, n, pre, table, block=6,
                       checkpoint=ck)
    assert over == serial
    assert not (tmp_path / "ck" / "greedy_rounds.jsonl").exists()

    ck2 = ClusterCheckpoint(str(tmp_path / "ck"), fp)
    cl2 = TableCl(table, 0.95)
    out = _overlapped(monkeypatch, n, pre, table, block=6,
                      pre_backend=StreamTablePre(pre, n, block=6),
                      cl=cl2, checkpoint=ck2)
    assert out == serial
    assert cl2.calls == []


def test_overlap_preempted_run_resumes_stage_serial(
        monkeypatch, tmp_path):
    """Kill at the greedy-round-saved boundary: the overlapped run
    saved its streaming-phase ANIs as ONE digest-bound round record,
    the resume disengages overlap (checkpointed distances), replays
    the record with zero recomputation, and lands on the identical
    clustering — no pair is paid for twice across the two runs."""
    pre, table = _family_workload(10, 4, seed=9, none_rate=0.0)
    n = 40
    serial = _serial(monkeypatch, n, pre, table)

    fp = run_fingerprint(g(n), "stub-pre", "stub-exact", 0.95, 0.9)
    ck1 = ClusterCheckpoint(str(tmp_path / "ck"), fp)
    saved = ck1.save_greedy_round

    def save_then_stop(digest, pairs):
        saved(digest, pairs)
        interrupt.request_stop()

    monkeypatch.setattr(ck1, "save_greedy_round", save_then_stop)
    cl1 = TableCl(table, 0.95)
    interrupt.reset()
    try:
        with pytest.raises(interrupt.PreemptionRequested):
            _overlapped(monkeypatch, n, pre, table, block=6,
                        pre_backend=StreamTablePre(pre, n, block=6),
                        cl=cl1, checkpoint=ck1)
    finally:
        interrupt.reset()
    assert (tmp_path / "ck" / "greedy_rounds.jsonl").exists()

    # resume is stage-serial BY DESIGN, even with overlap still forced
    # (checkpointed distances make the run ineligible, not failed) —
    # and the plain TablePre proves no stream is needed to resume
    before = timing.GLOBAL.counters()
    ck2 = ClusterCheckpoint(str(tmp_path / "ck"), fp)
    cl2 = TableCl(table, 0.95)
    monkeypatch.setenv("GALAH_TPU_OVERLAP", "1")
    out = cluster(g(n), TablePre(pre), cl2, checkpoint=ck2)
    after = timing.GLOBAL.counters()
    assert out == serial
    assert after.get("greedy-replayed-pairs", 0) > before.get(
        "greedy-replayed-pairs", 0)
    paid1 = set(map(frozenset, cl1.pairs_computed))
    paid2 = set(map(frozenset, cl2.pairs_computed))
    assert not (paid1 & paid2)
    assert not (tmp_path / "ck" / "greedy_rounds.jsonl").exists()


def test_overlap_depth_bounds_spec_buffer(monkeypatch):
    """GALAH_TPU_OVERLAP_DEPTH is a hard bound on the speculative
    fragment-ANI buffer: the pending high-water mark never exceeds it
    and the offered pairs arrive split over multiple batches."""
    pre, table = _family_workload(8, 6, seed=17, none_rate=0.0)
    n = 48
    serial = _serial(monkeypatch, n, pre, table)
    monkeypatch.setenv("GALAH_TPU_OVERLAP_DEPTH", "4")
    obs_metrics.reset()
    before = timing.GLOBAL.counters()
    over = _overlapped(monkeypatch, n, pre, table, block=3,
                       rep_rounds=6)
    after = timing.GLOBAL.counters()
    assert over == serial
    snap = obs_metrics.snapshot()
    peak = snap["overlap.spec_pending_peak"]["value"]
    assert peak is not None and 0 < peak <= 4
    assert after.get("overlap-spec-batches", 0) - before.get(
        "overlap-spec-batches", 0) >= 2


def test_overlap_occupancy_gauges(monkeypatch):
    """The overlapped run reports per-stage occupancy (greedy and
    fragment from the engine; the unlabelled whole-pipeline gauge is
    their mean), every value clamped to [0, 1]."""
    pre, table = _family_workload(8, 4, seed=19)
    n = 32
    obs_metrics.reset()
    _overlapped(monkeypatch, n, pre, table, block=4, rep_rounds=4)
    snap = obs_metrics.snapshot()
    for name in ("workload.pipeline_occupancy[greedy]",
                 "workload.pipeline_occupancy[fragment]",
                 "workload.pipeline_occupancy"):
        assert name in snap, name
        v = snap[name]["value"]
        assert v is not None and 0.0 <= v <= 1.0, name
    assert snap["overlap.eager_rounds"]["value"] == n // 4
    assert snap["overlap.spec_pairs"]["value"] > 0


def test_overlap_mode_and_depth_parsing(monkeypatch):
    from galah_tpu.cluster import engine

    monkeypatch.delenv("GALAH_TPU_OVERLAP", raising=False)
    assert engine._overlap_mode() == "auto"
    monkeypatch.setenv("GALAH_TPU_OVERLAP", "1")
    assert engine._overlap_mode() == "1"
    monkeypatch.setenv("GALAH_TPU_OVERLAP", "bogus")
    assert engine._overlap_mode() == "auto"
    monkeypatch.delenv("GALAH_TPU_OVERLAP_DEPTH", raising=False)
    assert engine._overlap_depth() == 512
    monkeypatch.setenv("GALAH_TPU_OVERLAP_DEPTH", "7")
    assert engine._overlap_depth() == 7
    monkeypatch.setenv("GALAH_TPU_OVERLAP_DEPTH", "0")
    assert engine._overlap_depth() == 1
    monkeypatch.setenv("GALAH_TPU_OVERLAP_DEPTH", "oops")
    assert engine._overlap_depth() == 512


def test_overlap_backpressure_under_slow_ingest(monkeypatch, tmp_path):
    """The whole pipeline end-to-end on real FASTAs with injected
    slow ingest (GALAH_FI slow-io at the io.ingest site) and a tiny
    in-flight window: the run completes, matches the stage-serial
    clustering byte-for-byte, keeps the speculative buffer within
    GALAH_TPU_OVERLAP_DEPTH, and reports occupancy for every stage."""
    from galah_tpu.backends.minhash_backend import MinHashPreclusterer
    from galah_tpu.io.diskcache import CacheDir
    from galah_tpu.resilience import faults

    rng = np.random.default_rng(21)
    base = rng.choice(list("ACGT"), size=5000)
    paths = []
    for i in range(6):
        seq = base.copy()
        if i >= 3:  # second family
            sites = rng.random(seq.shape[0]) < 0.03
            seq[sites] = rng.choice(list("ACGT"),
                                    size=int(sites.sum()))
        p = tmp_path / f"m{i}.fna"
        p.write_text(">c\n" + "".join(seq) + "\n")
        paths.append(str(p))

    # the single-device-CPU AUTO strategy is "c", which keeps the
    # historical staged shape — pin the device (XLA) strategy so the
    # streamed pair pass engages on this host
    monkeypatch.setenv("GALAH_TPU_SKETCH_STRATEGY", "xla")
    monkeypatch.setenv("GALAH_TPU_GREEDY_STRATEGY", "device")
    monkeypatch.setenv("GALAH_TPU_OVERLAP", "0")
    serial = cluster(
        paths,
        MinHashPreclusterer(0.95, sketch_size=64,
                            cache=CacheDir(str(tmp_path / "c_ser"))),
        ConstCl())

    monkeypatch.setenv("GALAH_TPU_OVERLAP", "1")
    monkeypatch.setenv("GALAH_TPU_OVERLAP_DEPTH", "2")
    monkeypatch.setenv(
        "GALAH_FI",
        "site=io.ingest;kind=slow-io;prob=1;seed=1;hang=0.02")
    faults.reset()
    obs_metrics.reset()
    try:
        over = cluster(
            paths,
            MinHashPreclusterer(0.95, sketch_size=64,
                                cache=CacheDir(str(tmp_path / "c_ov"))),
            ConstCl())
    finally:
        monkeypatch.delenv("GALAH_FI")
        faults.reset()
    assert over == serial
    snap = obs_metrics.snapshot()
    peak = snap["overlap.spec_pending_peak"]["value"]
    assert peak is not None and peak <= 2
    for stage in ("ingest", "sketch", "pairs", "greedy", "fragment"):
        name = f"workload.pipeline_occupancy[{stage}]"
        assert name in snap, name
        v = snap[name]["value"]
        assert v is not None and 0.0 <= v <= 1.0, name
    assert snap["workload.pipeline_occupancy"]["value"] is not None


def test_overlap_flags_registered(monkeypatch):
    from galah_tpu.config import env_value

    monkeypatch.delenv("GALAH_TPU_OVERLAP", raising=False)
    monkeypatch.delenv("GALAH_TPU_OVERLAP_DEPTH", raising=False)
    assert env_value("GALAH_TPU_OVERLAP") == "auto"
    assert env_value("GALAH_TPU_OVERLAP_DEPTH") == "512"
