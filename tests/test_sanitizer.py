"""GalahSan runtime concurrency sanitizer: deterministic two-thread
reproducers for every finding kind (on synthetic modules with isolated
Sanitizer instances), the report/summary shapes, and the tier-1 gate
that the repo's own threaded modules run violation-free under the real
workload (conftest arms the process-wide GLOBAL via GALAH_SAN=1)."""

import json
import threading
import types

import pytest

from galah_tpu.analysis import sanitizer
from galah_tpu.analysis.sanitizer import (SanDict, SanList, SanLock,
                                          Sanitizer)


def make_module(name="synth_mod", **attrs):
    mod = types.ModuleType(name)
    for k, v in attrs.items():
        setattr(mod, k, v)
    return mod


def kinds(san):
    return sorted(f["kind"] for f in san.findings())


def errors_by_kind(san):
    out = {}
    for f in san.errors():
        out.setdefault(f["kind"], []).append(f)
    return out


# ---------------------------------------------------------------------------
# SanLock mechanics
# ---------------------------------------------------------------------------


def test_sanlock_wraps_once_and_upgrades_to_declared():
    san = Sanitizer()
    raw = threading.Lock()
    a = san._wrap_lock(raw, "m.py:_A", declared=False)
    assert isinstance(a, SanLock) and not a.declared
    # same inner object -> same proxy; a declared wrap upgrades it
    b = san._wrap_lock(raw, "m.py:_A", declared=True)
    assert b is a and a.declared
    assert san._wrap_lock(a, "m.py:_A", declared=True) is a
    with a:
        assert a.locked()
    assert not a.locked()
    assert a.acquisitions == 1


def test_reentrant_same_name_pair_records_no_edge():
    """Two SanLocks sharing a canonical name (per-instance locks of
    one class) must not produce a self-edge."""
    san = Sanitizer()
    a = san._wrap_lock(threading.Lock(), "m.py:C._lock", declared=True)
    b = san._wrap_lock(threading.Lock(), "m.py:C._lock", declared=True)
    with a:
        with b:
            pass
    assert san.edges == {}


# ---------------------------------------------------------------------------
# Lock-order reproducers (synthetic modules)
# ---------------------------------------------------------------------------


def test_inversion_reproducer():
    mod = make_module(LOCK_ORDER=["_A", "_B"],
                      _A=threading.Lock(), _B=threading.Lock())
    san = Sanitizer()
    san.install_module(mod, "synth_mod.py")
    with mod._B:
        with mod._A:  # declared order says _A before _B
            pass
    by = errors_by_kind(san)
    assert list(by) == ["inversion"]
    (f,) = by["inversion"]
    assert f["locks"] == ["synth_mod.py:_B", "synth_mod.py:_A"]
    assert "tests/test_sanitizer.py:" in f["where"]
    assert "declares synth_mod.py:_A before" in f["detail"]
    # the declared pair itself was never exercised in order
    assert san.summary()["inversions"] == 1
    assert san.summary()["unexercised"] == 1


def test_declared_order_exercised_is_clean():
    mod = make_module(LOCK_ORDER=["_A", "_B"],
                      _A=threading.Lock(), _B=threading.Lock())
    san = Sanitizer()
    san.install_module(mod, "synth_mod.py")
    with mod._A:
        with mod._B:
            pass
    assert san.errors() == []
    assert san.summary()["unexercised"] == 0
    assert san.summary()["edges_observed"] == 1


def test_undeclared_edge_reproducer():
    """Two DECLARED locks nested with no LOCK_ORDER pair covering
    them: an ordering obligation the annotations never took."""
    mod = make_module(GUARDED_BY={"_X": "_A", "_Y": "_C"},
                      _A=threading.Lock(), _C=threading.Lock(),
                      _X={}, _Y={})
    san = Sanitizer()
    san.install_module(mod, "synth_mod.py")
    with mod._A:
        with mod._C:
            pass
    by = errors_by_kind(san)
    assert list(by) == ["undeclared_edge"]
    (f,) = by["undeclared_edge"]
    assert f["locks"] == ["synth_mod.py:_A", "synth_mod.py:_C"]
    assert "no LOCK_ORDER declares this pair" in f["detail"]


def test_undeclared_acquisition_reproducer():
    """A nested acquisition involving a lock absent from every
    annotation is an error; a BARE acquisition of the same lock is
    not (the repo keeps helper locks that never nest)."""
    mod = make_module(GUARDED_BY={"_X": "_A"},
                      _A=threading.Lock(), _U=threading.Lock(),
                      _X={})
    san = Sanitizer()
    san.install_module(mod, "synth_mod.py")
    with mod._U:  # bare: no finding
        pass
    assert san.errors() == []
    with mod._A:
        with mod._U:  # nested involvement: finding
            pass
    by = errors_by_kind(san)
    assert list(by) == ["undeclared_acquisition"]
    (f,) = by["undeclared_acquisition"]
    assert "synth_mod.py:_U" in f["detail"]
    assert "tests/test_sanitizer.py:" in f["where"]


# ---------------------------------------------------------------------------
# Race reproducers (GUARDED_BY mutation checks)
# ---------------------------------------------------------------------------


def _registry_module():
    class Registry:
        def __init__(self):
            self._lock = threading.Lock()
            self._items = []

        def good_add(self, item):
            with self._lock:
                self._items.append(item)

        def bad_add(self, item):
            self._items.append(item)

    return make_module(
        GUARDED_BY={"Registry._items": "Registry._lock"},
        Registry=Registry), Registry


def test_unguarded_instance_mutation_from_worker_is_a_race():
    mod, Registry = _registry_module()
    san = Sanitizer()
    san.install_module(mod, "synth_mod.py")
    reg = mod.Registry()
    assert isinstance(reg._lock, SanLock)
    assert isinstance(reg._items, SanList)
    reg.good_add(1)          # locked: clean
    reg.bad_add(2)           # owner thread, lock never foreign: clean
    assert san.errors() == []
    t = threading.Thread(target=reg.bad_add, args=(3,))
    t.start()
    t.join()
    by = errors_by_kind(san)
    assert list(by) == ["race"]
    (f,) = by["race"]
    assert f["locks"] == ["synth_mod.py:Registry._lock"]
    assert "Registry._items mutated (append)" in f["detail"]
    assert "tests/test_sanitizer.py:" in f["where"]


def test_owner_rebind_after_foreign_touch_is_a_race():
    mod, Registry = _registry_module()
    san = Sanitizer()
    san.install_module(mod, "synth_mod.py")
    reg = mod.Registry()
    reg._items = []          # still single-owner: clean
    assert san.errors() == []
    t = threading.Thread(target=reg.good_add, args=(1,))
    t.start()
    t.join()
    reg._items = []          # lock is now shared: rebind needs it
    by = errors_by_kind(san)
    assert list(by) == ["race"]
    assert "_items rebind" in by["race"][0]["detail"]
    # ... and rebinding WITH the lock held is clean
    san2 = Sanitizer()
    mod2, _ = _registry_module()
    san2.install_module(mod2, "synth_mod.py")
    reg2 = mod2.Registry()
    t = threading.Thread(target=reg2.good_add, args=(1,))
    t.start()
    t.join()
    with reg2._lock:
        reg2._items = []
    assert san2.errors() == []


def test_unguarded_global_container_mutation_is_a_race():
    mod = make_module(GUARDED_BY={"_CACHE": "_LOCK"},
                      _LOCK=threading.Lock(), _CACHE={})
    san = Sanitizer()
    san.install_module(mod, "synth_mod.py")
    assert isinstance(mod._CACHE, SanDict)

    def locked_write():
        with mod._LOCK:
            mod._CACHE["a"] = 1

    def bare_write():
        mod._CACHE["b"] = 2

    t = threading.Thread(target=locked_write)
    t.start()
    t.join()
    assert san.errors() == []
    t = threading.Thread(target=bare_write)
    t.start()
    t.join()
    by = errors_by_kind(san)
    assert list(by) == ["race"]
    (f,) = by["race"]
    assert "synth_mod.py:_CACHE mutated (__setitem__)" in f["detail"]


def test_duplicate_races_dedup_by_site():
    mod = make_module(GUARDED_BY={"_CACHE": "_LOCK"},
                      _LOCK=threading.Lock(), _CACHE={})
    san = Sanitizer()
    san.install_module(mod, "synth_mod.py")

    def bare_write(i):
        mod._CACHE[i] = i  # same site every iteration

    for i in range(3):
        t = threading.Thread(target=bare_write, args=(i,))
        t.start()
        t.join()
    assert san.summary()["races"] == 1


# ---------------------------------------------------------------------------
# Report / summary shapes
# ---------------------------------------------------------------------------


def test_summary_and_report_shape(tmp_path, monkeypatch):
    mod = make_module(LOCK_ORDER=["_A", "_B"],
                      _A=threading.Lock(), _B=threading.Lock())
    san = Sanitizer()
    san.install_module(mod, "synth_mod.py")
    with mod._A:
        with mod._B:
            pass
    s = san.summary()
    assert s == {"enabled": True, "modules": 1, "locks": 2,
                 "declared_locks": 2, "acquisitions": 2,
                 "edges_observed": 1, "edges_declared": 1,
                 "undeclared_acquisitions": 0, "undeclared_edges": 0,
                 "inversions": 0, "races": 0, "unexercised": 0}
    rep = san.report()
    assert rep["version"] == 1
    assert rep["modules"] == ["synth_mod.py"]
    assert rep["locks"]["synth_mod.py:_A"]["declared"]
    assert rep["edges"][0]["held"] == "synth_mod.py:_A"
    assert rep["declared_order"] == [{"outer": "synth_mod.py:_A",
                                     "inner": "synth_mod.py:_B",
                                     "module": "synth_mod.py"}]
    out = tmp_path / "san.json"
    assert san.write_report(str(out)) == str(out)
    assert json.loads(out.read_text())["summary"] == s
    # env-var default path
    env_out = tmp_path / "env.json"
    monkeypatch.setenv("GALAH_SAN_REPORT", str(env_out))
    san.write_report()
    assert env_out.exists()


def test_reset_observations_keeps_instrumentation():
    mod = make_module(LOCK_ORDER=["_A", "_B"],
                      _A=threading.Lock(), _B=threading.Lock())
    san = Sanitizer()
    san.install_module(mod, "synth_mod.py")
    with mod._B:
        with mod._A:
            pass
    assert san.errors()
    san.reset_observations()
    assert san.errors() == []
    assert san.summary()["acquisitions"] == 0
    with mod._A:  # still instrumented
        pass
    assert san.summary()["acquisitions"] == 1


def test_enabled_flag_parsing(monkeypatch):
    monkeypatch.delenv("GALAH_SAN", raising=False)
    assert not sanitizer.enabled()
    monkeypatch.setenv("GALAH_SAN", "0")
    assert not sanitizer.enabled()
    monkeypatch.setenv("GALAH_SAN", "1")
    assert sanitizer.enabled()


# ---------------------------------------------------------------------------
# The tier-1 gate: the repo's own threaded modules, under load
# ---------------------------------------------------------------------------


def _exercise_threaded_modules():
    """A bounded cross-module workload touching the instrumented
    locks from several threads: metrics registry + instances, events
    warn-once, stage timing with adoption, dispatch demotion state."""
    import logging

    from galah_tpu.obs import events, metrics
    from galah_tpu.utils import timing

    log = logging.getLogger("galah.san.gate")
    token = timing.stage_token()

    def work(i):
        with timing.adopt(token):
            with timing.stage(f"san_gate_{i % 2}"):
                timing.counter("san_gate", 1)
                metrics.counter("san.gate.count").inc()
                metrics.gauge("san.gate.gauge").set(i)
                metrics.histogram("san.gate.hist").observe(float(i))
                metrics.pipeline_occupancy(0.5, stage="san_gate")
                events.warn_once(log, "san gate warning",
                                 key=f"san-gate-{i % 2}")

    threads = [threading.Thread(target=work, args=(i,))
               for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    work(99)
    metrics.snapshot()
    timing.GLOBAL.items()


def test_repo_runs_violation_free_under_sanitizer():
    """THE GATE: with GALAH_SAN=1 (conftest), the repo's declared
    lock annotations must hold under a real multi-threaded workload —
    zero undeclared acquisitions, zero unordered edges, zero
    inversions, zero races. This test failing means an annotation
    drifted from runtime behavior; fix the code or the annotation,
    don't relax the gate."""
    if not sanitizer.GLOBAL.installed:
        pytest.skip("GALAH_SAN=0: process-wide sanitizer not armed")
    _exercise_threaded_modules()
    errs = sanitizer.GLOBAL.errors()
    assert errs == [], json.dumps(errs, indent=1)
    s = sanitizer.GLOBAL.summary()
    assert s["modules"] == 15  # == len(THREADED_MODULES)
    assert s["acquisitions"] > 0
    assert (s["undeclared_acquisitions"] == s["undeclared_edges"]
            == s["inversions"] == s["races"] == 0)


def test_global_summary_feeds_run_report():
    if not sanitizer.GLOBAL.installed:
        pytest.skip("GALAH_SAN=0: process-wide sanitizer not armed")
    assert sanitizer.summary_if_enabled() == sanitizer.GLOBAL.summary()

    from galah_tpu.obs import report as report_mod

    rep = report_mod.assemble("test", argv=["galah-tpu", "test"])
    assert rep["version"] == report_mod.REPORT_VERSION
    assert rep["sanitizer"]["enabled"] is True
    rendered = report_mod.render(rep)
    assert "concurrency sanitizer (GalahSan):" in rendered
    rep2 = json.loads(json.dumps(rep))
    rep2["sanitizer"]["races"] = 2
    out = report_mod.diff(rep, rep2)
    assert "sanitizer drift:" in out
    assert "races: 0 -> 2 (+2)" in out
