"""Native collision counter (csrc/collision.c) vs the numpy reference.

The C path must produce bit-identical (pi, pj, counts) triples in the
same order as _collision_pair_counts_np for every input class the
screen sees: planted families, near-duplicate mega-clusters (big-run
group dedup), ragged row lengths, empty rows, and adversarial
random fuzz. collision_pair_counts auto-routes to C when it builds,
so every consumer (threshold_pairs_c, screen_pairs, the device sparse
path) inherits the speedup with the semantics pinned here.
"""

import numpy as np
import pytest

from galah_tpu.ops.collision import (
    _BIG_RUN,
    _collision_pair_counts_np,
    collision_pair_counts,
)
from galah_tpu.ops.constants import SENTINEL

try:
    from galah_tpu.ops._ccollision import collision_pair_counts_c
except ImportError:  # pragma: no cover - toolchain-less environments
    collision_pair_counts_c = None

needs_c = pytest.mark.skipif(collision_pair_counts_c is None,
                             reason="C toolchain unavailable")


def _assert_identical(mat, lens):
    got = collision_pair_counts_c(mat, lens, _BIG_RUN)
    want = _collision_pair_counts_np(mat, lens)
    for g, w, name in zip(got, want, ("pi", "pj", "counts")):
        np.testing.assert_array_equal(g, w, err_msg=name)


def _family_matrix(rng, n, k, fam):
    base = rng.integers(0, 1 << 62, size=(fam, k), dtype=np.uint64)
    mat = np.empty((n, k), np.uint64)
    for i in range(n):
        row = base[i % fam].copy()
        n_mut = int(rng.integers(0, max(1, k // 8)))
        idx = rng.choice(k, size=n_mut, replace=False)
        row[idx] = rng.integers(0, 1 << 62, size=n_mut, dtype=np.uint64)
        row.sort()
        mat[i] = row
    return mat


@needs_c
def test_planted_families_identical():
    rng = np.random.default_rng(11)
    mat = _family_matrix(rng, 64, 96, 16)
    lens = np.full(64, 96, np.int64)
    _assert_identical(mat, lens)


@needs_c
def test_mega_cluster_big_run_dedup_identical():
    rng = np.random.default_rng(12)
    k = 48
    shared = np.sort(rng.integers(0, 1 << 62, size=k, dtype=np.uint64))
    n = _BIG_RUN * 2 + 7   # every shared hash makes a run > _BIG_RUN
    mat = np.tile(shared, (n, 1))
    # a second, partially-overlapping mega-group exercises distinct
    # group signatures
    mat[n // 2:, : k // 2] = np.sort(
        rng.integers(0, 1 << 62, size=k // 2, dtype=np.uint64))
    mat.sort(axis=1)
    lens = np.full(n, k, np.int64)
    _assert_identical(mat, lens)


@needs_c
def test_ragged_and_empty_rows_identical():
    rng = np.random.default_rng(13)
    n, k = 50, 32
    mat = np.full((n, k), np.uint64(SENTINEL), dtype=np.uint64)
    lens = np.zeros(n, np.int64)
    pool = rng.integers(0, 1 << 16, size=64, dtype=np.uint64)  # dense
    for i in range(n):
        m = int(rng.integers(0, k + 1))
        lens[i] = m
        if m:
            vals = rng.choice(pool, size=m, replace=False)
            mat[i, :m] = np.sort(vals)
    _assert_identical(mat, lens)


@needs_c
def test_all_empty_and_no_collisions():
    n, k = 8, 16
    mat = np.full((n, k), np.uint64(SENTINEL), dtype=np.uint64)
    lens = np.zeros(n, np.int64)
    _assert_identical(mat, lens)
    rng = np.random.default_rng(14)
    mat2 = np.sort(rng.integers(0, 1 << 62, size=(n, k),
                                dtype=np.uint64), axis=1)
    _assert_identical(mat2, np.full(n, k, np.int64))


@needs_c
def test_fuzz_identical_across_structures():
    rng = np.random.default_rng(15)
    for trial in range(25):
        n = int(rng.integers(2, 120))
        k = int(rng.integers(1, 64))
        fam = int(rng.integers(1, max(2, n // 2)))
        if trial % 3 == 0:
            # collision-dense small universe
            mat = np.empty((n, k), np.uint64)
            for i in range(n):
                mat[i] = np.sort(rng.choice(
                    np.arange(4 * k, dtype=np.uint64), size=k,
                    replace=False))
        else:
            mat = _family_matrix(rng, n, k, fam)
        lens = rng.integers(0, k + 1, size=n).astype(np.int64)
        mm = np.full((n, k), np.uint64(SENTINEL), dtype=np.uint64)
        for i in range(n):
            mm[i, : lens[i]] = mat[i, : lens[i]]
        _assert_identical(mm, lens)


@needs_c
def test_auto_route_uses_c():
    """collision_pair_counts routes to the C counter when it builds."""
    rng = np.random.default_rng(16)
    mat = _family_matrix(rng, 32, 40, 8)
    lens = np.full(32, 40, np.int64)
    got = collision_pair_counts(mat, lens)
    want = collision_pair_counts_c(mat, lens, _BIG_RUN)
    for g, w in zip(got, want):
        np.testing.assert_array_equal(g, w)
