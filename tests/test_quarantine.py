"""Bad-input quarantine (galah_tpu/resilience/quarantine.py).

Pins the --on-bad-genome contract: under "skip" unreadable genomes land
in a quarantine manifest and the surviving genomes cluster exactly as a
run that never saw the bad ones; under "error" (the default) nothing
changed from before the feature existed.
"""

import gzip
import json
import os

import numpy as np
import pytest

from galah_tpu.genome_inputs import parse_genome_inputs
from galah_tpu.resilience.quarantine import (
    MANIFEST_NAME,
    QuarantineManifest,
    manifest_output_dir,
    preflight_quarantine,
    validate_genome,
)

pytestmark = pytest.mark.fault_injection


def write_genome(path, seed, length=30_000, mutate_from=None,
                 rate=0.02):
    rng = np.random.default_rng(seed)
    if mutate_from is None:
        seq = rng.integers(0, 4, size=length)
    else:
        seq = np.array(mutate_from, copy=True)
        sites = rng.random(seq.shape[0]) < rate
        seq[sites] = (seq[sites]
                      + rng.integers(1, 4, size=int(sites.sum()))) % 4
    path.write_text(">c\n" + "".join("ACGT"[c] for c in seq) + "\n")
    return seq


@pytest.fixture
def bad_files(tmp_path):
    """One of each quarantine-worthy pathology."""
    bad = tmp_path / "bad.fna"
    bad.write_text("this is not FASTA at all\n")
    empty = tmp_path / "empty.fna"
    empty.write_text("")
    trunc = tmp_path / "trunc.fna.gz"
    whole = gzip.compress(b">c\n" + b"ACGT" * 2000 + b"\n")
    trunc.write_bytes(whole[: len(whole) // 2])
    missing = tmp_path / "missing.fna"
    return {"bad": str(bad), "empty": str(empty),
            "trunc": str(trunc), "missing": str(missing)}


# -- validate_genome ------------------------------------------------


def test_validate_genome_verdicts(tmp_path, bad_files):
    good = tmp_path / "good.fna"
    write_genome(good, seed=1, length=5000)
    assert validate_genome(str(good)) is None

    assert validate_genome(bad_files["missing"])[0] == "missing"
    assert validate_genome(bad_files["empty"])[0] == "empty"
    assert validate_genome(bad_files["trunc"])[0] == "corrupt"
    reason, _detail = validate_genome(bad_files["bad"])
    assert reason in ("corrupt", "empty")


def test_missing_file_not_retried(tmp_path, monkeypatch):
    """FileNotFoundError is deterministic — the IO retry loop must not
    burn its backoff budget on it."""
    import time as time_mod

    slept = []
    monkeypatch.setattr(time_mod, "sleep",
                        lambda d: slept.append(d))
    verdict = validate_genome(str(tmp_path / "nope.fna"))
    assert verdict[0] == "missing"
    assert slept == []


# -- manifest -------------------------------------------------------


def test_manifest_write_load_roundtrip(tmp_path):
    m = QuarantineManifest()
    m.add("/data/a.fna", "corrupt", "bad gzip stream")
    m.add("/data/b.fna", "missing")
    out = m.write(str(tmp_path))
    assert os.path.basename(out) == MANIFEST_NAME

    with open(out) as f:
        data = json.load(f)
    assert data["version"] == 1
    assert [r["path"] for r in data["quarantined"]] == [
        "/data/a.fna", "/data/b.fna"]

    back = QuarantineManifest.load(out)
    assert back.records() == m.records()
    assert back.paths() == {"/data/a.fna", "/data/b.fna"}


def test_manifest_output_dir_anchors(tmp_path):
    cd = str(tmp_path / "out" / "clusters.tsv")
    rl = str(tmp_path / "reps" / "reps.txt")
    assert manifest_output_dir(cluster_definition=cd) == str(
        tmp_path / "out")
    assert manifest_output_dir(representative_list=rl) == str(
        tmp_path / "reps")
    assert manifest_output_dir(checkpoint_dir="/ck") == "/ck"
    assert manifest_output_dir() == "."


# -- preflight ------------------------------------------------------


def test_preflight_keeps_good_quarantines_bad(tmp_path, bad_files):
    good1 = tmp_path / "g1.fna"
    good2 = tmp_path / "g2.fna"
    write_genome(good1, seed=1, length=5000)
    write_genome(good2, seed=2, length=5000)
    paths = [str(good1), bad_files["bad"], str(good2),
             bad_files["missing"], bad_files["trunc"]]

    kept, manifest = preflight_quarantine(paths)
    assert kept == [str(good1), str(good2)]
    assert manifest.paths() == {bad_files["bad"], bad_files["missing"],
                                bad_files["trunc"]}
    reasons = {r.path: r.reason for r in manifest.records()}
    assert reasons[bad_files["missing"]] == "missing"
    assert reasons[bad_files["trunc"]] == "corrupt"


def test_preflight_all_good_is_identity(tmp_path):
    paths = []
    for i in range(3):
        p = tmp_path / f"g{i}.fna"
        write_genome(p, seed=i, length=4000)
        paths.append(str(p))
    kept, manifest = preflight_quarantine(paths)
    assert kept == paths
    assert len(manifest) == 0


# -- genome input parsing under the skip policy ---------------------


def test_parse_inputs_error_policy_unchanged(tmp_path):
    with pytest.raises(FileNotFoundError):
        parse_genome_inputs(
            genome_fasta_files=[str(tmp_path / "nope.fna")])


def test_parse_inputs_skip_drops_missing_into_manifest(tmp_path):
    good = tmp_path / "g.fna"
    write_genome(good, seed=3, length=4000)
    m = QuarantineManifest()
    out = parse_genome_inputs(
        genome_fasta_files=[str(good), str(tmp_path / "nope.fna")],
        on_bad_genome="skip", manifest=m)
    assert out == [str(good)]
    assert m.paths() == {str(tmp_path / "nope.fna")}
    assert m.records()[0].reason == "missing"


def test_parse_inputs_skip_all_missing_still_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        parse_genome_inputs(
            genome_fasta_files=[str(tmp_path / "a.fna"),
                                str(tmp_path / "b.fna")],
            on_bad_genome="skip", manifest=QuarantineManifest())


# -- acceptance (c): quarantined run == run that never saw the file --


VALUES = {"ani": 95.0, "precluster_ani": 90.0,
          "min_aligned_fraction": 15.0, "fragment_length": 3000,
          "precluster_method": "finch", "cluster_method": "skani",
          "threads": 1}


def _cluster_paths(paths, **extra):
    """Cluster and return path-level clusters (index-free compare)."""
    from galah_tpu.api import generate_galah_clusterer

    cl = generate_galah_clusterer(paths, {**VALUES, **extra})
    return (sorted(sorted(cl.genome_paths[i] for i in c)
                   for c in cl.cluster()),
            cl)


def test_skip_policy_clusters_match_clean_run(tmp_path, bad_files):
    """Corrupt FASTA under --on-bad-genome skip is quarantined and the
    surviving genomes cluster bit-identically to a run that never
    included it (the tentpole's acceptance criterion c)."""
    base = write_genome(tmp_path / "a.fna", seed=11)
    write_genome(tmp_path / "b.fna", seed=12, mutate_from=base)
    write_genome(tmp_path / "far.fna", seed=13)
    good = [str(tmp_path / "a.fna"), str(tmp_path / "b.fna"),
            str(tmp_path / "far.fna")]

    clean, _cl = _cluster_paths(good)
    dirty_paths = good[:2] + [bad_files["trunc"]] + good[2:]
    dirty, cl = _cluster_paths(dirty_paths, on_bad_genome="skip")

    assert dirty == clean
    assert cl.quarantine is not None
    assert cl.quarantine.paths() == {bad_files["trunc"]}
    assert bad_files["trunc"] not in cl.genome_paths


def test_error_policy_raises_on_corrupt(tmp_path, bad_files):
    write_genome(tmp_path / "a.fna", seed=11)
    paths = [str(tmp_path / "a.fna"), bad_files["bad"]]
    with pytest.raises(Exception):
        _cluster_paths(paths)[0]


def test_all_quarantined_raises(tmp_path, bad_files):
    with pytest.raises(ValueError, match="quarantin"):
        _cluster_paths([bad_files["bad"], bad_files["empty"]],
                       on_bad_genome="skip")
