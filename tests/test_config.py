"""Config: percentage normalization boundary semantics.

Reference: src/cluster_argument_parsing.rs:1160-1182 — [1, 100] is
percent (1 means 1%), [0, 1) is already a fraction, outside is an error.
"""

import pytest

from galah_tpu.config import ClusterConfig, parse_percentage


def test_percent_range():
    assert parse_percentage(95) == pytest.approx(0.95)
    assert parse_percentage(100) == pytest.approx(1.0)
    assert parse_percentage(1.0) == pytest.approx(0.01)  # 1 means 1%!
    assert parse_percentage(15) == pytest.approx(0.15)


def test_fraction_range():
    assert parse_percentage(0.95) == pytest.approx(0.95)
    assert parse_percentage(0.0) == 0.0
    assert parse_percentage(0.999) == pytest.approx(0.999)


def test_out_of_range():
    with pytest.raises(ValueError):
        parse_percentage(150)
    with pytest.raises(ValueError):
        parse_percentage(-1)


def test_cluster_config_validates_methods():
    with pytest.raises(ValueError, match="precluster"):
        ClusterConfig(precluster_method="nope")
    with pytest.raises(ValueError, match="cluster method"):
        ClusterConfig(cluster_method="nope")
    with pytest.raises(ValueError, match="quality formula"):
        ClusterConfig(quality_formula="nope")
